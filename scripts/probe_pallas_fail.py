"""Capture the exact Mosaic failure behind the Pallas fallback.

probe_r3's pallas_probe2 compiled for ~70 s then fell back; the
dispatch()-level fallback logged the exception and threw it away. This
probe calls verify_pallas DIRECTLY (no fallback) at bucket 128 and
writes the full traceback to PALLAS_FAIL.txt so the next kernel fix is
aimed, not guessed. SIGTERM-safe, exits cleanly to release the claim.
"""

from __future__ import annotations

import os
import sys
import time
import traceback

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

OUT = os.path.join(os.path.dirname(__file__), "..", "PALLAS_FAIL.txt")


def main() -> None:
    os.environ["TM_TPU_PALLAS"] = "1"
    import jax
    import numpy as np

    cache = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
    )
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    lines = [f"devices: {jax.devices()}"]
    from device_session import _batch
    from tendermint_tpu.ops import ed25519_kernel as K
    from tendermint_tpu.ops.ed25519_pallas import verify_pallas

    pks, msgs, sigs = _batch(128, seed=7)
    pk_b = K._join_cols(pks, 32, 0)
    sig_b = K._join_cols(sigs, 64, 0)
    import hashlib

    dig = [
        hashlib.sha512(s[:32] + p + m).digest()
        for p, m, s in zip(pks, msgs, sigs)
    ]
    dig_b = K._join_cols(dig, 64, 0)

    t0 = time.perf_counter()
    try:
        import jax.numpy as jnp

        ok = verify_pallas(
            jnp.asarray(pk_b), jnp.asarray(sig_b), jnp.asarray(dig_b)
        )
        ok = np.asarray(ok)
        dt = time.perf_counter() - t0
        lines.append(f"SUCCESS in {dt:.1f}s: all_valid={bool(ok.all())}")
        # time warm runs
        t0 = time.perf_counter()
        for _ in range(5):
            np.asarray(
                verify_pallas(
                    jnp.asarray(pk_b), jnp.asarray(sig_b), jnp.asarray(dig_b)
                )
            )
        lines.append(f"warm: {(time.perf_counter() - t0) / 5 * 1e3:.1f} ms/128")
    except Exception:
        dt = time.perf_counter() - t0
        lines.append(f"FAILED after {dt:.1f}s:")
        lines.append(traceback.format_exc())

    with open(OUT, "w") as f:
        f.write("\n".join(lines) + "\n")
    print("\n".join(lines[-2:]))


if __name__ == "__main__":
    main()
