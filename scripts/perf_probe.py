"""Component-level timing probe for the ed25519 device program.

Times each stage of the verification pipeline at batch N on the
attached device, plus an int32 VPU roofline probe, to direct kernel
optimization. Not part of the test suite; run manually:

    python scripts/perf_probe.py [N]
"""

from __future__ import annotations

import sys
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax


def timeit(fn, *args, reps=5):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
    import __graft_entry__ as G
    from tendermint_tpu.ops import ed25519_kernel as K
    from tendermint_tpu.ops import edwards as E
    from tendermint_tpu.ops import field25519 as F

    reps = max(1, 8192 // n)
    pk, sig, dig = G._example_batch(min(n, 512))
    tile = lambda a: np.tile(a, (1, -(-n // a.shape[1])))[:, :n]  # noqa: E731
    pk_b = jnp.asarray(tile(pk))
    sig_b = jnp.asarray(tile(sig))
    dig_b = jnp.asarray(tile(dig))

    full = jax.jit(K._verify_tile)
    t_full = timeit(full, pk_b, sig_b, dig_b, reps=reps)
    print(f"full program      N={n}: {t_full*1e3:8.1f} ms  "
          f"({n/t_full:,.0f} sigs/s)")

    # stage: byte prep + digits (everything before decompress)
    def prep(pk_b, sig_b, dig_b):
        pk = pk_b.astype(jnp.int32)
        sg = sig_b.astype(jnp.int32)
        dg = dig_b.astype(jnp.int32)
        pk = pk.at[31].set(pk[31] & 0x7F)
        r = sg[:32]
        r = r.at[31].set(r[31] & 0x7F)
        yA = K._fe_from_bytes_dev(pk)
        yR = K._fe_from_bytes_dev(r)
        s_ok = K._s_lt_l_dev(sg[32:])
        dS = K._nibbles_dev(sg[32:])
        dk = K._nibbles_dev(K._mod_l_dev(dg))
        return yA, yR, s_ok, dS, dk

    jprep = jax.jit(prep)
    t_prep = timeit(jprep, pk_b, sig_b, dig_b, reps=reps)
    print(f"scalar prep           : {t_prep*1e3:8.1f} ms")

    yA, yR, s_ok, dS, dk = jprep(pk_b, sig_b, dig_b)
    signA = jnp.zeros((n,), jnp.int32)

    # stage: decompress both points
    dec = jax.jit(lambda yA, yR, s: (E.decompress(yA, s), E.decompress(yR, s)))
    t_dec = timeit(dec, yA, yR, signA, reps=reps)
    print(f"decompress x2         : {t_dec*1e3:8.1f} ms")

    (A, _), (R, _) = dec(yA, yR, signA)

    # stage: -A table build
    tbl = jax.jit(K._build_neg_a_table)
    t_tbl = timeit(tbl, A, reps=reps)
    print(f"neg-A table build     : {t_tbl*1e3:8.1f} ms")

    TA = tbl(A)

    # stage: the full curve stage (decompress + table + scan + compare)
    # — the production body, not a copy (scan-only time = this minus
    # the decompress and table rows above)
    jcurve = jax.jit(K._scalar_mult_check)
    signR = jnp.zeros((n,), jnp.int32)
    t_curve = timeit(jcurve, yA, signA, yR, signR, dS, dk, reps=reps)
    print(f"curve stage (prod)    : {t_curve*1e3:8.1f} ms")

    # stage: single point ops (per-call, amortized over a 64-iter loop)
    def dbl_loop(p):
        return lax.fori_loop(0, 256, lambda _i, a: E.point_double(a), p)

    t_dbl = timeit(jax.jit(dbl_loop), A, reps=reps)
    print(f"256 point_doubles     : {t_dbl*1e3:8.1f} ms "
          f"({t_dbl/256*1e6:.0f} us each)")

    def add_loop(p, qc):
        return lax.fori_loop(
            0, 128, lambda _i, a: E.point_add_cached(a, qc), p
        )

    QC = E.cache_point(A)
    t_add = timeit(jax.jit(add_loop), A, QC, reps=reps)
    print(f"128 point_adds        : {t_add*1e3:8.1f} ms "
          f"({t_add/128*1e6:.0f} us each)")

    def sel_loop(TA, dk):
        def body(i, acc):
            return acc + K._onehot_select(TA, dk[0])
        return lax.fori_loop(0, 128, body, jnp.zeros_like(TA[0]))

    t_sel = timeit(jax.jit(sel_loop), TA, dk, reps=reps)
    print(f"128 onehot selects    : {t_sel*1e3:8.1f} ms "
          f"({t_sel/128*1e6:.0f} us each)")

    # roofline: raw int32 multiply-add on the same array shape
    def mac_loop(a, b):
        def body(i, acc):
            return acc + (a * b + acc) * jnp.int32(3)
        return lax.fori_loop(0, 1000, body, jnp.zeros_like(a))

    a = jnp.ones((4, 39, n), jnp.int32)
    t_mac = timeit(jax.jit(mac_loop), a, a, reps=reps)
    per = t_mac / 1000
    elems = 4 * 39 * n
    print(f"1000 int32 3-MAC iters on (4,39,{n}): {t_mac*1e3:8.1f} ms "
          f"-> {elems*3/per/1e9:.0f} G int32-MAC/s")

    # field op costs
    x = jnp.ones((4, F.NLIMBS, n), jnp.int32)
    t_mul = timeit(
        jax.jit(lambda x: lax.fori_loop(0, 64, lambda _i, a: F.mul(a, x), x)),
        x, reps=reps,
    )
    print(f"64 stacked F.mul      : {t_mul*1e3:8.1f} ms "
          f"({t_mul/64*1e6:.0f} us each)")


if __name__ == "__main__":
    main()
