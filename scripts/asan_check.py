"""AddressSanitizer sweep of the native batch-equation kernel.

Builds an ASAN variant of native/ed25519_batch.c and drives every
exported entry point through all three MSM paths (Straus < 1024 terms,
Pippenger w8, Pippenger w11), multi-block SHA-512 message shapes, the
scalar/hash test hooks, and the sr25519 ristretto path — valid and
corrupted batches. Run after ANY change to the C kernel:

    python scripts/asan_check.py

Exits nonzero on an ASAN report or a wrong verification result.
(The suite's differential tests check semantics; this checks memory.)
"""

from __future__ import annotations

import ctypes
import os
import random
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "tendermint_tpu", "native", "ed25519_batch.c")


def main() -> int:
    cc = os.environ.get("CC", "cc")
    so = os.path.join(tempfile.mkdtemp(), "ed25519_batch_asan.so")
    subprocess.run(
        [cc, "-O1", "-g", "-fsanitize=address", "-shared", "-fPIC",
         "-o", so, SRC],
        check=True,
    )
    asan = subprocess.run(
        [cc, "-print-file-name=libasan.so"],
        capture_output=True, text=True, check=True,
    ).stdout.strip()
    # re-exec under LD_PRELOAD so ASAN is initialized before python
    if not os.environ.get("TM_ASAN_CHILD"):
        env = dict(os.environ)
        env["TM_ASAN_CHILD"] = so
        env["LD_PRELOAD"] = asan
        env.setdefault("ASAN_OPTIONS", "detect_leaks=0")
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = ""
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env
        ).returncode
    return run_checks(os.environ["TM_ASAN_CHILD"])


def _ed25519_keygen():
    """(make_signer(seed) -> obj with .sign(msg), pub_bytes(signer))
    for the sweep's test signatures.

    Prefers the OpenSSL-backed `cryptography` wheel; a container
    without the wheel (this box — PR 1 gated the dependency) falls
    back to the repo's pure-Python RFC-8032 signer. The fallback is
    a TOOLCHAIN substitution, not a weakening: both paths produce the
    identical deterministic RFC-8032 signatures, and the fallback is
    pinned against RFC 8032 test vector 1 here before anything trusts
    it — a broken signer would otherwise launder wrong-signature
    results into the memory sweep."""
    try:
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PrivateKey,
        )
        from cryptography.hazmat.primitives.serialization import (
            Encoding,
            PublicFormat,
        )

        def make(seed: bytes):
            return Ed25519PrivateKey.from_private_bytes(seed)

        def pub(sk) -> bytes:
            return sk.public_key().public_bytes(
                Encoding.Raw, PublicFormat.Raw
            )

        return make, pub
    except ImportError:
        from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519

        def make(seed: bytes):
            return PrivKeyEd25519(seed)

        def pub(sk) -> bytes:
            return sk.pub_key().bytes()

        # RFC 8032 §7.1 TEST 1: seed -> pub key and empty-message
        # signature must match bit-for-bit before the sweep runs.
        # Explicit raises, not asserts: `python -O` must not compile
        # the guard away
        vec = make(bytes.fromhex(
            "9d61b19deffd5a60ba844af492ec2cc4"
            "4449c5697b326919703bac031cae7f60"
        ))
        if pub(vec) != bytes.fromhex(
            "d75a980182b10ab7d54bfed3c964073a"
            "0ee172f3daa62325af021a68f707511a"
        ):
            raise RuntimeError(
                "fallback ed25519 keygen diverges from RFC 8032"
            )
        if vec.sign(b"") != bytes.fromhex(
            "e5564300c360ac729086e2cc806e828a"
            "84877f1eb8e5d974d873e06522490155"
            "5fb8821590a33bacc61e39701cf9b46b"
            "d25bf5f0595bbe24655141438e7a100b"
        ):
            raise RuntimeError(
                "fallback ed25519 signer diverges from RFC 8032"
            )
        return make, pub


def run_checks(so: str) -> int:
    sys.path.insert(0, REPO)
    lib = ctypes.CDLL(so)
    argtypes = [ctypes.c_char_p] * 5 + [ctypes.c_uint64]
    lib.tm_ed25519_batch_verify.argtypes = argtypes
    lib.tm_ed25519_batch_verify.restype = ctypes.c_int
    lib.tm_sr25519_batch_verify.argtypes = argtypes
    lib.tm_sr25519_batch_verify.restype = ctypes.c_int
    lib.tm_ed25519_verify_full.argtypes = [ctypes.c_char_p] * 3 + [
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_char_p, ctypes.c_uint64
    ]
    lib.tm_ed25519_verify_full.restype = ctypes.c_int
    lib.tm_sc_mod_l_test.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.tm_sha512_test.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p
    ]

    random.seed(5)
    out32 = ctypes.create_string_buffer(32)
    for _ in range(200):
        lib.tm_sc_mod_l_test(random.randbytes(64), out32)
    out64 = ctypes.create_string_buffer(64)
    for ln in (0, 1, 111, 112, 113, 127, 128, 129, 600):
        lib.tm_sha512_test(random.randbytes(ln), ln, out64)

    make_signer, pub_bytes = _ed25519_keygen()
    keys = []
    for i in range(8):
        sk = make_signer(bytes([i + 1]) * 32)
        keys.append((sk, pub_bytes(sk)))
    # sizes hitting Straus (<512 sigs), Pippenger w8, and w11 (>1700)
    for n in (1, 2, 7, 48, 600, 2048):
        pks, sigs, blob = bytearray(), bytearray(), bytearray()
        offs = (ctypes.c_uint64 * (n + 1))()
        pos = 0
        for i in range(n):
            sk, pk = keys[i % 8]
            m = b"asan-%d-" % i + b"y" * ((i * 53) % 500)
            pks += pk
            sigs += sk.sign(m)
            offs[i] = pos
            blob += m
            pos += len(m)
        offs[n] = pos
        rc = lib.tm_ed25519_verify_full(
            bytes(pks), bytes(sigs), bytes(blob), offs,
            random.randbytes(16 * n), n,
        )
        assert rc == 1, (n, rc)
        bad = bytearray(sigs)
        bad[32] ^= 1
        rc = lib.tm_ed25519_verify_full(
            bytes(pks), bytes(bad), bytes(blob), offs,
            random.randbytes(16 * n), n,
        )
        assert rc in (0, -1), (n, rc)

    import jax

    jax.config.update("jax_platforms", "cpu")
    from tendermint_tpu.crypto.ed25519 import _rlc_scalars
    from tendermint_tpu.crypto.sr25519 import (
        PrivKeySr25519,
        _parse_signature,
        challenge_batch,
    )

    privs = [PrivKeySr25519.from_seed(bytes([i + 3]) * 32) for i in range(4)]
    n = 40
    pks_l, msgs, sigs_l = [], [], []
    for i in range(n):
        p = privs[i % 4]
        m = b"sr-asan-%d" % i
        pks_l.append(p.pub_key().bytes())
        msgs.append(m)
        sigs_l.append(p.sign(m))
    parsed = [_parse_signature(s) for s in sigs_l]
    ks = challenge_batch(pks_l, msgs, [r for r, _ in parsed])
    zb, a_sc, z_sc = _rlc_scalars([s for _, s in parsed], ks)
    rc = lib.tm_sr25519_batch_verify(
        b"".join(pks_l), b"".join(r for r, _ in parsed), zb, a_sc, z_sc, n
    )
    assert rc == 1, rc

    # whole-batch sr25519 entry (merlin/STROBE in C) across STROBE
    # rate boundaries, valid + marker-stripped + corrupted-s batches
    lib.tm_sr25519_verify_full.argtypes = lib.tm_ed25519_verify_full.argtypes
    lib.tm_sr25519_verify_full.restype = ctypes.c_int
    lib.tm_sr25519_challenge_test.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_uint64, ctypes.c_char_p,
    ]
    for mlen in (0, 1, 165, 166, 167, 400):
        lib.tm_sr25519_challenge_test(
            random.randbytes(32), random.randbytes(32),
            random.randbytes(mlen), mlen, out32,
        )
    for n in (1, 2, 40, 600):
        pks_b, sigs_b, blob = bytearray(), bytearray(), bytearray()
        offs = (ctypes.c_uint64 * (n + 1))()
        pos = 0
        for i in range(n):
            p = privs[i % 4]
            m = b"srfull-%d-" % i + b"z" * ((i * 71) % 400)
            pks_b += p.pub_key().bytes()
            sigs_b += p.sign(m)
            offs[i] = pos
            blob += m
            pos += len(m)
        offs[n] = pos
        rc = lib.tm_sr25519_verify_full(
            bytes(pks_b), bytes(sigs_b), bytes(blob), offs,
            random.randbytes(16 * n), n,
        )
        assert rc == 1, (n, rc)
        bad = bytearray(sigs_b)
        bad[63] &= 0x7F  # strip the v1 marker on sig 0
        rc = lib.tm_sr25519_verify_full(
            bytes(pks_b), bytes(bad), bytes(blob), offs,
            random.randbytes(16 * n), n,
        )
        assert rc == 0, (n, rc)

    # decoded-point cache hooks: stats/clear under mixed-curve traffic
    lib.tm_pk_cache_stats.argtypes = [ctypes.POINTER(ctypes.c_uint64)]
    lib.tm_pk_cache_clear.argtypes = []
    stats = (ctypes.c_uint64 * 4)()
    lib.tm_pk_cache_stats(stats)
    lib.tm_pk_cache_clear()
    lib.tm_pk_cache_stats(stats)
    assert list(stats) == [0, 0, 0, 0]

    # fixed-base multiply + ristretto encode (sign/keygen path):
    # edge scalars (0, 1, L-1) and random ones
    lib.tm_ristretto_basemul.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.tm_ristretto_basemul.restype = ctypes.c_int
    L = 2**252 + 27742317777372353535851937790883648493
    out32 = ctypes.create_string_buffer(32)
    for k in [0, 1, 2, L - 1] + [
        random.randrange(L) for _ in range(32)
    ]:
        rc = lib.tm_ristretto_basemul(
            int(k).to_bytes(32, "little"), out32
        )
        assert rc == 0, k

    print("ASAN PASS: all entry points, all MSM paths, no reports")
    return 0


if __name__ == "__main__":
    sys.exit(main())
