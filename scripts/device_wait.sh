#!/bin/bash
# Poll for the tunneled TPU to become claimable again. Each attempt is
# a short-lived python that goes through the axon sitecustomize claim;
# a TERM while waiting for the claim is safe (the claim was never
# granted to us). Exits 0 the moment a device answers.
for i in $(seq 1 "${1:-120}"); do
  if timeout --signal=TERM 90 python -c "import jax; print(jax.devices())" >/tmp/device_wait_out 2>&1; then
    echo "device back after $i attempts: $(cat /tmp/device_wait_out | tail -1)"
    exit 0
  fi
  sleep 60
done
echo "device still unreachable after ${1:-120} attempts"
exit 1
