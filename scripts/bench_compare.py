#!/usr/bin/env python3
"""BENCH_*.json trajectory differ — catch perf regressions between a
fresh bench line and the banked one (ISSUE 15 satellite).

The repo banks bench trajectories (BENCH_r0N.json, BENCH_LOAD.json,
BENCH_STATELESS.json, ...) as one JSON document per run; until now
comparing a fresh run against the banked numbers was eyeball work.
This script flattens both documents into dotted row keys, compares
every numeric row they share, prints the % delta per row, and exits
nonzero when any row regressed past the threshold — so a bench rerun
can gate a PR the way the lint gates do.

    python scripts/bench_compare.py FRESH.json BANKED.json
    python scripts/bench_compare.py fresh.json BENCH_r05.json \\
        --threshold 0.15 --rows 'verify_commit*'

Direction matters: `*_per_s`-style rows are higher-is-better,
`*_ms`/`*_s`/`*_us` latency rows are lower-is-better. Rows whose
direction the suffix table can't classify are PRINTED but never fail
the gate (a moving `num_cpu_devices` is information, not a
regression). Rows present in the banked file but missing from the
fresh one fail the gate — a silently vanished measurement is how
trajectories rot. A row whose VALUE is null on either side (a
measurement that legitimately had no value that run, e.g. a recovery
phase that never happened) is reported as info and never fails.

`--gate` is the strict CI form of the default mode (ISSUE 17
satellite): failing rows go to stderr followed by one `GATE
PASS`/`GATE FAIL` verdict line, and — the difference that matters — an
EMPTY gateable-row set fails. The default mode's "no failures → exit
0" is the wrong contract for automation: a typo'd `--rows` filter or
a malformed fresh document compares nothing and sails through; under
`--gate` a run that held zero rows to the threshold is itself a
failure.

`--ledger` switches to the bottleneck-ledger diff (ISSUE 16): instead
of numeric rows it compares the two documents' `bottleneck_ledger`
blocks — per-subsystem wall-sample share deltas in percentage points,
buckets that newly entered or vanished from the ranked table, and the
headline attribution/idle/serving-vs-consensus shifts. A throughput
PR's claim ("moved time out of eventbus") is auditable as a share
delta here. Ledger mode is informational (exit 0) — attribution
SHIFTS are the point of a perf PR, not a regression; it exits 2 only
when a side has no ledger. `--variant NAME` descends into
`variants.NAME` first (the subs256 row banks its own ledger).
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import sys
from typing import Dict, Optional, Tuple

__all__ = [
    "compare",
    "compare_ledgers",
    "direction_of",
    "flatten",
    "ledger_of",
    "main",
]

# metadata keys that are never measurements (any nesting level)
_SKIP_KEYS = {
    "schema",
    "seed",
    "recorded_unix",
    "recorded_at",
    "timestamp",
    "git",
    "note",
    "notes",
}

# row-name suffix -> direction. higher = bigger is better,
# lower = smaller is better. Checked longest-suffix-first.
_HIGHER_SUFFIXES = (
    "_per_s",
    "per_s",
    "_per_sec",
    "_throughput",
    "_hits",
    "_held",
    "sigs_per_s",
    "headers_per_s",
    "_speedup",
    "_x",
)
_LOWER_SUFFIXES = (
    "_ms",
    "_us",
    "_ns",
    "_s",
    "_seconds",
    "_latency",
    "_wall",
    "_overhead",
    "_errors",
    "_timeouts",
    "_dropped",
    "_evictions",
    "_misses",
)


def direction_of(key: str) -> Optional[int]:
    """+1 higher-is-better, -1 lower-is-better, None unknown.
    Segments are consulted leaf-first so the most specific name wins
    (`routes_p99_ms.status` is a latency row: the `status` leaf says
    nothing, its `routes_p99_ms` parent does)."""
    for seg in reversed(key.lower().split(".")):
        # throughput markers may sit mid-name with a qualifier after
        # them (light_sync_warm_headers_per_s_150vals)
        if "per_s" in seg or "throughput" in seg:
            return 1
        for suf in _HIGHER_SUFFIXES:
            if seg.endswith(suf):
                return 1
        for suf in _LOWER_SUFFIXES:
            if seg.endswith(suf):
                return -1
    return None


def flatten(doc: dict, prefix: str = "") -> Dict[str, Optional[float]]:
    """Numeric leaves of a bench document as {dotted.key: value};
    bools and metadata keys are skipped. A JSON null leaf is kept as
    None — "the measurement legitimately had no value this run"
    (e.g. a chaos artifact's heal_detection_s when no stall-reset was
    needed) is information, NOT a vanished row."""
    out: Dict[str, Optional[float]] = {}
    for k, v in doc.items():
        if k in _SKIP_KEYS:
            continue
        key = f"{prefix}{k}"
        if isinstance(v, bool):
            continue
        if v is None:
            out[key] = None
        elif isinstance(v, (int, float)):
            out[key] = float(v)
        elif isinstance(v, dict):
            out.update(flatten(v, prefix=key + "."))
        # lists/strings are not trajectory rows
    return out


def compare(
    fresh: dict,
    banked: dict,
    threshold: float = 0.10,
    rows: str = "",
) -> Tuple[list, list]:
    """Per-row comparison. Returns (report_rows, failures): every
    report row is (key, banked, fresh, delta_pct, direction, status)
    with status in {ok, regressed, improved, info, missing}."""
    f_flat, b_flat = flatten(fresh), flatten(banked)
    report, failures = [], []
    for key in sorted(b_flat):
        if rows and not fnmatch.fnmatch(key, rows):
            continue
        old = b_flat[key]
        if key not in f_flat:
            row = (key, old, None, None, direction_of(key), "missing")
            report.append(row)
            failures.append(row)
            continue
        new = f_flat[key]
        if old is None or new is None:
            # a null on either side is not comparable and not a
            # regression — report it, never fail on it
            report.append(
                (key, old, new, None, direction_of(key), "info")
            )
            continue
        if old == 0:
            delta = 0.0 if new == 0 else float("inf")
        else:
            delta = (new - old) / abs(old)
        d = direction_of(key)
        if d is None:
            status = "info"
        elif (d > 0 and delta < -threshold) or (
            d < 0 and delta > threshold
        ):
            status = "regressed"
        elif (d > 0 and delta > threshold) or (
            d < 0 and delta < -threshold
        ):
            status = "improved"
        else:
            status = "ok"
        row = (key, old, new, delta, d, status)
        report.append(row)
        if status == "regressed":
            failures.append(row)
    return report, failures


def ledger_of(doc: dict, variant: str = "") -> Optional[dict]:
    """Find the bottleneck-ledger block in a BENCH_LOAD-shaped
    document: `variants.NAME` first when asked, then the document's
    `bottleneck_ledger`, then the document itself if it already IS a
    ledger (a fixture or an extracted block)."""
    if variant:
        doc = (doc.get("variants") or {}).get(variant) or {}
    led = doc.get("bottleneck_ledger")
    if led is None and "entries" in doc and "samples_total" in doc:
        led = doc
    return led


def compare_ledgers(fresh: dict, banked: dict) -> dict:
    """Diff two bottleneck ledgers: per-subsystem share deltas in
    percentage points (ranked by magnitude), new-entrant / vanished
    buckets, and the headline attribution + split shifts."""

    def _pp(new, old):
        if new is None and old is None:
            return None
        return round(((new or 0.0) - (old or 0.0)) * 100, 2)

    f_ent = {e["subsystem"]: e for e in fresh.get("entries", [])}
    b_ent = {e["subsystem"]: e for e in banked.get("entries", [])}
    rows = []
    for name in sorted(set(f_ent) | set(b_ent)):
        f, b = f_ent.get(name), b_ent.get(name)
        rows.append(
            {
                "subsystem": name,
                "banked_share": b["share"] if b else None,
                "fresh_share": f["share"] if f else None,
                "delta_pp": _pp(
                    f["share"] if f else None,
                    b["share"] if b else None,
                ),
                "status": (
                    "shared" if f and b else ("new" if f else "vanished")
                ),
            }
        )
    rows.sort(key=lambda r: (-abs(r["delta_pp"] or 0.0), r["subsystem"]))

    headline = {}
    for key in ("attributed_share", "unattributed_share", "idle_share"):
        headline[key] = {
            "banked": banked.get(key),
            "fresh": fresh.get(key),
            "delta_pp": _pp(fresh.get(key), banked.get(key)),
        }
    f_split = fresh.get("consensus_vs_serving") or {}
    b_split = banked.get("consensus_vs_serving") or {}
    for key in ("serving_share", "consensus_share"):
        headline[key] = {
            "banked": b_split.get(key),
            "fresh": f_split.get(key),
            "delta_pp": _pp(f_split.get(key), b_split.get(key)),
        }
    return {
        "samples": {
            "banked": banked.get("samples_total"),
            "fresh": fresh.get("samples_total"),
        },
        "headline": headline,
        "subsystems": rows,
        "new_entrants": [
            r["subsystem"] for r in rows if r["status"] == "new"
        ],
        "vanished": [
            r["subsystem"] for r in rows if r["status"] == "vanished"
        ],
    }


def _fmt_val(v) -> str:
    if v is None:
        return "-"
    if abs(v) >= 1000:
        return f"{v:.0f}"
    return f"{v:.4g}"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Compare a fresh bench JSON against a banked "
        "BENCH_*.json; exit 1 on any regression past the threshold."
    )
    ap.add_argument("fresh", help="fresh bench line / document (JSON)")
    ap.add_argument("banked", help="banked trajectory file (JSON)")
    ap.add_argument(
        "--threshold",
        type=float,
        default=0.10,
        help="relative regression tolerance (default 0.10 = 10%%)",
    )
    ap.add_argument(
        "--rows",
        default="",
        help="fnmatch filter on dotted row keys (e.g. 'verify_*')",
    )
    ap.add_argument(
        "--all",
        action="store_true",
        help="print every row, not just changed/failed ones",
    )
    ap.add_argument(
        "--json", action="store_true", help="emit the report as JSON"
    )
    ap.add_argument(
        "--gate",
        action="store_true",
        help="strict CI mode: print failing rows plus one GATE "
        "PASS/FAIL verdict line, and fail when ZERO rows were "
        "gateable (a gate that compared nothing must not pass — "
        "a typo'd --rows filter or an empty banked file would "
        "otherwise green-light anything)",
    )
    ap.add_argument(
        "--ledger",
        action="store_true",
        help="diff the documents' bottleneck_ledger blocks instead of "
        "numeric rows (informational, exit 0)",
    )
    ap.add_argument(
        "--variant",
        default="",
        help="with --ledger: diff variants.NAME's ledger (e.g. subs256)",
    )
    args = ap.parse_args(argv)

    try:
        with open(args.fresh) as f:
            fresh = json.load(f)
        with open(args.banked) as f:
            banked = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.ledger:
        f_led = ledger_of(fresh, args.variant)
        b_led = ledger_of(banked, args.variant)
        if f_led is None or b_led is None:
            side = "fresh" if f_led is None else "banked"
            print(
                f"error: no bottleneck_ledger in the {side} document"
                + (f" (variant {args.variant!r})" if args.variant else ""),
                file=sys.stderr,
            )
            return 2
        diff = compare_ledgers(f_led, b_led)
        if args.json:
            print(json.dumps(diff, indent=1))
            return 0
        s = diff["samples"]
        print(
            f"ledger: {_fmt_val(s['banked'])} banked samples -> "
            f"{_fmt_val(s['fresh'])} fresh"
        )
        for key, h in diff["headline"].items():
            print(
                f"{key:>20}: {_fmt_val(h['banked'])} -> "
                f"{_fmt_val(h['fresh'])}  "
                f"({h['delta_pp']:+.1f}pp)"
                if h["delta_pp"] is not None
                else f"{key:>20}: -"
            )
        for r in diff["subsystems"]:
            pp = r["delta_pp"]
            print(
                f"{r['status']:>9}  {r['subsystem']}: "
                f"{_fmt_val(r['banked_share'])} -> "
                f"{_fmt_val(r['fresh_share'])}"
                + (f"  ({pp:+.1f}pp)" if pp is not None else "")
            )
        return 0
    report, failures = compare(
        fresh, banked, threshold=args.threshold, rows=args.rows
    )
    if args.gate:
        # gateable = rows the threshold can actually act on: a known
        # direction and both values present, or a vanished measurement
        gateable = [
            r
            for r in report
            if r[5] in ("ok", "regressed", "improved", "missing")
        ]
        for k, old, new, delta, _d, status in failures:
            pct = (
                "vanished"
                if status == "missing"
                else (
                    "inf"
                    if delta == float("inf")
                    else f"{delta * 100:+.1f}%"
                )
            )
            print(
                f"{status:>9}  {k}: {_fmt_val(old)} -> "
                f"{_fmt_val(new)}  ({pct})",
                file=sys.stderr,
            )
        if not gateable:
            print(
                f"GATE FAIL: 0 gateable rows (of {len(report)} "
                f"compared) — nothing to hold the line on",
                file=sys.stderr,
            )
            return 1
        if failures:
            print(
                f"GATE FAIL: {len(failures)} of {len(gateable)} "
                f"gateable rows regressed past "
                f"{args.threshold * 100:.0f}% (or went missing)",
                file=sys.stderr,
            )
            return 1
        print(
            f"GATE PASS: {len(gateable)} gateable rows within "
            f"{args.threshold * 100:.0f}% of the banked trajectory"
        )
        return 0
    if args.json:
        print(
            json.dumps(
                {
                    "threshold": args.threshold,
                    "rows": [
                        {
                            "key": k,
                            "banked": old,
                            "fresh": new,
                            "delta_pct": (
                                round(delta * 100, 2)
                                if delta is not None
                                and delta != float("inf")
                                else None
                            ),
                            "status": status,
                        }
                        for k, old, new, delta, _d, status in report
                    ],
                    "regressions": len(failures),
                },
                indent=1,
            )
        )
    else:
        shown = 0
        for k, old, new, delta, d, status in report:
            if not args.all and status in ("ok", "info"):
                continue
            arrow = {1: "↑better", -1: "↓better", None: ""}[d]
            pct = (
                "-"
                if delta is None
                else ("inf" if delta == float("inf") else f"{delta * 100:+.1f}%")
            )
            print(
                f"{status:>9}  {k}: {_fmt_val(old)} -> "
                f"{_fmt_val(new)}  ({pct}) {arrow}"
            )
            shown += 1
        if shown == 0:
            print(
                f"all {len(report)} compared rows within "
                f"{args.threshold * 100:.0f}% of the banked trajectory"
            )
        if failures:
            print(
                f"FAIL: {len(failures)} row(s) regressed past "
                f"{args.threshold * 100:.0f}% (or went missing)",
                file=sys.stderr,
            )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
