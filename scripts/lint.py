#!/usr/bin/env python
"""tmlint CLI — run the consensus-invariant static analyzer.

Usage:
    python scripts/lint.py                    # full package vs baseline
    python scripts/lint.py --rule det-float   # one rule class only
    python scripts/lint.py --no-baseline      # every violation, raw
    python scripts/lint.py --baseline-update  # re-accept current state
    python scripts/lint.py --list-rules       # rule catalog
    python scripts/lint.py path/to/file.py    # specific files (paths
                                              # inside tendermint_tpu/)

Exit codes (the contract tests/test_lint.py and CI rely on):
    0  clean — no violations beyond the checked-in baseline
    1  new violations found (or any violation under --no-baseline)
    2  usage or internal error

The baseline lives at tendermint_tpu/analysis/baseline.json and is
fingerprinted by source-line content, so unrelated edits never shift
it. docs/static_analysis.md documents the workflow and the
suppression policy (`# tmlint: disable=<rule>` with a justification).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.analysis import tmlint  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="lint.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "paths", nargs="*",
        help="files to check (default: the whole tendermint_tpu package)",
    )
    ap.add_argument(
        "--rule", action="append", dest="rules", metavar="ID",
        help="only run this rule id (repeatable)",
    )
    ap.add_argument(
        "--baseline", default=tmlint.BASELINE_PATH,
        help="baseline file (default: tendermint_tpu/analysis/baseline.json)",
    )
    ap.add_argument(
        "--baseline-update", action="store_true",
        help="accept the current violation set as the new baseline",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report and fail on every violation",
    )
    ap.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    ap.add_argument(
        "--stats", action="store_true",
        help="print per-rule counts and timing",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in tmlint.all_rules():
            print(f"{rule.id}: {rule.title}")
            print(f"    {rule.rationale}")
        return 0

    if args.baseline_update and (args.rules or args.paths):
        # a filtered scan would overwrite the whole baseline with its
        # subset, silently deleting every other grandfathered entry
        print(
            "error: --baseline-update requires a full-package, "
            "all-rules run (drop --rule and path arguments)",
            file=sys.stderr,
        )
        return 2

    t0 = time.monotonic()
    try:
        if args.paths:
            root = tmlint.package_root()
            violations = []
            for p in args.paths:
                abspath = os.path.abspath(p)
                rel = os.path.relpath(abspath, root).replace(os.sep, "/")
                if rel.startswith(".."):
                    print(
                        f"error: {p} is outside the package root {root}",
                        file=sys.stderr,
                    )
                    return 2
                violations.extend(tmlint.check_file(abspath, rel, args.rules))
        else:
            violations = tmlint.check_package(rules=args.rules)
    except (ValueError, OSError, SyntaxError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    elapsed = time.monotonic() - t0

    if args.baseline_update:
        counts = tmlint.save_baseline(violations, args.baseline)
        print(
            f"baseline updated: {len(counts)} fingerprints covering "
            f"{len(violations)} accepted violations -> {args.baseline}"
        )
        return 0

    if args.no_baseline:
        new = violations
    else:
        baseline = tmlint.load_baseline(args.baseline)
        new = tmlint.new_violations(violations, baseline)

    for v in new:
        print(v.render())

    if args.stats:
        per_rule: dict = {}
        for v in violations:
            per_rule[v.rule] = per_rule.get(v.rule, 0) + 1
        print(
            f"-- {len(violations)} total violations "
            f"({len(new)} new), {elapsed:.2f}s --"
        )
        for rid in sorted(per_rule):
            print(f"   {rid}: {per_rule[rid]}")

    if new:
        print(
            f"\n{len(new)} new violation(s). Fix them, add a justified "
            "`# tmlint: disable=<rule>` suppression, or (for accepted "
            "debt) run scripts/lint.py --baseline-update.",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
