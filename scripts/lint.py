#!/usr/bin/env python
"""tmlint + tmcheck + tmrace CLI — the consensus-invariant static
analyzers.

Usage:
    python scripts/lint.py                    # full gate: tmlint +
                                              # tmcheck + tmrace
    python scripts/lint.py --rule det-float   # one tmlint rule class only
    python scripts/lint.py --taint            # tmcheck taint pass only
    python scripts/lint.py --schema           # tmcheck schema gate only
    python scripts/lint.py --race             # tmrace data-race +
                                              # lock-order pass only
    python scripts/lint.py --memo-audit       # memo-soundness audit
                                              # only (prints the full
                                              # memoized-function list)
    python scripts/lint.py --no-baseline      # every violation, raw
    python scripts/lint.py --baseline-update  # re-accept current state
                                              # (tmlint, taint AND race
                                              # baselines)
    python scripts/lint.py --schema-update    # regenerate the golden
                                              # wire-schema table
    python scripts/lint.py --list-rules       # rule catalog
    python scripts/lint.py path/to/file.py    # specific files (tmlint
                                              # only; tmcheck/tmrace are
                                              # whole-program)

Exit codes (the contract tests/test_lint.py, tests/test_tmcheck.py,
tests/test_tmrace.py and CI rely on):
    0  clean — no violations beyond the checked-in baselines/golden
    1  new violations found (or any violation under --no-baseline)
    2  usage or internal error

Baselines: tendermint_tpu/analysis/baseline.json (tmlint),
tendermint_tpu/analysis/tmcheck/taint_baseline.json (taint),
tendermint_tpu/analysis/tmrace/race_baseline.json (race), and the
golden wire schema tendermint_tpu/analysis/tmcheck/schema.json.
--baseline-update / --schema-update refuse filtered runs (a subset
scan would silently overwrite the whole file).
docs/static_analysis.md documents the workflow and the suppression
policy (`# tmlint: disable=<rule>`, `# tmcheck: taint-ok/taint-break`,
`# tmcheck: unparsed=N/unwritten=N`, `# tmrace: race-ok/guarded-by`).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.analysis import tmcheck, tmlint, tmrace  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="lint.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "paths", nargs="*",
        help="files to check (default: the whole tendermint_tpu package)",
    )
    ap.add_argument(
        "--rule", action="append", dest="rules", metavar="ID",
        help="only run this tmlint rule id (repeatable; skips tmcheck)",
    )
    ap.add_argument(
        "--baseline", default=tmlint.BASELINE_PATH,
        help="tmlint baseline file "
             "(default: tendermint_tpu/analysis/baseline.json)",
    )
    ap.add_argument(
        "--baseline-update", action="store_true",
        help="accept the current violation set as the new baseline "
             "(tmlint and taint)",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baselines: report and fail on every violation",
    )
    ap.add_argument(
        "--taint", action="store_true",
        help="run only the tmcheck interprocedural taint pass",
    )
    ap.add_argument(
        "--schema", action="store_true",
        help="run only the tmcheck wire-schema conformance gate",
    )
    ap.add_argument(
        "--race", action="store_true",
        help="run only the tmrace data-race + lock-order pass",
    )
    ap.add_argument(
        "--memo-audit", action="store_true", dest="memo_audit",
        help="run only the memo-soundness audit and print the full "
             "memoized-function listing (tmcheck.memoaudit)",
    )
    ap.add_argument(
        "--schema-update", action="store_true",
        help="regenerate the golden wire-schema table "
             "(tendermint_tpu/analysis/tmcheck/schema.json)",
    )
    ap.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    ap.add_argument(
        "--stats", action="store_true",
        help="print per-rule counts and timing",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in tmlint.all_rules():
            print(f"{rule.id}: {rule.title}")
            print(f"    {rule.rationale}")
        for rid, title in tmcheck.RULES:
            print(f"{rid}: {title}")
        for rid, title in tmrace.RULES:
            print(f"{rid}: {title}")
        return 0

    filtered = bool(args.rules or args.paths)
    if args.baseline_update and filtered:
        # a filtered scan would overwrite the whole baseline with its
        # subset, silently deleting every other grandfathered entry
        print(
            "error: --baseline-update requires a full-package, "
            "all-rules run (drop --rule and path arguments)",
            file=sys.stderr,
        )
        return 2
    if args.baseline_update and (args.schema or args.memo_audit):
        # the schema gate has no counted baseline — its accepted state
        # IS the golden table — and the memo audit ships with zero
        # accepted debt by design; silently succeeding here would let
        # an operator believe a red gate was accepted when nothing ran
        print(
            "error: --baseline-update has nothing to update for the "
            "schema/memo-audit sections (use --schema-update for the "
            "golden table; the memo audit has no baseline)",
            file=sys.stderr,
        )
        return 2
    if args.schema_update and (
        filtered or args.taint or args.race or args.memo_audit
    ):
        # same hazard: the golden table covers EVERY codec module (and
        # combining with --taint/--race/--memo-audit would silently
        # skip that gate while returning 0 — the update mode below
        # disables them)
        print(
            "error: --schema-update requires a full-package run "
            "(drop --rule/--taint/--race/--memo-audit and path "
            "arguments)",
            file=sys.stderr,
        )
        return 2

    sections = args.taint or args.schema or args.race or args.memo_audit
    run_tmlint = not sections
    run_taint = args.taint or not (
        args.schema or args.race or args.memo_audit or filtered
    )
    run_schema = args.schema or not (
        args.taint or args.race or args.memo_audit or filtered
    )
    run_race = args.race or not (
        args.taint or args.schema or args.memo_audit or filtered
    )
    run_memo = args.memo_audit or not (
        args.taint or args.schema or args.race or filtered
    )
    # update modes run ONLY the sections they update: computing (then
    # discarding) the other gates' violations would both waste ~2 s
    # and return 0 past a red gate the operator never saw
    if args.baseline_update:
        run_schema = False
        run_memo = False
    if args.schema_update:
        run_tmlint = False
        run_taint = False
        run_race = False
        run_memo = False

    t0 = time.monotonic()
    violations = []
    new = []
    try:
        if run_tmlint:
            if args.paths:
                root = tmlint.package_root()
                for p in args.paths:
                    abspath = os.path.abspath(p)
                    rel = os.path.relpath(abspath, root).replace(os.sep, "/")
                    if rel.startswith(".."):
                        print(
                            f"error: {p} is outside the package root {root}",
                            file=sys.stderr,
                        )
                        return 2
                    violations.extend(
                        tmlint.check_file(abspath, rel, args.rules)
                    )
            else:
                violations.extend(tmlint.check_package(rules=args.rules))
            if args.baseline_update:
                counts = tmlint.save_baseline(violations, args.baseline)
                print(
                    f"tmlint baseline updated: {len(counts)} fingerprints "
                    f"covering {len(violations)} accepted violations -> "
                    f"{args.baseline}"
                )
            elif args.no_baseline:
                new.extend(violations)
            else:
                new.extend(
                    tmlint.new_violations(
                        violations, tmlint.load_baseline(args.baseline)
                    )
                )

        pkg = None
        if run_taint:
            pkg = tmcheck.build_package()
            taint_v = tmcheck.taint_violations(pkg)
            violations.extend(taint_v)
            if args.baseline_update:
                counts = tmcheck.update_taint_baseline(pkg)
                print(
                    f"taint baseline updated: {len(counts)} fingerprints "
                    f"-> {tmcheck.TAINT_BASELINE_PATH}"
                )
            elif args.no_baseline:
                new.extend(taint_v)
            else:
                new.extend(tmcheck.new_taint_violations(pkg))

        if run_race:
            # one analyze() pass serves report, baseline diff AND
            # baseline update — the race pass dominates gate runtime,
            # so it must never run twice
            race_pkg = pkg or tmcheck.build_package()
            pkg = race_pkg
            race_v = tmrace.race_violations(race_pkg)
            violations.extend(race_v)
            if args.baseline_update:
                counts = tmlint.save_baseline(
                    race_v,
                    tmrace.RACE_BASELINE_PATH,
                    note=tmrace.RACE_BASELINE_NOTE,
                )
                print(
                    f"race baseline updated: {len(counts)} fingerprints "
                    f"-> {tmrace.RACE_BASELINE_PATH}"
                )
            elif args.no_baseline:
                new.extend(race_v)
            else:
                new.extend(
                    tmlint.new_violations(
                        race_v,
                        tmlint.load_baseline(tmrace.RACE_BASELINE_PATH),
                    )
                )

        if run_memo:
            # no baseline: every memo-audit finding is a new violation
            memo_pkg = pkg or tmcheck.build_package()
            pkg = memo_pkg
            report, memo_findings = tmcheck.memoaudit.audit(memo_pkg)
            memo_v = tmcheck.memoaudit.findings_to_violations(
                memo_findings
            )
            violations.extend(memo_v)
            new.extend(memo_v)
            if args.memo_audit:
                # the listing IS the point of --memo-audit: every
                # memoized function, its inputs, and its audit outcome
                print(tmcheck.memoaudit.render_report(report))

        if args.schema_update:
            data = tmcheck.update_schema_golden()
            print(
                f"golden schema updated: {len(data['messages'])} messages "
                f"-> {tmcheck.GOLDEN_PATH}"
            )
        elif run_schema:
            # the golden table IS the schema baseline: drift always
            # fails, --no-baseline changes nothing here
            schema_v = tmcheck.schema_violations()
            violations.extend(schema_v)
            new.extend(schema_v)
    except (ValueError, OSError, SyntaxError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    elapsed = time.monotonic() - t0

    if args.baseline_update or args.schema_update:
        return 0

    for v in new:
        print(v.render())

    if args.stats:
        per_rule: dict = {}
        for v in violations:
            per_rule[v.rule] = per_rule.get(v.rule, 0) + 1
        sections = [
            s
            for s, on in (
                ("tmlint", run_tmlint),
                ("taint", run_taint),
                ("schema", run_schema),
                ("race", run_race),
                ("memo", run_memo),
            )
            if on
        ]
        print(
            f"-- [{'+'.join(sections)}] {len(violations)} total violations "
            f"({len(new)} new), {elapsed:.2f}s --"
        )
        for rid in sorted(per_rule):
            print(f"   {rid}: {per_rule[rid]}")

    if new:
        print(
            f"\n{len(new)} new violation(s). Fix them, add a justified "
            "suppression/annotation (# tmlint: disable=..., # tmcheck: "
            "taint-ok/taint-break/unparsed=N, # tmrace: "
            "race-ok/guarded-by=...), or for consciously accepted "
            "changes run scripts/lint.py --baseline-update / "
            "--schema-update.",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
