#!/usr/bin/env python
"""tmlint + tmcheck + tmrace + tmtrace + tmlive + tmsafe + tmcost +
tmmc + tmct CLI — the consensus-invariant static analyzers.

Usage:
    python scripts/lint.py                    # full gate: tmlint +
                                              # tmcheck + tmrace +
                                              # tmtrace + tmlive +
                                              # tmsafe
    python scripts/lint.py --rule det-float   # one tmlint rule class only
    python scripts/lint.py --taint            # tmcheck taint pass only
    python scripts/lint.py --schema           # tmcheck schema gate only
    python scripts/lint.py --race             # tmrace data-race +
                                              # lock-order pass only
    python scripts/lint.py --live             # tmlive liveness +
                                              # boundedness pass only
    python scripts/lint.py --adv              # tmsafe adversarial-input
                                              # safety pass only
    python scripts/lint.py --cost             # tmcost per-request
                                              # cost-bound pass only
    python scripts/lint.py --mc               # tmmc exhaustive model-
                                              # checking gate only
                                              # (DYNAMIC: runs the
                                              # consensus implementation
                                              # under the explorer)
    python scripts/lint.py --ct               # tmct secret-flow /
                                              # constant-time pass only
                                              # (crypto-plane timing +
                                              # lifetime proof)
    python scripts/lint.py --cost-update      # regenerate the reviewed
                                              # per-request budget table
    python scripts/lint.py --memo-audit       # memo-soundness audit
                                              # only (prints the full
                                              # memoized-function list)
    python scripts/lint.py --trace            # tmtrace device-dispatch
                                              # proof only (static +
                                              # fast-tier compile gate)
    python scripts/lint.py --trace-full       # ... with the FULL
                                              # root × bucket eval_shape
                                              # sweep (the device-
                                              # campaign pre-flight;
                                              # minutes, not seconds)
    python scripts/lint.py --no-baseline      # every violation, raw
    python scripts/lint.py --baseline-update  # re-accept current state
                                              # (tmlint, taint, race,
                                              # trace, live AND safe
                                              # baselines)
    python scripts/lint.py --schema-update    # regenerate the golden
                                              # wire-schema table
    python scripts/lint.py --signatures-update  # regenerate the golden
                                              # jit-signature table
    python scripts/lint.py --list-rules       # rule catalog
    python scripts/lint.py path/to/file.py    # specific files (tmlint
                                              # only; tmcheck/tmrace/
                                              # tmtrace are
                                              # whole-program)

Exit codes (the contract tests/test_lint.py, tests/test_tmcheck.py,
tests/test_tmrace.py, tests/test_tmtrace.py and CI rely on):
    0  clean — no violations beyond the checked-in baselines/goldens
    1  new violations found (or any violation under --no-baseline)
    2  usage or internal error

Baselines: tendermint_tpu/analysis/baseline.json (tmlint),
tendermint_tpu/analysis/tmcheck/taint_baseline.json (taint),
tendermint_tpu/analysis/tmrace/race_baseline.json (race),
tendermint_tpu/analysis/tmtrace/trace_baseline.json (trace),
tendermint_tpu/analysis/tmlive/live_baseline.json (live),
tendermint_tpu/analysis/tmsafe/safe_baseline.json (adv),
tendermint_tpu/analysis/tmcost/cost_baseline.json (cost),
tendermint_tpu/analysis/tmmc/mc_baseline.json (mc — ships empty and
should stay empty), tendermint_tpu/analysis/tmct/ct_baseline.json
(ct — ships empty and stays empty: crypto-plane findings are fixed or
suppressed in-file with a written reason, never baselined), and the
golden tables tendermint_tpu/analysis/tmcheck/schema.json +
tendermint_tpu/analysis/tmtrace/jit_signatures.json +
tendermint_tpu/analysis/tmcost/cost_budgets.json.
--baseline-update / --schema-update / --signatures-update /
--cost-update refuse filtered runs (a subset scan would silently
overwrite the whole file). docs/static_analysis.md documents the
workflow and the suppression policy (`# tmlint: disable=<rule>`,
`# tmcheck: taint-ok/taint-break`, `# tmcheck:
unparsed=N/unwritten=N`, `# tmrace: race-ok/guarded-by`,
`# tmtrace: trace-ok`, `# tmlive: block-ok/grow-ok/bounded=`,
`# tmsafe: <rule>-ok`, `# tmcost: <rule>-ok`, `# tmmc: mc-ok`,
`# tmct: ct-ok — why` — the tmct reason is mandatory).

The full gate parses the package ONCE: the tmcheck call-graph build is
the shared substrate every section (including tmlint's syntactic rules
and the schema extraction) reads its module trees from; --stats
reports the full-gate wall and the substrate build time.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.analysis import (  # noqa: E402
    tmcheck,
    tmcost,
    tmct,
    tmlint,
    tmlive,
    tmrace,
    tmsafe,
    tmtrace,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="lint.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "paths", nargs="*",
        help="files to check (default: the whole tendermint_tpu package)",
    )
    ap.add_argument(
        "--rule", action="append", dest="rules", metavar="ID",
        help="only run this tmlint rule id (repeatable; skips tmcheck)",
    )
    ap.add_argument(
        "--baseline", default=tmlint.BASELINE_PATH,
        help="tmlint baseline file "
             "(default: tendermint_tpu/analysis/baseline.json)",
    )
    ap.add_argument(
        "--baseline-update", action="store_true",
        help="accept the current violation set as the new baseline "
             "(tmlint and taint)",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baselines: report and fail on every violation",
    )
    ap.add_argument(
        "--taint", action="store_true",
        help="run only the tmcheck interprocedural taint pass",
    )
    ap.add_argument(
        "--schema", action="store_true",
        help="run only the tmcheck wire-schema conformance gate",
    )
    ap.add_argument(
        "--race", action="store_true",
        help="run only the tmrace data-race + lock-order pass",
    )
    ap.add_argument(
        "--live", action="store_true",
        help="run only the tmlive liveness + boundedness pass",
    )
    ap.add_argument(
        "--adv", action="store_true",
        help="run only the tmsafe adversarial-input safety pass",
    )
    ap.add_argument(
        "--cost", action="store_true",
        help="run only the tmcost per-request cost-bound pass",
    )
    ap.add_argument(
        "--mc", action="store_true",
        help="run only the tmmc exhaustive model-checking gate "
             "(dynamic: explores the real consensus implementation "
             "for the fixed 4-validator/2-height byzantine scenario)",
    )
    ap.add_argument(
        "--ct", action="store_true",
        help="run only the tmct secret-flow / constant-time pass "
             "(crypto-plane timing + lifetime proof)",
    )
    ap.add_argument(
        "--cost-update", action="store_true", dest="cost_update",
        help="regenerate the reviewed per-request cost budget table "
             "(tendermint_tpu/analysis/tmcost/cost_budgets.json)",
    )
    ap.add_argument(
        "--memo-audit", action="store_true", dest="memo_audit",
        help="run only the memo-soundness audit and print the full "
             "memoized-function listing (tmcheck.memoaudit)",
    )
    ap.add_argument(
        "--trace", action="store_true",
        help="run only the tmtrace device-dispatch proof (static "
             "passes + fast-tier compile gate)",
    )
    ap.add_argument(
        "--trace-full", action="store_true", dest="trace_full",
        help="with the tmtrace section: run the FULL root × bucket "
             "eval_shape sweep (the device-campaign pre-flight; "
             "minutes of tracing, not seconds)",
    )
    ap.add_argument(
        "--schema-update", action="store_true",
        help="regenerate the golden wire-schema table "
             "(tendermint_tpu/analysis/tmcheck/schema.json)",
    )
    ap.add_argument(
        "--signatures-update", action="store_true",
        dest="signatures_update",
        help="regenerate the golden jit-signature table "
             "(tendermint_tpu/analysis/tmtrace/jit_signatures.json)",
    )
    ap.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    ap.add_argument(
        "--stats", action="store_true",
        help="print per-rule counts and timing",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in tmlint.all_rules():
            print(f"{rule.id}: {rule.title}")
            print(f"    {rule.rationale}")
        for rid, title in tmcheck.RULES:
            print(f"{rid}: {title}")
        for rid, title in tmrace.RULES:
            print(f"{rid}: {title}")
        for rid, title in tmtrace.RULES:
            print(f"{rid}: {title}")
        for rid, title in tmlive.RULES:
            print(f"{rid}: {title}")
        for rid, title in tmsafe.RULES:
            print(f"{rid}: {title}")
        for rid, title in tmcost.RULES:
            print(f"{rid}: {title}")
        from tendermint_tpu.analysis import tmmc
        for rid, title in tmmc.RULES:
            print(f"{rid}: {title}")
        for rid, title in tmct.RULES:
            print(f"{rid}: {title}")
        return 0

    filtered = bool(args.rules or args.paths)
    trace_selected = args.trace or args.trace_full
    if args.baseline_update and filtered:
        # a filtered scan would overwrite the whole baseline with its
        # subset, silently deleting every other grandfathered entry
        print(
            "error: --baseline-update requires a full-package, "
            "all-rules run (drop --rule and path arguments)",
            file=sys.stderr,
        )
        return 2
    if args.baseline_update and (args.schema or args.memo_audit):
        # the schema gate has no counted baseline — its accepted state
        # IS the golden table — and the memo audit ships with zero
        # accepted debt by design; silently succeeding here would let
        # an operator believe a red gate was accepted when nothing ran
        print(
            "error: --baseline-update has nothing to update for the "
            "schema/memo-audit sections (use --schema-update for the "
            "golden table; the memo audit has no baseline)",
            file=sys.stderr,
        )
        return 2
    if args.schema_update and (
        filtered
        or args.taint
        or args.race
        or args.live
        or args.adv
        or args.cost
        or args.mc
        or args.ct
        or args.memo_audit
        or trace_selected
    ):
        # same hazard: the golden table covers EVERY codec module (and
        # combining with --taint/--race/--live/--adv/--memo-audit/
        # --trace would silently skip that gate while returning 0 —
        # the update mode below disables them)
        print(
            "error: --schema-update requires a full-package run "
            "(drop --rule/--taint/--race/--live/--adv/--cost/--mc/"
            "--ct/--memo-audit/--trace and path arguments)",
            file=sys.stderr,
        )
        return 2
    if args.signatures_update and (
        filtered
        or args.taint
        or args.schema
        or args.race
        or args.live
        or args.adv
        or args.cost
        or args.mc
        or args.ct
        or args.memo_audit
        or trace_selected
        or args.schema_update
        or args.baseline_update
    ):
        # the golden covers EVERY jit root in the package; a combined
        # run would silently skip the named gate while returning 0
        print(
            "error: --signatures-update requires a full-package run "
            "(drop --rule/--taint/--schema/--race/--live/--adv/--cost/"
            "--mc/--ct/--memo-audit/--trace/other update modes and "
            "path arguments)",
            file=sys.stderr,
        )
        return 2
    if args.cost_update and (
        filtered
        or args.taint
        or args.schema
        or args.race
        or args.live
        or args.adv
        or args.mc
        or args.ct
        or args.memo_audit
        or trace_selected
        or args.schema_update
        or args.signatures_update
        or args.baseline_update
    ):
        # the budget table covers EVERY serving root in the package; a
        # combined run would silently skip the named gate while
        # returning 0 (same hazard class as --schema-update)
        print(
            "error: --cost-update requires a full-package run "
            "(drop --rule/--taint/--schema/--race/--live/--adv/--mc/"
            "--ct/--memo-audit/--trace/other update modes and path "
            "arguments)",
            file=sys.stderr,
        )
        return 2

    sections = (
        args.taint
        or args.schema
        or args.race
        or args.live
        or args.adv
        or args.cost
        or args.mc
        or args.ct
        or args.memo_audit
        or trace_selected
    )
    run_tmlint = not sections
    others = {
        "taint": args.taint,
        "schema": args.schema,
        "race": args.race,
        "live": args.live,
        "adv": args.adv,
        "cost": args.cost,
        "mc": args.mc,
        "ct": args.ct,
        "memo": args.memo_audit,
        "trace": trace_selected,
    }

    def _only(section: str) -> bool:
        return others[section] or not (
            any(on for name, on in others.items() if name != section)
            or filtered
        )

    run_taint = _only("taint")
    run_schema = _only("schema")
    run_race = _only("race")
    run_live = _only("live")
    run_adv = _only("adv")
    run_cost = _only("cost")
    run_mc = _only("mc")
    run_ct = _only("ct")
    run_memo = _only("memo")
    run_trace = _only("trace")
    # update modes run ONLY the sections they update: computing (then
    # discarding) the other gates' violations would both waste ~2 s
    # and return 0 past a red gate the operator never saw
    if args.baseline_update:
        run_schema = False
        run_memo = False
    if args.schema_update:
        run_tmlint = False
        run_taint = False
        run_race = False
        run_live = False
        run_adv = False
        run_cost = False
        run_mc = False
        run_ct = False
        run_memo = False
        run_trace = False
    if args.signatures_update:
        run_tmlint = False
        run_taint = False
        run_schema = False
        run_race = False
        run_live = False
        run_adv = False
        run_cost = False
        run_mc = False
        run_ct = False
        run_memo = False
        run_trace = False
    if args.cost_update:
        run_tmlint = False
        run_taint = False
        run_schema = False
        run_race = False
        run_live = False
        run_adv = False
        run_cost = False
        run_mc = False
        run_ct = False
        run_memo = False
        run_trace = False

    t0 = time.monotonic()
    violations = []
    new = []
    # the shared substrate: ONE parse of the package serves the call
    # graph, tmlint's syntactic rules, and the schema extraction —
    # with 8 sections, re-parsing per section was the gate's single
    # largest fixed cost
    pkg = None
    substrate_s = 0.0
    needs_graph = (
        run_taint
        or run_race
        or run_live
        or run_adv
        or run_cost
        or run_ct
        or run_memo
        or run_trace
        or args.signatures_update
        or args.cost_update
    )
    try:
        if needs_graph:
            t_sub = time.monotonic()
            pkg = tmcheck.build_package()
            substrate_s = time.monotonic() - t_sub
        if run_tmlint:
            if args.paths:
                root = tmlint.package_root()
                for p in args.paths:
                    abspath = os.path.abspath(p)
                    rel = os.path.relpath(abspath, root).replace(os.sep, "/")
                    if rel.startswith(".."):
                        print(
                            f"error: {p} is outside the package root {root}",
                            file=sys.stderr,
                        )
                        return 2
                    violations.extend(
                        tmlint.check_file(abspath, rel, args.rules)
                    )
            else:
                violations.extend(
                    tmlint.check_package(rules=args.rules, pkg=pkg)
                )
            if args.baseline_update:
                counts = tmlint.save_baseline(violations, args.baseline)
                print(
                    f"tmlint baseline updated: {len(counts)} fingerprints "
                    f"covering {len(violations)} accepted violations -> "
                    f"{args.baseline}"
                )
            elif args.no_baseline:
                new.extend(violations)
            else:
                new.extend(
                    tmlint.new_violations(
                        violations, tmlint.load_baseline(args.baseline)
                    )
                )

        if run_taint:
            pkg = pkg or tmcheck.build_package()
            taint_v = tmcheck.taint_violations(pkg)
            violations.extend(taint_v)
            if args.baseline_update:
                counts = tmcheck.update_taint_baseline(pkg)
                print(
                    f"taint baseline updated: {len(counts)} fingerprints "
                    f"-> {tmcheck.TAINT_BASELINE_PATH}"
                )
            elif args.no_baseline:
                new.extend(taint_v)
            else:
                new.extend(tmcheck.new_taint_violations(pkg))

        if run_race:
            # one analyze() pass serves report, baseline diff AND
            # baseline update — the race pass dominates gate runtime,
            # so it must never run twice
            race_pkg = pkg or tmcheck.build_package()
            pkg = race_pkg
            race_v = tmrace.race_violations(race_pkg)
            violations.extend(race_v)
            if args.baseline_update:
                counts = tmlint.save_baseline(
                    race_v,
                    tmrace.RACE_BASELINE_PATH,
                    note=tmrace.RACE_BASELINE_NOTE,
                )
                print(
                    f"race baseline updated: {len(counts)} fingerprints "
                    f"-> {tmrace.RACE_BASELINE_PATH}"
                )
            elif args.no_baseline:
                new.extend(race_v)
            else:
                new.extend(
                    tmlint.new_violations(
                        race_v,
                        tmlint.load_baseline(tmrace.RACE_BASELINE_PATH),
                    )
                )

        if run_live:
            # same single-pass rule as tmrace: one analyze() serves
            # report, baseline diff AND baseline update
            live_pkg = pkg or tmcheck.build_package()
            pkg = live_pkg
            live_v = tmlive.live_violations(live_pkg)
            violations.extend(live_v)
            if args.baseline_update:
                counts = tmlint.save_baseline(
                    live_v,
                    tmlive.LIVE_BASELINE_PATH,
                    note=tmlive.LIVE_BASELINE_NOTE,
                )
                print(
                    f"live baseline updated: {len(counts)} fingerprints "
                    f"-> {tmlive.LIVE_BASELINE_PATH}"
                )
            elif args.no_baseline:
                new.extend(live_v)
            else:
                new.extend(
                    tmlint.new_violations(
                        live_v,
                        tmlint.load_baseline(tmlive.LIVE_BASELINE_PATH),
                    )
                )

        if run_adv:
            # same single-pass rule as tmrace/tmlive
            adv_pkg = pkg or tmcheck.build_package()
            pkg = adv_pkg
            adv_v = tmsafe.safe_violations(adv_pkg)
            violations.extend(adv_v)
            if args.baseline_update:
                counts = tmlint.save_baseline(
                    adv_v,
                    tmsafe.SAFE_BASELINE_PATH,
                    note=tmsafe.SAFE_BASELINE_NOTE,
                )
                print(
                    f"safe baseline updated: {len(counts)} fingerprints "
                    f"-> {tmsafe.SAFE_BASELINE_PATH}"
                )
            elif args.no_baseline:
                new.extend(adv_v)
            else:
                new.extend(
                    tmlint.new_violations(
                        adv_v,
                        tmlint.load_baseline(tmsafe.SAFE_BASELINE_PATH),
                    )
                )

        if run_cost:
            # one analyze() pass serves report, baseline diff AND the
            # budget gate (same single-pass rule as tmrace/tmtrace)
            cost_pkg = pkg or tmcheck.build_package()
            pkg = cost_pkg
            cost_v = tmcost.cost_violations(cost_pkg)
            violations.extend(cost_v)
            # golden-gated cost-budget findings can NEVER be absorbed
            # by the counted baseline — their accepted state is
            # cost_budgets.json (--cost-update)
            cost_base, cost_gated = tmcost.split_baselineable(cost_v)
            if args.baseline_update:
                counts = tmlint.save_baseline(
                    cost_base,
                    tmcost.COST_BASELINE_PATH,
                    note=tmcost.COST_BASELINE_NOTE,
                )
                print(
                    f"cost baseline updated: {len(counts)} fingerprints "
                    f"-> {tmcost.COST_BASELINE_PATH}"
                )
                if cost_gated:
                    print(
                        f"note: {len(cost_gated)} golden-gated tmcost "
                        "finding(s) were NOT baselined (fix them or run "
                        "--cost-update):",
                        file=sys.stderr,
                    )
                    new.extend(cost_gated)
            elif args.no_baseline:
                new.extend(cost_v)
            else:
                new.extend(
                    tmlint.new_violations(
                        cost_base,
                        tmlint.load_baseline(tmcost.COST_BASELINE_PATH),
                    )
                )
                new.extend(cost_gated)

        if args.cost_update:
            cost_pkg = pkg or tmcheck.build_package()
            pkg = cost_pkg
            data = tmcost.update_budgets(cost_pkg)
            print(
                f"cost budgets updated: {len(data['roots'])} serving "
                f"roots -> {tmcost.BUDGETS_PATH}"
            )

        if run_memo:
            # no baseline: every memo-audit finding is a new violation
            memo_pkg = pkg or tmcheck.build_package()
            pkg = memo_pkg
            report, memo_findings = tmcheck.memoaudit.audit(memo_pkg)
            memo_v = tmcheck.memoaudit.findings_to_violations(
                memo_findings
            )
            violations.extend(memo_v)
            new.extend(memo_v)
            if args.memo_audit:
                # the listing IS the point of --memo-audit: every
                # memoized function, its inputs, and its audit outcome
                print(tmcheck.memoaudit.render_report(report))

        if run_trace:
            trace_pkg = pkg or tmcheck.build_package()
            pkg = trace_pkg
            # one analyze() pass serves report, baseline diff AND
            # baseline update (same single-pass rule as tmrace)
            trace_report = tmtrace.analyze(
                trace_pkg, full=args.trace_full
            )
            trace_v = trace_report.violations
            violations.extend(trace_v)
            if args.stats and trace_report.stats.get("tier"):
                st = trace_report.stats
                print(
                    f"-- tmtrace live tier={st.get('tier')}: "
                    f"{st.get('traced')} cases in "
                    f"{st.get('total_s')}s, skipped_heavy="
                    f"{len(st.get('skipped_heavy', []))}, "
                    f"jit_cache={st.get('jit_cache')} --"
                )
            # golden-gated rules (signature drift / unknown root /
            # compile failure) can NEVER be absorbed by the counted
            # baseline — their accepted state is jit_signatures.json
            trace_base, trace_gated = tmtrace.split_baselineable(trace_v)
            if args.baseline_update:
                counts = tmlint.save_baseline(
                    trace_base,
                    tmtrace.TRACE_BASELINE_PATH,
                    note=tmtrace.TRACE_BASELINE_NOTE,
                )
                print(
                    f"trace baseline updated: {len(counts)} fingerprints "
                    f"-> {tmtrace.TRACE_BASELINE_PATH}"
                )
                if trace_gated:
                    print(
                        f"note: {len(trace_gated)} golden-gated tmtrace "
                        "finding(s) were NOT baselined (fix them or run "
                        "--signatures-update):",
                        file=sys.stderr,
                    )
                    new.extend(trace_gated)
            elif args.no_baseline:
                new.extend(trace_v)
            else:
                new.extend(
                    tmlint.new_violations(
                        trace_base,
                        tmlint.load_baseline(tmtrace.TRACE_BASELINE_PATH),
                    )
                )
                new.extend(trace_gated)

        if run_mc:
            # DYNAMIC section — no AST substrate: it runs the real
            # consensus implementation under the tmmc explorer for the
            # fixed gate scenario (4 validators, 2 heights, one
            # equivocator) and converts invariant violations into lint
            # findings anchored at the failed checker's def line in
            # analysis/tmmc/invariants.py. Imported lazily: the model
            # harness pulls in the full consensus stack, which no
            # static section needs.
            from tendermint_tpu.analysis import tmmc
            mc_report = tmmc.analyze()
            mc_v = tmmc.mc_violations(mc_report)
            violations.extend(mc_v)
            if args.stats:
                st = mc_report.stats
                print(
                    f"-- tmmc gate: {st.get('states')} states / "
                    f"{st.get('edges')} edges in {st.get('wall_s')}s, "
                    f"dedup_hits={st.get('dedup_hits')}, "
                    f"sleep_skips={st.get('sleep_skips')}, "
                    f"stopped_by={st.get('stopped_by')}, "
                    f"suppressed={mc_report.suppressed} --"
                )
            if args.baseline_update:
                counts = tmlint.save_baseline(
                    mc_v,
                    tmmc.MC_BASELINE_PATH,
                    note=tmmc.MC_BASELINE_NOTE,
                )
                print(
                    f"mc baseline updated: {len(counts)} fingerprints "
                    f"-> {tmmc.MC_BASELINE_PATH}"
                )
            elif args.no_baseline:
                new.extend(mc_v)
            else:
                new.extend(
                    tmlint.new_violations(
                        mc_v,
                        tmlint.load_baseline(tmmc.MC_BASELINE_PATH),
                    )
                )

        if run_ct:
            # one analyze() pass serves report, baseline diff AND
            # baseline update (same single-pass rule as tmrace)
            ct_pkg = pkg or tmcheck.build_package()
            pkg = ct_pkg
            ct_report = tmct.analyze(ct_pkg)
            ct_v = ct_report.violations
            violations.extend(ct_v)
            if args.stats:
                st = ct_report.stats
                print(
                    f"-- tmct gate: {st.get('privkey_classes')} privkey "
                    f"classes / {st.get('secret_attrs')} secret attrs / "
                    f"{st.get('seeded_functions')} seeded functions, "
                    f"region={st.get('region')} analyzed functions, "
                    f"suppressed={st.get('suppressed')} --"
                )
            if args.baseline_update:
                counts = tmlint.save_baseline(
                    ct_v,
                    tmct.CT_BASELINE_PATH,
                    note=tmct.CT_BASELINE_NOTE,
                )
                print(
                    f"ct baseline updated: {len(counts)} fingerprints "
                    f"-> {tmct.CT_BASELINE_PATH}"
                )
            elif args.no_baseline:
                new.extend(ct_v)
            else:
                new.extend(
                    tmlint.new_violations(
                        ct_v,
                        tmlint.load_baseline(tmct.CT_BASELINE_PATH),
                    )
                )

        if args.signatures_update:
            sig_pkg = pkg or tmcheck.build_package()
            pkg = sig_pkg
            data = tmtrace.update_signatures_golden(sig_pkg)
            print(
                f"golden jit signatures updated: "
                f"{len(data['roots'])} roots -> {tmtrace.GOLDEN_PATH}"
            )

        if args.schema_update:
            data = tmcheck.update_schema_golden(pkg=pkg)
            print(
                f"golden schema updated: {len(data['messages'])} messages "
                f"-> {tmcheck.GOLDEN_PATH}"
            )
        elif run_schema:
            # the golden table IS the schema baseline: drift always
            # fails, --no-baseline changes nothing here
            schema_v = tmcheck.schema_violations(pkg=pkg)
            violations.extend(schema_v)
            new.extend(schema_v)
    except (ValueError, OSError, SyntaxError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    elapsed = time.monotonic() - t0

    if (
        args.baseline_update
        or args.schema_update
        or args.signatures_update
        or args.cost_update
    ):
        # `new` is non-empty here only for golden-gated tmtrace
        # findings an update mode refused to absorb: surface them and
        # fail so the operator can't mistake the update for acceptance
        for v in new:
            print(v.render())
        return 1 if new else 0

    for v in new:
        print(v.render())

    if args.stats:
        per_rule: dict = {}
        for v in violations:
            per_rule[v.rule] = per_rule.get(v.rule, 0) + 1
        sections = [
            s
            for s, on in (
                ("tmlint", run_tmlint),
                ("taint", run_taint),
                ("schema", run_schema),
                ("race", run_race),
                ("live", run_live),
                ("adv", run_adv),
                ("cost", run_cost),
                ("mc", run_mc),
                ("ct", run_ct),
                ("memo", run_memo),
                ("trace", run_trace),
            )
            if on
        ]
        print(
            f"-- [{'+'.join(sections)}] {len(violations)} total violations "
            f"({len(new)} new), full-gate wall {elapsed:.2f}s"
            + (
                f" (substrate: {len(pkg.modules)} modules parsed once, "
                f"{substrate_s:.2f}s)"
                if pkg is not None
                else ""
            )
            + " --"
        )
        for rid in sorted(per_rule):
            print(f"   {rid}: {per_rule[rid]}")

    if new:
        print(
            f"\n{len(new)} new violation(s). Fix them, add a justified "
            "suppression/annotation (# tmlint: disable=..., # tmcheck: "
            "taint-ok/taint-break/unparsed=N, # tmrace: "
            "race-ok/guarded-by=..., # tmtrace: trace-ok, "
            "# tmlive: block-ok/grow-ok/bounded=..., "
            "# tmsafe: <rule>-ok, # tmcost: <rule>-ok, "
            "# tmmc: mc-ok, # tmct: ct-ok — why), or for "
            "consciously accepted changes run scripts/lint.py "
            "--baseline-update / --schema-update / --signatures-update "
            "/ --cost-update.",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
