"""Local Mosaic/XLA-TPU compile check — NO device or claim needed.

libtpu ships in this image, so the real TPU compiler (including
Mosaic's jaxpr->vreg pipeline) runs locally against a compile-only
v5e topology. This is how the 'Invalid vector register cast' in the
bool Kogge-Stone recode was found and fixed in minutes after weeks of
blind 70-second remote probes and wedged claims (PERF.md session 2).

Run on CPU only: env PYTHONPATH= JAX_PLATFORMS=cpu python scripts/aot_check.py

Checks, each compiled under shard_map over a 4-chip v5e:2x2 mesh
(batch axis sharded — the production layout of parallel/sharding.py):

  hybrid      — verify_hybrid (Pallas dual-mult segment + XLA around)
  sr-hybrid   — _verify_tile_sr with the same Pallas dual-mult
  monolithic  — verify_pallas (whole tile in one kernel)

All three compile as of 2026-07-31 (~35s / ~38s / ~22s) after two
bool-lattice fixes: the i1-vreg concatenate in _recode_signed and the
scalar-True i8 select in _lt_const_dev.
"""

from __future__ import annotations

import functools
import os
import sys
import time
import traceback

sys.path.insert(
    0, os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
)


def main() -> int:
    import jax
    import jax.numpy as jnp
    from jax.experimental import topologies
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tendermint_tpu.ops import sr25519_kernel as S
    from tendermint_tpu.ops.ed25519_pallas import (
        dual_mult_pallas,
        verify_hybrid,
        verify_pallas,
    )

    topo = topologies.get_topology_desc(
        platform="tpu", topology_name="v5e:2x2"
    )
    mesh = topologies.make_mesh(topo, (4,), ("x",))

    failures = 0

    def aot(inner, name, rows):
        nonlocal failures
        fn = shard_map(
            inner,
            mesh=mesh,
            in_specs=(P(None, "x"),) * 3,
            out_specs=P("x"),
            check_rep=False,
        )
        args = [
            jax.ShapeDtypeStruct(
                (r, 512), jnp.int32, sharding=NamedSharding(mesh, P(None, "x"))
            )
            for r in rows
        ]
        t0 = time.perf_counter()
        try:
            jax.jit(fn).lower(*args).compile()
            print(f"{name}: OK in {time.perf_counter() - t0:.1f}s", flush=True)
        except Exception:
            failures += 1
            print(
                f"{name}: FAILED after {time.perf_counter() - t0:.1f}s",
                flush=True,
            )
            traceback.print_exc(limit=3)

    aot(verify_hybrid, "hybrid", (32, 64, 64))
    aot(
        functools.partial(S._verify_tile_sr, dual_fn=dual_mult_pallas),
        "sr-hybrid",
        (32, 64, 32),
    )
    aot(verify_pallas, "monolithic", (32, 64, 64))
    return failures


if __name__ == "__main__":
    raise SystemExit(main())
