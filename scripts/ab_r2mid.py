"""A/B the r2-mid tree (commit 2d191db, the 67,324 sigs/s best-ever)
against the current tree IN THE SAME DEVICE SESSION — the regression
attribution VERDICT r3 asked for (PERF.md "The 67k -> 45k regression":
~18% was unattributed because the r2-mid number came from a different
session with a 74 ms-RTT tunnel).

Run AFTER the current-tree probes (scripts/probe_r3.py) have finished
and their process has exited — two device clients must never overlap.
Imports the r2-mid tree from the .ab_r2mid git worktree and times its
XLA verifier with the same harness/batch shapes as probe_r3's
xla_tput3 stage. Results land in AB_R2MID.json.

SIGTERM-safe: uses device_session's handlers; never killed externally
(device-claim discipline).
"""

from __future__ import annotations

import json
import os
import sys
import time

SCRIPTS = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(SCRIPTS)
AB_DIR = os.path.join(REPO, ".ab_r2mid")
OUT = os.path.join(REPO, "AB_R2MID.json")

sys.path.insert(0, SCRIPTS)
sys.path.insert(0, REPO)

from device_session import _batch, _throughput, install_handlers  # noqa: E402


def main() -> None:
    install_handlers()
    if not os.path.isdir(AB_DIR):
        raise SystemExit(f"worktree missing: {AB_DIR}")

    import jax

    cache = os.path.join(REPO, ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    out = {"r2mid_commit": "2d191db", "started_unix": time.time()}

    def save() -> None:
        tmp = OUT + ".tmp"
        with open(tmp, "w") as f:
            json.dump(out, f, indent=1)
        os.replace(tmp, OUT)

    # make absolutely sure the worktree's package wins the import
    for mod in [m for m in sys.modules if m.startswith("tendermint_tpu")]:
        del sys.modules[mod]
    sys.path.insert(0, AB_DIR)
    import tendermint_tpu

    assert tendermint_tpu.__file__.startswith(AB_DIR), (
        tendermint_tpu.__file__
    )
    from tendermint_tpu.ops.ed25519_kernel import Ed25519Verifier

    pks, msgs, sigs = _batch(8192)
    t0 = time.perf_counter()
    rate = _throughput(Ed25519Verifier(bucket_sizes=[8192]), pks, msgs, sigs)
    out["r2mid_xla_tput_8192_sigs_per_s"] = round(rate, 1)
    out["seconds"] = round(time.perf_counter() - t0, 1)
    save()
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
