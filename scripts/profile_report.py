#!/usr/bin/env python
"""Render a profiler table (libs/profiler.py) into human-readable form.

Input is either a `profile.json` (debug bundle / `profile` RPC route /
tmload report's `profile` block) or raw collapsed-stack lines
(`role;frame;frame... count`, the flamegraph.pl format emitted by
`profiler.folded()`).

    python scripts/profile_report.py profile.json
    python scripts/profile_report.py --folded stacks.txt
    python scripts/profile_report.py profile.json --top 15 --min-pct 2

Outputs, in order:
  1. the subsystem share table (the bottleneck ledger's raw ranking)
  2. top-N **self** frames (innermost frame of each sample — who is ON
     the CPU / holding the wall)
  3. top-N **cumulative** frames (anywhere in the stack — who is
     responsible transitively)
  4. a collapsed flamegraph as an indented text tree (children sorted
     by weight, pruned below --min-pct of total samples)

Exit codes: 0 rendered, 2 unreadable/empty input.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Tuple


def parse_folded_line(line: str) -> Tuple[List[str], int]:
    """One `a;b;c N` line -> (frames, count). Raises ValueError."""
    body, _, count = line.rstrip().rpartition(" ")
    if not body:
        raise ValueError(f"not a folded line: {line!r}")
    return body.split(";"), int(count)


def load_stacks(path: str, folded: bool) -> Tuple[List[dict], Dict[str, float]]:
    """-> (entries [{stack: [frames], count}], subsystem_shares)."""
    with open(path) as f:
        raw = f.read()
    entries: List[dict] = []
    shares: Dict[str, float] = {}
    if folded:
        for line in raw.splitlines():
            if not line.strip():
                continue
            frames, count = parse_folded_line(line)
            entries.append({"stack": frames, "count": count})
    else:
        doc = json.loads(raw)
        if "profile" in doc and isinstance(doc["profile"], dict):
            doc = doc["profile"]  # tmload report nesting
        shares = doc.get("subsystem_shares", {}) or {}
        for e in doc.get("stacks", []):
            frames = e["stack"].split(";") if e.get("stack") else []
            head = [e["role"]] if e.get("role") else []
            if e.get("task"):
                head.append(e["task"])
            entries.append(
                {"stack": head + frames, "count": int(e["count"])}
            )
    return entries, shares


def self_cumulative(
    entries: List[dict],
) -> Tuple[Dict[str, int], Dict[str, int]]:
    self_c: Dict[str, int] = {}
    cum_c: Dict[str, int] = {}
    for e in entries:
        stack, count = e["stack"], e["count"]
        if not stack:
            continue
        leaf = stack[-1]
        self_c[leaf] = self_c.get(leaf, 0) + count
        for frame in set(stack):
            cum_c[frame] = cum_c.get(frame, 0) + count
    return self_c, cum_c


def print_table(
    title: str, counts: Dict[str, int], total: int, top: int
) -> None:
    print(f"\n== {title} ==")
    print(f"{'samples':>9}  {'share':>6}  frame")
    for frame, n in sorted(counts.items(), key=lambda kv: -kv[1])[:top]:
        print(f"{n:>9}  {100.0 * n / total:>5.1f}%  {frame}")


class _Node:
    __slots__ = ("count", "children")

    def __init__(self) -> None:
        self.count = 0
        self.children: Dict[str, "_Node"] = {}


def build_tree(entries: List[dict]) -> _Node:
    root = _Node()
    for e in entries:
        root.count += e["count"]
        node = root
        for frame in e["stack"]:
            node = node.children.setdefault(frame, _Node())
            node.count += e["count"]
    return root


def print_tree(
    node: _Node, total: int, min_count: int, depth: int = 0
) -> None:
    for frame, child in sorted(
        node.children.items(), key=lambda kv: -kv[1].count
    ):
        if child.count < min_count:
            continue
        pct = 100.0 * child.count / total
        print(f"{'  ' * depth}{pct:5.1f}% {child.count:>7}  {frame}")
        print_tree(child, total, min_count, depth + 1)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="profile_report.py",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("path", help="profile.json (or folded text with --folded)")
    ap.add_argument(
        "--folded",
        action="store_true",
        help="input is raw collapsed-stack lines, not profile.json",
    )
    ap.add_argument(
        "--top", type=int, default=25, help="rows in the self/cumulative tables"
    )
    ap.add_argument(
        "--min-pct",
        type=float,
        default=1.0,
        help="prune flame-tree nodes below this %% of total samples",
    )
    ap.add_argument(
        "--no-tree", action="store_true", help="skip the flame tree"
    )
    args = ap.parse_args(argv)

    try:
        entries, shares = load_stacks(args.path, args.folded)
    except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
        print(f"error: cannot read {args.path}: {e}", file=sys.stderr)
        return 2
    total = sum(e["count"] for e in entries)
    if total == 0:
        print(
            f"error: no samples in {args.path} (profiler never enabled?)",
            file=sys.stderr,
        )
        return 2

    print(f"{total} samples, {len(entries)} unique stacks")
    if shares:
        print("\n== subsystem shares ==")
        for name, share in sorted(shares.items(), key=lambda kv: -kv[1]):
            print(f"{100.0 * share:>5.1f}%  {name}")

    self_c, cum_c = self_cumulative(entries)
    print_table(f"top {args.top} self", self_c, total, args.top)
    print_table(f"top {args.top} cumulative", cum_c, total, args.top)

    if not args.no_tree:
        min_count = max(1, int(total * args.min_pct / 100.0))
        print(f"\n== flame tree (>= {args.min_pct}% of samples) ==")
        print_tree(build_tree(entries), total, min_count)
    return 0


if __name__ == "__main__":
    sys.exit(main())
