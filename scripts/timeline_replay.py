#!/usr/bin/env python3
"""Offline consensus-timeline reconstruction from a WAL — the
post-mortem half of the flight recorder (ISSUE 15).

A wedged or dead node can't serve its `consensus_timeline` RPC ring,
but its WAL holds every input the consensus loop processed (proposals,
block parts, votes, timeouts — write-before-process) plus the
round-step markers `_new_step` writes, each stamped with the wall
clock. This script rebuilds the same event stream the live recorder
captured and prints a per-height phase table: when the proposal
landed, when the count-based +2/3 prevote/precommit thresholds
crossed, how many rounds burned, how many timeouts fired, and the
wall spans between phases — with ZERO live state.

    python scripts/timeline_replay.py ~/.tendermint/data/cs.wal
    python scripts/timeline_replay.py cs.wal --json out.json
    python scripts/timeline_replay.py cs.wal --events     # raw stream
    python scripts/timeline_replay.py cs.wal --validators 4

Vote thresholds are COUNT-based (> 2/3 of the committee, inferred as
max(validator_index)+1 unless --validators is given): exact for
equal-power sets, an approximation otherwise — derived events carry a
`derived` attr saying so. Gossip stall-resets are reactor-side state,
not consensus inputs, so a WAL reconstruction cannot contain them
(the live ring and the stall-reset counters do).
"""

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from tendermint_tpu.consensus.timeline import (  # noqa: E402
    events_from_wal,
    summarize_heights,
)


def _fmt(v, width):
    s = "-" if v is None else str(v)
    return s.rjust(width)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Rebuild the consensus flight-recorder timeline "
        "from a WAL (post-mortem, zero live state)."
    )
    ap.add_argument("wal", help="path to the WAL head file (cs.wal)")
    ap.add_argument(
        "--validators",
        type=int,
        default=0,
        help="committee size for the count-based vote thresholds "
        "(default: inferred as max validator index + 1)",
    )
    ap.add_argument(
        "--events",
        action="store_true",
        help="print the raw reconstructed event stream, one JSON "
        "object per line, instead of the per-height table",
    )
    ap.add_argument(
        "--json",
        metavar="PATH",
        default="",
        help="also write {events, heights} as JSON to PATH "
        "('-' = stdout)",
    )
    args = ap.parse_args(argv)

    wal = args.wal
    if os.path.isdir(wal):
        # the default home layout is data/cs.wal/wal (config.py
        # wal_file): pointing at the group DIRECTORY means its head
        head = os.path.join(wal, "wal")
        if not os.path.exists(head):
            print(
                f"error: {wal} is a directory without a 'wal' head "
                "file — pass the WAL head file itself",
                file=sys.stderr,
            )
            return 2
        wal = head
    if not os.path.exists(wal):
        print(f"error: no WAL at {wal}", file=sys.stderr)
        return 2
    events = events_from_wal(wal, validators=args.validators)
    heights = summarize_heights(events)

    if args.json:
        doc = json.dumps(
            {"events": events, "heights": heights}, indent=1
        )
        if args.json == "-":
            print(doc)
        else:
            with open(args.json, "w") as f:
                f.write(doc + "\n")

    if args.events:
        for e in events:
            print(json.dumps(e))
        return 0

    if not events:
        print("no decodable records in the WAL group")
        return 1

    print(
        f"{len(events)} events over {len(heights)} heights "
        f"from {args.wal}"
    )
    hdr = (
        "height  rounds  timeouts  prop->polka_ms  "
        "polka->quorum_ms  quorum->commit_ms  total_ms"
    )
    print(hdr)
    print("-" * len(hdr))
    for row in heights:
        print(
            _fmt(row["height"], 6)
            + _fmt(row["rounds"], 8)
            + _fmt(row["timeouts"], 10)
            + _fmt(row["proposal_to_polka_ms"], 16)
            + _fmt(row["polka_to_precommit_quorum_ms"], 18)
            + _fmt(row["precommit_quorum_to_commit_ms"], 19)
            + _fmt(row["first_event_to_commit_ms"], 10)
        )
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # `timeline_replay.py wal | head` closes our stdout mid-table
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
