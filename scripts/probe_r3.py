"""Round-3 focused device probes, appended to DEVICE_SESSION.json.

Stages, in run order (Pallas LAST — a server-side Mosaic compile can
hang 20+ min holding the claim; bank the XLA numbers first):

  xla_tput3       — headline: the current default tree (signed-digit
                    half-tables, MXU B-select, device SHA-512) at 8192
  xla_mosaic_form — scan+flip vs fori+one-hot window walks as plain
                    XLA programs (regression attribution, PERF.md)
  sr_tput2        — sr25519 throughput on the current tree
  commit_10k      — 10k-validator VerifyCommit p50 + phase breakdown
                    with the templated sign-bytes path
  xla_hostsha     — XLA throughput with host-side SHA-512 (A/B
                    against the device hash)
  pallas_probe2   — the segmented hybrid kernel (TM_TPU_PALLAS=1 ->
                    Pallas dual-mult, XLA around it) at bucket 128
  pallas_tput2    — hybrid throughput at 8192 if the probe held
  pallas_sr       — sr25519 hybrid throughput at 8192 (gated on the
                    ed25519 hybrid probe holding — same kernel)
  pallas_full     — monolithic whole-tile kernel at 8192, with its own
                    128-bucket probe gate (first-ever device compile)

Prior-session entries for these stages are dropped before the run (the
stage writer merges). SIGTERM-safe, never SIGKILLs the device client
(see device_session.py).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

from device_session import (  # noqa: E402
    RESULTS,
    _batch,
    _save,
    _stage,
    _state,
    _throughput,
)

if os.path.exists(RESULTS):
    with open(RESULTS) as f:
        prev = json.load(f)
    _state["stages"].update(prev.get("stages", {}))
    _state["devices"] = prev.get("devices")
# drop prior-session entries for the stages this run re-executes:
# _stage merges (setdefault().update()), so a stale sigs_per_s from an
# old success would otherwise survive inside a newly-skipped stage
for _k in (
    "pallas_probe2",
    "pallas_tput2",
    "pallas_sr",
    "pallas_full",
    "xla_hostsha",
    "xla_tput3",
    "xla_mosaic_form",
    "sr_tput2",
    "commit_10k",
):
    _state["stages"].pop(_k, None)


@_stage("pallas_probe2")
def stage_probe2():
    os.environ["TM_TPU_PALLAS"] = "1"
    try:
        from tendermint_tpu.ops.ed25519_kernel import Ed25519Verifier

        pks, msgs, sigs = _batch(128, seed=5)
        v = Ed25519Verifier(bucket_sizes=[128])
        t0 = time.perf_counter()
        ok = v.verify(pks, msgs, sigs)
        compile_s = time.perf_counter() - t0
        assert bool(ok.all())
        used_pallas = v._is_pallas(v._compiled.get(v._bucket(128)))
        t0 = time.perf_counter()
        for _ in range(5):
            v.verify(pks, msgs, sigs)
        warm_s = (time.perf_counter() - t0) / 5
        return {
            "compile_s": round(compile_s, 1),
            "warm_run_s": round(warm_s, 4),
            "used_pallas": bool(used_pallas),
        }
    finally:
        os.environ.pop("TM_TPU_PALLAS", None)


@_stage("pallas_tput2")
def stage_tput2():
    probe = _state["stages"].get("pallas_probe2", {})
    if not (probe.get("ok") and probe.get("used_pallas")):
        return {"skipped": "pallas probe2 did not hold"}
    return _pallas_tput_8192("1", probe_first=False)


def _sr_batch(seed: int, n: int = 8192, tag: bytes = b"sr"):
    """n (pk, msg, sig) sr25519 triples over 64 keys — shared by the
    XLA and hybrid sr throughput stages (schnorrkel signing on host is
    the slow part; build once per stage, not per variant)."""
    from tendermint_tpu.crypto.sr25519 import PrivKeySr25519

    privs = [
        PrivKeySr25519.from_seed(bytes([i, seed]) + b"\x00" * 30)
        for i in range(64)
    ]
    pks, msgs, sigs = [], [], []
    for i in range(n):
        p = privs[i % 64]
        m = tag + b"-%08d" % i
        pks.append(p.pub_key().bytes())
        msgs.append(m)
        sigs.append(p.sign(m))
    return pks, msgs, sigs


def _pallas_tput_8192(mode: str, probe_first: bool):
    """Shared body of the Pallas ed25519 throughput stages: set
    TM_TPU_PALLAS=<mode>, optionally prove a cheap 128-bucket compile
    first (bail before risking a long Mosaic compile at 8192 — the
    probe's fallback already downgraded if Mosaic rejected it), then
    time 8192. Restores the env var on exit."""
    from tendermint_tpu.ops.ed25519_kernel import Ed25519Verifier

    prev = os.environ.get("TM_TPU_PALLAS")
    os.environ["TM_TPU_PALLAS"] = mode
    try:
        if probe_first:
            pks, msgs, sigs = _batch(128, seed=5)
            v = Ed25519Verifier(bucket_sizes=[128])
            assert bool(v.verify(pks, msgs, sigs).all())
            if not v._is_pallas(v._compiled.get(v._bucket(128))):
                return {"skipped": f"{mode} probe at 128 fell back"}
        pks, msgs, sigs = _batch(8192)
        v = Ed25519Verifier(bucket_sizes=[8192])
        rate = _throughput(v, pks, msgs, sigs)
        used = v._is_pallas(v._compiled.get(v._bucket(8192)))
        return {"sigs_per_s": round(rate, 1), "used_pallas": bool(used)}
    finally:
        if prev is None:
            os.environ.pop("TM_TPU_PALLAS", None)
        else:
            os.environ["TM_TPU_PALLAS"] = prev


@_stage("pallas_full")
def stage_pallas_full():
    """The monolithic whole-tile kernel (TM_TPU_PALLAS=full) at 8192 —
    compiles in ~22s via the local AOT check; everything in one
    pallas_call keeps even the prep/compare intermediates in VMEM.
    Probes at bucket 128 first: this kernel has never compiled on the
    real device, and a hung device-side Mosaic compile holds the claim
    (the failure mode in this file's header)."""
    return _pallas_tput_8192("full", probe_first=True)


@_stage("pallas_sr")
def stage_pallas_sr():
    """sr25519 hybrid (Pallas dual-mult segment) at 8192, only if the
    ed25519 hybrid probe held — same kernel, so no point paying another
    Mosaic compile budget if it already failed."""
    probe = _state["stages"].get("pallas_probe2", {})
    if not (probe.get("ok") and probe.get("used_pallas")):
        return {"skipped": "ed25519 hybrid probe did not hold"}
    os.environ["TM_TPU_PALLAS"] = "1"
    try:
        from tendermint_tpu.ops import sr25519_kernel as S

        pks, msgs, sigs = _sr_batch(seed=7, tag=b"sr-hybrid")
        v = S.Sr25519Verifier(bucket_sizes=[8192])
        rate = _throughput(v, pks, msgs, sigs, reps=4)
        still_hybrid = 8192 in v._pallas_proven
        return {
            "sigs_per_s": round(rate, 1),
            "used_pallas": bool(still_hybrid),
        }
    finally:
        os.environ.pop("TM_TPU_PALLAS", None)


@_stage("xla_hostsha")
def stage_hostsha():
    os.environ.pop("TM_TPU_PALLAS", None)
    os.environ["TM_TPU_HOST_SHA512"] = "1"
    try:
        from tendermint_tpu.ops.ed25519_kernel import Ed25519Verifier

        pks, msgs, sigs = _batch(8192)
        rate = _throughput(Ed25519Verifier(bucket_sizes=[8192]), pks, msgs, sigs)
        return {"sigs_per_s": round(rate, 1)}
    finally:
        os.environ.pop("TM_TPU_HOST_SHA512", None)


@_stage("xla_tput3")
def stage_xla3():
    """The current default tree: scan window walk + unrolled device
    SHA-512. The r3 headline XLA number."""
    os.environ.pop("TM_TPU_PALLAS", None)
    from tendermint_tpu.ops.ed25519_kernel import Ed25519Verifier

    pks, msgs, sigs = _batch(8192)
    rate = _throughput(Ed25519Verifier(bucket_sizes=[8192]), pks, msgs, sigs)
    return {"sigs_per_s": round(rate, 1)}


@_stage("xla_mosaic_form")
def stage_mosaic_form():
    """A/B the two window-walk forms as plain XLA programs: scan+flip
    (default) vs fori_loop+one-hot (the Pallas tile body). Attributes
    part of the 67k->45k regression question (PERF.md)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tendermint_tpu.ops import ed25519_kernel as K

    pks, msgs, sigs = _batch(8192, seed=9)
    v = K.Ed25519Verifier(bucket_sizes=[8192])
    handle = v.dispatch(pks, msgs, sigs)
    ok = v.gather(handle)
    assert bool(ok.all())
    # rebuild the packed inputs exactly as dispatch() does
    import hashlib

    pk_b = K._join_cols(pks, 32, 0)
    sig_b = K._join_cols(sigs, 64, 0)
    dig_b = K._join_cols(
        [
            hashlib.sha512(s[:32] + p + m).digest()
            for p, m, s in zip(pks, msgs, sigs)
        ],
        64,
        0,
    )
    args = tuple(jnp.asarray(a) for a in (pk_b, sig_b, dig_b))
    out = {}
    # 2x2: window-walk form (scan vs fori+one-hot) x fixed-base select
    # engine (MXU einsum vs VPU one-hot) — isolates each variable
    for name, mosaic, mxu in (
        ("scan_mxu", False, True),
        ("scan_vpu", False, False),
        ("onehot_vpu", True, False),
        ("onehot_mxu", True, True),
    ):
        def tile(a, b, c, _m=mosaic, _x=mxu):
            dual = lambda A, dS, dk: K.dual_mult_sb_minus_ka(
                A, dS, dk, mosaic=_m, mxu=_x
            )
            return K._verify_tile(a, b, c, dual_fn=dual)

        fn = jax.jit(tile)
        r = fn(*args)
        jax.block_until_ready(r)
        assert bool(np.asarray(r).all())
        t0 = time.perf_counter()
        for _ in range(4):
            jax.block_until_ready(fn(*args))
        out[name + "_sigs_per_s"] = round(8192 / ((time.perf_counter() - t0) / 4), 1)
    return out


@_stage("sr_tput2")
def stage_sr2():
    from tendermint_tpu.ops.sr25519_kernel import Sr25519Verifier

    pks, msgs, sigs = _sr_batch(seed=99, tag=b"sr-session")
    rate = _throughput(
        Sr25519Verifier(bucket_sizes=[8192]), pks, msgs, sigs, reps=4
    )
    return {"sigs_per_s": round(rate, 1)}


@_stage("commit_10k")
def stage_commit_10k():
    """10k-validator VerifyCommit p50 + phase breakdown with the
    templated sign-bytes path (BASELINE config 5's latency half)."""
    import bench

    p50, p95 = bench.bench_commit_latency(10_000, reps=5, light=False)
    breakdown = bench.bench_commit_breakdown(10_000, reps=5)
    return {"p50_ms": round(p50, 2), "p95_ms": round(p95, 2), "breakdown": breakdown}


def main():
    from device_session import install_handlers

    install_handlers()
    import jax

    cache = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
    )
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    # Pallas stages LAST: a server-side Mosaic compile can hang for
    # 20+ minutes holding the claim (PERF.md session-2 findings); all
    # XLA measurements must be banked before taking that risk.
    for st in (
        stage_xla3,
        stage_mosaic_form,
        stage_sr2,
        stage_commit_10k,
        stage_hostsha,
        stage_probe2,
        stage_tput2,
        stage_pallas_sr,
        stage_pallas_full,
    ):
        st()
    print(json.dumps(_state["stages"], indent=1))


if __name__ == "__main__":
    main()
