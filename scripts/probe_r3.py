"""Round-3 focused device probes, appended to DEVICE_SESSION.json:

  pallas_probe2 — retry the Mosaic compile after the scatter fixes
  pallas_tput2  — pallas throughput at 8192 if the probe held
  xla_hostsha   — XLA throughput with host-side SHA-512 (A/B against
                  the device-hash path, chasing the 45k vs 67k gap)

SIGTERM-safe, never SIGKILLs the device client (see device_session.py).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

from device_session import (  # noqa: E402
    RESULTS,
    _batch,
    _save,
    _stage,
    _state,
    _throughput,
)

if os.path.exists(RESULTS):
    with open(RESULTS) as f:
        prev = json.load(f)
    _state["stages"].update(prev.get("stages", {}))
    _state["devices"] = prev.get("devices")
# drop prior-session entries for the stages this run re-executes:
# _stage merges (setdefault().update()), so a stale sigs_per_s from an
# old success would otherwise survive inside a newly-skipped stage
for _k in ("pallas_probe2", "pallas_tput2", "xla_hostsha"):
    _state["stages"].pop(_k, None)


@_stage("pallas_probe2")
def stage_probe2():
    os.environ["TM_TPU_PALLAS"] = "1"
    try:
        from tendermint_tpu.ops.ed25519_kernel import Ed25519Verifier

        pks, msgs, sigs = _batch(128, seed=5)
        v = Ed25519Verifier(bucket_sizes=[128])
        t0 = time.perf_counter()
        ok = v.verify(pks, msgs, sigs)
        compile_s = time.perf_counter() - t0
        assert bool(ok.all())
        used_pallas = v._is_pallas(v._compiled.get(v._bucket(128)))
        t0 = time.perf_counter()
        for _ in range(5):
            v.verify(pks, msgs, sigs)
        warm_s = (time.perf_counter() - t0) / 5
        return {
            "compile_s": round(compile_s, 1),
            "warm_run_s": round(warm_s, 4),
            "used_pallas": bool(used_pallas),
        }
    finally:
        os.environ.pop("TM_TPU_PALLAS", None)


@_stage("pallas_tput2")
def stage_tput2():
    probe = _state["stages"].get("pallas_probe2", {})
    if not (probe.get("ok") and probe.get("used_pallas")):
        return {"skipped": "pallas probe2 did not hold"}
    os.environ["TM_TPU_PALLAS"] = "1"
    try:
        from tendermint_tpu.ops.ed25519_kernel import Ed25519Verifier

        pks, msgs, sigs = _batch(8192)
        v = Ed25519Verifier(bucket_sizes=[8192])
        rate = _throughput(v, pks, msgs, sigs)
        still_pallas = v._is_pallas(v._compiled.get(v._bucket(8192)))
        return {"sigs_per_s": round(rate, 1), "used_pallas": bool(still_pallas)}
    finally:
        os.environ.pop("TM_TPU_PALLAS", None)


@_stage("xla_hostsha")
def stage_hostsha():
    os.environ.pop("TM_TPU_PALLAS", None)
    os.environ["TM_TPU_HOST_SHA512"] = "1"
    try:
        from tendermint_tpu.ops.ed25519_kernel import Ed25519Verifier

        pks, msgs, sigs = _batch(8192)
        rate = _throughput(Ed25519Verifier(bucket_sizes=[8192]), pks, msgs, sigs)
        return {"sigs_per_s": round(rate, 1)}
    finally:
        os.environ.pop("TM_TPU_HOST_SHA512", None)


def main():
    import jax

    cache = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
    )
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    for st in (stage_probe2, stage_tput2, stage_hostsha):
        st()
    print(json.dumps(_state["stages"], indent=1))


if __name__ == "__main__":
    main()
