"""One scripted TPU session: everything round 3 needs from the chip.

Run this the moment the tunneled device grants a claim (it may be
wedged for hours after an unclean client death — see PERF.md). Stages,
each persisted to DEVICE_SESSION.json as it completes so a mid-session
wedge keeps earlier results:

  1. rtt          — per-call tunnel round-trip (context for latencies)
  2. xla_tput     — pipelined ed25519 throughput at 8192, XLA path
                    (the post-T-less/projective tree, device-sha512)
  3. pallas_probe — ONE verify_pallas compile+run at bucket 128 under
                    a hard budget (TM_PALLAS_BUDGET_S, default 900 s);
                    Mosaic compile goes through the remote-compile leg
  4. pallas_tput  — if the probe succeeded: throughput at 8192 with
                    TM_TPU_PALLAS=1
  5. sr_tput      — sr25519 device throughput at 8192
  6. Decision aid — prints whether to flip the Pallas default

SIGTERM-safe: no stage SIGKILLs anything; a watchdog thread only
*records* overruns, never kills the device client.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

RESULTS = os.path.join(os.path.dirname(__file__), "..", "DEVICE_SESSION.json")
_state: dict = {"started_unix": time.time(), "stages": {}}
# RLock, not Lock: the SIGTERM handler runs on the main thread and
# calls _save(); with a plain Lock a signal landing inside _mutate's
# critical section would self-deadlock — and a TERM that hangs invites
# the SIGKILL that wedges the device claim.
_save_lock = threading.RLock()


def _mutate(fn) -> None:
    """Apply fn(_state) and persist, all under the save lock so
    json.dump never iterates a dict another thread is inserting into."""
    with _save_lock:
        fn(_state)
        tmp = RESULTS + ".tmp"
        with open(tmp, "w") as f:
            json.dump(_state, f, indent=1)
        os.replace(tmp, RESULTS)


def _save() -> None:
    _mutate(lambda st: None)


def _stage(name: str):
    def deco(fn):
        def run():
            t0 = time.time()
            try:
                out = fn()
                out = {"ok": True, **out}
            except Exception as e:
                out = {"ok": False, "error": repr(e)}
            out["seconds"] = round(time.time() - t0, 1)
            # merge, don't assign: the budget reporter may already have
            # recorded over_budget_s in this stage's entry
            _mutate(
                lambda st: st["stages"].setdefault(name, {}).update(out)
            )
            print(f"[{name}] {_state['stages'][name]}", flush=True)

        return run

    return deco


def _graceful_exit(signum, frame):
    # through _mutate: an unlocked insert here could race the budget
    # reporter's json.dump (the RLock makes this safe even if the
    # signal lands while this thread already holds the lock)
    _mutate(lambda st: st.__setitem__("interrupted", signum))
    sys.exit(128 + signum)


def install_handlers() -> None:
    """SIGTERM/SIGINT persist the stage state then exit. Called by the
    stage runners' mains — NOT at import: importers that only want
    _batch/_throughput (e.g. probe_pallas_fail) must not have a TERM
    overwrite DEVICE_SESSION.json with an empty session."""
    signal.signal(signal.SIGTERM, _graceful_exit)
    signal.signal(signal.SIGINT, _graceful_exit)


def _batch(n, seed=3):
    import numpy as np
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )
    from cryptography.hazmat.primitives.serialization import (
        Encoding,
        PublicFormat,
    )

    rng = np.random.default_rng(seed)
    keys = []
    for _ in range(64):
        sk = Ed25519PrivateKey.from_private_bytes(
            rng.integers(0, 256, 32, dtype=np.uint8).tobytes()
        )
        keys.append(
            (sk, sk.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw))
        )
    pks, msgs, sigs = [], [], []
    for i in range(n):
        sk, pk = keys[i % 64]
        msg = b"device-session-%08d" % i
        pks.append(pk)
        msgs.append(msg)
        sigs.append(sk.sign(msg))
    return pks, msgs, sigs


def _throughput(verifier, pks, msgs, sigs, reps=8, depth=4):
    ok = verifier.verify(pks, msgs, sigs)
    assert bool(ok.all()), "warm-up failed"
    t0 = time.perf_counter()
    handles = []
    all_ok = True
    for _ in range(reps):
        handles.append(verifier.dispatch(pks, msgs, sigs))
        if len(handles) >= depth:
            all_ok &= bool(verifier.gather(handles.pop(0)).all())
    for h in handles:
        all_ok &= bool(verifier.gather(h).all())
    dt = (time.perf_counter() - t0) / reps
    assert all_ok, "a pipelined batch failed verification"
    return len(pks) / dt


@_stage("rtt")
def stage_rtt():
    import jax
    import jax.numpy as jnp

    _state["devices"] = [str(d) for d in jax.devices()]
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros(8, jnp.int32)
    f(x).block_until_ready()
    ts = []
    for _ in range(10):
        t0 = time.perf_counter()
        f(x).block_until_ready()
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return {"rtt_ms_p50": round(ts[5] * 1e3, 2)}


@_stage("xla_tput")
def stage_xla():
    os.environ.pop("TM_TPU_PALLAS", None)
    from tendermint_tpu.ops.ed25519_kernel import Ed25519Verifier

    pks, msgs, sigs = _batch(8192)
    rate = _throughput(Ed25519Verifier(bucket_sizes=[8192]), pks, msgs, sigs)
    return {"sigs_per_s": round(rate, 1)}


@_stage("pallas_probe")
def stage_pallas_probe():
    """Time ONE Mosaic compile+run at bucket 128. The budget thread
    only reports; it never kills the process (a SIGKILL wedges the
    device claim server-side for hours)."""
    budget = float(os.environ.get("TM_PALLAS_BUDGET_S", "900"))
    os.environ["TM_TPU_PALLAS"] = "1"
    progress = {"t0": time.time(), "done": False}

    def reporter():
        while not progress["done"]:
            waited = time.time() - progress["t0"]
            if waited > budget:
                _mutate(
                    lambda st: st["stages"]
                    .setdefault("pallas_probe", {})
                    .__setitem__("over_budget_s", round(waited, 0))
                )
            time.sleep(30)

    threading.Thread(target=reporter, daemon=True).start()
    try:
        from tendermint_tpu.ops.ed25519_kernel import Ed25519Verifier

        pks, msgs, sigs = _batch(128, seed=5)
        v = Ed25519Verifier(bucket_sizes=[128])
        t0 = time.perf_counter()
        ok = v.verify(pks, msgs, sigs)  # first call: compile + run
        compile_s = time.perf_counter() - t0
        assert bool(ok.all())
        # a Pallas->XLA fallback inside dispatch() would also "pass":
        # check which program actually served the bucket
        used_pallas = v._is_pallas(v._compiled.get(v._bucket(128)))
        t0 = time.perf_counter()
        for _ in range(5):
            v.verify(pks, msgs, sigs)
        warm_s = (time.perf_counter() - t0) / 5
        return {
            "compile_s": round(compile_s, 1),
            "warm_run_s": round(warm_s, 4),
            "used_pallas": bool(used_pallas),
        }
    finally:
        progress["done"] = True
        os.environ.pop("TM_TPU_PALLAS", None)


@_stage("pallas_tput")
def stage_pallas_tput():
    probe = _state["stages"].get("pallas_probe", {})
    if not (probe.get("ok") and probe.get("used_pallas")):
        return {"skipped": "pallas probe did not succeed"}
    os.environ["TM_TPU_PALLAS"] = "1"
    try:
        from tendermint_tpu.ops.ed25519_kernel import Ed25519Verifier

        pks, msgs, sigs = _batch(8192)
        rate = _throughput(
            Ed25519Verifier(bucket_sizes=[8192]), pks, msgs, sigs
        )
        return {"sigs_per_s": round(rate, 1)}
    finally:
        os.environ.pop("TM_TPU_PALLAS", None)


@_stage("sr_tput")
def stage_sr():
    from tendermint_tpu.crypto.sr25519 import PrivKeySr25519
    from tendermint_tpu.ops.sr25519_kernel import Sr25519Verifier

    privs = [PrivKeySr25519.from_seed(bytes([i, 99]) + b"\x00" * 30)
             for i in range(64)]
    pks, msgs, sigs = [], [], []
    for i in range(8192):
        p = privs[i % 64]
        m = b"sr-session-%08d" % i
        pks.append(p.pub_key().bytes())
        msgs.append(m)
        sigs.append(p.sign(m))
    rate = _throughput(
        Sr25519Verifier(bucket_sizes=[8192]), pks, msgs, sigs, reps=4
    )
    return {"sigs_per_s": round(rate, 1)}


def main():
    install_handlers()
    # persist compilations so a re-run after a wedge resumes fast
    import jax

    cache = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
    )
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    for st in (stage_rtt, stage_xla, stage_pallas_probe,
               stage_pallas_tput, stage_sr):
        st()

    s = _state["stages"]
    xla = s.get("xla_tput", {}).get("sigs_per_s")
    pal = s.get("pallas_tput", {}).get("sigs_per_s")
    print("\n==== device session summary ====")
    print(json.dumps(s, indent=1))
    if xla and pal:
        print(
            f"pallas/xla = {pal / xla:.2f}x -> "
            + ("FLIP the default to Pallas" if pal > xla else "keep XLA")
        )


if __name__ == "__main__":
    main()
