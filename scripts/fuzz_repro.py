#!/usr/bin/env python3
"""Replay a tmmc witness trace (or re-run a named exploration) and
dump the flight-recorder timeline of every model node.

The model checker (tendermint_tpu/analysis/tmmc) emits violations as
replayable witnesses: (seed, config, explicit transition list). This
CLI is the other half of that contract — it re-executes a banked
witness deterministically on the REAL consensus implementation and
renders what each node's TimelineRecorder captured, so a red gate
finding turns into a per-node, per-height event narrative instead of
a fingerprint.

    python scripts/fuzz_repro.py trace.json           # replay a banked
                                                      # witness file
    python scripts/fuzz_repro.py trace.json --events  # full per-node
                                                      # event stream
    python scripts/fuzz_repro.py trace.json --json out.json
    python scripts/fuzz_repro.py --config gate --seed 0
                                                      # re-run a named
                                                      # scenario; on
                                                      # violation,
                                                      # minimize + dump
    python scripts/fuzz_repro.py --config gate --save witness.json
                                                      # bank the
                                                      # minimized trace

Exit codes: 0 — the outcome matched expectation (a trace carrying a
rule reproduced it; a rule-less trace or green exploration stayed
green); 1 — it did not (expected violation failed to reproduce, an
unexpected one appeared, or the exploration found violations).
"""

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from tendermint_tpu.analysis import tmmc  # noqa: E402
from tendermint_tpu.analysis.tmmc.explorer import (  # noqa: E402
    Trace,
    explore,
    minimize_trace,
    replay_trace,
)


def _fmt_transition(t) -> str:
    if t[0] == "t":
        return f"timeout@mc{t[1]}"
    return f"deliver@mc{t[1]} {t[2]}"


def _node_dump(node) -> dict:
    return {
        "moniker": node.moniker,
        "height": node.cs.rs.height,
        "round": node.cs.rs.round,
        "step": node.cs.rs.step,
        "store_height": node.block_store.height(),
        "detections": [list(d) for d in node.detections],
        "pending_evidence": len(node.evpool._pending),
        "events": [e.to_dict() for e in node.timeline.snapshot()],
    }


def _print_timeline(dump: dict, events: bool) -> None:
    for nd in dump["nodes"]:
        print(
            f"\n== {nd['moniker']}  h{nd['height']} r{nd['round']} "
            f"s{nd['step']}  store={nd['store_height']} "
            f"detections={len(nd['detections'])} "
            f"pending_evidence={nd['pending_evidence']} =="
        )
        evs = nd["events"]
        if not events:
            # phase view: drop the per-transition `step` churn, keep
            # the crossings (proposal/polka/quorum/commit/evidence)
            evs = [e for e in evs if e["kind"] != "step"]
        for e in evs:
            attrs = {
                k: v
                for k, v in e.items()
                if k
                not in ("seq", "kind", "height", "round", "step",
                        "t_mono_ns", "t_wall_ns")
            }
            extra = f"  {attrs}" if attrs else ""
            print(
                f"  [{e['seq']:>4}] h{e['height']} r{e['round']} "
                f"{e['kind']}{extra}"
            )


def _replay_and_dump(trace: Trace) -> dict:
    net, found, complete = replay_trace(trace)
    try:
        dump = {
            "config": trace.config,
            "seed": trace.seed,
            "rule": trace.rule,
            "transitions": [
                _fmt_transition(t) for t in trace.transitions
            ],
            "complete": complete,
            "violations": [
                {"rule": r, "message": m} for r, m in found
            ],
            "nodes": [_node_dump(n) for n in net.nodes],
        }
    finally:
        net.close()
        net.loop.close()
    return dump


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Replay a tmmc witness trace into a "
        "flight-recorder timeline dump."
    )
    ap.add_argument(
        "trace", nargs="?",
        help="witness trace JSON (as banked by --save or emitted by "
        "the gate); omit to run --config exploration instead",
    )
    ap.add_argument(
        "--config", default=None,
        help="named tmmc scenario to explore (gate, agreement-ab, "
        "accountability-ab) when no trace file is given",
    )
    ap.add_argument(
        "--seed", type=int, default=None,
        help="override the scenario's schedule seed",
    )
    ap.add_argument(
        "--events", action="store_true",
        help="print the FULL per-node event stream (default: phase "
        "crossings only)",
    )
    ap.add_argument(
        "--json", metavar="OUT",
        help="also write the machine-readable dump to OUT",
    )
    ap.add_argument(
        "--save", metavar="OUT",
        help="exploration mode: bank the minimized witness trace",
    )
    args = ap.parse_args(argv)

    if args.trace is None and args.config is None:
        ap.error("give a trace file or --config NAME")

    if args.trace is not None:
        with open(args.trace) as f:
            trace = Trace.from_json(json.load(f))
        dump = _replay_and_dump(trace)
        _print_timeline(dump, args.events)
        reproduced = [v["rule"] for v in dump["violations"]]
        if not dump["complete"]:
            print("\nreplay INCOMPLETE: a transition was not enabled "
                  "(trace does not match this tree)", file=sys.stderr)
        print(f"\nviolations: {reproduced or 'none'}")
        if args.json:
            with open(args.json, "w") as f:
                json.dump(dump, f, indent=1, sort_keys=True)
            print(f"wrote {args.json}")
        if trace.rule:
            ok = dump["complete"] and trace.rule in reproduced
            print(f"expected {trace.rule}: "
                  f"{'reproduced' if ok else 'NOT reproduced'}")
            return 0 if ok else 1
        return 1 if reproduced else 0

    cfg, budgets, seed = tmmc.named_config(args.config)
    if args.seed is not None:
        seed = args.seed
    print(f"exploring {args.config}: {cfg.describe()}")
    print(f"budgets {budgets.describe()} seed {seed}")
    result = explore(cfg, budgets, seed=seed, stop_at_first=True)
    st = result.stats
    print(
        f"states={st['states']} edges={st['edges']} "
        f"unique={st['unique_fingerprints']} "
        f"dedup_hits={st['dedup_hits']} "
        f"sleep_skips={st['sleep_skips']} "
        f"stopped_by={st['stopped_by']} wall={st['wall_s']}s"
    )
    if not result.violations:
        print("no violations within the horizon")
        return 0
    first = result.violations[0]
    print(f"\nVIOLATION {first.rule}: {first.message}")
    print(f"minimizing witness (depth {len(first.trace.transitions)})...")
    small = minimize_trace(first.trace)
    print(f"minimized depth {len(small.transitions)}")
    dump = _replay_and_dump(small)
    _print_timeline(dump, args.events)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(dump, f, indent=1, sort_keys=True)
        print(f"wrote {args.json}")
    if args.save:
        with open(args.save, "w") as f:
            json.dump(small.to_json(), f, indent=1, sort_keys=True)
        print(f"banked witness -> {args.save}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
