"""Bisect Mosaic lowering failures by compiling tile sub-segments as
individual Pallas kernels via the local compile-only topology.
Throwaway-grade tool; see scripts/aot_check.py for the stable checks."""

from __future__ import annotations

import os
import sys
import time
import traceback

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.experimental import pallas as pl  # noqa: E402
from jax.experimental import topologies  # noqa: E402
from jax.experimental.pallas import tpu as pltpu  # noqa: E402
from jax.experimental.shard_map import shard_map  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

TILE = 128


def pallas_wrap(fn, in_shapes, out_shape):
    """Wrap fn (pure jnp, batch-minor) in a single-tile pallas_call,
    hoisting trace-time consts exactly like ed25519_pallas._closed."""
    avals = [jax.ShapeDtypeStruct(s, jnp.int32) for s in in_shapes]
    cj = jax.make_jaxpr(fn)(*avals)
    consts = [np.asarray(c) for c in cj.consts]
    n_in = len(in_shapes)

    def kernel(*refs):
        ins = [r[...] for r in refs[:n_in]]
        cs = [r[...] for r in refs[n_in:-1]]
        out = jax.core.eval_jaxpr(cj.jaxpr, cs, *ins)
        refs[-1][...] = out[0].reshape(out_shape).astype(jnp.int32)

    def spec(s):
        return pl.BlockSpec(s, lambda *_: (0,) * len(s), memory_space=pltpu.VMEM)

    def call(*args):
        return pl.pallas_call(
            kernel,
            grid=(1,),
            in_specs=[spec(s) for s in in_shapes]
            + [spec(c.shape) for c in consts],
            out_specs=spec(out_shape),
            out_shape=jax.ShapeDtypeStruct(out_shape, jnp.int32),
        )(*args, *[jnp.asarray(c) for c in consts])

    return call


def main():
    from tendermint_tpu.ops import ed25519_kernel as K
    from tendermint_tpu.ops import edwards as E
    from tendermint_tpu.ops import field25519 as F

    topo = topologies.get_topology_desc(
        platform="tpu", topology_name="v5e:2x2"
    )
    mesh = topologies.make_mesh(topo, (4,), ("x",))

    L = F.NLIMBS
    cases = {
        "mod_l+nibbles": (
            lambda d: (K._nibbles_dev(K._mod_l_dev(d)),),
            [(64, TILE)],
            (64, TILE),
        ),
        "s_lt_l": (
            lambda s: (K._s_lt_l_dev(s).astype(jnp.int32)[None, :],),
            [(32, TILE)],
            (1, TILE),
        ),
        "fe_from_bytes": (
            lambda b: (K._fe_from_bytes_dev(b & K._TOPCLEAR),),
            [(32, TILE)],
            (L, TILE),
        ),
        "decompress": (
            lambda y, s: (
                E.decompress(y, s[0])[0][..., 0, :, :],
            ),
            [(L, TILE), (1, TILE)],
            (L, TILE),
        ),
        "decompress_ok": (
            lambda y, s: (
                E.decompress(y, s[0])[1].astype(jnp.int32)[None, :],
            ),
            [(L, TILE), (1, TILE)],
            (1, TILE),
        ),
    }
    which = sys.argv[1:] or list(cases)
    for name in which:
        fn, ins, out = cases[name]
        call = pallas_wrap(fn, ins, out)
        smfn = shard_map(
            call,
            mesh=mesh,
            in_specs=tuple(P() for _ in ins),
            out_specs=P(),
            check_rep=False,
        )
        args = [
            jax.ShapeDtypeStruct(s, jnp.int32, sharding=NamedSharding(mesh, P()))
            for s in ins
        ]
        t0 = time.perf_counter()
        try:
            jax.jit(smfn).lower(*args).compile()
            print(f"{name}: OK in {time.perf_counter() - t0:.1f}s", flush=True)
        except Exception as e:
            msg = repr(e)
            cut = msg.find("The MLIR operation")
            print(
                f"{name}: FAILED {time.perf_counter() - t0:.1f}s: "
                f"{msg[:200]} ... {msg[cut:cut + 220] if cut > 0 else ''}",
                flush=True,
            )


if __name__ == "__main__":
    main()
