"""Measure sharding overhead of the mesh-partitioned verify program.

The <5 ms 10k-commit target lives on an 8-chip v4-8 mesh this
environment cannot time (one tunneled chip). What CAN be measured here
is the other half of the division-by-8 arithmetic (PERF.md "The <5 ms
10k-validator floor"): how much EXTRA work the partitioned XLA program
does versus the single-device program on the same total batch.

Method: the virtual-device CPU mesh (the same
`xla_force_host_platform_device_count` mechanism the multi-chip dryrun
uses) executes the genuinely partitioned program — SPMD partitioning,
per-shard programs, the final validity-bitmap all-gather — but all
shards share this box's one physical core. So for a FIXED total batch,
wall time under n virtual devices ≈ wall time under 1 device plus the
sharding-induced overhead (partition bookkeeping + collectives). The
reported `overhead_vs_1dev` is that fraction; on a real mesh with n
physical chips, expected time ≈ t_1 x (1 + overhead) / n.

Each mesh size runs in a fresh subprocess (device count is fixed at
backend init). Results land in SHARD_SCALING.json and a PERF.md table.

Reference analog: the reference scales the same work across CPU
goroutines (crypto/ed25519/ed25519.go:202-237); its sync overhead is a
WaitGroup join, ours is one bool all-gather per batch.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BATCH = 512
REPS = 3
MESH_SIZES = (1, 2, 4, 8)

_CHILD = r"""
import json, sys, time
import numpy as np

n_dev = int(sys.argv[1])
batch = int(sys.argv[2])
reps = int(sys.argv[3])

from tendermint_tpu.parallel.sharding import ShardedEd25519Verifier, make_mesh
from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey
from cryptography.hazmat.primitives.serialization import Encoding, PublicFormat

rng = np.random.default_rng(7)
keys = []
for _ in range(64):
    sk = Ed25519PrivateKey.from_private_bytes(
        rng.integers(0, 256, 32, dtype=np.uint8).tobytes()
    )
    keys.append((sk, sk.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)))
pks, msgs, sigs = [], [], []
for i in range(batch):
    sk, pk = keys[i % 64]
    m = b"shard-scaling-%06d" % i
    pks.append(pk)
    msgs.append(m)
    sigs.append(sk.sign(m))

mesh = make_mesh()
assert mesh.devices.size == n_dev, (mesh.devices.size, n_dev)
v = ShardedEd25519Verifier(mesh, bucket_sizes=[batch])
t0 = time.perf_counter()
ok = v.verify(pks, msgs, sigs)
compile_s = time.perf_counter() - t0
assert bool(ok.all())
ts = []
for _ in range(reps):
    t0 = time.perf_counter()
    ok = v.verify(pks, msgs, sigs)
    ts.append(time.perf_counter() - t0)
    assert bool(ok.all())
ts.sort()
print(json.dumps({
    "n_dev": n_dev,
    "batch": batch,
    "compile_s": round(compile_s, 1),
    "wall_s_median": round(ts[len(ts) // 2], 3),
    "wall_s_all": [round(t, 3) for t in ts],
}))
"""


def main() -> None:
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    rows = []
    for n in MESH_SIZES:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n}"
        ).strip()
        # strip the axon sitecustomize: this is CPU-only work and must
        # not touch the tunnel claim (PERF.md device-claim discipline)
        env["PYTHONPATH"] = repo
        r = subprocess.run(
            [sys.executable, "-c", _CHILD, str(n), str(BATCH), str(REPS)],
            capture_output=True,
            text=True,
            env=env,
            cwd=repo,
            timeout=1800,
        )
        if r.returncode != 0:
            print(r.stdout)
            print(r.stderr[-2000:], file=sys.stderr)
            raise SystemExit(f"mesh size {n} failed")
        row = json.loads(r.stdout.strip().splitlines()[-1])
        rows.append(row)
        print(row, flush=True)
    t1 = rows[0]["wall_s_median"]
    for row in rows:
        row["overhead_vs_1dev"] = round(row["wall_s_median"] / t1 - 1.0, 4)
        # what a mesh of n PHYSICAL devices would take: conservative —
        # negative measured overhead (smaller per-shard working sets
        # are CPU-cache-friendlier) is clamped to zero rather than
        # projected as a superlinear win
        row["projected_n_phys_chips_s"] = round(
            t1 * (1.0 + max(0.0, row["overhead_vs_1dev"])) / row["n_dev"], 4
        )
    worst = max(r["overhead_vs_1dev"] for r in rows)
    if worst <= 0.0:
        verdict = (
            "measured overhead is non-positive at every mesh size: the "
            "partitioned program is cheaper per sig (smaller per-shard "
            "intermediates are cache-friendlier), i.e. partitioning "
            "itself costs nothing measurable and the divide-by-n mesh "
            "arithmetic holds"
        )
    else:
        verdict = (
            f"measured overhead is POSITIVE (worst {worst:+.1%}): "
            "partitioning adds real cost on this run; the divide-by-n "
            "mesh arithmetic must be discounted by this factor"
        )
    out = {
        "recorded_unix": time.time(),
        "note": (
            "fixed total batch on 1 physical core; n virtual devices "
            "execute the genuinely partitioned SPMD program on that "
            "one core, so wall(n)/wall(1)-1 bounds sharding-induced "
            "overhead (partition + final bitmap all-gather). " + verdict
        ),
        "rows": rows,
    }
    path = os.path.join(repo, "SHARD_SCALING.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
