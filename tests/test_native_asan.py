"""AddressSanitizer gate for the native batch kernel.

The reference's CI runs its native crypto under the Go race/memory
sanitizers on every change (Makefile test targets); here the analog is
an ASAN build of native/ed25519_batch.c driven through every exported
entry point (scripts/asan_check.py). Wired into the suite so a C
change can't land unswept — previously the sweep was manual-only
(VERDICT r4 weak #7). Skips cleanly where the toolchain or libasan is
unavailable.

Long-standing seed failure, DIAGNOSED: the sweep never had a memory
bug — the container ships no `cryptography` wheel (PR 1 gated the
dependency package-wide, but the ASAN driver still imported it to
mint test signatures), so the child died on ImportError before a
single entry point ran. The fix is a toolchain probe in
scripts/asan_check.py::_ed25519_keygen: prefer the wheel, else
substitute the repo's pure-Python RFC-8032 signer, PINNED against
RFC 8032 test vector 1 before the sweep trusts it. Nothing is
excluded — both signers emit identical deterministic signatures, so
the sweep keeps every MSM path and batch shape it always had.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECK = os.path.join(REPO, "scripts", "asan_check.py")


def _asan_available() -> bool:
    cc = os.environ.get("CC", "cc")
    try:
        out = subprocess.run(
            [cc, "-print-file-name=libasan.so"],
            capture_output=True,
            text=True,
            timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return False
    path = out.stdout.strip()
    # an unresolved -print-file-name echoes the bare name back
    return out.returncode == 0 and os.path.sep in path and os.path.exists(
        path
    )


@pytest.mark.slow
def test_native_kernel_asan_sweep():
    if os.environ.get("TM_TPU_NO_NATIVE"):
        pytest.skip("native disabled via TM_TPU_NO_NATIVE")
    if not _asan_available():
        pytest.skip("no C compiler with libasan on this host")
    # strip any ambient LD_PRELOAD (profilers, jemalloc) so it can't
    # leak into the ASAN-instrumented child and produce unrelated
    # reports; the script re-execs itself under ASAN's own preload and
    # exits nonzero on any report. Whole sweep measures ~7 s; the
    # timeout is only a hang cap.
    env = {k: v for k, v in os.environ.items() if k != "LD_PRELOAD"}
    proc = subprocess.run(
        [sys.executable, CHECK],
        capture_output=True,
        text=True,
        timeout=1200,
        env=env,
    )
    assert proc.returncode == 0, (
        "ASAN sweep failed:\n" + proc.stdout[-4000:] + proc.stderr[-4000:]
    )
