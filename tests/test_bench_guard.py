"""The bench's banked-line emission machinery.

The round-end driver parses exactly one JSON line from bench.py; these
pin the guarantees that line survives the observed failure modes (a
tunnel that dies mid-stage, an unserializable extra, a wedged claim)
without paying for a full bench run.
"""

import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))

import bench  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_emit():
    bench._EMIT.clear()
    bench._EMIT.update({"done": False, "line": None})
    yield
    bench._EMIT.clear()
    bench._EMIT.update({"done": False, "line": None})


def _line(extra=None):
    return {
        "metric": "m",
        "value": 1.5,
        "unit": "sigs/s/cpu",
        "vs_baseline": 2.0,
        "extra": extra if extra is not None else {},
    }


def test_emit_line_prints_exactly_once(capsys):
    bench._EMIT["line"] = _line()
    bench._emit_line()
    bench._emit_line()
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1
    assert json.loads(out[0])["value"] == 1.5


def test_emit_line_noop_without_banked_line(capsys):
    bench._emit_line()
    assert capsys.readouterr().out == ""
    assert not bench._EMIT["done"]


def test_emit_line_stall_tag(capsys):
    bench._EMIT["line"] = _line()
    bench._emit_line(stall="stage 'x' exceeded its budget")
    d = json.loads(capsys.readouterr().out)
    assert "exceeded" in d["extra"]["stall"]


def test_emit_line_minimal_fallback_on_unserializable_extra(capsys):
    bench._EMIT["line"] = _line(extra={"bad": object()})
    bench._emit_line(stall="why")
    d = json.loads(capsys.readouterr().out)
    # scalar headline fields survive; the poisoned extra is replaced
    assert d["value"] == 1.5 and d["unit"] == "sigs/s/cpu"
    assert "stall" in d["extra"]
    assert bench._EMIT["done"]


def test_emit_line_moves_cpu_alias_keys_to_side_file(
    capsys, tmp_path, monkeypatch
):
    """VERDICT weak #6 / next #7: the r5 line carried every key twice
    (plain + `_cpu` alias) and overflowed the driver's tail window
    (`parsed: null`). Aliases whose plain twin exists must leave the
    line for the side file; cpu-only primaries (no twin) stay."""
    side = tmp_path / "side.json"
    monkeypatch.setattr(bench, "_CPU_SIDE_FILE", str(side))
    bench._EMIT["line"] = _line(
        extra={
            "verify_commit_10k_p50_ms": 3.1,
            "verify_commit_10k_p50_cpu_ms": 24.2,
            "verify_commit_10k_breakdown_ms": {"host": 1},
            "verify_commit_10k_breakdown_cpu_ms": {"host": 9},
            "cpu_single_verify_sigs_per_s": 1000.0,  # primary, no twin
            "backend": "device",
        }
    )
    bench._emit_line()
    d = json.loads(capsys.readouterr().out)
    extra = d["extra"]
    assert "verify_commit_10k_p50_cpu_ms" not in extra
    assert "verify_commit_10k_breakdown_cpu_ms" not in extra
    assert extra["verify_commit_10k_p50_ms"] == 3.1
    assert extra["cpu_single_verify_sigs_per_s"] == 1000.0
    moved = json.loads(side.read_text())
    assert moved == {
        "verify_commit_10k_p50_cpu_ms": 24.2,
        "verify_commit_10k_breakdown_cpu_ms": {"host": 9},
    }
    # the live banked dict is untouched (stall-guard concurrency)
    assert "verify_commit_10k_p50_cpu_ms" in bench._EMIT["line"]["extra"]


def test_emit_line_keeps_cpu_alias_when_twin_is_placeholder(
    capsys, tmp_path, monkeypatch
):
    """Mid-device-run stall: the plain keys still hold the pre-seeded
    {'skipped': 'device stage not reached'} stubs (bench.py seeds them
    before the device stages) or an {'error': ...} from a failed
    stage — the `_cpu` alias is then the run's ONLY real measurement
    and must stay in the line, not be evicted to the side file."""
    side = tmp_path / "side.json"
    monkeypatch.setattr(bench, "_CPU_SIDE_FILE", str(side))
    bench._EMIT["line"] = _line(
        extra={
            "verify_commit_10k_p50_ms": {
                "skipped": "device stage not reached"
            },
            "verify_commit_10k_p50_cpu_ms": 24.2,
            "verify_commit_10k_warm": {"error": "DeviceTimeout(...)"},
            "verify_commit_10k_warm_cpu": {"p50_ms": 30.0},
        }
    )
    bench._emit_line(stall="stage 'device:commit_10k' exceeded its budget")
    d = json.loads(capsys.readouterr().out)
    assert d["extra"]["verify_commit_10k_p50_cpu_ms"] == 24.2
    assert d["extra"]["verify_commit_10k_warm_cpu"] == {"p50_ms": 30.0}
    assert not side.exists()


def test_emit_line_keeps_cpu_keys_without_twin(capsys, tmp_path, monkeypatch):
    """A fallback run where canonicalization did NOT happen (or a
    cpu-only stage) must not lose its only copy of a number."""
    side = tmp_path / "side.json"
    monkeypatch.setattr(bench, "_CPU_SIDE_FILE", str(side))
    bench._EMIT["line"] = _line(
        extra={"merkle_proof_batch_per_s_cpu": 42.0}
    )
    bench._emit_line()
    d = json.loads(capsys.readouterr().out)
    assert d["extra"]["merkle_proof_batch_per_s_cpu"] == 42.0
    assert not side.exists()


def test_emit_line_restores_aliases_when_side_file_unwritable(
    capsys, tmp_path, monkeypatch
):
    """Read-only checkout / full disk: if the side file can't be
    written, the evicted rows must go BACK into the line (data over
    line size) with an error marker — never silently vanish."""
    side = tmp_path / "no-such-dir" / "side.json"
    monkeypatch.setattr(bench, "_CPU_SIDE_FILE", str(side))
    bench._EMIT["line"] = _line(
        extra={
            "verify_commit_10k_p50_ms": 3.1,
            "verify_commit_10k_p50_cpu_ms": 24.2,
        }
    )
    bench._emit_line()
    d = json.loads(capsys.readouterr().out)
    assert d["extra"]["verify_commit_10k_p50_cpu_ms"] == 24.2
    assert "cpu_side_file_error" in d["extra"]


def test_probe_device_subprocess_honors_cpu_fallback_env(monkeypatch):
    monkeypatch.setenv("TM_BENCH_CPU_FALLBACK", "1")
    assert bench._probe_device_subprocess(5.0) is False


def test_stall_guard_emits_banked_line_and_exits_3():
    """End-to-end guard firing: a subprocess banks a line, arms the
    guard with a tiny budget, then blocks — the watcher must print the
    banked line with the stall tag and exit 3. (Subprocess because the
    guard exits via os._exit.)"""
    script = r"""
import sys, time
sys.path.insert(0, %r)
import bench
bench._EMIT["line"] = {"metric": "m", "value": 7, "unit": "u",
                       "vs_baseline": 1, "extra": {}}
g = bench._StallGuard(1.0)
g.tick("wedged-stage", 1.0)
time.sleep(60)
print("guard never fired")
sys.exit(0)
""" % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=50,
        env={**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 3, (r.returncode, r.stdout, r.stderr)
    d = json.loads(r.stdout.strip().splitlines()[-1])
    assert d["value"] == 7
    assert "wedged-stage" in d["extra"]["stall"]


def test_stall_guard_disarm_prevents_firing():
    script = r"""
import sys, time
sys.path.insert(0, %r)
import bench
bench._EMIT["line"] = {"metric": "m", "value": 7, "unit": "u",
                       "vs_baseline": 1, "extra": {}}
g = bench._StallGuard(1.0)
g.tick("s", 1.0)
g.disarm()
time.sleep(12)
bench._emit_line()
""" % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=40,
        env={**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, (r.returncode, r.stderr)
    d = json.loads(r.stdout.strip())
    assert "stall" not in d["extra"]


def test_tmlive_gate_row_never_initializes_jax():
    """The tmlive_gate row lives in the banked CPU block BEFORE the
    device probe: running it must never import jax (a wedged claim
    hangs backend init — the whole reason the CPU block is banked
    first). Run in a clean subprocess so this file's own imports don't
    mask a violation."""
    script = """
import sys
sys.path.insert(0, %r)
import bench
row = bench.bench_tmlive_gate()
assert row["wall_s"] > 0 and "findings" in row and "suppressed" in row
assert set(row["findings"]) == {
    "live-block-under-lock", "live-block-in-main-loop",
    "live-unbounded-blocking", "live-grow-unbounded",
}
assert "jax" not in sys.modules, "tmlive_gate dragged jax in"
print("OK")
""" % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=120,
        env={**os.environ, "PYTHONPATH": ""},
    )
    assert r.returncode == 0, (r.returncode, r.stderr)
    assert "OK" in r.stdout


def test_tmsafe_gate_row_never_initializes_jax():
    """Same contract for the tmsafe_gate row: banked CPU block, pure
    stdlib AST, jax must never load."""
    script = """
import sys
sys.path.insert(0, %r)
import bench
row = bench.bench_tmsafe_gate()
assert row["wall_s"] > 0 and "findings" in row and "suppressed" in row
assert set(row["findings"]) == {
    "safe-alloc-unbounded", "safe-index-unchecked",
    "safe-unvalidated-use", "safe-quadratic-decode",
}
assert row["entries"] >= 100 and row["sinks_cataloged"] >= 10
assert "jax" not in sys.modules, "tmsafe_gate dragged jax in"
print("OK")
""" % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=120,
        env={**os.environ, "PYTHONPATH": ""},
    )
    assert r.returncode == 0, (r.returncode, r.stderr)
    assert "OK" in r.stdout


def test_tmcost_gate_row_never_initializes_jax():
    """Same contract for the ISSUE-14 tmcost_gate row: banked CPU
    block, pure stdlib AST, jax must never load — and the row reads
    the gate's own stats (findings, suppressions, budget coverage)."""
    script = """
import sys
sys.path.insert(0, %r)
import bench
row = bench.bench_tmcost_gate()
assert row["wall_s"] > 0 and "findings" in row and "suppressed" in row
assert set(row["findings"]) == {
    "cost-superlinear", "cost-recompute",
    "cost-unclamped-alloc", "cost-budget",
}
assert row["roots"] >= 50 and row["budgeted"] == row["roots"]
assert "jax" not in sys.modules, "tmcost_gate dragged jax in"
print("OK")
""" % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=120,
        env={**os.environ, "PYTHONPATH": ""},
    )
    assert r.returncode == 0, (r.returncode, r.stderr)
    assert "OK" in r.stdout


def test_tmct_gate_row_never_initializes_jax():
    """Same contract for the ISSUE-20 tmct_gate row: banked CPU
    block, pure stdlib AST over the crypto plane, jax must never
    load — and the row reads the gate's own stats (per-rule findings,
    suppressions, the machine-derived source-catalog sizes) so it can
    never diverge from `scripts/lint.py --ct`."""
    script = """
import sys
sys.path.insert(0, %r)
import bench
row = bench.bench_tmct_gate()
assert row["wall_s"] > 0 and "findings" in row and "suppressed" in row
assert set(row["findings"]) == {
    "ct-secret-branch", "ct-secret-index", "ct-secret-compare",
    "ct-vartime-pow", "ct-leak-telemetry", "ct-leak-lifetime",
}
assert sum(row["findings"].values()) == 0, "head crypto plane is red"
assert row["privkey_classes"] >= 4 and row["secret_attrs"] >= 1
assert "jax" not in sys.modules, "tmct_gate dragged jax in"
# the secp commit rows ride the same banked CPU block: the
# pure-Python backend must never drag jax in either (small n so the
# guard stays cheap; the banked BENCH_SECP.json comes from full runs)
p50, p95 = bench.bench_commit_latency(
    12, reps=2, light=False, use_device=False, key_type="secp256k1"
)
assert p50 > 0 and p95 >= p50
assert "jax" not in sys.modules, "secp commit row dragged jax in"
print("OK")
""" % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=120,
        env={**os.environ, "PYTHONPATH": ""},
    )
    assert r.returncode == 0, (r.returncode, r.stderr)
    assert "OK" in r.stdout


def test_tmmc_gate_row_never_initializes_jax():
    """Same contract for the ISSUE-19 tmmc_gate row: the model
    harness drives the REAL consensus implementation with in-memory
    stores — pure-CPU protocol execution, jax must never load.
    TM_TPU_MC_BENCH_FAST shrinks the reduction horizon so this guard
    stays cheap; the banked full-run record (and its persist) is only
    written by real bench runs."""
    import json as _json

    script = """
import json, sys
sys.path.insert(0, %r)
import bench
row = bench.bench_tmmc_gate()
assert row["gate_wall_s"] > 0 and row["gate_states"] > 0
assert row["gate_violations"] == 0
assert row["reduction_x"] >= 1.0
assert "jax" not in sys.modules, "tmmc_gate dragged jax in"
print("ROW=" + json.dumps(row))
print("OK")
""" % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=300,
        env={**os.environ, "PYTHONPATH": "", "TM_TPU_MC_BENCH_FAST": "1"},
    )
    assert r.returncode == 0, (r.returncode, r.stderr)
    assert "OK" in r.stdout
    row = _json.loads(
        r.stdout.split("ROW=", 1)[1].splitlines()[0]
    )
    # fast mode must not have clobbered the banked full-run artifact
    banked = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_MC.json",
    )
    with open(banked) as f:
        full = _json.load(f)
    assert full["horizon_depth"] > row["horizon_depth"]


def test_serving_cache_row_never_initializes_jax():
    """The ISSUE-14 serving-cache A/B row drives the REAL light_blocks
    handler against proto-backed stub stores — pure codec + cache
    work, jax must never load. Tiny shape; the full-size medians land
    in BENCH_STATELESS.json on real runs."""
    script = """
import sys
sys.path.insert(0, %r)
import bench
row = bench.bench_serving_cache_page(
    n_vals=4, page=5, reps=1, rounds=1
)
assert row["page"] == 5 and row["cache_hits"] >= 5
for key in ("warm_serve_ms", "uncached_serve_ms", "speedup_warm"):
    assert row[key] > 0, key
assert "jax" not in sys.modules, "serving-cache row dragged jax in"
print("OK")
""" % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=300,
        env={**os.environ, "PYTHONPATH": ""},
    )
    assert r.returncode == 0, (r.returncode, r.stderr)
    assert "OK" in r.stdout


def test_load_smoke_row_never_initializes_jax():
    """The ISSUE-12 load row boots a live multi-node localnet and
    drives real HTTP/websocket traffic — all of it must stay off the
    jax backend (loadgen/localnet.py pins tpu.enable=false): the row
    lives in the banked CPU block BEFORE the device probe, where a
    wedged claim would hang backend init. Tiny shape here; the real
    BENCH_LOAD.json run uses the defaults."""
    script = """
import sys
sys.path.insert(0, %r)
import bench
row, report = bench.bench_load_smoke(
    n_nodes=2, duration_s=1.5, rate=40, subscribers=2, warmup_s=0.0
)
assert row["nodes"] == 2 and row["wall_s"] > 0
for key in ("requests_per_s", "sustained_txs_per_s",
            "committed_txs_per_s", "errors_total", "timeouts_total",
            "subscribers_held", "routes_p99_ms", "mempool_size_max"):
    assert key in row, key
assert row["subscribers_held"] == 2
assert report["schema"] == "bench_load/v1"
assert report["scenario"]["seed"] == 2026
for op, d in report["routes"].items():
    assert d["count"] > 0 and d["p999_ms"] >= d["p50_ms"] > 0, op
assert "jax" not in sys.modules, "load smoke dragged jax in"
print("OK")
""" % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=300,
        env={**os.environ, "PYTHONPATH": ""},
    )
    assert r.returncode == 0, (r.returncode, r.stderr)
    assert "OK" in r.stdout


def test_chaos_smoke_row_never_initializes_jax():
    """The ISSUE-13 chaos row boots live localnets, partitions and
    heals them, and reads the safety/recovery verdicts — all in the
    banked CPU block BEFORE the device probe, so none of it may touch
    the jax backend (loadgen/localnet.py pins tpu.enable=false; the
    fault plane is pure stdlib). One tiny 3-node minority-partition
    scenario here; the real BENCH_CHAOS.json run uses the shipped
    catalog."""
    script = """
import sys
sys.path.insert(0, %r)
import bench
from tendermint_tpu.loadgen import ChaosScenario
cs = ChaosScenario(
    name="minority_partition", kind="partition",
    spec={"isolate": [2]}, fault_s=1.0, baseline_s=0.5,
    recovery_slo_s=20.0,
)
row, report = bench.bench_chaos_smoke(
    n_nodes=3, seed=11, rate=25.0, scenarios=[cs]
)
assert row["scenarios"] == 1
assert report["schema"] == "bench_chaos/v1"
r = report["scenarios"][0]
assert r["safety_ok"] and r["heights_checked"] >= 1, r
assert r["recovered_within_slo"] and r["passed"], r
assert r["net_faults_applied"], "partition applied no faults"
assert "jax" not in sys.modules, "chaos smoke dragged jax in"
print("OK")
""" % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=300,
        env={**os.environ, "PYTHONPATH": ""},
    )
    assert r.returncode == 0, (r.returncode, r.stderr)
    assert "OK" in r.stdout


def test_byz_smoke_row_never_initializes_jax():
    """The ISSUE-18 byzantine row boots live localnets with the
    adversary plane armed, drives equivocation, and reads the
    safety/accountability verdicts — all in the banked CPU block
    BEFORE the device probe, so none of it may touch the jax backend
    (consensus/byzantine.py is pure stdlib; loadgen/localnet.py pins
    tpu.enable=false). One equivocation scenario here; the real
    BENCH_BYZ.json run uses the shipped catalog."""
    script = """
import sys
sys.path.insert(0, %r)
import bench
from tendermint_tpu.loadgen import ByzScenario
sc = ByzScenario(
    name="equivocate_prevote",
    spec="equivocate:h=4..5:step=prevote:seed={seed}",
    h_lo=4, h_hi=5, evidence_slo_s=20.0, baseline_s=0.5,
)
row, report = bench.bench_byz_smoke(
    n_nodes=4, seed=11, rate=25.0, scenarios=[sc]
)
assert row["scenarios"] == 1
assert report["schema"] == "bench_byz/v1"
r = report["scenarios"][0]
assert r["safety_ok"] and r["heights_checked"] >= 1, r
assert r["fired"] >= 1 and r["accountable"], r
assert r["evidence_committed"] >= 1 and r["passed"], r
assert row["evidence_committed_total"] >= 1
assert report["summary"]["tte_evidence_commit_s"], report["summary"]
from tendermint_tpu.consensus import byzantine
assert not byzantine.armed(), "the arc left the plane armed"
assert "jax" not in sys.modules, "byz smoke dragged jax in"
print("OK")
""" % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=300,
        env={**os.environ, "PYTHONPATH": ""},
    )
    assert r.returncode == 0, (r.returncode, r.stderr)
    assert "OK" in r.stdout


def test_profiler_rows_never_initialize_jax():
    """The ISSUE-16 rows (profiler_overhead, fanout_publish) live in
    the banked CPU block BEFORE the device probe: the sampler is pure
    threading/sys stdlib and the fan-out row is pure pubsub — jax must
    never load. Tiny shapes; the real numbers land in the banked line
    on full runs."""
    script = """
import sys
sys.path.insert(0, %r)
import bench
row = bench.bench_profiler_overhead(reps=20_000, window_s=0.1)
for key in ("disabled_label_ns", "armed_label_ns",
            "sampling_overhead_pct_97hz", "samples_in_window",
            "flood_stacks", "flood_collapsed_samples"):
    assert key in row, key
assert row["bounded"], row
from tendermint_tpu.libs import profiler
assert not profiler.is_enabled() and not profiler.labels_armed()
assert profiler.stats()["samples_total"] == 0  # row cleans up
row = bench.bench_fanout_publish(subs=32, publishes=200)
assert row["subs"] == 32 and row["deliveries_per_publish"] == 32
assert row["same_query_us"] > 0 and row["distinct_query_us"] > 0
assert "jax" not in sys.modules, "profiler rows dragged jax in"
print("OK")
""" % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=300,
        env={**os.environ, "PYTHONPATH": ""},
    )
    assert r.returncode == 0, (r.returncode, r.stderr)
    assert "OK" in r.stdout


def test_stateless_bulk_rows_never_initialize_jax():
    """The ISSUE-11 rows (merkle_multiproof_10k,
    light_sync_bulk_150vals) live in the banked CPU block BEFORE the
    device probe: pure hashlib/numpy + the CPU light client, jax must
    never load. Tiny shapes — the full-size A/B medians land in
    BENCH_STATELESS.json on real runs."""
    script = """
import sys
sys.path.insert(0, %r)
import bench
row = bench.bench_merkle_multiproof(n=200, k=16, reps=1, rounds=1)
assert row["leaves"] == 200 and row["k"] == 16
for key in ("per_proof_build_ms", "vector_build_ms", "vector_serve_ms",
            "speedup_cold", "speedup_serving", "verify_speedup"):
    assert key in row, key
row = bench.bench_light_sync_bulk(
    n_vals=4, n_headers=6, reps=1, rounds=1
)
assert row["headers"] == 6 and row["commit_memo_hits"] >= 1
for key in ("warm_client_headers_per_s", "warm_bulk_headers_per_s",
            "speedup_warm", "cold_bulk_headers_per_s"):
    assert row[key] > 0, key
assert "jax" not in sys.modules, "stateless bulk rows dragged jax in"
print("OK")
""" % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=300,
        env={**os.environ, "PYTHONPATH": ""},
    )
    assert r.returncode == 0, (r.returncode, r.stderr)
    assert "OK" in r.stdout
