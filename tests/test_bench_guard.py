"""The bench's banked-line emission machinery.

The round-end driver parses exactly one JSON line from bench.py; these
pin the guarantees that line survives the observed failure modes (a
tunnel that dies mid-stage, an unserializable extra, a wedged claim)
without paying for a full bench run.
"""

import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))

import bench  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_emit():
    bench._EMIT.clear()
    bench._EMIT.update({"done": False, "line": None})
    yield
    bench._EMIT.clear()
    bench._EMIT.update({"done": False, "line": None})


def _line(extra=None):
    return {
        "metric": "m",
        "value": 1.5,
        "unit": "sigs/s/cpu",
        "vs_baseline": 2.0,
        "extra": extra if extra is not None else {},
    }


def test_emit_line_prints_exactly_once(capsys):
    bench._EMIT["line"] = _line()
    bench._emit_line()
    bench._emit_line()
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1
    assert json.loads(out[0])["value"] == 1.5


def test_emit_line_noop_without_banked_line(capsys):
    bench._emit_line()
    assert capsys.readouterr().out == ""
    assert not bench._EMIT["done"]


def test_emit_line_stall_tag(capsys):
    bench._EMIT["line"] = _line()
    bench._emit_line(stall="stage 'x' exceeded its budget")
    d = json.loads(capsys.readouterr().out)
    assert "exceeded" in d["extra"]["stall"]


def test_emit_line_minimal_fallback_on_unserializable_extra(capsys):
    bench._EMIT["line"] = _line(extra={"bad": object()})
    bench._emit_line(stall="why")
    d = json.loads(capsys.readouterr().out)
    # scalar headline fields survive; the poisoned extra is replaced
    assert d["value"] == 1.5 and d["unit"] == "sigs/s/cpu"
    assert "stall" in d["extra"]
    assert bench._EMIT["done"]


def test_probe_device_subprocess_honors_cpu_fallback_env(monkeypatch):
    monkeypatch.setenv("TM_BENCH_CPU_FALLBACK", "1")
    assert bench._probe_device_subprocess(5.0) is False


def test_stall_guard_emits_banked_line_and_exits_3():
    """End-to-end guard firing: a subprocess banks a line, arms the
    guard with a tiny budget, then blocks — the watcher must print the
    banked line with the stall tag and exit 3. (Subprocess because the
    guard exits via os._exit.)"""
    script = r"""
import sys, time
sys.path.insert(0, %r)
import bench
bench._EMIT["line"] = {"metric": "m", "value": 7, "unit": "u",
                       "vs_baseline": 1, "extra": {}}
g = bench._StallGuard(1.0)
g.tick("wedged-stage", 1.0)
time.sleep(60)
print("guard never fired")
sys.exit(0)
""" % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=50,
        env={**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 3, (r.returncode, r.stdout, r.stderr)
    d = json.loads(r.stdout.strip().splitlines()[-1])
    assert d["value"] == 7
    assert "wedged-stage" in d["extra"]["stall"]


def test_stall_guard_disarm_prevents_firing():
    script = r"""
import sys, time
sys.path.insert(0, %r)
import bench
bench._EMIT["line"] = {"metric": "m", "value": 7, "unit": "u",
                       "vs_baseline": 1, "extra": {}}
g = bench._StallGuard(1.0)
g.tick("s", 1.0)
g.disarm()
time.sleep(12)
bench._emit_line()
""" % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=40,
        env={**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, (r.returncode, r.stderr)
    d = json.loads(r.stdout.strip())
    assert "stall" not in d["extra"]
