"""Byzantine and fault-injection tests over live networks
(reference models: internal/consensus/byzantine_test.go — a
double-signing validator driven through an in-process network —
and test/e2e/runner/perturb.go — kill/disconnect perturbations).
"""

import asyncio
import time

import pytest

from tendermint_tpu.config import Config
from tendermint_tpu.consensus.msgs import VoteMessage
from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.node import NodeKey, make_node
from tendermint_tpu.p2p.transport import MemoryNetwork, MemoryTransport
from tendermint_tpu.p2p.types import Envelope
from tendermint_tpu.privval import FilePV, MockPV
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.canonical import PREVOTE_TYPE
from tendermint_tpu.types.evidence import DuplicateVoteEvidence
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.vote import Vote

CHAIN = "byz-chain"


def run(coro):
    return asyncio.run(coro)


def _fast(cfg: Config) -> None:
    cfg.consensus.timeout_propose = 2.0
    cfg.consensus.timeout_prevote = 1.0
    cfg.consensus.timeout_precommit = 1.0
    cfg.consensus.timeout_commit = 0.2
    cfg.consensus.peer_gossip_sleep_duration = 0.01
    cfg.rpc.laddr = "tcp://127.0.0.1:0"


def _localnet(tmp_path, n, chain_id=CHAIN, db="memdb"):
    privs = [
        PrivKeyEd25519.from_seed(bytes([i + 120]) * 32) for i in range(n)
    ]
    genesis = GenesisDoc(
        chain_id=chain_id,
        genesis_time_ns=time.time_ns(),
        validators=[
            GenesisValidator(pub_key=p.pub_key(), power=10) for p in privs
        ],
    )
    net = MemoryNetwork()
    cfgs = []
    for i in range(n):
        cfg = Config()
        cfg.base.home = str(tmp_path / f"node{i}")
        cfg.base.chain_id = chain_id
        cfg.base.db_backend = db
        cfg.ensure_dirs()
        _fast(cfg)
        cfg.p2p.laddr = f"node{i}:26656"
        genesis.save_as(cfg.base.path(cfg.base.genesis_file))
        FilePV.from_priv_key(
            privs[i],
            cfg.base.path(cfg.priv_validator.key_file),
            cfg.base.path(cfg.priv_validator.state_file),
        ).save()
        cfgs.append(cfg)
    node_ids = [
        NodeKey.load_or_generate(c.base.path(c.base.node_key_file)).node_id
        for c in cfgs
    ]
    for i, cfg in enumerate(cfgs):
        cfg.p2p.persistent_peers = ",".join(
            f"{node_ids[j]}@node{j}:26656" for j in range(n) if j != i
        )
    nodes = [
        make_node(cfg, transport=MemoryTransport(net, f"node{i}:26656"))
        for i, cfg in enumerate(cfgs)
    ]
    return privs, genesis, net, cfgs, node_ids, nodes


def test_double_signing_validator_caught_evidenced_committed(tmp_path):
    """A validator that signs conflicting prevotes over the REAL
    reactor/vote-channel path is detected by honest peers, turned into
    DuplicateVoteEvidence, and committed in a block
    (reference: internal/consensus/byzantine_test.go:552)."""

    async def go():
        privs, genesis, net, cfgs, node_ids, nodes = _localnet(tmp_path, 4)
        byz_idx = 0
        byz_priv = privs[byz_idx]
        byz = nodes[byz_idx]
        # no double-sign protection on the byzantine node
        byz.privval = MockPV(byz_priv)

        for n in nodes:
            await n.start()
        try:
            cs = byz.consensus
            reactor = byz.consensus_reactor
            byz_addr = byz_priv.pub_key().address()
            attacked = asyncio.Event()

            orig_do_prevote = cs.do_prevote

            async def byz_do_prevote(height, round_):
                # honest prevote first (signed + gossiped normally)
                await orig_do_prevote(height, round_)
                if attacked.is_set() or cs.rs.proposal_block is None:
                    return
                # conflicting prevote for a fabricated block, sent over
                # the real vote channel to every peer
                order = {
                    v.address: i
                    for i, v in enumerate(cs.rs.validators.validators)
                }
                evil = Vote(
                    type=PREVOTE_TYPE,
                    height=height,
                    round=round_,
                    block_id=BlockID(
                        hash=b"\xde" * 32,
                        part_set_header=PartSetHeader(
                            total=1, hash=b"\xad" * 32
                        ),
                    ),
                    timestamp_ns=time.time_ns(),
                    validator_address=byz_addr,
                    validator_index=order[byz_addr],
                )
                await byz.privval.sign_vote(genesis.chain_id, evil)
                await reactor.vote_ch.send(
                    Envelope(message=VoteMessage(vote=evil), broadcast=True)
                )
                attacked.set()

            cs.do_prevote = byz_do_prevote

            # evidence should land in a committed block on honest nodes
            deadline = time.monotonic() + 120.0
            found = None
            while time.monotonic() < deadline and found is None:
                await asyncio.sleep(0.3)
                for n in nodes[1:]:
                    for h in range(1, n.block_store.height() + 1):
                        block = n.block_store.load_block(h)
                        if block is None:
                            continue
                        for ev in block.evidence:
                            if isinstance(ev, DuplicateVoteEvidence):
                                found = (n, h, ev)
                                break
            assert found is not None, "evidence never committed"
            _, height, ev = found
            assert ev.vote_a.validator_address == byz_addr
            assert ev.vote_b.validator_address == byz_addr
            assert ev.vote_a.block_id != ev.vote_b.block_id
            # the chain keeps making progress after the attack
            tip = max(n.block_store.height() for n in nodes[1:])
            await nodes[1].consensus.wait_for_height(tip + 1, timeout=60.0)
        finally:
            for n in nodes:
                await n.stop()

    run(go())


def test_kill_node_then_restart_catches_up(tmp_path):
    """Perturbation 'kill': stop one validator, let the others advance,
    restart it over the same home dir — block sync must bring it back
    to the tip (reference: test/e2e/runner/perturb.go kill + the
    blocksync switchover)."""

    async def go():
        privs, genesis, net, cfgs, node_ids, nodes = _localnet(
            tmp_path, 4, chain_id="kill-chain", db="sqlite"
        )
        for n in nodes:
            await n.start()
        try:
            await asyncio.gather(
                *(n.consensus.wait_for_height(3, timeout=120.0)
                  for n in nodes)
            )
            # kill node3
            await nodes[3].stop()
            survivors = nodes[:3]
            tip = max(n.block_store.height() for n in survivors)
            await asyncio.gather(
                *(n.consensus.wait_for_height(tip + 3, timeout=120.0)
                  for n in survivors)
            )
            # restart from the same home; must catch up via block sync
            revived = make_node(
                cfgs[3],
                transport=MemoryTransport(net, "node3:26656"),
            )
            await revived.start()
            nodes[3] = revived
            target = max(n.block_store.height() for n in survivors)
            deadline = time.monotonic() + 120.0
            while revived.block_store.height() < target:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"revived node at {revived.block_store.height()}, "
                        f"target {target}"
                    )
                await asyncio.sleep(0.3)
            # and it agrees with the others
            h = revived.block_store.height()
            assert (
                revived.block_store.load_block(h - 1).hash()
                == survivors[0].block_store.load_block(h - 1).hash()
            )
        finally:
            for n in nodes:
                if n.is_running:
                    await n.stop()

    run(go())


def test_disconnect_all_peers_then_reconnect(tmp_path):
    """Perturbation 'disconnect': sever every connection of one node;
    persistent-peer redial must restore them and consensus continues
    (reference: test/e2e/runner/perturb.go disconnect)."""

    async def go():
        privs, genesis, net, cfgs, node_ids, nodes = _localnet(
            tmp_path, 4, chain_id="disc-chain"
        )
        for n in nodes:
            await n.start()
        try:
            await asyncio.gather(
                *(n.consensus.wait_for_height(2, timeout=120.0)
                  for n in nodes)
            )
            victim = nodes[3]
            for pid in list(victim.router._peer_conns):
                victim.router._peer_down(pid)
            assert not victim.peer_manager.peers()
            # redial restores the mesh
            deadline = time.monotonic() + 60.0
            while len(victim.peer_manager.peers()) < 3:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"only {len(victim.peer_manager.peers())} peers back"
                    )
                await asyncio.sleep(0.2)
            # and consensus keeps advancing on every node
            tip = max(n.block_store.height() for n in nodes)
            await asyncio.gather(
                *(n.consensus.wait_for_height(tip + 2, timeout=120.0)
                  for n in nodes)
            )
        finally:
            for n in nodes:
                await n.stop()

    run(go())


def test_replay_initial_height_above_one(tmp_path):
    """Replay-matrix cell: a chain whose genesis initial_height > 1
    must recover from a crash at its FIRST height (WAL EndHeight maps
    to 0 — reference: internal/consensus/replay.go:127-129)."""

    async def go():
        priv = PrivKeyEd25519.from_seed(b"\x7f" * 32)
        genesis = GenesisDoc(
            chain_id="ih-chain",
            genesis_time_ns=time.time_ns(),
            initial_height=5,
            validators=[GenesisValidator(pub_key=priv.pub_key(), power=10)],
        )
        cfg = Config()
        cfg.base.home = str(tmp_path / "ih")
        cfg.base.chain_id = "ih-chain"
        cfg.base.db_backend = "sqlite"
        cfg.ensure_dirs()
        _fast(cfg)
        genesis.save_as(cfg.base.path(cfg.base.genesis_file))
        FilePV.from_priv_key(
            priv,
            cfg.base.path(cfg.priv_validator.key_file),
            cfg.base.path(cfg.priv_validator.state_file),
        ).save()
        node = make_node(cfg)
        await node.start()
        try:
            await node.consensus.wait_for_height(7, timeout=60.0)
            assert node.block_store.base() >= 5  # chain starts at 5
        finally:
            await node.stop()
        # restart: WAL replay over initial_height must not be skipped
        node2 = make_node(cfg)
        await node2.start()
        try:
            h = node2.block_store.height()
            await node2.consensus.wait_for_height(h + 2, timeout=60.0)
        finally:
            await node2.stop()

    run(go())
