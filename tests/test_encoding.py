"""Proto wire-format tests, cross-checked against the google.protobuf
runtime (available in the image) to pin exact byte compatibility."""

import struct

import pytest

from tendermint_tpu.encoding.proto import (
    FieldReader,
    ProtoWriter,
    decode_varint,
    encode_varint,
    encode_zigzag,
    decode_zigzag,
    iter_fields,
    length_prefixed,
    read_length_prefixed,
)


@pytest.mark.parametrize(
    "value,expected",
    [
        (0, b"\x00"),
        (1, b"\x01"),
        (127, b"\x7f"),
        (128, b"\x80\x01"),
        (300, b"\xac\x02"),
        (1 << 32, b"\x80\x80\x80\x80\x10"),
    ],
)
def test_varint(value, expected):
    assert encode_varint(value) == expected
    assert decode_varint(expected) == (value, len(expected))


def test_varint_negative_int64_is_ten_bytes():
    enc = encode_varint(-1)
    assert len(enc) == 10
    v, _ = decode_varint(enc)
    assert v == (1 << 64) - 1


def test_varint_fast_path_boundaries():
    """The encoder has interned 1-byte and computed 2-byte fast paths;
    pin their boundaries and that negatives (e.g. proposer priorities)
    take the 10-byte two's-complement path, not a fast path."""
    for v in (0x7F, 0x80, 0x3FFF, 0x4000, 0x4001):
        enc = encode_varint(v)
        dec, off = decode_varint(enc)
        assert (dec, off) == (v, len(enc))
    assert encode_varint(0x3FFF) == b"\xff\x7f"
    assert encode_varint(0x4000) == b"\x80\x80\x01"
    for v in (-2, -(1 << 30), -(1 << 62)):
        enc = encode_varint(v)
        assert len(enc) == 10
        assert decode_varint(enc)[0] == (v & ((1 << 64) - 1))


def test_zigzag_roundtrip():
    for v in (0, -1, 1, -2, 2, 2**31, -(2**31), 2**62):
        assert decode_zigzag(encode_zigzag(v)) == v


def test_writer_matches_protobuf_runtime():
    # Hand-build the same message with the installed protobuf runtime's
    # low-level encoder to confirm wire bytes are identical.
    from google.protobuf.internal import encoder

    buf = []
    add = buf.append
    encoder.UInt32Encoder(1, False, False)(add, 7, None)
    encoder.StringEncoder(2, False, False)(add, "chain-A", None)
    encoder.SFixed64Encoder(3, False, False)(add, -5, None)
    expected = b"".join(buf)

    w = ProtoWriter()
    w.uint(1, 7)
    w.string(2, "chain-A")
    w.sfixed64(3, -5)
    assert w.finish() == expected


def test_zero_values_omitted():
    w = ProtoWriter()
    w.uint(1, 0)
    w.string(2, "")
    w.bytes(3, b"")
    w.sfixed64(4, 0)
    assert w.finish() == b""


def test_embedded_message_and_reader():
    inner = ProtoWriter()
    inner.uint(1, 3)
    inner.bytes(2, b"ab")
    w = ProtoWriter()
    w.uint(1, 9)
    w.message(2, inner)
    w.message(3, None)  # omitted
    w.message(4, ProtoWriter())  # empty but present
    data = w.finish()

    r = FieldReader(data)
    assert r.uint(1) == 9
    assert r.get(3) is None
    assert r.get(4) == b""
    inner_r = FieldReader(r.bytes(2))
    assert inner_r.uint(1) == 3
    assert inner_r.bytes(2) == b"ab"


def test_field_order_enforced():
    w = ProtoWriter()
    w.uint(2, 1)
    with pytest.raises(ValueError):
        w.uint(1, 1)


def test_length_prefixed_roundtrip():
    msg = b"hello world"
    framed = length_prefixed(msg)
    got, off = read_length_prefixed(framed)
    assert got == msg and off == len(framed)


def test_iter_fields_fixed_types():
    w = ProtoWriter()
    w.sfixed64(1, -2)
    w.sfixed32(2, -3)
    fields = list(iter_fields(w.finish()))
    assert fields[0][0] == 1 and struct.unpack("<q", struct.pack("<Q", fields[0][2]))[0] == -2
    assert fields[1][0] == 2 and struct.unpack("<i", struct.pack("<I", fields[1][2]))[0] == -3


def test_vote_sign_template_matches_full_marshal():
    """VoteSignTemplate's spliced output must be byte-identical to the
    full canonical marshal for every flag/timestamp/height shape a
    commit can contain (the template is the hot path behind
    Commit.vote_sign_bytes)."""
    from tendermint_tpu.types.block_id import BlockID, PartSetHeader
    from tendermint_tpu.types.canonical import (
        PRECOMMIT_TYPE,
        VoteSignTemplate,
        vote_sign_bytes,
    )
    from tendermint_tpu.types.commit import Commit, CommitSig

    bid = BlockID(
        hash=b"\x11" * 32,
        part_set_header=PartSetHeader(total=3, hash=b"\x22" * 32),
    )
    for height, round_ in ((1, 0), (77, 4), (2**40, 1)):
        for blk in (bid, BlockID()):
            tpl = VoteSignTemplate(
                "tpl-chain", PRECOMMIT_TYPE, height, round_, blk
            )
            for ts in (0, 1, 999_999_999, 1_700_000_000_123_456_789):
                assert tpl.sign_bytes(ts) == vote_sign_bytes(
                    "tpl-chain", PRECOMMIT_TYPE, height, round_, blk, ts
                )

    # and through the Commit cache: mixed for-block / nil signatures
    sigs = [
        CommitSig.for_block(b"\x01" * 64, b"\xaa" * 20, 5_000_000_001),
        CommitSig.for_nil(b"\x02" * 64, b"\xbb" * 20, 6_000_000_002),
        CommitSig.for_block(b"\x03" * 64, b"\xcc" * 20, 7_000_000_003),
    ]
    commit = Commit(height=9, round=2, block_id=bid, signatures=sigs)
    for i in range(3):
        assert commit.vote_sign_bytes("tpl-chain", i) == commit.get_vote(
            i
        ).sign_bytes("tpl-chain")


def test_commit_sign_bytes_batch_matches_per_index():
    """sign_bytes_batch: None at absent indexes, byte-identical to the
    per-index path elsewhere, across mixed for-block/nil/absent sets."""
    from tendermint_tpu.types.block_id import BlockID, PartSetHeader
    from tendermint_tpu.types.commit import Commit, CommitSig

    bid = BlockID(
        hash=b"\x44" * 32,
        part_set_header=PartSetHeader(total=1, hash=b"\x55" * 32),
    )
    sigs = []
    for i in range(25):
        if i % 5 == 3:
            sigs.append(CommitSig.absent())
        elif i % 5 == 4:
            sigs.append(
                CommitSig.for_nil(
                    bytes([i]) * 64, bytes([i]) * 20, 10**9 * i + i
                )
            )
        else:
            sigs.append(
                CommitSig.for_block(
                    bytes([i]) * 64, bytes([i]) * 20, 10**9 * i + 7 * i
                )
            )
    commit = Commit(height=12, round=1, block_id=bid, signatures=sigs)
    batch = commit.sign_bytes_batch("batch-chain")
    for i, cs in enumerate(sigs):
        if cs.is_absent():
            assert batch[i] is None
        else:
            assert batch[i] == commit.get_vote(i).sign_bytes("batch-chain")


def test_native_sign_bytes_batch_matches_python():
    """native/signbytes.c must be byte-identical to the Python splice
    loop AND to the full per-vote marshal, across timestamp encoding
    edge cases (zero, nanos-only, seconds-only, negative, epoch+1)."""
    from tendermint_tpu.types.canonical import VoteSignTemplate
    from tendermint_tpu.types.block_id import BlockID, PartSetHeader
    from tendermint_tpu.native import signbytes_lib

    if signbytes_lib() is None:
        import pytest

        pytest.skip("no native toolchain")
    bid = BlockID(
        hash=b"\x11" * 32,
        part_set_header=PartSetHeader(total=3, hash=b"\x22" * 32),
    )
    tpl = VoteSignTemplate("native-chain", 2, 77, 4, bid)
    cases = [
        0,
        1,
        999_999_999,            # nanos only
        1_000_000_000,          # seconds only
        1_700_000_000_123_456_789,
        -1,                     # negative ns: floored divmod
        -1_000_000_001,
        2**62,
    ]
    native_rows = tpl._sign_bytes_batch_native(cases)
    assert native_rows is not None
    py_rows = [tpl.sign_bytes(ns) for ns in cases]
    assert native_rows == py_rows
    # out-of-int64 timestamps fall back to the Python loop
    assert tpl._sign_bytes_batch_native([2**70]) is None
    assert tpl.sign_bytes_batch([2**70]) == [tpl.sign_bytes(2**70)]
