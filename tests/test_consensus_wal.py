"""WAL, consensus message codec, and timeout ticker tests
(reference: internal/consensus/wal_test.go, ticker semantics)."""

import asyncio
import os
import struct

import pytest

from tendermint_tpu.consensus.msgs import (
    BlockPartMessage,
    EndHeightMessage,
    EventDataRoundStateWAL,
    HasVoteMessage,
    MsgInfo,
    NewRoundStepMessage,
    NewValidBlockMessage,
    ProposalMessage,
    ProposalPOLMessage,
    TimeoutInfo,
    VoteMessage,
    VoteSetBitsMessage,
    VoteSetMaj23Message,
    decode_msg,
    decode_timed_wal_message,
    encode_msg,
    encode_timed_wal_message,
)
from tendermint_tpu.consensus.ticker import TimeoutTicker
from tendermint_tpu.consensus.wal import (
    MAX_MSG_SIZE,
    WAL,
    WALDecodeError,
    iter_wal_records,
)
from tendermint_tpu.crypto.merkle import Proof
from tendermint_tpu.libs.bits import BitArray
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.canonical import PRECOMMIT_TYPE, PREVOTE_TYPE
from tendermint_tpu.types.part_set import Part
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.vote import Vote


def run(coro):
    return asyncio.get_event_loop_policy().new_event_loop().run_until_complete(coro)


def bid():
    return BlockID(hash=b"\x11" * 32, part_set_header=PartSetHeader(2, b"\x22" * 32))


# -- codec roundtrips --


@pytest.mark.parametrize(
    "msg",
    [
        NewRoundStepMessage(height=5, round=1, step=3, seconds_since_start_time=7, last_commit_round=0),
        NewValidBlockMessage(height=5, round=1, block_part_set_header=PartSetHeader(3, b"\x07" * 32), block_parts=BitArray.from_words(3, [0b101]), is_commit=True),
        ProposalMessage(proposal=Proposal(height=2, round=0, pol_round=-1, block_id=bid(), timestamp_ns=123456789, signature=b"\x01" * 64)),
        ProposalPOLMessage(height=4, proposal_pol_round=1, proposal_pol=BitArray.from_words(4, [0b1011])),
        BlockPartMessage(height=9, round=2, part=Part(index=1, bytes=b"chunk", proof=Proof(total=2, index=1, leaf_hash=b"\x03" * 32))),
        VoteMessage(vote=Vote(type=PREVOTE_TYPE, height=3, round=0, block_id=bid(), timestamp_ns=42, validator_address=b"\x05" * 20, validator_index=2, signature=b"\x06" * 64)),
        HasVoteMessage(height=3, round=0, type=PRECOMMIT_TYPE, index=7),
        VoteSetMaj23Message(height=3, round=0, type=PREVOTE_TYPE, block_id=bid()),
        VoteSetBitsMessage(height=3, round=0, type=PREVOTE_TYPE, block_id=bid(), votes=BitArray.from_words(5, [0b11010])),
    ],
    ids=lambda m: type(m).__name__,
)
def test_msg_envelope_roundtrip(msg):
    data = encode_msg(msg)
    back = decode_msg(data)
    assert back == msg


def test_wal_message_roundtrips():
    for msg in (
        MsgInfo(msg=HasVoteMessage(height=1, round=0, type=PREVOTE_TYPE, index=0), peer_id="peer1"),
        TimeoutInfo(duration_s=3.5, height=10, round=2, step=4),
        EndHeightMessage(height=33),
        EventDataRoundStateWAL(height=5, round=0, step="RoundStepPropose"),
    ):
        data = encode_timed_wal_message(1_700_000_000_000_000_000, msg)
        ts, back = decode_timed_wal_message(data)
        assert ts == 1_700_000_000_000_000_000
        assert back == msg


# -- WAL file behavior --


def wal_path(tmp_path):
    return str(tmp_path / "cs.wal" / "wal")


def test_wal_write_read_roundtrip(tmp_path):
    async def go():
        w = WAL(wal_path(tmp_path))
        await w.start()
        w.write(MsgInfo(msg=HasVoteMessage(height=1, round=0, type=PREVOTE_TYPE, index=3)))
        w.write_sync(TimeoutInfo(duration_s=1.0, height=1, round=0, step=3))
        w.write_end_height(1)
        await w.stop()

    run(go())
    msgs = [m for _, m in iter_wal_records(wal_path(tmp_path))]
    assert len(msgs) == 3
    assert isinstance(msgs[0], MsgInfo)
    assert isinstance(msgs[1], TimeoutInfo)
    assert msgs[2] == EndHeightMessage(height=1)


def test_wal_search_for_end_height(tmp_path):
    async def go():
        w = WAL(wal_path(tmp_path))
        await w.start()
        for h in (1, 2, 3):
            w.write(MsgInfo(msg=HasVoteMessage(height=h, round=0, type=PREVOTE_TYPE, index=h)))
            w.write_end_height(h)
        w.write(MsgInfo(msg=HasVoteMessage(height=4, round=0, type=PREVOTE_TYPE, index=4)))
        await w.stop()

        after2 = w.search_for_end_height(2)
        assert after2 is not None
        # messages of heights 3 and 4 (EndHeight markers skipped)
        hv = [m.msg.index for m in after2 if isinstance(m, MsgInfo)]
        assert hv == [3, 4]

        assert w.search_for_end_height(9) is None

    run(go())


def test_wal_torn_tail_truncated_on_restart(tmp_path):
    path = wal_path(tmp_path)

    async def write_good():
        w = WAL(path)
        await w.start()
        w.write_sync(MsgInfo(msg=HasVoteMessage(height=1, round=0, type=PREVOTE_TYPE, index=1)))
        await w.stop()

    run(write_good())
    size_good = os.path.getsize(path)
    # simulate crash mid-write: valid header, truncated body
    with open(path, "ab") as f:
        f.write(struct.pack(">II", 0xDEAD, 100) + b"short")

    async def restart():
        w = WAL(path)
        await w.start()
        await w.stop()

    run(restart())
    assert os.path.getsize(path) == size_good
    assert len(list(iter_wal_records(path))) == 1


def test_wal_corrupt_crc_stops_iteration(tmp_path):
    path = wal_path(tmp_path)

    async def go():
        w = WAL(path)
        await w.start()
        w.write_sync(EndHeightMessage(height=1))
        w.write_sync(EndHeightMessage(height=2))
        await w.stop()

    run(go())
    # flip a byte in the second record's payload
    with open(path, "r+b") as f:
        data = f.read()
        f.seek(len(data) - 1)
        f.write(bytes([data[-1] ^ 0xFF]))
    msgs = list(iter_wal_records(path))
    assert len(msgs) == 1  # stops at corruption


def test_wal_oversize_message_rejected(tmp_path):
    async def go():
        w = WAL(wal_path(tmp_path))
        await w.start()
        big = MsgInfo(
            msg=BlockPartMessage(
                part=Part(
                    index=0,
                    bytes=b"x" * (MAX_MSG_SIZE + 10),
                    proof=Proof(total=1, index=0, leaf_hash=b"\x00" * 32),
                )
            )
        )
        with pytest.raises(ValueError, match="too big"):
            w.write(big)
        await w.stop()

    run(go())


# -- rotation (autofile-group analog) --


def test_wal_rotates_and_replays_across_boundary(tmp_path):
    """Write more than two head-sizes of records through a small-limit
    WAL: the head must rotate into .NNN chunks, and
    search_for_end_height must find a marker that lives in a ROTATED
    chunk and return the records after it across the chunk boundary
    (reference: internal/libs/autofile/group.go:66-100 rotation;
    wal.go:202-254 group search)."""
    from tendermint_tpu.consensus.wal import iter_wal_group, wal_group_files

    path = wal_path(tmp_path)

    async def go():
        w = WAL(path, head_size_limit=1024)
        await w.start()
        n_heights = 40  # ~90 bytes/record * 3 records/height >> 2 heads
        for h in range(1, n_heights + 1):
            w.write(MsgInfo(msg=HasVoteMessage(height=h, round=0, type=PREVOTE_TYPE, index=h % 4)))
            w.write(TimeoutInfo(duration_s=1.0, height=h, round=0, step=3))
            w.write_end_height(h)
        await w.stop()
        return w

    w = run(go())
    files = wal_group_files(path)
    assert len(files) >= 3, f"expected rotation, group is {files}"
    assert os.path.getsize(path) < 1024 + 200  # head stays bounded
    # every record survives, in order, across all chunks
    heights = [
        m.height
        for _, m in iter_wal_group(path)
        if isinstance(m, EndHeightMessage)
    ]
    assert heights == list(range(1, 41))
    # EndHeight(5) lives in the FIRST chunk (rotated out of the head)
    first_chunk = [
        m for _, m in iter_wal_records(files[0])
        if isinstance(m, EndHeightMessage)
    ]
    assert 5 in [m.height for m in first_chunk]
    tail = w.search_for_end_height(5)
    assert tail is not None
    hv = [m.msg.index for m in tail if isinstance(m, MsgInfo)]
    assert hv[0] == 6 % 4, "replay must resume right after the marker"
    # it crossed at least one boundary: records from the last height
    # (in the head) are present too
    assert any(
        isinstance(m, MsgInfo) and m.msg.height == 40 for m in tail
    )


def test_wal_total_size_cap_prunes_oldest(tmp_path):
    """The group never exceeds the total-size limit: oldest chunks are
    deleted, the head survives, and a search for a pruned height
    reports None (reference: group.go:129 checkTotalSizeLimit)."""
    from tendermint_tpu.consensus.wal import wal_group_files

    path = wal_path(tmp_path)

    async def go():
        w = WAL(path, head_size_limit=2048, total_size_limit=8192)
        await w.start()
        for h in range(1, 300):
            w.write(MsgInfo(msg=HasVoteMessage(height=h, round=0, type=PREVOTE_TYPE, index=0)))
            w.write_end_height(h)
        await w.stop()
        return w

    w = run(go())
    files = wal_group_files(path)
    total = sum(os.path.getsize(p) for p in files)
    assert total <= 8192 + 2048, f"group too big: {total}"
    assert os.path.exists(path)  # head never pruned
    # early heights were pruned with their chunks
    assert w.search_for_end_height(1) is None
    # recent heights still replayable
    assert w.search_for_end_height(298) is not None


def test_wal_old_chunk_corruption_does_not_mask_tail(tmp_path):
    """Bit-rot in an OLD rotated chunk must not hide an intact recent
    EndHeight marker from crash recovery: the group search scans
    newest-first (reference: wal.go:202-254 backwards scan)."""
    from tendermint_tpu.consensus.wal import wal_group_files

    path = wal_path(tmp_path)

    async def go():
        w = WAL(path, head_size_limit=1024)
        await w.start()
        for h in range(1, 40):
            w.write(MsgInfo(msg=HasVoteMessage(height=h, round=0, type=PREVOTE_TYPE, index=h % 4)))
            w.write_end_height(h)
        await w.stop()
        return w

    w = run(go())
    files = wal_group_files(path)
    assert len(files) >= 3
    # corrupt a record in the OLDEST chunk
    with open(files[0], "r+b") as f:
        f.seek(20)
        b = f.read(1)
        f.seek(20)
        f.write(bytes([b[0] ^ 0xFF]))
    # recent-height recovery is unaffected
    tail = w.search_for_end_height(38)
    assert tail is not None
    assert any(
        isinstance(m, MsgInfo) and m.msg.height == 39 for m in tail
    )
    # a search whose suffix would CROSS the corrupt chunk fails loudly
    # (None) instead of assembling a replay history with a silent gap
    assert w.search_for_end_height(1) is None


def test_wal_restart_after_rotation_truncates_only_head(tmp_path):
    """A torn tail after rotation affects only the head; restart
    truncates it and the rotated chunks stay intact."""
    from tendermint_tpu.consensus.wal import iter_wal_group, wal_group_files

    path = wal_path(tmp_path)

    async def write_phase():
        w = WAL(path, head_size_limit=1024)
        await w.start()
        for h in range(1, 30):
            w.write(MsgInfo(msg=HasVoteMessage(height=h, round=0, type=PREVOTE_TYPE, index=0)))
            w.write_end_height(h)
        await w.stop()

    run(write_phase())
    n_before = len(list(iter_wal_group(path)))
    assert len(wal_group_files(path)) >= 2
    # crash mid-write on the head
    with open(path, "ab") as f:
        f.write(struct.pack(">II", 0xBEEF, 50) + b"torn")

    async def restart():
        w = WAL(path, head_size_limit=1024)
        await w.start()
        await w.stop()

    run(restart())
    assert len(list(iter_wal_group(path))) == n_before


# -- ticker --


def test_ticker_fires_and_ignores_stale():
    async def go():
        t = TimeoutTicker()
        await t.start()
        t.schedule(TimeoutInfo(duration_s=0.05, height=2, round=1, step=4))
        # stale schedules (older height/round) must be ignored
        t.schedule(TimeoutInfo(duration_s=0.01, height=1, round=0, step=4))
        t.schedule(TimeoutInfo(duration_s=0.01, height=2, round=0, step=4))
        ti = await asyncio.wait_for(t.timeout_queue.get(), timeout=1.0)
        assert (ti.height, ti.round, ti.step) == (2, 1, 4)
        assert t.timeout_queue.empty()
        await t.stop()

    run(go())


def test_ticker_newer_overrides_pending():
    async def go():
        t = TimeoutTicker()
        await t.start()
        t.schedule(TimeoutInfo(duration_s=10.0, height=1, round=0, step=4))
        t.schedule(TimeoutInfo(duration_s=0.02, height=1, round=1, step=4))
        ti = await asyncio.wait_for(t.timeout_queue.get(), timeout=1.0)
        assert ti.round == 1
        await t.stop()

    run(go())


def test_wal_generator_produces_replayable_log(tmp_path):
    """Node-driven WAL fixture (reference:
    internal/consensus/wal_generator.go): a real single-validator run
    writes the WAL; the log contains the genuine input sequencing —
    EndHeight markers per committed height, own votes, proposals — and
    replays through the same iterator the crash path uses."""
    import asyncio

    from tendermint_tpu.consensus.msgs import (
        EndHeightMessage,
        MsgInfo,
        ProposalMessage,
        VoteMessage,
    )
    from tendermint_tpu.consensus.wal import iter_wal_records
    from tendermint_tpu.consensus.wal_generator import generate_wal

    wal_path, genesis, priv = asyncio.run(
        generate_wal(str(tmp_path / "gen"), n_blocks=3)
    )
    msgs = [m for _, m in iter_wal_records(wal_path)]
    assert msgs, "generated WAL is empty"
    end_heights = [
        m.height for m in msgs if isinstance(m, EndHeightMessage)
    ]
    # one marker per committed height
    assert set(end_heights) >= {1, 2, 3}, end_heights
    votes = [
        mi.msg.vote
        for mi in (m for m in msgs if isinstance(m, MsgInfo))
        if isinstance(mi.msg, VoteMessage)
    ]
    props = [
        mi.msg
        for mi in (m for m in msgs if isinstance(m, MsgInfo))
        if isinstance(mi.msg, ProposalMessage)
    ]
    # a real run signs prevote+precommit per height and one proposal
    assert len(votes) >= 6 and len(props) >= 3
    assert all(v.signature for v in votes)

    # the tail after the LAST EndHeight replays like catchup does:
    # records for the in-progress height (possibly none if the node
    # stopped right at a boundary)
    from tendermint_tpu.consensus.wal import WAL

    w = WAL(wal_path)
    tail = w.search_for_end_height(max(end_heights))
    assert tail is not None


def test_wal_unknown_message_type_degrades_as_corruption(tmp_path):
    """A CRC-valid record whose payload doesn't decode (e.g. a WAL
    written by a newer binary with a new message type) must degrade
    like a torn/corrupt record — readers stop there — instead of
    crashing boot/crash-recovery with a ValueError (ADVICE r4)."""
    import zlib

    from tendermint_tpu.consensus.wal import _frame, iter_wal_group

    path = wal_path(tmp_path)

    async def go():
        w = WAL(path)
        await w.start()
        w.write(MsgInfo(msg=HasVoteMessage(
            height=1, round=0, type=PREVOTE_TYPE, index=0
        )))
        w.write_end_height(1)
        await w.stop()
        return w

    w = run(go())
    # every shape of CRC-valid-but-undecodable payload maps to
    # WALDecodeError: unknown type tag (ValueError) and a timestamp
    # field with the wrong wire type (TypeError in the decoder)
    from tendermint_tpu.consensus.wal import _decode_record

    for payload in (
        b"\xfe\xfd" + b"\x99" * 40,
        b"\x08\x01\x12\x04\x0a\x02\x08\x01",
    ):
        with pytest.raises(WALDecodeError):
            _decode_record(payload)

    # append a CRC-valid but undecodable record (unknown type tag)
    garbage = b"\xfe\xfd" + b"\x99" * 40
    with open(path, "ab") as f:
        f.write(_frame(garbage))
    assert zlib.crc32(garbage)  # sanity: the frame really is CRC-valid

    # all readers stop at the undecodable record without raising
    msgs = list(iter_wal_records(path))
    assert len(msgs) == 2
    assert list(iter_wal_group(path)) == msgs
    # group search (boot/crash recovery path) survives too
    assert w.search_for_end_height(1) == []

    # a node restart repairs the tail (truncates the undecodable
    # record, like the reference's corruption repair) so new records
    # land after the good prefix and stay reachable
    async def go2():
        w2 = WAL(path)
        await w2.start()
        w2.write_end_height(2)
        await w2.stop()
        return w2

    w2 = run(go2())
    assert w2.search_for_end_height(2) is not None


# -- injected storage faults (crypto/faults harness, ISSUE 3) --


def test_wal_short_write_fault_recovers_replayable_prefix(tmp_path):
    """A seeded short-write injected on the LAST append (the on-disk
    shape of a crash mid-write, produced by the fault harness instead
    of hand-truncating the file): restart must truncate the torn tail
    and search_for_end_height must still hand back the intact replay
    prefix."""
    from tendermint_tpu.crypto import faults

    path = wal_path(tmp_path)

    async def write_with_fault():
        w = WAL(path)
        await w.start()
        for h in (1, 2, 3):
            w.write(MsgInfo(msg=HasVoteMessage(height=h, round=0, type=PREVOTE_TYPE, index=h)))
            w.write_end_height(h)
        # the torn record: only a seeded prefix of the frame reaches
        # the file; the "crash" is the handle closing without repair
        with faults.inject("wal.write", mode="short_write", seed=9) as r:
            w.write(MsgInfo(msg=HasVoteMessage(height=4, round=0, type=PREVOTE_TYPE, index=0)))
            assert r.fired == 1
        w._f.flush()
        w._f.close()
        w._f = None
        await w.stop()

    run(write_with_fault())
    torn_size = os.path.getsize(path)

    async def restart():
        w = WAL(path)
        await w.start()
        await w.stop()
        return w

    w = run(restart())
    # the torn tail is gone; every complete record survived
    assert os.path.getsize(path) < torn_size
    msgs = [m for _, m in iter_wal_records(path)]
    ends = [m.height for m in msgs if isinstance(m, EndHeightMessage)]
    assert ends == [1, 2, 3]
    tail = w.search_for_end_height(2)
    assert tail is not None
    hv = [m.msg.index for m in tail if isinstance(m, MsgInfo)]
    assert hv == [3]


def test_wal_fsync_fault_at_rotation_propagates_and_recovers(tmp_path):
    """An fsync failure injected at the ROTATION boundary: the write
    that triggers rotation must surface the OSError (write_sync's
    durability promise cannot be silently dropped), and a restart over
    whatever reached disk must still recover a replayable prefix
    through the group scan."""
    from tendermint_tpu.crypto import faults
    from tendermint_tpu.consensus.wal import iter_wal_group

    path = wal_path(tmp_path)

    async def go():
        w = WAL(path, head_size_limit=512)
        await w.start()
        w.write_end_height(1)  # a durable marker before the fault
        # buffered appends only, so the next fsync consult is the
        # ROTATION's own (write() rotates once the head crosses 512)
        written = 0
        with faults.inject("wal.fsync", mode="io_error", times=1) as r:
            with pytest.raises(OSError, match="injected I/O fault"):
                for i in range(200):
                    w.write(MsgInfo(msg=HasVoteMessage(height=2, round=0, type=PREVOTE_TYPE, index=i % 4)))
                    written += 1
            assert r.fired == 1  # it was the rotation fsync that blew
        # crash: drop the handle without a clean stop (no repair pass)
        if w._f is not None:
            w._f.close()
            w._f = None
        return written

    completed = run(go())
    assert completed >= 1  # some records were accepted before the fault

    async def restart():
        w = WAL(path, head_size_limit=512)
        await w.start()
        await w.stop()
        return w

    w = run(restart())
    msgs = [m for _, m in iter_wal_group(path)]
    # the replayable prefix: the durable marker plus a contiguous run
    # of the buffered records (whatever reached the file before the
    # failed fsync; nothing reordered, nothing fabricated)
    assert isinstance(msgs[0], EndHeightMessage) and msgs[0].height == 1
    idxs = [m.msg.index for m in msgs[1:] if isinstance(m, MsgInfo)]
    assert idxs == [i % 4 for i in range(len(idxs))]
    # the record that TRIGGERED rotation hit the file before the fsync
    # blew, so recovery may see one more record than the writer counted
    assert len(idxs) <= completed + 1
    tail = w.search_for_end_height(1)
    assert tail is not None and len(tail) == len(idxs)
