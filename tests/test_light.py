"""Light client tests — sequential + skipping (bisection) verification,
backwards verification, trust root pinning, divergence detection
(reference model: light/client_test.go, light/verifier_test.go,
light/detector_test.go).
"""

import asyncio
import time

import pytest

from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.light import (
    Client,
    DivergenceError,
    LightBlockNotFoundError,
    LightClientError,
    LightStore,
    NewValSetCantBeTrustedError,
    Provider,
    TrustOptions,
    verify_adjacent,
    verify_backwards,
    verify_non_adjacent,
)
from tendermint_tpu.store.kv import MemKV
from tendermint_tpu.types import BlockID, Commit, CommitSig, Vote
from tendermint_tpu.types.canonical import PRECOMMIT_TYPE
from tendermint_tpu.types.header import Consensus, Header
from tendermint_tpu.types.light import LightBlock, SignedHeader
from tendermint_tpu.types.part_set import PartSetHeader
from tendermint_tpu.types.validator import Validator, ValidatorSet

CHAIN = "light-chain"
HOUR_NS = 3600 * 1_000_000_000


def run(coro):
    return asyncio.run(coro)


def val_pair(seed: int, power: int = 10):
    pk = PrivKeyEd25519.from_seed(bytes([seed]) * 32)
    return Validator(pub_key=pk.pub_key(), voting_power=power), pk


def make_set(seeds, power=10):
    pairs = [val_pair(s, power) for s in seeds]
    vals = ValidatorSet([v for v, _ in pairs])
    by_addr = {v.address: pk for v, pk in pairs}
    privs = [by_addr[v.address] for v in vals.validators]
    return vals, privs


def build_chain(
    n_heights,
    seeds_at=None,
    base_time_ns=None,
    app_hash=b"\x07" * 32,
    chain_id=CHAIN,
):
    """A verifiable chain of LightBlocks 1..n_heights.

    `seeds_at(h)` returns the validator seed list at height h (controls
    churn); default is a static 4-validator set."""
    if seeds_at is None:
        seeds_at = lambda h: [1, 2, 3, 4]  # noqa: E731
    if base_time_ns is None:
        base_time_ns = time.time_ns() - n_heights * 2_000_000_000
    blocks = {}
    prev_bid = BlockID()
    for h in range(1, n_heights + 1):
        vals, privs = make_set(seeds_at(h))
        next_vals, _ = make_set(seeds_at(h + 1))
        header = Header(
            version=Consensus(block=11),
            chain_id=chain_id,
            height=h,
            time_ns=base_time_ns + h * 1_000_000_000,
            last_block_id=prev_bid,
            validators_hash=vals.hash(),
            next_validators_hash=next_vals.hash(),
            app_hash=app_hash,
            proposer_address=vals.validators[0].address,
        )
        bid = BlockID(
            hash=header.hash(),
            part_set_header=PartSetHeader(total=1, hash=b"\x22" * 32),
        )
        sigs = []
        for i, v in enumerate(vals.validators):
            vote = Vote(
                type=PRECOMMIT_TYPE,
                height=h,
                round=0,
                block_id=bid,
                timestamp_ns=header.time_ns,
                validator_address=v.address,
                validator_index=i,
            )
            vote.signature = privs[i].sign(vote.sign_bytes(chain_id))
            sigs.append(
                CommitSig.for_block(
                    vote.signature, v.address, vote.timestamp_ns
                )
            )
        commit = Commit(height=h, round=0, block_id=bid, signatures=sigs)
        blocks[h] = LightBlock(
            signed_header=SignedHeader(header=header, commit=commit),
            validator_set=vals,
        )
        prev_bid = bid
    return blocks


class DictProvider(Provider):
    def __init__(self, blocks, id_="dict"):
        self.blocks = blocks
        self._id = id_
        self.reported = []

    def id(self):
        return self._id

    async def light_block(self, height):
        if height == 0:
            height = max(self.blocks)
        if height not in self.blocks:
            raise LightBlockNotFoundError(str(height))
        return self.blocks[height]

    async def report_evidence(self, ev):
        self.reported.append(ev)


def make_client(blocks, witnesses=None, sequential=False, store=None,
                trust_height=1, period_ns=200 * HOUR_NS):
    root = blocks[trust_height]
    return Client(
        CHAIN,
        TrustOptions(
            period_ns=period_ns,
            height=trust_height,
            hash=root.signed_header.hash(),
        ),
        DictProvider(blocks, "primary"),
        witnesses if witnesses is not None else [],
        store if store is not None else LightStore(MemKV()),
        sequential=sequential,
    )


# ---------------------------------------------------------------------------
# verifier unit tests


class TestVerifier:
    def test_adjacent_ok(self):
        blocks = build_chain(3)
        now = time.time_ns()
        verify_adjacent(
            CHAIN,
            blocks[1].signed_header,
            blocks[2].signed_header,
            blocks[2].validator_set,
            200 * HOUR_NS,
            now,
        )

    def test_non_adjacent_ok_same_vals(self):
        blocks = build_chain(5)
        now = time.time_ns()
        verify_non_adjacent(
            CHAIN,
            blocks[1].signed_header,
            blocks[1].validator_set,
            blocks[5].signed_header,
            blocks[5].validator_set,
            200 * HOUR_NS,
            now,
        )

    def test_non_adjacent_full_churn_untrusted(self):
        # validator set at height 8 shares nobody with height 1
        def seeds(h):
            if h >= 6:
                return [11, 12, 13, 14]
            return [1, 2, 3, 4]

        blocks = build_chain(8, seeds_at=seeds)
        now = time.time_ns()
        with pytest.raises(NewValSetCantBeTrustedError):
            verify_non_adjacent(
                CHAIN,
                blocks[1].signed_header,
                blocks[1].validator_set,
                blocks[8].signed_header,
                blocks[8].validator_set,
                200 * HOUR_NS,
                now,
            )

    def test_backwards_ok_and_tampered(self):
        blocks = build_chain(3)
        verify_backwards(
            CHAIN, blocks[2].signed_header, blocks[3].signed_header
        )
        with pytest.raises(Exception):
            verify_backwards(
                CHAIN, blocks[1].signed_header, blocks[3].signed_header
            )

    # boundary cells modeled on the reference's model-based verifier
    # traces (light/mbt/driver_test.go): header-field checks must fire
    # before any signature work

    @staticmethod
    def _resign(header, seeds=(1, 2, 3, 4)):
        """A properly signed SignedHeader for a (mutated) header, so
        header-field checks are reached instead of hash linkage."""
        import dataclasses

        vals, privs = make_set(list(seeds))
        header = dataclasses.replace(header, validators_hash=vals.hash())
        bid = BlockID(
            hash=header.hash(),
            part_set_header=PartSetHeader(total=1, hash=b"\x22" * 32),
        )
        sigs = []
        for i, v in enumerate(vals.validators):
            vote = Vote(
                type=PRECOMMIT_TYPE,
                height=header.height,
                round=0,
                block_id=bid,
                timestamp_ns=header.time_ns,
                validator_address=v.address,
                validator_index=i,
            )
            sigs.append(
                CommitSig.for_block(
                    privs[i].sign(vote.sign_bytes(CHAIN)),
                    v.address,
                    vote.timestamp_ns,
                )
            )
        commit = Commit(
            height=header.height, round=0, block_id=bid, signatures=sigs
        )
        return SignedHeader(header=header, commit=commit), vals

    def test_rejects_non_monotonic_header_time(self):
        import dataclasses

        from tendermint_tpu.light.errors import InvalidHeaderError

        blocks = build_chain(3)
        bad_header = dataclasses.replace(
            blocks[2].signed_header.header,
            time_ns=blocks[1].signed_header.header.time_ns,
        )
        bad, bad_vals = self._resign(bad_header)
        with pytest.raises(InvalidHeaderError, match="time"):
            verify_adjacent(
                CHAIN,
                blocks[1].signed_header,
                bad,
                bad_vals,
                200 * HOUR_NS,
                time.time_ns(),
            )

    def test_rejects_header_time_from_future(self):
        import dataclasses

        from tendermint_tpu.light.errors import InvalidHeaderError

        blocks = build_chain(3)
        bad_header = dataclasses.replace(
            blocks[2].signed_header.header,
            time_ns=time.time_ns() + HOUR_NS,
        )
        bad, bad_vals = self._resign(bad_header)
        with pytest.raises(InvalidHeaderError, match="future"):
            verify_adjacent(
                CHAIN,
                blocks[1].signed_header,
                bad,
                bad_vals,
                200 * HOUR_NS,
                time.time_ns(),
            )

    def test_rejects_validator_set_hash_mismatch(self):
        from tendermint_tpu.light.errors import InvalidHeaderError

        blocks = build_chain(3)
        wrong_vals, _ = make_set([21, 22, 23, 24])
        with pytest.raises(InvalidHeaderError, match="validators_hash"):
            verify_adjacent(
                CHAIN,
                blocks[1].signed_header,
                blocks[2].signed_header,
                wrong_vals,
                200 * HOUR_NS,
                time.time_ns(),
            )

    def test_trust_level_bounds(self):
        from tendermint_tpu.types.validation import Fraction

        blocks = build_chain(5)
        now = time.time_ns()
        for bad in (Fraction(1, 4), Fraction(4, 3), Fraction(1, 0)):
            with pytest.raises(ValueError, match="trust level"):
                verify_non_adjacent(
                    CHAIN,
                    blocks[1].signed_header,
                    blocks[1].validator_set,
                    blocks[5].signed_header,
                    blocks[5].validator_set,
                    200 * HOUR_NS,
                    now,
                    trust_level=bad,
                )
        # exactly 1/3 is the allowed lower bound
        verify_non_adjacent(
            CHAIN,
            blocks[1].signed_header,
            blocks[1].validator_set,
            blocks[5].signed_header,
            blocks[5].validator_set,
            200 * HOUR_NS,
            now,
            trust_level=Fraction(1, 3),
        )

    def test_expired_trusted_header_rejected(self):
        from tendermint_tpu.light.errors import OldHeaderExpiredError

        base = time.time_ns() - 100 * HOUR_NS
        blocks = build_chain(3, base_time_ns=base)
        with pytest.raises(OldHeaderExpiredError):
            verify_adjacent(
                CHAIN,
                blocks[1].signed_header,
                blocks[2].signed_header,
                blocks[2].validator_set,
                HOUR_NS,  # trusting period long expired
                time.time_ns(),
            )


# ---------------------------------------------------------------------------
# store


def test_light_store_roundtrip_and_prune():
    blocks = build_chain(10)
    store = LightStore(MemKV())
    for lb in blocks.values():
        store.save_light_block(lb)
    assert store.size() == 10
    assert store.latest_light_block().height == 10
    assert store.first_light_block().height == 1
    assert store.light_block_before(5).height == 4
    store.prune(3)
    assert store.size() == 3
    assert store.first_light_block().height == 8


# ---------------------------------------------------------------------------
# client


def test_client_sequential_sync():
    blocks = build_chain(12)
    client = make_client(blocks, sequential=True)

    async def go():
        lb = await client.verify_light_block_at_height(12)
        assert lb.height == 12
        # sequential stored every interim header
        assert client.store.size() == 12

    run(go())


def test_client_skipping_single_hop():
    blocks = build_chain(30)
    client = make_client(blocks)

    async def go():
        lb = await client.verify_light_block_at_height(30)
        assert lb.height == 30
        # static validator set: one non-adjacent hop, no interim fetches
        assert client.store.size() == 2

    run(go())


def test_client_skipping_bisects_through_churn():
    # one validator replaced every 3 heights: height 13+ shares nobody
    # with height 1, forcing pivots
    def seeds(h):
        base = [1, 2, 3, 4]
        for i in range((h - 1) // 3):
            base[i % 4] = 11 + i
        return base

    blocks = build_chain(16, seeds_at=seeds)
    client = make_client(blocks)

    async def go():
        lb = await client.verify_light_block_at_height(16)
        assert lb.height == 16
        assert client.store.size() > 2  # pivots were stored

    run(go())

    # every stored block must be part of the real chain
    for h in range(1, 17):
        stored = client.store.light_block(h)
        if stored is not None:
            assert stored.signed_header.hash() == blocks[h].signed_header.hash()


def test_client_backwards_verification():
    blocks = build_chain(10)
    client = make_client(blocks, trust_height=8)

    async def go():
        lb = await client.verify_light_block_at_height(3)
        assert lb.height == 3
        assert (
            lb.signed_header.hash() == blocks[3].signed_header.hash()
        )

    run(go())


def test_client_rejects_wrong_trust_hash():
    blocks = build_chain(3)
    client = Client(
        CHAIN,
        TrustOptions(period_ns=200 * HOUR_NS, height=1, hash=b"\x13" * 32),
        DictProvider(blocks),
        [],
        LightStore(MemKV()),
    )
    with pytest.raises(LightClientError):
        run(client.initialize())


def test_client_rejects_expired_root():
    blocks = build_chain(3, base_time_ns=time.time_ns() - 400 * HOUR_NS)
    client = make_client(blocks, period_ns=1 * HOUR_NS)
    with pytest.raises(LightClientError):
        run(client.initialize())


def test_client_primary_failover_to_witness():
    blocks = build_chain(8)
    empty = DictProvider({1: blocks[1]}, "flaky")
    good = DictProvider(blocks, "witness")
    client = Client(
        CHAIN,
        TrustOptions(
            period_ns=200 * HOUR_NS,
            height=1,
            hash=blocks[1].signed_header.hash(),
        ),
        empty,
        [good],
        LightStore(MemKV()),
    )

    async def go():
        lb = await client.verify_light_block_at_height(8)
        assert lb.height == 8
        assert client.primary.id() == "witness"

    run(go())


def test_detector_catches_forked_witness():
    """A witness serving a *verifiable* conflicting header at the target
    height is a light-client attack: evidence is reported and the
    client halts (reference: light/detector_test.go)."""
    blocks = build_chain(8)
    fork = build_chain(8, app_hash=b"\x66" * 32)  # same vals, different state
    # sanity: same height, different hash, both properly signed
    assert (
        blocks[8].signed_header.hash() != fork[8].signed_header.hash()
    )
    witness = DictProvider(fork, "forked-witness")
    client = make_client(blocks, witnesses=[witness])

    async def go():
        with pytest.raises(DivergenceError) as exc_info:
            await client.verify_light_block_at_height(8)
        assert exc_info.value.evidence
        assert witness.reported  # evidence went to the witness too

    run(go())


def test_detector_drops_garbage_witness():
    blocks = build_chain(8)
    garbage = build_chain(8, chain_id="other-chain")
    witness = DictProvider(garbage, "garbage-witness")
    honest = DictProvider(blocks, "honest-witness")
    client = make_client(blocks, witnesses=[witness, honest])

    async def go():
        lb = await client.verify_light_block_at_height(8)
        assert lb.height == 8
        ids = [w.id() for w in client.witnesses]
        assert "garbage-witness" not in ids
        assert "honest-witness" in ids

    run(go())


def test_client_update_to_latest():
    blocks = build_chain(6)
    client = make_client(blocks)

    async def go():
        lb = await client.update()
        assert lb.height == 6
        assert await client.update() is None  # already latest

    run(go())


def test_client_sequential_windowed_multiwindow():
    """A sync spanning several SEQUENTIAL_BATCH_HOPS windows stores
    every interim header, exactly like the one-hop loop. Group
    affinity is pinned explicitly (the default depends on whether the
    native batch kernel built — see test_crypto's affinity-policy
    tests) so the merged-window path deterministically runs."""
    from tendermint_tpu.crypto.batch import (
        group_affinity_state,
        restore_group_affinity,
        set_group_affinity,
    )
    from tendermint_tpu.light.client import SEQUENTIAL_BATCH_HOPS

    n = SEQUENTIAL_BATCH_HOPS * 2 + 5
    blocks = build_chain(n)
    client = make_client(blocks, sequential=True)

    async def go():
        lb = await client.verify_light_block_at_height(n)
        assert lb.height == n
        assert client.store.size() == n

    prev = group_affinity_state()
    set_group_affinity(SEQUENTIAL_BATCH_HOPS)
    try:
        run(go())
    finally:
        restore_group_affinity(prev)


def test_client_sequential_windowed_bad_sig_exact_error():
    """A corrupted commit signature mid-window must surface the exact
    per-height error via the fallback path, with every hop before it
    verified and stored — reference one-hop semantics."""
    from tendermint_tpu.light.client import SEQUENTIAL_BATCH_HOPS

    n = SEQUENTIAL_BATCH_HOPS + 8
    bad_h = SEQUENTIAL_BATCH_HOPS + 3  # inside the second window
    from tendermint_tpu.crypto.batch import (
        group_affinity_state,
        restore_group_affinity,
        set_group_affinity,
    )
    from tendermint_tpu.light.errors import InvalidHeaderError

    blocks = build_chain(n)
    bad = blocks[bad_h]
    sigs = list(bad.signed_header.commit.signatures)
    s0 = sigs[0]
    sigs[0] = CommitSig.for_block(
        s0.signature[:-1] + bytes([s0.signature[-1] ^ 1]),
        s0.validator_address,
        s0.timestamp_ns,
    )
    bad.signed_header.commit.signatures = sigs
    client = make_client(blocks, sequential=True)

    async def go():
        with pytest.raises(InvalidHeaderError):
            await client.verify_light_block_at_height(n)
        # every height before the corruption verified and stored
        assert client.store.light_block(bad_h - 1) is not None
        assert client.store.light_block(bad_h) is None

    prev = group_affinity_state()
    set_group_affinity(SEQUENTIAL_BATCH_HOPS)
    try:
        run(go())
    finally:
        restore_group_affinity(prev)
