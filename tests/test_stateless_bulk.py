"""Bulk stateless serving (ISSUE 11): vectorized merkle multi-proofs,
batched light-client verification, and the bulk `light_blocks` route.

The property tests here are the oracle pins the vectorized paths are
allowed to exist under: multi-proof construction and verification must
be byte-identical (aunts, total/index fields, root, bitmap) to the
recursive per-proof reference in crypto/merkle.py for randomized tree
sizes — non-power-of-two, K=1 and K=N corners included — warm (held
MerkleMultiTree) and cold; verify_commit_light_bulk and
verify_adjacent_batch must raise the reference errors and share the
PR-7 commit memo with the per-commit paths.
"""

import asyncio
import copy
import random
import time

import pytest

from tendermint_tpu.crypto import merkle, sigcache
from tendermint_tpu.light.provider import Provider
from tendermint_tpu.light.verifier import verify_adjacent, verify_adjacent_batch
from tendermint_tpu.light.errors import (
    InvalidHeaderError,
    LightBlockNotFoundError,
)
from tendermint_tpu.types.light import (
    LightBlock,
    LightBlocksRequest,
    LightBlocksResponse,
)
from tendermint_tpu.types.validation import (
    InvalidCommitError,
    NotEnoughVotingPowerError,
    verify_commit_light,
    verify_commit_light_bulk,
)

from .test_light import CHAIN, DictProvider, build_chain, make_client

HOUR_NS = 3600 * 1_000_000_000


@pytest.fixture(autouse=True)
def _fresh_sigcache():
    sigcache.reset()
    yield
    sigcache.reset()


# ---------------------------------------------------------------------------
# vectorized merkle multi-proofs vs the recursive oracle


def _items(n, rng):
    return [bytes([rng.randrange(256)]) * (1 + i % 7) for i in range(n)]


@pytest.mark.parametrize("seed", range(6))
def test_property_multiproofs_byte_identical_to_reference(seed):
    """Randomized sizes (non-power-of-two included) and index sets
    (K=1 and K=N corners forced): the vectorized construction must
    produce the recursion's exact proofs — total, index, leaf_hash and
    every aunt byte — and the same root, cold and warm."""
    rng = random.Random(0xBEEF + seed)
    sizes = {1, 2, 3, rng.randrange(4, 70), rng.randrange(70, 200)}
    for n in sorted(sizes):
        items = _items(n, rng)
        root_o, proofs_o = merkle.proofs_from_byte_slices(items)
        tree = merkle.MerkleMultiTree.from_byte_slices(items)
        assert tree.root == root_o
        assert tree.total == n
        for idxs in (
            [rng.randrange(n)],  # K=1
            list(range(n)),  # K=N
            sorted(rng.sample(range(n), min(n, 5))),
            [n - 1, 0, n // 2],  # unsorted, duplicates allowed below
            [0, 0, n - 1],
        ):
            root_v, proofs_v = merkle.multiproofs_from_byte_slices(
                items, idxs
            )
            assert root_v == root_o
            warm = tree.proofs(idxs)
            for i, pv, pw in zip(idxs, proofs_v, warm):
                po = proofs_o[i]
                for p in (pv, pw):
                    assert p.total == po.total
                    assert p.index == po.index
                    assert p.leaf_hash == po.leaf_hash
                    assert p.aunts == po.aunts
                po.verify(root_o, items[i])  # oracle accepts its twin


def test_multiproofs_empty_tree_and_range_errors():
    root, proofs = merkle.multiproofs_from_byte_slices([], [])
    assert root == merkle.empty_hash() and proofs == []
    with pytest.raises(ValueError, match="out of range"):
        merkle.multiproofs_from_byte_slices([b"a"], [1])
    with pytest.raises(ValueError, match="out of range"):
        merkle.multiproofs_from_byte_slices([b"a", b"b"], [0, -1])
    tree = merkle.MerkleMultiTree.from_byte_slices([b"a", b"b"])
    with pytest.raises(ValueError, match="out of range"):
        tree.proof(2)


@pytest.mark.parametrize("seed", range(4))
def test_property_verify_multiproofs_bitmap_matches_reference(seed):
    """The batched verifier's bitmap equals verify_proofs_batch's for
    intact proofs AND for every mutation class the per-proof verifier
    rejects (corrupt aunt, corrupt leaf hash, extra/missing aunt,
    wrong total/index) — the shared-node memo may never flip a
    verdict."""
    rng = random.Random(0xFACE + seed)
    n = rng.randrange(2, 90)
    items = _items(n, rng)
    root, proofs = merkle.proofs_from_byte_slices(items)
    bits_ref = merkle.verify_proofs_batch(proofs, root, items)
    bits_new = merkle.verify_multiproofs_batch(proofs, root, items)
    assert bits_ref.all() and (bits_ref == bits_new).all()

    mutated = [copy.deepcopy(p) for p in proofs]
    leaves = list(items)
    for k, p in enumerate(mutated):
        mode = k % 6
        if mode == 1 and p.aunts:
            p.aunts[rng.randrange(len(p.aunts))] = b"\x00" * 32
        elif mode == 2:
            p.leaf_hash = b"\x13" * 32
        elif mode == 3:
            p.aunts = p.aunts + [b"\x17" * 32]
        elif mode == 4 and p.aunts:
            p.aunts = p.aunts[:-1]
        elif mode == 5:
            p.total += 1
        # mode 0: left intact
    bits_ref = merkle.verify_proofs_batch(mutated, root, leaves)
    bits_new = merkle.verify_multiproofs_batch(mutated, root, leaves)
    assert (bits_ref == bits_new).all()


# ---------------------------------------------------------------------------
# verify_commit_light_bulk: reference errors + shared commit memo


def _rows(blocks, heights):
    return [
        (
            blocks[h].validator_set,
            blocks[h].signed_header.commit.block_id,
            h,
            blocks[h].signed_header.commit,
        )
        for h in heights
    ]


def test_bulk_commit_light_verifies_and_warms_the_commit_memo():
    blocks = build_chain(6)
    rows = _rows(blocks, range(1, 7))
    s0 = sigcache.stats()
    verify_commit_light_bulk(CHAIN, rows)
    s1 = sigcache.stats()
    assert s1["misses"] - s0["misses"] > 0  # cold: real probes
    # warm fleet pass: every commit short-circuits on the memo
    verify_commit_light_bulk(CHAIN, rows)
    s2 = sigcache.stats()
    assert s2["commit_hits"] - s1["commit_hits"] == 6
    assert s2["misses"] == s1["misses"]


def test_bulk_commit_light_memo_interops_with_per_commit_path():
    """The bulk pass writes the SAME memo key verify_commit_light's
    vectorized path probes — each warms the other."""
    blocks = build_chain(2)
    (vals, bid, h, commit) = _rows(blocks, [2])[0]
    verify_commit_light_bulk(CHAIN, [(vals, bid, h, commit)])
    s0 = sigcache.stats()
    verify_commit_light(CHAIN, vals, bid, h, commit)
    s1 = sigcache.stats()
    assert s1["commit_hits"] - s0["commit_hits"] == 1
    # and the reverse direction
    sigcache.reset()
    verify_commit_light(CHAIN, vals, bid, h, commit)
    s0 = sigcache.stats()
    verify_commit_light_bulk(CHAIN, [(vals, bid, h, commit)])
    s1 = sigcache.stats()
    assert s1["commit_hits"] - s0["commit_hits"] == 1


def test_bulk_commit_light_reference_errors():
    blocks = build_chain(3)
    vals, bid, h, commit = _rows(blocks, [2])[0]
    # _verify_basic errors surface per commit, reference text
    with pytest.raises(InvalidCommitError, match="wrong height"):
        verify_commit_light_bulk(CHAIN, [(vals, bid, 99, commit)])
    # tally failure raises the reference NotEnoughVotingPowerError
    from tendermint_tpu.types.commit import BLOCK_ID_FLAG_ABSENT

    starved = copy.deepcopy(commit)
    for cs in starved.signatures[1:]:
        cs.block_id_flag = BLOCK_ID_FLAG_ABSENT
        cs.signature = b""
    starved.invalidate_memos()
    with pytest.raises(NotEnoughVotingPowerError):
        verify_commit_light_bulk(CHAIN, [(vals, bid, h, starved)])
    # a bad signature fails the merged check (no index attribution)
    bad = copy.deepcopy(commit)
    bad.signatures[0].signature = b"\x00" * 64
    bad.invalidate_memos()
    with pytest.raises(InvalidCommitError):
        verify_commit_light_bulk(CHAIN, [(vals, bid, h, bad)])
    # and a failed bulk pass must not have memoized anything
    s = sigcache.stats()
    verify_commit_light_bulk(CHAIN, [(vals, bid, h, commit)])
    assert sigcache.stats()["commit_hits"] == s["commit_hits"]


def test_bulk_commit_light_cache_disabled_still_verifies():
    blocks = build_chain(2)
    rows = _rows(blocks, [1, 2])
    with sigcache.disabled():
        verify_commit_light_bulk(CHAIN, rows)
        bad = copy.deepcopy(rows[0][3])
        bad.signatures[0].signature = b"\x00" * 64
        bad.invalidate_memos()
        with pytest.raises(InvalidCommitError):
            verify_commit_light_bulk(
                CHAIN, [(rows[0][0], rows[0][1], 1, bad)]
            )


# ---------------------------------------------------------------------------
# verify_adjacent_batch


def test_adjacent_batch_matches_per_hop_loop():
    blocks = build_chain(8)
    now = time.time_ns()
    chain = [blocks[h] for h in range(2, 9)]
    verify_adjacent_batch(
        CHAIN, blocks[1].signed_header, chain, 200 * HOUR_NS, now
    )
    # warm second pass: commit memos only
    s0 = sigcache.stats()
    verify_adjacent_batch(
        CHAIN, blocks[1].signed_header, chain, 200 * HOUR_NS, now
    )
    assert sigcache.stats()["commit_hits"] - s0["commit_hits"] == 7
    # and the per-hop reference accepts the same chain
    prev = blocks[1]
    for b in chain:
        verify_adjacent(
            CHAIN, prev.signed_header, b.signed_header,
            b.validator_set, 200 * HOUR_NS, now,
        )
        prev = b


def test_adjacent_batch_per_hop_header_errors():
    blocks = build_chain(5)
    now = time.time_ns()
    # a gap in the run is a per-hop header error (adjacent_header_checks)
    with pytest.raises(ValueError, match="must be adjacent"):
        verify_adjacent_batch(
            CHAIN,
            blocks[1].signed_header,
            [blocks[2], blocks[4]],
            200 * HOUR_NS,
            now,
        )
    # a corrupted signature mid-run surfaces as InvalidHeaderError
    chain = [copy.deepcopy(blocks[h]) for h in range(2, 6)]
    c = chain[2].signed_header.commit
    c.signatures[0].signature = b"\x00" * 64
    c.invalidate_memos()
    with pytest.raises(InvalidHeaderError):
        verify_adjacent_batch(
            CHAIN, blocks[1].signed_header, chain, 200 * HOUR_NS, now
        )


# ---------------------------------------------------------------------------
# client integration: bulk fetch + windowed bulk verify


class CountingBulkProvider(DictProvider):
    def __init__(self, blocks, id_="bulk"):
        super().__init__(blocks, id_)
        self.bulk_calls = 0
        self.single_calls = 0
        self.fail_bulk = False

    async def light_block(self, height):
        self.single_calls += 1
        return await super().light_block(height)

    async def light_blocks(self, first, last):
        self.bulk_calls += 1
        if self.fail_bulk:
            raise LightBlockNotFoundError("bulk disabled")
        return [self.blocks[h] for h in range(first, last + 1)]


def test_client_sequential_window_uses_bulk_fetch_and_verify():
    from tendermint_tpu.crypto.batch import (
        group_affinity_state,
        restore_group_affinity,
        set_group_affinity,
    )
    from tendermint_tpu.light.client import SEQUENTIAL_BATCH_HOPS

    blocks = build_chain(2 * SEQUENTIAL_BATCH_HOPS + 5)
    provider = CountingBulkProvider(blocks, "primary")
    client = make_client(blocks, sequential=True)
    client.primary = provider
    prev = group_affinity_state()
    set_group_affinity(SEQUENTIAL_BATCH_HOPS)
    try:
        lb = asyncio.run(
            client.verify_light_block_at_height(
                2 * SEQUENTIAL_BATCH_HOPS + 5, time.time_ns()
            )
        )
    finally:
        restore_group_affinity(prev)
    assert lb.height == 2 * SEQUENTIAL_BATCH_HOPS + 5
    # windows fetched in bulk; the target fetch is the only extra
    assert client.store.light_block(SEQUENTIAL_BATCH_HOPS) is not None
    assert provider.bulk_calls >= 2
    assert provider.single_calls <= 2  # the target/height-0 fetches


def test_client_bulk_fetch_failure_falls_back_per_height():
    from tendermint_tpu.crypto.batch import (
        group_affinity_state,
        restore_group_affinity,
        set_group_affinity,
    )
    from tendermint_tpu.light.client import SEQUENTIAL_BATCH_HOPS

    blocks = build_chain(10)
    provider = CountingBulkProvider(blocks, "primary")
    provider.fail_bulk = True
    client = make_client(blocks, sequential=True)
    client.primary = provider
    prev = group_affinity_state()
    set_group_affinity(SEQUENTIAL_BATCH_HOPS)
    try:
        lb = asyncio.run(
            client.verify_light_block_at_height(10, time.time_ns())
        )
    finally:
        restore_group_affinity(prev)
    assert lb.height == 10
    assert provider.bulk_calls >= 1  # tried the bulk surface first
    assert provider.single_calls >= 8  # served per height


def test_default_provider_bulk_is_the_per_height_loop():
    blocks = build_chain(5)
    p = DictProvider(blocks)
    got = asyncio.run(p.light_blocks(2, 4))
    assert [b.height for b in got] == [2, 3, 4]


# ---------------------------------------------------------------------------
# codecs: golden round-trip + hostile pages


def test_light_blocks_codecs_roundtrip():
    blocks = build_chain(3)
    req = LightBlocksRequest(min_height=2, max_height=9, max_blocks=4)
    again = LightBlocksRequest.from_proto(req.to_proto())
    assert again == req
    resp = LightBlocksResponse(
        light_blocks=[blocks[2], blocks[3]], last_height=3
    )
    decoded = LightBlocksResponse.from_proto(resp.to_proto())
    assert decoded.last_height == 3
    assert [b.height for b in decoded.light_blocks] == [2, 3]
    assert (
        decoded.light_blocks[0].signed_header.hash()
        == blocks[2].signed_header.hash()
    )
    decoded.light_blocks[0].validate_basic(CHAIN)
    # empty page still round-trips
    empty = LightBlocksResponse.from_proto(
        LightBlocksResponse(last_height=7).to_proto()
    )
    assert empty.light_blocks == [] and empty.last_height == 7
    # wire-type confusion fails as the sanctioned parse error
    from tendermint_tpu.encoding.proto import ProtoWriter

    w = ProtoWriter()
    w.uint(1, 5)  # varint where the repeated message belongs
    with pytest.raises(ValueError):
        LightBlocksResponse.from_proto(w.finish())


class _StubRPC:
    """Stands in for HTTPProvider._client: serves scripted pages."""

    def __init__(self, pages):
        self.pages = list(pages)
        self.calls = []

    async def call(self, method, **params):
        assert method == "light_blocks"
        self.calls.append(params)
        resp = self.pages.pop(0)
        return {
            "count": len(resp.light_blocks),
            "last_height": resp.last_height,
            "light_blocks": resp.to_proto().hex(),
        }


def _http_provider_with(pages):
    from tendermint_tpu.light.provider import HTTPProvider

    p = HTTPProvider.__new__(HTTPProvider)
    p.addr = "stub:0"
    p._client = _StubRPC(pages)
    return p


def test_http_provider_pages_past_the_server_clamp():
    blocks = build_chain(7)
    pages = [
        LightBlocksResponse(
            light_blocks=[blocks[2], blocks[3], blocks[4]], last_height=7
        ),
        LightBlocksResponse(
            light_blocks=[blocks[5], blocks[6]], last_height=7
        ),
    ]
    p = _http_provider_with(pages)
    got = asyncio.run(p.light_blocks(2, 6))
    assert [b.height for b in got] == [2, 3, 4, 5, 6]
    assert p._client.calls == [
        {"min_height": 2, "max_height": 6},
        {"min_height": 5, "max_height": 6},
    ]


def test_http_provider_rejects_hostile_pages():
    blocks = build_chain(6)
    # out-of-order page
    p = _http_provider_with(
        [LightBlocksResponse(light_blocks=[blocks[4]], last_height=6)]
    )
    with pytest.raises(LightBlockNotFoundError, match="out of order"):
        asyncio.run(p.light_blocks(2, 4))
    # empty page (no progress possible)
    p = _http_provider_with([LightBlocksResponse(last_height=6)])
    with pytest.raises(LightBlockNotFoundError, match="empty"):
        asyncio.run(p.light_blocks(2, 4))
    # over-full page: surplus beyond the asked range is ignored
    p = _http_provider_with(
        [
            LightBlocksResponse(
                light_blocks=[blocks[2], blocks[3], blocks[4]],
                last_height=6,
            )
        ]
    )
    got = asyncio.run(p.light_blocks(2, 3))
    assert [b.height for b in got] == [2, 3]


# ---------------------------------------------------------------------------
# the rpc route itself (in-process Environment; the live-node path is
# covered by tests/test_rpc.py)


class _BS:
    def __init__(self, blocks, gap_at=None):
        self.blocks = blocks
        self.gap_at = gap_at

    def height(self):
        return max(self.blocks)

    def base(self):
        return min(self.blocks)

    def load_block_meta(self, h):
        if h == self.gap_at or h not in self.blocks:
            return None

        class M:
            pass

        m = M()
        m.header = self.blocks[h].signed_header.header
        return m

    def load_block_commit(self, h):
        lb = self.blocks.get(h)
        return lb.signed_header.commit if lb else None

    def load_seen_commit(self):
        return None


class _SS:
    def __init__(self, blocks):
        self.blocks = blocks

    def load_validators(self, h):
        lb = self.blocks.get(h)
        return lb.validator_set if lb else None


def _env(blocks, gap_at=None):
    from tendermint_tpu.libs.metrics import Registry
    from tendermint_tpu.rpc.core import Environment
    from tendermint_tpu.rpc.metrics import RPCMetrics

    return Environment(
        chain_id=CHAIN,
        block_store=_BS(blocks, gap_at=gap_at),
        state_store=_SS(blocks),
        metrics=RPCMetrics(Registry()),
    )


def _call(env, **params):
    from tendermint_tpu.rpc.jsonrpc import RPCRequest

    return asyncio.run(
        env.light_blocks(
            RPCRequest(method="light_blocks", params=params, req_id=1)
        )
    )


def test_light_blocks_route_serves_clamped_ascending_pages():
    from tendermint_tpu.rpc.core import LIGHT_BLOCKS_PAGE_CAP

    blocks = build_chain(LIGHT_BLOCKS_PAGE_CAP + 10)
    env = _env(blocks)
    res = _call(env, min_height=3)
    page = LightBlocksResponse.from_proto(bytes.fromhex(res["light_blocks"]))
    assert res["count"] == LIGHT_BLOCKS_PAGE_CAP
    assert [b.height for b in page.light_blocks] == list(
        range(3, 3 + LIGHT_BLOCKS_PAGE_CAP)
    )
    assert res["last_height"] == LIGHT_BLOCKS_PAGE_CAP + 10
    # every served block is verifiable material
    page.light_blocks[0].validate_basic(CHAIN)
    # max_blocks shrinks the page, never grows it
    assert _call(env, min_height=1, max_blocks=3)["count"] == 3
    assert (
        _call(env, min_height=1, max_blocks=10_000)["count"]
        == LIGHT_BLOCKS_PAGE_CAP
    )
    # out-of-store ranges clamp to the store; empty range serves zero
    assert _call(env, min_height=10**9)["count"] == 0
    assert _call(env, max_height=-5)["count"] == 0
    # metrics: one counter bump per request, batch sizes observed
    m = env.metrics
    assert m.light_blocks_requests._values[()] == 5.0


def test_light_blocks_route_gap_ends_the_page():
    blocks = build_chain(10)
    env = _env(blocks, gap_at=5)
    res = _call(env, min_height=2, max_height=9)
    page = LightBlocksResponse.from_proto(bytes.fromhex(res["light_blocks"]))
    assert [b.height for b in page.light_blocks] == [2, 3, 4]
