"""scripts/bench_compare.py — the BENCH_*.json trajectory differ
(ISSUE 15 satellite): seeded regressed / improved / missing-row
fixtures through the comparison engine and the CLI exit contract.
"""

import importlib.util
import json
import os
import sys

import pytest

_SPEC = importlib.util.spec_from_file_location(
    "bench_compare",
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
        "bench_compare.py",
    ),
)
bench_compare = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(bench_compare)


BANKED = {
    "schema": "bench/v1",  # metadata: never compared
    "recorded_unix": 1_000.0,
    "verify_commit_10k_per_s": 1000.0,
    "warm_verify_ms": 0.32,
    "nested": {"routes_p99_ms": {"status": 12.0}, "held": 16},
    "num_cpu_devices": 8,  # direction unknown: info only
    "all_passed": True,  # bools are not trajectory rows
}


def fresh(**overrides):
    doc = {
        "verify_commit_10k_per_s": 1000.0,
        "warm_verify_ms": 0.32,
        "nested": {"routes_p99_ms": {"status": 12.0}, "held": 16},
        "num_cpu_devices": 8,
    }
    doc.update(overrides)
    return doc


class TestEngine:
    def test_identical_documents_pass(self):
        report, failures = bench_compare.compare(fresh(), BANKED)
        assert failures == []
        assert {r[5] for r in report} <= {"ok", "info"}

    def test_throughput_regression_fails(self):
        report, failures = bench_compare.compare(
            fresh(verify_commit_10k_per_s=800.0), BANKED
        )
        assert [f[0] for f in failures] == ["verify_commit_10k_per_s"]
        (key, old, new, delta, d, status) = failures[0]
        assert status == "regressed" and d == 1
        assert delta == pytest.approx(-0.2)

    def test_latency_regression_fails_nested_too(self):
        _, failures = bench_compare.compare(
            fresh(nested={"routes_p99_ms": {"status": 30.0}, "held": 16}),
            BANKED,
        )
        assert [f[0] for f in failures] == [
            "nested.routes_p99_ms.status"
        ]

    def test_improvement_passes_and_is_labeled(self):
        report, failures = bench_compare.compare(
            fresh(verify_commit_10k_per_s=2000.0, warm_verify_ms=0.1),
            BANKED,
        )
        assert failures == []
        improved = {r[0] for r in report if r[5] == "improved"}
        assert improved == {
            "verify_commit_10k_per_s",
            "warm_verify_ms",
        }

    def test_missing_row_fails(self):
        doc = fresh()
        del doc["warm_verify_ms"]
        _, failures = bench_compare.compare(doc, BANKED)
        assert [(f[0], f[5]) for f in failures] == [
            ("warm_verify_ms", "missing")
        ]

    def test_unknown_direction_never_fails(self):
        _, failures = bench_compare.compare(
            fresh(num_cpu_devices=1), BANKED
        )
        assert failures == []

    def test_null_value_is_info_not_missing(self):
        """A null leaf (a measurement that legitimately had no value
        that run — a chaos artifact's heal_detection_s when no
        stall-reset was needed) must not fail as a vanished row, in
        EITHER direction."""
        banked = dict(BANKED, heal_detection_s=1.2)
        report, failures = bench_compare.compare(
            fresh(heal_detection_s=None), banked
        )
        assert failures == []
        (row,) = [r for r in report if r[0] == "heal_detection_s"]
        assert row[5] == "info" and row[2] is None
        # null on the banked side, numeric fresh: also info
        banked = dict(BANKED, heal_detection_s=None)
        _, failures = bench_compare.compare(
            fresh(heal_detection_s=3.4), banked
        )
        assert failures == []

    def test_threshold_is_respected(self):
        doc = fresh(verify_commit_10k_per_s=920.0)  # -8%
        _, at10 = bench_compare.compare(doc, BANKED, threshold=0.10)
        _, at5 = bench_compare.compare(doc, BANKED, threshold=0.05)
        assert at10 == [] and len(at5) == 1

    def test_rows_filter(self):
        doc = fresh(verify_commit_10k_per_s=100.0, warm_verify_ms=99.0)
        _, failures = bench_compare.compare(
            doc, BANKED, rows="warm_*"
        )
        assert [f[0] for f in failures] == ["warm_verify_ms"]

    def test_direction_table(self):
        d = bench_compare.direction_of
        assert d("verify_per_s") == 1
        assert d("light_sync_warm_headers_per_s_150vals") == 1
        assert d("nested.routes_p99_ms.status") == -1
        assert d("tmlive_gate.wall_s") == -1
        assert d("subscribers_held") == 1
        assert d("num_cpu_devices") is None


class TestCLI:
    def _write(self, tmp_path, name, doc):
        p = tmp_path / name
        p.write_text(json.dumps(doc))
        return str(p)

    def test_exit_zero_when_clean(self, tmp_path, capsys):
        f = self._write(tmp_path, "fresh.json", fresh())
        b = self._write(tmp_path, "banked.json", BANKED)
        assert bench_compare.main([f, b]) == 0
        assert "within 10%" in capsys.readouterr().out

    def test_exit_one_on_regression_and_json_report(
        self, tmp_path, capsys
    ):
        f = self._write(
            tmp_path, "fresh.json", fresh(warm_verify_ms=1.0)
        )
        b = self._write(tmp_path, "banked.json", BANKED)
        assert bench_compare.main([f, b, "--json"]) == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["regressions"] == 1
        row = next(
            r for r in doc["rows"] if r["key"] == "warm_verify_ms"
        )
        assert row["status"] == "regressed"

    def test_exit_two_on_unreadable_input(self, tmp_path):
        b = self._write(tmp_path, "banked.json", BANKED)
        assert (
            bench_compare.main(
                [str(tmp_path / "missing.json"), b]
            )
            == 2
        )

    def test_ledger_self_compare_banked_load_artifact(self, capsys):
        """The real BENCH_LOAD.json carries a bottleneck ledger (ISSUE
        16): it must self-diff clean through --ledger, for the main row
        and the subs256 variant."""
        root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        path = os.path.join(root, "BENCH_LOAD.json")
        assert bench_compare.main([path, path, "--ledger"]) == 0
        assert (
            bench_compare.main(
                [path, path, "--ledger", "--variant", "subs256"]
            )
            == 0
        )

    def test_self_compare_banked_artifacts(self, capsys):
        """Every banked BENCH_* file in the repo self-compares clean
        (the differ must accept the real artifact shapes)."""
        root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        compared = 0
        for name in sorted(os.listdir(root)):
            if not (
                name.startswith("BENCH_") and name.endswith(".json")
            ):
                continue
            path = os.path.join(root, name)
            assert bench_compare.main([path, path]) == 0, name
            compared += 1
        assert compared >= 3  # the repo banks several trajectories


class TestGate:
    """--gate (ISSUE 17 satellite): the strict CI contract — failing
    rows + one verdict line, and an empty gateable-row set FAILS."""

    def _write(self, tmp_path, name, doc):
        p = tmp_path / name
        p.write_text(json.dumps(doc))
        return str(p)

    def test_gate_passes_clean_with_verdict(self, tmp_path, capsys):
        f = self._write(tmp_path, "fresh.json", fresh())
        b = self._write(tmp_path, "banked.json", BANKED)
        assert bench_compare.main([f, b, "--gate"]) == 0
        out = capsys.readouterr().out
        assert out.startswith("GATE PASS:")
        assert "within 10%" in out

    def test_gate_fails_on_regression_with_rows_on_stderr(
        self, tmp_path, capsys
    ):
        f = self._write(
            tmp_path,
            "fresh.json",
            fresh(verify_commit_10k_per_s=500.0, warm_verify_ms=2.0),
        )
        b = self._write(tmp_path, "banked.json", BANKED)
        assert bench_compare.main([f, b, "--gate"]) == 1
        captured = capsys.readouterr()
        assert "GATE FAIL: 2 of" in captured.err
        assert "verify_commit_10k_per_s" in captured.err
        assert "warm_verify_ms" in captured.err
        assert "GATE PASS" not in captured.out

    def test_gate_fails_on_missing_row(self, tmp_path, capsys):
        doc = fresh()
        del doc["warm_verify_ms"]
        f = self._write(tmp_path, "fresh.json", doc)
        b = self._write(tmp_path, "banked.json", BANKED)
        assert bench_compare.main([f, b, "--gate"]) == 1
        assert "vanished" in capsys.readouterr().err

    def test_gate_fails_on_zero_gateable_rows(self, tmp_path, capsys):
        """The contract the default mode lacks: a filter that matched
        nothing, or a banked doc with only direction-unknown rows,
        must FAIL the gate rather than vacuously pass it."""
        f = self._write(tmp_path, "fresh.json", fresh())
        b = self._write(tmp_path, "banked.json", BANKED)
        # fnmatch filter that matches no row at all
        assert (
            bench_compare.main(
                [f, b, "--gate", "--rows", "no_such_row_*"]
            )
            == 1
        )
        assert "0 gateable rows" in capsys.readouterr().err
        # ...while the DEFAULT mode exits 0 on the same inputs (the
        # vacuous pass --gate exists to close off)
        assert (
            bench_compare.main([f, b, "--rows", "no_such_row_*"]) == 0
        )
        capsys.readouterr()
        # direction-unknown-only documents: nothing gateable either
        f2 = self._write(tmp_path, "f2.json", {"num_cpu_devices": 8})
        b2 = self._write(tmp_path, "b2.json", {"num_cpu_devices": 4})
        assert bench_compare.main([f2, b2, "--gate"]) == 1
        assert "0 gateable rows" in capsys.readouterr().err

    def test_gate_respects_threshold(self, tmp_path, capsys):
        f = self._write(
            tmp_path,
            "fresh.json",
            fresh(verify_commit_10k_per_s=920.0),  # -8%
        )
        b = self._write(tmp_path, "banked.json", BANKED)
        assert bench_compare.main([f, b, "--gate"]) == 0
        capsys.readouterr()
        assert (
            bench_compare.main([f, b, "--gate", "--threshold", "0.05"])
            == 1
        )

    def test_gate_covers_byz_artifact_shape(self, tmp_path, capsys):
        """ISSUE 18 satellite: the BENCH_BYZ summary block's TTE/TTFC
        leaves are direction-annotated (all `_s` = lower-is-better), a
        regressed accountability latency fails the gate, and a
        scenario vanishing from the summary is a missing row = fail."""

        def byz_doc(tte=0.4, detect=0.01, drop=None):
            summary = {
                "tte_evidence_commit_s": {
                    "equivocate_prevote": tte,
                    "equivocate_precommit": 0.5,
                },
                "lightclient_detect_tte_s": detect,
                "double_sign_ttfc_after_restart_s": 2.1,
                "evidence_committed_hits": 6,
            }
            if drop:
                del summary["tte_evidence_commit_s"][drop]
            return {
                "schema": "bench_byz/v1",
                "seed": 2026,
                "nodes": 4,
                "offered_rate_per_s": 40.0,
                "scenarios": [],  # lists are never rows
                "summary": summary,
                "all_passed": True,
            }

        assert bench_compare.direction_of(
            "summary.tte_evidence_commit_s.equivocate_prevote"
        ) == -1
        assert bench_compare.direction_of(
            "summary.lightclient_detect_tte_s"
        ) == -1
        b = self._write(tmp_path, "banked.json", byz_doc())
        f = self._write(tmp_path, "fresh.json", byz_doc())
        assert bench_compare.main([f, b, "--gate"]) == 0
        capsys.readouterr()
        # detection-to-commit latency doubled: regression
        f2 = self._write(tmp_path, "f2.json", byz_doc(tte=0.9))
        assert bench_compare.main([f2, b, "--gate"]) == 1
        assert "tte_evidence_commit_s" in capsys.readouterr().err
        # a scenario dropped out of the campaign: missing row
        f3 = self._write(
            tmp_path, "f3.json", byz_doc(drop="equivocate_prevote")
        )
        assert bench_compare.main([f3, b, "--gate"]) == 1
        assert "vanished" in capsys.readouterr().err

    def test_gate_self_compare_banked_byz_artifact(self, capsys):
        """The real BENCH_BYZ.json gates clean against itself — the
        strict mode accepts the byzantine artifact shape, with its
        summary block supplying the gateable rows."""
        root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        path = os.path.join(root, "BENCH_BYZ.json")
        assert bench_compare.main([path, path, "--gate"]) == 0
        assert capsys.readouterr().out.startswith("GATE PASS:")

    def test_gate_self_compare_banked_load_artifact(self, capsys):
        """The real BENCH_LOAD.json gates clean against itself — the
        strict mode accepts the repo's actual artifact shape."""
        root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        path = os.path.join(root, "BENCH_LOAD.json")
        assert bench_compare.main([path, path, "--gate"]) == 0
        assert capsys.readouterr().out.startswith("GATE PASS:")

    def test_gate_self_compare_banked_mc_artifact(self, capsys):
        """The real BENCH_MC.json gates clean against itself — the
        model-checker record's directional keys (gate_wall_s lower,
        gate_states_per_s / gate_dedup_hits / reduction_x / edges_x
        higher) are all recognized by the suffix tables."""
        root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        path = os.path.join(root, "BENCH_MC.json")
        assert bench_compare.main([path, path, "--gate"]) == 0
        assert capsys.readouterr().out.startswith("GATE PASS:")

    def test_banked_mc_artifact_pins_acceptance_criteria(self):
        """ISSUE 19 acceptance, audited against the banked record: the
        exhaustive 4-validator/2-height byzantine gate run found zero
        violations, and POR+dedup beats naive enumeration by >= 10x at
        matched state coverage (reduction_x is exact, not a lower
        bound, when coverage_matched is true)."""
        root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        with open(os.path.join(root, "BENCH_MC.json")) as f:
            doc = json.load(f)
        assert doc["gate_violations"] == 0
        assert doc["gate_states"] >= 100
        assert doc["reduction_x"] >= 10.0
        assert doc["coverage_matched"] is True
        assert doc["naive_states"] > doc["reduced_states"]
        assert doc["config"]["n_validators"] == 4
        assert doc["config"]["target_height"] == 2
        assert doc["config"]["byz"]

    def test_gate_self_compare_banked_secp_artifact(self, capsys):
        """The real BENCH_SECP.json gates clean against itself — the
        native-secp256k1 record's directional keys (secp_sign_us /
        secp_verify_us and the nested p50_ms/p95_ms commit rows,
        all lower-is-better) are recognized by the suffix tables."""
        root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        path = os.path.join(root, "BENCH_SECP.json")
        assert bench_compare.main([path, path, "--gate"]) == 0
        assert capsys.readouterr().out.startswith("GATE PASS:")

    def test_banked_secp_artifact_pins_acceptance_criteria(self):
        """ISSUE 20 acceptance, audited against the banked record:
        the pure-secp 1k commit and the three-class mixed 10k commit
        both carry real measurements (the backend no longer raises at
        use), and the mixed row declares the 1:1:1 rotation so the
        semantics change vs the two-class pre-native rows is
        self-describing."""
        root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        with open(os.path.join(root, "BENCH_SECP.json")) as f:
            doc = json.load(f)
        assert doc["secp_sign_us"] > 0
        assert doc["secp_verify_us"] > 0
        assert doc["verify_commit_1k_secp"]["p50_ms"] > 0
        mixed = doc["verify_commit_10k_mixed_keys"]
        assert mixed["p50_ms"] > 0
        assert mixed["p95_ms"] >= mixed["p50_ms"]
        assert mixed["rotation"] == "ed25519/sr25519/secp256k1 1:1:1"


def _ledger(entries, attributed=0.95, idle=0.5, serving=0.2,
            consensus=0.25, samples=400):
    return {
        "samples_total": samples,
        "attributed_share": attributed,
        "unattributed_share": round(1.0 - attributed, 4),
        "idle_share": idle,
        "entries": [
            {
                "rank": i + 1,
                "subsystem": name,
                "share": share,
                "work_share": 0.0,
                "samples": int(share * samples),
                "signals": {},
            }
            for i, (name, share) in enumerate(entries)
        ],
        "consensus_vs_serving": {
            "serving_share": serving,
            "consensus_share": consensus,
        },
    }


_LED_BANKED = _ledger(
    [("eventbus", 0.20), ("rpc", 0.15), ("consensus", 0.10)]
)
_LED_FRESH = _ledger(
    [("consensus", 0.18), ("rpc", 0.14), ("merkle", 0.05)],
    attributed=0.97,
    serving=0.14,
    samples=500,
)


class TestLedgerDiff:
    """--ledger mode (ISSUE 16): the bottleneck-ledger differ."""

    def test_ledger_of_locates_the_block(self):
        doc = {"bottleneck_ledger": _LED_BANKED}
        assert bench_compare.ledger_of(doc) is _LED_BANKED
        # bare ledger fixtures pass through
        assert bench_compare.ledger_of(_LED_BANKED) is _LED_BANKED
        # variant descent
        doc = {"variants": {"subs256": {"bottleneck_ledger": _LED_FRESH}}}
        assert bench_compare.ledger_of(doc, "subs256") is _LED_FRESH
        assert bench_compare.ledger_of(doc) is None
        assert bench_compare.ledger_of({}, "subs256") is None

    def test_compare_ledgers_share_deltas_and_buckets(self):
        diff = bench_compare.compare_ledgers(_LED_FRESH, _LED_BANKED)
        assert diff["samples"] == {"banked": 400, "fresh": 500}
        by_name = {r["subsystem"]: r for r in diff["subsystems"]}
        # the fix's claim, auditable: eventbus left the ranked table
        assert by_name["eventbus"]["status"] == "vanished"
        assert by_name["eventbus"]["delta_pp"] == pytest.approx(-20.0)
        assert by_name["eventbus"]["fresh_share"] is None
        assert by_name["merkle"]["status"] == "new"
        assert by_name["merkle"]["delta_pp"] == pytest.approx(5.0)
        assert by_name["consensus"]["status"] == "shared"
        assert by_name["consensus"]["delta_pp"] == pytest.approx(8.0)
        assert diff["new_entrants"] == ["merkle"]
        assert diff["vanished"] == ["eventbus"]
        # rows ranked by delta magnitude
        mags = [abs(r["delta_pp"]) for r in diff["subsystems"]]
        assert mags == sorted(mags, reverse=True)
        h = diff["headline"]
        assert h["attributed_share"]["delta_pp"] == pytest.approx(2.0)
        assert h["serving_share"]["delta_pp"] == pytest.approx(-6.0)
        assert h["consensus_share"]["delta_pp"] == pytest.approx(0.0)

    def test_compare_ledgers_handles_missing_split(self):
        bare = {"samples_total": 1, "entries": []}
        diff = bench_compare.compare_ledgers(bare, bare)
        assert diff["subsystems"] == []
        assert diff["headline"]["serving_share"]["delta_pp"] is None

    def _write(self, tmp_path, name, doc):
        p = tmp_path / name
        p.write_text(json.dumps(doc))
        return str(p)

    def test_cli_ledger_mode_text_and_json(self, tmp_path, capsys):
        f = self._write(
            tmp_path,
            "fresh.json",
            {"bottleneck_ledger": _LED_FRESH},
        )
        b = self._write(
            tmp_path,
            "banked.json",
            {"bottleneck_ledger": _LED_BANKED},
        )
        assert bench_compare.main([f, b, "--ledger"]) == 0
        out = capsys.readouterr().out
        assert "vanished  eventbus" in out
        assert "new  merkle" in out
        assert "attributed_share" in out
        assert bench_compare.main([f, b, "--ledger", "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["vanished"] == ["eventbus"]

    def test_cli_ledger_mode_exit_two_without_ledger(
        self, tmp_path, capsys
    ):
        f = self._write(
            tmp_path, "fresh.json", {"bottleneck_ledger": _LED_FRESH}
        )
        b = self._write(tmp_path, "banked.json", {"requests_per_s": 1})
        assert bench_compare.main([f, b, "--ledger"]) == 2
        assert (
            "banked" in capsys.readouterr().err
        ), "error names the side missing the ledger"

    def test_cli_ledger_variant_descent(self, tmp_path):
        doc = {
            "bottleneck_ledger": _LED_BANKED,
            "variants": {
                "subs256": {"bottleneck_ledger": _LED_FRESH}
            },
        }
        p = self._write(tmp_path, "load.json", doc)
        assert bench_compare.main(
            [p, p, "--ledger", "--variant", "subs256"]
        ) == 0
        assert bench_compare.main(
            [p, p, "--ledger", "--variant", "nope"]
        ) == 2
