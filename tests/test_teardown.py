"""Clean asyncio teardown: node stop leaves ZERO pending tasks.

Diagnosis of the PR-13-noted "Task was destroyed but it is pending"
`Queue.get` warnings at loop close (then attributed to channel
out_queue/reactor tasks): the actual leak was the websocket writer
loop (rpc/jsonrpc.py WSConn._writer_loop). It parks in
`asyncio.wait([get, closed])` where `get = ensure_future(sendq.get())`
— and `asyncio.wait` does NOT cancel its awaitables when the waiting
task is cancelled, so a server stop with a live WS subscriber
abandoned the pending bare `Queue.get()` task forever. At interpreter
exit its destructor fired the warning (plus an "Event loop is closed"
ignored-exception). Reproduced deterministically with a 2-node
localnet + one subscriber; fixed by cancelling `get` in the loop's
finally.

This test pins the whole teardown contract, filter-style: run a node
with a live subscriber, stop it, and assert (a) zero pending tasks
remain on the loop and (b) the asyncio machinery emits no
destroyed-pending messages through loop close + GC — so ANY future
task leak in teardown (reactors, routers, pumps, writer loops) fails
here, not as noise at the end of an unrelated run.
"""

import asyncio
import gc
import logging
import tempfile
import threading

import pytest


@pytest.mark.filterwarnings("ignore::DeprecationWarning")
def test_node_stop_with_live_ws_subscriber_leaves_no_pending_tasks():
    from tendermint_tpu.loadgen.localnet import start_localnet
    from tendermint_tpu.rpc.client import WSClient

    # collect everything asyncio complains about: the destroyed-
    # pending message arrives via the loop exception handler (from
    # Task.__del__) or the asyncio logger, depending on timing
    complaints = []

    class _H(logging.Handler):
        def emit(self, record):
            complaints.append(record.getMessage())

    handler = _H()
    logging.getLogger("asyncio").addHandler(handler)
    loop = asyncio.new_event_loop()
    loop.set_exception_handler(
        lambda _l, ctx: complaints.append(str(ctx.get("message", "")))
    )
    asyncio.set_event_loop(loop)
    try:

        async def scenario():
            with tempfile.TemporaryDirectory() as home:
                net = await start_localnet(1, home)
                ws = WSClient(net.rpc_addrs[0])
                await ws.connect()
                await ws.call("subscribe", query="tm.event='NewBlock'")
                await asyncio.sleep(0.3)
                # stop the node while the subscriber is still
                # connected — the reproduced leak shape
                await net.stop()
                try:
                    await ws.close()
                except Exception:
                    pass  # server side is already gone
                # flight-recorder drain contract (ISSUE 15): the ring
                # captured the run (bounded), survives the stop for
                # post-mortem reads, records nothing further once
                # disabled, and reset() drains it clean
                tl = net.nodes[0].consensus.timeline
                assert 0 < len(tl) <= tl.capacity
                tl.disable()
                tl.record("step", 999, 0, step="post-stop")
                assert tl.snapshot()[-1].height != 999
                tl.reset()
                assert len(tl) == 0 and tl.snapshot() == []
            # give cancelled tasks their completion ticks
            for _ in range(10):
                await asyncio.sleep(0)

        loop.run_until_complete(
            asyncio.wait_for(scenario(), timeout=120)
        )
        pending = [t for t in asyncio.all_tasks(loop) if not t.done()]
        assert not pending, (
            "tasks still pending after node stop: "
            + "; ".join(repr(t) for t in pending)
        )
    finally:
        loop.close()
        asyncio.set_event_loop(None)
        logging.getLogger("asyncio").removeHandler(handler)
    # destructors of any leaked task fire here
    gc.collect()
    destroyed = [
        m for m in complaints if "destroyed but it is pending" in m
    ]
    assert not destroyed, destroyed


def _profiler_threads():
    return [
        t for t in threading.enumerate() if t.name == "tt-profiler"
    ]


@pytest.mark.filterwarnings("ignore::DeprecationWarning")
def test_profiler_sampler_stopped_and_joined_on_node_stop():
    """ISSUE 16 teardown contract: the profiler-owning node's stop
    STOPS AND JOINS the sampler thread — zero surviving threads, and
    not one further sample lands after the stop."""
    from tendermint_tpu.libs import profiler
    from tendermint_tpu.loadgen.localnet import start_localnet

    assert _profiler_threads() == []
    profiler.reset()

    async def scenario():
        with tempfile.TemporaryDirectory() as home:
            net = await start_localnet(1, home, profiler=True)
            try:
                assert profiler.is_enabled()
                assert len(_profiler_threads()) == 1
                # real consensus work under the sampler
                await net.wait_for_height(3, timeout=60.0)
            finally:
                await net.stop()

    asyncio.run(asyncio.wait_for(scenario(), timeout=120))
    assert not profiler.is_enabled()
    assert _profiler_threads() == [], "sampler survived node stop"
    n = profiler.stats()["samples_total"]
    assert n > 0, "profiler-enabled run collected no samples"
    import time as _time

    _time.sleep(0.1)
    assert profiler.stats()["samples_total"] == n, (
        "samples accrued after the sampler was stopped"
    )
    profiler.reset()


@pytest.mark.filterwarnings("ignore::DeprecationWarning")
def test_profiler_disabled_path_takes_zero_samples(monkeypatch):
    """Counting-stub mirror of the trace/timeline disabled-path tests:
    with `instrumentation.profiler=false` (the default) a REAL
    consensus run must reach _take_sample zero times — the kill switch
    is one module-attribute read, not a cheap sample."""
    from tendermint_tpu.libs import profiler
    from tendermint_tpu.loadgen.localnet import start_localnet

    calls = {"n": 0}

    def counting_stub():
        calls["n"] += 1

    monkeypatch.setattr(profiler, "_take_sample", counting_stub)
    profiler.reset()

    async def scenario():
        with tempfile.TemporaryDirectory() as home:
            net = await start_localnet(1, home)  # profiler off
            try:
                assert not profiler.is_enabled()
                await net.wait_for_height(3, timeout=60.0)
            finally:
                await net.stop()

    asyncio.run(asyncio.wait_for(scenario(), timeout=120))
    assert calls["n"] == 0, (
        f"disabled profiler sampled {calls['n']} times"
    )
    assert profiler.stats()["samples_total"] == 0
    assert _profiler_threads() == []
