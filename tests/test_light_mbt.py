"""Model-based light-client tests: replay the reference's TLA+-derived
trace corpus through our verifier (reference: light/mbt/driver_test.go
+ json/*.json — see tests/data/light_mbt/README.md for provenance).

Each trace carries real ed25519 signatures produced by the reference
implementation over ITS canonical sign-bytes; verifying them here is an
end-to-end cross-check of our deterministic encoding
(types/canonical.py), header hashing (types/header.py), validator-set
hashing, and the trust-level rules (light/verifier.py) against an
independent implementation.
"""

import base64
import glob
import json
import os

import pytest

from tendermint_tpu.crypto.ed25519 import PubKeyEd25519
from tendermint_tpu.light.errors import (
    InvalidHeaderError,
    NewValSetCantBeTrustedError,
    OldHeaderExpiredError,
)
from tendermint_tpu.light.verifier import verify
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.commit import Commit, CommitSig
from tendermint_tpu.types.header import Consensus, Header
from tendermint_tpu.types.light import SignedHeader
from tendermint_tpu.types.validator import Validator, ValidatorSet

_DIR = os.path.join(os.path.dirname(__file__), "data", "light_mbt")

CHAIN_ID = "test-chain"


# -- JSON decoding (the reference's tmjson wire shapes) --------------------


def _time_ns(s) -> int:
    if s is None:
        return 0
    # exact integer parse — float seconds lose ns precision, which
    # would corrupt sign-bytes for sub-microsecond timestamps
    from tendermint_tpu.types.timestamp import from_rfc3339

    return from_rfc3339(s)


def _hex(s) -> bytes:
    return bytes.fromhex(s) if s else b""


def _block_id(d) -> BlockID:
    if d is None:
        return BlockID()
    parts = d.get("parts") or {}
    return BlockID(
        hash=_hex(d.get("hash")),
        part_set_header=PartSetHeader(
            total=int(parts.get("total", 0)),
            hash=_hex(parts.get("hash")),
        ),
    )


def _header(d) -> Header:
    v = d.get("version") or {}
    return Header(
        version=Consensus(
            block=int(v.get("block", 0)), app=int(v.get("app", 0))
        ),
        chain_id=d["chain_id"],
        height=int(d["height"]),
        time_ns=_time_ns(d.get("time")),
        last_block_id=_block_id(d.get("last_block_id")),
        last_commit_hash=_hex(d.get("last_commit_hash")),
        data_hash=_hex(d.get("data_hash")),
        validators_hash=_hex(d.get("validators_hash")),
        next_validators_hash=_hex(d.get("next_validators_hash")),
        consensus_hash=_hex(d.get("consensus_hash")),
        app_hash=_hex(d.get("app_hash")),
        last_results_hash=_hex(d.get("last_results_hash")),
        evidence_hash=_hex(d.get("evidence_hash")),
        proposer_address=_hex(d.get("proposer_address")),
    )


def _commit(d) -> Commit:
    sigs = []
    for s in d.get("signatures") or ():
        sig = s.get("signature")
        sigs.append(
            CommitSig(
                block_id_flag=int(s["block_id_flag"]),
                validator_address=_hex(s.get("validator_address")),
                timestamp_ns=_time_ns(s.get("timestamp")),
                signature=base64.b64decode(sig) if sig else b"",
            )
        )
    return Commit(
        height=int(d["height"]),
        round=int(d.get("round", 0)),
        block_id=_block_id(d.get("block_id")),
        signatures=sigs,
    )


def _signed_header(d) -> SignedHeader:
    return SignedHeader(
        header=_header(d["header"]), commit=_commit(d["commit"])
    )


def _valset(d) -> ValidatorSet:
    vals = []
    for v in d.get("validators") or ():
        pk = v["pub_key"]
        assert pk["type"] == "tendermint/PubKeyEd25519"
        vals.append(
            Validator(
                pub_key=PubKeyEd25519(base64.b64decode(pk["value"])),
                voting_power=int(v["voting_power"]),
                proposer_priority=int(v.get("proposer_priority") or 0),
            )
        )
    vs = ValidatorSet(vals)
    prop = d.get("proposer")
    if prop:
        addr = _hex(prop.get("address"))
        for v in vs.validators:
            if v.address == addr:
                vs.proposer = v
                break
    return vs


def _traces():
    return sorted(glob.glob(os.path.join(_DIR, "*.json")))


@pytest.mark.parametrize(
    "path", _traces(), ids=lambda p: os.path.basename(p)[:-5]
)
def test_mbt_trace(path):
    """reference: light/mbt/driver_test.go TestVerify, verdict mapping
    SUCCESS -> no error, NOT_ENOUGH_TRUST -> ErrNewValSetCantBeTrusted,
    INVALID -> ErrInvalidHeader | ErrOldHeaderExpired."""
    with open(path) as f:
        tc = json.load(f)

    trusted_sh = _signed_header(tc["initial"]["signed_header"])
    trusted_next_vals = _valset(tc["initial"]["next_validator_set"])
    trusting_period_ns = int(tc["initial"]["trusting_period"])

    for step, inp in enumerate(tc["input"]):
        new_sh = _signed_header(inp["block"]["signed_header"])
        new_vals = _valset(inp["block"]["validator_set"])
        now_ns = _time_ns(inp["now"])
        err = None
        try:
            verify(
                CHAIN_ID,
                trusted_sh,
                trusted_next_vals,
                new_sh,
                new_vals,
                trusting_period_ns,
                now_ns,
                max_clock_drift_ns=1_000_000_000,
            )
        except (
            InvalidHeaderError,
            NewValSetCantBeTrustedError,
            OldHeaderExpiredError,
        ) as e:
            err = e

        verdict = inp["verdict"]
        ctx = f"{os.path.basename(path)} step {step}: {err!r}"
        if verdict == "SUCCESS":
            assert err is None, ctx
        elif verdict == "NOT_ENOUGH_TRUST":
            assert isinstance(err, NewValSetCantBeTrustedError), ctx
        elif verdict == "INVALID":
            assert isinstance(
                err, (InvalidHeaderError, OldHeaderExpiredError)
            ), ctx
        else:
            pytest.fail(f"unexpected verdict {verdict!r}")

        if err is None:  # advance trusted state
            trusted_sh = new_sh
            trusted_next_vals = _valset(
                inp["block"]["next_validator_set"]
            )


def test_corpus_present():
    assert len(_traces()) >= 9


def test_harness_detects_corrupted_signature():
    """Sanity check that the driver really verifies signatures: flip a
    byte in a SUCCESS step's commit and the verdict must change."""
    path = os.path.join(_DIR, "MC4_4_faulty_TestSuccess.json")
    with open(path) as f:
        tc = json.load(f)
    trusted_sh = _signed_header(tc["initial"]["signed_header"])
    trusted_next_vals = _valset(tc["initial"]["next_validator_set"])
    # find the first SUCCESS step and corrupt every signature
    inp = next(i for i in tc["input"] if i["verdict"] == "SUCCESS")
    new_sh = _signed_header(inp["block"]["signed_header"])
    new_vals = _valset(inp["block"]["validator_set"])
    for cs in new_sh.commit.signatures:
        if cs.signature:
            cs.signature = cs.signature[:-1] + bytes(
                [cs.signature[-1] ^ 1]
            )
    with pytest.raises((InvalidHeaderError, NewValSetCantBeTrustedError)):
        verify(
            CHAIN_ID,
            trusted_sh,
            trusted_next_vals,
            new_sh,
            new_vals,
            int(tc["initial"]["trusting_period"]),
            _time_ns(inp["now"]),
            max_clock_drift_ns=1_000_000_000,
        )
