"""ShardedEd25519Verifier on the suite's virtual 8-device CPU mesh:
bucket rounding to mesh multiples, uneven batches, invalid-signature
localization across shards, and the node-level `[tpu] devices` install
seam (reference: the backend choice is config, not code —
crypto/crypto.go:53-61; sharding layout: tendermint_tpu/parallel)."""

import asyncio
import hashlib

import numpy as np
import pytest

import jax

from tendermint_tpu.crypto import batch as crypto_batch
from tendermint_tpu.crypto import tpu_verifier
from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.parallel import ShardedEd25519Verifier, make_mesh


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    assert len(devs) >= 8, "conftest should provide 8 virtual devices"
    return make_mesh(devs[:8])


def _sign_set(n, tag=b"shard"):
    keys = [
        PrivKeyEd25519.from_seed(hashlib.sha256(tag + bytes([i])).digest())
        for i in range(n)
    ]
    msgs = [b"sharded-msg-" + bytes([i]) for i in range(n)]
    sigs = [k.sign(m) for k, m in zip(keys, msgs)]
    return [k.pub_key().bytes() for k in keys], msgs, sigs


def test_bucket_rounds_to_mesh_multiples(mesh):
    v = ShardedEd25519Verifier(mesh, bucket_sizes=[4, 10, 100])
    # every configured bucket is rounded up to a multiple of 8
    assert all(b % 8 == 0 for b in v.bucket_sizes)
    for n in (1, 4, 9, 100, 101, 20_000):  # incl. oversized
        assert v._bucket(n) % 8 == 0
        assert v._bucket(n) >= n


def test_uneven_batch_verifies(mesh):
    # 13 signatures on 8 devices: bucket pads to a multiple of 8
    pks, msgs, sigs = _sign_set(13)
    v = ShardedEd25519Verifier(mesh, bucket_sizes=[8])
    ok = v.verify(pks, msgs, sigs)
    assert ok.shape == (13,) and ok.all()


def test_invalid_sigs_localized_across_shards(mesh):
    # corruptions landing in different device shards of a 16-batch
    pks, msgs, sigs = _sign_set(16)
    bad = {0, 7, 9, 15}  # shard boundaries with 16/8 = 2 per device
    for i in bad:
        sigs[i] = sigs[i][:40] + bytes([sigs[i][40] ^ 1]) + sigs[i][41:]
    v = ShardedEd25519Verifier(mesh, bucket_sizes=[16])
    ok = v.verify(pks, msgs, sigs)
    assert ok.tolist() == [i not in bad for i in range(16)]


def test_matches_single_chip_verifier(mesh):
    from tendermint_tpu.ops.ed25519_kernel import Ed25519Verifier

    pks, msgs, sigs = _sign_set(11, b"eq")
    sigs[3] = b"\x00" * 64
    sharded = ShardedEd25519Verifier(mesh).verify(pks, msgs, sigs)
    single = Ed25519Verifier().verify(pks, msgs, sigs)
    assert sharded.tolist() == single.tolist()


def test_node_installs_sharded_verifier_from_config(tmp_path):
    """`[tpu] devices = 8` routes the node's batch verification through
    a mesh-sharded verifier; a live commit then flows across the mesh."""
    from tendermint_tpu.node.node import make_node

    from tests.test_node import make_genesis, make_home

    async def go():
        priv = PrivKeyEd25519.from_seed(b"\x77" * 32)
        genesis = make_genesis([priv])
        cfg = make_home(tmp_path, 0, genesis, priv)
        cfg.tpu.devices = 8
        node = make_node(cfg)
        try:
            bv = crypto_batch.create_batch_verifier(
                priv.pub_key(), size_hint=64
            )
            assert isinstance(bv, tpu_verifier.TpuEd25519BatchVerifier)
            assert isinstance(bv._verifier, ShardedEd25519Verifier)
            assert bv._verifier.mesh.devices.size == 8
            # and the sharded path actually verifies
            pks, msgs, sigs = _sign_set(9, b"node")
            keys = [
                PrivKeyEd25519.from_seed(
                    hashlib.sha256(b"node" + bytes([i])).digest()
                )
                for i in range(9)
            ]
            for k, m, s in zip(keys, msgs, sigs):
                bv.add(k.pub_key(), m, s)
            ok, bitmap = bv.verify()
            assert ok and bitmap == [True] * 9
        finally:
            tpu_verifier.uninstall()

    asyncio.run(go())


def test_device_mesh_config_validation():
    from tendermint_tpu.node.node import Node

    assert Node._device_mesh(1) is None
    m = Node._device_mesh(0)  # all visible devices
    assert m is not None and m.devices.size == len(jax.devices())
    with pytest.raises(RuntimeError, match="only"):
        Node._device_mesh(10_000)


# ---------------------------------------------------------------------------
# sr25519


def _sr_sign_set(n, tag=b"sr-shard"):
    from tendermint_tpu.crypto.sr25519 import PrivKeySr25519

    keys = [
        PrivKeySr25519.from_seed(hashlib.sha256(tag + bytes([i])).digest())
        for i in range(n)
    ]
    msgs = [b"sr-sharded-" + bytes([i]) for i in range(n)]
    sigs = [k.sign(m) for k, m in zip(keys, msgs)]
    return [k.pub_key().bytes() for k in keys], msgs, sigs


def test_sr25519_bucket_rounds_to_mesh_multiples(mesh):
    from tendermint_tpu.parallel import ShardedSr25519Verifier

    v = ShardedSr25519Verifier(mesh, bucket_sizes=[4, 10, 100])
    assert all(b % 8 == 0 for b in v.bucket_sizes)
    for n in (1, 9, 101, 20_000):
        assert v._bucket(n) % 8 == 0 and v._bucket(n) >= n


def test_sr25519_uneven_batch_and_localization(mesh):
    from tendermint_tpu.parallel import ShardedSr25519Verifier

    pks, msgs, sigs = _sr_sign_set(13)
    bad = {2, 8, 12}
    for i in bad:
        sigs[i] = sigs[i][:40] + bytes([sigs[i][40] ^ 1]) + sigs[i][41:]
    v = ShardedSr25519Verifier(mesh, bucket_sizes=[8])
    ok = v.verify(pks, msgs, sigs)
    assert ok.tolist() == [i not in bad for i in range(13)]


def test_sr25519_matches_single_chip(mesh):
    from tendermint_tpu.ops.sr25519_kernel import Sr25519Verifier
    from tendermint_tpu.parallel import ShardedSr25519Verifier

    pks, msgs, sigs = _sr_sign_set(9, b"sr-eq")
    sigs[4] = b"\x00" * 64
    sharded = ShardedSr25519Verifier(mesh).verify(pks, msgs, sigs)
    single = Sr25519Verifier().verify(pks, msgs, sigs)
    assert sharded.tolist() == single.tolist()


def test_mesh_install_shards_sr25519(mesh):
    """install(mesh=...) must route sr25519 batches through the
    sharded verifier too (crypto/crypto.go:53-61: backend is config)."""
    from tendermint_tpu.crypto.sr25519 import PrivKeySr25519
    from tendermint_tpu.parallel import ShardedSr25519Verifier

    tpu_verifier.install(min_batch=2, mesh=mesh)
    try:
        priv = PrivKeySr25519.from_seed(b"\x21" * 32)
        bv = crypto_batch.create_batch_verifier(priv.pub_key(), size_hint=8)
        assert isinstance(bv, tpu_verifier.TpuSr25519BatchVerifier)
        assert isinstance(bv._verifier, ShardedSr25519Verifier)
        for i in range(8):
            m = b"mesh-sr-%d" % i
            bv.add(priv.pub_key(), m, priv.sign(m))
        ok, bitmap = bv.verify()
        assert ok and bitmap == [True] * 8
    finally:
        tpu_verifier.uninstall()
