"""Per-block serving cache (rpc/servingcache.py) + the tx_proofs
route: byte-identity with the uncached paths, hit/eviction accounting,
the mutation-epoch flush, the tip seen-commit exclusion, and the
kill switches — the dynamic half of tmcost's cost-recompute fix (the
static half is tests/test_tmcost.py's strip-the-cache A/B)."""

import asyncio
import os

import pytest

from tendermint_tpu.rpc import servingcache
from tendermint_tpu.rpc.servingcache import ServingCache
from tendermint_tpu.types.light import LightBlocksResponse
from tendermint_tpu.types.tx import tx_hash, txs_hash, txs_proofs

from .test_stateless_bulk import CHAIN, _BS, _SS, _call, _env, build_chain


def _counter(env, name):
    return getattr(env.metrics, "servingcache_" + name)._values.get(
        (), 0.0
    )


# ---------------------------------------------------------------------------
# light_blocks / light_block through the cache


def test_page_bytes_identical_cold_and_warm():
    """The blob-assembled page must be byte-identical to
    LightBlocksResponse.to_proto — cold (all misses), warm (all hits),
    and with the cache disabled."""
    blocks = build_chain(12)
    env = _env(blocks)
    ref = LightBlocksResponse(
        light_blocks=[blocks[h] for h in range(2, 9)], last_height=12
    ).to_proto().hex()
    cold = _call(env, min_height=2, max_height=8)
    assert cold["light_blocks"] == ref
    warm = _call(env, min_height=2, max_height=8)
    assert warm["light_blocks"] == ref
    assert _counter(env, "hits") >= 7.0
    with servingcache.disabled():
        off = _call(env, min_height=2, max_height=8)
    assert off["light_blocks"] == ref


def test_light_block_single_route_serves_the_same_blob():
    blocks = build_chain(6)
    env = _env(blocks)
    res = asyncio.run(
        env.light_block(_Req({"height": 4}))
    )
    assert res["light_block"] == blocks[4].to_proto().hex()
    # second call is a pure cache hit
    h0 = _counter(env, "hits")
    res2 = asyncio.run(env.light_block(_Req({"height": 4})))
    assert res2 == res and _counter(env, "hits") == h0 + 1


class _Req:
    def __init__(self, params):
        self.params = params
        self.ws = None
        self.req_id = 1


def test_lru_bound_and_eviction_accounting():
    blocks = build_chain(30)
    env = _env(blocks)
    env.serving_cache.capacity = 5
    for h in range(1, 21):
        env.serving_cache.encoded_light_block(h)
    assert len(env.serving_cache._blobs) <= 5
    assert _counter(env, "evictions") >= 15.0


def test_env_kill_switch_and_zero_capacity():
    blocks = build_chain(8)
    env = _env(blocks)
    os.environ["TM_TPU_NO_SERVCACHE"] = "1"
    try:
        ref = _call(env, min_height=2, max_height=6)
        assert env.serving_cache.entries() == 0
    finally:
        del os.environ["TM_TPU_NO_SERVCACHE"]
    # capacity 0 (config [rpc] serving_cache_blocks = 0) also disables
    env2 = _env(blocks)
    env2.serving_cache.capacity = 0
    got = _call(env2, min_height=2, max_height=6)
    assert got == ref
    assert env2.serving_cache.entries() == 0


def test_mutation_epoch_flushes_the_cache():
    """An in-place Validator (or Commit) wire-field write anywhere in
    the process makes every cached encoding suspect: the next request
    flushes and re-assembles (the PR-7 epoch machinery, ridden rather
    than rebuilt)."""
    blocks = build_chain(8)
    env = _env(blocks)
    _call(env, min_height=2, max_height=6)
    assert env.serving_cache.entries() == 5
    v = blocks[3].validator_set.validators[0]
    v.voting_power = v.voting_power  # post-init write bumps the epoch
    res = _call(env, min_height=2, max_height=6)
    # flushed and rebuilt — fresh misses, and content still correct
    page = LightBlocksResponse.from_proto(
        bytes.fromhex(res["light_blocks"])
    )
    assert [b.height for b in page.light_blocks] == [2, 3, 4, 5, 6]
    assert env.serving_cache.entries() == 5
    c = blocks[4].signed_header.commit
    c.round = c.round  # commit epoch too
    env.serving_cache.encoded_light_block(2)
    assert env.serving_cache.entries() == 1  # flushed again


class _TipBS(_BS):
    """Top height has no canonical commit — only the seen commit."""

    def load_block_commit(self, h):
        if h == self.height():
            return None
        return super().load_block_commit(h)

    def load_seen_commit(self):
        return self.blocks[self.height()].signed_header.commit


def test_tip_seen_commit_fallback_is_served_but_never_cached():
    blocks = build_chain(6)
    from tendermint_tpu.libs.metrics import Registry
    from tendermint_tpu.rpc.core import Environment
    from tendermint_tpu.rpc.metrics import RPCMetrics

    env = Environment(
        chain_id=CHAIN,
        block_store=_TipBS(blocks),
        state_store=_SS(blocks),
        metrics=RPCMetrics(Registry()),
    )
    res = _call(env, min_height=4, max_height=6)
    page = LightBlocksResponse.from_proto(
        bytes.fromhex(res["light_blocks"])
    )
    assert [b.height for b in page.light_blocks] == [4, 5, 6]
    # heights 4,5 cached; the tip (6, seen-commit) must not be
    assert sorted(env.serving_cache._blobs) == [4, 5]


# ---------------------------------------------------------------------------
# tx_proofs route from the held tree


class _TxBS:
    def __init__(self, txs, top=5):
        self.txs = txs
        self._top = top

    def height(self):
        return self._top

    def base(self):
        return 1

    def load_block(self, h):
        class B:
            pass

        b = B()
        b.txs = self.txs
        return b if h <= self._top else None

    def load_block_meta(self, h):
        return object() if h <= self._top else None

    def load_block_commit(self, h):
        return object() if h <= self._top else None

    def load_seen_commit(self):
        return None


def _tx_env(txs):
    from tendermint_tpu.libs.metrics import Registry
    from tendermint_tpu.rpc.core import Environment
    from tendermint_tpu.rpc.metrics import RPCMetrics

    return Environment(
        chain_id=CHAIN,
        block_store=_TxBS(txs),
        state_store=_SS({}),
        metrics=RPCMetrics(Registry()),
    )


def test_tx_proofs_route_serves_reference_identical_proofs():
    from tendermint_tpu.crypto.merkle import Proof

    txs = [b"tx-%d" % i for i in range(9)]
    env = _tx_env(txs)
    res = asyncio.run(
        env.tx_proofs(_Req({"height": 3, "indices": [0, 4, 8]}))
    )
    assert res["root"] == txs_hash(txs).hex()
    assert res["total"] == 9
    ref = txs_proofs(txs)
    for hexp, i in zip(res["proofs"], [0, 4, 8]):
        p = Proof.from_proto_bytes(bytes.fromhex(hexp))
        rp = ref[i]
        assert (p.total, p.index, p.leaf_hash, p.aunts) == (
            rp.total, rp.index, rp.leaf_hash, rp.aunts
        )
        # verifies against the header's data_hash root
        p.verify(txs_hash(txs), tx_hash(txs[i]))
    # the tree is HELD: same object serves the next request
    t1 = env.serving_cache.tx_tree(3)
    assert env.serving_cache.tx_tree(3) is t1


def test_tx_proofs_route_param_validation_and_clamp():
    from tendermint_tpu.rpc.core import TX_PROOFS_CAP
    from tendermint_tpu.rpc.jsonrpc import RPCError

    txs = [b"t%d" % i for i in range(4)]
    env = _tx_env(txs)
    for bad in (None, "nope", [1, "x"], [True], {"a": 1}):
        with pytest.raises(RPCError):
            asyncio.run(
                env.tx_proofs(_Req({"height": 3, "indices": bad}))
            )
    with pytest.raises(RPCError):  # out of range
        asyncio.run(
            env.tx_proofs(_Req({"height": 3, "indices": [99]}))
        )
    with pytest.raises(RPCError):  # negative aliasing refused
        asyncio.run(
            env.tx_proofs(_Req({"height": 3, "indices": [-1]}))
        )
    # an index past int64 overflows inside numpy's asarray: that is
    # invalid CLIENT input (INVALID_PARAMS), not an internal error
    from tendermint_tpu.rpc.jsonrpc import INVALID_PARAMS

    with pytest.raises(RPCError) as exc:
        asyncio.run(
            env.tx_proofs(_Req({"height": 3, "indices": [2**70]}))
        )
    assert exc.value.code == INVALID_PARAMS
    # shrink-only clamp: an oversized list serves the first CAP
    res = asyncio.run(
        env.tx_proofs(
            _Req({"height": 3, "indices": [0] * (TX_PROOFS_CAP + 50)})
        )
    )
    assert len(res["proofs"]) == TX_PROOFS_CAP
    assert env.metrics.tx_proofs_requests._values[()] == 1.0


def test_tx_proofs_route_is_in_the_route_table():
    env = _tx_env([b"a"])
    assert env.routes()["tx_proofs"] == env.tx_proofs
