"""RPC layer tests: an external client drives a live node end-to-end
(reference model: rpc/client/rpc_test.go, rpc/jsonrpc tests).

Boots a single-validator node with the RPC server on an ephemeral port,
then exercises the route surface over real HTTP and websocket
connections — info routes, the tx lifecycle (broadcast_tx_commit →
tx_search), ABCI passthrough, and event subscriptions.
"""

import asyncio
import base64
import json
import time

import pytest

from tendermint_tpu.config import Config
from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.node import make_node
from tendermint_tpu.privval import FilePV
from tendermint_tpu.rpc import HTTPClient, RPCClientError, WSClient
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.tx import tx_hash

CHAIN = "rpc-chain"


def run(coro):
    return asyncio.run(coro)


def _make_cfg(tmp_path) -> tuple[Config, PrivKeyEd25519]:
    priv = PrivKeyEd25519.from_seed(b"\x09" * 32)
    genesis = GenesisDoc(
        chain_id=CHAIN,
        genesis_time_ns=time.time_ns(),
        validators=[GenesisValidator(pub_key=priv.pub_key(), power=10)],
    )
    cfg = Config()
    cfg.base.home = str(tmp_path / "rpcnode")
    cfg.base.chain_id = CHAIN
    cfg.base.db_backend = "memdb"
    cfg.consensus.timeout_commit = 0.2
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.ensure_dirs()
    genesis.save_as(cfg.base.path(cfg.base.genesis_file))
    FilePV.from_priv_key(
        priv,
        cfg.base.path(cfg.priv_validator.key_file),
        cfg.base.path(cfg.priv_validator.state_file),
    ).save()
    return cfg, priv


async def _boot(tmp_path):
    cfg, priv = _make_cfg(tmp_path)
    node = make_node(cfg)
    await node.start()
    await node.consensus.wait_for_height(2, timeout=60.0)
    addr = f"127.0.0.1:{node.rpc_server.bound_port}"
    return node, addr


def test_info_and_block_routes(tmp_path):
    async def go():
        node, addr = await _boot(tmp_path)
        c = HTTPClient(addr)
        try:
            # health + status
            assert await c.call("health") == {}
            st = await c.call("status")
            assert st["sync_info"]["latest_block_height"] >= 1
            assert st["validator_info"]["voting_power"] == 10
            assert not st["sync_info"]["catching_up"]

            # net_info (no peers on a solo node)
            ni = await c.call("net_info")
            assert ni["n_peers"] == 0

            # genesis round-trips the chain id
            gen = await c.call("genesis")
            assert gen["genesis"]["chain_id"] == CHAIN
            chunk = await c.call("genesis_chunked", chunk=0)
            data = base64.b64decode(chunk["data"])
            assert json.loads(data)["chain_id"] == CHAIN

            # block routes agree with the node's own store
            h = node.block_store.height()
            blk = await c.call("block", height=h)
            assert blk["block"]["header"]["height"] == h
            assert blk["block"]["header"]["chain_id"] == CHAIN
            expected_hash = node.block_store.load_block(h).hash().hex()
            assert blk["block_id"]["hash"] == expected_hash

            by_hash = await c.call("block_by_hash", hash=expected_hash)
            assert by_hash["block"]["header"]["height"] == h

            hdr = await c.call("header", height=h)
            assert hdr["header"]["height"] == h
            hdr2 = await c.call("header_by_hash", hash=expected_hash)
            assert hdr2["header"]["height"] == h

            chain = await c.call("blockchain", min_height=1, max_height=h)
            assert chain["last_height"] >= h
            assert chain["block_metas"][0]["header"]["height"] == h

            # commit: block h's canonical commit lands when block h+1 is
            # saved, i.e. once consensus starts height h+2
            await node.consensus.wait_for_height(h + 2, timeout=30.0)
            cm = await c.call("commit", height=h)
            assert cm["canonical"]
            assert cm["signed_header"]["commit"]["height"] == h

            vals = await c.call("validators", height=h)
            assert vals["total"] == 1
            assert vals["validators"][0]["voting_power"] == 10

            cp = await c.call("consensus_params", height=h)
            assert cp["consensus_params"]["block"]["max_bytes"] > 0

            # bulk stateless serving: light_blocks serves a verifiable
            # ascending page that agrees with the single-height route
            from tendermint_tpu.types.light import (
                LightBlock,
                LightBlocksResponse,
            )

            single = await c.call("light_block", height=h)
            lb_single = LightBlock.from_proto(
                bytes.fromhex(single["light_block"])
            )
            bulk = await c.call("light_blocks", min_height=1, max_height=h)
            page = LightBlocksResponse.from_proto(
                bytes.fromhex(bulk["light_blocks"])
            )
            assert bulk["count"] == len(page.light_blocks) >= 1
            assert [b.height for b in page.light_blocks] == list(
                range(1, 1 + bulk["count"])
            )
            for b in page.light_blocks:
                b.validate_basic(CHAIN)
            if bulk["count"] >= h:
                assert (
                    page.light_blocks[h - 1].signed_header.hash()
                    == lb_single.signed_header.hash()
                )
            # the node's registry carries the bulk-route series
            assert (
                node.rpc_env.metrics.light_blocks_requests._values[()]
                >= 1.0
            )

            cs = await c.call("consensus_state")
            assert cs["round_state"]["height"] >= h
            dump = await c.call("dump_consensus_state")
            assert dump["round_state"]["height"] >= h

            # abci passthrough
            info = await c.call("abci_info")
            assert info["response"]["last_block_height"] >= 1

            # unknown method
            with pytest.raises(RPCClientError):
                await c.call("no_such_method")
            # out-of-range height
            with pytest.raises(RPCClientError):
                await c.call("block", height=10_000)
        finally:
            await c.close()
            await node.stop()

    run(go())


def test_tx_lifecycle_commit_and_search(tmp_path):
    async def go():
        node, addr = await _boot(tmp_path)
        c = HTTPClient(addr, timeout=30.0)
        try:
            tx = b"rpckey=rpcvalue"
            res = await c.call(
                "broadcast_tx_commit", tx=base64.b64encode(tx).decode()
            )
            assert res["check_tx"]["code"] == 0
            assert res["deliver_tx"]["code"] == 0
            assert res["height"] >= 1
            assert res["hash"] == tx_hash(tx).hex()

            # the tx is queryable from the app over abci_query
            q = await c.call(
                "abci_query", data=b"rpckey".hex(), path="/key"
            )
            assert bytes.fromhex(q["response"]["value"]) == b"rpcvalue"

            # and from the kv indexer
            got = await c.call("tx", hash=tx_hash(tx).hex())
            assert got["height"] == res["height"]
            assert base64.b64decode(got["tx"]) == tx

            found = await c.call(
                "tx_search", query=f"tx.height={res['height']}"
            )
            assert found["total_count"] >= 1
            assert any(
                t["hash"] == tx_hash(tx).hex() for t in found["txs"]
            )

            # block_search by height event
            bs = await c.call(
                "block_search", query=f"block.height={res['height']}"
            )
            assert bs["total_count"] >= 1

            # block_results carries the DeliverTx result
            br = await c.call("block_results", height=res["height"])
            assert br["txs_results"][0]["code"] == 0

            # sync/async variants
            tx2 = b"k2=v2"
            r2 = await c.call(
                "broadcast_tx_sync", tx=base64.b64encode(tx2).decode()
            )
            assert r2["code"] == 0
            tx3 = b"k3=v3"
            r3 = await c.call(
                "broadcast_tx_async", tx=base64.b64encode(tx3).decode()
            )
            assert r3["hash"] == tx_hash(tx3).hex()

            # check_tx (query conn, no mempool insertion)
            r4 = await c.call(
                "check_tx", tx=base64.b64encode(b"k4=v4").decode()
            )
            assert r4["code"] == 0

            # unconfirmed_txs drains as blocks commit
            n0 = await c.call("num_unconfirmed_txs")
            assert n0["n_txs"] >= 0
            await c.call("unsafe_flush_mempool")
            n1 = await c.call("num_unconfirmed_txs")
            assert n1["n_txs"] == 0
        finally:
            await c.close()
            await node.stop()

    run(go())


def test_websocket_subscribe_new_block_and_tx(tmp_path):
    async def go():
        node, addr = await _boot(tmp_path)
        ws = WSClient(addr, timeout=30.0)
        try:
            await ws.connect()
            assert await ws.call("subscribe", query="tm.event='NewBlock'") == {}
            ev = await ws.next_event(timeout=30.0)
            assert ev["query"] == "tm.event='NewBlock'"
            h = ev["data"]["value"]["block"]["header"]["height"]
            assert h >= 1

            # a second subscription on the same socket: tx events
            assert await ws.call("subscribe", query="tm.event='Tx'") == {}
            tx = b"wskey=wsvalue"
            res = await ws.call(
                "broadcast_tx_sync", tx=base64.b64encode(tx).decode()
            )
            assert res["code"] == 0
            for _ in range(20):
                ev = await ws.next_event(timeout=30.0)
                if ev["query"] == "tm.event='Tx'":
                    break
            else:
                pytest.fail("no Tx event received")
            assert ev["data"]["value"]["tx"] == tx.hex()

            # unsubscribe stops the NewBlock feed eventually
            await ws.call("unsubscribe", query="tm.event='NewBlock'")
            await ws.call("unsubscribe_all")
        finally:
            await ws.close()
            await node.stop()

    run(go())


def test_uri_get_and_batch_post(tmp_path):
    """URI GET form + JSON-RPC batch POST (reference:
    rpc/jsonrpc/server/http_uri_handler.go)."""

    async def go():
        node, addr = await _boot(tmp_path)
        host, port = addr.split(":")
        try:
            reader, writer = await asyncio.open_connection(host, int(port))
            writer.write(
                f"GET /status HTTP/1.1\r\nHost: {host}\r\n\r\n".encode()
            )
            await writer.drain()
            line = await reader.readline()
            assert b"200" in line
            headers = {}
            while True:
                ln = await reader.readline()
                if ln in (b"\r\n", b"\n", b""):
                    break
                k, _, v = ln.decode().partition(":")
                headers[k.strip().lower()] = v.strip()
            body = await reader.readexactly(int(headers["content-length"]))
            obj = json.loads(body)
            assert obj["result"]["sync_info"]["latest_block_height"] >= 1

            # batch POST on the same keep-alive connection
            batch = json.dumps(
                [
                    {"jsonrpc": "2.0", "id": 1, "method": "health"},
                    {"jsonrpc": "2.0", "id": 2, "method": "status"},
                ]
            ).encode()
            writer.write(
                (
                    f"POST / HTTP/1.1\r\nHost: {host}\r\n"
                    "Content-Type: application/json\r\n"
                    f"Content-Length: {len(batch)}\r\n\r\n"
                ).encode()
                + batch
            )
            await writer.drain()
            line = await reader.readline()
            assert b"200" in line
            headers = {}
            while True:
                ln = await reader.readline()
                if ln in (b"\r\n", b"\n", b""):
                    break
                k, _, v = ln.decode().partition(":")
                headers[k.strip().lower()] = v.strip()
            body = await reader.readexactly(int(headers["content-length"]))
            arr = json.loads(body)
            assert [o["id"] for o in arr] == [1, 2]
            assert arr[1]["result"]["sync_info"]["latest_block_height"] >= 1
            writer.close()
        finally:
            await node.stop()

    run(go())


def test_local_client_matches_http(tmp_path):
    """LocalClient (in-process, no network hop) serves the same route
    surface and answers as the HTTP client (reference:
    rpc/client/local/local.go)."""
    from tendermint_tpu.rpc import LocalClient, RPCClientError

    async def go():
        node, addr = await _boot(tmp_path)
        http = HTTPClient(addr)
        local = LocalClient.from_node(node)
        try:
            await node.consensus.wait_for_height(2, timeout=60.0)
            h_status = await http.call("status")
            l_status = await local.call("status")
            assert l_status["node_info"] == h_status["node_info"]
            assert l_status["validator_info"] == h_status["validator_info"]
            l_block = await local.call("block", height=1)
            h_block = await http.call("block", height=1)
            assert l_block["block_id"] == h_block["block_id"]
            assert await local.call("health") == {}
            with pytest.raises(RPCClientError, match="websocket"):
                await local.call("subscribe", query="tm.event='NewBlock'")
            with pytest.raises(RPCClientError, match="unknown method"):
                await local.call("nope")
        finally:
            await http.close()
            await node.stop()

    run(go())
