"""tmrace: the whole-program static data-race / lock-order gate.

Three jobs: (1) run tmrace over the whole package on every tier-1
invocation, failing on anything beyond the (empty) race baseline —
the static complement of lockwatch's runtime witness; (2) unit-test
the analysis against the seeded mini-packages in tests/data/race/;
(3) pin the RANK_EDGES contract: every edge lockwatch declares static
must be derivable from source, so the rank table can't drift.
"""

import importlib.util
import os
import subprocess
import sys
import threading
import time

import pytest

from tendermint_tpu.analysis import lockwatch, tmrace
from tendermint_tpu.analysis.tmlint import (
    Violation,
    load_baseline,
    new_violations,
    save_baseline,
)
from tendermint_tpu.analysis.tmcheck.callgraph import build_package
from tendermint_tpu.analysis.tmrace.lockorder import (
    STATIC_RANK_NAMES,
    ranked_edges,
)

FIXTURES = os.path.join(os.path.dirname(__file__), "data", "race")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RANK_FIXTURE_NAMES = {"mod.py:a_lock": "A", "mod.py:b_lock": "B"}


def _fixture_report(name: str, **kwargs):
    pkg = build_package(os.path.join(FIXTURES, name))
    kwargs.setdefault("include_test_roots", False)
    kwargs.setdefault("rank_edges", {})
    kwargs.setdefault("rank_names", {})
    return tmrace.analyze(pkg, **kwargs)


# ---------------------------------------------------------------------------
# THE gate: whole package against the checked-in (empty) baseline


@pytest.fixture(scope="module")
def head_report():
    return tmrace.analyze()


def test_package_clean_against_baseline(head_report):
    """tmrace over the whole package; anything beyond
    tmrace/race_baseline.json fails tier-1 — fix it, suppress it with
    a justified `# tmrace: race-ok`/`guarded-by=`, or consciously
    re-baseline (docs/static_analysis.md)."""
    new = new_violations(
        head_report.violations, load_baseline(tmrace.RACE_BASELINE_PATH)
    )
    assert not new, "new tmrace violations:\n" + "\n".join(
        v.render() for v in new
    )


def test_race_baseline_is_checked_in_and_empty():
    """Every true positive the first full run surfaced was fixed (the
    faults.py env-latch ordering, the kernel _DEFAULT double-construct)
    or carries an in-file justified suppression, so the baseline must
    stay empty — new findings fail loudly, not silently grandfather."""
    assert os.path.exists(tmrace.RACE_BASELINE_PATH)
    assert load_baseline(tmrace.RACE_BASELINE_PATH) == {}


def test_full_package_run_under_budget():
    """Runtime budget: the race pass runs on every tier-1 invocation
    and must stay under 10 s for the whole package (measured ~5 s for
    160+ modules, call-graph build included)."""
    t0 = time.monotonic()
    tmrace.analyze()
    elapsed = time.monotonic() - t0
    assert elapsed < 10.0, f"tmrace full-package run took {elapsed:.1f}s"


# ---------------------------------------------------------------------------
# thread-root discovery over the real package


def test_head_root_catalog(head_report):
    """The statically enumerated entry points include the idioms the
    codebase actually uses: spawned threads (breaker probe, gather
    watchdog), the probe retry Timer, the asyncio main loop with the
    consensus receive loop labeled, and RPC registration tables."""
    by_key = {}
    for r in head_report.roots:
        by_key.setdefault((r.kind, r.key[0]), []).append(r)
    assert ("thread", "crypto/breaker.py") in by_key
    assert ("thread", "crypto/tpu_verifier.py") in by_key
    assert ("timer", "crypto/breaker.py") in by_key
    kinds = {r.kind for r in head_report.roots}
    assert "receive-loop" in kinds
    assert "rpc" in kinds
    # spawned identities race themselves; the single event loop doesn't
    assert all(
        r.self_concurrent for r in head_report.roots if r.kind == "thread"
    )
    assert not any(
        r.self_concurrent for r in head_report.roots if r.kind == "async"
    )


def test_callback_escape_reaches_probe_thread(head_report):
    """The breaker set_probe idiom: _device_probe is only ever CALLED
    through CircuitBreaker._run_probe's stored callback, so it must be
    rooted under the probe thread's identity — the chain that makes
    tpu_verifier's watchdog/deadline machinery concurrent with the
    main loop."""
    ids = head_report.identities.get(
        ("crypto/tpu_verifier.py", "_device_probe"), set()
    )
    assert "thread:crypto/breaker.py:CircuitBreaker._run_probe" in ids


def test_concurrent_region_covers_shared_metrics(head_report):
    """Metric mutators are reachable from the main loop AND the probe
    machinery — exactly the multi-root shape the lockset pass exists
    to check."""
    ids = head_report.identities.get(("libs/metrics.py", "Counter.inc"))
    assert ids is not None and len(ids) >= 2
    assert ("libs/metrics.py", "Counter.inc") in head_report.concurrent_region


# ---------------------------------------------------------------------------
# seeded fixtures (tests/data/race/): each check fails when violated


def test_fixture_unguarded_global_flagged():
    report = _fixture_report("unguarded_pkg")
    rules = {(v.rule, v.line) for v in report.violations}
    assert ("race-unguarded-global", 14) in rules, [
        v.render() for v in report.violations
    ]
    # the _lock-guarded twin of the same shape passes
    assert not any("GUARDED" in v.message for v in report.violations)


def test_fixture_cross_identity_single_degree_endpoints_flagged():
    """A race whose endpoints are each reachable from only ONE root
    identity (handler: main-loop only; worker_write: its own thread
    only) must still be paired — the concurrency cut is per VARIABLE
    over the union of the sites' identities, not per function, so
    neither endpoint being in the concurrent region is no excuse."""
    report = _fixture_report("split_pkg")
    flagged = {
        v.line
        for v in report.violations
        if v.rule == "race-unguarded-global" and "global `SPLIT`" in v.message
    }
    assert flagged == {18, 36}, [v.render() for v in report.violations]
    # handler is main-loop-only (degree 1): NOT in the per-function
    # concurrent region, so its line-36 site is only reachable through
    # the per-variable union — the line the old collector dropped
    # (worker_write IS in the region: a spawned thread root is
    # self-concurrent, start() may run twice)
    assert ("mod.py", "handler") not in report.concurrent_region
    assert report.identities[("mod.py", "handler")] == {"main-loop"}
    # the locked twin with the same split shape passes
    assert not any("SPLIT_GUARDED" in v.message for v in report.violations)


def test_fixture_nested_def_scopes_do_not_leak():
    """Global declarations and locally-bound names are per-SCOPE:
    a nested `global N` must not reclassify the enclosing function's
    plain local `N = 1` as a module-global write, and a name bound
    only inside a nested def must not shadow the outer function's
    read of the same-named module global (which pairs reader's thread
    identity with writer_handler's main-loop write)."""
    from tendermint_tpu.analysis.tmrace.lockset import Summarizer

    pkg = build_package(os.path.join(FIXTURES, "nested_pkg"))
    report = _fixture_report("nested_pkg")
    # the de-shadowed read makes M a two-identity variable: flagged
    m_lines = {
        v.line
        for v in report.violations
        if v.rule == "race-unguarded-global" and "global `M`" in v.message
    }
    assert m_lines == {38}, [v.render() for v in report.violations]
    # N never crosses identities — no violation either way; the scope
    # split is asserted at the summary level
    assert not any("global `N`" in v.message for v in report.violations)
    s = Summarizer(pkg)
    outer = s.summarize_function(pkg.functions[("mod.py", "outer_local")])
    assert not any(
        a.var == ("g", "mod.py", "N") and a.write for a in outer.accesses
    ), "enclosing local write leaked into global classification"
    helper_key = next(
        k
        for k in pkg.functions
        if k[0] == "mod.py" and k[1].endswith("helper_n")
    )
    nested = s.summarize_function(pkg.functions[helper_key])
    assert any(
        a.var == ("g", "mod.py", "N") and a.write for a in nested.accesses
    ), "the nested def's OWN global write must still be seen"


def test_fixture_unguarded_witness_names_both_roots():
    report = _fixture_report("unguarded_pkg")
    v = next(
        v for v in report.violations if v.rule == "race-unguarded-global"
    )
    assert "main-loop" in v.message
    assert "thread:" in v.message


def test_fixture_rank_contradiction_flagged():
    report = _fixture_report(
        "rank_pkg", rank={"A": 10, "B": 5}, rank_names=RANK_FIXTURE_NAMES
    )
    lock_order = [
        v for v in report.violations if v.rule == "race-lock-order"
    ]
    assert any(
        "contradicts lockwatch RANK" in v.message for v in lock_order
    )


def test_fixture_cycle_flagged_without_any_rank():
    """c_lock/d_lock are unranked: the A->B B->A cycle is still a
    latent deadlock and must be flagged on the raw static graph."""
    report = _fixture_report("rank_pkg")
    assert any(
        v.rule == "race-lock-order" and "cycle" in v.message
        for v in report.violations
    )


def test_fixture_rank_drift_flagged():
    """An edge declared static in RANK_EDGES that the source does not
    produce is itself a violation — the drift direction lockwatch
    cannot see."""
    report = _fixture_report(
        "rank_pkg",
        rank={},
        rank_names=RANK_FIXTURE_NAMES,
        rank_edges={("B", "A"): "static"},
    )
    assert any(
        v.rule == "race-rank-drift" for v in report.violations
    )
    # and an unknown classification string is an error, not a skip
    report = _fixture_report(
        "rank_pkg",
        rank={},
        rank_names=RANK_FIXTURE_NAMES,
        rank_edges={("A", "B"): "sometimes"},
    )
    assert any(
        v.rule == "race-rank-drift" and "sometimes" in v.message
        for v in report.violations
    )


def test_fixture_suppression_forms_pass():
    """race-ok, guarded-by=, and a justified tmlint
    lock-global-mutation disable each silence the finding."""
    report = _fixture_report("suppressed_pkg")
    assert report.violations == [], [
        v.render() for v in report.violations
    ]


def test_fixture_baseline_round_trip(tmp_path):
    """Counted-fingerprint semantics, same as tmlint/tmcheck: saving
    masks the current findings; one MORE identical-shaped site still
    fails the gate."""
    report = _fixture_report("unguarded_pkg")
    assert report.violations
    path = str(tmp_path / "race_baseline.json")
    save_baseline(report.violations, path)
    assert new_violations(report.violations, load_baseline(path)) == []
    extra = report.violations + [
        Violation(
            rule="race-unguarded-global",
            path="mod.py",
            line=99,
            col=0,
            message="seeded",
            source="OTHER = 1",
        )
    ]
    assert len(new_violations(extra, load_baseline(path))) == 1


# ---------------------------------------------------------------------------
# the RANK_EDGES contract: lockwatch's table cannot drift from source


def test_rank_edges_static_all_derived(head_report):
    derived = ranked_edges(head_report.edges)
    for edge, cls in lockwatch.RANK_EDGES.items():
        assert cls in ("static", "runtime-only"), edge
        if cls == "static":
            assert edge in derived, (
                f"RANK_EDGES declares {edge} static but tmrace cannot "
                "derive it — update the table or mark it runtime-only"
            )


def test_every_derived_edge_is_declared(head_report):
    """The inverse direction: a NEW statically derived edge between
    ranked locks must be added to RANK_EDGES — the table is the
    reviewed inventory of the lock graph."""
    for edge in ranked_edges(head_report.edges):
        assert edge in lockwatch.RANK_EDGES, (
            f"statically derived edge {edge} missing from "
            "lockwatch.RANK_EDGES"
        )


def test_static_rank_names_round_trip():
    """STATIC_RANK_NAMES maps lockset identities onto lockwatch's RANK
    namespace; every target must actually be ranked, and every edge in
    RANK_EDGES must stay inside that namespace."""
    for static_name, rank_name in STATIC_RANK_NAMES.items():
        assert rank_name in lockwatch.RANK, (static_name, rank_name)
    for a, b in lockwatch.RANK_EDGES:
        assert a in lockwatch.RANK and b in lockwatch.RANK, (a, b)


def test_rank_declared_edges_respect_rank_order():
    for (a, b), _cls in lockwatch.RANK_EDGES.items():
        assert lockwatch.RANK[a] < lockwatch.RANK[b], (
            f"RANK_EDGES entry {(a, b)} contradicts RANK itself"
        )


# ---------------------------------------------------------------------------
# CLI contract (scripts/lint.py --race)


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"), *args],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def _load_lint_module():
    spec = importlib.util.spec_from_file_location(
        "lint_cli", os.path.join(REPO, "scripts", "lint.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow
def test_cli_race_clean_exit_zero():
    r = _run_cli("--race", "--stats")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "[race]" in r.stdout


def test_cli_race_seeded_violation_exit_one(monkeypatch):
    """The exit contract end to end: a race finding beyond the (empty)
    baseline exits 1 through the real main()."""
    lint = _load_lint_module()
    seeded = [
        Violation(
            rule="race-unguarded-global",
            path="crypto/fake.py",
            line=1,
            col=0,
            message="seeded unguarded shared write",
            source="X = 1",
        )
    ]
    monkeypatch.setattr(
        lint.tmrace, "race_violations", lambda pkg=None, **kw: seeded
    )
    monkeypatch.setattr(
        lint.tmcheck, "build_package", lambda root=None: None
    )
    assert lint.main(["--race"]) == 1
    # rank-contradiction findings ride the same rule set / exit path
    seeded[0] = Violation(
        rule="race-lock-order",
        path="crypto/fake.py",
        line=1,
        col=0,
        message="seeded RANK-contradicting edge",
        source="with b_lock:",
    )
    assert lint.main(["--race"]) == 1


def test_cli_race_baseline_update_refuses_filtered_runs():
    """Same hazard the PR-5 fix closed for --schema-update: a filtered
    scan would overwrite the whole-file baseline with its subset."""
    r = _run_cli("--race", "--baseline-update", "--rule", "det-float")
    assert r.returncode == 2
    assert "full-package" in r.stderr
    r = _run_cli(
        "--race", "--baseline-update", "tendermint_tpu/crypto/faults.py"
    )
    assert r.returncode == 2


def test_cli_race_and_schema_combine():
    # section flags compose pairwise, same as --taint --schema and
    # --taint --race: both requested sections run, the others don't
    r = _run_cli("--race", "--schema", "--stats")
    assert r.returncode == 0
    assert "[schema+race]" in r.stdout


def test_cli_list_rules_includes_race():
    r = _run_cli("--list-rules")
    assert r.returncode == 0
    for rid, _title in tmrace.RULES:
        assert rid in r.stdout


# ---------------------------------------------------------------------------
# regression tests for the true positives the first full run surfaced


def test_faults_env_latch_never_answers_before_rules_load(monkeypatch):
    """tmrace finding #1 (crypto/faults.py): armed() used to set
    _ENV_LOADED BEFORE parsing TM_TPU_FAULT, so a second thread could
    see the latch up and answer False while the first was still
    parsing — a fault rule armed via env could be silently skipped
    exactly once. The latch now rises under _LOCK after _ARMED is
    refreshed."""
    from tendermint_tpu.crypto import faults

    monkeypatch.setenv("TM_TPU_FAULT", "tpu.dispatch:raise")
    faults.reset()
    faults._ENV_LOADED = False

    entered = threading.Event()
    proceed = threading.Event()
    real_parse = faults._parse_rule

    def slow_parse(spec):
        entered.set()
        assert proceed.wait(5), "test deadlock"
        return real_parse(spec)

    monkeypatch.setattr(faults, "_parse_rule", slow_parse)
    results = {}
    t = threading.Thread(target=lambda: results.setdefault(
        "first", faults.armed()
    ), daemon=True)
    t.start()
    assert entered.wait(5)
    # release the parser shortly AFTER this thread is blocked on _LOCK
    threading.Timer(0.05, proceed.set).start()
    # old code: returns False here (latch already up, rules not loaded)
    assert faults.armed() is True
    t.join(5)
    assert results["first"] is True
    monkeypatch.delenv("TM_TPU_FAULT")
    faults.reset()
    faults.load_env()  # re-sync armed state with the cleared env


@pytest.mark.parametrize(
    "module_name, class_name",
    [
        ("tendermint_tpu.ops.ed25519_kernel", "Ed25519Verifier"),
        ("tendermint_tpu.ops.sr25519_kernel", "Sr25519Verifier"),
    ],
)
def test_default_verifier_single_construction_under_hammer(
    module_name, class_name, monkeypatch
):
    """tmrace finding #2 (ops kernels): concurrent first calls to
    default_verifier() — the asyncio loop and the breaker probe thread
    — could each construct a verifier, and the loser's compiled-program
    cache was silently discarded. Now double-checked under
    _DEFAULT_LOCK: exactly one construction, everyone gets it."""
    mod = importlib.import_module(module_name)
    built = []
    barrier = threading.Barrier(8)

    class Counting:
        def __init__(self):
            built.append(self)
            time.sleep(0.05)  # widen the old race window

    monkeypatch.setattr(mod, class_name, Counting)
    monkeypatch.setattr(mod, "_DEFAULT", None)

    got = []

    def hammer():
        barrier.wait(5)
        got.append(mod.default_verifier())

    threads = [
        threading.Thread(target=hammer, daemon=True) for _ in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    assert len(built) == 1, f"{len(built)} constructions under contention"
    assert all(g is built[0] for g in got)
