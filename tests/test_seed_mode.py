"""Seed-mode node test: validators that only know the seed discover
each other via PEX and reach consensus (reference: node/seed.go — a
PEX-only node whose job is address introduction)."""

import asyncio
import time

from tendermint_tpu.config import Config
from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.node import NodeKey, make_node
from tendermint_tpu.p2p.transport import MemoryNetwork, MemoryTransport
from tendermint_tpu.privval import FilePV
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

CHAIN = "seed-chain"


def _cfg(tmp_path, name: str, mode: str = "validator") -> Config:
    cfg = Config()
    cfg.base.home = str(tmp_path / name)
    cfg.base.chain_id = CHAIN
    cfg.base.db_backend = "memdb"
    cfg.base.mode = mode
    cfg.consensus.timeout_propose = 2.0
    cfg.consensus.timeout_prevote = 1.0
    cfg.consensus.timeout_precommit = 1.0
    cfg.consensus.timeout_commit = 0.2
    cfg.consensus.peer_gossip_sleep_duration = 0.01
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    cfg.p2p.laddr = f"{name}:26656"
    cfg.ensure_dirs()
    return cfg


def test_validators_bootstrap_through_seed(tmp_path):
    async def go():
        n_vals = 3
        privs = [
            PrivKeyEd25519.from_seed(bytes([i + 160]) * 32)
            for i in range(n_vals)
        ]
        genesis = GenesisDoc(
            chain_id=CHAIN,
            genesis_time_ns=time.time_ns(),
            validators=[
                GenesisValidator(pub_key=p.pub_key(), power=10)
                for p in privs
            ],
        )
        net = MemoryNetwork()

        seed_cfg = _cfg(tmp_path, "seed", mode="seed")
        genesis.save_as(seed_cfg.base.path(seed_cfg.base.genesis_file))
        seed_id = NodeKey.load_or_generate(
            seed_cfg.base.path(seed_cfg.base.node_key_file)
        ).node_id
        seed = make_node(
            seed_cfg, transport=MemoryTransport(net, "seed:26656")
        )

        vals = []
        for i in range(n_vals):
            cfg = _cfg(tmp_path, f"val{i}")
            genesis.save_as(cfg.base.path(cfg.base.genesis_file))
            FilePV.from_priv_key(
                privs[i],
                cfg.base.path(cfg.priv_validator.key_file),
                cfg.base.path(cfg.priv_validator.state_file),
            ).save()
            # validators know ONLY the seed — peer discovery must come
            # from PEX through it
            cfg.p2p.bootstrap_peers = f"{seed_id}@seed:26656"
            vals.append(
                make_node(
                    cfg,
                    transport=MemoryTransport(net, f"val{i}:26656"),
                )
            )

        await seed.start()
        for v in vals:
            await v.start()
        try:
            # every validator must find the other two and make blocks
            await asyncio.gather(
                *(
                    v.consensus.wait_for_height(2, timeout=120.0)
                    for v in vals
                )
            )
            for v in vals:
                peers = v.peer_manager.peers()
                others = [
                    o.node_key.node_id for o in vals if o is not v
                ]
                assert all(o in peers for o in others), (
                    v.node_key.node_id,
                    peers,
                )
        finally:
            for v in vals:
                await v.stop()
            await seed.stop()

    asyncio.run(go())
