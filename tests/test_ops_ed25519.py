"""Differential tests for the full device ed25519 batch verifier against
the CPU implementations (OpenSSL fast path + pure-Python ZIP-215 oracle).
This mirrors the reference's own batch-vs-single equivalence strategy
(reference: types/validation_test.go, crypto/ed25519/ed25519_test.go)."""

import hashlib

import numpy as np
import pytest

from tendermint_tpu.crypto import ed25519_math as em
from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.ops.ed25519_kernel import Ed25519Verifier


@pytest.fixture(scope="module")
def verifier():
    return Ed25519Verifier(bucket_sizes=[8])


def _sign_set(n, tag=b""):
    keys = [
        PrivKeyEd25519.from_seed(hashlib.sha256(tag + bytes([i])).digest())
        for i in range(n)
    ]
    msgs = [b"msg-" + tag + bytes([i]) for i in range(n)]
    sigs = [k.sign(m) for k, m in zip(keys, msgs)]
    return [k.pub_key().bytes() for k in keys], msgs, sigs


def test_valid_batch(verifier):
    pks, msgs, sigs = _sign_set(6)
    ok = verifier.verify(pks, msgs, sigs)
    assert ok.tolist() == [True] * 6


def test_mixed_batch_bitmap(verifier):
    pks, msgs, sigs = _sign_set(6, b"x")
    # corrupt sig at 1, message at 3, pubkey at 5
    sigs[1] = sigs[1][:32] + (
        (int.from_bytes(sigs[1][32:], "little") ^ 1).to_bytes(32, "little")
    )
    msgs[3] = b"tampered"
    pks[5] = hashlib.sha256(b"not a point seed").digest()  # likely invalid/other key
    ok = verifier.verify(pks, msgs, sigs)
    # cross-check every index against the ZIP-215 oracle
    expect = [em.zip215_verify(p, m, s) for p, m, s in zip(pks, msgs, sigs)]
    assert ok.tolist() == expect
    assert not ok[1] and not ok[3] and not ok[5]


def test_high_s_rejected(verifier):
    pks, msgs, sigs = _sign_set(2, b"s")
    s = int.from_bytes(sigs[0][32:], "little")
    sigs[0] = sigs[0][:32] + (s + em.L).to_bytes(32, "little")
    ok = verifier.verify(pks, msgs, sigs)
    assert ok.tolist() == [False, True]


def test_malformed_sizes(verifier):
    pks, msgs, sigs = _sign_set(3, b"z")
    sigs[0] = sigs[0][:40]
    pks[1] = pks[1][:10]
    ok = verifier.verify(pks, msgs, sigs)
    assert ok.tolist() == [False, False, True]


def test_noncanonical_y_zip215_accepted(verifier):
    # Build a signature whose R has a y >= p encoding: R = point with
    # small y where y + p < 2^255. Craft via oracle: take a valid sig and
    # re-encode R non-canonically if possible; else assert oracle parity.
    pks, msgs, sigs = _sign_set(1, b"nc")
    r_int = int.from_bytes(sigs[0][:32], "little")
    y = r_int & ((1 << 255) - 1)
    if y + em.P < (1 << 255):  # rarely true for random points
        nc = (y + em.P) | (r_int & (1 << 255))
        sigs[0] = nc.to_bytes(32, "little") + sigs[0][32:]
    ok = verifier.verify(pks, msgs, sigs)
    expect = [em.zip215_verify(pks[0], msgs[0], sigs[0])]
    assert ok.tolist() == expect


def test_empty_batch(verifier):
    assert verifier.verify([], [], []).tolist() == []


def test_small_order_points_match_oracle(verifier):
    """Cofactor-sensitive edge class: small-order encodings for A and R
    (identity, y=-1 order 2, y=0 order 4). ZIP-215's cofactored
    equation accepts combinations a cofactorless verifier rejects; the
    kernel must agree with the pure-Python oracle bit for bit
    (reference semantics: crypto/ed25519/ed25519.go:27-29)."""
    ident = bytes([1]) + bytes(31)                    # y=1, order 1
    y_minus1 = int(em.P - 1).to_bytes(32, "little")   # y=-1, order 2
    y0_a = bytes(32)                                  # y=0, order 4
    y0_b = bytes(31) + bytes([0x80])                  # y=0, other root
    small = [ident, y_minus1, y0_a, y0_b]
    # order-8 torsion, derived not hard-coded: [L]P of an arbitrary
    # curve point lands in the 8-torsion; keep the order-8 ones.
    # Without these, [4]P == identity for every case and an off-by-one
    # in the kernel's cofactor-doubling loop would go unnoticed.
    for y in range(2, 200):
        pt = em.decompress(int(y).to_bytes(32, "little"))
        if pt is None:
            continue
        t = em.scalar_mult(em.L, pt)
        if (
            em.compress(em.scalar_mult(4, t)) != ident
            and em.compress(em.scalar_mult(8, t)) == ident
        ):
            enc = em.compress(t)
            small.append(enc)  # order-8 point
            small.append(enc[:31] + bytes([enc[31] ^ 0x80]))  # its negation
            break
    assert len(small) == 6, "order-8 torsion point not found"

    msg = b"small-order"
    cases = []
    # small-order A with R = small-order and S in {0, 1}
    for a in small:
        for r in small:
            for s_int in (0, 1):
                sig = r + int(s_int).to_bytes(32, "little")
                cases.append((a, msg, sig))
    # valid honest signature but R replaced by a small-order point
    priv = PrivKeyEd25519.from_seed(b"\x77" * 32)
    pk = priv.pub_key().bytes()
    honest = priv.sign(msg)
    for r in small:
        cases.append((pk, msg, r + honest[32:]))

    pks = [c[0] for c in cases]
    msgs = [c[1] for c in cases]
    sigs = [c[2] for c in cases]
    got = verifier.verify(pks, msgs, sigs)
    expect = [em.zip215_verify(p, m, s) for p, m, s in cases]
    assert list(got) == expect, list(zip(got, expect))
    # sanity: at least one cofactored acceptance exists in this set
    assert any(expect), "expected some small-order case to verify"


def test_sha512_kernel_matches_hashlib():
    """Device SHA-512 (ops/sha512_kernel.py) vs hashlib across block
    boundaries (111/112 bytes is the one/two-block edge)."""
    import jax
    import jax.numpy as jnp

    from tendermint_tpu.ops.sha512_kernel import sha512_fixed

    rng = np.random.default_rng(11)
    for length in (0, 1, 111, 112, 127, 128, 250):
        msgs = [
            bytes(rng.integers(0, 256, length, dtype=np.uint8))
            for _ in range(4)
        ]
        if length:
            rows = (
                np.frombuffer(b"".join(msgs), dtype=np.uint8)
                .reshape(4, length)
                .T
            )
        else:
            rows = np.zeros((0, 4), dtype=np.uint8)
        got = np.asarray(jax.jit(sha512_fixed)(jnp.asarray(rows)))
        for i, m in enumerate(msgs):
            assert got[:, i].tobytes() == hashlib.sha512(m).digest()


def test_sha512_unrolled_compress_matches_scan_form():
    """The TPU trace-time compression (_compress unrolled branch) vs
    the scan form the CPU backend traces — the unrolled branch never
    runs under JAX_PLATFORMS=cpu, so its math is covered directly."""
    import jax.numpy as jnp

    from tendermint_tpu.ops import sha512_kernel as SK

    import unittest.mock as mock

    rng = np.random.default_rng(13)
    state = jnp.asarray(rng.integers(0, 2**32, (8, 2, 5), dtype=np.uint32))
    block = jnp.asarray(rng.integers(0, 2**32, (16, 2, 5), dtype=np.uint32))
    # trace the unrolled branch by bypassing the backend gate
    with mock.patch("jax.default_backend", return_value="tpu"):
        got = np.asarray(SK._compress(state, block))
    want = np.asarray(SK._compress_scan(state, block))
    assert (got == want).all()


def test_mixed_message_lengths_device_digests(verifier):
    """dispatch groups by message length for the device SHA-512 and
    reassembles digests in batch order."""
    pks, msgs, sigs = _sign_set(6, b"len")
    keys = [
        PrivKeyEd25519.from_seed(hashlib.sha256(b"len" + bytes([i])).digest())
        for i in range(6)
    ]
    msgs = [b"x" * (10 + 7 * (i % 3)) for i in range(6)]
    sigs = [k.sign(m) for k, m in zip(keys, msgs)]
    sigs[2] = sigs[2][:10] + bytes([sigs[2][10] ^ 1]) + sigs[2][11:]
    ok = verifier.verify(pks, msgs, sigs)
    assert ok.tolist() == [True, True, False, True, True, True]


def test_host_sha512_env_knob(verifier, monkeypatch):
    monkeypatch.setenv("TM_TPU_HOST_SHA512", "1")
    pks, msgs, sigs = _sign_set(5, b"knob")
    assert verifier.verify(pks, msgs, sigs).all()


def test_recode_signed_value_preserving():
    """_recode_signed must re-express the radix-16 value exactly with
    digits in [-8, 7] — including maximal carry-propagation runs (all
    7s, all 8s, all 15s) where the Kogge-Stone lattice is stressed."""
    import jax
    import jax.numpy as jnp

    from tendermint_tpu.ops import ed25519_kernel as K

    rng = np.random.default_rng(5)
    cols = [
        rng.integers(0, 16, 64) for _ in range(12)
    ] + [
        np.full(64, 7), np.full(64, 8), np.full(64, 15), np.zeros(64),
        np.array([15] * 63 + [0]),  # carry run stopping at the top
    ]
    # keep the top digit small enough that no carry is dropped (the
    # dropped-carry case is gated by s < L upstream — see docstring)
    for c in cols:
        c[-1] = min(int(c[-1]), 6)
    d = np.stack(cols, axis=1).astype(np.int32)  # (64, N)
    e = np.asarray(jax.jit(K._recode_signed)(jnp.asarray(d)))
    assert e.min() >= -8 and e.max() <= 7
    w = 16 ** np.arange(64, dtype=object)
    for j in range(d.shape[1]):
        orig = int(sum(int(x) * int(p) for x, p in zip(d[:, j], w)))
        got = int(sum(int(x) * int(p) for x, p in zip(e[:, j], w)))
        assert got == orig, j
