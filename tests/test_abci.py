"""ABCI layer tests: codec round-trips, local + socket clients, proxy mux,
kvstore app semantics (reference test model: abci/example/example_test.go,
abci/client/socket_client_test.go, abci/example/kvstore/kvstore_test.go)."""

import asyncio

import pytest

from tendermint_tpu.abci import (
    AppConns,
    KVStoreApplication,
    LocalClient,
    SocketClient,
    SocketServer,
    local_creator,
)
from tendermint_tpu.abci import types as T
from tendermint_tpu.abci.codec import (
    decode_request,
    decode_response,
    encode_request,
    encode_response,
)


def run(coro):
    return asyncio.run(coro)


# ---------------------------------------------------------------------------
# codec


REQ_SAMPLES = [
    T.RequestEcho(message="hello"),
    T.RequestFlush(),
    T.RequestInfo(version="v1", block_version=11, p2p_version=8, abci_version="0.17"),
    T.RequestInitChain(
        time_ns=123456789,
        chain_id="test-chain",
        validators=(
            T.ValidatorUpdate(pub_key=T.PubKey("ed25519", b"\x01" * 32), power=10),
        ),
        app_state_bytes=b'{"x":1}',
        initial_height=5,
    ),
    T.RequestQuery(data=b"k", path="/store", height=7, prove=True),
    T.RequestBeginBlock(
        hash=b"\xaa" * 32,
        header_bytes=b"\x0a\x00",
        last_commit_info=T.LastCommitInfo(
            round=2,
            votes=(
                T.VoteInfo(
                    validator=T.Validator(address=b"\x02" * 20, power=3),
                    signed_last_block=True,
                ),
            ),
        ),
        byzantine_validators=(
            T.Misbehavior(
                kind=T.MISBEHAVIOR_DUPLICATE_VOTE,
                validator=T.Validator(address=b"\x03" * 20, power=4),
                height=9,
                time_ns=1111,
                total_voting_power=100,
            ),
        ),
    ),
    T.RequestCheckTx(tx=b"a=1", type=T.CheckTxType.RECHECK),
    T.RequestDeliverTx(tx=b"a=1"),
    T.RequestEndBlock(height=12),
    T.RequestCommit(),
    T.RequestListSnapshots(),
    T.RequestOfferSnapshot(
        snapshot=T.Snapshot(height=10, format=1, chunks=3, hash=b"\x04" * 32),
        app_hash=b"\x05" * 32,
    ),
    T.RequestLoadSnapshotChunk(height=10, format=1, chunk=2),
    T.RequestApplySnapshotChunk(index=1, chunk=b"chunk", sender="peer1"),
]

RESP_SAMPLES = [
    T.ResponseException(error="boom"),
    T.ResponseEcho(message="hello"),
    T.ResponseFlush(),
    T.ResponseInfo(
        data="{}", version="kv/1", app_version=1, last_block_height=4,
        last_block_app_hash=b"\x06" * 32,
    ),
    T.ResponseInitChain(app_hash=b"\x07" * 32),
    T.ResponseQuery(code=0, key=b"k", value=b"v", height=4, log="exists"),
    T.ResponseBeginBlock(
        events=(
            T.Event(
                type="begin",
                attributes=(T.EventAttribute(b"k", b"v", True),),
            ),
        )
    ),
    T.ResponseCheckTx(code=0, gas_wanted=1, priority=9, sender="s"),
    T.ResponseDeliverTx(
        code=0,
        data=b"result",
        events=(T.Event(type="app", attributes=(T.EventAttribute(b"a", b"b"),)),),
    ),
    T.ResponseEndBlock(
        validator_updates=(
            T.ValidatorUpdate(pub_key=T.PubKey("ed25519", b"\x08" * 32), power=0),
        )
    ),
    T.ResponseCommit(data=b"\x09" * 32, retain_height=2),
    T.ResponseListSnapshots(
        snapshots=(T.Snapshot(height=1, format=1, chunks=1, hash=b"\x0a" * 32),)
    ),
    T.ResponseOfferSnapshot(result=T.OFFER_SNAPSHOT_ACCEPT),
    T.ResponseLoadSnapshotChunk(chunk=b"bytes"),
    T.ResponseApplySnapshotChunk(
        result=T.APPLY_CHUNK_RETRY, refetch_chunks=(0, 2), reject_senders=("bad",)
    ),
]


@pytest.mark.parametrize("req", REQ_SAMPLES, ids=lambda r: type(r).__name__)
def test_request_roundtrip(req):
    assert decode_request(encode_request(req)) == req


@pytest.mark.parametrize("resp", RESP_SAMPLES, ids=lambda r: type(r).__name__)
def test_response_roundtrip(resp):
    assert decode_response(encode_response(resp)) == resp


# ---------------------------------------------------------------------------
# kvstore app


def test_kvstore_set_get_commit():
    app = KVStoreApplication()
    assert app.check_tx(T.RequestCheckTx(tx=b"name=alice")).is_ok
    app.begin_block(T.RequestBeginBlock())
    assert app.deliver_tx(T.RequestDeliverTx(tx=b"name=alice")).is_ok
    app.end_block(T.RequestEndBlock(height=1))
    c1 = app.commit()
    assert c1.data != b""

    r = app.query(T.RequestQuery(data=b"name"))
    assert r.value == b"alice"
    # bare tx stores key=key
    app.deliver_tx(T.RequestDeliverTx(tx=b"solo"))
    assert app.query(T.RequestQuery(data=b"solo")).value == b"solo"
    # app hash changes deterministically with state
    c2 = app.commit()
    assert c2.data != c1.data
    app2 = KVStoreApplication()
    app2.deliver_tx(T.RequestDeliverTx(tx=b"name=alice"))
    app2.deliver_tx(T.RequestDeliverTx(tx=b"solo"))
    assert app2.commit().data == c2.data


def test_kvstore_validator_updates():
    app = KVStoreApplication()
    pk = b"\x11" * 32
    tx = f"val:{pk.hex()}!7".encode()
    assert app.check_tx(T.RequestCheckTx(tx=tx)).is_ok
    assert not app.check_tx(T.RequestCheckTx(tx=b"val:zz!1")).is_ok
    app.begin_block(T.RequestBeginBlock())
    assert app.deliver_tx(T.RequestDeliverTx(tx=tx)).is_ok
    resp = app.end_block(T.RequestEndBlock(height=1))
    assert resp.validator_updates == (
        T.ValidatorUpdate(pub_key=T.PubKey("ed25519", pk), power=7),
    )
    assert app.query(T.RequestQuery(path="/val", data=pk.hex().encode())).value == b"7"


def test_kvstore_snapshot_restore():
    app = KVStoreApplication()
    for i in range(50):
        app.deliver_tx(T.RequestDeliverTx(tx=f"k{i}=v{i}".encode()))
    app.commit()
    snap = app.take_snapshot()
    assert app.list_snapshots(T.RequestListSnapshots()).snapshots[0] == snap

    restored = KVStoreApplication()
    assert (
        restored.offer_snapshot(
            T.RequestOfferSnapshot(snapshot=snap, app_hash=app.app_hash)
        ).result
        == T.OFFER_SNAPSHOT_ACCEPT
    )
    for i in range(snap.chunks):
        chunk = app.load_snapshot_chunk(
            T.RequestLoadSnapshotChunk(height=snap.height, format=1, chunk=i)
        ).chunk
        restored.apply_snapshot_chunk(T.RequestApplySnapshotChunk(index=i, chunk=chunk))
    assert restored.app_hash == app.app_hash
    assert restored.state == app.state


# ---------------------------------------------------------------------------
# clients


def test_local_client_roundtrip():
    async def go():
        app = KVStoreApplication()
        client = LocalClient(app)
        await client.start()
        assert (await client.echo("hi")).message == "hi"
        await client.deliver_tx(T.RequestDeliverTx(tx=b"x=y"))
        resp = await client.commit()
        assert resp.data == app.app_hash
        await client.stop()

    run(go())


def test_socket_client_server_roundtrip():
    async def go():
        app = KVStoreApplication()
        server = SocketServer("tcp://127.0.0.1:0", app)
        await server.start()
        client = SocketClient(f"tcp://127.0.0.1:{server.listen_port}")
        await client.start()

        assert (await client.echo("ping")).message == "ping"
        info = await client.info(T.RequestInfo(version="v"))
        assert info.last_block_height == 0

        # pipeline several requests concurrently; FIFO matching must hold
        results = await asyncio.gather(
            client.deliver_tx(T.RequestDeliverTx(tx=b"a=1")),
            client.deliver_tx(T.RequestDeliverTx(tx=b"b=2")),
            client.check_tx(T.RequestCheckTx(tx=b"c=3")),
        )
        assert all(r.is_ok for r in results)
        commit = await client.commit()
        assert commit.data == app.app_hash
        q = await client.query(T.RequestQuery(data=b"a"))
        assert q.value == b"1"

        await client.stop()
        await server.stop()

    run(go())


def test_app_conns_mux():
    async def go():
        app = KVStoreApplication()
        conns = AppConns(local_creator(app))
        await conns.start()
        # four independent connections hit one app
        await conns.mempool.check_tx(T.RequestCheckTx(tx=b"m=1"))
        await conns.consensus.deliver_tx(T.RequestDeliverTx(tx=b"c=1"))
        info = await conns.query.info(T.RequestInfo())
        assert info.last_block_height == 0
        snaps = await conns.snapshot.list_snapshots(T.RequestListSnapshots())
        assert snaps.snapshots == ()
        await conns.stop()

    run(go())
