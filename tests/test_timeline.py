"""Consensus flight recorder (ISSUE 15): recorder units, the
disabled-path zero-overhead contract, seed-determinism of the event
stream, WAL post-mortem reconstruction, the fleet merger on a live
4-node localnet, and the consensus_timeline RPC route.
"""

import asyncio

import pytest

from tendermint_tpu.consensus import timeline as tlmod
from tendermint_tpu.consensus.metrics import ConsensusMetrics
from tendermint_tpu.consensus.timeline import (
    EV_COMMIT,
    EV_POLKA,
    EV_PROPOSAL,
    EV_STEP,
    EV_TIMEOUT,
    TimelineRecorder,
    events_from_wal,
    summarize_heights,
)
from tendermint_tpu.libs.metrics import Registry


def run(coro):
    return asyncio.run(coro)


def fresh_metrics() -> ConsensusMetrics:
    return ConsensusMetrics(Registry())


class TestRecorder:
    def test_record_page_eviction_and_cursor(self):
        tl = TimelineRecorder(capacity=8)
        for h in range(1, 13):
            tl.record(EV_STEP, h, 0, step="RoundStepPropose")
        assert len(tl) == 8  # ring bound held
        assert tl.dropped_before() == 4  # seqs 1..4 evicted
        events, next_seq, dropped = tl.page(0, 100)
        assert dropped == 4
        assert [e["seq"] for e in events] == list(range(5, 13))
        # cursor resume: page size 3 walks without overlap or gap
        got, cursor = [], 0
        while True:
            page, cursor, _ = tl.page(cursor, 3)
            if not page:
                break
            got.extend(e["seq"] for e in page)
        assert got == list(range(5, 13))

    def test_crossing_dedup_and_metric_feed(self):
        m = fresh_metrics()
        tl = TimelineRecorder(capacity=64, metrics=m)
        tl.mark_new_height(5)
        tl.mark_proposal(5, 0)
        for _ in range(4):  # every vote after the threshold re-fires
            tl.mark_polka(5, 0)
            tl.mark_precommit_quorum(5, 0)
        kinds = [e.kind for e in tl.snapshot()]
        assert kinds.count(EV_POLKA) == 1
        assert kinds.count("precommit_quorum") == 1
        # each quorum latency observed exactly once
        assert m.quorum_prevote_latency.count() == 1
        assert m.quorum_precommit_latency.count() == 1
        tl.mark_commit(5, 2, 7, "abcd")
        # rounds-to-commit observed once, as commit round + 1
        assert m.rounds_per_height.count() == 1
        assert "rounds_per_height_sum 3" in "\n".join(
            m.rounds_per_height.render()
        )

    def test_new_height_clears_dedup_and_anchors(self):
        tl = TimelineRecorder(capacity=64)
        tl.mark_new_height(1)
        tl.mark_polka(1, 0)
        tl.mark_new_height(2)
        tl.mark_polka(2, 0)
        polkas = [e for e in tl.snapshot() if e.kind == EV_POLKA]
        assert [(e.height, e.round) for e in polkas] == [(1, 0), (2, 0)]

    def test_quorum_latency_requires_same_round(self):
        m = fresh_metrics()
        tl = TimelineRecorder(capacity=64, metrics=m)
        tl.mark_new_height(3)
        tl.mark_proposal(3, 0)
        tl.mark_polka(3, 1)  # crossed in a LATER round: no pairing
        assert m.quorum_prevote_latency.count() == 0

    def test_disabled_path_allocates_nothing(self):
        """Kill-switch mirror of the PR-1 span test: a disabled
        recorder constructs no event object and touches no ring."""
        built = []
        orig = tlmod.TimelineEvent

        class Counting(orig):
            def __init__(self, *a, **kw):
                built.append(1)
                super().__init__(*a, **kw)

        tl = TimelineRecorder(capacity=8, enabled=False)
        tlmod.TimelineEvent = Counting
        try:
            for _ in range(100):
                tl.record(EV_STEP, 1, 0, step="RoundStepPropose")
        finally:
            tlmod.TimelineEvent = orig
        assert built == [] and len(tl) == 0

    def test_kill_switch_silences_ring_not_metrics(self):
        m = fresh_metrics()
        tl = TimelineRecorder(capacity=64, enabled=False, metrics=m)
        tl.mark_new_height(1)
        tl.mark_proposal(1, 0)
        tl.mark_polka(1, 0)
        tl.mark_stall_reset("live", 1, 0, "peerpeerpeer")
        assert len(tl) == 0  # ring silent
        assert m.quorum_prevote_latency.count() == 1  # metrics live
        assert m.stall_resets.value(kind="live") == 1.0

    def test_to_json_shape(self):
        import json

        tl = TimelineRecorder(capacity=4)
        tl.record(EV_COMMIT, 9, 1, num_txs=3, block="ff00")
        doc = json.loads(tl.to_json())
        assert doc["enabled"] and doc["dropped_before"] == 0
        (e,) = doc["timeline"]
        assert e["kind"] == EV_COMMIT and e["height"] == 9
        assert e["num_txs"] == 3 and e["round"] == 1
        assert e["t_mono_ns"] > 0 and e["t_wall_ns"] > 0


def test_disabled_recorder_zero_calls_through_real_transitions():
    """The step-transition sites guard on `tl.enabled` BEFORE calling
    record() — pinned with a counting stub through a real
    single-validator consensus run (the `timeline_overhead` bench
    row's 'adds ~0 ns' claim is this call-site contract), while the
    always-on mark_* crossings keep feeding the metrics plane."""
    from tests.test_consensus_state import Node, single_genesis
    from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519

    async def go():
        priv = PrivKeyEd25519.from_seed(b"\x31" * 32)
        node = Node(priv, single_genesis(priv))
        m = fresh_metrics()
        tl = TimelineRecorder(enabled=False, metrics=m)
        calls = []
        orig_record = tl.record

        def counting_record(*a, **kw):
            calls.append(a)
            return orig_record(*a, **kw)

        tl.record = counting_record
        node.cs.timeline = tl
        node.cs.timeline.mark_new_height(node.cs.rs.height)
        await node.cs.start()
        try:
            await node.cs.wait_for_height(3, timeout=20.0)
        finally:
            await node.cs.stop()
        assert calls == []  # disabled: record() never even called
        assert len(tl) == 0
        # the crossings still fed the reference-parity metrics
        assert m.rounds_per_height.count() >= 2
        assert m.quorum_precommit_latency.count() >= 2

    run(go())


def test_event_sequence_deterministic_for_seed():
    """Same seed => same event sequence per node (ISSUE 15 test
    item): two runs of the identical seeded vote-delivery schedule
    into a real ConsensusState produce byte-identical
    (kind, height, round, step) streams. Long protocol timeouts keep
    wall-clock noise out of the stream; the gossip RNG is pinned by
    the schedule (libs/schedulefuzz contract)."""
    from tests.test_consensus_lock import LockHarness, wait_for
    from tests.test_consensus_state import fast_config
    from tendermint_tpu.libs.schedulefuzz import Schedule
    from tendermint_tpu.types.canonical import (
        PRECOMMIT_TYPE,
        PREVOTE_TYPE,
    )

    async def one_run(seed: int):
        sched = Schedule(seed)
        sched.seed_gossip()
        h = LockHarness(seed_base=240)
        # no mid-round timeout may race the delivery: the sequence
        # must be a pure function of the schedule
        h.cs.cfg = fast_config(
            timeout_propose=10.0,
            timeout_prevote=10.0,
            timeout_precommit=10.0,
        )
        tl = TimelineRecorder(capacity=1024)
        h.cs.timeline = tl
        await h.cs.start()
        try:
            prevote = await h.wait_own_vote(PREVOTE_TYPE, 0)
            b1 = prevote.block_id
            plan = []
            for priv in h.stubs:
                plan.append(await h.make_vote(priv, PREVOTE_TYPE, 0, b1))
                plan.append(
                    await h.make_vote(priv, PRECOMMIT_TYPE, 0, b1)
                )
            for vote in sched.with_dups(sched.shuffled(plan), 3):
                h.send_vote(vote)
                await sched.yield_point()
            await wait_for(
                lambda: h.node.block_store.height() >= 1,
                timeout=30.0,
                what=f"commit under schedule {seed}",
            )
        finally:
            await h.cs.stop()
        return [
            (e.kind, e.height, e.round, e.step)
            for e in tl.snapshot()
            if e.kind != EV_TIMEOUT  # the only wall-clock-driven kind
        ]

    for seed in (7, 19):
        a = run(one_run(seed))
        b = run(one_run(seed))
        assert a == b, f"event stream depends on more than seed {seed}"
        assert any(e[0] == EV_COMMIT for e in a)
        assert any(e[0] == EV_POLKA for e in a)


def test_wal_reconstruction_rebuilds_phase_story(tmp_path):
    """events_from_wal on a real node's WAL: every committed height
    gets step markers, the proposal, both count-based quorum
    crossings, and the end-height commit — and summarize_heights
    produces a full phase table (the scripts/timeline_replay.py
    surface). The committee size is inferred from the log."""
    from tendermint_tpu.consensus.wal_generator import generate_wal

    async def go():
        return await generate_wal(str(tmp_path / "walgen"), 3)

    wal_path, _genesis, _priv = run(go())
    events = events_from_wal(wal_path)
    by_height = {}
    for e in events:
        by_height.setdefault(e["height"], set()).add(e["kind"])
    for h in (1, 2, 3):
        kinds = by_height[h]
        assert EV_STEP in kinds
        assert EV_PROPOSAL in kinds
        assert EV_POLKA in kinds  # single validator: quorum == 1 vote
        assert "precommit_quorum" in kinds
        assert EV_COMMIT in kinds
    # derived crossings say so, and carry the inferred committee
    polka = next(e for e in events if e["kind"] == EV_POLKA)
    assert polka["derived"] == "count_threshold"
    assert polka["committee"] == 1
    rows = summarize_heights(events)
    assert [r["height"] for r in rows][:3] == [1, 2, 3]
    for r in rows[:3]:
        assert r["proposal_to_polka_ms"] is not None
        assert r["polka_to_precommit_quorum_ms"] is not None
        assert r["precommit_quorum_to_commit_ms"] is not None
        assert r["timeouts"] == 0  # healthy solo run

    # wall times are monotone non-decreasing within the stream
    walls = [e["t_wall_ns"] for e in events]
    assert walls == sorted(walls)


def test_wal_reconstruction_counts_quorum_per_block(tmp_path):
    """Nil and mixed vote sets must NOT fake a crossing: the live
    sites require +2/3 for ONE non-nil block (state.py guards both
    polka and precommit-quorum on is_zero), so the count-based WAL
    derivation keys voters by (…, block_id). A 4-validator log where
    all 4 precommit nil, or split 2/2 across blocks, reconstructs
    with zero polka/quorum events; 3/4 on one block crosses."""
    from tendermint_tpu.consensus.msgs import MsgInfo, VoteMessage
    from tendermint_tpu.consensus.wal import WAL
    from tendermint_tpu.types.block_id import BlockID, PartSetHeader
    from tendermint_tpu.types.vote import Vote
    from tendermint_tpu.types.canonical import (
        PRECOMMIT_TYPE,
        PREVOTE_TYPE,
    )

    def blk(tag: bytes) -> BlockID:
        return BlockID(
            hash=tag * 32, part_set_header=PartSetHeader(1, tag * 32)
        )

    def vote(vtype, height, round_, bid, idx):
        return MsgInfo(
            msg=VoteMessage(
                vote=Vote(
                    type=vtype,
                    height=height,
                    round=round_,
                    block_id=bid,
                    timestamp_ns=1,
                    validator_address=bytes([idx]) * 20,
                    validator_index=idx,
                    signature=b"\x01" * 64,
                )
            )
        )

    path = str(tmp_path / "nilwal")

    async def go():
        w = WAL(path)
        await w.start()
        # h=1 r=0: all 4 precommit NIL (a burned round) — no quorum
        for i in range(4):
            w.write(vote(PRECOMMIT_TYPE, 1, 0, BlockID(), i))
        # h=1 r=1: prevotes split 2/2 across two blocks — no polka
        for i, tag in enumerate((b"\xaa", b"\xaa", b"\xbb", b"\xbb")):
            w.write(vote(PREVOTE_TYPE, 1, 1, blk(tag), i))
        # h=1 r=2: 3 of 4 prevote the SAME block — polka crosses
        for i in range(3):
            w.write(vote(PREVOTE_TYPE, 1, 2, blk(b"\xcc"), i))
        await w.stop()

    run(go())
    events = events_from_wal(path)
    crossings = [
        e
        for e in events
        if e["kind"] in (EV_POLKA, "precommit_quorum")
    ]
    assert [(e["kind"], e["round"]) for e in crossings] == [
        (EV_POLKA, 2)
    ]
    assert crossings[0]["voters"] == 3  # 2/3 of committee=4 -> 3


def test_fleet_merge_and_rpc_route_on_live_localnet(tmp_path):
    """Merge correctness on a live 4-node localnet (ISSUE 15 test
    item): every committed height is attributed, no orphan events —
    and the consensus_timeline RPC route pages the same ring over
    real HTTP with the seq cursor."""
    from tendermint_tpu.loadgen import timeline as fleet
    from tendermint_tpu.loadgen.localnet import start_localnet
    from tendermint_tpu.rpc.client import HTTPClient

    async def go():
        ln = await start_localnet(4, str(tmp_path / "fleetnet"))
        try:
            await ln.wait_for_height(4, timeout=60.0)
            collected = fleet.collect(ln)
            assert set(collected) == {
                "load0",
                "load1",
                "load2",
                "load3",
            }
            rows = fleet.attribute_heights(collected)
            common = min(n.block_store.height() for n in ln.nodes)
            max_h = max(n.consensus.rs.height for n in ln.nodes)
            attributed = {r["height"] for r in rows}
            # every committed height has an attribution row
            assert attributed.issuperset(range(1, common + 1))
            # no orphan events: every event lands at a real height
            # (at most the in-progress one past the tips)
            for evs in collected.values():
                for e in evs:
                    assert 1 <= e["height"] <= max_h + 1
            for r in rows[: common]:
                assert r["nodes_committed"] >= 1
                assert r["proposer_lag_ms"] is not None
                assert r["commit_spread_ms"] is not None
            summary = fleet.fleet_summary(collected)
            assert summary["heights_attributed"] == len(rows)
            assert summary["events_total"] == sum(
                len(v) for v in collected.values()
            )
            assert (
                summary["proposal_to_polka"]["mean_ms"] is not None
            )

            # the RPC route serves the same ring, paged
            c = HTTPClient(ln.rpc_addrs[0])
            try:
                first = await c.call(
                    "consensus_timeline", max_events=5
                )
                assert first["node"] == "load0"
                assert first["enabled"] is True
                assert len(first["events"]) == 5  # shrink-clamped
                got = list(first["events"])
                cursor = first["next_seq"]
                while True:
                    page = await c.call(
                        "consensus_timeline", after_seq=cursor
                    )
                    if not page["events"]:
                        break
                    got.extend(page["events"])
                    cursor = page["next_seq"]
                seqs = [e["seq"] for e in got]
                assert seqs == sorted(seqs)
                assert len(seqs) == len(set(seqs))  # no overlap
                ring = ln.nodes[0].consensus.timeline
                # pages cover the ring as of the LAST page fetch
                # (live chain: new events append between pages)
                assert set(seqs).issuperset(
                    e["seq"]
                    for e in collected["load0"]
                    if e["seq"] > first["dropped_before"]
                )
                assert ring.capacity == first["capacity"]
            finally:
                await c.close()
        finally:
            await ln.stop()

    run(go())
