"""tmmc — the exhaustive consensus exploration plane (ISSUE 19).

Tier-1: the model harness (real ConsensusState objects, lifted
network), the DFS explorer (sleep sets + fingerprint dedup, budgets,
trace minimization/replay), the four machine-checked invariants, the
`scripts/lint.py --mc` gate section, and the seeded A/B proofs: a
package copy with the prevote quorum weakened to 1/2 turns
mc-agreement red, a copy with evidence formation disabled turns
mc-accountability red — each with a minimized witness trace that
replays to the same violation.

The A/B tests run in subprocesses against a mutated COPY of the
package (PYTHONPATH points at the copy) so the installed tree is
never touched.
"""

import asyncio
import json
import os
import shutil
import subprocess
import sys

import pytest

from tendermint_tpu.analysis import tmmc
from tendermint_tpu.analysis.tmmc.explorer import (
    Budgets,
    Trace,
    explore,
    measure_reduction,
    minimize_trace,
    replay_trace,
)
from tendermint_tpu.analysis.tmmc.harness import MCConfig, ModelNet

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = MCConfig(n_validators=2, target_height=1, max_round=1)


def _greedy_run(cfg, max_steps=400):
    """Drive one net along the delivery-first greedy schedule to
    completion; returns the closed-over nodes' summary."""
    loop = asyncio.new_event_loop()
    net = ModelNet(cfg, loop)
    try:
        steps = 0
        while not net.all_done() and steps < max_steps:
            enabled = net.transitions()
            if not enabled:
                break
            deliveries = [t for t in enabled if t[0] == "d"]
            net.apply(sorted(deliveries or enabled)[0])
            steps += 1
        from tendermint_tpu.analysis.tmmc import invariants

        return {
            "done": net.all_done(),
            "steps": steps,
            "heights": [n.block_store.height() for n in net.nodes],
            "hashes": [
                n.block_store.load_block_meta(1).block_id.hash
                for n in net.nodes
                if n.block_store.load_block_meta(1)
            ],
            "detections": [len(n.detections) for n in net.nodes],
            "violations": invariants.check_all(net, net.transitions()),
        }
    finally:
        net.close()
        loop.close()


class TestHarness:
    def test_greedy_happy_path_commits_identically(self):
        r = _greedy_run(MCConfig(n_validators=4, target_height=2))
        assert r["done"], r
        assert r["heights"] == [2, 2, 2, 2]
        assert len(set(r["hashes"])) == 1
        assert r["violations"] == []

    def test_fingerprints_deterministic_and_state_sensitive(self):
        loop = asyncio.new_event_loop()
        a, b = ModelNet(TINY, loop), ModelNet(TINY, loop)
        try:
            assert a.fingerprint() == b.fingerprint()
            t = sorted(a.transitions())[0]
            a.apply(t)
            assert a.fingerprint() != b.fingerprint()
            b.apply(t)
            assert a.fingerprint() == b.fingerprint()
        finally:
            a.close()
            b.close()
            loop.close()

    def test_equivocation_detected_and_evidence_committed(self):
        cfg = MCConfig(
            n_validators=4,
            target_height=2,
            byz=(
                {
                    "behavior": "equivocate",
                    "h_lo": 1,
                    "h_hi": 1,
                    "victim": "mc0",
                },
            ),
        )
        r = _greedy_run(cfg)
        assert r["done"], r
        # somebody observed the double-sign, and accountability held
        # at every probe point of the greedy run's final state
        assert sum(r["detections"]) >= 1
        assert r["violations"] == []

    def test_config_validation_rejects_non_forced_specs(self):
        with pytest.raises(ValueError):
            MCConfig(
                byz=({"behavior": "equivocate", "p": 0.5, "victim": "mc0"},)
            )
        with pytest.raises(ValueError):
            MCConfig(
                byz=({"behavior": "equivocate", "victim": "not-a-node"},)
            )


class TestExplorer:
    def test_tiny_config_exhausts_and_stays_green(self):
        res = explore(
            TINY,
            Budgets(max_states=3_000, max_depth=32, max_edges=8_000,
                    wall_s=30.0),
            seed=0,
            stop_at_first=False,
        )
        assert res.ok, [v.message for v in res.violations]
        assert res.stats["stopped_by"] == "exhausted"
        assert res.stats["terminals"] >= 1
        assert res.stats["sleep_skips"] > 0
        assert res.stats["dedup_hits"] > 0

    def test_naive_mode_covers_same_states_with_more_visits(self):
        b = Budgets(max_states=10**6, max_depth=4, max_edges=10**6,
                    wall_s=30.0)
        reduced = explore(TINY, b, seed=0, stop_at_first=False)
        naive = explore(
            TINY, b, seed=0, reduce=False, dedup=False,
            stop_at_first=False,
        )
        assert reduced.stats["stopped_by"] == "exhausted"
        assert naive.stats["stopped_by"] == "exhausted"
        # identical coverage of the depth-4 subspace, paid for with
        # strictly more state visits
        assert (
            naive.stats["unique_fingerprints"]
            == reduced.stats["unique_fingerprints"]
        )
        assert naive.stats["states"] > reduced.stats["states"]

    def test_measure_reduction_reports_exact_ratios(self):
        r = measure_reduction(
            TINY,
            Budgets(max_states=10**6, max_depth=4, max_edges=10**6,
                    wall_s=30.0),
            seed=0,
            naive_edge_factor=50.0,
            naive_wall_s=30.0,
        )
        assert r["reduced_exhausted"]
        assert r["coverage_matched"]
        assert not r["reduction_lower_bound"]
        assert r["reduction_x"] > 1.0
        assert r["edges_x"] > 1.0

    def test_trace_json_roundtrip(self):
        t = Trace(
            seed=7,
            config=tmmc.GATE_CONFIG.describe(),
            transitions=[("t", 0), ("d", 1, ("v", 1, 0, 1, 0, "ab"))],
            rule="mc-agreement",
            message="x",
        )
        back = Trace.from_json(json.loads(json.dumps(t.to_json())))
        assert back.transitions == t.transitions
        assert back.config == t.config
        assert (back.seed, back.rule, back.message) == (7, t.rule, "x")


class TestGate:
    def test_gate_scenario_green_within_tier1_budget(self):
        """THE acceptance run: 4 validators / 2 heights / one
        equivocator, explored exhaustively-within-budget inside the
        gate — zero violations on HEAD, and the wall cost stays
        pinned under 15 s so the gate (and tier-1) can afford it."""
        report = tmmc.analyze()
        assert report.violations == []
        assert report.mc == []
        st = report.stats
        assert st["wall_s"] < 15.0, st
        assert st["states"] >= 100
        assert st["budgets"] == tmmc.GATE_BUDGETS.describe()
        assert st["config"] == tmmc.GATE_CONFIG.describe()
        # the gate ran WITH the adversary armed and the model saw it
        assert st["config"]["byz"], "gate scenario lost its adversary"

    def test_named_configs_resolve(self):
        for name in ("gate", "agreement-ab", "accountability-ab"):
            cfg, budgets, seed = tmmc.named_config(name)
            assert isinstance(cfg, MCConfig)
            assert isinstance(budgets, Budgets)
        with pytest.raises(KeyError):
            tmmc.named_config("nope")

    def test_baseline_ships_empty(self):
        with open(tmmc.MC_BASELINE_PATH) as f:
            data = json.load(f)
        assert data["entries"] == {}

    def test_cli_mc_section_green(self):
        """scripts/lint.py --mc is the tenth gate section: exit 0 on
        HEAD, a stats line carrying the exploration record."""
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "lint.py"),
             "--mc", "--stats"],
            capture_output=True, text=True, timeout=180, cwd=REPO,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "[mc]" in r.stdout
        assert "-- tmmc gate:" in r.stdout
        assert "stopped_by=" in r.stdout

    def test_cli_update_mode_refusal_matrix(self):
        """--mc combined with a golden-update mode must refuse (the
        update would silently disable the named gate while exiting 0)
        — same parity contract every other section obeys."""
        lint = os.path.join(REPO, "scripts", "lint.py")
        for mode in (
            "--schema-update", "--signatures-update", "--cost-update"
        ):
            r = subprocess.run(
                [sys.executable, lint, mode, "--mc"],
                capture_output=True, text=True, timeout=60, cwd=REPO,
            )
            assert r.returncode == 2, (mode, r.stdout, r.stderr)
            assert "--mc" in r.stderr, (mode, r.stderr)

    def test_suppression_comment_is_honored(self, tmp_path):
        """`# tmmc: mc-ok` on a checker def suppresses that rule's
        findings — proven against the real suppression scanner by
        faking a violation at a checker anchored under an annotation."""
        from tendermint_tpu.analysis.tmmc import gate as g
        from tendermint_tpu.analysis.tmmc.explorer import (
            ExploreResult,
            MCViolation,
        )

        trace = Trace(
            seed=0, config=tmmc.GATE_CONFIG.describe(), transitions=[],
            rule="mc-agreement", message="synthetic",
        )
        result = ExploreResult(
            violations=[
                MCViolation("mc-agreement", "synthetic", trace)
            ],
            stats={},
        )
        violations, suppressed = g._to_violations(result)
        # no annotation in invariants.py on HEAD: the finding surfaces
        assert suppressed == 0
        assert len(violations) == 1
        assert violations[0].rule == "mc-agreement"
        assert "fuzz_repro" in violations[0].message

    def test_rules_and_lint_registration(self):
        ids = [rid for rid, _ in tmmc.RULES]
        assert ids == [
            "mc-agreement",
            "mc-validity",
            "mc-accountability",
            "mc-stall",
        ]
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "lint.py"),
             "--list-rules"],
            capture_output=True, text=True, timeout=120,
        )
        assert r.returncode == 0
        for rid in ids:
            assert rid in r.stdout


# ---------------------------------------------------------------------------
# seeded A/B proofs


_AB_RUNNER = """
import json, sys
sys.path.insert(0, {copy!r})
from tendermint_tpu.analysis import tmmc
from tendermint_tpu.analysis.tmmc.explorer import (
    explore, minimize_trace, replay_trace,
)
cfg, budgets, seed = tmmc.named_config({name!r})
res = explore(cfg, budgets, seed=seed, stop_at_first=True)
out = {{
    "rules": [v.rule for v in res.violations],
    "states": res.stats["states"],
    "stopped_by": res.stats["stopped_by"],
}}
if res.violations:
    v = res.violations[0]
    small = minimize_trace(v.trace)
    net, found, complete = replay_trace(small)
    net.close(); net.loop.close()
    out.update({{
        "orig_depth": len(v.trace.transitions),
        "minimized_depth": len(small.transitions),
        "replay_complete": complete,
        "replay_rules": sorted({{r for r, _ in found}}),
        "witness": small.to_json(),
    }})
print(json.dumps(out))
"""


def _mutated_copy(tmp_path, rel_path, old, new):
    """Copy the package into tmp and apply one surgical mutation."""
    copy = tmp_path / "ab"
    copy.mkdir()
    shutil.copytree(
        os.path.join(REPO, "tendermint_tpu"),
        copy / "tendermint_tpu",
        ignore=shutil.ignore_patterns("__pycache__"),
    )
    target = copy / "tendermint_tpu" / rel_path
    src = target.read_text()
    assert src.count(old) == 1, f"mutation anchor drifted in {rel_path}"
    target.write_text(src.replace(old, new))
    return str(copy)


def _run_ab(copy, name):
    r = subprocess.run(
        [sys.executable, "-c", _AB_RUNNER.format(copy=copy, name=name)],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "PYTHONPATH": ""},
    )
    assert r.returncode == 0, r.stderr
    return json.loads(r.stdout)


class TestSeededAB:
    def test_weakened_quorum_turns_agreement_red(self, tmp_path):
        """A/B proof 1: replace the +2/3 prevote/precommit quorum with
        1/2 in a package COPY — the explorer finds two nodes
        committing different blocks at one height, and the minimized
        witness replays to the same mc-agreement violation."""
        copy = _mutated_copy(
            tmp_path,
            os.path.join("types", "vote_set.py"),
            "quorum = self.val_set.total_voting_power() * 2 // 3 + 1",
            "quorum = self.val_set.total_voting_power() // 2",
        )
        out = _run_ab(copy, "agreement-ab")
        assert "mc-agreement" in out["rules"], out
        assert out["minimized_depth"] <= out["orig_depth"]
        assert out["replay_complete"]
        assert "mc-agreement" in out["replay_rules"]
        # the witness is a bankable JSON artifact
        assert out["witness"]["rule"] == "mc-agreement"
        assert out["witness"]["transitions"]

    def test_agreement_scenario_green_on_head(self):
        cfg, budgets, seed = tmmc.named_config("agreement-ab")
        res = explore(cfg, budgets, seed=seed, stop_at_first=False)
        assert res.ok, [v.message for v in res.violations]
        assert res.stats["stopped_by"] == "exhausted"

    def test_dropped_evidence_turns_accountability_red(self, tmp_path):
        """A/B proof 2: make EvidencePool.update throw away the
        consensus buffer (detected double-signs never become
        DuplicateVoteEvidence) in a package COPY — the explorer finds
        a detection whose pool update formed nothing, and the
        minimized witness replays to the same mc-accountability
        violation."""
        copy = _mutated_copy(
            tmp_path,
            os.path.join("evidence", "pool.py"),
            "buffered, self._consensus_buffer = self._consensus_buffer, []",
            "buffered, self._consensus_buffer = [], []",
        )
        out = _run_ab(copy, "accountability-ab")
        assert "mc-accountability" in out["rules"], out
        assert out["minimized_depth"] <= out["orig_depth"]
        assert out["replay_complete"]
        assert "mc-accountability" in out["replay_rules"]

    def test_accountability_scenario_green_on_head(self):
        cfg, budgets, seed = tmmc.named_config("accountability-ab")
        res = explore(cfg, budgets, seed=seed, stop_at_first=False)
        assert res.ok, [v.message for v in res.violations]
        assert res.stats["stopped_by"] == "exhausted"


class TestFuzzRepro:
    def test_replay_banked_witness_dumps_timeline(self, tmp_path):
        """scripts/fuzz_repro.py round-trip: bank a witness trace (a
        benign prefix of the tiny scenario), replay it through the
        CLI, and get the per-node flight-recorder dump."""
        loop = asyncio.new_event_loop()
        net = ModelNet(TINY, loop)
        try:
            transitions = []
            for _ in range(6):
                enabled = net.transitions()
                if not enabled:
                    break
                deliveries = [t for t in enabled if t[0] == "d"]
                t = sorted(deliveries or enabled)[0]
                net.apply(t)
                transitions.append(t)
        finally:
            net.close()
            loop.close()
        trace = Trace(
            seed=0, config=TINY.describe(), transitions=transitions
        )
        tf = tmp_path / "witness.json"
        tf.write_text(json.dumps(trace.to_json()))
        out_json = tmp_path / "dump.json"
        r = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "fuzz_repro.py"),
             str(tf), "--json", str(out_json)],
            capture_output=True, text=True, timeout=180,
        )
        assert r.returncode == 0, (r.stdout, r.stderr)
        dump = json.loads(out_json.read_text())
        assert dump["complete"]
        assert dump["violations"] == []
        assert len(dump["nodes"]) == 2
        # the flight recorder saw the replay: per-node event streams
        assert any(nd["events"] for nd in dump["nodes"])

    def test_minimize_preserves_rule(self):
        """minimize_trace never returns a trace that fails to replay
        to the original rule (exercised on a synthetic violation via
        the stall checker on an artificial empty-transition state is
        overkill here — instead pin the API contract on a no-op
        minimization: a trace with no removable transition)."""
        t = Trace(
            seed=0, config=TINY.describe(), transitions=[], rule="",
        )
        assert minimize_trace(t).transitions == []
