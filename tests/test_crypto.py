import hashlib

import pytest

from tendermint_tpu.crypto import (
    Ed25519BatchVerifier,
    PrivKeyEd25519,
    PrivKeySecp256k1,
    PubKeyEd25519,
    batch,
    merkle,
    pubkey_from_proto,
    pubkey_to_proto,
    tmhash,
)
from tendermint_tpu.crypto import ed25519_math as em


def test_ed25519_sign_verify_roundtrip():
    sk = PrivKeyEd25519.generate()
    pk = sk.pub_key()
    msg = b"vote sign bytes"
    sig = sk.sign(msg)
    assert len(sig) == 64
    assert pk.verify_signature(msg, sig)
    assert not pk.verify_signature(msg + b"x", sig)
    assert not pk.verify_signature(msg, sig[:-1] + bytes([sig[-1] ^ 1]))
    assert len(pk.address()) == 20
    assert pk.address() == hashlib.sha256(pk.bytes()).digest()[:20]


def test_ed25519_rfc8032_vector():
    # RFC 8032 §7.1 TEST 2
    seed = bytes.fromhex(
        "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb"
    )
    sk = PrivKeyEd25519.from_seed(seed)
    assert sk.pub_key().bytes() == bytes.fromhex(
        "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c"
    )
    msg = bytes.fromhex("72")
    sig = sk.sign(msg)
    assert sig == bytes.fromhex(
        "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
        "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00"
    )
    # pure-python ZIP-215 oracle agrees
    assert em.zip215_verify(sk.pub_key().bytes(), msg, sig)


def test_zip215_oracle_matches_fast_path_on_random_sigs():
    for i in range(8):
        sk = PrivKeyEd25519.from_seed(hashlib.sha256(bytes([i])).digest())
        msg = f"msg-{i}".encode()
        sig = sk.sign(msg)
        assert em.zip215_verify(sk.pub_key().bytes(), msg, sig)
        bad = sig[:32] + (int.from_bytes(sig[32:], "little") ^ 1).to_bytes(32, "little")
        assert not em.zip215_verify(sk.pub_key().bytes(), msg, bad)
        assert sk.pub_key().verify_signature(msg, sig)


def test_zip215_rejects_high_s():
    sk = PrivKeyEd25519.generate()
    msg = b"m"
    sig = sk.sign(msg)
    s = int.from_bytes(sig[32:], "little")
    high_s = s + em.L
    bad = sig[:32] + high_s.to_bytes(32, "little")
    assert not sk.pub_key().verify_signature(msg, bad)
    assert not em.zip215_verify(sk.pub_key().bytes(), msg, bad)


def test_batch_verifier_bitmap():
    bv = Ed25519BatchVerifier()
    keys = [PrivKeyEd25519.generate() for _ in range(5)]
    msgs = [f"m{i}".encode() for i in range(5)]
    sigs = [k.sign(m) for k, m in zip(keys, msgs)]
    sigs[2] = keys[2].sign(b"other")  # corrupt one
    for k, m, s in zip(keys, msgs, sigs):
        bv.add(k.pub_key(), m, s)
    assert len(bv) == 5
    ok, bitmap = bv.verify()
    assert not ok
    assert bitmap == [True, True, False, True, True]
    assert len(bv) == 0  # verify() drains (one-shot contract)


def test_native_batch_equation_paths():
    """Batches >= _NATIVE_BATCH_MIN ride the native RLC batch equation:
    an all-valid batch returns all-True in one native call; any invalid
    signature falls back to per-signature verification with the exact
    bitmap (the reference's batch-failure behavior,
    crypto/ed25519/ed25519.go:202-237)."""
    from tendermint_tpu.crypto import ed25519 as e

    if e._native_batch_fn() is None:
        pytest.skip("no native toolchain")
    n = max(e._NATIVE_BATCH_MIN, 48)
    keys = [PrivKeyEd25519.from_seed(bytes([i + 1]) * 32) for i in range(8)]
    bv = e.Ed25519BatchVerifier()
    for i in range(n):
        k = keys[i % 8]
        # vary message length across SHA-512 block boundaries (real
        # vote sign-bytes exceed one block; the native sha512_3 must
        # straddle its 128-byte buffer correctly)
        m = b"nb-%d-" % i + b"x" * ((i * 37) % 600)
        bv.add(k.pub_key(), m, k.sign(m))
    ok, bits = bv.verify()
    assert ok and bits == [True] * n

    # one bad signature: exact per-index attribution
    bv = e.Ed25519BatchVerifier()
    for i in range(n):
        k = keys[i % 8]
        m = b"nb2-%d" % i
        sig = k.sign(m)
        if i == 17:
            s = (int.from_bytes(sig[32:], "little") + 1) % em.L
            sig = sig[:32] + s.to_bytes(32, "little")
        bv.add(k.pub_key(), m, sig)
    ok, bits = bv.verify()
    assert not ok
    assert [i for i, b in enumerate(bits) if not b] == [17]


def test_native_batch_zip215_differential():
    """The native batch equation agrees with the pure-Python ZIP-215
    oracle on edge encodings: small-order R, non-canonical y, high-s —
    packed into one batch whose expected bitmap the oracle defines."""
    from tendermint_tpu.crypto import ed25519 as e

    if e._native_batch_fn() is None:
        pytest.skip("no native toolchain")
    keys = [PrivKeyEd25519.from_seed(bytes([i + 31]) * 32) for i in range(4)]
    items = []
    expected = []
    n = max(e._NATIVE_BATCH_MIN, 40)
    for i in range(n):
        k = keys[i % 4]
        m = b"zdiff-%d" % i
        sig = k.sign(m)
        if i % 5 == 1:  # small-order R (identity encoding)
            sig = (1).to_bytes(32, "little") + sig[32:]
        elif i % 5 == 2:  # high-s (>= L): invalid under ZIP-215
            s = int.from_bytes(sig[32:], "little") + em.L
            if s < 2**256:
                sig = sig[:32] + s.to_bytes(32, "little")
        elif i % 5 == 3:  # flipped msg binding
            m = b"zdiff-other-%d" % i
        items.append((k.pub_key(), m, sig))
        expected.append(em.zip215_verify(k.pub_key().bytes(), m, sig))
    bv = e.Ed25519BatchVerifier()
    for pk, m, sig in items:
        bv.add(pk, m, sig)
    ok, bits = bv.verify()
    assert bits == expected
    assert ok == all(expected)


def test_batch_dispatch():
    sk = PrivKeyEd25519.generate()
    assert batch.supports_batch_verifier(sk.pub_key())
    bv = batch.create_batch_verifier(sk.pub_key(), size_hint=4)
    assert isinstance(bv, Ed25519BatchVerifier)
    # secp256k1 batches first-class through the native backend now
    # (the PR-1 wheel-gated shim raised here)
    sk2 = PrivKeySecp256k1.generate()
    assert batch.supports_batch_verifier(sk2.pub_key())
    bv2 = batch.create_batch_verifier(sk2.pub_key(), size_hint=4)
    ok_empty, bits_empty = bv2.verify()
    assert (ok_empty, bits_empty) == (False, [])


def test_secp256k1_roundtrip():
    sk = PrivKeySecp256k1.generate()
    pk = sk.pub_key()
    assert len(pk.bytes()) == 33
    assert len(pk.address()) == 20
    msg = b"tx bytes"
    sig = sk.sign(msg)
    assert len(sig) == 64
    assert pk.verify_signature(msg, sig)
    assert not pk.verify_signature(b"other", sig)
    # high-s rejected
    s = int.from_bytes(sig[32:], "big")
    order = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
    high = sig[:32] + (order - s).to_bytes(32, "big")
    assert not pk.verify_signature(msg, high)


def test_pubkey_proto_roundtrip():
    for sk in (PrivKeyEd25519.generate(), PrivKeySecp256k1.generate()):
        pk = sk.pub_key()
        enc = pubkey_to_proto(pk)
        back = pubkey_from_proto(enc)
        assert back == pk


def test_tmhash():
    assert tmhash.sum256(b"") == hashlib.sha256(b"").digest()
    assert tmhash.sum_truncated(b"abc") == hashlib.sha256(b"abc").digest()[:20]


def test_merkle_known_shapes():
    # empty
    assert merkle.hash_from_byte_slices([]) == hashlib.sha256(b"").digest()
    # single leaf: root == leafHash(item)
    item = b"hello"
    assert merkle.hash_from_byte_slices([item]) == hashlib.sha256(
        b"\x00" + item
    ).digest()
    # two leaves
    l0 = hashlib.sha256(b"\x00a").digest()
    l1 = hashlib.sha256(b"\x00b").digest()
    assert merkle.hash_from_byte_slices([b"a", b"b"]) == hashlib.sha256(
        b"\x01" + l0 + l1
    ).digest()
    # three leaves: split point 2 -> inner(inner(l0,l1), l2)
    l2 = hashlib.sha256(b"\x00c").digest()
    left = hashlib.sha256(b"\x01" + l0 + l1).digest()
    assert merkle.hash_from_byte_slices([b"a", b"b", b"c"]) == hashlib.sha256(
        b"\x01" + left + l2
    ).digest()


@pytest.mark.parametrize("n", [1, 2, 3, 5, 8, 13, 100])
def test_merkle_proofs(n):
    items = [f"item-{i}".encode() for i in range(n)]
    root, proofs = merkle.proofs_from_byte_slices(items)
    assert root == merkle.hash_from_byte_slices(items)
    for i, proof in enumerate(proofs):
        proof.verify(root, items[i])
        assert proof.total == n and proof.index == i
        with pytest.raises(ValueError):
            proof.verify(root, b"wrong leaf")
    # tampered root
    with pytest.raises(ValueError):
        proofs[0].verify(b"\x00" * 32, items[0])


def test_merkle_proof_proto_roundtrip():
    items = [b"a", b"b", b"c"]
    root, proofs = merkle.proofs_from_byte_slices(items)
    p = proofs[1]
    again = merkle.Proof.from_proto_bytes(p.to_proto_bytes())
    assert again.total == p.total and again.index == p.index
    assert again.leaf_hash == p.leaf_hash and again.aunts == p.aunts
    again.verify(root, items[1])


def test_streaming_chunked_dispatch(monkeypatch):
    """The TPU batch seam's chunked streaming dispatch (overlaps host
    assembly with device compute on real accelerators) must preserve
    the bitmap contract exactly: add-order alignment across chunk
    boundaries, invalids localized, __len__ counting in-flight sigs."""
    from tendermint_tpu.crypto import tpu_verifier as T
    from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519

    monkeypatch.setattr(T, "_STREAMING", True)
    monkeypatch.setattr(T._TpuBatchVerifier, "STREAM_CHUNK", 4)
    v = T.TpuEd25519BatchVerifier()
    n = 11
    for i in range(n):
        priv = PrivKeyEd25519.from_seed(bytes([i + 7]) * 32)
        msg = b"stream-%d" % i
        sig = priv.sign(msg)
        if i in (2, 6, 10):  # one bad index in every chunk + remainder
            sig = sig[:3] + bytes([sig[3] ^ 1]) + sig[4:]
        v.add(priv.pub_key(), msg, sig)
        assert len(v) == i + 1  # in-flight chunks still counted
    all_ok, bits = v.verify()
    assert not all_ok
    assert len(bits) == n
    assert [i for i, ok in enumerate(bits) if not ok] == [2, 6, 10]
    # a second verify on the drained verifier reports empty
    assert v.verify() == (False, [])


def test_batch_verifier_drains_on_every_backend(monkeypatch):
    """verify() is one-shot on the non-streaming path too — backends
    must not diverge on a second verify() call (review finding)."""
    from tendermint_tpu.crypto import tpu_verifier as T
    from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519

    monkeypatch.setattr(T, "_STREAMING", False)
    v = T.TpuEd25519BatchVerifier()
    priv = PrivKeyEd25519.from_seed(b"\x09" * 32)
    v.add(priv.pub_key(), b"drain", priv.sign(b"drain"))
    assert v.verify() == (True, [True])
    assert v.verify() == (False, [])
    assert len(v) == 0
    # the CPU verifiers behind the same crypto.batch seam honor the
    # identical one-shot contract (semantics must not depend on which
    # factory wins — review finding)
    from tendermint_tpu.crypto.ed25519 import Ed25519BatchVerifier
    from tendermint_tpu.crypto.sr25519 import (
        PrivKeySr25519,
        Sr25519BatchVerifier,
    )

    cv = Ed25519BatchVerifier()
    cv.add(priv.pub_key(), b"drain", priv.sign(b"drain"))
    assert cv.verify() == (True, [True])
    assert cv.verify() == (False, [])
    assert len(cv) == 0
    sp = PrivKeySr25519.from_seed(b"\x0a" * 32)
    sv = Sr25519BatchVerifier()
    sv.add(sp.pub_key(), b"drain", sp.sign(b"drain"))
    assert sv.verify() == (True, [True])
    assert sv.verify() == (False, [])
    assert len(sv) == 0


def test_native_scalar_and_sha512_building_blocks():
    """Differential checks of the native host-prep building blocks
    against Python: sc_mod_l (Barrett reduction mod L) over random and
    boundary 512-bit inputs, and the C SHA-512 against hashlib across
    every padding boundary. These are the pieces tm_ed25519_verify_full
    composes for consensus signature verification."""
    import ctypes
    import random

    from tendermint_tpu import native

    lib = native.load("ed25519_batch")
    if lib is None:
        pytest.skip("no native toolchain")
    lib.tm_sc_mod_l_test.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.tm_sc_mod_l_test.restype = None
    lib.tm_sha512_test.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p
    ]
    lib.tm_sha512_test.restype = None

    L = em.L
    rng = random.Random(1234)
    cases = [
        0, 1, L - 1, L, L + 1, 2 * L, 3 * L - 1, 2**252, 2**256 - 1,
        2**512 - 1, (L << 260) + 12345,
    ]
    cases += [rng.getrandbits(512) for _ in range(500)]
    out = ctypes.create_string_buffer(32)
    for x in cases:
        lib.tm_sc_mod_l_test((x % 2**512).to_bytes(64, "little"), out)
        assert int.from_bytes(out.raw, "little") == (x % 2**512) % L

    out64 = ctypes.create_string_buffer(64)
    for ln in list(range(0, 130)) + [111, 112, 113, 127, 128, 129,
                                     239, 240, 241, 255, 256, 1000]:
        data = bytes(rng.randrange(256) for _ in range(ln))
        lib.tm_sha512_test(data, ln, out64)
        assert out64.raw == hashlib.sha512(data).digest(), ln


def test_proof_operators_chain():
    """Multi-op proof chaining (reference: crypto/merkle/proof_op.go:
    60-90, proof_value.go, proof_key_path.go): a value proven into a
    substore root, the substore root proven into the app root, chained
    through a url keypath consumed last-component-first."""
    from tendermint_tpu.crypto.merkle import (
        Proof,
        ProofOperators,
        ValueOp,
        proofs_from_byte_slices,
    )
    from tendermint_tpu.encoding.proto import ProtoWriter

    def kv_leaf(key: bytes, value: bytes) -> bytes:
        w = ProtoWriter()
        w.bytes(1, key)
        w.bytes(2, hashlib.sha256(value).digest())
        return w.finish()

    # level 1: the substore, three keys
    value = b"the-stored-value"
    sub_items = [
        kv_leaf(b"alpha", b"a-value"),
        kv_leaf(b"key", value),
        kv_leaf(b"zeta", b"z-value"),
    ]
    sub_root, sub_proofs = proofs_from_byte_slices(sub_items)
    op1 = ValueOp(b"key", sub_proofs[1])

    # level 2: the app root over store roots (substore root is the
    # "value" the second op hashes)
    app_items = [
        kv_leaf(b"other", b"whatever"),
        kv_leaf(b"store", sub_root),
    ]
    app_root, app_proofs = proofs_from_byte_slices(app_items)
    op2 = ValueOp(b"store", app_proofs[1])

    ops = ProofOperators([op1, op2])
    ops.verify_value(app_root, "/store/key", value)
    # hex-escaped path component resolves to the same key
    ops2 = ProofOperators([op1, op2])
    ops2.verify_value(app_root, "/store/x:" + b"key".hex(), value)

    # wrong value fails
    with pytest.raises(ValueError):
        ProofOperators([op1, op2]).verify_value(
            app_root, "/store/key", b"tampered"
        )
    # wrong root fails
    with pytest.raises(ValueError):
        ProofOperators([op1, op2]).verify_value(
            b"\x00" * 32, "/store/key", value
        )
    # keypath order matters (outermost first in the path)
    with pytest.raises(ValueError):
        ProofOperators([op1, op2]).verify_value(
            app_root, "/key/store", value
        )
    # unconsumed path components are rejected
    with pytest.raises(ValueError):
        ProofOperators([op1, op2]).verify_value(
            app_root, "/extra/store/key", value
        )


def test_decoded_point_cache():
    """The native decoded-point cache (reference analog:
    crypto/ed25519/ed25519.go:50-56 caches 4096 expanded keys):
    re-verifying the same keys hits the cache, a cached key still
    rejects a bad signature (only the decode is cached, never the
    equation), and the ed25519/ristretto decoders never alias even
    for byte-identical encodings."""
    from tendermint_tpu import native
    from tendermint_tpu.crypto import ed25519 as e
    from tendermint_tpu.crypto import sr25519 as sr

    if e._native_batch_fn() is None:
        pytest.skip("no native toolchain")
    import os

    if os.environ.get("TM_TPU_NO_PKCACHE"):
        pytest.skip("cache disabled via TM_TPU_NO_PKCACHE")
    lib = native.ed25519_batch_lib()
    lib.tm_pk_cache_clear()

    keys = [
        PrivKeyEd25519.from_seed(bytes([i + 1, 0xC4]) + b"\x77" * 30)
        for i in range(24)
    ]
    triples = [
        (k.pub_key(), b"pkc-%d" % i, k.sign(b"pkc-%d" % i))
        for i, k in enumerate(keys)
    ]

    def run(expect_ok=True, corrupt_at=None):
        bv = e.Ed25519BatchVerifier()
        for i, (pk, m, s) in enumerate(triples):
            if i == corrupt_at:
                s = s[:32] + bytes(
                    ((int.from_bytes(s[32:], "little") + 1) % em.L)
                    .to_bytes(32, "little")
                )
            bv.add(pk, m, s)
        ok, bits = bv.verify()
        assert ok is expect_ok
        return bits

    run()
    s0 = native.pk_cache_stats()
    assert s0["inserts"] >= 24 and s0["hits"] == 0
    run()
    s1 = native.pk_cache_stats()
    assert s1["hits"] >= 24  # every A point served from cache
    assert s1["inserts"] == s0["inserts"]

    # cached keys must not weaken verification: same keys, one bad sig
    bits = run(expect_ok=False, corrupt_at=7)
    assert [i for i, b in enumerate(bits) if not b] == [7]

    # cross-curve isolation: verifying sr25519 after ed25519 populated
    # the cache must decode fresh ristretto points (curve-tagged keys),
    # and both curves stay correct back-to-back
    sks = [
        sr.PrivKeySr25519.from_seed(bytes([i + 1, 0xD5]) + b"\x66" * 30)
        for i in range(8)
    ]
    bv = sr.Sr25519BatchVerifier()
    for i, k in enumerate(sks):
        m = b"pkc-sr-%d" % i
        bv.add(k.pub_key(), m, k.sign(m))
    ok, _ = bv.verify()
    assert ok
    run()  # ed25519 entries still valid after sr25519 traffic


def test_group_affinity_policy():
    """The merged-window affinity policy (light sequential windows,
    statesync backfill):

    - uninstalled process + native batch kernel -> 32 (the exact-size
      native RLC equation gets cheaper per sig with batch size)
    - uninstalled process, no native -> 1 (OpenSSL-sequential gains
      nothing from merging)
    - device factory installed but JAX backend is NOT an accelerator
      -> 1 (merged batches would route to the padded JAX kernel,
      measured 5x slower — the regression guard)
    """
    from tendermint_tpu.crypto import batch as B
    from tendermint_tpu.crypto import tpu_verifier

    prev = B.group_affinity_state()
    try:
        # module default, native present (it is in CI: built on demand)
        B.set_group_affinity_fn(B.native_cpu_affinity)
        from tendermint_tpu.crypto.ed25519 import _native_batch_fn

        expected = 32 if _native_batch_fn() is not None else 1
        assert B.group_affinity() == expected

        # no native -> 1
        import tendermint_tpu.crypto.ed25519 as ed

        B.restore_group_affinity((None, None, False))
        saved = ed._native_batch_fn
        ed._native_batch_fn = lambda: None
        try:
            B.set_group_affinity_fn(B.native_cpu_affinity)
            assert B.group_affinity() == 1
        finally:
            ed._native_batch_fn = saved

        # installed on a non-accelerator backend -> 1 (tests run with
        # JAX_PLATFORMS=cpu, so install()'s deferred fn answers 1)
        B.restore_group_affinity((None, None, False))
        try:
            tpu_verifier.install(min_batch=2)
            assert B.group_affinity() == 1
        finally:
            tpu_verifier.uninstall()
    finally:
        B.restore_group_affinity(prev)


def test_ed25519_rfc8032_vector():
    """RFC 8032 §7.1 TEST 3 pins keygen + signing bit-for-bit, whether
    the OpenSSL wheel or the gated pure-Python path produced them."""
    from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519

    seed = bytes.fromhex(
        "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7"
    )
    pub = bytes.fromhex(
        "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025"
    )
    sig = bytes.fromhex(
        "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
        "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a"
    )
    msg = bytes.fromhex("af82")
    priv = PrivKeyEd25519.from_seed(seed)
    assert priv.pub_key().bytes() == pub
    assert priv.sign(msg) == sig
    assert priv.pub_key().verify_signature(msg, sig)
    assert not priv.pub_key().verify_signature(msg + b"x", sig)


def test_pure_chacha20poly1305_rfc8439_vector():
    """The gated pure-Python AEAD (used when the cryptography wheel is
    absent) against RFC 8439 §2.8.2 — the full known-answer vector."""
    from tendermint_tpu.crypto.symmetric import PureChaCha20Poly1305

    key = bytes(range(0x80, 0xA0))
    nonce = bytes.fromhex("070000004041424344454647")
    aad = bytes.fromhex("50515253c0c1c2c3c4c5c6c7")
    pt = (
        b"Ladies and Gentlemen of the class of '99: If I could offer you "
        b"only one tip for the future, sunscreen would be it."
    )
    want_ct = bytes.fromhex(
        "d31a8d34648e60db7b86afbc53ef7ec2"
        "a4aded51296e08fea9e2b5a736ee62d6"
        "3dbea45e8ca9671282fafb69da92728b"
        "1a71de0a9e060b2905d6a5b67ecd3b36"
        "92ddbd7f2d778b8c9803aee328091b58"
        "fab324e4fad675945585808b4831d7bc"
        "3ff4def08e4b7a9de576d26586cec64b"
        "6116"
    )
    want_tag = bytes.fromhex("1ae10b594f09e26a7e902ecbd0600691")
    aead = PureChaCha20Poly1305(key)
    out = aead.encrypt(nonce, pt, aad)
    assert out == want_ct + want_tag
    assert aead.decrypt(nonce, out, aad) == pt
    tampered = out[:-1] + bytes([out[-1] ^ 1])
    with pytest.raises(ValueError):
        aead.decrypt(nonce, tampered, aad)


def test_x25519_rfc7748_vector():
    """The gated pure-Python X25519 ladder against RFC 7748 §5.2
    vector 1 and the §6.1 Diffie-Hellman vector."""
    from tendermint_tpu.p2p.conn import _x25519_scalarmult

    k = bytes.fromhex(
        "a546e36bf0527c9d3b16154b82465edd62144c0ac1fc5a18506a2244ba449ac4"
    )
    u = bytes.fromhex(
        "e6db6867583030db3594c1a424b15f7c726624ec26b3353b10a903a6d0ab1c4c"
    )
    want = bytes.fromhex(
        "c3da55379de9c6908e94ea4df28d084f32eccf03491c71f754b4075577a28552"
    )
    assert _x25519_scalarmult(k, u) == want
    alice_priv = bytes.fromhex(
        "77076d0a7318a57d3c16c17251b26645df4c2f87ebc0992ab177fba51db92c2a"
    )
    bob_priv = bytes.fromhex(
        "5dab087e624a8a4b79e17f8b83800ee66f3bb1292618b6fd1c2f8b27ff88e0eb"
    )
    base = (9).to_bytes(32, "little")
    alice_pub = _x25519_scalarmult(alice_priv, base)
    bob_pub = _x25519_scalarmult(bob_priv, base)
    shared = bytes.fromhex(
        "4a5d9d5ba4ce2de1728e3bf480350f25e07e21c947d19e3376f09b3c1e161742"
    )
    assert _x25519_scalarmult(alice_priv, bob_pub) == shared
    assert _x25519_scalarmult(bob_priv, alice_pub) == shared


def test_group_affinity_deferred_fn_never_lost_to_racing_reader():
    """Regression (advisor round 5 + review): a reader interleaving
    with set_group_affinity_fn() must never permanently cache the
    fallback affinity of 1 — the locked snapshot plus the fn identity
    re-check inside the lock retries until it resolves the installed
    fn."""
    import threading

    from tendermint_tpu.crypto import batch

    state0 = batch.group_affinity_state()
    try:
        for _ in range(50):
            batch.restore_group_affinity((None, None, False))
            go = threading.Event()

            def read():
                go.wait(1.0)
                batch.group_affinity()

            readers = [
                threading.Thread(target=read, daemon=True) for _ in range(4)
            ]
            for t in readers:
                t.start()
            go.set()
            batch.set_group_affinity_fn(lambda: 8)
            for t in readers:
                t.join(5.0)
            # whatever the interleaving, the installed fn must win for
            # every later caller (a reader that cached 1 pre-install
            # would have been invalidated by set_group_affinity_fn)
            assert batch.group_affinity() == 8
    finally:
        batch.restore_group_affinity(state0)


def test_group_affinity_fn_swapped_mid_compute_retries():
    """The fn identity check: a compute based on a stale fn must not
    publish over a newer install."""
    from tendermint_tpu.crypto import batch

    state0 = batch.group_affinity_state()
    try:
        calls = []

        def slow_fn():
            calls.append("old")
            # a newer install lands while the old fn is mid-compute
            batch.set_group_affinity_fn(lambda: 32)
            return 2

        batch.restore_group_affinity((None, None, False))
        batch.set_group_affinity_fn(slow_fn)
        assert batch.group_affinity() == 32
        assert calls == ["old"]
    finally:
        batch.restore_group_affinity(state0)
