"""SecretConnection known-answer vectors and independent cross-checks.

Pins the framework-local wire format (reference model:
internal/p2p/conn/secret_connection.go + its testdata vectors) so an
accidental change to key derivation, nonce layout, or frame format
fails loudly, and cross-checks the HKDF step against an independent
HMAC-SHA256 implementation built only on hashlib (RFC 5869), not the
`cryptography` package the production code uses.
"""

import asyncio
import hashlib
import hmac
import struct

from tendermint_tpu.p2p.conn import (
    _HKDF_INFO,
    SecretConnection,
    _auth_sig_bytes,
    _derive,
    _parse_auth_sig,
)


def _hkdf_rfc5869(ikm: bytes, info: bytes, length: int) -> bytes:
    """Independent HKDF-SHA256 (extract with zero salt + expand)."""
    prk = hmac.new(b"\x00" * 32, ikm, hashlib.sha256).digest()
    okm = b""
    t = b""
    i = 1
    while len(okm) < length:
        t = hmac.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
        okm += t
        i += 1
    return okm[:length]


SHARED = bytes(range(32))
EPH_A = b"\x01" * 32
EPH_B = b"\x02" * 32


def test_derive_matches_independent_hkdf():
    okm = _hkdf_rfc5869(SHARED + EPH_A + EPH_B, _HKDF_INFO, 96)
    send_a, recv_a, chal_a = _derive(SHARED, EPH_A, EPH_B)
    assert (send_a, recv_a, chal_a) == (okm[:32], okm[32:64], okm[64:])


def test_derive_symmetry_and_role_assignment():
    send_a, recv_a, chal_a = _derive(SHARED, EPH_A, EPH_B)
    send_b, recv_b, chal_b = _derive(SHARED, EPH_B, EPH_A)
    assert chal_a == chal_b
    assert (send_a, recv_a) == (recv_b, send_b)
    assert send_a != recv_a


def test_derive_known_answer():
    """Locks the byte layout with a hard-coded vector: any change to
    the HKDF inputs, the info string, or the key-ordering rule changes
    this digest (and silently forks the wire protocol)."""
    send, recv, chal = _derive(SHARED, EPH_A, EPH_B)
    assert hashlib.sha256(send + recv + chal).hexdigest() == (
        "a2cbb19ae7aed2e3ef33aae32920566bb5d32829c113c432f1bda219abd0fd7b"
    )


def test_nonce_layout():
    conn = SecretConnection.__new__(SecretConnection)
    assert conn._nonce(0) == b"\x00" * 12
    assert conn._nonce(1) == struct.pack("<Q", 1) + b"\x00" * 4
    assert conn._nonce(2**40) == struct.pack("<Q", 2**40) + b"\x00" * 4


def test_auth_sig_roundtrip_and_layout():
    from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519

    priv = PrivKeyEd25519.from_seed(b"\x07" * 32)
    sig = priv.sign(b"challenge")
    data = _auth_sig_bytes(priv.pub_key(), sig)
    pub, parsed_sig = _parse_auth_sig(data)
    assert pub.bytes() == priv.pub_key().bytes()
    assert parsed_sig == sig
    # proto layout: field 1 key type (string), field 2 pubkey, field 3 sig
    assert data[0] == (1 << 3) | 2  # tag 1, wire type 2
    ktype = priv.pub_key().type().encode()
    assert data[2 : 2 + len(ktype)] == ktype


def test_full_handshake_framed_traffic_and_mutual_auth():
    """A loopback handshake: both sides authenticate, NodeInfo-style
    payloads flow through the AEAD frames, and a flipped ciphertext bit
    kills the connection (transcript binding of post-handshake data)."""
    from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519

    async def go():
        a_priv = PrivKeyEd25519.from_seed(b"\x0a" * 32)
        b_priv = PrivKeyEd25519.from_seed(b"\x0b" * 32)
        result = {}

        async def server(reader, writer):
            try:
                sc = await SecretConnection.handshake(reader, writer, b_priv)
                result["server_peer"] = sc.remote_pubkey.bytes()
                msg = await sc.read_frame()
                await sc.write_frame(b"ack:" + msg)
                # second read receives the tampered frame below
                await sc.read_frame()
                result["server_err"] = None  # tamper NOT detected
            except Exception as e:
                result["server_err"] = repr(e)
            finally:
                writer.close()

        srv = await asyncio.start_server(server, "127.0.0.1", 0)
        port = srv.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        sc = await SecretConnection.handshake(reader, writer, a_priv)
        assert sc.remote_pubkey.bytes() == b_priv.pub_key().bytes()
        await sc.write_frame(b"node-info-bytes")
        assert await sc.read_frame() == b"ack:node-info-bytes"
        assert result.get("server_peer") == a_priv.pub_key().bytes()

        # tamper: flip one ciphertext bit on the wire — AEAD must reject
        ct = sc._send.encrypt(
            sc._nonce(sc._send_nonce), b"tampered-payload", None
        )
        sc._send_nonce += 1
        bad = bytes([ct[0] ^ 1]) + ct[1:]
        writer.write(struct.pack(">I", len(bad)) + bad)
        await writer.drain()
        await asyncio.sleep(0.2)
        assert result.get("server_err") is not None, (
            "server accepted tampered frame"
        )
        sc.close()
        srv.close()
        await srv.wait_closed()

    asyncio.run(go())
