"""Byzantine adversary plane tests (ISSUE 18).

Tier-1: the TM_TPU_BYZ rule grammar (parse, seed reproducibility, the
raise-once env latch — the crypto/faults.py contract, mirrored), the
zero-overhead kill switch (a disarmed localnet installs no harness and
consults no rule), and one seconds-scale end-to-end equivocation arc
on a live 4-node localnet proving the full evidence lifecycle:
harness-crafted duplicate vote → vote_set conflict → evidence pool →
gossip → committed DuplicateVoteEvidence naming the victim. The full
shipped catalog (conflicting proposals, amnesia, withholding, the
light-client fork control, the double-sign guard) is the bench
byz_smoke row (BENCH_BYZ.json).
"""

import asyncio

import pytest

from tendermint_tpu.consensus import byzantine
from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.types.canonical import PRECOMMIT_TYPE, PREVOTE_TYPE
from tendermint_tpu.types.evidence import DuplicateVoteEvidence


def run(coro, timeout=240.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


@pytest.fixture(autouse=True)
def _clean_plane(monkeypatch):
    """Every test starts and ends disarmed, with the env latch
    re-armed so a TM_TPU_BYZ leaked by another test cannot bleed in."""
    monkeypatch.delenv("TM_TPU_BYZ", raising=False)
    byzantine.reset()
    yield
    byzantine.reset()


# -- the rule grammar --------------------------------------------------


def test_env_spec_parses_and_arms(monkeypatch):
    monkeypatch.setenv(
        "TM_TPU_BYZ",
        "equivocate:h=4..7:seed=9:step=precommit;"
        "withhold:h=5:p=0.5:times=2:victim=load2",
    )
    byzantine.load_env()
    assert byzantine.armed()
    rules = {r.behavior: r for r in byzantine.rules()}
    eq = rules["equivocate"]
    assert (eq.h_lo, eq.h_hi, eq.seed, eq.step) == (4, 7, 9, "precommit")
    assert eq.victim == "load1"  # the default victim
    wh = rules["withhold"]
    # h=N pins a single height; victim/p/times pass through
    assert (wh.h_lo, wh.h_hi) == (5, 5)
    assert (wh.p, wh.times, wh.victim) == (0.5, 2, "load2")
    monkeypatch.setenv("TM_TPU_BYZ", "")
    byzantine.load_env()
    assert not byzantine.armed()


def test_malformed_env_spec_raises_once_then_disarmed(monkeypatch):
    """A bad TM_TPU_BYZ surfaces ONCE; the latch rises even on parse
    failure, and all-or-nothing parsing arms none of a spec that dies
    mid-list (the crypto/faults.py load_env contract)."""
    monkeypatch.setenv(
        "TM_TPU_BYZ", "equivocate:h=4..6;teleport:h=5"
    )
    monkeypatch.setattr(byzantine, "_ENV_LOADED", False)
    with pytest.raises(ValueError):
        byzantine.armed()
    assert not byzantine.armed()  # latched: no re-raise, disarmed
    assert byzantine.rules() == []
    # a corrected spec re-arms via the explicit reload path
    monkeypatch.setenv("TM_TPU_BYZ", "equivocate:h=4..6")
    byzantine.load_env()
    assert byzantine.armed()


def test_bad_options_raise():
    with pytest.raises(ValueError):
        byzantine._parse_rule("equivocate:warp=9")
    with pytest.raises(ValueError):
        byzantine._parse_rule("equivocate:h")
    with pytest.raises(ValueError):
        byzantine.ByzRule("teleport")
    with pytest.raises(ValueError):
        byzantine.ByzRule("equivocate", step="commit")


def test_rules_are_seed_reproducible():
    """Whether consult k misbehaves is a pure function of (seed, k):
    the plane's reproducibility contract (module doc)."""

    def pattern(seed):
        fired = []
        with byzantine.inject(
            "equivocate", h_lo=1, p=0.5, seed=seed
        ) as rule:
            for i in range(50):
                if (
                    byzantine._plan(
                        "equivocate", 5, "load1", PREVOTE_TYPE
                    )
                    is not None
                ):
                    fired.append(i)
            assert rule.fired == len(fired)
        return fired

    a, b, c = pattern(7), pattern(7), pattern(8)
    assert a == b
    assert a != c  # different seed, different schedule
    assert a  # p=0.5 over 50 consults fires at least once


def test_window_victim_step_and_times_filters():
    with byzantine.inject(
        "equivocate", h_lo=4, h_hi=6, step="precommit", times=1
    ):
        # outside the height window / wrong victim / wrong step: no
        assert byzantine._plan("equivocate", 3, "load1") is None
        assert byzantine._plan("equivocate", 7, "load1") is None
        assert byzantine._plan("equivocate", 5, "load0") is None
        assert (
            byzantine._plan("equivocate", 5, "load1", PREVOTE_TYPE)
            is None
        )
        # matching consult fires; the times budget then exhausts
        assert (
            byzantine._plan("equivocate", 5, "load1", PRECOMMIT_TYPE)
            is not None
        )
        assert (
            byzantine._plan("equivocate", 5, "load1", PRECOMMIT_TYPE)
            is None
        )
    assert not byzantine.armed()  # scope exited: disarmed


# -- the kill switch ---------------------------------------------------


def test_disarmed_localnet_never_consults(tmp_path):
    """The zero-overhead contract: with TM_TPU_BYZ unset no node
    installs a harness and no hook consults the rule list — the
    byzantine plane costs a disarmed production net exactly nothing
    beyond one armed() check at assembly."""
    from tendermint_tpu.loadgen import start_localnet

    assert not byzantine.armed()

    async def go():
        ln = await start_localnet(2, str(tmp_path / "calm"), seed=31)
        try:
            await ln.wait_for_height(3, timeout=60.0)
        finally:
            await ln.stop()

    run(go())
    assert byzantine.consults() == 0
    assert byzantine.harnesses() == []


# -- the end-to-end evidence lifecycle ---------------------------------


def test_live_equivocation_commits_evidence(tmp_path):
    """One end-to-end equivocation arc in tier-1 (the full catalog is
    the bench byz_smoke row): the env-armed plane makes load1 sign
    duplicate prevotes at heights 4-5, and every honest node must
    commit DuplicateVoteEvidence naming the victim — detection,
    pooling, gossip, and block inclusion all live."""
    import os

    from tendermint_tpu.loadgen import start_localnet

    seed = 41
    os.environ["TM_TPU_BYZ"] = f"equivocate:h=4..5:seed={seed}"
    try:
        byzantine.load_env()
        assert byzantine.armed()
        victim_priv = PrivKeyEd25519.from_seed(
            seed.to_bytes(8, "big") + bytes([1]) * 24
        )
        victim_addr = victim_priv.pub_key().address()

        async def go():
            ln = await start_localnet(
                4, str(tmp_path / "byznet"), seed=seed
            )
            try:
                # clear the misbehavior window plus slack for the
                # evidence to gossip and land in a committed block
                await ln.wait_for_height(7, timeout=90.0)
                deadline = asyncio.get_event_loop().time() + 30.0
                found = []
                while asyncio.get_event_loop().time() < deadline:
                    found = _victim_evidence(
                        ln.nodes[0].block_store, victim_addr
                    )
                    if {ev.height() for ev in found} >= {4, 5}:
                        break
                    await asyncio.sleep(0.2)
                # the harness actually misbehaved, on schedule
                fired = [
                    f
                    for h in byzantine.harnesses()
                    for f in h.fired
                ]
                assert fired, "harness never fired"
                assert {f[1] for f in fired} == {4, 5}
                assert {ev.height() for ev in found} >= {4, 5}, found
                for ev in found:
                    # conflicting votes, same HRS+validator, both
                    # verifiable against the victim's key
                    a, b = ev.vote_a, ev.vote_b
                    assert a.block_id.key() != b.block_id.key()
                    assert a.validator_address == victim_addr
                    assert b.validator_address == victim_addr
                    assert (a.height, a.round, a.type) == (
                        b.height,
                        b.round,
                        b.type,
                    )
                # every OTHER node committed the same evidence (the
                # stores hold identical blocks — gossip + consensus
                # carried accountability fleet-wide)
                for n in ln.nodes[1:]:
                    other = _victim_evidence(
                        n.block_store, victim_addr
                    )
                    assert {e.hash() for e in other} >= {
                        e.hash() for e in found
                    }
            finally:
                await ln.stop()

        run(go())
        assert byzantine.consults() > 0
    finally:
        os.environ.pop("TM_TPU_BYZ", None)


def _victim_evidence(store, victim_addr):
    out = []
    for h in range(1, store.height() + 1):
        block = store.load_block(h)
        if block is None:
            continue
        for ev in block.evidence:
            if (
                isinstance(ev, DuplicateVoteEvidence)
                and ev.vote_a.validator_address == victim_addr
            ):
                out.append(ev)
    return out
