"""Device sr25519 kernel tests: ristretto decode differentials (RFC
9496), full verify differentials against the host schnorrkel oracle
(crypto/sr25519.py), and the device seam through create_batch_verifier
and VerifyCommit mixed sets (reference model: crypto/sr25519/batch.go,
crypto/batch/batch.go:11-33)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tendermint_tpu.crypto import batch as crypto_batch
from tendermint_tpu.crypto import ristretto as rst
from tendermint_tpu.crypto import tpu_verifier
from tendermint_tpu.crypto.sr25519 import (
    PrivKeySr25519,
    PubKeySr25519,
    Sr25519BatchVerifier,
)
from tendermint_tpu.ops import field25519 as F
from tendermint_tpu.ops import sr25519_kernel as SK


def _decode_rows(encodings):
    rows = (
        np.frombuffer(b"".join(encodings), dtype=np.uint8)
        .reshape(-1, 32)
        .T.astype(np.int32)
    )
    pt, ok = jax.jit(SK.ristretto_decode_dev)(jnp.asarray(rows))
    return np.asarray(pt), np.asarray(ok)


def _affine(pt, i):
    x = F.from_limbs(np.asarray(F.canonical(pt[0, :, i : i + 1]))[:, 0])
    y = F.from_limbs(np.asarray(F.canonical(pt[1, :, i : i + 1]))[:, 0])
    return x, y


class TestRistrettoDecodeDev:
    def test_generator_multiples_match_host(self):
        encs = [rst.encode(rst.mul_base(k)) for k in range(16)]
        pt, ok = _decode_rows(encs)
        assert ok.all()
        for i, e in enumerate(encs):
            hx, hy, hz, _ = rst.decode(e)
            zi = pow(hz, rst.P - 2, rst.P)
            assert _affine(pt, i) == (hx * zi % rst.P, hy * zi % rst.P)

    def test_invalid_encodings_rejected(self):
        bad = [
            (1).to_bytes(32, "little"),  # negative (odd)
            int(rst.P).to_bytes(32, "little"),  # == p: non-canonical
            int(rst.P + 2).to_bytes(32, "little"),  # > p, even
            b"\xff" * 32,  # way over p
            bytes(range(32)),  # non-square candidate
            (2).to_bytes(32, "little"),  # may or may not decode: differential
        ]
        _, ok = _decode_rows(bad)
        for i, e in enumerate(bad):
            assert bool(ok[i]) == (rst.decode(e) is not None), e.hex()

    def test_random_differential(self):
        rng = np.random.default_rng(7)
        encs = [bytes(rng.integers(0, 256, 32, dtype=np.uint8)) for _ in range(64)]
        # make a quarter of them valid points
        for j in range(0, 64, 4):
            encs[j] = rst.encode(rst.mul_base(int(rng.integers(1, 2**62))))
        _, ok = _decode_rows(encs)
        for i, e in enumerate(encs):
            assert bool(ok[i]) == (rst.decode(e) is not None), (i, e.hex())


class TestSr25519KernelVerify:
    def _fixtures(self, n=8):
        privs = [PrivKeySr25519.from_seed(bytes([i + 1]) * 32) for i in range(n)]
        msgs = [b"vote-%d" % i for i in range(n)]
        sigs = [p.sign(m) for p, m in zip(privs, msgs)]
        pks = [p.pub_key().bytes() for p in privs]
        return pks, msgs, sigs

    def test_all_valid(self):
        pks, msgs, sigs = self._fixtures()
        assert SK.batch_verify_host(pks, msgs, sigs).all()

    def test_corruptions_localized_and_match_host(self):
        pks, msgs, sigs = self._fixtures()
        sigs[1] = sigs[1][:63] + bytes([sigs[1][63] & 0x7F])  # marker off
        # s >= L: set s to L (plus marker bit)
        l_bytes = bytearray(int(rst.L).to_bytes(32, "little"))
        l_bytes[31] |= 0x80
        sigs[2] = sigs[2][:32] + bytes(l_bytes)
        msgs[3] = b"tampered"
        pks[4] = (1).to_bytes(32, "little")  # undecodable pubkey
        sigs[5] = (1).to_bytes(32, "little") + sigs[5][32:]  # undecodable R
        sigs[6] = b"short"  # malformed size
        got = SK.batch_verify_host(pks, msgs, sigs)
        expect = []
        for pk, m, s in zip(pks, msgs, sigs):
            try:
                expect.append(PubKeySr25519(pk).verify_signature(m, s))
            except ValueError:
                expect.append(False)
        assert got.tolist() == expect
        assert got.tolist() == [True, False, False, False, False, False, False, True]

    def test_padding_does_not_leak(self):
        # a bucket-padded batch (3 -> bucket 8) must ignore pad lanes
        pks, msgs, sigs = self._fixtures(3)
        got = SK.batch_verify_host(pks, msgs, sigs)
        assert got.shape == (3,) and got.all()


class TestChallengeBatch:
    def test_matches_scalar_transcripts(self):
        from tendermint_tpu.crypto.sr25519 import (
            _challenge,
            _signing_transcript,
            challenge_batch,
        )

        rng = np.random.default_rng(3)
        pks = [bytes(rng.integers(0, 256, 32, dtype=np.uint8)) for _ in range(40)]
        rs = [bytes(rng.integers(0, 256, 32, dtype=np.uint8)) for _ in range(40)]
        # several length groups incl. empty and rate-straddling (>166)
        msgs = [
            b"m" * (0, 1, 100, 166, 167, 400)[i % 6] for i in range(40)
        ]
        want = [
            _challenge(_signing_transcript(m), pk, r)
            for pk, m, r in zip(pks, msgs, rs)
        ]
        assert challenge_batch(pks, msgs, rs) == want

    def test_python_fallback_matches(self, monkeypatch):
        from tendermint_tpu.crypto import merlin
        from tendermint_tpu.crypto.sr25519 import challenge_batch

        want = challenge_batch([b"\x01" * 32], [b"msg"], [b"\x02" * 32])
        monkeypatch.setattr(merlin, "_NATIVE", False)  # force pure python
        assert challenge_batch([b"\x01" * 32], [b"msg"], [b"\x02" * 32]) == want


class TestDeviceSeam:
    def test_install_routes_sr25519(self):
        try:
            tpu_verifier.install(min_batch=2)
            sk = PrivKeySr25519.from_seed(b"\x0e" * 32)
            bv = crypto_batch.create_batch_verifier(sk.pub_key(), size_hint=8)
            assert isinstance(bv, tpu_verifier.TpuSr25519BatchVerifier)
            # tiny batches still decline to CPU
            bv_small = crypto_batch.create_batch_verifier(
                sk.pub_key(), size_hint=1
            )
            assert isinstance(bv_small, Sr25519BatchVerifier)
            sks = [PrivKeySr25519.from_seed(bytes([40 + i]) * 32) for i in range(6)]
            msgs = [b"m%d" % i for i in range(6)]
            for i, (s, m) in enumerate(zip(sks, msgs)):
                sig = s.sign(m)
                if i == 2:
                    sig = sig[:8] + bytes([sig[8] ^ 1]) + sig[9:]
                bv.add(s.pub_key(), m, sig)
            ok, bitmap = bv.verify()
            assert not ok
            assert bitmap == [True, True, False, True, True, True]
        finally:
            tpu_verifier.uninstall()

    def test_mixed_commit_on_device(self):
        from .test_sr25519 import _mixed_commit
        from tendermint_tpu.types.validation import verify_commit

        try:
            sigs_before = tpu_verifier.stats()["sigs"]
            tpu_verifier.install(min_batch=2)
            vals, commit, block_id, _, _ = _mixed_commit(5, 4)
            verify_commit("mixed-chain", vals, block_id, 5, commit)
            # both key-type groups went through device batch verifiers
            assert tpu_verifier.stats()["sigs"] >= sigs_before + 9
        finally:
            tpu_verifier.uninstall()
