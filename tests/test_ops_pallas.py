"""Differential test: fused Pallas ed25519 kernel vs the XLA program.

Runs in Pallas interpret mode on CPU (Mosaic lowering is exercised on
real hardware by bench.py); the XLA `_verify_tile` program — itself
differential-tested against the pure-Python ZIP-215 oracle in
test_ops_ed25519.py — is the reference here. A small tile keeps the
interpreter affordable in CI.
"""

import hashlib

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519  # noqa: E402
from tendermint_tpu.ops import ed25519_kernel as K  # noqa: E402
from tendermint_tpu.ops.ed25519_pallas import verify_pallas  # noqa: E402

TILE = 8


def _batch(n, corrupt=()):
    pks, msgs, sigs = [], [], []
    for i in range(n):
        priv = PrivKeyEd25519.from_seed(bytes([i]) * 32)
        msg = b"pallas-%d" % i
        sig = priv.sign(msg)
        if i in corrupt:
            sig = sig[:4] + bytes([sig[4] ^ 1]) + sig[5:]
        pks.append(priv.pub_key().bytes())
        msgs.append(msg)
        sigs.append(sig)
    digs = [
        hashlib.sha512(s[:32] + p + m).digest()
        for p, m, s in zip(pks, msgs, sigs)
    ]
    return (
        jnp.asarray(K._join_cols(pks, 32, 0)),
        jnp.asarray(K._join_cols(sigs, 64, 0)),
        jnp.asarray(K._join_cols(digs, 64, 0)),
    )


def test_pallas_matches_xla_program():
    pk, sig, dig = _batch(2 * TILE, corrupt={3, 11})
    ref = np.asarray(K._verify_tile(pk, sig, dig))
    got = np.asarray(
        verify_pallas(pk, sig, dig, interpret=True, tile=TILE)
    )
    assert ref.dtype == got.dtype == np.bool_
    assert (ref == got).all()
    assert not got[3] and not got[11]
    assert got.sum() == 2 * TILE - 2


def test_hybrid_matches_xla_program():
    """The segmented program (Pallas dual-mult, XLA around it) must
    return the exact bitmap of the pure-XLA tile."""
    from tendermint_tpu.ops.ed25519_pallas import verify_hybrid

    pk, sig, dig = _batch(2 * TILE, corrupt={0, 9})
    ref = np.asarray(K._verify_tile(pk, sig, dig))
    got = np.asarray(verify_hybrid(pk, sig, dig, interpret=True, tile=TILE))
    assert (ref == got).all()
    assert not got[0] and not got[9]
    assert got.sum() == 2 * TILE - 2


def test_mosaic_jaxpr_clean():
    """The mosaic-path bodies must stay free of primitives Mosaic
    cannot lower (scatter, gather, dynamic_slice, rev, rank-1 iota) —
    each was found the hard way on hardware (PERF.md). Guards the
    kernels' lowerability without needing a TPU in CI.

    Capability-gated per primitive: some jax versions (0.4.37:
    zero-width-ellipsis static slices lower to `gather`) introduce a
    banned primitive for constructs that are *semantically* clean, so
    that primitive is undecidable at the jaxpr level there —
    `toolchain.mosaic_probe()` names exactly which
    (bench.py records the verdict in every BENCH_* line, and the AOT
    check on real hardware remains its ground truth). Coverage for
    every NON-laundered primitive (scatter, rev, dynamic_slice,
    rank-1 iota…) is kept: a kernel edit introducing one of those
    still fails here, on every toolchain. Only a toolchain that
    launders everything would skip outright."""
    from tendermint_tpu.ops import field25519 as F
    from tendermint_tpu.ops import toolchain

    probe = toolchain.mosaic_probe()
    laundered = set()
    for prims in probe["introduced"].values():
        laundered.update(prims)
    decidable = (set(toolchain.BANNED) | {"iota(rank-1)"}) - laundered
    if not decidable:  # pragma: no cover - no known toolchain does this
        pytest.skip(
            "toolchain lowers known-clean constructs to EVERY banned "
            f"primitive (jax {probe['jax_version']}: "
            f"{probe['introduced']}); jaxpr-level cleanliness is "
            "undecidable here — AOT check on hardware is the gate"
        )

    i32 = jnp.int32
    s32 = jax.ShapeDtypeStruct((32, TILE), i32)
    s64 = jax.ShapeDtypeStruct((64, TILE), i32)
    pt = jax.ShapeDtypeStruct((4, F.NLIMBS, TILE), i32)
    bad = toolchain.banned_prims_of(
        lambda a, b, c: K._verify_tile(a, b, c, mosaic=True), s32, s64, s64
    ) - laundered
    assert not bad, (
        f"monolithic tile body uses {bad} "
        f"(toolchain-laundered and excluded: {sorted(laundered)})"
    )
    bad = toolchain.banned_prims_of(
        lambda a, b, c: K.dual_mult_sb_minus_ka(a, b, c, mosaic=True),
        pt, s64, s64,
    ) - laundered
    assert not bad, (
        f"dual-mult body uses {bad} "
        f"(toolchain-laundered and excluded: {sorted(laundered)})"
    )


def test_sr25519_hybrid_matches_xla_program():
    """The sr25519 hybrid (Pallas dual-mult segment) must return the
    exact bitmap of the pure-XLA sr25519 tile."""
    import functools

    from tendermint_tpu.crypto.sr25519 import PrivKeySr25519
    from tendermint_tpu.ops import sr25519_kernel as S
    from tendermint_tpu.ops.ed25519_pallas import dual_mult_pallas

    pks, msgs, sigs = [], [], []
    for i in range(TILE):
        priv = PrivKeySr25519.from_seed(bytes([i, 3]) + b"\x00" * 30)
        m = b"sr-pallas-%d" % i
        sig = priv.sign(m)
        if i in (1, 5):
            sig = sig[:6] + bytes([sig[6] ^ 1]) + sig[7:]
        pks.append(priv.pub_key().bytes())
        msgs.append(m)
        sigs.append(sig)
    from tendermint_tpu.crypto.sr25519 import challenge_batch

    ks = [
        k.to_bytes(32, "little")
        for k in challenge_batch(pks, msgs, [s[:32] for s in sigs])
    ]
    pk_b = jnp.asarray(K._join_cols(pks, 32, 0))
    sig_b = jnp.asarray(K._join_cols(sigs, 64, 0))
    k_b = jnp.asarray(K._join_cols(ks, 32, 0))
    ref = np.asarray(S._verify_tile_sr(pk_b, sig_b, k_b))
    dual = functools.partial(dual_mult_pallas, interpret=True, tile=TILE)
    got = np.asarray(S._verify_tile_sr(pk_b, sig_b, k_b, dual_fn=dual))
    assert (ref == got).all()
    assert not got[1] and not got[5]
    assert got.sum() == TILE - 2
