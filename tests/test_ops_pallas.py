"""Differential test: fused Pallas ed25519 kernel vs the XLA program.

Runs in Pallas interpret mode on CPU (Mosaic lowering is exercised on
real hardware by bench.py); the XLA `_verify_tile` program — itself
differential-tested against the pure-Python ZIP-215 oracle in
test_ops_ed25519.py — is the reference here. A small tile keeps the
interpreter affordable in CI.
"""

import hashlib

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519  # noqa: E402
from tendermint_tpu.ops import ed25519_kernel as K  # noqa: E402
from tendermint_tpu.ops.ed25519_pallas import verify_pallas  # noqa: E402

TILE = 8


def _batch(n, corrupt=()):
    pks, msgs, sigs = [], [], []
    for i in range(n):
        priv = PrivKeyEd25519.from_seed(bytes([i]) * 32)
        msg = b"pallas-%d" % i
        sig = priv.sign(msg)
        if i in corrupt:
            sig = sig[:4] + bytes([sig[4] ^ 1]) + sig[5:]
        pks.append(priv.pub_key().bytes())
        msgs.append(msg)
        sigs.append(sig)
    digs = [
        hashlib.sha512(s[:32] + p + m).digest()
        for p, m, s in zip(pks, msgs, sigs)
    ]
    return (
        jnp.asarray(K._join_cols(pks, 32, 0)),
        jnp.asarray(K._join_cols(sigs, 64, 0)),
        jnp.asarray(K._join_cols(digs, 64, 0)),
    )


def test_pallas_matches_xla_program():
    pk, sig, dig = _batch(2 * TILE, corrupt={3, 11})
    ref = np.asarray(K._verify_tile(pk, sig, dig))
    got = np.asarray(
        verify_pallas(pk, sig, dig, interpret=True, tile=TILE)
    )
    assert ref.dtype == got.dtype == np.bool_
    assert (ref == got).all()
    assert not got[3] and not got[11]
    assert got.sum() == 2 * TILE - 2


def test_hybrid_matches_xla_program():
    """The segmented program (Pallas dual-mult, XLA around it) must
    return the exact bitmap of the pure-XLA tile."""
    from tendermint_tpu.ops.ed25519_pallas import verify_hybrid

    pk, sig, dig = _batch(2 * TILE, corrupt={0, 9})
    ref = np.asarray(K._verify_tile(pk, sig, dig))
    got = np.asarray(verify_hybrid(pk, sig, dig, interpret=True, tile=TILE))
    assert (ref == got).all()
    assert not got[0] and not got[9]
    assert got.sum() == 2 * TILE - 2


def test_mosaic_jaxpr_clean():
    """The mosaic-path bodies must stay free of primitives Mosaic
    cannot lower (scatter, gather, dynamic_slice, rev, rank-1 iota) —
    each was found the hard way on hardware (PERF.md). Guards the
    kernels' lowerability without needing a TPU in CI."""
    import jax

    from tendermint_tpu.ops import field25519 as F

    banned = {
        "scatter", "scatter-add", "gather", "dynamic_slice",
        "dynamic_update_slice", "rev",
    }

    def check(fn, *avals):
        seen = set()

        def walk(jaxpr):
            for eq in jaxpr.eqns:
                name = eq.primitive.name
                if name in banned:
                    seen.add(name)
                if name == "iota" and len(eq.outvars[0].aval.shape) == 1:
                    seen.add("iota(rank-1)")
                for p in eq.params.values():
                    if hasattr(p, "jaxpr"):
                        walk(p.jaxpr)
                    elif isinstance(p, (list, tuple)):
                        for q in p:
                            if hasattr(q, "jaxpr"):
                                walk(q.jaxpr)

        walk(jax.make_jaxpr(fn)(*avals).jaxpr)
        return seen

    i32 = jnp.int32
    s32 = jax.ShapeDtypeStruct((32, TILE), i32)
    s64 = jax.ShapeDtypeStruct((64, TILE), i32)
    pt = jax.ShapeDtypeStruct((4, F.NLIMBS, TILE), i32)
    bad = check(
        lambda a, b, c: K._verify_tile(a, b, c, mosaic=True), s32, s64, s64
    )
    assert not bad, f"monolithic tile body uses {bad}"
    bad = check(
        lambda a, b, c: K.dual_mult_sb_minus_ka(a, b, c, mosaic=True),
        pt, s64, s64,
    )
    assert not bad, f"dual-mult body uses {bad}"


def test_sr25519_hybrid_matches_xla_program():
    """The sr25519 hybrid (Pallas dual-mult segment) must return the
    exact bitmap of the pure-XLA sr25519 tile."""
    import functools

    from tendermint_tpu.crypto.sr25519 import PrivKeySr25519
    from tendermint_tpu.ops import sr25519_kernel as S
    from tendermint_tpu.ops.ed25519_pallas import dual_mult_pallas

    pks, msgs, sigs = [], [], []
    for i in range(TILE):
        priv = PrivKeySr25519.from_seed(bytes([i, 3]) + b"\x00" * 30)
        m = b"sr-pallas-%d" % i
        sig = priv.sign(m)
        if i in (1, 5):
            sig = sig[:6] + bytes([sig[6] ^ 1]) + sig[7:]
        pks.append(priv.pub_key().bytes())
        msgs.append(m)
        sigs.append(sig)
    from tendermint_tpu.crypto.sr25519 import challenge_batch

    ks = [
        k.to_bytes(32, "little")
        for k in challenge_batch(pks, msgs, [s[:32] for s in sigs])
    ]
    pk_b = jnp.asarray(K._join_cols(pks, 32, 0))
    sig_b = jnp.asarray(K._join_cols(sigs, 64, 0))
    k_b = jnp.asarray(K._join_cols(ks, 32, 0))
    ref = np.asarray(S._verify_tile_sr(pk_b, sig_b, k_b))
    dual = functools.partial(dual_mult_pallas, interpret=True, tile=TILE)
    got = np.asarray(S._verify_tile_sr(pk_b, sig_b, k_b, dual_fn=dual))
    assert (ref == got).all()
    assert not got[1] and not got[5]
    assert got.sum() == TILE - 2
