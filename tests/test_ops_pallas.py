"""Differential test: fused Pallas ed25519 kernel vs the XLA program.

Runs in Pallas interpret mode on CPU (Mosaic lowering is exercised on
real hardware by bench.py); the XLA `_verify_tile` program — itself
differential-tested against the pure-Python ZIP-215 oracle in
test_ops_ed25519.py — is the reference here. A small tile keeps the
interpreter affordable in CI.
"""

import hashlib

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519  # noqa: E402
from tendermint_tpu.ops import ed25519_kernel as K  # noqa: E402
from tendermint_tpu.ops.ed25519_pallas import verify_pallas  # noqa: E402

TILE = 8


def _batch(n, corrupt=()):
    pks, msgs, sigs = [], [], []
    for i in range(n):
        priv = PrivKeyEd25519.from_seed(bytes([i]) * 32)
        msg = b"pallas-%d" % i
        sig = priv.sign(msg)
        if i in corrupt:
            sig = sig[:4] + bytes([sig[4] ^ 1]) + sig[5:]
        pks.append(priv.pub_key().bytes())
        msgs.append(msg)
        sigs.append(sig)
    digs = [
        hashlib.sha512(s[:32] + p + m).digest()
        for p, m, s in zip(pks, msgs, sigs)
    ]
    return (
        jnp.asarray(K._join_cols(pks, 32, 0)),
        jnp.asarray(K._join_cols(sigs, 64, 0)),
        jnp.asarray(K._join_cols(digs, 64, 0)),
    )


def test_pallas_matches_xla_program():
    pk, sig, dig = _batch(2 * TILE, corrupt={3, 11})
    ref = np.asarray(K._verify_tile(pk, sig, dig))
    got = np.asarray(
        verify_pallas(pk, sig, dig, interpret=True, tile=TILE)
    )
    assert ref.dtype == got.dtype == np.bool_
    assert (ref == got).all()
    assert not got[3] and not got[11]
    assert got.sum() == 2 * TILE - 2
