"""Block sync tests — a fresh node fetches verified blocks in parallel
from the network, then switches to consensus
(reference model: internal/blocksync/reactor_test.go, pool_test.go)."""

import asyncio

from tendermint_tpu.blocksync import (
    BlockPool,
    BlockRequestMessage,
    BlockResponseMessage,
    BlocksyncCodec,
    StatusRequestMessage,
    StatusResponseMessage,
)
from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.p2p.p2ptest import TestNetwork
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

from .test_reactors import CHAIN, FullNode


def run(coro):
    return asyncio.run(coro)


def test_blocksync_codec_roundtrip():
    for msg in (
        BlockRequestMessage(height=7),
        StatusRequestMessage(),
        StatusResponseMessage(height=10, base=2),
    ):
        assert BlocksyncCodec.decode(BlocksyncCodec.encode(msg)) == msg


def test_pool_requesters_and_order():
    async def go():
        sent = []
        pool = BlockPool(1, lambda h, p: sent.append((h, p)))
        await pool.start()
        pool.set_peer_range("peerA", 0, 5)
        pool.set_peer_range("peerB", 0, 5)
        await asyncio.sleep(0.2)
        # requesters spawned for heights 1..5
        requested_heights = {h for h, _ in sent}
        assert requested_heights == {1, 2, 3, 4, 5}

        # feed blocks out of order; peek returns them in order
        from tendermint_tpu.types.block import make_block
        from tendermint_tpu.types.commit import Commit

        blocks = {}
        for h in (2, 1, 3):
            b = make_block(h, [], Commit(), [])
            b.header.height = h
            blocks[h] = b
            pool.add_block("peerA", b)
        first, second = pool.peek_two_blocks()
        assert first.header.height == 1 and second.header.height == 2
        pool.pop_request()
        first, second = pool.peek_two_blocks()
        assert first.header.height == 2 and second.header.height == 3
        await pool.stop()

    run(go())


def test_fresh_node_block_syncs_and_joins_consensus():
    async def go():
        # 4 validators make progress; a 5th non-validator node starts at
        # genesis in block-sync mode and must catch up then follow
        privs = [PrivKeyEd25519.from_seed(bytes([i + 100]) * 32) for i in range(4)]
        genesis = GenesisDoc(
            chain_id=CHAIN,
            genesis_time_ns=1_700_000_000_000_000_000,
            validators=[
                GenesisValidator(pub_key=p.pub_key(), power=10) for p in privs
            ],
        )
        net = TestNetwork(5, chain_id=CHAIN)
        validators = [
            FullNode(net.nodes[i], privs[i], genesis) for i in range(4)
        ]
        fresh = FullNode(net.nodes[4], None, genesis, block_sync=True)

        for v in validators:
            await v.start()
        await net.start()
        try:
            await asyncio.gather(
                *(v.cs.wait_for_height(6, timeout=90.0) for v in validators)
            )
            # start the fresh node only now: it is 6+ blocks behind, so
            # catching up must go through the block-sync pipeline (peer-UP
            # events were buffered in its subscriptions)
            await fresh.start()

            # the fresh node must catch up via block sync
            async def synced():
                while not fresh.bs_reactor.synced:
                    await asyncio.sleep(0.05)

            await asyncio.wait_for(synced(), 60.0)
            assert fresh.block_store.height() >= 4

            # ... and then follow consensus as a full node
            target = validators[0].cs.rs.height + 2
            await fresh.cs.wait_for_height(target, timeout=60.0)
        finally:
            for v in validators:
                await v.stop()
            await fresh.stop()
            await net.stop()

        # identical chain
        for h in range(1, 5):
            assert (
                fresh.block_store.load_block(h).hash()
                == validators[0].block_store.load_block(h).hash()
            )
        # the app replayed all the blocks too
        assert fresh.app.height == fresh.block_store.height()

    run(go())
