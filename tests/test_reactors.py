"""Multi-validator consensus through the real reactor + p2p stack —
the in-process equivalent of the reference's 4-validator localnet
(reference model: internal/consensus/reactor_test.go, p2ptest harness).
"""

import asyncio

import pytest

from tendermint_tpu.abci import KVStoreApplication, LocalClient
from tendermint_tpu.config import ConsensusConfig, MempoolConfig
from tendermint_tpu.consensus import ConsensusState
from tendermint_tpu.consensus.reactor import (
    ConsensusReactor,
    consensus_channel_descriptors,
)
from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.eventbus import EventBus
from tendermint_tpu.evidence import (
    EvidencePool,
    EvidenceReactor,
    evidence_channel_descriptor,
)
from tendermint_tpu.mempool import TxMempool
from tendermint_tpu.mempool.reactor import (
    MempoolReactor,
    mempool_channel_descriptor,
)
from tendermint_tpu.p2p.p2ptest import TestNetwork
from tendermint_tpu.privval import MockPV
from tendermint_tpu.state import StateStore, state_from_genesis
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.store.block_store import BlockStore
from tendermint_tpu.store.kv import MemKV
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

CHAIN = "reactor-chain"


def run(coro):
    return asyncio.run(coro)


def fast_config():
    return ConsensusConfig(
        timeout_propose=2.0,
        timeout_propose_delta=0.5,
        timeout_prevote=1.0,
        timeout_prevote_delta=0.5,
        timeout_precommit=1.0,
        timeout_precommit_delta=0.5,
        timeout_commit=0.2,
        skip_timeout_commit=False,
        peer_gossip_sleep_duration=0.01,
        peer_query_maj23_sleep_duration=0.5,
    )


class FullNode:
    """Everything a validator runs, wired over a p2ptest node."""

    def __init__(self, p2p_node, priv, genesis, block_sync=False,
                 state_sync=False):
        from tendermint_tpu.blocksync import (
            BlocksyncReactor,
            blocksync_channel_descriptor,
        )

        self.p2p = p2p_node
        self.app = KVStoreApplication()
        self.client = LocalClient(self.app)
        self.state_store = StateStore(MemKV())
        state = state_from_genesis(genesis)
        self.state_store.save(state)
        self.block_store = BlockStore(MemKV())
        self.mempool = TxMempool(self.client, MempoolConfig())
        self.bus = EventBus()
        self.evpool = EvidencePool(MemKV(), self.state_store, self.block_store)
        self.exec = BlockExecutor(
            self.state_store, self.client, self.mempool,
            evidence_pool=self.evpool, block_store=self.block_store,
            event_bus=self.bus,
        )
        self.cs = ConsensusState(
            fast_config(), state, self.exec, self.block_store,
            privval=MockPV(priv) if priv is not None else None,
            event_bus=self.bus,
            evidence_pool=self.evpool,
        )
        cs_channels = {
            cid: self.p2p.open_channel(d)
            for cid, d in consensus_channel_descriptors().items()
        }
        self.cs_reactor = ConsensusReactor(
            self.cs, cs_channels, self.p2p.peer_manager.subscribe(), self.bus,
            wait_sync=block_sync or state_sync,
        )
        self.mp_reactor = MempoolReactor(
            self.mempool,
            self.p2p.open_channel(mempool_channel_descriptor()),
            self.p2p.peer_manager.subscribe(),
        )
        self.ev_reactor = EvidenceReactor(
            self.evpool,
            self.p2p.open_channel(evidence_channel_descriptor()),
            self.p2p.peer_manager.subscribe(),
        )
        self.bs_reactor = BlocksyncReactor(
            state, self.exec, self.block_store,
            self.p2p.open_channel(blocksync_channel_descriptor()),
            self.p2p.peer_manager.subscribe(),
            block_sync=block_sync,
            consensus_reactor=self.cs_reactor,
            event_bus=self.bus,
        )
        from tendermint_tpu.config import StateSyncConfig
        from tendermint_tpu.statesync import (
            StatesyncReactor,
            statesync_channel_descriptors,
        )

        self.ss_reactor = StatesyncReactor(
            genesis.chain_id,
            state,
            self.client,
            self.state_store,
            self.block_store,
            {
                cid: self.p2p.open_channel(d)
                for cid, d in statesync_channel_descriptors().items()
            },
            self.p2p.peer_manager.subscribe(),
            cfg=StateSyncConfig(discovery_time=0.5),
        )

    async def start(self):
        await self.bus.start()
        await self.cs_reactor.start()
        await self.mp_reactor.start()
        await self.ev_reactor.start()
        await self.bs_reactor.start()
        await self.ss_reactor.start()

    async def stop(self):
        await self.ss_reactor.stop()
        await self.bs_reactor.stop()
        await self.ev_reactor.stop()
        await self.mp_reactor.stop()
        await self.cs_reactor.stop()
        await self.bus.stop()


def make_cluster(n):
    privs = [PrivKeyEd25519.from_seed(bytes([i + 100]) * 32) for i in range(n)]
    genesis = GenesisDoc(
        chain_id=CHAIN,
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=[
            GenesisValidator(pub_key=p.pub_key(), power=10) for p in privs
        ],
    )
    net = TestNetwork(n, chain_id=CHAIN)
    nodes = [
        FullNode(net.nodes[i], privs[i], genesis) for i in range(n)
    ]
    return net, nodes


async def start_cluster(net, nodes):
    for node in nodes:
        await node.start()
    await net.start()


async def stop_cluster(net, nodes):
    for node in nodes:
        await node.stop()
    await net.stop()


def test_four_validators_reach_consensus_over_p2p():
    async def go():
        net, nodes = make_cluster(4)
        await start_cluster(net, nodes)
        try:
            await asyncio.gather(
                *(n.cs.wait_for_height(4, timeout=60.0) for n in nodes)
            )
        finally:
            await stop_cluster(net, nodes)

        for h in range(1, 4):
            hashes = {n.block_store.load_block(h).hash() for n in nodes}
            assert len(hashes) == 1, f"divergent block at height {h}"
        proposers = {
            nodes[0].block_store.load_block(h).header.proposer_address
            for h in range(1, 4)
        }
        assert len(proposers) >= 2  # rotation happened

    run(go())


def test_tx_gossip_and_commit_over_p2p():
    async def go():
        net, nodes = make_cluster(4)
        await start_cluster(net, nodes)
        try:
            await asyncio.gather(
                *(n.cs.wait_for_height(2, timeout=60.0) for n in nodes)
            )
            # submit a tx at node 3 only; gossip must carry it to proposers
            await nodes[3].mempool.check_tx(b"gossip=works")
            target = nodes[0].cs.rs.height + 3
            await asyncio.gather(
                *(n.cs.wait_for_height(target, timeout=60.0) for n in nodes)
            )
        finally:
            await stop_cluster(net, nodes)

        for n in nodes:
            assert n.app.state.get(b"gossip") == b"works", "tx missing on a node"

    run(go())


def test_catchup_votes_dropped_during_wait_sync_are_resent():
    """Regression for the process-net SIGKILL wedge: a restarted
    validator announces its height while its consensus reactor is
    still in wait_sync (the blocksync grace window), the peers stream
    the stored-commit precommits for that height into the void —
    marking them delivered — and when the node finally switches to
    consensus nobody ever resends, wedging it at its boot height
    forever while the net runs ahead. The gossip-votes stall-reset
    (reactor.py `vote_catchup_stall`, the votes-side twin of
    `_gossip_catchup_part`'s forget-and-resend) must recover it.

    Deterministic form of the race: the laggard joins in wait_sync
    with its blocksync switch HELD for long enough that the peers
    exhaust (and mark) every catchup precommit, then switches."""

    async def go():
        net, nodes = make_cluster(4)
        laggard = nodes[3]
        # stall-reset observability (ISSUE 15): the wedge-save must be
        # VISIBLE — counter + flight-recorder event, not just the
        # silent mark reset. Test-harness nodes share DEFAULT_REGISTRY,
        # so one instrument counts the whole cluster; delta vs the
        # entry value isolates this test from earlier ones.
        stall_ctr = nodes[0].cs.metrics.stall_resets
        catchup_base = stall_ctr.value(kind="catchup")
        for node in nodes[:3]:
            await node.start()
        await net.start()
        try:
            await asyncio.gather(
                *(n.cs.wait_for_height(3, timeout=60.0) for n in nodes[:3])
            )
            # hold the laggard's blocksync: never caught up, nothing to
            # apply — its consensus reactor stays wait_sync, DROPPING
            # every catchup vote/part the peers now stream and mark
            laggard.cs_reactor.wait_sync = True
            laggard.bs_reactor.block_sync = True
            laggard.bs_reactor.pool.is_caught_up = lambda: False
            laggard.bs_reactor.pool.peek_two_blocks = lambda: (None, None)
            await laggard.start()
            # long enough for the peers' gossip (tick 0.01 s) to drain
            # all 4 precommits of the laggard's height into the void
            await asyncio.sleep(1.5)
            assert laggard.cs.rs.height <= 2  # still parked
            # blocksync "finishes" (its pool saw nothing): switch
            await laggard.bs_reactor._switch_to_consensus()
            # without the stall-reset this wedges forever; with it the
            # peers forget their delivered-marks after ~1 s and resend
            await laggard.cs.wait_for_height(3, timeout=30.0)
        finally:
            await stop_cluster(net, nodes)
        for height in range(1, 3):
            assert (
                laggard.block_store.load_block(height).hash()
                == nodes[0].block_store.load_block(height).hash()
            )
        # the recovery ran THROUGH the catchup stall-reset: the tick
        # that saved the wedge is now observable (counter + a
        # stall_reset event in some peer's flight recorder)
        assert stall_ctr.value(kind="catchup") > catchup_base
        assert any(
            e.kind == "stall_reset"
            and e.attrs
            and e.attrs.get("reset") == "catchup"
            for n in nodes[:3]
            for e in n.cs.timeline.snapshot()
        )

    run(go())


def test_live_votes_dropped_by_partition_are_resent():
    """Regression for the majority-partition-heal wedge (ISSUE 13,
    witnessed in the chaos campaign): a 2|2 partition drops in-flight
    prevotes while every connection SURVIVES, so `_send_vote`'s
    optimistic `set_has_vote` marks claim delivery; after heal, no
    side holds 2/3 prevotes, no timeout is scheduled without a +2/3
    majority, and same-height gossip finds nothing "missing" to send —
    all four nodes park at (height, round 0, prevote) forever. The
    live-height gossip stall-reset (reactor.py `live_vote_stall` →
    `PeerState.reset_live_votes`, the same-height twin of
    `vote_catchup_stall`) must forget the marks and resend."""
    from tendermint_tpu.crypto import faults

    async def go():
        net, nodes = make_cluster(4)
        # same shared-registry delta pattern as the catchup test above
        stall_ctr = nodes[0].cs.metrics.stall_resets
        live_base = stall_ctr.value(kind="live") + stall_ctr.value(
            kind="last_commit"
        )
        await start_cluster(net, nodes)
        try:
            await asyncio.gather(
                *(n.cs.wait_for_height(3, timeout=60.0) for n in nodes)
            )
            # p2ptest monikers are node0..node3: cut 2|2 — neither
            # side can assemble 2/3, and every vote gossiped during
            # the window is dropped ON a live connection (the exact
            # shape TCP can't produce but a partitioned WAN can)
            faults.set_partition("node0,node1|node2,node3")
            await asyncio.sleep(3.0)  # gossip drains into the void
            heal_at = max(n.cs.rs.height for n in nodes)
            faults.set_partition("")
            # without the stall-reset this times out at heal_at
            await asyncio.gather(
                *(
                    n.cs.wait_for_height(heal_at + 1, timeout=30.0)
                    for n in nodes
                )
            )
        finally:
            faults.set_partition("")
            await stop_cluster(net, nodes)
        common = min(n.block_store.height() for n in nodes)
        for height in range(1, common + 1):
            assert (
                nodes[1].block_store.load_block(height).hash()
                == nodes[0].block_store.load_block(height).hash()
            )
        # the un-wedge ran through a live-height (or last-commit,
        # when the partition straddled a commit boundary) stall-reset
        # — visible as a counter bump + flight-recorder events
        live_after = stall_ctr.value(kind="live") + stall_ctr.value(
            kind="last_commit"
        )
        assert live_after > live_base
        assert any(
            e.kind == "stall_reset"
            for n in nodes
            for e in n.cs.timeline.snapshot()
        )

    run(go())


def test_lagging_node_catches_up():
    async def go():
        net, nodes = make_cluster(4)
        # start only 3 of 4 validators; consensus still has 3/4 > 2/3 power
        for node in nodes[:3]:
            await node.start()
        await net.start()
        try:
            await asyncio.gather(
                *(n.cs.wait_for_height(3, timeout=60.0) for n in nodes[:3])
            )
            # now start the laggard; catchup gossip must bring it up
            await nodes[3].start()
            await nodes[3].cs.wait_for_height(3, timeout=60.0)
        finally:
            await stop_cluster(net, nodes)

        h = min(3, nodes[3].block_store.height())
        assert h >= 2
        for height in range(1, h + 1):
            assert (
                nodes[3].block_store.load_block(height).hash()
                == nodes[0].block_store.load_block(height).hash()
            )

    run(go())


def test_evidence_broadcast_paces_to_the_recv_clamp():
    """Sender-side chunking regression (code-review finding on the
    ISSUE-14 recv clamp): a pending backlog larger than
    MAX_MSG_EVIDENCE must drain across broadcast ticks — one clamp-
    sized chunk per message — instead of going out as one oversized
    message whose tail the receiver's clamp would drop and whose
    re-offers would resend the SAME prefix forever (pending_evidence
    iterates in stable insertion order, so the starvation was
    deterministic)."""
    import tendermint_tpu.evidence.reactor as evr
    from tendermint_tpu.evidence.reactor import (
        MAX_MSG_EVIDENCE,
        EvidenceReactor,
    )

    n_extra = 10

    class _Ev:
        def __init__(self, i):
            self._h = i.to_bytes(8, "big")

        def hash(self):
            return self._h

    class _Pool:
        def __init__(self, n):
            self.items = [_Ev(i) for i in range(n)]

        def pending_evidence(self, _cap):
            return list(self.items), 0

    class _Chan:
        def __init__(self):
            self.sent = []

        def try_send(self, env):
            self.sent.append(env.message.evidence)
            return True

    async def go():
        r = EvidenceReactor.__new__(EvidenceReactor)
        r.pool = _Pool(MAX_MSG_EVIDENCE + n_extra)
        r.channel = _Chan()
        old = evr._BROADCAST_INTERVAL
        evr._BROADCAST_INTERVAL = 0.001
        try:
            t = asyncio.ensure_future(r._broadcast_to_peer("peer0"))
            for _ in range(500):
                await asyncio.sleep(0.002)
                if len(r.channel.sent) >= 2:
                    break
            t.cancel()
            try:
                await t
            except asyncio.CancelledError:
                pass
        finally:
            evr._BROADCAST_INTERVAL = old
        sent = r.channel.sent
        assert sent, "broadcast loop never sent"
        assert all(len(b) <= MAX_MSG_EVIDENCE for b in sent)
        assert len(sent[0]) == MAX_MSG_EVIDENCE
        delivered = {e.hash() for batch in sent for e in batch}
        assert len(delivered) == MAX_MSG_EVIDENCE + n_extra

    run(go())
