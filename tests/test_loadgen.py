"""tmload harness tests (ISSUE 12).

Tier-1: scenario/schedule determinism, the coordinated-omission
property of the open-loop driver (against a stub server — no net),
a seconds-scale seeded closed-loop smoke against a LIVE in-process
node over real HTTP/websocket asserting nonzero per-route sketch
counts node-side, slow-request exemplar capture on an injected-slow
route (crypto/faults `rpc.route` hang), and a tmlive boundedness gate
scoped to the new package. The full sustained multi-node open-loop
run is `@pytest.mark.slow`.
"""

import asyncio

import pytest

from tendermint_tpu.crypto import faults
from tendermint_tpu.libs import trace
from tendermint_tpu.loadgen import (
    Scenario,
    run_localnet_scenario,
    run_scenario,
    start_localnet,
)
from tendermint_tpu.loadgen.driver import arrival_offsets, run_open_loop
from tendermint_tpu.rpc import HTTPClient


def run(coro, timeout=240.0):
    return asyncio.run(asyncio.wait_for(coro, timeout))


class TestScenario:
    def test_validation(self):
        Scenario().validate()
        with pytest.raises(ValueError):
            Scenario(mode="sideways").validate()
        with pytest.raises(ValueError):
            Scenario(arrival="uniform").validate()
        with pytest.raises(ValueError):
            Scenario(duration_s=0).validate()
        with pytest.raises(ValueError):
            Scenario(mode="open", rate=0).validate()
        with pytest.raises(ValueError):
            Scenario(mix=(("teleport", 1.0),)).validate()
        with pytest.raises(ValueError):
            Scenario(mix=(("block", -1.0),)).validate()
        scn = Scenario().with_(rate=50.0)
        assert scn.rate == 50.0

    def test_arrival_schedule_is_seeded_and_shaped(self):
        scn = Scenario(
            seed=42, mode="open", duration_s=4.0, rate=100.0
        )
        a = arrival_offsets(scn)
        b = arrival_offsets(scn)
        assert a == b  # one seed, one schedule
        assert a != arrival_offsets(scn.with_(seed=43))
        # poisson at rate R over D seconds lands near R*D arrivals
        assert 0.7 * 400 <= len(a) <= 1.3 * 400
        assert all(0 <= t < scn.duration_s for t in a)
        assert a == sorted(a)
        # fixed spacing is exact
        fixed = arrival_offsets(
            scn.with_(arrival="fixed", duration_s=2.0)
        )
        assert len(fixed) == 199  # 2s at 100/s, t=0 excluded
        gaps = {
            round(y - x, 9) for x, y in zip(fixed, fixed[1:])
        }
        assert gaps == {0.01}
        # the ramp thins the head, not the tail
        ramped = arrival_offsets(
            scn.with_(arrival="fixed", ramp_s=2.0)
        )
        head = sum(1 for t in ramped if t < 1.0)
        tail = sum(1 for t in ramped if t >= 3.0)
        assert head < tail


class _StallPool:
    """Duck-typed ClientPool: the first `stall_n` requests hang
    `stall_s`, the rest answer instantly — a server that freezes under
    its opening burst."""

    def __init__(self, stall_s: float, stall_n: int) -> None:
        self.calls = 0
        self._stall_s = stall_s
        self._stall_n = stall_n

    async def call(self, method, **params):
        self.calls += 1
        if self.calls <= self._stall_n:
            await asyncio.sleep(self._stall_s)
        return {}


def test_open_loop_measures_from_intended_time():
    """Coordinated-omission correction: when the server stalls, the
    requests scheduled DURING the stall must each report the queueing
    delay they suffered (latency from intended arrival), so the p99
    reflects the stall even though only one request touched it."""
    scn = Scenario(
        seed=9,
        mode="open",
        duration_s=0.5,
        rate=100.0,
        arrival="fixed",
        max_inflight=1,  # one connection: the stall queues everyone
        mix=(("status", 1.0),),
        timeout_s=5.0,
    )
    pool = _StallPool(stall_s=0.3, stall_n=1)
    stats, scheduled = run(run_open_loop(scn, [pool]))
    st = stats["status"]
    assert scheduled == len(arrival_offsets(scn))
    assert st.count == scheduled
    # ~30 requests were scheduled during the 0.3 s stall; with the
    # single connection each of them queued — the sketch must show a
    # fat tail even though the "slow" call was a single one
    delayed = scheduled * 0.3 / 0.5 * 0.66  # conservative floor
    over_100ms = sum(
        c
        for i, c in st.sketch.snapshot()._counts.items()
        if 2.0 * st.sketch._gamma ** i / (st.sketch._gamma + 1) > 0.1
    )
    assert over_100ms >= delayed, (over_100ms, delayed)
    assert st.sketch.quantile(0.5) > 0.05


@pytest.fixture
def _trace_off_after():
    yield
    trace.disable()
    trace.reset()
    trace.disable_exemplars()
    trace.reset_exemplars()


def test_load_smoke_closed_loop_live_node(tmp_path):
    """The deterministic tier-1 smoke: a seconds-scale seeded
    closed-loop run against a live in-process node over real HTTP +
    websocket. Every route in the mix must land nonzero counts in the
    harness sketches AND in the node's per-route registry family
    (requests_total / latency sketch / inflight gauge present)."""

    async def go():
        net = await start_localnet(1, str(tmp_path / "smoke"), seed=21)
        try:
            scn = Scenario(
                seed=21,
                mode="closed",
                duration_s=1.5,
                concurrency=3,
                subscribers=2,
                timeout_s=10.0,
            )
            rep = await run_scenario(
                scn, net.rpc_addrs, nodes=net.nodes
            )
            mixed = set(scn.mix_ops())
            assert set(rep["routes"]) == mixed
            for op, row in rep["routes"].items():
                assert row["count"] > 0, op
                assert row["p50_ms"] > 0.0, op
                assert row["p999_ms"] >= row["p99_ms"] >= row["p50_ms"]
            assert rep["errors_total"] == 0, rep["routes"]
            assert rep["timeouts_total"] == 0
            assert rep["sustained_txs_per_s"] > 0
            assert rep["subscribers"]["connected"] == 2
            assert rep["subscribers"]["held"] == 2
            # node-side per-route family recorded the same traffic
            m = net.nodes[0].rpc_env.metrics
            for op in mixed:
                assert m.requests_total.value(route=op) > 0, op
                assert m.request_latency.count(route=op) > 0, op
                assert m.inflight.value(route=op) == 0, op  # drained
            # saturation scrape ran and saw the websocket holders
            assert rep["saturation"]["scrapes"] >= 2
            assert rep["saturation"]["rpc_ws_connections_max"] == 2
            # registry exposition carries the route-labeled summary
            text = net.nodes[0]._render_metrics()
            assert (
                'tendermint_tpu_rpc_request_latency_seconds_count'
                '{route="broadcast_tx_sync"}'
            ) in text
            return rep
        finally:
            await net.stop()

    rep1 = run(go())
    # the scenario spec round-trips through the report (reproducibility
    # contract: the row names its own recipe)
    assert rep1["scenario"]["seed"] == 21
    assert rep1["scenario"]["mode"] == "closed"


def test_slow_route_exemplar_capture(tmp_path, _trace_off_after):
    """A route pushed past its SLO by an injected `rpc.route` hang
    (crypto/faults) must capture a bounded, kill-switched exemplar
    carrying its span tree, and increment rpc_slow_requests_total."""

    async def go():
        trace.enable()
        trace.reset()
        trace.enable_exemplars(capacity=4)
        trace.reset_exemplars()
        net = await start_localnet(1, str(tmp_path / "slo"), seed=5)
        try:
            env_metrics = net.nodes[0].rpc_env.metrics
            env_metrics.slo_s["abci_query"] = 0.02
            c = HTTPClient(net.rpc_addrs[0])
            with faults.inject(
                "rpc.route", "hang", hang_s=0.06, key="abci_query"
            ):
                await c.call("abci_query", data="00ff")
            await c.call("status")  # under SLO: no exemplar
            exs = trace.exemplar_snapshot()
            assert len(exs) == 1
            ex = exs[0]
            assert ex["route"] == "abci_query"
            assert ex["dur_ms"] > ex["slo_ms"] == 20.0
            names = [s["name"] for s in ex["spans"]]
            assert "rpc_request" in names
            root = next(
                s for s in ex["spans"] if s["name"] == "rpc_request"
            )
            assert root["attrs"]["method"] == "abci_query"
            assert (
                env_metrics.slow_requests.value(route="abci_query") == 1
            )
            assert env_metrics.slow_requests.value(route="status") == 0

            # bounded: capacity 4 evicts oldest, never grows
            env_metrics.slo_s["status"] = 0.0
            for _ in range(7):
                await c.call("status")
            assert len(trace.exemplar_snapshot()) == 4

            # kill switch: no captures while disabled
            trace.disable_exemplars()
            before = len(trace.exemplar_snapshot())
            await c.call("status")
            assert len(trace.exemplar_snapshot()) == before
            # ... but the slow-request counter still counts
            assert env_metrics.slow_requests.value(route="status") == 8
            await c.close()
        finally:
            await net.stop()

    run(go())


def test_debug_bundle_carries_slow_request_exemplars(
    tmp_path, _trace_off_after
):
    """cmd debug packs the exemplar ring as slow_requests.json."""
    import json
    import tarfile

    from tendermint_tpu.cmd.commands import main as cmd_main

    trace.enable_exemplars(capacity=8)
    trace.reset_exemplars()
    trace.record_slow_request("block", 1.5, 1.0)
    home = tmp_path / "dbg-home"
    rc = cmd_main(["--home", str(home), "init", "validator"])
    assert rc == 0
    out = tmp_path / "bundle.tar.gz"
    rc = cmd_main(
        ["--home", str(home), "debug", "--output", str(out)]
    )
    assert rc == 0
    with tarfile.open(out) as tar:
        data = json.load(tar.extractfile("slow_requests.json"))
    assert data["slow_requests"][0]["route"] == "block"
    assert data["slow_requests"][0]["dur_ms"] == 1500.0


def test_shed_subscriber_is_notified_and_quota_freed():
    """A websocket subscriber dropped for lagging (eventbus queue
    overflow) receives a final ERR_TERMINATED error frame naming its
    query, and its slot in the per-client subscription quota is freed
    so it can re-subscribe — silence was the old behavior, and a fleet
    client can't tell silence from 'no events matched'."""
    from tendermint_tpu.pubsub import ERR_TERMINATED, SubscriptionError
    from tendermint_tpu.rpc.core import Environment

    env = Environment(
        chain_id="shed", block_store=None, state_store=None
    )

    class _Sub:
        async def next(self):
            raise SubscriptionError(ERR_TERMINATED)

    class _WS:
        client_id = "ws-shed"

        def __init__(self):
            self.sent = []
            self.closed = asyncio.Event()

        async def send_json(self, obj):
            self.sent.append(obj)

    ws = _WS()
    env._ws_subs[ws.client_id] = {"q1"}
    run(env._pump_events(ws, _Sub(), "q1", req_id=7))
    assert len(ws.sent) == 1
    err = ws.sent[0]["error"]
    assert err["message"] == ERR_TERMINATED
    assert err["data"] == "q1"
    assert env._ws_subs[ws.client_id] == set()  # quota freed


def test_loadgen_package_is_tmlive_clean():
    """Zero liveness/boundedness findings on the new package: the
    whole-program tmlive pass must neither flag nor need new
    suppressions under tendermint_tpu/loadgen/ (bounded= annotations
    are reviewed in-file)."""
    from tendermint_tpu.analysis import tmcheck, tmlive

    pkg = tmcheck.build_package()
    violations = tmlive.live_violations(pkg)
    mine = [v for v in violations if "loadgen/" in v.path]
    assert mine == [], [v.render() for v in mine]


@pytest.mark.slow
def test_sustained_open_loop_multi_node(tmp_path):
    """The BENCH_LOAD-shaped sustained run: open-loop Poisson arrivals
    against a 3-validator localnet with subscribers held throughout.
    Asserts the serving-side health the smoke can't: sustained
    committed throughput, bounded error fraction, full subscriber
    retention."""
    scn = Scenario(
        seed=2026,
        mode="open",
        duration_s=10.0,
        warmup_s=1.0,
        rate=250.0,
        ramp_s=1.0,
        subscribers=16,
        max_inflight=64,
        timeout_s=10.0,
    )
    rep = run(
        run_localnet_scenario(scn, 3, str(tmp_path / "sustained")),
        timeout=300.0,
    )
    total = rep["requests_total"]
    assert total >= 0.7 * scn.rate * (scn.duration_s - scn.ramp_s / 2)
    assert rep["errors_total"] + rep["timeouts_total"] <= 0.02 * total
    assert rep["sustained_txs_per_s"] > 50
    assert rep["committed_txs_per_s"] > 10
    assert rep["subscribers"]["held"] == 16
    assert rep["subscribers"]["events_received"] > 0
    assert rep["saturation"]["consensus_total_txs_delta"] > 0
    for op in scn.mix_ops():
        assert rep["routes"][op]["p999_ms"] > 0


def test_localnet_boot_reaches_height2_fast(tmp_path):
    """ISSUE 13 satellite regression: in-process localnet boot used to
    OCCASIONALLY take tens of seconds — every node's first dials race
    peer startup, each refused dial fed the old +10%-jitter schedule
    (0.5 s base doubling toward the 20 s persistent cap), and a few
    early failures parked a link for most of a minute. The jittered
    capped exponential backoff with FULL [d/2, d] jitter plus the
    localnet's snappy retry caps (min 0.1 s, persistent cap 2 s) bound
    the worst link at ~2 s between attempts, so a 3-node boot must
    reach height 2 (block 1 committed everywhere) well inside the
    budget."""
    import time as _time

    from tendermint_tpu.loadgen.localnet import start_localnet

    async def go():
        t0 = _time.monotonic()
        net = await start_localnet(3, str(tmp_path), chain_id="bootnet")
        try:
            return _time.monotonic() - t0
        finally:
            await net.stop()

    wall = run(go(), timeout=90.0)
    # typical is 4-10 s on this box; the bound is the regression line
    # between "jitter schedule healthy" and "a link parked on backoff"
    assert wall < 30.0, f"localnet boot took {wall:.1f}s"


def test_chaos_scenario_smoke(tmp_path):
    """One end-to-end chaos arc in tier-1 (the full shipped catalog is
    the bench chaos_smoke row): minority partition under open-loop
    traffic — safety verdict from the scraped stores, recovery within
    SLO, and the fault plane left disarmed afterwards."""
    from tendermint_tpu.loadgen import ChaosScenario, run_chaos_scenario

    cs = ChaosScenario(
        name="minority_partition",
        kind="partition",
        spec={"isolate": [2]},
        fault_s=1.5,
        baseline_s=1.0,
        recovery_slo_s=20.0,
    )
    row = run(
        run_chaos_scenario(
            cs, str(tmp_path), n_nodes=3, seed=5, rate=25.0
        ),
        timeout=180.0,
    )
    assert row["passed"], row
    assert row["safety_ok"] and row["heights_checked"] >= 1
    assert row["recovered_within_slo"]
    assert row["net_faults_applied"], "partition applied no faults"
    assert not faults.net_armed()  # the arc disarmed the plane
