"""Differential tests: device field/point ops vs pure-Python bigint oracle."""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tendermint_tpu.crypto import ed25519_math as em
from tendermint_tpu.ops import edwards as E
from tendermint_tpu.ops import field25519 as F

random.seed(42)
P = F.P_INT

# jit wrappers: eager per-op dispatch is slow on the virtual-device CPU
# platform; one compiled program per shape keeps the suite fast.
_add_cached = jax.jit(lambda p, q: E.point_add_cached(p, E.cache_point(q)))
_double = jax.jit(E.point_double)
_decompress = jax.jit(E.decompress)
_field = {
    name: jax.jit(getattr(F, name))
    for name in ("add", "sub", "mul", "neg", "sqr", "canonical", "is_zero", "eq")
}


def _pack(vals):
    # batch-minor layout: (NLIMBS, N)
    return jnp.asarray(np.stack([F.to_limbs(v) for v in vals], axis=1))


@pytest.fixture(scope="module")
def elems():
    xs = [random.randrange(P) for _ in range(16)] + [0, 1, P - 1, P - 2]
    ys = [random.randrange(P) for _ in range(20)]
    return xs, ys, _pack(xs), _pack(ys)


def _vals(arr):
    a = np.asarray(arr)
    return [F.from_limbs(a[:, i]) for i in range(a.shape[1])]


def test_field_ops(elems):
    xs, ys, A, B = elems
    assert _vals(_field['add'](A, B)) == [(x + y) % P for x, y in zip(xs, ys)]
    assert _vals(_field['sub'](A, B)) == [(x - y) % P for x, y in zip(xs, ys)]
    assert _vals(_field['mul'](A, B)) == [(x * y) % P for x, y in zip(xs, ys)]
    assert _vals(_field['neg'](A)) == [(-x) % P for x in xs]
    assert _vals(_field['sqr'](A)) == [x * x % P for x in xs]


def test_field_deep_chain(elems):
    xs, ys, A, B = elems
    C, D = A, B
    ce, de = list(xs), list(ys)
    for _ in range(4):
        C2 = F.mul(F.sub(C, D), F.add(C, D))
        ce2 = [(c - d) * (c + d) % P for c, d in zip(ce, de)]
        D, de = C, ce
        C, ce = C2, ce2
    assert _vals(C) == ce


def test_canonical_and_iszero(elems):
    xs, ys, A, B = elems
    can = np.asarray(F.canonical(_field['sub'](A, B)))
    for i, (x, y) in enumerate(zip(xs, ys)):
        val = sum(int(can[j][i]) << (13 * j) for j in range(F.NLIMBS))
        assert val == (x - y) % P
    assert bool(jnp.all(_field['is_zero'](_field['sub'](A, A))))
    assert not bool(jnp.any(_field['eq'](A, B)))


def test_pow_p58(elems):
    xs, _, A, _ = elems
    e = (P - 5) // 8
    assert _vals(jax.jit(F.pow_p58)(A)) == [pow(x, e, P) for x in xs]


def _rand_points(n):
    pts = []
    for _ in range(n):
        k = random.randrange(1, em.L)
        pts.append(em.scalar_mult(k, em.B_POINT))
    return pts


def _pack_points(pts):
    arrs = []
    for pt in pts:
        X, Y, Z, _ = pt
        zinv = pow(Z, P - 2, P)
        x, y = X * zinv % P, Y * zinv % P
        arrs.append(E.pack_point(x, y))
    # (N, 4, L) -> batch-minor (4, L, N)
    return jnp.asarray(np.stack(arrs, axis=2))


def _affine(dev_pts):
    """Device extended points (4, L, N) -> list of affine (x, y) ints."""
    a = np.asarray(F.canonical(jnp.asarray(dev_pts)))
    out = []
    for i in range(a.shape[-1]):
        X = sum(int(a[0][j][i]) << (13 * j) for j in range(F.NLIMBS))
        Y = sum(int(a[1][j][i]) << (13 * j) for j in range(F.NLIMBS))
        Z = sum(int(a[2][j][i]) << (13 * j) for j in range(F.NLIMBS))
        zi = pow(Z, P - 2, P)
        out.append((X * zi % P, Y * zi % P))
    return out


def _affine_ref(pt):
    X, Y, Z, _ = pt
    zi = pow(Z, P - 2, P)
    return (X * zi % P, Y * zi % P)


def test_point_add_double():
    ps = _rand_points(6)
    qs = _rand_points(6)
    dp, dq = _pack_points(ps), _pack_points(qs)
    got = _affine(_add_cached(dp, dq))
    expect = [_affine_ref(em.point_add(p, q)) for p, q in zip(ps, qs)]
    assert got == expect
    got2 = _affine(_double(dp))
    assert got2 == [_affine_ref(em.point_double(p)) for p in ps]
    # negate + identity checks
    got3 = _affine(_add_cached(dp, E.negate(dp)))
    ident = np.asarray(
        E.is_identity(_add_cached(dp, E.negate(dp)))
    )
    assert ident.all()
    # adding the identity leaves the point unchanged
    idp = E.identity(6)
    assert _affine(_add_cached(dp, idp)) == [
        _affine_ref(p) for p in ps
    ]


def test_decompress_matches_oracle():
    pts = _rand_points(5)
    raw = [em.compress(p) for p in pts]
    ys, signs = [], []
    for r in raw:
        yi = int.from_bytes(r, "little")
        signs.append(yi >> 255)
        ys.append(yi & ((1 << 255) - 1))
    y_arr = _pack(ys)
    s_arr = jnp.asarray(np.array(signs, dtype=np.int32))
    dev_pts, ok = _decompress(y_arr, s_arr)
    assert np.asarray(ok).all()
    assert _affine(dev_pts) == [_affine_ref(p) for p in pts]
    # invalid encodings rejected: y with no sqrt
    bad_y = 2  # x^2 = (4-1)/(4d+1): overwhelmingly non-square for y=2
    dev_pts2, ok2 = E.decompress(_pack([bad_y]), jnp.asarray(np.array([0], np.int32)))
    assert bool(np.asarray(ok2)[0]) == (em.decompress((2).to_bytes(32, "little")) is not None)


# -- adversarial limb-envelope contract --
#
# The carry schedule's int32-safety argument (see F.mul/F.sqr/F.carry
# docstrings) rests on every mul/sqr input being loose-normalized:
# limbs in [-2^11, 2^13 + 2^11). These tests feed the EXTREMES of that
# envelope — not just random canonical values — so any future
# carry-pass tightening that silently narrows the accepted envelope
# fails here instead of corrupting a rare verification.


def _env_cases():
    """(NLIMBS, N) batches of worst-case loose-normal limb vectors.

    Individual limbs hit both envelope extremes, but every column's
    VALUE is kept nonnegative (top limb pinned high): the carry
    schedule's dropped-top-carry argument in F.mul/F.sqr only holds for
    nonnegative operand values, which is the program's invariant — the
    +2p biases in add/sub/neg/point ops keep every representative's
    value >= 0 even when single limbs go negative."""
    lo, hi = -(1 << 11), (1 << 13) + (1 << 11) - 1
    rng = np.random.default_rng(7)
    alt0 = np.where(np.arange(F.NLIMBS) % 2 == 0, hi, lo)
    alt1 = np.where(np.arange(F.NLIMBS) % 2 == 1, hi, lo)
    alt0[-1] = alt1[-1] = hi
    cols = [np.full(F.NLIMBS, hi), alt0, alt1]
    for _ in range(13):
        c = rng.choice(np.array([lo, hi, 0, 1, -1]), F.NLIMBS)
        c[-1] = hi  # dominates the worst negative lower-limb sum
        cols.append(c)
    out = np.stack(cols, axis=1).astype(np.int32)
    assert all(_limb_value(out[:, j]) >= 0 for j in range(out.shape[1]))
    return out


def _limb_value(col):
    return sum(int(v) << (F.RADIX * i) for i, v in enumerate(col))


def test_mul_sqr_envelope_extremes():
    batch = _env_cases()
    vals = [_limb_value(batch[:, j]) for j in range(batch.shape[1])]
    a = jnp.asarray(batch)
    got_sqr = np.asarray(_field["sqr"](a))
    got_mul = np.asarray(_field["mul"](a, a[:, ::-1].copy()))
    for j in range(batch.shape[1]):
        want_sq = vals[j] * vals[j] % P
        assert _limb_value(got_sqr[:, j]) % P == want_sq, f"sqr col {j}"
        want_mul = vals[j] * vals[batch.shape[1] - 1 - j] % P
        assert _limb_value(got_mul[:, j]) % P == want_mul, f"mul col {j}"
    # outputs must land back inside the loose-normal envelope, or the
    # NEXT mul's int32-safety argument breaks
    lo, hi = -(1 << 11), (1 << 13) + (1 << 11)
    for out in (got_sqr, got_mul):
        assert out.min() >= lo and out.max() < hi


def test_carry_output_envelope():
    """F.carry's documented output envelope over extreme raw inputs
    (|x| < 2^17ish — the post-add/sub magnitude it claims to accept)."""
    rng = np.random.default_rng(11)
    x = rng.integers(-(1 << 17), 1 << 17, size=(F.NLIMBS, 64)).astype(
        np.int32
    )
    out = np.asarray(jax.jit(F.carry)(jnp.asarray(x)))
    vals_in = [_limb_value(x[:, j]) for j in range(64)]
    lo, hi = -(1 << 11), (1 << 13) + (1 << 11)
    assert out.min() >= lo and out.max() < hi
    for j in range(64):
        assert _limb_value(out[:, j]) % P == vals_in[j] % P
