"""Pubsub query language, server fan-out, and event bus tests
(reference test model: internal/pubsub/pubsub_test.go,
internal/pubsub/query/query_test.go, internal/eventbus/event_bus_test.go)."""

import asyncio

import pytest

from tendermint_tpu.abci import types as abci
from tendermint_tpu.eventbus import EventBus
from tendermint_tpu.pubsub import (
    Server,
    SubscriptionError,
    compile_query,
)
from tendermint_tpu.pubsub.query import QuerySyntaxError, query_for_event
from tendermint_tpu.types import events as E


def run(coro):
    return asyncio.run(coro)


# ---------------------------------------------------------------------------
# query language


@pytest.mark.parametrize(
    "query,tags,want",
    [
        ("tm.event = 'Tx'", {"tm.event": ["Tx"]}, True),
        ("tm.event = 'Tx'", {"tm.event": ["NewBlock"]}, False),
        ("tm.event = 'Tx'", {}, False),
        ("tx.height = 5", {"tx.height": ["5"]}, True),
        ("tx.height = 5", {"tx.height": ["6"]}, False),
        ("tx.height < 10", {"tx.height": ["5"]}, True),
        ("tx.height <= 5", {"tx.height": ["5"]}, True),
        ("tx.height > 100", {"tx.height": ["99"]}, False),
        ("tx.height >= 99", {"tx.height": ["99"]}, True),
        # multi-valued tags: any value matching suffices
        ("app.key = 'k2'", {"app.key": ["k1", "k2"]}, True),
        ("app.key CONTAINS 'arti'", {"app.key": ["particle"]}, True),
        ("app.key CONTAINS 'arti'", {"app.key": ["art-free"]}, False),
        ("app.key EXISTS", {"app.key": ["x"]}, True),
        ("app.key EXISTS", {"other": ["x"]}, False),
        (
            "tm.event = 'Tx' AND tx.height = 5",
            {"tm.event": ["Tx"], "tx.height": ["5"]},
            True,
        ),
        (
            "tm.event = 'Tx' AND tx.height = 5",
            {"tm.event": ["Tx"], "tx.height": ["7"]},
            False,
        ),
        # non-numeric values never match numeric comparisons
        ("tx.height > 1", {"tx.height": ["abc"]}, False),
    ],
)
def test_query_matches(query, tags, want):
    assert compile_query(query).matches(tags) is want


@pytest.mark.parametrize(
    "bad",
    [
        "",
        "AND",
        "tag =",
        "= 'x'",
        "tag CONTAINS 5",
        "tag < 'str'",
        "a = 'x' AND",
        "a = 'x' b = 'y'",
    ],
)
def test_query_syntax_errors(bad):
    with pytest.raises(QuerySyntaxError):
        compile_query(bad)


def test_query_for_event():
    q = query_for_event("NewBlock")
    assert q.matches({"tm.event": ["NewBlock"]})
    assert not q.matches({"tm.event": ["Tx"]})


# ---------------------------------------------------------------------------
# pubsub server


def test_pubsub_fanout_and_unsubscribe():
    async def go():
        s = Server()
        await s.start()
        sub_tx = s.subscribe("c1", "tm.event = 'Tx'")
        sub_all = s.subscribe("c2", "tm.event EXISTS")

        s.publish("block-data", {"tm.event": ["NewBlock"]})
        s.publish("tx-data", {"tm.event": ["Tx"]})

        msg = await sub_tx.next()
        assert msg.data == "tx-data"
        first = await sub_all.next()
        second = await sub_all.next()
        assert [first.data, second.data] == ["block-data", "tx-data"]

        s.unsubscribe("c1", "tm.event = 'Tx'")
        with pytest.raises(SubscriptionError):
            s.unsubscribe("c1", "tm.event = 'Tx'")
        assert s.num_clients() == 1
        await s.stop()

    run(go())


def test_pubsub_slow_subscriber_terminated():
    async def go():
        s = Server()
        await s.start()
        sub = s.subscribe("slow", "tm.event EXISTS", limit=2)
        for _ in range(3):  # overflow the 2-slot buffer
            s.publish("x", {"tm.event": ["Tx"]})
        # buffered messages still drain, then the subscription errors out
        await sub.next()
        await sub.next()
        with pytest.raises(SubscriptionError):
            await sub.next()
        # server dropped it
        assert s.num_clients() == 0
        await s.stop()

    run(go())


def test_pubsub_duplicate_subscribe_rejected():
    async def go():
        s = Server()
        await s.start()
        s.subscribe("c", "tm.event = 'Tx'")
        with pytest.raises(SubscriptionError):
            s.subscribe("c", "tm.event = 'Tx'")
        await s.stop()

    run(go())


# ---------------------------------------------------------------------------
# event bus


class _Hdr:
    height = 7


class _Blk:
    header = _Hdr()


def test_eventbus_tx_tags_and_app_events():
    async def go():
        bus = EventBus()
        await bus.start()
        sub = bus.subscribe("test", "tm.event = 'Tx' AND app.creator = 'kvstore'")
        other = bus.subscribe("test", "tm.event = 'Tx' AND app.creator = 'nobody'")

        result = abci.ResponseDeliverTx(
            events=(
                abci.Event(
                    type="app",
                    attributes=(abci.EventAttribute(b"creator", b"kvstore", True),),
                ),
            )
        )
        bus.publish_tx(
            E.EventDataTx(height=7, tx=b"a=1", index=0, result=result),
            tx_hash=b"\xab" * 32,
        )
        msg = await sub.next()
        assert msg.events[E.TX_HEIGHT_KEY] == ["7"]
        assert msg.events[E.TX_HASH_KEY] == ["AB" * 32]
        assert msg.data.height == 7
        assert other._queue.empty()
        await bus.stop()

    run(go())


def test_eventbus_new_block_and_round_steps():
    async def go():
        bus = EventBus()
        await bus.start()
        sub_nb = bus.subscribe("t", query_for_event(E.EventValue.NEW_BLOCK))
        sub_step = bus.subscribe("t", query_for_event(E.EventValue.NEW_ROUND_STEP))

        bus.publish_new_block(
            E.EventDataNewBlock(block=_Blk(), block_id=None)
        )
        bus.publish_new_round_step(
            E.EventDataRoundState(height=7, round=0, step="propose")
        )
        nb = await sub_nb.next()
        assert nb.events[E.BLOCK_HEIGHT_KEY] == ["7"]
        st = await sub_step.next()
        assert st.data.step == "propose"
        bus.unsubscribe_all("t")
        await bus.stop()

    run(go())


def test_pubsub_next_wakes_on_terminate():
    """A consumer blocked in next() must wake promptly when its
    subscription is terminated (no 0.5s polling)."""
    import time as _time
    from tendermint_tpu.pubsub import Server, SubscriptionError

    async def go():
        srv = Server()
        sub = srv.subscribe("c", "tm.event = 'Tx'", limit=2)

        async def consume():
            try:
                await sub.next()
            except SubscriptionError as e:
                return str(e)

        task = asyncio.get_event_loop().create_task(consume())
        await asyncio.sleep(0.01)  # let consumer block in next()
        t0 = _time.monotonic()
        srv.unsubscribe("c", "tm.event = 'Tx'")
        reason = await asyncio.wait_for(task, timeout=1.0)
        assert _time.monotonic() - t0 < 0.2
        assert reason == "unsubscribed"

    run(go())
