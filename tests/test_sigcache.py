"""Verified-signature cache (crypto/sigcache): safety and bounds.

The cache may only ever skip work a fresh verify would repeat — any
byte difference (forged signature, mutated sign-bytes, an equivocating
vote's other block) is a miss by construction, and every error the
uncached paths raise must be byte-identical with the cache warm, cold,
and disabled. The counting-stub smoke test is the CI tripwire the
bench can't be: a warm verify_commit must perform ZERO underlying
signature verifications (and the expected N when disabled), so a cache
regression fails the suite rather than a bench row.
"""

import pytest

from tendermint_tpu.crypto import sigcache
from tendermint_tpu.crypto.ed25519 import Ed25519BatchVerifier, PubKeyEd25519
from tendermint_tpu.types import (
    PRECOMMIT_TYPE,
    InvalidCommitError,
    VoteSet,
    verify_commit,
)
from tendermint_tpu.types.validation import verify_triples_grouped
from tendermint_tpu.types.vote_set import ConflictingVoteError

from .test_types import (
    CHAIN_ID,
    make_block_id,
    make_validators,
    signed_vote,
)
from .test_validation import make_commit


@pytest.fixture(autouse=True)
def fresh_cache():
    """Each test starts cold and restores the default capacity."""
    sigcache.reset()
    sigcache.set_capacity(sigcache.DEFAULT_CAPACITY)
    yield
    sigcache.reset()
    sigcache.set_capacity(sigcache.DEFAULT_CAPACITY)


class CountingStub:
    """Counts underlying signature verifications through both seams:
    single verifies (PubKeyEd25519.verify_signature) and batch drains
    (Ed25519BatchVerifier.verify, counted per queued item)."""

    def __init__(self, monkeypatch):
        self.singles = 0
        self.batched = 0
        stub = self
        real_single = PubKeyEd25519.verify_signature
        real_batch = Ed25519BatchVerifier.verify

        def counting_single(pk_self, msg, sig):
            stub.singles += 1
            return real_single(pk_self, msg, sig)

        def counting_batch(bv_self):
            stub.batched += len(bv_self._items)
            return real_batch(bv_self)

        monkeypatch.setattr(
            PubKeyEd25519, "verify_signature", counting_single
        )
        monkeypatch.setattr(Ed25519BatchVerifier, "verify", counting_batch)

    @property
    def total(self):
        return self.singles + self.batched

    def reset(self):
        self.singles = 0
        self.batched = 0


# -- cache mechanics --


def test_exact_triple_keying():
    pk, sb, sig = b"\x01" * 32, b"sign-bytes", b"\x02" * 64
    sigcache.add(pk, sb, sig)
    assert sigcache.seen(pk, sb, sig)
    # any byte difference in any component is a miss
    assert not sigcache.seen(b"\x03" + pk[1:], sb, sig)
    assert not sigcache.seen(pk, sb + b"x", sig)
    assert not sigcache.seen(pk, sb, sig[:-1] + b"\x00")


def test_component_boundaries_unambiguous():
    """Shifting bytes between sign_bytes and signature (or pubkey) must
    never alias: the key length-prefixes the fixed-size components."""
    sigcache.add(b"\x01" * 32, b"ab", b"\x02" * 64)
    assert not sigcache.seen(b"\x01" * 32, b"a", b"b" + b"\x02" * 63)


def test_generation_rotation_is_bounded():
    sigcache.set_capacity(100)
    base = sigcache.stats()["evictions"]
    for i in range(1000):
        sigcache.add(b"\x01" * 32, b"msg-%d" % i, b"\x02" * 64)
    # two generations of at most `capacity` entries each
    assert sigcache.entries() <= 200
    assert sigcache.stats()["evictions"] > base


def test_promotion_survives_rotation():
    """A stable signer set's triples outlive rotation: a hit in the old
    generation is promoted into the young one."""
    sigcache.set_capacity(10)
    hot = (b"\x07" * 32, b"hot-triple", b"\x08" * 64)
    sigcache.add(*hot)
    for i in range(200):
        sigcache.add(b"\x01" * 32, b"churn-%d" % i, b"\x02" * 64)
        assert sigcache.seen(*hot)  # each consult re-promotes


def test_env_gate_disables(monkeypatch):
    monkeypatch.setenv("TM_TPU_NO_SIGCACHE", "1")
    assert not sigcache.enabled()
    sigcache.add(b"\x01" * 32, b"m", b"\x02" * 64)
    assert not sigcache.seen(b"\x01" * 32, b"m", b"\x02" * 64)
    assert sigcache.entries() == 0


def test_disabled_scope():
    with sigcache.disabled():
        assert not sigcache.enabled()
    assert sigcache.enabled()


# -- bulk API: one set-intersection replaces the per-triple probes --


def test_bulk_probe_hits_and_promotes():
    keys = [(b"\x01" * 32, b"msg-%d" % i, b"\x02" * 64) for i in range(6)]
    for k in keys[:3]:
        sigcache.add_key(k)
    hits = sigcache.seen_keys_bulk(keys)
    assert hits == set(keys[:3])
    # old-generation hits are promoted, like seen_key
    sigcache.set_capacity(4)
    sigcache.reset()
    hot = (b"\x07" * 32, b"hot", b"\x08" * 64)
    sigcache.add_key(hot)
    for i in range(20):
        sigcache.add_key((b"\x01" * 32, b"churn-%d" % i, b"\x02" * 64))
        assert sigcache.seen_keys_bulk([hot]) == {hot}  # re-promoted
    assert sigcache.seen_keys_bulk([]) == set()


def test_bulk_add_respects_generation_bound():
    sigcache.set_capacity(100)
    base = sigcache.stats()["evictions"]
    for start in range(0, 1000, 250):
        sigcache.add_keys_bulk(
            (b"\x01" * 32, b"bulk-%d" % i, b"\x02" * 64)
            for i in range(start, start + 250)
        )
        # the documented bound survives bulk drains bigger than a
        # whole generation: at most 2 x capacity resident
        assert sigcache.entries() <= 200
    assert sigcache.stats()["evictions"] > base


def test_commit_memo_gates():
    key = ("commit-memo", "chain", True, True, 1, object(), object(), b"")
    sigcache.add_commit(key)
    assert sigcache.seen_commit(key)
    with sigcache.commit_memo_disabled():
        assert not sigcache.commit_memo_enabled()
        assert not sigcache.seen_commit(key)  # probe disabled
        sigcache.add_commit(key)  # insert dropped silently
    assert sigcache.seen_commit(key)
    with sigcache.disabled():  # the cache-wide gate covers commit keys
        assert not sigcache.commit_memo_enabled()
        assert not sigcache.seen_commit(key)


def test_commit_memo_env_gate(monkeypatch):
    monkeypatch.setenv("TM_TPU_NO_COMMIT_MEMO", "1")
    assert sigcache.enabled()  # triples unaffected
    assert not sigcache.commit_memo_enabled()


# -- safety: failures never cached, errors identical warm/cold/disabled --


def test_forged_signature_never_hits():
    vals, bid, commit = make_commit(4)
    verify_commit(CHAIN_ID, vals, bid, 1, commit)  # warm the good sigs
    forged = bytearray(commit.signatures[2].signature)
    forged[0] ^= 0xFF
    commit.signatures[2].signature = bytes(forged)
    # the forged triple differs in bytes -> miss -> real verify -> fail,
    # warm or not, and the failure is never inserted
    for _ in range(2):
        with pytest.raises(InvalidCommitError, match=r"#2"):
            verify_commit(CHAIN_ID, vals, bid, 1, commit)
    sb = commit.vote_sign_bytes(CHAIN_ID, 2)
    assert not sigcache.seen_key(
        sigcache.key_for(
            vals.validators[2].pub_key.bytes(),
            sb,
            commit.signatures[2].signature,
        )
    )


def test_mutated_sign_bytes_never_hit():
    vals, bid, commit = make_commit(4)
    verify_commit(CHAIN_ID, vals, bid, 1, commit)
    # same signatures presented over different sign-bytes (wrong chain)
    # must all miss and fail verification
    with pytest.raises(InvalidCommitError, match="wrong signature"):
        verify_commit("other-chain", vals, bid, 1, commit)


def test_wrong_signature_error_identical_warm_cold_disabled():
    """The `wrong signature (#i)` index attribution must not depend on
    cache state: warm (good sigs cached), cold, and disabled runs all
    raise the same error."""
    vals, bid, commit = make_commit(4)
    forged = bytearray(commit.signatures[1].signature)
    forged[3] ^= 0x10
    commit.signatures[1].signature = bytes(forged)

    def error_text():
        with pytest.raises(InvalidCommitError) as ei:
            verify_commit(CHAIN_ID, vals, bid, 1, commit)
        return str(ei.value)

    cold = error_text()
    warm = error_text()  # good sigs were cached by the cold attempt
    sigcache.reset()
    with sigcache.disabled():
        off = error_text()
    assert cold == warm == off
    assert "wrong signature (#1)" in cold


def test_equivocating_vote_conflict_identical():
    """An equivocating vote (same validator, different block) is a
    different triple — never a hit — and ConflictingVoteError fires
    identically warm, cold, and disabled."""

    def run():
        vals, privs = make_validators(4)
        vs = VoteSet(CHAIN_ID, 1, 0, PRECOMMIT_TYPE, vals)
        a = signed_vote(privs[0], vals, 0, make_block_id(b"\x0a"))
        b = signed_vote(privs[0], vals, 0, make_block_id(b"\x0b"))
        assert vs.add_vote(a)
        with pytest.raises(ConflictingVoteError) as ei:
            vs.add_vote(b)
        return str(ei.value)

    cold = run()
    warm = run()  # both triples cached by the first pass
    with sigcache.disabled():
        off = run()
    assert cold == warm == off


# -- the CI tripwire: warm commits do zero crypto --


def test_warm_verify_commit_does_zero_signature_verifications(monkeypatch):
    stub = CountingStub(monkeypatch)
    vals, bid, commit = make_commit(5)
    n_sigs = 5
    verify_commit(CHAIN_ID, vals, bid, 1, commit)
    assert stub.batched == n_sigs  # cold: every signature verified
    stub.reset()
    verify_commit(CHAIN_ID, vals, bid, 1, commit)
    assert stub.total == 0  # warm: a hash scan, no crypto at all
    # disabled: the full N again, through the same code path
    stub.reset()
    with sigcache.disabled():
        verify_commit(CHAIN_ID, vals, bid, 1, commit)
    assert stub.batched == n_sigs


def test_warm_vote_set_ingest_does_zero_verifications(monkeypatch):
    """add_vote after verify-ahead population: Vote.verify hits the
    cache (the cross-stage half: gossip-verify warms LastCommit and
    vice versa)."""
    stub = CountingStub(monkeypatch)
    vals, privs = make_validators(4)
    bid = make_block_id(b"\x0c")
    votes = [signed_vote(p, vals, i, bid) for i, p in enumerate(privs)]
    # populate as _preverify_votes would (batch verify + cache insert)
    from tendermint_tpu.crypto.batch import (
        create_batch_verifier,
        drain_and_cache,
    )

    bv = create_batch_verifier(privs[0].pub_key(), size_hint=4)
    keys = []
    for v, p in zip(votes, privs):
        sb = v.sign_bytes(CHAIN_ID)
        bv.add(p.pub_key(), sb, v.signature)
        keys.append(sigcache.key_for(p.pub_key().bytes(), sb, v.signature))
    ok, _ = drain_and_cache(bv, keys)
    assert ok
    stub.reset()
    vs = VoteSet(CHAIN_ID, 1, 0, PRECOMMIT_TYPE, vals)
    for v in votes:
        assert vs.add_vote(v)
    assert stub.total == 0


def test_merged_triples_warm_and_group_sized(monkeypatch):
    """verify_triples_grouped consults before assembly (second call is
    crypto-free) and sizes each per-type batch to its own group, not
    the merged total."""
    hints = []
    from tendermint_tpu.crypto import batch as crypto_batch
    from tendermint_tpu.types import validation

    real_create = crypto_batch.create_batch_verifier

    def spying_create(pk, size_hint=0):
        hints.append((pk.type(), size_hint))
        return real_create(pk, size_hint=size_hint)

    monkeypatch.setattr(
        validation, "create_batch_verifier", spying_create
    )
    vals, privs = make_validators(3)
    bid = make_block_id(b"\x0d")
    triples = []
    for i, p in enumerate(privs):
        v = signed_vote(p, vals, i, bid)
        triples.append(
            (p.pub_key(), v.sign_bytes(CHAIN_ID), v.signature)
        )
    try:
        from tendermint_tpu.crypto.sr25519 import PrivKeySr25519

        sr = PrivKeySr25519.from_seed(b"\x31" * 32)
        msg = b"merged-group-msg"
        triples.append((sr.pub_key(), msg, sr.sign(msg)))
    except ImportError:
        sr = None
    verify_triples_grouped(triples)
    # each group's bucket pads to its own size, not len(triples)
    want = {("ed25519", 3)}
    if sr is not None:
        want.add(("sr25519", 1))
    assert set(hints) == want
    # warm: no verifier is even created
    hints.clear()
    stub = CountingStub(monkeypatch)
    verify_triples_grouped(triples)
    assert hints == [] and stub.total == 0


def test_bounded_over_many_heights():
    """The acceptance bound: heights of churn never grow the cache past
    two generations (the 100-height localnet shape, compressed)."""
    sigcache.set_capacity(100)
    vals, privs = make_validators(4)
    for height in range(1, 101):
        bid = make_block_id(bytes([height]))
        vs = VoteSet(CHAIN_ID, height, 0, PRECOMMIT_TYPE, vals)
        for i, p in enumerate(privs):
            vs.add_vote(signed_vote(p, vals, i, bid, height=height))
        commit = vs.make_commit()
        verify_commit(CHAIN_ID, vals, bid, height, commit)
        assert sigcache.entries() <= 200  # 2 generations x capacity
