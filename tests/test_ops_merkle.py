"""Differential tests: device SHA-256/merkle kernels vs the CPU oracle
(the reference's own batch-vs-single equivalence pattern,
types/validation.go:146-148, applied to crypto/merkle)."""

import hashlib
import random

import numpy as np
import pytest

import jax.numpy as jnp

from tendermint_tpu.crypto import merkle
from tendermint_tpu.ops import merkle_kernel as MK
from tendermint_tpu.ops import sha256_kernel as SK

random.seed(99)


def _rand(n: int) -> bytes:
    return bytes(random.randrange(256) for _ in range(n))


def _cols(items):
    return jnp.asarray(
        np.frombuffer(b"".join(items), dtype=np.uint8).reshape(
            len(items), -1
        ).T
    )


class TestSha256Kernel:
    @pytest.mark.parametrize("length", [0, 1, 32, 55, 56, 64, 65, 119, 200])
    def test_matches_hashlib_across_padding_boundaries(self, length):
        msgs = [_rand(length) for _ in range(7)]
        got = np.asarray(SK.sha256_fixed(_cols(msgs) if length else
                                         jnp.zeros((0, 7), jnp.uint8)))
        for i, m in enumerate(msgs):
            assert got[:, i].tobytes() == hashlib.sha256(m).digest()

    def test_unrolled_compress_matches_scan_form(self):
        """The TPU trace-time form (_compress_unrolled) against the CPU
        scan form over random states/blocks — the unrolled branch never
        traces on the CPU backend, so cover its math directly."""
        rng = np.random.default_rng(5)
        state = jnp.asarray(
            rng.integers(0, 2**32, (8, 9), dtype=np.uint32)
        )
        block = jnp.asarray(
            rng.integers(0, 2**32, (16, 9), dtype=np.uint32)
        )
        got = np.asarray(SK._compress_unrolled(state, block))
        want = np.asarray(SK._compress(state, block))
        assert (got == want).all()

    def test_leaf_and_inner_prefixes(self):
        leaves = [_rand(40) for _ in range(5)]
        got = np.asarray(SK.leaf_hash_batch(_cols(leaves)))
        for i, leaf in enumerate(leaves):
            assert got[:, i].tobytes() == merkle.leaf_hash(leaf)
        lefts = [_rand(32) for _ in range(5)]
        rights = [_rand(32) for _ in range(5)]
        got = np.asarray(SK.inner_hash_batch(_cols(lefts), _cols(rights)))
        for i in range(5):
            assert got[:, i].tobytes() == merkle.inner_hash(
                lefts[i], rights[i]
            )


class TestTreeRoot:
    @pytest.mark.parametrize("n", [1, 2, 3, 4, 5, 7, 8, 13, 64, 100, 257])
    def test_matches_cpu_tree_shape(self, n):
        """Pairwise level reduction must reproduce the reference's
        split-point tree for every size, power of two or not."""
        items = [_rand(random.randrange(1, 80)) for _ in range(n)]
        want = merkle.hash_from_byte_slices(items)
        got = MK.tree_root([merkle.leaf_hash(it) for it in items])
        assert got == want


class TestProofVerification:
    def test_batch_verify_valid_and_corrupted(self):
        items = [b"item-%d" % i for i in range(37)]
        root, proofs = merkle.proofs_from_byte_slices(items)
        # all valid
        bitmap = MK.verify_proofs(proofs, root)
        assert bitmap.all() and len(bitmap) == 37
        # corrupt one aunt, one leaf hash, one index
        proofs[5].aunts[0] = bytes(32)
        proofs[11].leaf_hash = bytes(32)
        proofs[20].index = 21
        bitmap = MK.verify_proofs(proofs, root)
        expect = np.ones(37, dtype=bool)
        expect[[5, 11, 20]] = False
        # index 21 now carries proof-of-20's aunts: wrong root
        assert (bitmap == expect).all(), np.nonzero(bitmap != expect)

    def test_mixed_depths_one_program(self):
        """Proofs from trees of different sizes (different depths) pad
        into one scan."""
        items_a = [b"a%d" % i for i in range(3)]
        items_b = [b"b%d" % i for i in range(64)]
        root_a, proofs_a = merkle.proofs_from_byte_slices(items_a)
        root_b, proofs_b = merkle.proofs_from_byte_slices(items_b)
        assert MK.verify_proofs(proofs_a, root_a).all()
        assert MK.verify_proofs(proofs_b, root_b).all()
        # cross-root check fails
        assert not MK.verify_proofs(proofs_a, root_b).any()

    def test_structurally_invalid_reported_false(self):
        items = [b"x%d" % i for i in range(8)]
        root, proofs = merkle.proofs_from_byte_slices(items)
        proofs[2].total = 0
        proofs[3].aunts = proofs[3].aunts[:-1]  # wrong depth
        bitmap = MK.verify_proofs(proofs, root)
        expect = np.ones(8, dtype=bool)
        expect[[2, 3]] = False
        assert (bitmap == expect).all()


class TestInstallGate:
    def test_hash_from_byte_slices_routes_large_lists(self):
        items = [b"tx-%d" % i for i in range(600)]
        want_cpu = merkle.hash_from_byte_slices(items)
        MK.install(min_leaves=512)
        try:
            before = MK.stats()["roots"]
            got = merkle.hash_from_byte_slices(items)
            assert got == want_cpu
            assert MK.stats()["roots"] == before + 1
            # small lists stay on CPU
            small = [b"s%d" % i for i in range(4)]
            r = merkle.hash_from_byte_slices(small)
            assert MK.stats()["roots"] == before + 1
            assert r == merkle.hash_from_byte_slices(small)
        finally:
            MK.uninstall()

    def test_verify_proofs_batch_seam(self):
        items = [b"p%d" % i for i in range(80)]
        root, proofs = merkle.proofs_from_byte_slices(items)
        MK.install(min_leaves=16)
        try:
            bitmap = merkle.verify_proofs_batch(proofs, root, items)
            assert bitmap.all()
            # a tampered LEAF (not just proof) is caught by the
            # leaf-hash pre-check
            items2 = list(items)
            items2[7] = b"tampered"
            bitmap = merkle.verify_proofs_batch(proofs, root, items2)
            assert not bitmap[7] and bitmap.sum() == 79
        finally:
            MK.uninstall()
