import asyncio

import pytest

from tendermint_tpu.libs.bits import BitArray
from tendermint_tpu.libs.service import Service, ServiceError


def test_bitarray_basic():
    ba = BitArray(10)
    assert ba.is_empty() and not ba.is_full()
    assert ba.set(3) and ba.set(9)
    assert not ba.set(10)  # out of range
    assert ba.get(3) and not ba.get(4)
    assert ba.count() == 2
    assert list(ba.indices()) == [3, 9]
    assert ba.pick_random() in (3, 9)


def test_bitarray_algebra():
    a, b = BitArray(8), BitArray(8)
    a.set(1), a.set(2)
    b.set(2), b.set(3)
    assert list(a.or_(b).indices()) == [1, 2, 3]
    assert list(a.and_(b).indices()) == [2]
    assert list(a.sub(b).indices()) == [1]
    assert a.not_().count() == 6
    full = BitArray(4)
    for i in range(4):
        full.set(i)
    assert full.is_full()


def test_bitarray_words_roundtrip():
    ba = BitArray(130)
    for i in (0, 63, 64, 129):
        ba.set(i)
    again = BitArray.from_words(130, ba.to_words())
    assert again == ba


def test_bitarray_wire_roundtrip_with_zero_middle_word():
    """Regression (tmsafe PR): the old per-word `w.uint(2, word)`
    encoding reused the SINGULAR writer, whose proto3 zero-omission
    dropped all-zero middle words — bit 190 silently became bit 126
    once a word went quiet. Packed elems have no zero-omission."""
    from tendermint_tpu.consensus.msgs import (
        decode_bit_array,
        encode_bit_array,
    )

    ba = BitArray(200)
    ba.set(3)
    ba.set(190)  # word 2; word 1 stays all-zero
    dec = decode_bit_array(encode_bit_array(ba))
    assert dec == ba
    assert dec.get(190) and not dec.get(126)
    # all-zero and empty arrays round-trip too
    for size in (0, 100):
        z = BitArray(size)
        assert decode_bit_array(encode_bit_array(z)) == z


def test_bitarray_legacy_unpacked_words_still_decode():
    """Pre-packed WAL records carry per-word varint fields; the decoder
    keeps accepting them."""
    from tendermint_tpu.consensus.msgs import decode_bit_array
    from tendermint_tpu.encoding.proto import ProtoWriter

    w = ProtoWriter()
    w.int(1, 128)
    w.uint(2, 5)
    w.uint(2, 7)
    leg = decode_bit_array(w.finish())
    assert leg.to_words() == [5, 7]


def test_bitarray_from_words_rejects_unclamped_wire_size():
    """Regression (tmsafe first-run true positive): `bits` is an
    attacker-chosen varint and every BitArray op masks with
    `(1 << size) - 1` — ten wire bytes must not buy a 2**60-bit
    bigint allocation."""
    from tendermint_tpu.consensus.msgs import decode_bit_array
    from tendermint_tpu.encoding.proto import ProtoWriter
    from tendermint_tpu.libs.bits import MAX_BIT_ARRAY_SIZE

    with pytest.raises(ValueError, match="MAX_BIT_ARRAY_SIZE"):
        BitArray.from_words(MAX_BIT_ARRAY_SIZE + 1, [])
    w = ProtoWriter()
    w.int(1, 1 << 60)
    with pytest.raises(ValueError, match="MAX_BIT_ARRAY_SIZE"):
        decode_bit_array(w.finish())
    # the bound itself is fine
    assert BitArray.from_words(MAX_BIT_ARRAY_SIZE, []).size == (
        MAX_BIT_ARRAY_SIZE
    )


def test_bitarray_from_words_rejects_word_flood_and_stays_linear():
    """Review finding (this PR): clamping `size` alone still let a
    hostile packed elems field buy quadratic bigint work — 52k words
    against bits=100 cost ~9.5 s under the old per-word `|=` loop.
    The word count is now bounded by ceil(size/64) and assembly is a
    single linear int.from_bytes."""
    import time

    from tendermint_tpu.libs.bits import MAX_BIT_ARRAY_SIZE

    with pytest.raises(ValueError, match="words exceed size"):
        BitArray.from_words(100, [1] * 52_000)
    # legal worst case — a full MAX-size array — assembles fast
    n_words = (MAX_BIT_ARRAY_SIZE + 63) // 64
    t0 = time.monotonic()
    out = BitArray.from_words(MAX_BIT_ARRAY_SIZE, [1] * n_words)
    assert time.monotonic() - t0 < 1.0
    assert out.get(0) and out.get(64 * (n_words - 1))
    # words past uint64 are a parse error, not an OverflowError
    with pytest.raises(ValueError, match="uint64"):
        BitArray.from_words(128, [1 << 64])


class _Svc(Service):
    def __init__(self):
        super().__init__("test")
        self.ticks = 0

    async def on_start(self):
        self.spawn(self._tick())

    async def _tick(self):
        while True:
            self.ticks += 1
            await asyncio.sleep(0.01)


def test_service_lifecycle():
    async def run():
        svc = _Svc()
        await svc.start()
        assert svc.is_running
        with pytest.raises(ServiceError):
            await svc.start()
        await asyncio.sleep(0.05)
        await svc.stop()
        assert not svc.is_running
        await svc.wait()
        assert svc.ticks >= 2
        with pytest.raises(ServiceError):
            await svc.start()  # no restart

    asyncio.run(run())


def test_service_task_failure_stops_service():
    async def run():
        class Bad(Service):
            async def on_start(self):
                self.spawn(self._boom())

            async def _boom(self):
                raise RuntimeError("boom")

        svc = Bad("bad")
        await svc.start()
        for _ in range(50):
            if not svc.is_running:
                break
            await asyncio.sleep(0.01)
        assert not svc.is_running

    asyncio.run(run())


def test_config_roundtrip(tmp_path):
    from tendermint_tpu.config import Config, load_config, write_config

    cfg = Config()
    cfg.base.chain_id = "test-chain"
    cfg.consensus.timeout_propose = 1.25
    cfg.tpu.bucket_sizes = [4, 16]
    path = str(tmp_path / "config" / "config.toml")
    write_config(cfg, path)
    loaded = load_config(path)
    assert loaded.base.chain_id == "test-chain"
    assert loaded.consensus.timeout_propose == 1.25
    assert loaded.tpu.bucket_sizes == [4, 16]
    assert loaded.consensus.propose_timeout(2) == 1.25 + 2 * 0.5
