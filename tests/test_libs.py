import asyncio

import pytest

from tendermint_tpu.libs.bits import BitArray
from tendermint_tpu.libs.service import Service, ServiceError


def test_bitarray_basic():
    ba = BitArray(10)
    assert ba.is_empty() and not ba.is_full()
    assert ba.set(3) and ba.set(9)
    assert not ba.set(10)  # out of range
    assert ba.get(3) and not ba.get(4)
    assert ba.count() == 2
    assert list(ba.indices()) == [3, 9]
    assert ba.pick_random() in (3, 9)


def test_bitarray_algebra():
    a, b = BitArray(8), BitArray(8)
    a.set(1), a.set(2)
    b.set(2), b.set(3)
    assert list(a.or_(b).indices()) == [1, 2, 3]
    assert list(a.and_(b).indices()) == [2]
    assert list(a.sub(b).indices()) == [1]
    assert a.not_().count() == 6
    full = BitArray(4)
    for i in range(4):
        full.set(i)
    assert full.is_full()


def test_bitarray_words_roundtrip():
    ba = BitArray(130)
    for i in (0, 63, 64, 129):
        ba.set(i)
    again = BitArray.from_words(130, ba.to_words())
    assert again == ba


class _Svc(Service):
    def __init__(self):
        super().__init__("test")
        self.ticks = 0

    async def on_start(self):
        self.spawn(self._tick())

    async def _tick(self):
        while True:
            self.ticks += 1
            await asyncio.sleep(0.01)


def test_service_lifecycle():
    async def run():
        svc = _Svc()
        await svc.start()
        assert svc.is_running
        with pytest.raises(ServiceError):
            await svc.start()
        await asyncio.sleep(0.05)
        await svc.stop()
        assert not svc.is_running
        await svc.wait()
        assert svc.ticks >= 2
        with pytest.raises(ServiceError):
            await svc.start()  # no restart

    asyncio.run(run())


def test_service_task_failure_stops_service():
    async def run():
        class Bad(Service):
            async def on_start(self):
                self.spawn(self._boom())

            async def _boom(self):
                raise RuntimeError("boom")

        svc = Bad("bad")
        await svc.start()
        for _ in range(50):
            if not svc.is_running:
                break
            await asyncio.sleep(0.01)
        assert not svc.is_running

    asyncio.run(run())


def test_config_roundtrip(tmp_path):
    from tendermint_tpu.config import Config, load_config, write_config

    cfg = Config()
    cfg.base.chain_id = "test-chain"
    cfg.consensus.timeout_propose = 1.25
    cfg.tpu.bucket_sizes = [4, 16]
    path = str(tmp_path / "config" / "config.toml")
    write_config(cfg, path)
    loaded = load_config(path)
    assert loaded.base.chain_id == "test-chain"
    assert loaded.consensus.timeout_propose == 1.25
    assert loaded.tpu.bucket_sizes == [4, 16]
    assert loaded.consensus.propose_timeout(2) == 1.25 + 2 * 0.5
