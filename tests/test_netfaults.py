"""Network fault plane + self-healing peer lifecycle (ISSUE 13).

Three layers under test, mirroring tests/test_faults.py one level up:

- the plane itself: the p2p.send / p2p.recv / p2p.dial points, the
  drop / delay / duplicate / reorder modes, (src, dst, ch) keying, and
  the runtime-mutable partition sets — every mode seed-replayable
  (whether consult k fires is a pure function of (seed, k));
- the router under injected faults: messages dropped / duplicated /
  reordered / delayed per plan, partitions cutting links until the
  keepalive deadline evicts the peer, and the net healing afterwards;
- the self-healing lifecycle: jittered capped exponential dial
  backoff (computed once per failure, stored, observable), slow-peer
  shedding with eviction + ban window, and the disconnect REASON
  propagating to both sides' logs and metrics via the goodbye frame.
"""

import asyncio
import time

import pytest

from tendermint_tpu.consensus import msgs as cmsgs
from tendermint_tpu.crypto import faults
from tendermint_tpu.loadgen.scrape import parse_exposition
from tendermint_tpu.p2p import (
    ChannelDescriptor,
    Envelope,
    PeerManager,
    PeerManagerOptions,
)
from tendermint_tpu.p2p.p2ptest import TestNetwork
from tendermint_tpu.p2p.peermanager import backoff_delay
from tendermint_tpu.p2p.router import RouterOptions


def run(coro):
    return asyncio.run(coro)


ECHO = ChannelDescriptor(
    channel_id=0x42, message_type=cmsgs.HasVoteMessage, name="echo"
)


def _msg(h):
    return cmsgs.HasVoteMessage(height=h, round=0, type=1, index=0)


def _counter(node, name, **labels):
    """Read one counter series from a TestNode's registry."""
    parsed = parse_exposition(node.registry.render())
    key = "tendermint_tpu_" + name
    if labels:
        key += (
            "{"
            + ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
            + "}"
        )
    return parsed.get(key, 0.0)


# -- the plane ---------------------------------------------------------


def test_net_rule_env_grammar(monkeypatch):
    """The TM_TPU_FAULT grammar extends verbatim: network modes with
    src/dst/ch filters and delay_s/dup knobs parse from the env, and
    TM_TPU_PARTITION arms partition sets."""
    monkeypatch.setenv(
        "TM_TPU_FAULT",
        "p2p.send:drop:p=0.4:seed=9:src=load0:dst=load1:ch=34;"
        "p2p.recv:delay:delay_s=0.2;p2p.send:duplicate:dup=3",
    )
    monkeypatch.setenv("TM_TPU_PARTITION", "a,b|c")
    faults.load_env()
    try:
        rules = {(r.point, r.mode): r for r in faults.rules()}
        r = rules[("p2p.send", "drop")]
        assert (r.src, r.dst, r.ch, r.p) == ("load0", "load1", 34, 0.4)
        assert rules[("p2p.recv", "delay")].delay_s == 0.2
        assert rules[("p2p.send", "duplicate")].dup == 3
        assert faults.net_armed() and faults.armed()
        assert faults.partition_spec() == "a,b|c"
        assert faults.partition_blocked(("a",), ("c",))
    finally:
        monkeypatch.setenv("TM_TPU_FAULT", "")
        monkeypatch.delenv("TM_TPU_PARTITION")
        faults.load_env()
    assert not faults.net_armed()


@pytest.mark.parametrize("mode", ["drop", "delay", "duplicate", "reorder"])
def test_net_modes_seed_replayable(mode):
    """Every new mode rides the PR-3 seeding contract: which consults
    fire is a pure function of (seed, consult index)."""

    def pattern(seed):
        fired = []
        with faults.inject("p2p.send", mode=mode, p=0.5, seed=seed):
            for i in range(60):
                plan = faults.net_plan(
                    "p2p.send", src=("a",), dst=("b",), ch=1
                )
                if plan is not None:
                    fired.append(i)
        return fired

    a, b, c = pattern(5), pattern(5), pattern(6)
    assert a == b and a and a != c


def test_net_plan_src_dst_ch_filters():
    with faults.inject(
        "p2p.send", mode="drop", src="load0", dst="load1", ch=7
    ):
        hit = faults.net_plan(
            "p2p.send", src=("load0",), dst=("load1",), ch=7
        )
        assert hit is not None and hit.drop
        # wrong direction, wrong channel, wrong point: all filtered
        assert faults.net_plan(
            "p2p.send", src=("load1",), dst=("load0",), ch=7
        ) is None
        assert faults.net_plan(
            "p2p.send", src=("load0",), dst=("load1",), ch=8
        ) is None
        assert faults.net_plan(
            "p2p.recv", src=("load0",), dst=("load1",), ch=7
        ) is None


def test_label_match_exact_vs_prefix():
    """Monikers/hosts match labels exactly ("load1" must not swallow
    "load10", and neither may "validator1" swallow "validator10" just
    by being >= 8 chars); ONLY hex node-ID prefixes (>= 8 hex chars)
    match as prefixes."""
    faults.set_partition("load1|load10")
    try:
        assert faults.partition_blocked(("load1",), ("load10",))
        # "load1" is in group 0 ONLY — exact matching kept them apart
        assert not faults.partition_blocked(("load10",), ("load10",))
        # a LONG non-hex moniker still matches exactly, never as a
        # prefix: validator10 must land in ITS group, not validator1's
        faults.set_partition("validator1|validator10")
        assert faults.partition_blocked(
            ("validator1",), ("validator10",)
        )
        nid = "ab" * 20
        faults.set_partition(f"{nid[:12]}|other-node")
        assert faults.partition_blocked((nid,), ("other-node",))
    finally:
        faults.set_partition("")


def test_partition_runtime_mutable_and_unnamed_unaffected():
    faults.set_partition("a|b,c")
    try:
        assert faults.net_armed()
        assert faults.partition_blocked(("a",), ("b",))
        assert faults.partition_blocked(("c",), ("a",))
        assert not faults.partition_blocked(("b",), ("c",))
        # nodes the spec does not name keep every link
        assert not faults.partition_blocked(("z",), ("a",))
        assert not faults.partition_blocked(("a",), ("z",))
        faults.set_partition("")  # heal mid-run
        assert not faults.partition_blocked(("a",), ("b",))
    finally:
        faults.set_partition("")
    assert not faults.net_armed()


def test_partition_file_is_runtime_mutable(tmp_path, monkeypatch):
    """The file form (process nets): the spec re-reads on change, so an
    external orchestrator can partition and heal children mid-run."""
    pf = tmp_path / "partition"
    pf.write_text("v1|v0,v2")
    monkeypatch.setenv("TM_TPU_PARTITION_FILE", str(pf))
    faults.load_env()
    try:
        assert faults.net_armed()
        assert faults.partition_blocked(("v1",), ("v0",))
        time.sleep(0.25)  # past the stat() throttle
        pf.write_text("")
        time.sleep(0.25)
        assert not faults.partition_blocked(("v1",), ("v0",))
    finally:
        monkeypatch.delenv("TM_TPU_PARTITION_FILE")
        faults.load_env()


def test_malformed_fault_spec_keeps_partition_armed(monkeypatch):
    """A bad TM_TPU_FAULT raises once (the PR-6 latch) but must NOT
    strip TM_TPU_PARTITION as collateral — an e2e child whose
    partition silently never armed would measure an un-partitioned
    net."""
    monkeypatch.setenv("TM_TPU_FAULT", "p2p.send:bogus-mode")
    monkeypatch.setenv("TM_TPU_PARTITION", "a|b")
    monkeypatch.setattr(faults, "_ENV_LOADED", False)
    with pytest.raises(ValueError):
        faults.armed()
    try:
        assert faults.net_armed()
        assert faults.partition_blocked(("a",), ("b",))
    finally:
        monkeypatch.setenv("TM_TPU_FAULT", "")
        monkeypatch.delenv("TM_TPU_PARTITION")
        faults.load_env()


def test_net_armed_is_cheap_when_unarmed():
    """The zero-overhead contract: unarmed, the p2p hot path reads one
    module bool — and the plane reports unarmed."""
    assert not faults.net_armed()
    # tpu rules alone must not arm the NET plane (and vice versa)
    with faults.inject("tpu.dispatch", mode="raise"):
        assert faults.armed() and not faults.net_armed()
    with faults.inject("p2p.send", mode="drop"):
        assert faults.net_armed()
    assert not faults.net_armed()


# -- the router under the plane ---------------------------------------


async def _connected_pair(router_options=None):
    net = TestNetwork(2, router_options=router_options)
    channels = [n.open_channel(ECHO) for n in net.nodes]
    await net.start()
    return net, channels


def test_send_drop_rule_blocks_delivery():
    async def go():
        net, channels = await _connected_pair()
        try:
            with faults.inject(
                "p2p.send", mode="drop", src="node0", ch=ECHO.channel_id
            ):
                await channels[0].send(
                    Envelope(message=_msg(1), broadcast=True)
                )
                with pytest.raises(asyncio.TimeoutError):
                    await asyncio.wait_for(channels[1].receive(), 0.5)
                assert (
                    _counter(
                        net.nodes[0],
                        "p2p_net_faults_total",
                        point="p2p.send",
                        mode="drop",
                    )
                    >= 1
                )
            # disarmed: traffic flows again on the SAME connection
            await channels[0].send(
                Envelope(message=_msg(2), broadcast=True)
            )
            env = await asyncio.wait_for(channels[1].receive(), 5)
            assert env.message.height == 2
        finally:
            await net.stop()

    run(go())


def test_duplicate_and_reorder_modes_at_router():
    async def go():
        net, channels = await _connected_pair()
        try:
            with faults.inject(
                "p2p.send", mode="duplicate", dup=1, times=1,
                ch=ECHO.channel_id,
            ):
                await channels[0].send(
                    Envelope(message=_msg(7), broadcast=True)
                )
                a = await asyncio.wait_for(channels[1].receive(), 5)
                b = await asyncio.wait_for(channels[1].receive(), 5)
                assert a.message.height == b.message.height == 7

            # reorder: the first message is parked and delivered
            # BEHIND its successor
            with faults.inject(
                "p2p.recv", mode="reorder", times=1, ch=ECHO.channel_id
            ):
                t_before = time.monotonic()
                await channels[0].send(
                    Envelope(message=_msg(10), broadcast=True)
                )
                await asyncio.sleep(0.3)  # held, not yet delivered...
                # ...but the frame ARRIVED: it must count as liveness
                # (a held ping must not fake an unresponsive peer)
                assert (
                    net.nodes[1].router._peer_last_recv[
                        net.nodes[0].node_id
                    ]
                    >= t_before
                )
                await channels[0].send(
                    Envelope(message=_msg(11), broadcast=True)
                )
                first = await asyncio.wait_for(channels[1].receive(), 5)
                second = await asyncio.wait_for(channels[1].receive(), 5)
                assert (first.message.height, second.message.height) == (
                    11,
                    10,
                )
        finally:
            await net.stop()

    run(go())


def test_recv_delay_mode_adds_latency():
    async def go():
        net, channels = await _connected_pair()
        try:
            with faults.inject(
                "p2p.recv", mode="delay", delay_s=0.3, times=1,
                ch=ECHO.channel_id,
            ):
                t0 = time.monotonic()
                await channels[0].send(
                    Envelope(message=_msg(3), broadcast=True)
                )
                env = await asyncio.wait_for(channels[1].receive(), 5)
                assert env.message.height == 3
                assert time.monotonic() - t0 >= 0.25
        finally:
            await net.stop()

    run(go())


def test_partition_evicts_unresponsive_then_heals():
    """The full arc at router level: a partition cuts every frame
    (keepalives included) → the liveness deadline evicts the peer with
    reason `unresponsive` → the heal lets the dial machinery rebuild
    the connection on its jittered backoff schedule."""

    async def go():
        net, channels = await _connected_pair(
            router_options=RouterOptions(
                ping_interval=0.15, pong_timeout=0.15
            )
        )
        try:
            faults.set_partition("node0|node1")
            down = time.monotonic()
            while any(n.peer_manager.peers() for n in net.nodes):
                if time.monotonic() - down > 10:
                    raise AssertionError(
                        "partitioned peers never evicted"
                    )
                await asyncio.sleep(0.05)
            assert (
                _counter(
                    net.nodes[0],
                    "p2p_peer_disconnects_total",
                    reason="unresponsive",
                )
                + _counter(
                    net.nodes[1],
                    "p2p_peer_disconnects_total",
                    reason="unresponsive",
                )
                >= 1
            )
            faults.set_partition("")  # heal
            await net.wait_connected(timeout=20.0)
            await channels[0].send(
                Envelope(message=_msg(9), broadcast=True)
            )
            env = await asyncio.wait_for(channels[1].receive(), 5)
            assert env.message.height == 9
        finally:
            faults.set_partition("")
            await net.stop()

    run(go())


def test_slow_peer_shed_reason_lands_on_both_sides():
    """ISSUE 13 satellite: a shed slow peer used to be a silent
    queue-full debug line. Now the shedder evicts with reason
    `slow_peer` (counter + ban window) and the victim learns WHY via
    the goodbye frame (reason `remote/slow_peer` on ITS counter)."""

    narrow = ChannelDescriptor(
        channel_id=0x43,
        message_type=cmsgs.HasVoteMessage,
        name="narrow",
        send_queue_capacity=2,
    )

    async def go():
        net = TestNetwork(
            2,
            router_options=RouterOptions(
                slow_peer_drop_threshold=5,
                slow_peer_window_s=5.0,
                slow_peer_ban_s=0.8,
            ),
        )
        channels = [n.open_channel(narrow) for n in net.nodes]
        await net.start()
        try:
            shedder, victim = net.nodes
            vid = victim.node_id
            # park the shedder's send loop on an injected one-shot
            # delay: its 2-slot channel queue fills, and every further
            # broadcast is a send-queue shed
            with faults.inject(
                "p2p.send", mode="delay", delay_s=30.0, times=1,
                src="node0", ch=narrow.channel_id,
            ):
                for h in range(12):
                    await channels[0].send(
                        Envelope(message=_msg(h + 1), broadcast=True)
                    )
                    await asyncio.sleep(0.01)
            deadline = time.monotonic() + 10
            while (
                _counter(
                    shedder,
                    "p2p_peer_disconnects_total",
                    reason="slow_peer",
                )
                < 1
                and time.monotonic() < deadline
            ):
                await asyncio.sleep(0.05)
            assert (
                _counter(
                    shedder,
                    "p2p_peer_disconnects_total",
                    reason="slow_peer",
                )
                == 1
            )
            assert (
                _counter(
                    shedder,
                    "p2p_send_queue_dropped_total",
                    ch=narrow.channel_id,
                )
                >= 5
            )
            # the victim's side: reason arrived over the wire, got
            # sanitized against the fixed vocabulary, landed labeled
            deadline = time.monotonic() + 10
            while (
                _counter(
                    victim,
                    "p2p_peer_disconnects_total",
                    reason="remote/slow_peer",
                )
                < 1
                and time.monotonic() < deadline
            ):
                await asyncio.sleep(0.05)
            assert (
                _counter(
                    victim,
                    "p2p_peer_disconnects_total",
                    reason="remote/slow_peer",
                )
                == 1
            )
            # ban window: the shed peer sits out, then the pair heals
            peer = shedder.peer_manager._peers[vid]
            assert peer.banned_until > 0
            await net.wait_connected(timeout=20.0)
        finally:
            await net.stop()

    run(go())


def test_shutdown_reason_propagates_to_peer():
    """A clean local stop announces itself: the surviving side records
    `remote/shutdown` instead of a bare recv_error — a clean shutdown
    must be distinguishable from a crash (the goodbye frame is sent
    from on_stop, where the service already reads as not-running)."""

    async def go():
        net, _channels = await _connected_pair()
        victim = net.nodes[1]
        try:
            await net.nodes[0].router.stop()
            deadline = time.monotonic() + 10
            while (
                _counter(
                    victim,
                    "p2p_peer_disconnects_total",
                    reason="remote/shutdown",
                )
                < 1
                and time.monotonic() < deadline
            ):
                await asyncio.sleep(0.05)
            assert (
                _counter(
                    victim,
                    "p2p_peer_disconnects_total",
                    reason="remote/shutdown",
                )
                >= 1
            )
        finally:
            await net.stop()

    run(go())


def test_dial_drop_rule_keeps_net_apart_then_heals():
    """`p2p.dial:drop` at the transport boundary produces the same
    ConnectionError a dead peer would — the backoff machinery runs
    (dial_backoff histogram advances), and removing the rule lets the
    mesh form."""

    async def go():
        net = TestNetwork(2)
        for n in net.nodes:
            n.open_channel(ECHO)
        with faults.inject("p2p.dial", mode="drop"):
            await net.nodes[0].router.start()
            await net.nodes[1].router.start()
            net.nodes[0].peer_manager.add(
                f"{net.nodes[1].node_id}@{net.nodes[1].addr}"
            )
            await asyncio.sleep(1.0)
            assert not net.nodes[0].peer_manager.peers()
            parsed = parse_exposition(net.nodes[0].registry.render())
            assert (
                parsed.get(
                    "tendermint_tpu_p2p_dial_backoff_seconds_count", 0
                )
                >= 1
            )
        try:
            await net.wait_connected(timeout=20.0)
        finally:
            await net.stop()

    run(go())


# -- the backoff schedule ---------------------------------------------


class FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now


def test_backoff_delay_is_jittered_and_capped():
    opts = PeerManagerOptions(
        min_retry_time=0.25, max_retry_time=600.0,
        max_retry_time_persistent=20.0,
    )
    for attempts in range(1, 14):
        d = min(0.25 * (2 ** (attempts - 1)), 600.0)
        samples = [
            backoff_delay(attempts, opts, persistent=False)
            for _ in range(50)
        ]
        assert all(d / 2 <= s <= d for s in samples), (attempts, d)
    # full jitter actually jitters
    assert len({backoff_delay(6, opts, False) for _ in range(20)}) > 1
    # persistent peers cap earlier
    assert backoff_delay(12, opts, persistent=True) <= 20.0
    assert backoff_delay(0, opts, persistent=False) == 0.0


def test_refused_dial_retries_on_backoff_schedule():
    """ISSUE 13 satellite regression: a refused dial reschedules on
    the stored jittered-exponential schedule — not a fixed cadence —
    and the candidate stays unavailable exactly until retry_at."""

    async def go():
        clk = FakeClock()
        pm = PeerManager(
            "aa" * 20,
            PeerManagerOptions(min_retry_time=0.25),
            clock=clk,
        )
        nid = "bb" * 20
        pm.add(f"{nid}@h:1")
        delays = []
        for attempt in range(1, 7):
            node_id, _, _ = await asyncio.wait_for(pm.dial_next(), 2)
            assert node_id == nid
            pm.dial_failed(nid)
            peer = pm._peers[nid]
            d = min(0.25 * (2 ** (attempt - 1)), 600.0)
            assert d / 2 <= peer.retry_delay_s <= d, (
                attempt, peer.retry_delay_s,
            )
            delays.append(peer.retry_delay_s)
            # one tick before expiry: no candidate
            clk.now = peer.retry_at - 0.01
            assert pm._next_dial_candidate() is None
            clk.now = peer.retry_at + 0.01
        assert delays == sorted(delays)  # the schedule grows
        # an inbound connection proves liveness: schedule resets
        pm.accepted(nid)
        assert pm._peers[nid].dial_attempts == 0
        assert pm._peers[nid].retry_at == 0.0

    run(go())


def test_banned_peer_rejected_on_both_paths():
    async def go():
        clk = FakeClock()
        pm = PeerManager("aa" * 20, clock=clk)
        nid = "bb" * 20
        pm.add(f"{nid}@h:1")
        pm.ban(nid, 30.0)
        with pytest.raises(ValueError, match="banned"):
            pm.accepted(nid)
        assert pm._next_dial_candidate() is None
        clk.now += 31.0  # window over: the peer is dialable again
        assert pm._next_dial_candidate() is not None

    run(go())


def test_shed_slow_sets_reason_ban_and_evicts():
    async def go():
        clk = FakeClock()
        pm = PeerManager("aa" * 20, clock=clk)
        nid = "bb" * 20
        pm.add(f"{nid}@h:1")
        node_id, _, _ = await pm.dial_next()
        pm.dialed(node_id)
        pm.ready(node_id)
        pm.shed_slow(nid, ban_s=12.0)
        assert pm.evict_reason(nid) == "slow_peer"
        victim = await asyncio.wait_for(pm.evict_next(), 1)
        assert victim == nid
        assert pm._peers[nid].banned_until == clk.now + 12.0
        pm.disconnected(nid)
        assert pm.evict_reason(nid) == ""

    run(go())
