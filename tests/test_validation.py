"""VerifyCommit family — batch/single equivalence and device parity.

The reference's own pattern (types/validation_test.go): every case must
produce the same outcome whether verified signature-by-signature or as
one batch — and here additionally when the batch runs on the device
kernel.
"""

import pytest

from tendermint_tpu.crypto import batch as crypto_batch
from tendermint_tpu.crypto.tpu_verifier import TpuEd25519BatchVerifier
from tendermint_tpu.types import (
    BlockID,
    CommitSig,
    Fraction,
    InvalidCommitError,
    NotEnoughVotingPowerError,
    verify_commit,
    verify_commit_light,
    verify_commit_light_trusting,
)
from tendermint_tpu.types.validation import (
    _verify_commit_single,
)

from .test_types import (
    CHAIN_ID,
    make_block_id,
    make_validators,
    signed_vote,
)
from tendermint_tpu.types import PRECOMMIT_TYPE, VoteSet


def make_commit(n=4, signers=None, height=1, round_=0):
    """Commit with an explicit signer subset (may lack a majority —
    built directly rather than via VoteSet, like the reference's
    validation tests construct arbitrary commits)."""
    from tendermint_tpu.types import Commit

    vals, privs = make_validators(n)
    bid = make_block_id()
    signers = set(range(n) if signers is None else signers)
    sigs = []
    for i in range(n):
        if i in signers:
            v = signed_vote(
                privs[i], vals, i, bid, height=height, round_=round_
            )
            sigs.append(
                CommitSig.for_block(
                    v.signature, v.validator_address, v.timestamp_ns
                )
            )
        else:
            sigs.append(CommitSig.absent())
    return vals, bid, Commit(
        height=height, round=round_, block_id=bid, signatures=sigs
    )


class TestVerifyCommit:
    def test_all_signed_ok(self):
        vals, bid, commit = make_commit(4)
        verify_commit(CHAIN_ID, vals, bid, 1, commit)
        verify_commit_light(CHAIN_ID, vals, bid, 1, commit)
        verify_commit_light_trusting(
            CHAIN_ID, vals, commit, Fraction(1, 3)
        )

    def test_two_thirds_exactly_insufficient(self):
        # 2 of 4 equal-power signers is NOT > 2/3
        vals, bid, commit = make_commit(4, signers=[0, 1])
        with pytest.raises(NotEnoughVotingPowerError):
            verify_commit(CHAIN_ID, vals, bid, 1, commit)

    def test_three_quarters_sufficient(self):
        vals, bid, commit = make_commit(4, signers=[0, 1, 2])
        verify_commit(CHAIN_ID, vals, bid, 1, commit)

    def test_wrong_height_rejected(self):
        vals, bid, commit = make_commit(4)
        with pytest.raises(InvalidCommitError, match="height"):
            verify_commit(CHAIN_ID, vals, bid, 2, commit)

    def test_wrong_block_id_rejected(self):
        vals, bid, commit = make_commit(4)
        with pytest.raises(InvalidCommitError, match="block ID"):
            verify_commit(
                CHAIN_ID, vals, make_block_id(b"\x09"), 1, commit
            )

    def test_corrupt_signature_rejected_with_index(self):
        vals, bid, commit = make_commit(4)
        sig = bytearray(commit.signatures[2].signature)
        sig[0] ^= 0xFF
        commit.signatures[2].signature = bytes(sig)
        with pytest.raises(InvalidCommitError, match=r"#2"):
            verify_commit(CHAIN_ID, vals, bid, 1, commit)

    def test_set_size_mismatch(self):
        vals, bid, commit = make_commit(4)
        commit.signatures.append(CommitSig.absent())
        with pytest.raises(InvalidCommitError, match="wrong set size"):
            verify_commit(CHAIN_ID, vals, bid, 1, commit)

    def test_batch_single_equivalence(self):
        """reference: types/validation.go:146-148 — the batch path and
        single path must agree on every input."""
        cases = [
            make_commit(4),
            make_commit(4, signers=[0, 1, 2]),
            make_commit(7, signers=[0, 2, 3, 5, 6]),
        ]
        for vals, bid, commit in cases:
            verify_commit(CHAIN_ID, vals, bid, 1, commit)  # batch (CPU)
            _verify_commit_single(
                CHAIN_ID,
                vals,
                commit,
                vals.total_voting_power() * 2 // 3,
                lambda c: c.is_absent(),
                lambda c: c.is_for_block(),
                True,
                True,
            )

    def test_light_trusting_lookup_by_address(self):
        # trusted set = subset of signers' set: lookup must go by address
        vals, bid, commit = make_commit(4)
        # the full set passes at 1/3
        verify_commit_light_trusting(
            CHAIN_ID, vals, commit, Fraction(1, 3)
        )
        # only 2 of 4 signed: fails a 2/3 trust level
        vals2, _bid2, commit2 = make_commit(4, signers=[0, 1])
        with pytest.raises(NotEnoughVotingPowerError):
            verify_commit_light_trusting(
                CHAIN_ID, vals2, commit2, Fraction(2, 3)
            )

    def test_out_of_range_flag_keeps_reference_error(self):
        # from_proto reads block_id_flag as an unbounded varint, so a
        # hostile commit can carry a flag > 255. The vectorized-tally
        # memo must not turn that into an OverflowError: the flags
        # memo returns None and verify_commit stays on the scalar
        # loop, failing with the reference error type.
        from dataclasses import replace

        vals, bid, commit = make_commit(4)
        sigs = list(commit.signatures)
        sigs[1] = replace(sigs[1], block_id_flag=300)
        from tendermint_tpu.types import Commit

        bad = Commit(
            height=commit.height, round=commit.round,
            block_id=bid, signatures=sigs,
        )
        assert bad.block_id_flags_array() is None
        with pytest.raises(InvalidCommitError):
            verify_commit(CHAIN_ID, vals, bid, 1, bad)

    def test_tally_memo_arrays_are_read_only(self):
        # block_id_flags_array and powers_array both hand out live
        # memos, read-only for a uniform contract: writes must raise,
        # not silently corrupt a tally.
        import numpy as np

        vals, _bid, commit = make_commit(4)
        with pytest.raises(ValueError):
            vals.powers_array()[0] = 0
        with pytest.raises(ValueError):
            commit.block_id_flags_array()[0] = 0
        assert int(vals.powers_array().sum()) == vals.total_voting_power()
        assert np.all(commit.block_id_flags_array() >= 0)

    def test_powers_array_sees_in_place_power_mutation(self):
        # ValidatorSet hands out live Validator references, so an
        # embedder can mutate voting_power in place without running
        # _reindex. The scalar verify paths read val.voting_power
        # live; powers_array must not serve a stale memo or the
        # vectorized tally diverges from them (same staleness class
        # as the to_proto ADVICE-r5 fix — closed by the
        # Validator.__setattr__ epoch hook invalidating the memo).
        vals, _bid, _commit = make_commit(4)
        before = vals.powers_array().copy()
        vals.validators[0].voting_power += 7
        after = vals.powers_array()
        assert after[0] == before[0] + 7
        # and a copy() taken before the mutation reports its own
        # (un-mutated) powers, not a shared array
        vals2, _b2, _c2 = make_commit(4)
        snap = vals2.copy()
        vals2.validators[1].voting_power += 11
        assert snap.powers_array()[1] + 11 == vals2.powers_array()[1]

    def test_flag_just_past_uint8_rejected_without_numpy_overflow(self):
        # 256 wraps to 0 under numpy 1.x's modulo conversion (numpy 2
        # raises): the explicit range check must return None on both,
        # keeping verify_commit on the scalar loop / reference error.
        from dataclasses import replace
        from tendermint_tpu.types import Commit

        vals, bid, commit = make_commit(4)
        sigs = list(commit.signatures)
        sigs[2] = replace(sigs[2], block_id_flag=256)
        bad = Commit(
            height=commit.height, round=commit.round,
            block_id=bid, signatures=sigs,
        )
        assert bad.block_id_flags_array() is None
        with pytest.raises(InvalidCommitError):
            verify_commit(CHAIN_ID, vals, bid, 1, bad)


class TestDeviceCommitVerify:
    """Device parity: the TPU kernel path must agree with CPU on every
    commit (differential test, SURVEY.md §4 item d)."""

    @pytest.fixture(autouse=True)
    def install_device(self):
        from tendermint_tpu.crypto import tpu_verifier

        tpu_verifier.install(min_batch=2)
        yield
        tpu_verifier.uninstall()

    def test_device_verify_valid_commit(self):
        vals, bid, commit = make_commit(4)
        verify_commit(CHAIN_ID, vals, bid, 1, commit)

    def test_device_flags_bad_signature(self):
        vals, bid, commit = make_commit(4)
        sig = bytearray(commit.signatures[1].signature)
        sig[1] ^= 0x01
        commit.signatures[1].signature = bytes(sig)
        with pytest.raises(InvalidCommitError, match=r"#1"):
            verify_commit(CHAIN_ID, vals, bid, 1, commit)

    def test_device_verifier_used(self):
        v = crypto_batch.create_batch_verifier(
            make_validators(1)[0].validators[0].pub_key, size_hint=100
        )
        assert isinstance(v, TpuEd25519BatchVerifier)
