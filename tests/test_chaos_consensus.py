"""Chaos tests: BFT consensus under injected device faults.

The containment acceptance criteria (ISSUE 3 / docs/resilience.md):

- a multi-height consensus pipeline driven under 100% and intermittent
  injected device faults (raise + hang variants) produces commit
  hashes IDENTICAL to a fault-free run — the device is allowed to cost
  latency, never correctness;
- the breaker is observed cycling open -> half-open -> closed as
  faults clear, with at most one re-arm probe in flight at any moment
  and a bounded total probe count (no retry storm);
- a live 4-validator network keeps committing identical blocks at
  every height while faults fire mid-flight.

The device seam runs the REAL containment stack
(crypto/tpu_verifier._TpuBatchVerifier + crypto/breaker) over a
host-CPU backing, so the chaos schedule — not a jax compile — is what
these tests spend their time on; the fault points sit at the
dispatch/gather boundary, exactly where an XLA runtime would fail.
"""

import asyncio
import hashlib
import threading
import time

import pytest

from tendermint_tpu.crypto import breaker as B
from tendermint_tpu.crypto import faults, sigcache
from tendermint_tpu.crypto import tpu_verifier as T
from tendermint_tpu.crypto.batch import (
    register_device_factory,
    unregister_device_factory,
)
from tendermint_tpu.crypto.ed25519 import Ed25519BatchVerifier
from tendermint_tpu.crypto.keys import pubkey_from_type_and_bytes
from tendermint_tpu.types import PRECOMMIT_TYPE, VoteSet, verify_commit

from .test_types import CHAIN_ID, make_block_id, make_validators, signed_vote


class HostBacking:
    """dispatch/gather pair answering from the CPU batch verifier: the
    containment layer above it cannot tell it from a device, and the
    fault plane intercepts at exactly the same two points."""

    bucket_sizes = (8, 32, 128)

    def dispatch(self, pks, msgs, sigs):
        bv = Ed25519BatchVerifier()
        for pk, m, s in zip(pks, msgs, sigs):
            bv.add(pubkey_from_type_and_bytes("ed25519", pk), m, s)
        return bv.verify()[1]

    def gather(self, handle):
        return handle


class BreakerScope:
    """Wire the ed25519 route the way install() does — fresh breaker,
    single-flight probe against the backing — but with test-speed
    backoff, and record every state transition plus probe concurrency."""

    def __init__(self, backing, backoff_s=0.05):
        self.states = []
        self.probe_peak = 0
        self._in_flight = 0
        self._lock = threading.Lock()
        self.breaker = B.fresh("ed25519", backoff_base_s=backoff_s)
        self._record(self.breaker.state())

        def probe():
            with self._lock:
                self._in_flight += 1
                self.probe_peak = max(self.probe_peak, self._in_flight)
            self._record(self.breaker.state())  # HALF_OPEN at probe time
            try:
                return T._device_probe("ed25519", lambda: backing)
            finally:
                with self._lock:
                    self._in_flight -= 1

        self.breaker.set_probe(probe)

    def _record(self, state):
        if not self.states or self.states[-1] != state:
            self.states.append(state)

    def note(self):
        self._record(self.breaker.state())


@pytest.fixture
def device_seam(monkeypatch):
    """The TPU factory served by a HostBacking, min_batch=2, with the
    breaker scope armed — every >=2-signature batch rides the full
    containment stack."""
    backing = HostBacking()
    monkeypatch.setattr(T, "_SHARED_VERIFIER", backing)
    monkeypatch.setattr(T, "_MIN_BATCH", 2)
    monkeypatch.setattr(T, "_INSTALLED", True)
    register_device_factory("ed25519", T._factory)
    scope = BreakerScope(backing)
    yield scope
    unregister_device_factory("ed25519")


def _drive_chain(n_heights, n_vals=4):
    """n_heights of the addVote -> verify_commit pipeline over
    DETERMINISTIC votes (fixed timestamps, block IDs chained on the
    previous commit hash): vote batches drain through the device seam
    the way consensus verify-ahead does, each height's commit is
    verified through verify_commit, and the returned hash chain is a
    pure function of the inputs — any fault that leaked into
    verification (a dropped vote, a mis-attributed signature, a
    commit accepted that should fail) changes it."""
    vals, privs = make_validators(n_vals)
    from tendermint_tpu.crypto.batch import (
        create_batch_verifier,
        drain_and_cache,
    )

    chain = []
    prev = b"\x01"
    for h in range(1, n_heights + 1):
        bid = make_block_id(prev[:1] or b"\x01")
        votes = [
            signed_vote(p, vals, i, bid, height=h)
            for i, p in enumerate(privs)
        ]
        # the verify-ahead shape: one device batch over the height's
        # precommits (faults fire here), results recorded in sigcache
        bv = create_batch_verifier(privs[0].pub_key(), size_hint=len(votes))
        keys = []
        for v, p in zip(votes, privs):
            sb = v.sign_bytes(CHAIN_ID)
            bv.add(p.pub_key(), sb, v.signature)
            keys.append(
                sigcache.key_for(p.pub_key().bytes(), sb, v.signature)
            )
        ok, bits = drain_and_cache(bv, keys)
        assert ok and all(bits), f"height {h}: valid votes rejected"
        vs = VoteSet(CHAIN_ID, h, 0, PRECOMMIT_TYPE, vals)
        for v in votes:
            assert vs.add_vote(v)
        commit = vs.make_commit()
        # the next height's LastCommit check (faults fire here too)
        verify_commit(CHAIN_ID, vals, bid, h, commit)
        digest = hashlib.sha256(
            commit.hash() + bid.hash + prev
        ).digest()
        chain.append(digest)
        prev = digest
    return chain


def test_20_height_chain_identical_under_faults(device_seam):
    """The headline acceptance: 20 heights, clean vs 100% faults vs
    intermittent raise+hang faults — identical commit-hash chains."""
    sigcache.reset()
    clean = _drive_chain(20)

    sigcache.reset()  # force every height back onto the device seam
    with faults.inject("tpu.dispatch", mode="raise"):  # 100% faults
        all_faulted = _drive_chain(20)

    sigcache.reset()
    B.breaker_for("ed25519").close_now()
    with faults.inject("tpu.dispatch", mode="raise", p=0.3, seed=11), \
            faults.inject("tpu.gather", mode="hang", p=0.2, seed=12,
                          hang_s=0.25):
        # a short deadline so injected hangs surface as DeviceTimeout
        import os

        os.environ["TM_TPU_GATHER_DEADLINE_S"] = "0.1"
        try:
            intermittent = _drive_chain(20)
        finally:
            del os.environ["TM_TPU_GATHER_DEADLINE_S"]

    assert clean == all_faulted == intermittent
    assert len(clean) == 20
    assert T.stats()["faults"] > 0  # the chaos actually happened


def test_breaker_cycles_and_probe_bounded_under_intermittent_faults(
    device_seam,
):
    """Breaker lifecycle under a fault burst that then clears:
    open -> half-open -> closed observed, <=1 probe in flight ever,
    probe count bounded (no retry storm)."""
    scope = device_seam
    sigcache.reset()
    with sigcache.disabled():
        with faults.inject("tpu.dispatch", mode="raise"):
            _drive_chain(3)  # every batch faults; breaker trips
            scope.note()
        assert B.OPEN in scope.states
        # faults cleared: the timer-scheduled single-flight probe must
        # re-arm the route with no traffic at all
        deadline = time.monotonic() + 10.0
        while (
            scope.breaker.state() != B.CLOSED
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        scope.note()
    assert scope.states[0] == B.CLOSED
    seq = scope.states
    assert seq.index(B.OPEN) < seq.index(B.HALF_OPEN) <= len(seq) - 2
    assert seq[-1] == B.CLOSED
    assert scope.probe_peak <= 1
    # bounded probing: a 3-height fault burst plus recovery needs a
    # handful of probes, not one per faulted call
    assert scope.breaker.stats()["probes"] <= 8
    # and the re-armed route serves the device again, uncontained
    sigcache.reset()
    chain = _drive_chain(2)
    assert len(chain) == 2


# -- live consensus under chaos ---------------------------------------


def test_rpc_heartbeat_responsive_under_gather_hang(device_seam):
    """The dynamic twin of tmlive's `live-block-in-main-loop` proof: a
    live 4-validator net serves RPC while `tpu.gather` HANG faults (5 s
    hangs — fifty times the 0.1 s deadline) fire on the device seam.
    The gather watchdog + breaker must contain every hang off the
    event loop, so the HTTP /health and websocket heartbeats stay
    responsive — bounded p99, no multi-second stall — while the chain
    keeps committing. A wedge that parked the loop for even one raw
    hang would blow the bound by an order of magnitude."""
    import os

    from tendermint_tpu.rpc.client import HTTPClient, WSClient
    from tendermint_tpu.rpc.core import Environment
    from tendermint_tpu.rpc.jsonrpc import JSONRPCServer
    from .test_consensus_state import Node, RelayNet, fast_config
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
    from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519

    target = 12

    async def go():
        privs = [
            PrivKeyEd25519.from_seed(bytes([i + 140]) * 32)
            for i in range(4)
        ]
        genesis = GenesisDoc(
            chain_id="heartbeat-chain",
            genesis_time_ns=1_700_000_000_000_000_000,
            validators=[
                GenesisValidator(pub_key=p.pub_key(), power=10)
                for p in privs
            ],
        )
        nodes = [Node(p, genesis, cfg=fast_config()) for p in privs]
        RelayNet(nodes)
        env = Environment(
            chain_id="heartbeat-chain",
            block_store=nodes[0].block_store,
            state_store=nodes[0].state_store,
            consensus=nodes[0].cs,
        )
        srv = JSONRPCServer(env.routes())
        await srv.start("127.0.0.1", 0)
        addr = f"tcp://127.0.0.1:{srv.bound_port}"
        http = HTTPClient(addr, timeout=5.0)
        ws = WSClient(addr, timeout=5.0)
        await ws.connect()
        http_lat: list = []
        ws_lat: list = []
        stop = asyncio.Event()

        async def heartbeat(client, out):
            while not stop.is_set():
                t0 = time.monotonic()
                await client.call("health")
                out.append(time.monotonic() - t0)
                await asyncio.sleep(0.01)

        for n in nodes:
            await n.cs.start()
        hb = [
            asyncio.ensure_future(heartbeat(http, http_lat)),
            asyncio.ensure_future(heartbeat(ws, ws_lat)),
        ]
        try:
            await asyncio.gather(
                *(
                    n.cs.wait_for_height(target + 1, timeout=90.0)
                    for n in nodes
                )
            )
        finally:
            stop.set()
            await asyncio.gather(*hb, return_exceptions=True)
            for n in nodes:
                await n.cs.stop()
            await ws.close()
            await http.close()
            await srv.stop()
        return nodes, http_lat, ws_lat

    os.environ["TM_TPU_GATHER_DEADLINE_S"] = "0.1"
    try:
        with sigcache.disabled(), \
                faults.inject("tpu.gather", mode="hang", p=0.25, seed=31,
                              hang_s=5.0):
            nodes, http_lat, ws_lat = asyncio.run(go())
    finally:
        del os.environ["TM_TPU_GATHER_DEADLINE_S"]

    # the chaos was real and contained: the chain lived through it
    assert min(n.block_store.height() for n in nodes) >= target
    assert T.stats()["faults"] > 0
    # bounded heartbeat: both transports kept answering, p99 far below
    # the 5 s hang the watchdog swallowed (each faulted gather may park
    # the loop for at most the 0.1 s deadline, never the hang)
    from tendermint_tpu.libs.metrics import LatencySketch

    for name, lat in (("http", http_lat), ("ws", ws_lat)):
        # beat count: a 12-height fast-config run spans a couple of
        # seconds; a loop that swallowed even one raw 5 s hang would
        # deliver a fraction of this
        assert len(lat) >= 10, f"{name} heartbeat starved: {len(lat)} beats"
        sk = LatencySketch()
        for v in lat:
            sk.record(v)
        p99 = sk.quantile(0.99)
        assert p99 < 1.0, f"{name} heartbeat p99 {p99:.3f}s under faults"


def test_live_consensus_commits_identically_under_faults(device_seam):
    """A real 4-validator network (in-process gossip) runs 8 heights
    while raise+hang faults fire mid-flight on the device seam: every
    node commits the IDENTICAL block at every height and nobody stalls
    — degraded means slower, never wrong (the safety half the
    deterministic chain test can't cover: live vote interleaving,
    verify-ahead batches, replay of LastCommit inside block
    validation)."""
    from .test_consensus_state import Node, RelayNet, fast_config
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
    from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519

    target = 8

    async def go():
        privs = [
            PrivKeyEd25519.from_seed(bytes([i + 80]) * 32)
            for i in range(4)
        ]
        genesis = GenesisDoc(
            chain_id="chaos-chain",
            genesis_time_ns=1_700_000_000_000_000_000,
            validators=[
                GenesisValidator(pub_key=p.pub_key(), power=10)
                for p in privs
            ],
        )
        nodes = [Node(p, genesis, cfg=fast_config()) for p in privs]
        RelayNet(nodes)
        for n in nodes:
            await n.cs.start()
        try:
            await asyncio.gather(
                *(
                    n.cs.wait_for_height(target + 1, timeout=90.0)
                    for n in nodes
                )
            )
        finally:
            for n in nodes:
                await n.cs.stop()
        return nodes

    import os

    os.environ["TM_TPU_GATHER_DEADLINE_S"] = "0.1"
    try:
        with sigcache.disabled(), \
                faults.inject("tpu.dispatch", mode="raise", p=0.25,
                              seed=21), \
                faults.inject("tpu.gather", mode="hang", p=0.1, seed=22,
                              hang_s=0.2):
            nodes = asyncio.run(go())
    finally:
        del os.environ["TM_TPU_GATHER_DEADLINE_S"]

    for h in range(1, target + 1):
        hashes = {n.block_store.load_block(h).hash() for n in nodes}
        assert len(hashes) == 1, f"divergent block at height {h}"
    device_seam.note()
    # liveness held AND the chaos was real
    assert min(n.block_store.height() for n in nodes) >= target
