"""Span-tracing tests: nesting + attributes, the allocation-free
disabled path, histogram feeding, Chrome-trace export, and the
commit-pipeline span tree (addVote → batch_accumulate → tpu_dispatch
with merkle_hash in the same tree) from a live 4-validator consensus
run with the device batch-verifier seam installed."""

import asyncio
import json

import pytest

from tendermint_tpu.libs import trace
from tendermint_tpu.libs.metrics import Histogram


@pytest.fixture(autouse=True)
def _clean_trace():
    """Every test starts and ends with tracing off and an empty ring."""
    trace.disable()
    trace.reset()
    yield
    trace.disable()
    trace.reset()


class TestSpans:
    def test_nesting_records_parent_ids(self):
        trace.enable()
        with trace.span("outer", layer=1):
            with trace.span("middle"):
                with trace.span("inner"):
                    trace.add_attrs(deep=True)
        spans = trace.snapshot()
        # children exit (and record) before their parents
        assert [s.name for s in spans] == ["inner", "middle", "outer"]
        inner, middle, outer = spans
        assert inner.parent_id == middle.span_id
        assert middle.parent_id == outer.span_id
        assert outer.parent_id == 0
        assert inner.attrs["deep"] is True
        assert outer.attrs["layer"] == 1
        assert all(s.dur_us >= 0 for s in spans)

    def test_sibling_spans_share_parent(self):
        trace.enable()
        with trace.span("root"):
            with trace.span("a"):
                pass
            with trace.span("b"):
                pass
        a, b, root = trace.snapshot()
        assert a.parent_id == root.span_id
        assert b.parent_id == root.span_id

    def test_exception_recorded_and_context_restored(self):
        trace.enable()
        with pytest.raises(ValueError):
            with trace.span("boom"):
                raise ValueError("x")
        (s,) = trace.snapshot()
        assert s.attrs["error"] == "ValueError"
        assert trace.current() is None

    def test_disabled_path_allocates_nothing(self):
        """Kill switch: span() hands back the shared no-op singleton —
        no Span object, no ring entry, no live-span context."""
        assert not trace.is_enabled()
        s1 = trace.span("hot")
        s2 = trace.span("hot2")
        assert s1 is s2 is trace.NOOP_SPAN
        with s1:
            trace.add_attrs(ignored=1)  # no live span: no-op
            assert trace.current() is None
        assert trace.snapshot() == []

    def test_span_feeds_histogram_enabled_and_disabled(self):
        h = Histogram("t_span_h", "help", buckets=(0.5, 10.0))
        # disabled: degrades to exactly hist.time()
        with trace.span("timed", hist=h):
            pass
        assert h.count() == 1
        assert trace.snapshot() == []
        # enabled: observes AND records
        trace.enable()
        with trace.span("timed", hist=h):
            pass
        assert h.count() == 2
        assert [s.name for s in trace.snapshot()] == ["timed"]

    def test_ring_bounded_and_resizable(self):
        trace.enable(capacity=4)
        for i in range(10):
            with trace.span(f"s{i}"):
                pass
        names = [s.name for s in trace.snapshot()]
        assert names == ["s6", "s7", "s8", "s9"]
        trace.set_capacity(2)
        assert [s.name for s in trace.snapshot()] == ["s8", "s9"]
        # restore default for other tests
        trace.set_capacity(trace.DEFAULT_CAPACITY)

    def test_chrome_trace_export_is_valid(self):
        trace.enable()
        with trace.span("parent", kind="test"):
            with trace.span("child"):
                pass
        doc = json.loads(trace.to_chrome_trace())
        events = doc["traceEvents"]
        assert len(events) == 2
        by_name = {e["name"]: e for e in events}
        assert (
            by_name["child"]["args"]["parent_id"]
            == by_name["parent"]["args"]["span_id"]
        )
        for e in events:
            assert e["ph"] == "X"
            assert isinstance(e["ts"], float)
            assert isinstance(e["dur"], float)


class _FakeKernel:
    """Backing device verifier with the dispatch()/gather() pair and
    bucket shapes, minus the XLA program — the spans and telemetry in
    _TpuBatchVerifier.verify() are what's under test, and the inputs
    are honestly signed (see the consensus run below)."""

    bucket_sizes = (8, 32, 128)

    def dispatch(self, pks, msgs, sigs):
        return [True] * len(pks)

    def gather(self, handle):
        return handle


def _ancestor_names(span, by_id):
    names = []
    cur = span
    while cur.parent_id:
        cur = by_id.get(cur.parent_id)
        if cur is None:
            break
        names.append(cur.name)
    return names


def test_commit_pipeline_span_tree():
    """Acceptance: a commit verification emits a span tree rooted at
    addVote containing batch_accumulate → tpu_dispatch (with batch-size
    and pad-waste attributes) and merkle_hash, exportable as valid
    Chrome-trace JSON."""
    pytest.importorskip("jax")
    from tendermint_tpu.crypto import batch as cbatch
    from tendermint_tpu.crypto import sigcache
    from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
    from tendermint_tpu.crypto.tpu_verifier import TpuEd25519BatchVerifier
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    from .test_consensus_state import CHAIN, Node, RelayNet

    fake = _FakeKernel()
    cbatch.register_device_factory(
        "ed25519",
        lambda hint: TpuEd25519BatchVerifier(fake) if hint >= 2 else None,
    )
    trace.enable(capacity=65536)

    async def go():
        privs = [
            PrivKeyEd25519.from_seed(bytes([i + 140]) * 32)
            for i in range(4)
        ]
        genesis = GenesisDoc(
            chain_id=CHAIN,
            genesis_time_ns=1_700_000_000_000_000_000,
            validators=[
                GenesisValidator(pub_key=p.pub_key(), power=10)
                for p in privs
            ],
        )
        nodes = [Node(p, genesis) for p in privs]
        RelayNet(nodes)
        for n in nodes:
            await n.cs.start()
        try:
            await asyncio.gather(
                *(n.cs.wait_for_height(3, timeout=60.0) for n in nodes)
            )
        finally:
            for n in nodes:
                await n.cs.stop()

    try:
        # cache off: a warm LastCommit legitimately skips the device
        # (zero misses -> nothing to dispatch); this test asserts the
        # dispatch INSTRUMENTATION, so force every triple to batch
        with sigcache.disabled():
            asyncio.run(go())
        spans = trace.snapshot()
        by_id = {s.span_id: s for s in spans}

        dispatches = [s for s in spans if s.name == "tpu_dispatch"]
        assert dispatches, "no tpu_dispatch spans recorded"
        # full chain: tpu_dispatch under batch_accumulate under addVote
        chained = [
            s
            for s in dispatches
            if "batch_accumulate" in _ancestor_names(s, by_id)
            and "addVote" in _ancestor_names(s, by_id)
        ]
        assert chained, "no tpu_dispatch nested under addVote"
        d = chained[0]
        assert d.attrs["batch"] >= 2  # a 4-validator LastCommit
        assert d.attrs["bucket"] == 8  # smallest fake bucket
        assert d.attrs["pad_waste"] == 8 - d.attrs["batch"]
        assert "warm" in d.attrs
        assert d.attrs["host_prep_s"] >= 0.0
        # batch_accumulate carries the commit's signature count
        acc = by_id[d.parent_id]
        while acc.name != "batch_accumulate":
            acc = by_id[acc.parent_id]
        assert acc.attrs["sigs"] == 4
        # merkle hashing appears in the same addVote-rooted tree
        merkles = [
            s
            for s in spans
            if s.name == "merkle_hash"
            and "addVote" in _ancestor_names(s, by_id)
        ]
        assert merkles, "no merkle_hash in an addVote tree"
        # the whole ring exports as valid Chrome-trace JSON
        doc = json.loads(trace.to_chrome_trace())
        assert any(
            e["name"] == "tpu_dispatch" for e in doc["traceEvents"]
        )
        assert any(
            e["name"] == "block_execute" for e in doc["traceEvents"]
        )
    finally:
        cbatch.unregister_device_factory("ed25519")
