"""Seeded block-under-lock: the worker thread fsyncs while holding a
module lock; the timed twin (`wait(0.5)`) under the same lock is
bounded and must NOT be flagged."""

import os
import threading

_lock = threading.Lock()
_ev = threading.Event()


def flush_locked_bad(fd: int) -> None:
    with _lock:
        os.fsync(fd)


def wait_locked_ok() -> None:
    with _lock:
        _ev.wait(0.5)


def worker() -> None:
    flush_locked_bad(3)
    wait_locked_ok()


def start() -> None:
    t = threading.Thread(target=worker, daemon=True)
    t.start()
