"""Seeded spawned-thread residual: an untimed queue.get() on a worker
thread is `live-unbounded-blocking`; the suppressed twin carries the
reviewed block-ok rationale and passes. The producer pins put()'s
shifted (item, block, timeout) signature — `put(x, True)` blocks
forever and must flag, `put(x, True, 5.0)` is bounded — and the
subprocess worker pins Popen's positional-timeout forms."""

import queue
import subprocess
import threading

_q: queue.Queue = queue.Queue()
_q2: queue.Queue = queue.Queue()
_q3: queue.Queue = queue.Queue(maxsize=4)


def worker_bad() -> None:
    while True:
        _q.get()


def worker_ok() -> None:
    while True:
        # tmlive: block-ok — dedicated consumer thread: parking on the
        # queue is its whole job
        _q2.get()


def producer_bad(item) -> None:
    _q3.put(item, True)  # block=True, NO timeout: parks forever


def producer_ok(item) -> None:
    _q3.put(item, True, 5.0)  # positional timeout bounds it


def child_ok(cmd) -> None:
    p = subprocess.Popen(cmd)
    p.wait(30)  # positional timeout bounds the wait
    p.communicate(None, 30)


def start() -> None:
    threading.Thread(target=worker_bad, daemon=True).start()
    threading.Thread(target=worker_ok, daemon=True).start()
    threading.Thread(target=producer_bad, daemon=True).start()
    threading.Thread(target=producer_ok, daemon=True).start()
    threading.Thread(target=child_ok, daemon=True).start()
