"""Seeded block-in-main-loop: an async handler reaches a sync sleep
through a from-import alias (`nap`) — the alias machinery must see
through it. The awaited asyncio sleep and the constant-duration sync
sleep are not findings."""

import asyncio
from time import sleep as nap


def slow_helper(delay: float) -> None:
    nap(delay)


def quick_helper() -> None:
    nap(0.01)


async def handler(delay: float) -> None:
    slow_helper(delay)
    quick_helper()
    await asyncio.sleep(0.1)
