"""Seeded grow-unbounded: SEEN grows per request with no eviction.
The three bounded twins — ring (deque maxlen), rotation (reassigned),
reviewed annotation — must pass. SHADOWED pins the scoping rule: a
LOCAL `SHADOWED = []` binding elsewhere must not register as a fake
reset of the module global (the false-negative class tmrace's lockset
walker fixed for lock scoping)."""

from collections import deque
from typing import Dict

SEEN: Dict[str, int] = {}
RING: deque = deque(maxlen=64)
ROTATED: set = set()
# tmlive: bounded=keyed by a fixed route-name set
REGISTRY: Dict[str, int] = {}
SHADOWED: Dict[str, int] = {}
REBUILT: Dict[str, int] = {}
FILTERED: Dict[str, int] = {}
CROSS: Dict[str, int] = {}  # grown only from other.py


async def handler(key: str) -> None:
    global REBUILT
    SEEN[key] = SEEN.get(key, 0) + 1
    RING.append(key)
    ROTATED.add(key)
    REGISTRY[key] = 1
    SHADOWED[key] = 1
    # growth spelled as assignment: an additive self-rebuild must not
    # double as its own reset proof
    REBUILT = {**REBUILT, key: 1}
    FILTERED[key] = 1


def rotate() -> None:
    global ROTATED
    ROTATED = set()


def evict_stale() -> None:
    # a filtered copy references itself but IS eviction: a reset site
    global FILTERED
    FILTERED = {k: v for k, v in FILTERED.items() if v > 0}


def unrelated_local() -> list:
    # a plain local that happens to share the global's name: NOT a
    # reset site for the module container
    SHADOWED = []
    SHADOWED.append(1)
    return SHADOWED
