"""Cross-module growth: CROSS is born in mod.py but only ever grown
HERE — through both the from-import and the module-attr receiver
shapes. Both must resolve onto mod.py's container identity and flag
(no eviction site exists anywhere)."""

from . import mod
from .mod import CROSS


async def cross_handler(key: str) -> None:
    CROSS[key] = 1


async def cross_attr_handler(key: str) -> None:
    mod.CROSS[key] = 2
