"""Fixture: real violations silenced by justified suppressions."""
import time


def header_time():
    return time.time()  # tmlint: disable=det-wallclock — fixture: same-line form


def sign_time():
    # tmlint: disable=det-wallclock — fixture: comment-above form,
    # justification may span several comment lines before the code
    return time.time()


def unsuppressed():
    return time.time()
