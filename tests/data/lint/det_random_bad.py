"""Fixture: unseeded/global randomness the det-random rule flags."""
import os
import random
import uuid
from random import choice


def pick(candidates):
    return random.choice(candidates)


def shuffle_plan(items):
    random.shuffle(items)
    return items


def nonce():
    return os.urandom(8), uuid.uuid4()


def from_import_evasion(candidates):
    return choice(candidates)
