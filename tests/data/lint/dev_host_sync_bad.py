"""Fixture: implicit device->host syncs the rule flags."""
import numpy as np


def count_ok(bitmap):
    total = 0
    for lane in bitmap:
        total += int(lane.item())
    return total


def first_lane(bitmap):
    return float(bitmap[0])


def to_host(arr):
    return np.asarray(arr)
