"""Clean twin: reductions on device, one deliberate gather."""
import jax.numpy as jnp


def count_ok(bitmap):
    return jnp.sum(bitmap)


def all_ok(bitmap):
    return jnp.all(bitmap)
