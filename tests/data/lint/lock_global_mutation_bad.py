"""Fixture: unguarded shared-state mutations the rule flags."""
import threading

_CACHE: dict = {}
_PENDING: list = []
_lock = threading.Lock()


def remember(key, value):
    _CACHE[key] = value


def enqueue(item):
    _PENDING.append(item)


def reset():
    global _CACHE
    _CACHE = {}
