"""Clean twin: injected seeded RNG instances."""
import random


def pick(rng: random.Random, candidates):
    return rng.choice(candidates)


def make_rng(seed: int) -> random.Random:
    return random.Random(seed)


def pick_gossip(candidates):
    from tendermint_tpu.libs import rng

    return rng.choice(candidates)
