"""Clean twin: every mutation under the lock (or *_locked helper)."""
import threading

_CACHE: dict = {}
_PENDING: list = []
_lock = threading.Lock()


def remember(key, value):
    with _lock:
        _CACHE[key] = value


def enqueue(item):
    with _lock:
        _pending_push_locked(item)


def _pending_push_locked(item):
    _PENDING.append(item)


def reset():
    global _CACHE
    with _lock:
        _CACHE = {}
