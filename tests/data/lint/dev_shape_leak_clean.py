"""Clean twin: shapes from the padded bucket configuration."""
import jax.numpy as jnp

BUCKET = 2048


class Verifier:
    BUCKET = 2048

    def empty(self):
        return jnp.zeros(self.BUCKET)


def pad_batch():
    return jnp.zeros(BUCKET)


def lane_ids():
    return jnp.arange(2048)
