"""Fixture: hash-order set iteration the det-set-iter rule flags."""


def hash_addresses(addrs):
    seen = set(addrs)
    out = b""
    for a in seen:
        out += a
    return out


def encode_parts(parts):
    return [p.index for p in {p for p in parts}]
