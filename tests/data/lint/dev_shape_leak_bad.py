"""Fixture: data-dependent shapes the dev-shape-leak rule flags."""
import jax.numpy as jnp


def pad_batch(sigs):
    n = len(sigs)
    return jnp.zeros(n)


def lane_ids(batch):
    return jnp.arange(len(batch))
