"""Fixture: float arithmetic the det-float rule flags."""


def timeout_ns(seconds):
    return seconds * 1e9


def ratio(a: int, b: int):
    return a / b


def widen(x: int):
    return float(x)
