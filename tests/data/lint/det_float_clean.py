"""Clean twin: integer nanosecond math only."""

NS_PER_S = 1_000_000_000


def timeout_ns(seconds: int) -> int:
    return seconds * NS_PER_S


def ratio_floor(a: int, b: int) -> int:
    return a // b
