"""Fixture: non-daemon workers the lock-daemon rule flags."""
import threading


def spawn_probe(fn):
    t = threading.Thread(target=fn, name="probe")
    t.start()
    return t


def schedule(fn, delay):
    timer = threading.Timer(delay, fn)
    timer.start()
    return timer
