"""Clean twin: daemon kwarg, or daemon assigned before start()."""
import threading


def spawn_probe(fn):
    t = threading.Thread(target=fn, name="probe", daemon=True)
    t.start()
    return t


def schedule(fn, delay):
    timer = threading.Timer(delay, fn)
    timer.daemon = True
    timer.start()
    return timer
