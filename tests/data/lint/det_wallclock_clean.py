"""Clean twin: timestamps plumbed in; monotonic is local-only."""
import time


def header_time(now_ns: int):
    return now_ns


def elapsed(t0: int) -> int:
    return time.monotonic_ns() - t0
