"""Clean twin: sorted iteration and ordered structures."""


def hash_addresses(addrs):
    seen = set(addrs)
    out = b""
    for a in sorted(seen):
        out += a
    return out


def encode_parts(parts):
    by_index = {p.index: p for p in parts}
    return [by_index[i] for i in sorted(by_index)]
