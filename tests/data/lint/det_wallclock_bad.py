"""Fixture: wall-clock reads a determinism rule must flag."""
import time
from datetime import datetime
from time import time as now


def header_time():
    return time.time()


def sign_bytes_time():
    stamp = datetime.now()
    ns = time.time_ns()
    return stamp, ns


def from_import_evasion():
    return now()
