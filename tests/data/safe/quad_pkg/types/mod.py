"""Seeded safe-quadratic-decode: nested iteration over attacker-sized
collections in a decoder and in a validate_basic, with a clamped twin
and a set-membership twin staying green."""

from tendermint_tpu.encoding.proto import FieldReader

MAX_ITEMS = 100


def decode_bad_nested(data: bytes):
    r = FieldReader(data)
    items = r.get_all(1)
    pairs = []
    for a in items:  # BAD outer: attacker-sized
        for b in items:  # BAD inner: attacker-sized, no clamp
            pairs.append((a, b))
    return pairs


def decode_bad_membership(data: bytes):
    r = FieldReader(data)
    items = r.get_all(1)
    seen = []
    for x in items:  # attacker-sized loop ...
        if x in seen:  # BAD: O(n) list scan per element
            raise ValueError("duplicate")
        seen.append(x)
    return seen


def decode_clamped_nested(data: bytes):
    r = FieldReader(data)
    items = r.get_all(1)
    pairs = []
    for a in items[:MAX_ITEMS]:  # OK: one bound clamped
        for b in items:
            pairs.append((a, b))
    return pairs


def decode_set_membership(data: bytes):
    r = FieldReader(data)
    items = r.get_all(1)
    seen = set()
    for x in items:
        if x in seen:  # OK: set membership is O(1)
            raise ValueError("duplicate")
        seen.add(x)
    return list(seen)


class Thing:
    def __init__(self) -> None:
        self.parts = []
        self.names = []

    def validate_basic(self) -> None:
        for p in self.parts:  # validator loops are amplification
            for q in self.parts:  # BAD: quadratic pre-verification
                if p is not q and p == q:
                    raise ValueError("duplicate part")
