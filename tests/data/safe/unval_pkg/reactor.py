"""Seeded safe-unvalidated-use: an Envelope handler that reaches
VoteSet.add_vote without calling validate_basic first, with validated
/ transitively-validated / suppressed twins staying green."""

from .types.vote_set import VoteSet


class Envelope:
    def __init__(self, message=None) -> None:
        self.message = message


class Reactor:
    votes: VoteSet

    def __init__(self) -> None:
        self.votes = VoteSet()

    async def handle_bad(self, envelope: Envelope) -> None:
        msg = envelope.message
        self.votes.add_vote(msg)  # BAD: no validate_basic on the path

    async def handle_validated(self, envelope: Envelope) -> None:
        msg = envelope.message
        msg.validate_basic()
        self.votes.add_vote(msg)  # OK: validated first

    async def handle_transitive(self, envelope: Envelope) -> None:
        msg = envelope.message
        msg.validate_basic()
        self._apply(msg)  # OK: the guard covers the callee's sink too

    def _apply(self, msg) -> None:
        self.votes.add_vote(msg)

    async def handle_suppressed(self, envelope: Envelope) -> None:
        msg = envelope.message
        # tmsafe: safe-unvalidated-use-ok — fixture twin: validation is
        # definitionally elsewhere for this message kind
        self.votes.add_vote(msg)
