"""Fixture mirror of the real mutation-sink identity: the gate's
MUTATION_SINKS catalog keys on (path, qualname), so a fixture package
that defines types/vote_set.py::VoteSet.add_vote exercises the real
sink matching, not a test-only shim."""


class VoteSet:
    def __init__(self) -> None:
        self.votes = []

    def add_vote(self, vote) -> bool:
        self.votes.append(vote)
        return True
