"""Seeded safe-alloc-unbounded: decoders sizing allocations from
unclamped parsed varints, with clamped / guarded / suppressed twins
that must stay green. The decoders are discovered by the same schema
extraction that feeds the real gate (FieldReader reads of literal
tags), not by a hand catalog."""

from tendermint_tpu.encoding.proto import FieldReader

MAX_THING_BYTES = 1024


def decode_bad_bytes(data: bytes):
    r = FieldReader(data)
    n = r.uint(1)
    return bytes(n)  # BAD: unclamped parsed size


def decode_bad_range(data: bytes):
    r = FieldReader(data)
    count = r.uint(1)
    out = []
    for _ in range(count):  # BAD: unclamped parsed loop bound
        out.append(0)
    return out


def decode_bad_repeat(data: bytes):
    r = FieldReader(data)
    n = r.uint(1)
    return b"\x00" * n  # BAD: repetition sized by parsed int


def decode_bad_shift(data: bytes):
    r = FieldReader(data)
    size = r.uint(1)
    return (1 << size) - 1  # BAD: bigint allocation via shift


def decode_clamped(data: bytes):
    r = FieldReader(data)
    n = r.uint(1)
    if n > MAX_THING_BYTES:
        raise ValueError("too large")
    return bytes(n)  # OK: clamped against MAX_*


def decode_len_guarded(data: bytes):
    r = FieldReader(data)
    n = r.uint(1)
    if n > len(data):
        raise ValueError("length field exceeds payload")
    return bytes(n)  # OK: bounded by bytes actually received


def decode_min_clamped(data: bytes):
    r = FieldReader(data)
    n = r.uint(1)
    return bytes(min(n, MAX_THING_BYTES))  # OK: min() clamp


def decode_suppressed(data: bytes):
    r = FieldReader(data)
    n = r.uint(1)
    # tmsafe: safe-alloc-unbounded-ok — fixture twin: proves the
    # in-file suppression form reaches the line below
    return bytes(n)
