"""Seeded framing-family twin: a length-prefixed socket read with the
claimed length unclamped (BAD) next to the MAX_FRAME-guarded shape the
real connection layer uses (OK). Lives at p2p/conn.py inside the
fixture so the framing-module entry family discovers it, exactly like
the real tree."""

import struct

MAX_FRAME = 1 << 22


async def read_frame_bad(reader):
    hdr = await reader.readexactly(4)
    (length,) = struct.unpack(">I", hdr)
    return await reader.readexactly(length)  # BAD: unclamped claimed size


async def read_frame_guarded(reader):
    hdr = await reader.readexactly(4)
    (length,) = struct.unpack(">I", hdr)
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    return await reader.readexactly(length)  # OK: clamped first
