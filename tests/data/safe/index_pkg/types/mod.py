"""Seeded safe-index-unchecked: a decoder steering a subscript with an
unclamped parsed (signed!) integer, with range-checked / try-guarded /
suppressed twins staying green."""

from tendermint_tpu.encoding.proto import FieldReader

LOOKUP = ["a", "b", "c"]


def decode_bad_index(data: bytes):
    r = FieldReader(data)
    i = r.int64(1)
    return LOOKUP[i]  # BAD: int64 is signed; -1 aliases the last entry


def decode_checked_index(data: bytes):
    r = FieldReader(data)
    i = r.int64(1)
    if i < 0 or i >= len(LOOKUP):
        raise ValueError("index out of range")
    return LOOKUP[i]  # OK: range-checked


def decode_guarded_index(data: bytes):
    r = FieldReader(data)
    i = r.int64(1)
    try:
        return LOOKUP[i]  # OK: probe-and-translate idiom
    except IndexError:
        raise ValueError("index out of range") from None


def decode_suppressed_index(data: bytes):
    r = FieldReader(data)
    i = r.int64(1)
    # tmsafe: safe-index-unchecked-ok — fixture twin: suppression form
    return LOOKUP[i]
