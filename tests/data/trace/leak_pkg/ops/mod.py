"""Seeded tracer leaks: `tile` branches on a traced value and leaks
`float()` through an interprocedural call; `tile_clean` does the same
math with jnp.where / shape reads and must pass."""

import jax
import jax.numpy as jnp


def helper(v):
    return float(v)  # leak: concretizes a traced value


def tile(x, y):
    if x.sum() > 0:  # leak: Python branch on a traced value
        return x + y
    return x + helper(y)


def tile_clean(x, y, dual_fn=None):
    n = x.shape[1]  # shape reads are trace-static
    if n > 8:  # static branch: fine
        y = y * 2
    if dual_fn is None:  # None-check on a config param: fine
        z = jnp.where(x > 0, x + y, x - y)  # device select: fine
    else:
        z = dual_fn(x, y)
    return z


_JIT = jax.jit(tile)
_JIT_CLEAN = jax.jit(tile_clean)
