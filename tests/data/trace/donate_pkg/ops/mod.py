"""Seeded use-after-donate: `run` reads the buffer it donated to the
jit program; `run_clean` donates and never touches it again."""

import jax


def f(x):
    return x + 1


def g(x):
    return x * 2


_step = jax.jit(f, donate_argnums=(0,))
_step_clean = jax.jit(g, donate_argnums=(0,))


def run(buf):
    out = _step(buf)
    return out + buf  # flagged: buf's buffer was donated


def run_clean(buf):
    out = _step_clean(buf)
    return out + 1
