"""The seeded leak/shape sites from leak_pkg/dynshape_pkg, silenced
by every suppression form tmtrace honors: inline trace-ok, rule-named
trace-ok, comment-block-above, and the legacy tmlint disable for a
migrated rule. tmtrace must report NOTHING here."""

import jax
import jax.numpy as jnp


def helper(v):
    return float(v)  # tmtrace: trace-ok — fixture: host-side scalar by contract


def tile(x, y):
    # tmtrace: trace-ok=trace-tracer-leak — fixture: the justification
    # comment block above the offending line also covers it
    if x.sum() > 0:
        return x + y
    return x + helper(y)


def prep(batch):
    n = len(batch)
    # tmlint: disable=dev-shape-leak — fixture: legacy form for a
    # migrated rule must keep working
    return jnp.zeros((32, n), dtype=jnp.int32)


_JIT = jax.jit(tile)
