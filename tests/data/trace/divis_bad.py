"""A sharded-verifier class whose buckets do NOT round up to the mesh
width — handed to shardcheck.divisibility_violations by
tests/test_tmtrace.py to prove the gate turns red. Never imported by
production code."""


class BadSharded:
    """Mimics _MeshSharded's constructor contract but skips the
    round-up that makes every bucket divide by the mesh."""

    def __init__(self, mesh, bucket_sizes=None):
        self.mesh = mesh
        self.bucket_sizes = sorted(bucket_sizes or (8, 12, 100))

    def _bucket(self, n):
        for b in self.bucket_sizes:
            if b >= n:
                return b
        return n  # oversized: no mesh rounding either
