"""Seeded dynamic shape: `prep` hands a len()-derived dimension to a
jnp constructor (one XLA program per distinct batch size); the twin
launders it through the bucket table and must pass."""

import jax
import jax.numpy as jnp

BUCKETS = (8, 32, 128)


def bucket_for(n, sizes):
    for s in sizes:
        if s >= n:
            return s
    return n


def prep(batch):
    n = len(batch)
    return jnp.zeros((32, n), dtype=jnp.int32)  # dynamic: flagged


def prep_clean(batch):
    b = bucket_for(len(batch), BUCKETS)
    pad = jnp.zeros((32, b), dtype=jnp.int32)  # bucket-derived: fine
    rows, cols = pad.shape
    tail = jnp.zeros((rows, cols), dtype=jnp.int32)  # shape-derived
    return pad + tail


def body(x):
    return x * 2


_JIT = jax.jit(body)
