"""Seeded sharding mismatch: the mesh declares only the `sig` axis
but one PartitionSpec names `model` — dispatch would raise on the
first sharded call, mid-claim."""

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SIG_AXIS = "sig"


def make_mesh(devs):
    return Mesh(np.array(devs), (SIG_AXIS,))


def shard(mesh, fn):
    vec = NamedSharding(mesh, P(SIG_AXIS))  # declared: fine
    mat = NamedSharding(mesh, P(None, "sig"))  # literal, declared: fine
    bad = NamedSharding(mesh, P("model"))  # undeclared axis: flagged
    return jax.jit(fn, in_shardings=(mat,), out_shardings=vec), bad
