"""Seeded cross-identity race: SPLIT is written by `handler` (the
main-loop identity ONLY) and by `worker_write` (a worker-thread
identity ONLY) — each endpoint function has root degree 1, so only
the union of the sites' identities reveals the race (regression: the
collector once only looked inside the per-function concurrent region
and missed this class entirely). SPLIT_GUARDED is the locked twin:
same two single-identity endpoints, every write under _lock."""

import threading

SPLIT = 0
SPLIT_GUARDED = 0
_lock = threading.Lock()


def worker_write() -> None:
    global SPLIT
    SPLIT = 1


def worker_write_guarded() -> None:
    global SPLIT_GUARDED
    with _lock:
        SPLIT_GUARDED = 1


def start() -> None:
    t = threading.Thread(target=worker_write, daemon=True)
    t.start()
    t2 = threading.Thread(target=worker_write_guarded, daemon=True)
    t2.start()


async def handler() -> None:
    global SPLIT, SPLIT_GUARDED
    SPLIT = 2
    with _lock:
        SPLIT_GUARDED = 2
