"""Seeded race: COUNTER is written from two concurrent roots (the
spawned worker thread and the asyncio handler) with no common lock.
GUARDED takes the same two paths but every write holds _lock."""

import threading

COUNTER = 0
GUARDED = 0
_lock = threading.Lock()


def bump() -> None:
    global COUNTER
    COUNTER += 1


def bump_guarded() -> None:
    global GUARDED
    with _lock:
        GUARDED += 1


def worker() -> None:
    bump()
    bump_guarded()


def start() -> None:
    t = threading.Thread(target=worker, daemon=True)
    t.start()


async def handler() -> None:
    bump()
    bump_guarded()
