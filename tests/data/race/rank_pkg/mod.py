"""Seeded lock-order hazards: low_then_high contradicts the RANK the
test supplies (a_lock outranks b_lock there), and ab/ba together form
a two-lock cycle no single path shows."""

import threading

a_lock = threading.Lock()
b_lock = threading.Lock()
c_lock = threading.Lock()
d_lock = threading.Lock()


def low_then_high() -> None:
    with a_lock:
        with b_lock:
            pass


def ab() -> None:
    with c_lock:
        with d_lock:
            pass


def ba() -> None:
    with d_lock:
        with c_lock:
            pass


def worker() -> None:
    low_then_high()
    ab()
    ba()


def start() -> None:
    threading.Thread(target=worker, daemon=True).start()
