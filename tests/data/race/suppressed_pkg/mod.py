"""The unguarded_pkg shapes with every suppression form applied: this
package MUST analyze clean."""

import threading

LATCH = 0
ASSERTED = 0
JUSTIFIED: set = set()
_side_lock = threading.Lock()


def set_latch() -> None:
    global LATCH
    # tmrace: race-ok — idempotent latch, fixture twin of the
    # tpu_verifier._STREAMING idiom
    LATCH = 1


def indirect() -> None:
    global ASSERTED
    ASSERTED = 1  # tmrace: guarded-by=_side_lock


def justified_mutation() -> None:
    # tmlint: disable=lock-global-mutation — GIL-atomic set add,
    # fixture twin of the sigcache idiom
    JUSTIFIED.add(1)


def worker() -> None:
    set_latch()
    indirect()
    justified_mutation()


def start() -> None:
    threading.Thread(target=worker, daemon=True).start()


async def handler() -> None:
    set_latch()
    indirect()
    justified_mutation()
