"""Nested-def scoping: `outer_local` assigns a plain LOCAL `N` while
its nested `helper_n` declares `global N` — the enclosing write must
NOT be reclassified as a module-global write. And `reader`'s nested
`helper_m` binds `M` only in its own scope — that must not hide the
outer function's read of the module global `M`, which pairs with
`writer_handler`'s unguarded main-loop write into a real race."""

import threading

N = 0
M = 0


def outer_local() -> None:
    N = 1

    def helper_n() -> None:
        global N
        N = 2

    helper_n()


def reader() -> None:
    def helper_m(M) -> None:
        return M

    if M:
        pass


def start() -> None:
    threading.Thread(target=reader, daemon=True).start()


async def writer_handler() -> None:
    global M
    M = 3
