"""cost-unclamped-alloc fixture: allocations proportional to
store/attacker bounds, with clamped / guard-reclassed / suppressed
twins."""

from .rpctypes import RPCRequest

MAX_BUF = 4096


class Env:
    def __init__(self, block_store) -> None:
        self.block_store = block_store

    async def store_buf(self, req: RPCRequest):
        """RED: buffer sized by the whole store height range."""
        n = self.block_store.height()
        return bytes(n)

    async def store_buf_clamped(self, req: RPCRequest):
        """GREEN: min() clamp between derivation and use."""
        n = self.block_store.height()
        return bytes(min(n, MAX_BUF))

    async def attacker_repeat(self, req: RPCRequest):
        """RED: sequence repetition sized by a request integer."""
        n = int(req.params.get("n"))
        return b"\x00" * n

    async def attacker_repeat_guarded(self, req: RPCRequest):
        """GREEN: the guard-then-raise idiom re-classes n."""
        n = int(req.params.get("n"))
        if n > MAX_BUF:
            raise ValueError("too big")
        return b"\x00" * n

    async def store_buf_suppressed(self, req: RPCRequest):
        """GREEN (suppressed)."""
        n = self.block_store.height()
        # tmcost: cost-unclamped-alloc-ok — fixture rationale: bounded
        # by an out-of-band operator invariant
        return bytes(n)
