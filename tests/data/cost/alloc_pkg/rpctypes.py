class RPCRequest:
    params: dict = {}
