"""cost-superlinear fixture: nested known-unbounded bounds per
request, with clamped / suppressed / helper-fold twins."""

from .rpctypes import RPCRequest

MAX_PAGE = 20


class ValSet:
    validators: list = []


def scan(req: RPCRequest, vals: ValSet):
    """RED: attacker-sized outer loop x validator-set inner loop."""
    total = 0
    for h in req.params.get("heights"):
        for v in vals.validators:
            total += h + v
    return total


def scan_clamped(req: RPCRequest, vals: ValSet):
    """GREEN: one clamp is enough — MAX_PAGE x vset is vset-linear."""
    total = 0
    for h in req.params.get("heights")[:MAX_PAGE]:
        for v in vals.validators:
            total += h + v
    return total


def scan_suppressed(req: RPCRequest, vals: ValSet):
    """GREEN (suppressed): the reviewed-rationale escape hatch."""
    total = 0
    for h in req.params.get("heights"):
        # tmcost: cost-superlinear-ok — fixture rationale: the inner
        # set is bounded elsewhere by protocol admission
        for v in vals.validators:
            total += h + v
    return total


def _tally(vals: ValSet) -> int:
    s = 0
    for v in vals.validators:
        s += v
    return s


def scan_via_helper(req: RPCRequest, vals: ValSet):
    """RED at the call site: the callee's vset term folds into the
    attacker loop (interprocedural cost summaries)."""
    out = 0
    for h in req.params.get("heights"):
        out += _tally(vals) + h
    return out
