"""Stub request/envelope types so root discovery fires on the fixture
exactly as it does on the real package (annotation-name match)."""


class RPCRequest:
    params: dict = {}


class Envelope:
    message = None
    from_peer = ""
