"""The sanctioned memo layer: expensive calls in a module named
servingcache.py are the cache's miss path, exempt by design."""


class Cache:
    def __init__(self, block_store) -> None:
        self.block_store = block_store
        self._blobs: dict = {}

    def blob(self, height: int) -> bytes:
        got = self._blobs.get(height)
        if got is not None:
            return got
        meta = self.block_store.load_block_meta(height)
        out = meta.header.to_proto()  # GREEN: the cache IS the fix
        self._blobs[height] = out
        return out
