"""cost-recompute fixture: expensive pure encode of store-derived
content per request, with cached / suppressed twins."""

from .rpctypes import RPCRequest
from .servingcache import Cache


class Env:
    def __init__(self, block_store) -> None:
        self.block_store = block_store
        self.cache: Cache = Cache(block_store)

    async def header_raw(self, req: RPCRequest):
        """RED: per-block-immutable store content re-encoded per
        request."""
        meta = self.block_store.load_block_meta(req.params.get("height"))
        return {"header": meta.header.to_proto().hex()}

    async def header_cached(self, req: RPCRequest):
        """GREEN: the work lives in the serving-cache module."""
        blob = self.cache.blob(req.params.get("height"))
        return {"header": blob.hex()}

    async def header_suppressed(self, req: RPCRequest):
        """GREEN (suppressed): reviewed-rationale escape hatch."""
        meta = self.block_store.load_block_meta(req.params.get("height"))
        # tmcost: cost-recompute-ok — fixture rationale: this encode is
        # O(1) for this message shape
        return {"header": meta.header.to_proto().hex()}
