class RPCRequest:
    params: dict = {}
