"""tmlint + lockwatch: the correctness-tooling gate.

Two jobs: (1) run the consensus-invariant static analyzer over the
whole package on every tier-1 invocation, so a new nondeterminism /
lock-discipline / device-hygiene violation fails CI the way `-race`
and `go vet` gate the reference; (2) unit-test the analyzer and the
lock-order observer themselves against the fixture corpus in
tests/data/lint/.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from tendermint_tpu.analysis import lockwatch, tmlint

FIXTURES = os.path.join(os.path.dirname(__file__), "data", "lint")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def fixture_src(name: str) -> str:
    with open(os.path.join(FIXTURES, name), "r", encoding="utf-8") as f:
        return f.read()


def run_on_fixture(name: str, as_path: str, rule: str):
    return tmlint.check_source(fixture_src(name), as_path, rules=[rule])


# ---------------------------------------------------------------------------
# THE gate: whole package against the checked-in baseline


def test_package_clean_against_baseline():
    """Every rule over every package file; anything beyond
    analysis/baseline.json fails this tier-1 test — fix it, suppress
    it with a justification, or consciously re-baseline (see
    docs/static_analysis.md)."""
    violations = tmlint.check_package()
    new = tmlint.new_violations(violations, tmlint.load_baseline())
    assert not new, "new tmlint violations:\n" + "\n".join(
        v.render() for v in new
    )


def test_full_package_run_under_budget():
    """Bench-guard-style cost ceiling: the analyzer must stay cheap
    enough to run on every tier-1 invocation (10 s on CPU; measured
    ~1 s for ~150 files)."""
    t0 = time.monotonic()
    tmlint.check_package()
    elapsed = time.monotonic() - t0
    assert elapsed < 10.0, f"tmlint full-package run took {elapsed:.1f}s"


def test_seeded_violation_in_consensus_module_fails_gate():
    """A new wall-clock read seeded into a consensus-critical module
    must surface as a NEW violation against the real baseline."""
    bad = "import time\n\n\ndef stamp():\n    return time.time()\n"
    violations = tmlint.check_source(bad, "types/seeded_fixture.py")
    assert any(v.rule == "det-wallclock" for v in violations)
    new = tmlint.new_violations(violations, tmlint.load_baseline())
    assert any(v.rule == "det-wallclock" for v in new)


# ---------------------------------------------------------------------------
# per-rule fixture corpus: each rule flags its bad snippet and passes
# the clean twin

_CASES = [
    # (rule, bad fixture, clean fixture, synthetic in-package path)
    ("det-wallclock", "det_wallclock_bad.py", "det_wallclock_clean.py",
     "types/fixture.py"),
    ("det-random", "det_random_bad.py", "det_random_clean.py",
     "consensus/fixture.py"),
    ("det-float", "det_float_bad.py", "det_float_clean.py",
     "encoding/fixture.py"),
    ("det-set-iter", "det_set_iter_bad.py", "det_set_iter_clean.py",
     "crypto/merkle.py"),
    ("lock-daemon", "lock_daemon_bad.py", "lock_daemon_clean.py",
     "crypto/fixture.py"),
    ("lock-global-mutation", "lock_global_mutation_bad.py",
     "lock_global_mutation_clean.py", "crypto/fixture.py"),
    # dev-host-sync / dev-shape-leak migrated to tmtrace (PR 8):
    # their fixture-corpus tests live in tests/test_tmtrace.py now
]


@pytest.mark.parametrize(
    "rule,bad,clean,path", _CASES, ids=[c[0] for c in _CASES]
)
def test_rule_flags_bad_and_passes_clean(rule, bad, clean, path):
    flagged = run_on_fixture(bad, path, rule)
    assert flagged, f"{rule} missed every violation in {bad}"
    assert all(v.rule == rule for v in flagged)
    assert run_on_fixture(clean, path, rule) == [], (
        f"{rule} false-positived on {clean}"
    )


def test_every_rule_class_covered():
    """The acceptance criterion, mechanically: every registered rule
    has a bad fixture it flags and a clean twin it passes."""
    assert {c[0] for c in _CASES} == set(tmlint.rule_ids())


@pytest.mark.parametrize(
    "rule,bad,path",
    [(c[0], c[1], c[3]) for c in _CASES if c[0].startswith("det-")],
    ids=[c[0] for c in _CASES if c[0].startswith("det-")],
)
def test_determinism_rules_scoped_to_consensus_critical(rule, bad, path):
    """The same hazardous source outside the consensus-critical (or
    replay) scope is NOT flagged — p2p jitter may use wall clock and
    floats freely."""
    assert tmlint.check_source(fixture_src(bad), "p2p/fixture.py",
                               rules=[rule]) == []


def test_device_rules_no_longer_registered():
    """dev-host-sync / dev-shape-leak moved to tmtrace (PR 8) so one
    site is never double-reported; tmlint must not know the ids."""
    assert "dev-host-sync" not in tmlint.rule_ids()
    assert "dev-shape-leak" not in tmlint.rule_ids()
    with pytest.raises(ValueError):
        tmlint.check_source("x = 1\n", "parallel/f.py",
                            rules=["dev-host-sync"])


def test_lock_rules_scoped_to_threading_importers():
    src = "_CACHE: dict = {}\n\n\ndef remember(k, v):\n    _CACHE[k] = v\n"
    assert tmlint.check_source(
        src, "crypto/fixture.py", rules=["lock-global-mutation"]
    ) == []


# ---------------------------------------------------------------------------
# suppressions


def test_suppression_same_line_and_comment_above():
    violations = tmlint.check_source(
        fixture_src("suppressed.py"), "types/fixture.py",
        rules=["det-wallclock"],
    )
    # only the deliberately unsuppressed site survives
    assert len(violations) == 1
    line = violations[0].source
    assert "time.time()" in line
    src = fixture_src("suppressed.py")
    assert src.splitlines()[violations[0].line - 2].strip() == (
        "def unsuppressed():"
    )


def test_suppression_only_silences_named_rule():
    src = (
        "import time\n\n\ndef f():\n"
        "    return time.time()  # tmlint: disable=det-float\n"
    )
    violations = tmlint.check_source(src, "types/fixture.py")
    assert any(v.rule == "det-wallclock" for v in violations)


# ---------------------------------------------------------------------------
# baseline round-trip


def test_baseline_round_trip(tmp_path):
    bad = fixture_src("det_wallclock_bad.py")
    violations = tmlint.check_source(bad, "types/fixture.py")
    assert violations
    path = str(tmp_path / "baseline.json")
    tmlint.save_baseline(violations, path)
    # accepted: same violations are not "new"
    assert tmlint.new_violations(violations, tmlint.load_baseline(path)) == []
    # a NEW violation (textually distinct source line) is flagged;
    # identical lines would instead trip the counting path below
    grown = bad + "\n\ndef more():\n    later = time.time()\n    return later\n"
    regrown = tmlint.check_source(grown, "types/fixture.py")
    new = tmlint.new_violations(regrown, tmlint.load_baseline(path))
    assert len(new) == 1 and "later" in new[0].source
    assert new[0].line > len(bad.splitlines())


def test_baseline_counts_duplicate_lines():
    """Duplicating a grandfathered bad line is itself a new violation:
    fingerprints are counted, not just present/absent."""
    one = "import time\n\n\ndef f():\n    return time.time()\n"
    v1 = tmlint.check_source(one, "types/fixture.py")
    base = tmlint.baseline_counts(v1)
    two = one + "\n\ndef g():\n    return time.time()\n"
    v2 = tmlint.check_source(two, "types/fixture.py")
    new = tmlint.new_violations(v2, base)
    assert len(new) == 2  # both occurrences reported, allowance noted
    assert "baseline allows 1" in new[0].message


def test_baseline_file_is_checked_in_and_loads():
    assert os.path.exists(tmlint.BASELINE_PATH)
    with open(tmlint.BASELINE_PATH) as f:
        data = json.load(f)
    assert data["version"] == 1
    assert isinstance(data["entries"], dict)


# ---------------------------------------------------------------------------
# CLI contract


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"), *args],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def test_cli_clean_exit_zero():
    r = _run_cli("--stats")
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_unknown_rule_exit_two():
    r = _run_cli("--rule", "no-such-rule")
    assert r.returncode == 2
    assert "no-such-rule" in r.stderr


def test_cli_list_rules():
    r = _run_cli("--list-rules")
    assert r.returncode == 0
    for rid in tmlint.rule_ids():
        assert rid in r.stdout


# ---------------------------------------------------------------------------
# lockwatch


def _watched_pair(watch):
    a = lockwatch._WatchedLock(watch, threading.Lock(), "A")
    b = lockwatch._WatchedLock(watch, threading.Lock(), "B")
    return a, b


def test_lockwatch_detects_ab_ba_cycle():
    """The deliberate A->B / B->A construction: two threads witness
    opposite orders (sequenced so the test itself can't deadlock) and
    the report must name the cycle."""
    watch = lockwatch.LockWatch(hold_budget_s=10.0)
    a, b = _watched_pair(watch)
    t1_done = threading.Event()

    def t1():
        with a:
            with b:
                pass
        t1_done.set()

    def t2():
        t1_done.wait(5.0)
        with b:
            with a:
                pass

    th1 = threading.Thread(target=t1, daemon=True)
    th2 = threading.Thread(target=t2, daemon=True)
    th1.start(); th2.start()
    th1.join(5.0); th2.join(5.0)
    report = watch.report()
    assert ("A", "B") in report.edges and ("B", "A") in report.edges
    assert report.cycles, report.render()
    assert sorted(report.cycles[0]) == ["A", "B"]
    assert "CYCLE" in report.render()


def test_lockwatch_consistent_order_is_clean():
    watch = lockwatch.LockWatch(hold_budget_s=10.0)
    a, b = _watched_pair(watch)
    for _ in range(3):
        with a:
            with b:
                pass
    report = watch.report()
    assert report.edges == {("A", "B"): report.edges[("A", "B")]}
    assert report.cycles == []
    assert report.order_violations({"A": 1, "B": 2}) == []


def test_lockwatch_rank_violation():
    watch = lockwatch.LockWatch(hold_budget_s=10.0)
    a, b = _watched_pair(watch)
    with b:
        with a:  # declared order says A before B
            pass
    report = watch.report()
    bad = report.order_violations({"A": 1, "B": 2})
    assert len(bad) == 1 and bad[0]["edge"] == ("B", "A")


def test_lockwatch_hold_budget():
    watch = lockwatch.LockWatch(hold_budget_s=0.01)
    a, _ = _watched_pair(watch)
    with a:
        time.sleep(0.05)
    report = watch.report()
    assert len(report.long_holds) == 1
    assert report.long_holds[0]["name"] == "A"
    assert report.long_holds[0]["held_s"] >= 0.01


def test_lockwatch_rlock_reentry_is_not_a_self_cycle():
    watch = lockwatch.LockWatch(hold_budget_s=10.0)
    r = lockwatch._WatchedLock(watch, threading.RLock(), "R")
    with r:
        with r:
            pass
    report = watch.report()
    assert report.cycles == []
    assert ("R", "R") not in report.edges


def test_lockwatch_enable_disable_restores_modules():
    from tendermint_tpu.crypto import breaker, sigcache, tpu_verifier

    orig_sig = sigcache._lock
    orig_wedged = tpu_verifier._wedged_lock
    orig_threading = breaker.threading
    watch = lockwatch.enable()
    try:
        assert lockwatch.active() is watch
        assert isinstance(sigcache._lock, lockwatch._WatchedLock)
        # locks born during the window are watched and class-named
        br = breaker.CircuitBreaker("lint-fixture")
        assert isinstance(br._lock, lockwatch._WatchedLock)
        assert br._lock._name == "breaker.instance"
        br.record_failure()
        br.close_now()
    finally:
        report = lockwatch.disable()
    assert lockwatch.active() is None
    assert sigcache._lock is orig_sig
    assert tpu_verifier._wedged_lock is orig_wedged
    assert breaker.threading is orig_threading
    assert report.acquisitions > 0
    assert report.cycles == []
    assert report.order_violations() == []


def test_lockwatch_breaker_registry_order_witnessed():
    """fresh() takes breaker.registry then the retired instance's
    lock — the canonical declared edge; the chaos suites must witness
    it in THIS order only."""
    from tendermint_tpu.crypto import breaker

    lockwatch.enable()
    try:
        breaker.breaker_for("lint-order-fixture")
        breaker.fresh("lint-order-fixture")
        breaker.discard("lint-order-fixture")
    finally:
        report = lockwatch.disable()
    edge = ("breaker.registry", "breaker.instance")
    assert edge in report.edges
    assert report.cycles == []
    assert report.order_violations() == []


def test_cli_baseline_update_refuses_filtered_runs(tmp_path):
    """--baseline-update over a --rule or path subset would overwrite
    the whole baseline with the filtered slice, deleting every other
    grandfathered entry — refused with the usage exit code."""
    r = _run_cli("--rule", "det-float", "--baseline-update")
    assert r.returncode == 2 and "full-package" in r.stderr
    r = _run_cli("tendermint_tpu/crypto/batch.py", "--baseline-update")
    assert r.returncode == 2
    # and the real baseline was not touched
    assert tmlint.new_violations(
        tmlint.check_package(), tmlint.load_baseline()
    ) == []


def test_lockwatch_witnesses_import_time_metric_locks():
    """DEFAULT_REGISTRY's instruments were created at import, before
    any watch window — enable() must wrap their locks in place so the
    RANK-documented *->metrics.metric edges are witnessed, not
    assumed. sigcache._rotate bumps its eviction counter under the
    rotation lock: that edge must appear."""
    from tendermint_tpu.crypto import sigcache

    lockwatch.enable()
    try:
        with sigcache._lock:
            sigcache._m_evictions.inc(0)
    finally:
        report = lockwatch.disable()
    assert ("sigcache.rotate", "metrics.metric") in report.edges
    assert report.cycles == []
    assert report.order_violations() == []
    # restored: the registry's instruments carry real locks again
    assert not isinstance(
        sigcache._m_evictions._lock, lockwatch._WatchedLock
    )


def test_lockwatch_window_survivor_reports_to_active_watch():
    """A lock created inside one window but still alive in the next
    must record into the ACTIVE watch, not its dead creator."""
    w1 = lockwatch.LockWatch(hold_budget_s=10.0)
    survivor = lockwatch._WatchedLock(w1, threading.Lock(), "S")
    w2 = lockwatch.enable()
    try:
        other = lockwatch._WatchedLock(w2, threading.Lock(), "T")
        with survivor:
            with other:
                pass
    finally:
        report = lockwatch.disable()
    assert ("S", "T") in report.edges
    assert w1.report().edges == {}


def test_determinism_rules_catch_from_import_style():
    """The gate must not be evadable by import style: `from random
    import choice` / `from time import time as now` resolve to the
    same banned targets as the dotted forms."""
    src = (
        "from random import choice\n"
        "from time import time as now\n\n\n"
        "def pick(xs):\n    return choice(xs)\n\n\n"
        "def stamp():\n    return now()\n"
    )
    violations = tmlint.check_source(src, "consensus/fixture.py")
    assert any(v.rule == "det-random" for v in violations)
    violations = tmlint.check_source(src, "types/fixture.py")
    assert {v.rule for v in violations} >= {"det-random", "det-wallclock"}
