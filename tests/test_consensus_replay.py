"""Handshaker / ReplayBlocks tests — app behind store, crash between
SaveBlock and state save, crash between Commit and state save
(reference model: internal/consensus/replay_test.go)."""

import asyncio

import pytest

from tendermint_tpu.abci import KVStoreApplication, LocalClient
from tendermint_tpu.abci import types as abci
from tendermint_tpu.consensus.replay import Handshaker, HandshakeError
from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.state import state_from_genesis

from .test_consensus_state import Node, single_genesis

CHAIN = "cs-chain"


def run(coro):
    return asyncio.run(coro)


async def run_chain_to(node, height):
    await node.cs.start()
    await node.cs.wait_for_height(height, timeout=30.0)
    await node.cs.stop()


def test_fresh_chain_init_chain():
    async def go():
        priv = PrivKeyEd25519.from_seed(b"\x21" * 32)
        genesis = single_genesis(priv)
        state = state_from_genesis(genesis)
        app = KVStoreApplication()
        client = LocalClient(app)
        node = Node(priv, genesis)  # for stores only; not started
        h = Handshaker(
            node.state_store, state, node.block_store, genesis
        )
        await h.handshake(client)
        assert h.n_blocks == 0
        # InitChain delivered the validator set to the app
        assert len(app.validator_set) == 1

    run(go())


def test_app_behind_store_replays_into_app():
    async def go():
        priv = PrivKeyEd25519.from_seed(b"\x22" * 32)
        genesis = single_genesis(priv)
        node = Node(priv, genesis)
        # real boot order: handshake (InitChain) before consensus starts
        boot = Handshaker(
            node.state_store, node.state_store.load(), node.block_store,
            genesis,
        )
        await boot.handshake(node.client)
        node.cs.state = node.state_store.load()
        await run_chain_to(node, 4)
        tip = node.block_store.height()
        state = node.state_store.load()
        assert state.last_block_height == tip

        # a fresh app instance (height 0) must be caught up via replay
        fresh_app = KVStoreApplication()
        fresh_client = LocalClient(fresh_app)
        h = Handshaker(
            node.state_store, state, node.block_store, genesis
        )
        app_hash = await h.handshake(fresh_client)
        assert h.n_blocks == tip
        assert fresh_app.height == tip
        assert app_hash == state.app_hash
        info = await fresh_client.info(abci.RequestInfo())
        assert info.last_block_height == tip

    run(go())


def test_crash_before_apply_replays_last_block_with_real_app():
    async def go():
        priv = PrivKeyEd25519.from_seed(b"\x23" * 32)
        genesis = single_genesis(priv)
        node = Node(priv, genesis)

        # crash after SaveBlock(3) but before ApplyBlock(3)
        real_apply = node.exec.apply_block

        async def crashing_apply(state, block_id, block):
            if block.header.height == 3:
                raise RuntimeError("simulated crash before apply")
            return await real_apply(state, block_id, block)

        node.exec.apply_block = crashing_apply
        await node.cs.start()
        with pytest.raises(TimeoutError):
            await node.cs.wait_for_height(4, timeout=1.5)
        await node.cs.stop()

        assert node.block_store.height() == 3
        state = node.state_store.load()
        assert state.last_block_height == 2
        assert node.app.height == 2  # app also never saw block 3

        node.exec.apply_block = real_apply
        h = Handshaker(
            node.state_store, state, node.block_store, genesis
        )
        app_hash = await h.handshake(node.client)
        new_state = node.state_store.load()
        assert new_state.last_block_height == 3
        assert node.app.height == 3
        assert app_hash == new_state.app_hash

    run(go())


def test_crash_after_commit_replays_with_mock_app():
    async def go():
        priv = PrivKeyEd25519.from_seed(b"\x24" * 32)
        genesis = single_genesis(priv)
        node = Node(priv, genesis)

        # crash after the app committed height 3 but before state save
        real_save = node.state_store.save

        def crashing_save(state):
            if state.last_block_height == 3:
                raise RuntimeError("simulated crash before state save")
            return real_save(state)

        node.state_store.save = crashing_save
        await node.cs.start()
        with pytest.raises(TimeoutError):
            await node.cs.wait_for_height(4, timeout=1.5)
        await node.cs.stop()
        node.state_store.save = real_save

        assert node.block_store.height() == 3
        state = node.state_store.load()
        assert state.last_block_height == 2
        assert node.app.height == 3  # app DID commit block 3
        app_commits_before = node.app.height

        h = Handshaker(
            node.state_store, state, node.block_store, genesis
        )
        app_hash = await h.handshake(node.client)
        new_state = node.state_store.load()
        assert new_state.last_block_height == 3
        # the real app was not driven again (mock served the responses)
        assert node.app.height == app_commits_before
        assert app_hash == new_state.app_hash

    run(go())


def test_app_ahead_of_store_is_an_error():
    async def go():
        priv = PrivKeyEd25519.from_seed(b"\x25" * 32)
        genesis = single_genesis(priv)
        node = Node(priv, genesis)
        await run_chain_to(node, 3)
        state = node.state_store.load()

        class AheadApp(KVStoreApplication):
            def info(self, req):
                return abci.ResponseInfo(last_block_height=99)

        h = Handshaker(
            node.state_store, state, node.block_store, genesis
        )
        with pytest.raises(HandshakeError, match="ahead of store"):
            await h.handshake(LocalClient(AheadApp()))

    run(go())
