"""Remote signer tests (reference model: privval/signer_client_test.go,
signer_listener_endpoint_test.go): endpoint pairing over a real TCP
socket with SecretConnection, double-sign refusal through the wire,
reconnect behavior, and a full node producing blocks with its key held
by an external signer process."""

import asyncio
import time

import pytest

from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.privval import (
    FilePV,
    RemoteSignerError,
    RetrySignerClient,
    SignerListenerEndpoint,
    SignerServer,
)
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.canonical import PRECOMMIT_TYPE, PREVOTE_TYPE
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.vote import Vote

CHAIN = "signer-chain"


def run(coro):
    return asyncio.run(coro)


def _file_pv(tmp_path, seed=b"\x41"):
    return FilePV.from_priv_key(
        PrivKeyEd25519.from_seed(seed * 32),
        str(tmp_path / "pv_key.json"),
        str(tmp_path / "pv_state.json"),
    )


def _block_id(tag: bytes = b"\xaa") -> BlockID:
    return BlockID(
        hash=tag * 32,
        part_set_header=PartSetHeader(total=1, hash=b"\xbb" * 32),
    )


async def _pair(tmp_path, seed=b"\x41"):
    """Listener (node side) + signer server connected over loopback."""
    pv = _file_pv(tmp_path, seed)
    node_key = PrivKeyEd25519.from_seed(b"\x51" * 32)
    listener = SignerListenerEndpoint(
        "tcp://127.0.0.1:0", node_key, accept_timeout=10.0
    )
    await listener.start()
    signer = SignerServer(
        f"127.0.0.1:{listener.bound_port}", pv, redial_delay=0.1
    )
    await signer.start()
    return pv, listener, signer


def test_pubkey_vote_proposal_roundtrip(tmp_path):
    async def go():
        pv, listener, signer = await _pair(tmp_path)
        try:
            client = RetrySignerClient(listener, retries=10, delay=0.2)
            pk = await client.get_pub_key()
            assert pk.bytes() == pv.key.pub_key.bytes()

            vote = Vote(
                type=PREVOTE_TYPE,
                height=5,
                round=0,
                block_id=_block_id(),
                timestamp_ns=time.time_ns(),
                validator_address=pv.key.address,
                validator_index=0,
            )
            await client.sign_vote(CHAIN, vote)
            assert pk.verify_signature(vote.sign_bytes(CHAIN), vote.signature)

            prop = Proposal(
                height=6,
                round=0,
                pol_round=-1,
                block_id=_block_id(b"\xcc"),
                timestamp_ns=time.time_ns(),
            )
            await client.sign_proposal(CHAIN, prop)
            assert pk.verify_signature(
                prop.sign_bytes(CHAIN), prop.signature
            )
        finally:
            await signer.stop()
            await listener.stop()

    run(go())


def test_double_sign_refused_over_the_wire(tmp_path):
    """The signer's FilePV last-sign state must protect against
    conflicting votes exactly as a local key would
    (reference: privval/file.go:109 + signer request handler)."""

    async def go():
        pv, listener, signer = await _pair(tmp_path, seed=b"\x42")
        try:
            client = RetrySignerClient(listener, retries=10, delay=0.2)
            ts = time.time_ns()
            vote1 = Vote(
                type=PRECOMMIT_TYPE,
                height=9,
                round=0,
                block_id=_block_id(b"\x01"),
                timestamp_ns=ts,
                validator_address=pv.key.address,
                validator_index=0,
            )
            await client.sign_vote(CHAIN, vote1)
            # conflicting block at the same HRS: must be refused, and
            # the refusal must NOT be retried into success
            vote2 = Vote(
                type=PRECOMMIT_TYPE,
                height=9,
                round=0,
                block_id=_block_id(b"\x02"),
                timestamp_ns=ts,
                validator_address=pv.key.address,
                validator_index=0,
            )
            with pytest.raises(RemoteSignerError):
                await client.sign_vote(CHAIN, vote2)
            # same HRS and same block: signature is replayed, not re-signed
            vote3 = Vote(
                type=PRECOMMIT_TYPE,
                height=9,
                round=0,
                block_id=_block_id(b"\x01"),
                timestamp_ns=ts,
                validator_address=pv.key.address,
                validator_index=0,
            )
            await client.sign_vote(CHAIN, vote3)
            assert vote3.signature == vote1.signature
        finally:
            await signer.stop()
            await listener.stop()

    run(go())


def test_signer_reconnects_after_drop(tmp_path):
    async def go():
        pv, listener, signer = await _pair(tmp_path, seed=b"\x43")
        try:
            client = RetrySignerClient(listener, retries=20, delay=0.1)
            await client.get_pub_key()
            # kill the live connection; the signer's dial loop re-dials
            listener._conn.close()
            listener._conn = None
            listener._conn_ready.clear()
            pk = await client.get_pub_key()
            assert pk.bytes() == pv.key.pub_key.bytes()
        finally:
            await signer.stop()
            await listener.stop()

    run(go())


def test_node_with_remote_signer_produces_blocks(tmp_path):
    """A validator node whose privval is the remote-signer client, with
    the key living in an external SignerServer, reaches consensus
    (reference: the e2e harness's privval=tcp mode)."""
    from tendermint_tpu.config import Config
    from tendermint_tpu.node import make_node
    from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

    async def go():
        priv = PrivKeyEd25519.from_seed(b"\x44" * 32)
        genesis = GenesisDoc(
            chain_id="rs-chain",
            genesis_time_ns=time.time_ns(),
            validators=[GenesisValidator(pub_key=priv.pub_key(), power=10)],
        )
        cfg = Config()
        cfg.base.home = str(tmp_path / "node")
        cfg.base.chain_id = "rs-chain"
        cfg.base.db_backend = "memdb"
        cfg.consensus.timeout_commit = 0.2
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        cfg.priv_validator.listen_addr = "tcp://127.0.0.1:0"
        cfg.ensure_dirs()
        genesis.save_as(cfg.base.path(cfg.base.genesis_file))

        node = make_node(cfg)
        # external signer process holds the actual key
        signer_pv = FilePV.from_priv_key(
            priv,
            str(tmp_path / "signer_key.json"),
            str(tmp_path / "signer_state.json"),
        )
        # start the node; consensus blocks on get_pub_key until the
        # signer dials in
        start_task = asyncio.ensure_future(node.start())
        await asyncio.sleep(0.3)  # listener is up early in boot
        signer = SignerServer(
            f"127.0.0.1:{node.privval_listener.bound_port}",
            signer_pv,
            redial_delay=0.1,
        )
        await signer.start()
        await start_task
        try:
            await node.consensus.wait_for_height(3, timeout=60.0)
            assert node.block_store.height() >= 2
        finally:
            await node.stop()
            await signer.stop()

    run(go())


def test_signer_refuses_foreign_chain_id(tmp_path):
    """A chain-id-pinned SignerServer refuses sign requests for any
    other chain (reference: signer_requestHandler.go
    DefaultValidationRequestHandler chainID check) — a misconfigured
    node cannot pull signatures for a different network or advance the
    signer's last-sign state with foreign votes."""

    async def go():
        pv = _file_pv(tmp_path, b"\x47")
        node_key = PrivKeyEd25519.from_seed(b"\x52" * 32)
        listener = SignerListenerEndpoint(
            "tcp://127.0.0.1:0", node_key, accept_timeout=10.0
        )
        await listener.start()
        signer = SignerServer(
            f"127.0.0.1:{listener.bound_port}",
            pv,
            redial_delay=0.1,
            chain_id=CHAIN,
        )
        await signer.start()
        try:
            client = RetrySignerClient(listener, retries=10, delay=0.2)

            def vote():
                return Vote(
                    type=PREVOTE_TYPE,
                    height=5,
                    round=0,
                    block_id=_block_id(),
                    timestamp_ns=time.time_ns(),
                    validator_address=pv.key.address,
                    validator_index=0,
                )

            v = vote()
            with pytest.raises(Exception, match="serves"):
                await client.sign_vote("other-chain", v)
            assert v.signature is None or v.signature == b""
            # the pinned chain still signs, and the refusal didn't
            # burn the last-sign HRS state
            v2 = vote()
            await client.sign_vote(CHAIN, v2)
            pk = await client.get_pub_key()
            assert pk.verify_signature(v2.sign_bytes(CHAIN), v2.signature)
        finally:
            await signer.stop()
            await listener.stop()

    run(go())
