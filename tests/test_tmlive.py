"""tmlive: the whole-program liveness & boundedness gate.

Four jobs: (1) run tmlive over the whole package on every tier-1
invocation, failing on anything beyond the (empty) live baseline —
the static form of "the serving path never stalls and never grows
without bound"; (2) unit-test the analysis against the seeded
mini-packages in tests/data/live/ (each proven to turn the gate red);
(3) pin the blocking catalog's resolution machinery (alias evasion,
await exclusion, receiver-birth typing) and the boundedness
recognizers; (4) cross-check lockwatch's witnessed hold-budget
overruns against the static proof — every overrun must be explained.
"""

import importlib.util
import os
import subprocess
import sys
import time

import pytest

from tendermint_tpu.analysis import lockwatch, tmlive
from tendermint_tpu.analysis.tmlint import (
    Violation,
    load_baseline,
    new_violations,
    save_baseline,
)
from tendermint_tpu.analysis.tmcheck.callgraph import build_package
from tendermint_tpu.analysis.tmlive import blockcat, holdflow
from tendermint_tpu.analysis.tmlive.holdflow import (
    OVERRUN_OK,
    crosscheck_overruns,
)
from tendermint_tpu.analysis.tmrace.lockorder import STATIC_RANK_NAMES
from tendermint_tpu.analysis.tmrace.threadroots import MAIN_IDENTITY

FIXTURES = os.path.join(os.path.dirname(__file__), "data", "live")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fixture_report(name: str):
    pkg = build_package(os.path.join(FIXTURES, name))
    return tmlive.analyze(pkg)


# ---------------------------------------------------------------------------
# THE gate: whole package against the checked-in (empty) baseline


@pytest.fixture(scope="module")
def head_report():
    t0 = time.monotonic()
    rep = tmlive.analyze()
    rep.elapsed_s = time.monotonic() - t0
    return rep


def test_package_clean_against_baseline(head_report):
    """tmlive over the whole package; anything beyond
    tmlive/live_baseline.json fails tier-1 — fix it, suppress it with
    a justified `# tmlive: block-ok`/`grow-ok`/`bounded=`, or
    consciously re-baseline (docs/static_analysis.md)."""
    new = new_violations(
        head_report.violations, load_baseline(tmlive.LIVE_BASELINE_PATH)
    )
    assert not new, "new tmlive violations:\n" + "\n".join(
        v.render() for v in new
    )


def test_live_baseline_is_checked_in_and_empty():
    """Every true positive the first full run surfaced was fixed (the
    replay console's input() on the event loop now takes an executor
    hop) or carries an in-file justified annotation (WAL fsync
    protocol rationale, watchdog park, probe-triple/native-lib
    bounded= keys), so the baseline must stay empty — new findings
    fail loudly, not silently grandfather."""
    assert os.path.exists(tmlive.LIVE_BASELINE_PATH)
    assert load_baseline(tmlive.LIVE_BASELINE_PATH) == {}


def test_full_package_run_under_budget(head_report):
    """Runtime budget: the live pass runs on every tier-1 invocation
    and must stay bounded for the whole package (call-graph build +
    lockset propagation included; ~7 s when pinned, ~9.7 s by PR 20 —
    the package grew four analyzer subpackages and a native curve
    since, so the pin is 15 s to stop sub-second scheduler noise from
    flaking tier-1 while still catching a real blow-up). Times the
    module fixture's run rather than paying a second full analyze."""
    assert head_report.elapsed_s < 15.0, (
        f"tmlive full-package run took {head_report.elapsed_s:.1f}s"
    )


# ---------------------------------------------------------------------------
# the head catalog covers the sites the gate exists for


def test_head_catalog_covers_known_delicate_sites(head_report):
    """The reviewed catalog actually sees the sites ROADMAP's serving
    story hinges on: the WAL fsyncs (suppressed with protocol
    rationale, still cataloged unbounded), and the gather watchdog's
    park (suppressed residual)."""
    by_site = {
        (s.path, s.primitive): s
        for s in head_report.sites
    }
    wal_fsyncs = [
        s for s in head_report.sites
        if s.path == "consensus/wal.py" and s.primitive == "os.fsync"
    ]
    assert len(wal_fsyncs) >= 3  # flush_and_sync, on_stop, _rotate
    assert all(s.kind == blockcat.UNBOUNDED for s in wal_fsyncs)
    assert ("crypto/tpu_verifier.py", "threading.Event.wait") in by_site
    # the fault plane's injected hang is cataloged (and suppressed)
    assert ("crypto/faults.py", "time.sleep") in by_site
    # suppressions were exercised, not vacuous
    assert head_report.stats["suppressed"] >= 5


def test_head_wal_fsync_reachable_from_main_loop(head_report):
    """The consensus WAL's flush routine is a main-loop root and its
    fsync edges resolve — the suppression is covering a REAL reachable
    site, not dead code (the `self.wal: WAL` annotation in state.py
    exists for this)."""
    ids = head_report.identities.get(
        ("consensus/wal.py", "WAL.flush_and_sync"), set()
    )
    assert MAIN_IDENTITY in ids
    ids = head_report.identities.get(
        ("consensus/wal.py", "WAL.write_sync"), set()
    )
    assert MAIN_IDENTITY in ids


def test_head_growth_catalog_sees_bounded_idioms(head_report):
    """The boundedness recognizers classify the in-tree idioms: the
    trace ring (deque maxlen), the sigcache generations (rotation),
    and the annotated probe-triple/native-lib registries."""
    containers = head_report.containers
    ring = containers.get(("g", "libs/trace.py", "_ring"))
    assert ring is not None and ring.ring
    gen0 = containers.get(("g", "crypto/sigcache.py", "_gen0"))
    assert gen0 is not None and gen0.shrinks
    probe = containers.get(("g", "crypto/tpu_verifier.py", "_PROBE_TRIPLES"))
    assert probe is not None
    # annotated bounded= (grow line) — rooted grows but no finding
    assert any(g.key in head_report.identities for g in probe.grows)


def test_replay_console_does_not_block_the_loop():
    """Regression for the first-run finding tmlive fixed: the WAL
    replay console reads stdin on a daemon thread (the abci-console
    idiom) — never input() on the event loop, and never a
    default-executor hop whose teardown would make Ctrl-C hang until
    the operator pressed Enter."""
    import ast

    path = os.path.join(REPO, "tendermint_tpu", "cmd", "commands.py")
    src = open(path).read()
    tree = ast.parse(src)
    fn = next(
        n for n in ast.walk(tree)
        if isinstance(n, ast.AsyncFunctionDef)
        and n.name == "_replay_console"
    )

    def body_calls(node):
        # the coroutine's OWN statements: nested defs (the reader
        # thread target, where input() is allowed) are separate scopes
        stack = list(ast.iter_child_nodes(node))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(n, ast.Call):
                yield ast.unparse(n.func)
            stack.extend(ast.iter_child_nodes(n))

    calls = list(body_calls(fn))
    assert "input" not in calls
    assert not any("run_in_executor" in c for c in calls)
    # the console reads through the shared daemon-reader helper…
    assert "_stdin_reader_queue" in calls
    # …which spawns a daemon thread (one implementation serves both
    # the replay and abci consoles)
    helper = next(
        n for n in ast.walk(tree)
        if isinstance(n, ast.FunctionDef)
        and n.name == "_stdin_reader_queue"
    )
    threads = [
        c for c in ast.walk(helper)
        if isinstance(c, ast.Call)
        and ast.unparse(c.func).endswith("Thread")
    ]
    assert threads and any(
        kw.arg == "daemon"
        and isinstance(kw.value, ast.Constant)
        and kw.value.value is True
        for kw in threads[0].keywords
    )


# ---------------------------------------------------------------------------
# seeded fixtures: each family proven to turn the gate red


def test_fixture_block_under_lock_flagged():
    rep = _fixture_report("block_lock_pkg")
    assert [v.rule for v in rep.violations] == ["live-block-under-lock"]
    v = rep.violations[0]
    assert v.line == 14 and "os.fsync" in v.message
    assert "_lock" in v.message  # names the held lock
    # the timed wait under the same lock is bounded, not a finding —
    # but its lock IS recorded so a runtime hold-budget overrun on it
    # has a truthful static explanation (not a false "pure memory
    # ops" OVERRUN_OK claim)
    assert rep.stats["sites_bounded"] == 1
    assert "mod.py:_lock" in rep.suppressed_locks


def test_fixture_block_in_main_loop_flagged_through_alias():
    """`from time import sleep as nap` cannot evade the catalog, and
    the finding lands on the helper the async handler reaches — with
    the main-loop witness chain."""
    rep = _fixture_report("block_loop_pkg")
    assert [v.rule for v in rep.violations] == ["live-block-in-main-loop"]
    v = rep.violations[0]
    assert v.line == 11 and "time.sleep" in v.message
    assert "handler" in v.message and "slow_helper" in v.message
    # constant-duration sleep is bounded; awaited asyncio.sleep is not
    # even a site
    assert rep.stats["sites_total"] == 2
    assert rep.stats["sites_bounded"] == 1


def test_fixture_unbounded_blocking_residual_and_suppression():
    rep = _fixture_report("block_thread_pkg")
    assert [v.rule for v in rep.violations] == [
        "live-unbounded-blocking",  # untimed get
        "live-unbounded-blocking",  # put(item, True) — shifted args
    ]
    assert "queue.Queue.get" in rep.violations[0].message
    # put()'s leading item must not be misread as the block flag nor
    # its block flag as a timeout
    assert "queue.Queue.put" in rep.violations[1].message
    # bounded twins: put(x, True, 5.0), Popen.wait(30),
    # Popen.communicate(None, 30) — positional timeouts all recognized
    assert rep.stats["sites_bounded"] == 3
    # the block-ok twin passed and was counted
    assert rep.stats["suppressed"] == 1


def test_fixture_grow_unbounded_flagged_with_bounded_twins():
    rep = _fixture_report("grow_pkg")
    assert [v.rule for v in rep.violations] == [
        "live-grow-unbounded"
    ] * 5
    assert "`SEEN`" in rep.violations[0].message
    # the scoping rule: a LOCAL `SHADOWED = []` binding in an
    # unrelated function is neither a reset of the module global nor a
    # grow site against it — the global still flags
    assert "`SHADOWED`" in rep.violations[1].message
    # growth spelled as assignment: `REBUILT = {**REBUILT, k: 1}` is
    # an additive rebuild, not a reset that proves itself bounded
    assert "`REBUILT`" in rep.violations[2].message
    assert "additive rebuild" in rep.violations[2].message
    # cross-module growth resolves onto the birthing module's
    # identity through BOTH receiver shapes (from-import, module-attr)
    assert rep.violations[3].path == "other.py"
    assert "`CROSS`" in rep.violations[3].message
    assert rep.violations[4].path == "other.py"
    assert "mod.CROSS" in rep.violations[4].message
    # ring + rotation + annotation + filtered-copy twins all bounded
    # (a self-referential COMPREHENSION is eviction, not growth)
    assert rep.stats["containers_bounded"] == 4
    reasons = {
        c.var[2]: c.bounded_reason
        for c in rep.containers.values()
        if c.bounded_reason
    }
    assert reasons.get("RING") == "ring (deque maxlen)"
    assert "rotation" in reasons.get("ROTATED", "")
    assert "route-name set" in reasons.get("REGISTRY", "")
    assert "rotation" in reasons.get("FILTERED", "")


def test_fixture_baseline_round_trip(tmp_path):
    """save_baseline over a red fixture turns the diff green without
    touching the real baseline; a NEW (different-line) finding still
    fails."""
    rep = _fixture_report("grow_pkg")
    path = str(tmp_path / "live_baseline.json")
    save_baseline(rep.violations, path, note=tmlive.LIVE_BASELINE_NOTE)
    assert new_violations(rep.violations, load_baseline(path)) == []
    extra = rep.violations + [
        Violation(
            rule="live-grow-unbounded", path="mod.py", line=99, col=0,
            message="seeded new finding", source="OTHER[k] = v",
        )
    ]
    assert len(new_violations(extra, load_baseline(path))) == 1


# ---------------------------------------------------------------------------
# the lockwatch cross-check: witnessed overruns must be explained


def test_overrun_ok_names_only_ranked_locks():
    """OVERRUN_OK's scheduler-noise claims are per RANK name; a typo'd
    or stale entry (a lock that no longer exists in the rank table)
    would silently explain nothing."""
    assert set(OVERRUN_OK) <= set(lockwatch.RANK)


def test_crosscheck_explains_known_locks_and_flags_unknown():
    holds = [
        {"name": "sigcache.rotate", "held_s": 0.5, "budget_s": 0.25,
         "thread": "T", "where": "sigcache.py:1"},
        {"name": "mystery.lock", "held_s": 0.5, "budget_s": 0.25,
         "thread": "T", "where": "x.py:1"},
    ]
    out = crosscheck_overruns(holds, set(), set())
    assert len(out) == 1 and out[0]["name"] == "mystery.lock"
    assert "OVERRUN_OK" in out[0]["why"]


def test_crosscheck_accepts_statically_flagged_and_suppressed():
    """An overrun on a lock tmlive flagged (or suppressed) a blocking
    site under IS explained: the stall is known and reviewed."""
    holds = [
        {"name": "mystery.lock", "held_s": 1.0, "budget_s": 0.25,
         "thread": "T", "where": "x.py:1"},
    ]
    assert crosscheck_overruns(holds, {"mystery.lock"}, set()) == []
    assert crosscheck_overruns(holds, set(), {"mystery.lock"}) == []
    # a RANK-named overrun maps through STATIC_RANK_NAMES onto the
    # static lock identity the flag set uses
    static_name = next(
        s for s, r in STATIC_RANK_NAMES.items() if r == "breaker.instance"
    )
    holds = [
        {"name": "breaker.instance", "held_s": 1.0, "budget_s": 0.25,
         "thread": "T", "where": "breaker.py:1"},
    ]
    assert crosscheck_overruns(
        holds, {static_name}, set(), overrun_ok={}
    ) == []


def test_witnessed_overruns_statically_explained(head_report):
    """The live cross-check: every hold-budget overrun lockwatch has
    witnessed in THIS process (the chaos/fault/fuzz suites run under
    it) is either a tmlive-known blocking site or covered by a
    reviewed OVERRUN_OK rationale. An unexplained overrun means the
    catalog is missing a blocking primitive — fail loudly."""
    unexplained = crosscheck_overruns(
        lockwatch.HOLD_LOG,
        head_report.flagged_locks,
        head_report.suppressed_locks,
    )
    assert not unexplained, unexplained


def test_hold_log_records_structured_overruns(monkeypatch):
    """The runtime half produces records the cross-check can consume
    (name, acquisition site, durations, thread) — and feeds the
    process-global HOLD_LOG only when the watch is the ACTIVE one, so
    standalone unit-test watches with synthetic lock names never
    demand OVERRUN_OK entries."""
    import threading

    watch = lockwatch.LockWatch(hold_budget_s=0.0)
    standalone = lockwatch._WatchedLock(
        watch, threading.Lock(), "test.overrun"
    )
    before = len(lockwatch.HOLD_LOG)
    with standalone:
        time.sleep(0.002)
    report = watch.report()
    assert report.long_holds and report.long_holds[0]["name"] == "test.overrun"
    rec = report.long_holds[0]
    assert {"name", "where", "held_s", "budget_s", "thread"} <= set(rec)
    # standalone watch: per-watch record only, global log untouched
    assert len(lockwatch.HOLD_LOG) == before
    # the ACTIVE watch DOES feed the global log
    active = lockwatch.LockWatch(hold_budget_s=0.0)
    monkeypatch.setattr(lockwatch, "_ACTIVE", active)
    lock2 = lockwatch._WatchedLock(active, threading.Lock(), "test.overrun")
    with lock2:
        time.sleep(0.002)
    assert len(lockwatch.HOLD_LOG) == before + 1
    assert lockwatch.HOLD_LOG[-1]["name"] == "test.overrun"
    # keep the global log clean for the cross-check test: this
    # synthetic overrun names a lock OVERRUN_OK doesn't know
    lockwatch.HOLD_LOG.pop()


# ---------------------------------------------------------------------------
# CLI contract (scripts/lint.py --live)


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"), *args],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def _load_lint_module():
    spec = importlib.util.spec_from_file_location(
        "lint_cli_live", os.path.join(REPO, "scripts", "lint.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow
def test_cli_live_clean_exit_zero():
    r = _run_cli("--live", "--stats")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "[live]" in r.stdout


def test_cli_live_seeded_violation_exit_one(monkeypatch):
    """The exit contract end to end: a live finding beyond the (empty)
    baseline exits 1 through the real main()."""
    lint = _load_lint_module()
    seeded = [
        Violation(
            rule="live-block-in-main-loop",
            path="rpc/fake.py",
            line=1,
            col=0,
            message="seeded blocking call on the event loop",
            source="time.sleep(x)",
        )
    ]
    monkeypatch.setattr(
        lint.tmlive, "live_violations", lambda pkg=None, **kw: seeded
    )
    monkeypatch.setattr(
        lint.tmcheck, "build_package", lambda root=None: None
    )
    assert lint.main(["--live"]) == 1
    seeded[0] = Violation(
        rule="live-grow-unbounded",
        path="rpc/fake.py",
        line=1,
        col=0,
        message="seeded unbounded growth",
        source="SEEN[k] = v",
    )
    assert lint.main(["--live"]) == 1


def test_cli_live_baseline_update_refuses_filtered_runs():
    r = _run_cli("--live", "--baseline-update", "--rule", "det-float")
    assert r.returncode == 2
    assert "full-package" in r.stderr
    r = _run_cli(
        "--live", "--baseline-update", "tendermint_tpu/crypto/faults.py"
    )
    assert r.returncode == 2


def test_cli_update_modes_refuse_live():
    """--schema-update / --signatures-update combined with --live would
    silently skip the live gate while exiting 0 — same laundering class
    the PR-5/PR-8 refusal matrix closed."""
    r = _run_cli("--schema-update", "--live")
    assert r.returncode == 2 and "--live" in r.stderr
    r = _run_cli("--signatures-update", "--live")
    assert r.returncode == 2 and "--live" in r.stderr


def test_cli_list_rules_includes_live():
    r = _run_cli("--list-rules")
    assert r.returncode == 0
    for rid, _title in tmlive.RULES:
        assert rid in r.stdout
