"""Warm commit verification (ISSUE 7): zero-encode/zero-crypto guards,
memo safety, and byte-identical vectorized early exits.

Three families:

- **Counting-stub guards** — the fully-warm verify_commit path must
  perform ZERO canonical-vote encodes (the commit-scoped sign-bytes
  memo) and ZERO underlying signature verifications (sigcache), through
  every seam that can produce either; with the cache disabled the full
  crypto count returns while encodes stay memoized (determinism makes
  the sign-bytes memo legal even then).

- **Memo safety** — a memo may never change an outcome: chain_id
  mismatches miss; a mutated signature or timestamp is rejected with
  byte-identical errors warm/cold/disabled (the _MUT_EPOCH hook); an
  in-place ValidatorSet power mutation invalidates the commit-level
  memo (live powers fingerprint — the ADVICE-r5 staleness class).

- **Property tests** — the vectorized plans (masked-sum tally, prefix
  -sum early exit, bulk probe) must stop at the same vote, verify the
  same signature set, and raise the same error strings as the scalar
  reference loop (_verify_commit_batch_scalar), over randomized
  flag/power layouts including forged signatures, insufficient power,
  duplicate and unknown addresses. The scalar arm is forced exactly
  the way a hostile commit forces it: block_id_flags_array() -> None.
"""

import contextlib

import numpy as np
import pytest

from tendermint_tpu.crypto import sigcache
from tendermint_tpu.crypto.ed25519 import (
    Ed25519BatchVerifier,
    PrivKeyEd25519,
    PubKeyEd25519,
)
from tendermint_tpu.types import canonical
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.canonical import PRECOMMIT_TYPE, VoteSignTemplate
from tendermint_tpu.types.commit import Commit, CommitSig
from tendermint_tpu.types.validation import (
    InvalidCommitError,
    Fraction,
    NotEnoughVotingPowerError,
    collect_commit_light,
    verify_commit,
    verify_commit_light,
    verify_commit_light_trusting,
)
from tendermint_tpu.types.validator import Validator, ValidatorSet
from tendermint_tpu.types.vote import Vote

from .test_types import CHAIN_ID, make_block_id, make_validators
from .test_validation import make_commit


@pytest.fixture(autouse=True)
def fresh_cache():
    # a device factory left installed by an earlier test FILE would
    # route create_batch_verifier around the Ed25519BatchVerifier seam
    # the counting stubs patch — uninstall so the counts mean what the
    # guards assert regardless of suite ordering
    from tendermint_tpu.crypto import tpu_verifier

    tpu_verifier.uninstall()
    sigcache.reset()
    sigcache.set_capacity(sigcache.DEFAULT_CAPACITY)
    yield
    sigcache.reset()
    sigcache.set_capacity(sigcache.DEFAULT_CAPACITY)


# ---------------------------------------------------------------------------
# helpers


@contextlib.contextmanager
def scalar_reference():
    """Force the scalar reference loop the same way a hostile commit
    does: the flags memo reports unusable."""
    orig = Commit.block_id_flags_array
    Commit.block_id_flags_array = lambda self: None
    try:
        yield
    finally:
        Commit.block_id_flags_array = orig


class Counters:
    """Counts both crypto seams (single + batch verifies) and both
    encode seams (template splice single + batch, plus the plain
    canonical encoder Vote.sign_bytes bottoms out in)."""

    def __init__(self):
        self.singles = 0
        self.batched = 0
        self.encodes = 0

    @property
    def verifies(self):
        return self.singles + self.batched


@contextlib.contextmanager
def counting(monkeypatch_like=None):
    c = Counters()
    real_single = PubKeyEd25519.verify_signature
    real_batch = Ed25519BatchVerifier.verify
    real_tpl_one = VoteSignTemplate.sign_bytes
    real_tpl_batch = VoteSignTemplate.sign_bytes_batch
    real_canonical = canonical.vote_sign_bytes

    def counting_single(pk_self, msg, sig):
        c.singles += 1
        return real_single(pk_self, msg, sig)

    def counting_batch(bv_self):
        c.batched += len(bv_self._items)
        return real_batch(bv_self)

    def counting_tpl_one(tpl_self, ts):
        c.encodes += 1
        return real_tpl_one(tpl_self, ts)

    def counting_tpl_batch(tpl_self, timestamps):
        timestamps = list(timestamps)
        c.encodes += len(timestamps)
        return real_tpl_batch(tpl_self, timestamps)

    def counting_canonical(*a, **kw):
        c.encodes += 1
        return real_canonical(*a, **kw)

    PubKeyEd25519.verify_signature = counting_single
    Ed25519BatchVerifier.verify = counting_batch
    VoteSignTemplate.sign_bytes = counting_tpl_one
    VoteSignTemplate.sign_bytes_batch = counting_tpl_batch
    canonical.vote_sign_bytes = counting_canonical
    try:
        yield c
    finally:
        PubKeyEd25519.verify_signature = real_single
        Ed25519BatchVerifier.verify = real_batch
        VoteSignTemplate.sign_bytes = real_tpl_one
        VoteSignTemplate.sign_bytes_batch = real_tpl_batch
        canonical.vote_sign_bytes = real_canonical


def _signed_commit_sig(priv, addr, bid, height, round_, ts, nil=False):
    vote = Vote(
        type=PRECOMMIT_TYPE,
        height=height,
        round=round_,
        block_id=BlockID() if nil else bid,
        timestamp_ns=ts,
        validator_address=addr,
        validator_index=0,
    )
    sig = priv.sign(vote.sign_bytes(CHAIN_ID))
    if nil:
        return CommitSig.for_nil(sig, addr, ts)
    return CommitSig.for_block(sig, addr, ts)


def _random_layout(rng, n, forge=False):
    """A commit over n validators with randomized powers and a random
    ABSENT/NIL/COMMIT flag layout (>=2 non-absent so the batch path
    engages); optionally one forged signature at a random non-absent
    index."""
    privs = [PrivKeyEd25519.from_seed(bytes([i + 1]) * 32) for i in range(n)]
    powers = [int(rng.integers(1, 60)) for _ in range(n)]
    vals = ValidatorSet(
        [
            Validator(pub_key=p.pub_key(), voting_power=pw)
            for p, pw in zip(privs, powers)
        ]
    )
    by_addr = {p.pub_key().address(): p for p in privs}
    bid = make_block_id(b"\x0e")
    sigs = []
    n_signed = 0
    for v in vals.validators:
        r = float(rng.random())
        if r < 0.2:
            sigs.append(CommitSig.absent())
            continue
        nil = r < 0.35
        sigs.append(
            _signed_commit_sig(
                by_addr[v.address], v.address, bid, 1, 0, 1000, nil=nil
            )
        )
        n_signed += 1
    if n_signed < 2:
        # force the batch path: sign the first two validators
        for i in (0, 1):
            v = vals.validators[i]
            sigs[i] = _signed_commit_sig(
                by_addr[v.address], v.address, bid, 1, 0, 1000
            )
    commit = Commit(height=1, round=0, block_id=bid, signatures=sigs)
    if forge:
        non_absent = [
            i for i, cs in enumerate(sigs) if not cs.is_absent()
        ]
        j = int(rng.choice(non_absent))
        forged = bytearray(sigs[j].signature)
        forged[0] ^= 0xFF
        sigs[j].signature = bytes(forged)
    return vals, bid, commit


def _run_arm(fn, scalar):
    """One cold run of a verification callable: (error string or None,
    verify count, frozenset of cached triple keys)."""
    sigcache.reset()
    ctx = scalar_reference() if scalar else contextlib.nullcontext()
    err = None
    with counting() as c, ctx:
        try:
            fn()
        except Exception as e:  # noqa: BLE001 - error parity is the point
            err = f"{type(e).__name__}: {e}"
    cached = frozenset(
        k for k in (sigcache._gen0 | sigcache._gen1) if len(k) == 3
    )
    return err, c.verifies, cached


def _assert_arms_identical(fn, label):
    """The vectorized plan and the scalar reference must agree on the
    outcome, the number of signatures verified (the early-exit stop
    point), and the exact triples proven (which signatures were
    checked)."""
    v_err, v_cnt, v_keys = _run_arm(fn, scalar=False)
    s_err, s_cnt, s_keys = _run_arm(fn, scalar=True)
    assert v_err == s_err, f"{label}: error diverged\n  vector: {v_err}\n  scalar: {s_err}"
    assert v_cnt == s_cnt, f"{label}: verify count diverged ({v_cnt} vs {s_cnt}); err={v_err}"
    assert v_keys == s_keys, f"{label}: proven triple sets diverged"


# ---------------------------------------------------------------------------
# tier-1 counting-stub guards: warm = zero encodes AND zero verifies


def test_fully_warm_commit_zero_encodes_zero_verifies():
    vals, bid, commit = make_commit(6)
    with counting() as cold:
        verify_commit(CHAIN_ID, vals, bid, 1, commit)
    assert cold.verifies == 6
    assert cold.encodes >= 6  # sanity: the encode seam is counted
    with counting() as warm:
        verify_commit(CHAIN_ID, vals, bid, 1, commit)
    assert warm.verifies == 0
    assert warm.encodes == 0
    # the commit-level memo short-circuits the second warm pass: zero
    # triple probes on top of zero crypto/encodes
    s0 = sigcache.stats()
    with counting() as warm2:
        verify_commit(CHAIN_ID, vals, bid, 1, commit)
    s1 = sigcache.stats()
    assert warm2.verifies == 0 and warm2.encodes == 0
    assert s1["commit_hits"] - s0["commit_hits"] == 1
    assert s1["hits"] - s0["hits"] == 0  # no per-triple scan at all
    # disabled: the full crypto count returns through the same path;
    # encodes stay memoized (pure function of frozen inputs)
    with sigcache.disabled():
        with counting() as off:
            verify_commit(CHAIN_ID, vals, bid, 1, commit)
    assert off.verifies == 6
    assert off.encodes == 0


def test_fully_warm_light_and_trusting_zero_encodes(monkeypatch):
    vals, bid, commit = make_commit(6)
    verify_commit_light(CHAIN_ID, vals, bid, 1, commit)
    with counting() as warm:
        verify_commit_light(CHAIN_ID, vals, bid, 1, commit)
        verify_commit_light_trusting(CHAIN_ID, vals, commit, Fraction(1, 3))
    assert warm.verifies == 0
    assert warm.encodes == 0


def test_fresh_commit_object_same_bytes_still_warm():
    """The cross-HEIGHT warm shape: LastCommit arrives as a NEW Commit
    object with the same wire content. Triple keys are value-equal, so
    the bulk probe fully hits (zero crypto, fresh encodes only)."""
    vals, bid, commit = make_commit(5)
    verify_commit(CHAIN_ID, vals, bid, 1, commit)
    clone = Commit.from_proto(commit.to_proto())
    with counting() as c:
        verify_commit(CHAIN_ID, vals, bid, 1, clone)
    assert c.verifies == 0  # all 5 triples proven via the bulk probe
    assert c.encodes == 5  # a new object encodes once, then memoizes


# ---------------------------------------------------------------------------
# memo safety


def test_chain_id_mismatch_misses_and_fails():
    """The sign-bytes memo is keyed per chain_id and the commit memo
    binds it: warming on one chain must not leak into another."""
    vals, bid, commit = make_commit(4)
    verify_commit(CHAIN_ID, vals, bid, 1, commit)
    with pytest.raises(InvalidCommitError, match="wrong signature"):
        verify_commit("other-chain", vals, bid, 1, commit)
    # and the original chain is still warm and correct
    with counting() as c:
        verify_commit(CHAIN_ID, vals, bid, 1, commit)
    assert c.verifies == 0


def _error_text(fn):
    with pytest.raises(InvalidCommitError) as ei:
        fn()
    return str(ei.value)


def test_mutated_timestamp_rejected_identically_warm_cold_disabled():
    """A post-construction timestamp write changes the signed bytes:
    the _MUT_EPOCH hook must drop the sign-bytes memo AND the
    commit-level memo, so the warm path re-encodes, misses, and fails
    with the reference error — byte-identical to cold and disabled."""
    vals, bid, commit = make_commit(4)
    verify_commit(CHAIN_ID, vals, bid, 1, commit)  # fully warm + memoized
    commit.signatures[2].timestamp_ns += 1

    def run():
        return _error_text(
            lambda: verify_commit(CHAIN_ID, vals, bid, 1, commit)
        )

    warm = run()
    sigcache.reset()
    cold = run()
    with sigcache.disabled():
        off = run()
    assert warm == cold == off
    assert "wrong signature (#2)" in warm


def test_mutated_signature_rejected_identically_with_commit_memo():
    """Same for a signature write: the commit-level memo recorded by
    the first verify must not survive the mutation."""
    vals, bid, commit = make_commit(4)
    verify_commit(CHAIN_ID, vals, bid, 1, commit)
    forged = bytearray(commit.signatures[1].signature)
    forged[3] ^= 0x10
    commit.signatures[1].signature = bytes(forged)

    def run():
        return _error_text(
            lambda: verify_commit(CHAIN_ID, vals, bid, 1, commit)
        )

    warm = run()
    sigcache.reset()
    cold = run()
    with sigcache.disabled():
        off = run()
    assert warm == cold == off
    assert "wrong signature (#1)" in warm


def test_inplace_power_mutation_invalidates_commit_memo():
    """The ADVICE-r5 staleness class: an in-place voting_power write
    does not pass through _reindex, so the commit-memo key covers the
    LIVE powers bytes. Shrinking the signers' power below 2/3 must
    surface as NotEnoughVotingPower, never as a stale memo hit."""
    vals, bid, commit = make_commit(4, signers={0, 1, 2})
    verify_commit(CHAIN_ID, vals, bid, 1, commit)  # 30 of 40 > 26
    s0 = sigcache.stats()
    for i in range(3):
        vals.validators[i].voting_power = 1  # live tally: 3 + 10 absent
    with pytest.raises(NotEnoughVotingPowerError):
        verify_commit(CHAIN_ID, vals, bid, 1, commit)
    s1 = sigcache.stats()
    assert s1["commit_hits"] == s0["commit_hits"]  # key changed: no hit
    assert s1["commit_misses"] > s0["commit_misses"]


def test_inplace_pubkey_swap_invalidates_commit_memo():
    """An in-place pub_key re-assignment moves neither fingerprint
    token nor the powers bytes, so the commit-memo key binds the
    validator-mutation epoch (_VAL_MUT_EPOCH) too: the next verify
    must rebuild real keys against the NEW pub_key and reject the old
    signatures, never serve the stale success."""
    vals, bid, commit = make_commit(4)
    verify_commit(CHAIN_ID, vals, bid, 1, commit)
    s0 = sigcache.stats()
    vals.validators[1].pub_key = PrivKeyEd25519.from_seed(
        b"\x5a" * 32
    ).pub_key()
    with pytest.raises(InvalidCommitError):
        verify_commit(CHAIN_ID, vals, bid, 1, commit)
    s1 = sigcache.stats()
    assert s1["commit_hits"] == s0["commit_hits"]  # epoch moved: no hit


def test_validator_set_fingerprint_token_identity():
    vals, _ = make_validators(3)
    t = vals.fingerprint_token()
    assert vals.fingerprint_token() is t
    assert vals.copy().fingerprint_token() is not t  # copies diverge
    vals.update_with_change_set(
        [Validator(pub_key=PrivKeyEd25519.from_seed(b"\x77" * 32).pub_key(),
                   voting_power=5)]
    )
    assert vals.fingerprint_token() is not t  # membership change


def test_commit_fingerprint_token_replaced_on_mutation():
    _, _, commit = make_commit(3)
    t = commit.fingerprint_token()
    assert commit.fingerprint_token() is t
    commit.signatures[0].timestamp_ns += 1
    assert commit.fingerprint_token() is not t


def test_sign_bytes_memo_matches_fresh_encode():
    """The memoized rows must be byte-identical to a fresh encode of
    the reconstructed votes (the PR-2 contract, now across the memo)."""
    vals, bid, commit = make_commit(5, signers={0, 1, 2, 4})
    rows = commit.sign_bytes_batch(CHAIN_ID)
    again = commit.sign_bytes_batch(CHAIN_ID)
    assert rows is again  # memo hit returns the same list
    for i, cs in enumerate(commit.signatures):
        if cs.is_absent():
            assert rows[i] is None
            continue
        assert rows[i] == commit.get_vote(i).sign_bytes(CHAIN_ID)
        assert commit.vote_sign_bytes(CHAIN_ID, i) == rows[i]


def test_lazy_vote_sign_bytes_shares_rows_with_batch():
    vals, bid, commit = make_commit(4)
    a = commit.vote_sign_bytes(CHAIN_ID, 2)  # lazy fill first
    rows = commit.sign_bytes_batch(CHAIN_ID)  # completes the rest
    assert rows[2] == a
    assert all(rows[i] is not None for i in range(4))


# ---------------------------------------------------------------------------
# property tests: vectorized plans vs the scalar reference loop


N_SEEDS = 24


@pytest.mark.parametrize("seed", range(N_SEEDS))
def test_property_verify_commit_vector_matches_scalar(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(4, 24))
    vals, bid, commit = _random_layout(rng, n, forge=(seed % 3 == 0))
    _assert_arms_identical(
        lambda: verify_commit(CHAIN_ID, vals, bid, 1, commit),
        f"verify_commit seed={seed}",
    )


@pytest.mark.parametrize("seed", range(N_SEEDS))
def test_property_light_early_exit_matches_scalar(seed):
    rng = np.random.default_rng(1000 + seed)
    n = int(rng.integers(4, 24))
    vals, bid, commit = _random_layout(rng, n, forge=(seed % 3 == 0))
    _assert_arms_identical(
        lambda: verify_commit_light(CHAIN_ID, vals, bid, 1, commit),
        f"verify_commit_light seed={seed}",
    )


@pytest.mark.parametrize("seed", range(N_SEEDS))
def test_property_trusting_early_exit_matches_scalar(seed):
    rng = np.random.default_rng(2000 + seed)
    n = int(rng.integers(4, 24))
    vals, bid, commit = _random_layout(rng, n, forge=(seed % 4 == 0))
    sigs = commit.signatures
    non_absent = [i for i, cs in enumerate(sigs) if not cs.is_absent()]
    if seed % 3 == 0 and len(non_absent) >= 2:
        # duplicate address: the second occurrence must raise the
        # reference double-vote error iff the scan reaches it
        i, j = non_absent[0], non_absent[-1]
        sigs[j].validator_address = sigs[i].validator_address
    if seed % 5 == 0 and non_absent:
        # unknown address: skipped without verification
        sigs[non_absent[-1]].validator_address = b"\xfe" * 20
    trust = Fraction(1, 3) if seed % 2 else Fraction(2, 3)
    _assert_arms_identical(
        lambda: verify_commit_light_trusting(CHAIN_ID, vals, commit, trust),
        f"verify_commit_light_trusting seed={seed}",
    )


@pytest.mark.parametrize("seed", range(8))
def test_property_collect_commit_light_matches_scalar(seed):
    rng = np.random.default_rng(3000 + seed)
    n = int(rng.integers(4, 20))
    vals, bid, commit = _random_layout(rng, n)

    def run(scalar):
        ctx = scalar_reference() if scalar else contextlib.nullcontext()
        with ctx:
            try:
                triples = collect_commit_light(
                    CHAIN_ID, vals, bid, 1, commit
                )
                return [
                    (pk.bytes(), sb, sig) for pk, sb, sig in triples
                ], None
            except Exception as e:  # noqa: BLE001
                return None, f"{type(e).__name__}: {e}"

    v_t, v_err = run(False)
    s_t, s_err = run(True)
    assert v_err == s_err
    assert v_t == s_t  # same triples, same order, same stop point


def test_light_early_exit_stop_index_exact():
    """Deterministic pin of the prefix-sum crossing: with powers
    10,10,10,10 and 2/3 of 40 = 26, the light loop must stop after the
    THIRD for-block vote — the fourth signature is never verified, so
    forging it must not fail the verify (reference semantics)."""
    vals, bid, commit = make_commit(4)
    forged = bytearray(commit.signatures[3].signature)
    forged[0] ^= 0xFF
    commit.signatures[3].signature = bytes(forged)
    with counting() as c:
        verify_commit_light(CHAIN_ID, vals, bid, 1, commit)  # no raise
    assert c.verifies == 3
    # verify_commit checks ALL signatures and must reject the forgery
    with pytest.raises(InvalidCommitError, match=r"#3"):
        verify_commit(CHAIN_ID, vals, bid, 1, commit)


def test_insufficient_power_error_identical():
    vals, bid, commit = make_commit(4, signers={0})  # 10 of 40
    for fn in (
        lambda: verify_commit(CHAIN_ID, vals, bid, 1, commit),
        lambda: verify_commit_light(CHAIN_ID, vals, bid, 1, commit),
    ):
        v_err, _, _ = _run_arm(fn, scalar=False)
        s_err, _, _ = _run_arm(fn, scalar=True)
        assert v_err == s_err
        assert "insufficient voting power" in v_err
