"""Sharded-mempool oracle property tests (ISSUE 17 tentpole pin).

The sharded TxMempool (cfg.shards > 1) must be byte-identical to the
unsharded pool in every externally observable order: reap, gossip FIFO,
recheck app-call sequence, eviction victims, expiry, sender dedup. The
oracle is the same TxMempool with shards=1 (the pre-shard layout), fed
the identical op sequence; states are compared by tx BYTES, never by
WrappedTx.seq — the seq counter is process-global, so the two pools
draw interleaved values, but the relative order within each pool (the
only thing semantics depend on) is the same.
"""

import asyncio
import random

import pytest

from tendermint_tpu.abci import KVStoreApplication, LocalClient
from tendermint_tpu.abci import types as abci
from tendermint_tpu.config import MempoolConfig
from tendermint_tpu.libs.metrics import Registry
from tendermint_tpu.mempool import MempoolError, TxInfo, TxMempool, tx_key
from tendermint_tpu.mempool.metrics import MempoolMetrics


def run(coro):
    return asyncio.run(coro)


class OracleApp(KVStoreApplication):
    """Deterministic CheckTx verdicts driven by the tx bytes:

      ``s<sender>|p<prio>:payload`` — ok, with that sender/priority
      ``p<prio>:payload``           — ok, no sender
      ``bad...``                    — code=1 rejection
      any tx containing ``@drop``   — rejected on RECHECK only

    Also records every CheckTx tx in arrival order (``calls``) so the
    sharded pool's app-call sequence can be pinned against the oracle's.
    """

    def __init__(self):
        super().__init__()
        self.calls = []

    def check_tx(self, req):
        tx = req.tx
        self.calls.append((tx, req.type))
        if tx.startswith(b"bad"):
            return abci.ResponseCheckTx(code=1, log="rejected")
        if req.type == abci.CheckTxType.RECHECK and b"@drop" in tx:
            return abci.ResponseCheckTx(code=1, log="recheck-rejected")
        sender, body = "", tx
        if tx.startswith(b"s") and b"|" in tx:
            s, _, body = tx.partition(b"|")
            sender = s[1:].decode()
        prio = 0
        if body.startswith(b"p") and b":" in body:
            try:
                prio = int(body[1 : body.index(b":")])
            except ValueError:
                pass
        return abci.ResponseCheckTx(
            gas_wanted=1, priority=prio, sender=sender
        )


def make_pool(shards, cfg=None, app=None):
    cfg = cfg or MempoolConfig()
    cfg.shards = shards
    app = app or OracleApp()
    pool = TxMempool(
        LocalClient(app),
        cfg,
        metrics=MempoolMetrics(Registry()),
    )
    return pool, app


def fifo_walk(pool):
    """The gossip cursor's view: every pool tx in FIFO order."""
    out, cur = [], -1
    while True:
        w = pool.next_gossip_tx(cur)
        if w is None:
            return out
        out.append(w.tx)
        cur = w.seq


def fingerprint(pool):
    """Every externally observable order, in tx bytes."""
    return {
        "size": pool.size(),
        "bytes": pool.size_bytes(),
        "reap_all": pool.reap_max_bytes_max_gas(-1, -1),
        "reap_gas3": pool.reap_max_bytes_max_gas(-1, 3),
        "reap_top2": pool.reap_max_txs(2),
        "fifo": fifo_walk(pool),
        "senders": {s: k for s, k in pool._senders.items()},
        "cached": sorted(
            k for s in pool._shards for k in getattr(
                s.cache, "_map", {}
            )
        ),
    }


def check_invariants(pool):
    """The global accounting must equal the per-shard truth."""
    wtxs = [w for s in pool._shards for w in s.txs.values()]
    assert pool.size() == len(wtxs)
    assert pool.size_bytes() == sum(w.size() for w in wtxs)
    assert len({w.key for w in wtxs}) == len(wtxs)
    for s in pool._shards:
        for k, w in s.txs.items():
            assert pool._shard_for_key(k) is s
            assert w.key == k
    senders = {w.sender: w.key for w in wtxs if w.sender}
    assert pool._senders == senders


# ---------------------------------------------------------------------------


def test_oracle_random_trajectory():
    """Seeded random op soup — admissions (dups, bad txs, senders,
    priorities), commits with recheck, TTL expiry — applied to the
    sharded pool and the shards=1 oracle in lockstep. After every op
    the externally observable state must match byte-for-byte."""

    async def go():
        rng = random.Random(0xC0FFEE)
        cfg_a = MempoolConfig(size=24, max_txs_bytes=24 * 64)
        cfg_a.ttl_num_blocks = 6
        cfg_b = MempoolConfig(size=24, max_txs_bytes=24 * 64)
        cfg_b.ttl_num_blocks = 6
        sharded, app_a = make_pool(8, cfg_a)
        oracle, app_b = make_pool(1, cfg_b)

        issued = []
        height = 0

        def new_tx(i):
            prio = rng.randrange(0, 5)
            if rng.random() < 0.1:
                return b"bad%d" % i
            if rng.random() < 0.3:
                return b"s%d|p%d:tx%d" % (rng.randrange(12), prio, i)
            if rng.random() < 0.2:
                return b"p%d:@drop-tx%d" % (prio, i)
            return b"p%d:tx%d" % (prio, i)

        for step in range(400):
            op = rng.random()
            if op < 0.75 or not issued:
                tx = (
                    rng.choice(issued)
                    if issued and rng.random() < 0.15
                    else new_tx(step)
                )
                issued.append(tx)
                info = TxInfo(sender_id=rng.randrange(4))
                outcomes = []
                for pool in (sharded, oracle):
                    try:
                        res = await pool.check_tx(tx, info)
                        outcomes.append(("ok", res.code))
                    except MempoolError as e:
                        outcomes.append(("err", type(e).__name__))
                assert outcomes[0] == outcomes[1], (step, tx, outcomes)
            else:
                height += 1
                committed = sharded.reap_max_txs(rng.randrange(0, 6))
                resps = [
                    abci.ResponseDeliverTx(
                        code=0 if rng.random() < 0.8 else 1
                    )
                    for _ in committed
                ]
                await sharded.update(height, committed, resps)
                await oracle.update(height, committed, resps)
            check_invariants(sharded)
            assert fingerprint(sharded) == fingerprint(oracle), step

        assert sharded.size() > 0  # the soup actually admitted txs

    run(go())


def test_batch_matches_serial():
    """check_tx_batch must produce, per input index, exactly the
    outcome serial check_tx yields — including errors-as-values — and
    leave the pool in the identical state."""

    async def go():
        batch_pool, _ = make_pool(8)
        serial_pool, _ = make_pool(8)
        cfg = batch_pool.cfg
        txs = [
            b"p5:a",
            b"bad1",
            b"s7|p2:b",
            b"p5:a",  # dup of index 0
            b"s7|p9:c",  # sender dup of index 2
            b"x" * (cfg.max_tx_bytes + 1),
            b"p1:d",
        ]
        batch_out = await batch_pool.check_tx_batch(list(txs))
        serial_out = []
        for tx in txs:
            try:
                serial_out.append(await serial_pool.check_tx(tx))
            except MempoolError as e:
                serial_out.append(e)

        assert len(batch_out) == len(serial_out) == len(txs)
        for i, (b, s) in enumerate(zip(batch_out, serial_out)):
            assert type(b) is type(s), (i, b, s)
            if isinstance(b, abci.ResponseCheckTx):
                assert (b.code, b.priority, b.sender) == (
                    s.code,
                    s.priority,
                    s.sender,
                ), i
        assert fingerprint(batch_pool) == fingerprint(serial_pool)
        check_invariants(batch_pool)

    run(go())


def test_batch_empty_and_single():
    async def go():
        pool, _ = make_pool(8)
        assert await pool.check_tx_batch([]) == []
        out = await pool.check_tx_batch([b"p3:solo"])
        assert len(out) == 1 and out[0].is_ok
        assert pool.size() == 1

    run(go())


def test_epoch_barrier_excludes_all_admission():
    """lock() (held by consensus across Commit+Update) must block both
    serial and batch admission on every shard until unlock()."""

    async def go():
        pool, _ = make_pool(8)
        await pool.lock()
        t1 = asyncio.create_task(pool.check_tx(b"p1:a"))
        t2 = asyncio.create_task(pool.check_tx_batch([b"p2:b", b"p3:c"]))
        await asyncio.sleep(0.02)
        assert not t1.done() and not t2.done()
        assert pool.size() == 0
        pool.unlock()
        res1 = await asyncio.wait_for(t1, 1)
        res2 = await asyncio.wait_for(t2, 1)
        assert res1.is_ok and all(r.is_ok for r in res2)
        assert pool.size() == 3

    run(go())


def test_barrier_vs_batch_no_deadlock():
    """Barrier and batch admission acquire shard locks in the same
    ascending order — interleaving them many times must never wedge."""

    async def go():
        pool, _ = make_pool(8)

        async def churn_barrier():
            for _ in range(50):
                await pool.lock()
                await asyncio.sleep(0)
                pool.unlock()
                await asyncio.sleep(0)

        async def churn_batch(tag):
            for i in range(50):
                await pool.check_tx_batch(
                    [b"p1:%s-%d-%d" % (tag, i, j) for j in range(4)]
                )

        await asyncio.wait_for(
            asyncio.gather(
                churn_barrier(), churn_batch(b"x"), churn_batch(b"y")
            ),
            10,
        )
        check_invariants(pool)
        assert pool.size() == 400

    run(go())


def test_concurrent_admission_invariants():
    """Many overlapped check_tx/check_tx_batch calls (the app verdict
    suspends mid-admission) must keep the global accounting exact: no
    double-admits, no lost bytes, sender dedup global."""

    async def go():
        pool, app = make_pool(8)
        rng = random.Random(7)
        orig = LocalClient.check_tx

        async def slow_check_tx(self, req):
            await asyncio.sleep(rng.random() * 0.002)
            return await orig(self, req)

        pool._app.check_tx = slow_check_tx.__get__(pool._app)
        txs = [
            b"s%d|p%d:c%d" % (i % 9, i % 5, i) for i in range(60)
        ] + [b"p%d:n%d" % (i % 5, i) for i in range(60)]
        rng.shuffle(txs)

        async def admit(tx):
            try:
                return await pool.check_tx(tx)
            except MempoolError as e:
                return e

        coros = [admit(tx) for tx in txs[:80]]
        coros.append(pool.check_tx_batch(txs[80:]))
        await asyncio.gather(*coros)
        check_invariants(pool)
        # 9 sender slots + 60 senderless candidates, minus pool caps
        keys = {tx_key(w.tx) for s in pool._shards for w in s.txs.values()}
        assert len(keys) == pool.size()
        assert len({w.sender for s in pool._shards
                    for w in s.txs.values() if w.sender}) == len(
            pool._senders
        )

    run(go())


def test_eviction_spans_shards_and_counts_reason():
    """A full pool must evict the globally lowest-priority tx no matter
    which shard holds it, and count it under reason=full."""

    async def go():
        cfg = MempoolConfig(size=4)
        pool, _ = make_pool(8, cfg)
        for i in range(4):
            await pool.check_tx(b"p1:fill%d" % i)
        resident = set(fifo_walk(pool))
        res = await pool.check_tx(b"p9:vip")
        assert res.is_ok and pool.size() == 4
        now = set(fifo_walk(pool))
        assert b"p9:vip" in now
        assert len(resident - now) == 1  # exactly one low-prio victim
        full = pool.metrics.evicted_txs._values.get(("full",), 0)
        assert full == 1

    run(go())


def test_expiry_counts_reason():
    async def go():
        cfg = MempoolConfig()
        cfg.ttl_num_blocks = 1
        cfg.recheck = False
        pool, _ = make_pool(8, cfg)
        for i in range(5):
            await pool.check_tx(b"p1:e%d" % i)
        await pool.update(5, [], [])  # 5 - 0 > 1 → all expired
        assert pool.size() == 0
        expired = pool.metrics.evicted_txs._values.get(("expired",), 0)
        assert expired == 5

    run(go())


def test_recheck_app_call_sequence_matches_oracle():
    """The batched recheck must present the app the identical request
    sequence (tx order and RECHECK type) as the unsharded pool —
    chunking through check_tx_batch is invisible to the app."""

    async def go():
        cfg_a = MempoolConfig()
        cfg_a.tx_batch_size = 3  # force multiple chunks
        sharded, app_a = make_pool(8, cfg_a)
        oracle, app_b = make_pool(1)
        for i in range(10):
            tx = b"p%d:r%d%s" % (
                i % 4, i, b"@drop" if i % 3 == 0 else b""
            )
            await sharded.check_tx(tx)
            await oracle.check_tx(tx)
        app_a.calls.clear()
        app_b.calls.clear()
        await sharded.update(2, [], [])
        await oracle.update(2, [], [])
        assert app_a.calls == app_b.calls
        assert all(
            t == abci.CheckTxType.RECHECK for _, t in app_a.calls
        )
        assert fingerprint(sharded) == fingerprint(oracle)

    run(go())


def test_batch_prevalidator_runs_off_loop_and_rejects():
    """The BatchVerifier-shaped prevalidator sees only the txs that
    survived precheck, in input order, and its rejections surface as
    code!=0 responses without reaching the app."""

    async def go():
        seen = []

        def prevalidate(txs):
            seen.append(list(txs))
            return [b"deny" not in t for t in txs]

        app = OracleApp()
        cfg = MempoolConfig()
        cfg.shards = 8
        pool = TxMempool(
            LocalClient(app),
            cfg,
            metrics=MempoolMetrics(Registry()),
            prevalidator=prevalidate,
        )
        out = await pool.check_tx_batch(
            [b"p1:ok1", b"p1:deny-a", b"p1:ok1", b"p2:ok2"]
        )
        assert seen == [[b"p1:ok1", b"p1:deny-a", b"p2:ok2"]]
        assert out[0].is_ok
        assert not out[1].is_ok  # prevalidator rejection
        assert isinstance(out[2], MempoolError)  # in-batch dup
        assert out[3].is_ok
        # rejected tx never reached the app, and is re-admittable
        assert all(b"deny" not in t for t, _ in app.calls)
        assert not pool.cache.has(b"p1:deny-a")
        # serial path consults the same plugin
        with pytest.raises(MempoolError):
            await pool.check_tx(b"p1:ok1")  # cached
        res = await pool.check_tx(b"p3:deny-b")
        assert not res.is_ok

    run(go())


def test_windowed_gossip_matches_cursor_walk():
    """next_gossip_txs(cursor, n, budget) must return exactly the next
    n FIFO successors the one-at-a-time cursor walk would visit."""

    async def go():
        pool, _ = make_pool(8)
        for i in range(20):
            await pool.check_tx(b"p%d:g%d" % (i % 7, i))
        walk = fifo_walk(pool)
        cur, windowed = -1, []
        while True:
            win = pool.next_gossip_txs(cur, 6, 1 << 20)
            if not win:
                break
            windowed.extend(w.tx for w in win)
            cur = win[-1].seq
        assert windowed == walk
        # byte budget: first tx always granted, then cut
        win = pool.next_gossip_txs(-1, 100, 1)
        assert len(win) == 1 and win[0].tx == walk[0]

    run(go())
