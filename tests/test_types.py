"""Types layer: canonical encoding, votes, commits, headers, validator sets.

Mirrors the reference's own test strategy (types/validation_test.go,
types/validator_set_test.go, types/block_test.go): table-driven unit
tests plus batch-vs-single equivalence.
"""

import pytest

from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.types import (
    PRECOMMIT_TYPE,
    BlockID,
    Commit,
    CommitSig,
    Header,
    PartSetHeader,
    Proposal,
    Validator,
    ValidatorSet,
    Vote,
    VoteSet,
    commit_to_vote_set,
    make_block,
)
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.types.vote_set import ConflictingVoteError

CHAIN_ID = "test-chain"


def make_validators(n, power=10):
    """n deterministic validators with their privkeys, sorted as the
    ValidatorSet sorts them."""
    pairs = []
    for i in range(n):
        pk = PrivKeyEd25519.from_seed(bytes([i + 1]) * 32)
        pairs.append(pk)
    vals = ValidatorSet(
        [
            Validator(pub_key=pk.pub_key(), voting_power=power)
            for pk in pairs
        ]
    )
    by_addr = {pk.pub_key().address(): pk for pk in pairs}
    privs = [by_addr[v.address] for v in vals.validators]
    return vals, privs


def make_block_id(seed=b"\x01"):
    return BlockID(
        hash=seed * 32,
        part_set_header=PartSetHeader(total=1, hash=seed * 32),
    )


def signed_vote(priv, vals, idx, block_id, height=1, round_=0, ts=1000):
    v = Vote(
        type=PRECOMMIT_TYPE,
        height=height,
        round=round_,
        block_id=block_id,
        timestamp_ns=ts,
        validator_address=vals.validators[idx].address,
        validator_index=idx,
    )
    v.signature = priv.sign(v.sign_bytes(CHAIN_ID))
    return v


class TestVote:
    def test_sign_verify_roundtrip(self):
        vals, privs = make_validators(1)
        v = signed_vote(privs[0], vals, 0, make_block_id())
        v.verify(CHAIN_ID, privs[0].pub_key())

    def test_verify_rejects_wrong_chain(self):
        vals, privs = make_validators(1)
        v = signed_vote(privs[0], vals, 0, make_block_id())
        with pytest.raises(ValueError):
            v.verify("other-chain", privs[0].pub_key())

    def test_proto_roundtrip(self):
        vals, privs = make_validators(1)
        v = signed_vote(privs[0], vals, 0, make_block_id())
        v2 = Vote.from_proto(v.to_proto())
        assert v2 == v

    def test_nil_vote_sign_bytes_differ(self):
        vals, privs = make_validators(1)
        a = signed_vote(privs[0], vals, 0, make_block_id())
        b = signed_vote(privs[0], vals, 0, BlockID())
        assert a.sign_bytes(CHAIN_ID) != b.sign_bytes(CHAIN_ID)


class TestProposal:
    def test_sign_verify_proto(self):
        priv = PrivKeyEd25519.from_seed(b"\x07" * 32)
        p = Proposal(
            height=3,
            round=1,
            pol_round=-1,
            block_id=make_block_id(),
            timestamp_ns=123456789,
        )
        p.signature = priv.sign(p.sign_bytes(CHAIN_ID))
        assert p.verify(CHAIN_ID, priv.pub_key())
        p2 = Proposal.from_proto(p.to_proto())
        assert p2 == p
        assert p2.pol_round == -1


class TestValidatorSet:
    def test_sorted_by_power_then_address(self):
        privs = [PrivKeyEd25519.from_seed(bytes([i]) * 32) for i in range(1, 5)]
        vals = ValidatorSet(
            [
                Validator(pub_key=privs[0].pub_key(), voting_power=5),
                Validator(pub_key=privs[1].pub_key(), voting_power=50),
                Validator(pub_key=privs[2].pub_key(), voting_power=20),
                Validator(pub_key=privs[3].pub_key(), voting_power=20),
            ]
        )
        powers = [v.voting_power for v in vals.validators]
        assert powers == [50, 20, 20, 5]
        # equal powers tie-break by address ascending
        a, b = vals.validators[1], vals.validators[2]
        assert a.address < b.address
        assert vals.total_voting_power() == 95

    def test_proposer_rotation_weighted(self):
        vals, _ = make_validators(3)
        # equal power: each validator proposes once per 3 rounds
        seen = []
        vs = vals.copy()
        for _ in range(6):
            seen.append(vs.get_proposer().address)
            vs.increment_proposer_priority(1)
        assert len(set(seen[:3])) == 3
        assert seen[:3] == seen[3:6]

    def test_proposer_frequency_proportional(self):
        privs = [PrivKeyEd25519.from_seed(bytes([i]) * 32) for i in (1, 2)]
        vals = ValidatorSet(
            [
                Validator(pub_key=privs[0].pub_key(), voting_power=3),
                Validator(pub_key=privs[1].pub_key(), voting_power=1),
            ]
        )
        heavy = max(
            vals.validators, key=lambda v: v.voting_power
        ).address
        count = 0
        vs = vals.copy()
        for _ in range(40):
            if vs.get_proposer().address == heavy:
                count += 1
            vs.increment_proposer_priority(1)
        assert count == 30  # 3/4 of 40

    def test_update_with_change_set(self):
        vals, privs = make_validators(3)
        new_priv = PrivKeyEd25519.from_seed(b"\x99" * 32)
        vals.update_with_change_set(
            [Validator(pub_key=new_priv.pub_key(), voting_power=7)]
        )
        assert vals.size() == 4
        # remove one
        vals.update_with_change_set(
            [Validator(pub_key=new_priv.pub_key(), voting_power=0)]
        )
        assert vals.size() == 3

    def test_hash_changes_with_membership(self):
        vals, _ = make_validators(3)
        vals2, _ = make_validators(4)
        assert vals.hash() != vals2.hash()

    def test_proto_roundtrip(self):
        vals, _ = make_validators(3)
        vals.get_proposer()
        v2 = ValidatorSet.from_proto(vals.to_proto())
        assert v2.hash() == vals.hash()
        assert [v.address for v in v2.validators] == [
            v.address for v in vals.validators
        ]

    def test_to_proto_memo_tracks_priority_rotation(self):
        """to_proto is memoized (the light store serializes the same
        set once per header), but its wire form covers proposer
        priorities — rotation must invalidate it even though no
        membership changed."""
        vals, _ = make_validators(4)
        first = vals.to_proto()
        assert vals.to_proto() is first  # memo hit, same object
        rotated = vals.copy_increment_proposer_priority(1)
        assert rotated.to_proto() != first
        vals.increment_proposer_priority(1)
        after = vals.to_proto()
        assert after != first
        # the memoized bytes equal a fresh, unmemoized serialization
        rt = ValidatorSet.from_proto(after)
        assert [
            (v.address, v.voting_power, v.proposer_priority)
            for v in rt.validators
        ] == [
            (v.address, v.voting_power, v.proposer_priority)
            for v in vals.validators
        ]
        assert rt.proposer.address == vals.proposer.address

    def test_to_proto_memo_tracks_inplace_power_mutation(self):
        """ADVICE r5: ValidatorSet hands out live Validator references
        (the validators list itself), so an embedder mutating
        voting_power or the pub_key in place — without going through
        the change-set API — must still get fresh wire bytes, not the
        memo's stale ones."""
        vals, _ = make_validators(4)
        first = vals.to_proto()
        assert vals.to_proto() is first
        # in-place power mutation: no _reindex, no priority change
        vals.validators[0].voting_power += 5
        mutated = vals.to_proto()
        assert mutated != first
        rt = ValidatorSet.from_proto(mutated)
        assert rt.validators[0].voting_power == (
            vals.validators[0].voting_power
        )
        # pub_key identity swap on a detached proposer record
        assert vals.to_proto() is vals.to_proto()  # memo re-established
        other = PrivKeyEd25519.from_seed(b"\x99" * 32).pub_key()
        before = vals.to_proto()
        vals.proposer.pub_key = other
        assert vals.to_proto() != before


class TestVoteSet:
    def test_quorum_and_commit(self):
        vals, privs = make_validators(4)
        bid = make_block_id()
        vs = VoteSet(CHAIN_ID, 1, 0, PRECOMMIT_TYPE, vals)
        assert not vs.has_two_thirds_majority()
        for i in range(3):
            assert vs.add_vote(signed_vote(privs[i], vals, i, bid))
        assert vs.has_two_thirds_majority()
        maj, ok = vs.two_thirds_majority()
        assert ok and maj == bid
        commit = vs.make_commit()
        assert commit.size() == 4
        assert commit.signatures[3].is_absent()
        assert sum(1 for s in commit.signatures if s.is_for_block()) == 3

    def test_duplicate_vote_not_added(self):
        vals, privs = make_validators(4)
        bid = make_block_id()
        vs = VoteSet(CHAIN_ID, 1, 0, PRECOMMIT_TYPE, vals)
        v = signed_vote(privs[0], vals, 0, bid)
        assert vs.add_vote(v)
        assert not vs.add_vote(v)

    def test_conflicting_vote_raises(self):
        vals, privs = make_validators(4)
        vs = VoteSet(CHAIN_ID, 1, 0, PRECOMMIT_TYPE, vals)
        assert vs.add_vote(signed_vote(privs[0], vals, 0, make_block_id(b"\x01")))
        with pytest.raises(ConflictingVoteError):
            vs.add_vote(signed_vote(privs[0], vals, 0, make_block_id(b"\x02")))

    def test_nil_votes_tally_but_no_block_majority(self):
        vals, privs = make_validators(4)
        vs = VoteSet(CHAIN_ID, 1, 0, PRECOMMIT_TYPE, vals)
        for i in range(3):
            vs.add_vote(signed_vote(privs[i], vals, i, BlockID()))
        assert vs.has_two_thirds_any()
        maj, ok = vs.two_thirds_majority()
        assert ok and maj == BlockID()  # 2/3 for nil

    def test_commit_roundtrip_through_vote_set(self):
        vals, privs = make_validators(4)
        bid = make_block_id()
        vs = VoteSet(CHAIN_ID, 5, 2, PRECOMMIT_TYPE, vals)
        for i in range(4):
            vs.add_vote(
                signed_vote(privs[i], vals, i, bid, height=5, round_=2)
            )
        commit = vs.make_commit()
        vs2 = commit_to_vote_set(CHAIN_ID, commit, vals)
        assert vs2.has_two_thirds_majority()
        c2 = vs2.make_commit()
        assert c2.hash() == commit.hash()


class TestCommit:
    def test_proto_roundtrip(self):
        vals, privs = make_validators(4)
        bid = make_block_id()
        vs = VoteSet(CHAIN_ID, 1, 0, PRECOMMIT_TYPE, vals)
        for i in range(3):
            vs.add_vote(signed_vote(privs[i], vals, i, bid))
        commit = vs.make_commit()
        c2 = Commit.from_proto(commit.to_proto())
        assert c2.hash() == commit.hash()
        assert c2.block_id == commit.block_id

    def test_validate_basic(self):
        c = Commit(height=1, round=0, block_id=make_block_id(), signatures=[])
        with pytest.raises(ValueError, match="no signatures"):
            c.validate_basic()


class TestHeaderAndBlock:
    def test_header_hash_deterministic_and_field_sensitive(self):
        h = Header(
            chain_id=CHAIN_ID,
            height=3,
            time_ns=1234,
            validators_hash=b"\x01" * 32,
            next_validators_hash=b"\x02" * 32,
            consensus_hash=b"\x03" * 32,
            proposer_address=b"\x04" * 20,
        )
        h1 = h.hash()
        assert len(h1) == 32
        h.height = 4
        assert h.hash() != h1

    def test_header_hash_empty_without_validators_hash(self):
        assert Header(chain_id=CHAIN_ID, height=1).hash() == b""

    def test_header_proto_roundtrip(self):
        h = Header(
            chain_id=CHAIN_ID,
            height=3,
            time_ns=1234,
            validators_hash=b"\x01" * 32,
            proposer_address=b"\x04" * 20,
        )
        h2 = Header.from_proto(h.to_proto())
        assert h2 == h

    def test_block_roundtrip_and_part_set(self):
        commit = Commit()
        b = make_block(1, [b"tx1", b"tx2"], commit, [])
        b.header.validators_hash = b"\x01" * 32
        b.header.next_validators_hash = b"\x01" * 32
        b.header.consensus_hash = b"\x02" * 32
        b.header.proposer_address = b"\x03" * 20
        assert len(b.hash()) == 32
        ps = b.make_part_set(64)
        assert ps.is_complete()
        b2 = type(b).from_proto(ps.assemble())
        assert b2.hash() == b.hash()
        assert b2.txs == [b"tx1", b"tx2"]


class TestPartSet:
    def test_add_part_verifies_proof(self):
        data = bytes(range(256)) * 10
        ps = PartSet.from_data(data, part_size=128)
        rebuilt = PartSet.from_header(ps.header())
        for p in ps.parts:
            assert rebuilt.add_part(p)
        assert rebuilt.is_complete()
        assert rebuilt.assemble() == data

    def test_add_part_rejects_corrupt(self):
        data = b"x" * 300
        ps = PartSet.from_data(data, part_size=128)
        rebuilt = PartSet.from_header(ps.header())
        bad = ps.parts[0]
        bad.bytes = b"y" + bad.bytes[1:]
        with pytest.raises(ValueError, match="invalid proof"):
            rebuilt.add_part(bad)


def test_validator_set_hash_memo_tracks_membership():
    """The memoized ValidatorSet.hash() must change when membership or
    power changes, survive proposer rotation unchanged (priorities are
    not part of the merkle leaves), and round-trip through copy() and
    proto."""
    from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
    from tendermint_tpu.types.validator import Validator, ValidatorSet

    privs = [
        PrivKeyEd25519.from_seed(bytes([i + 1, 0x5e]) + b"\x24" * 30)
        for i in range(4)
    ]
    vals = ValidatorSet(
        [Validator(pub_key=p.pub_key(), voting_power=10) for p in privs]
    )
    h0 = vals.hash()
    assert vals.hash() == h0  # memo stable
    vals.increment_proposer_priority(3)
    assert vals.hash() == h0  # priorities not hashed

    cp = vals.copy()
    assert cp.hash() == h0

    # power change invalidates
    vals.update_with_change_set(
        [Validator(pub_key=privs[0].pub_key(), voting_power=25)]
    )
    h1 = vals.hash()
    assert h1 != h0
    # and matches a freshly-built set with the same membership
    fresh = ValidatorSet(
        [
            Validator(
                pub_key=p.pub_key(),
                voting_power=25 if i == 0 else 10,
            )
            for i, p in enumerate(privs)
        ]
    )
    assert fresh.hash() == h1
    # removal invalidates too
    vals.update_with_change_set(
        [Validator(pub_key=privs[1].pub_key(), voting_power=0)]
    )
    assert vals.hash() != h1
    # proto round-trip recomputes to the same root
    from tendermint_tpu.types.validator import ValidatorSet as VS

    assert VS.from_proto(vals.to_proto()).hash() == vals.hash()
