"""Symmetric crypto tests (reference models:
crypto/xchacha20poly1305/xchachapoly_test.go,
crypto/xsalsa20symmetric/symmetric_test.go)."""

import pytest

from tendermint_tpu.crypto.symmetric import (
    XChaCha20Poly1305,
    chacha20_block,
    decrypt_symmetric,
    encrypt_symmetric,
    hchacha20,
)


def test_chacha_block_matches_library_keystream():
    """The pure-Python ChaCha permutation vs the `cryptography`
    package's ChaCha20 keystream — the independent oracle for the
    HChaCha20 core. (Without the wheel the RFC 8439 vector test in
    test_crypto.py stands in as the oracle.)"""
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives.ciphers import Cipher, algorithms

    key = bytes(range(32))
    nonce12 = bytes(range(100, 112))
    for counter in (0, 1, 7):
        # library nonce layout: 4-byte LE counter || 12-byte nonce
        full = counter.to_bytes(4, "little") + nonce12
        enc = Cipher(
            algorithms.ChaCha20(key, full), mode=None
        ).encryptor()
        keystream = enc.update(b"\x00" * 64)
        assert chacha20_block(key, counter, nonce12) == keystream


def test_hchacha20_against_block_identity():
    """HChaCha20 equals the ChaCha block function minus the initial
    state on words {0-3, 12-15} (no feed-forward). Deriving it that way
    from the library-verified block anchors the subkey derivation to an
    independent implementation, with the result pinned as a vector."""
    import struct

    from tendermint_tpu.crypto.symmetric import _SIGMA

    key = bytes.fromhex(
        "000102030405060708090a0b0c0d0e0f"
        "101112131415161718191a1b1c1d1e1f"
    )
    nonce = bytes.fromhex("000000090000004a0000000031415927")
    counter = int.from_bytes(nonce[:4], "little")
    n12 = nonce[4:]
    blk = struct.unpack("<16I", chacha20_block(key, counter, n12))
    init = (
        list(_SIGMA)
        + list(struct.unpack("<8I", key))
        + [counter]
        + list(struct.unpack("<3I", n12))
    )
    sub = [
        (blk[i] - init[i]) & 0xFFFFFFFF
        for i in (*range(4), *range(12, 16))
    ]
    derived = struct.pack("<8I", *sub)
    got = hchacha20(key, nonce)
    assert got == derived
    assert got == bytes.fromhex(
        "82413b4227b27bfed30e42508a877d73"
        "a0f9e4d58a74a853c12ec41326d3ecdc"
    )


def test_xchacha_roundtrip_and_tamper():
    key = b"\x42" * 32
    aead = XChaCha20Poly1305(key)
    nonce = bytes(range(24))
    ct = aead.encrypt(nonce, b"hello xchacha", b"aad")
    assert aead.decrypt(nonce, ct, b"aad") == b"hello xchacha"
    with pytest.raises(Exception):
        aead.decrypt(nonce, ct[:-1] + bytes([ct[-1] ^ 1]), b"aad")
    with pytest.raises(Exception):
        aead.decrypt(nonce, ct, b"wrong-aad")
    # distinct nonces produce distinct ciphertexts
    assert aead.encrypt(bytes(24), b"hello xchacha", b"aad") != ct


def test_symmetric_roundtrip_wrong_key_and_short_input():
    secret = b"\x0c" * 32
    sealed = encrypt_symmetric(b"armored key bytes", secret)
    assert decrypt_symmetric(sealed, secret) == b"armored key bytes"
    # nonce is random: sealing twice differs
    assert encrypt_symmetric(b"armored key bytes", secret) != sealed
    with pytest.raises(Exception):
        decrypt_symmetric(sealed, b"\x0d" * 32)
    with pytest.raises(ValueError):
        decrypt_symmetric(b"short", secret)
    with pytest.raises(ValueError):
        encrypt_symmetric(b"x", b"bad-size-key")
