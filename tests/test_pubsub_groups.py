"""Grouped pubsub publish under churn (ISSUE 17 satellite, pinning the
PR-16 group fan-out).

The publish path indexes subscriptions by DISTINCT query source
(`Server._groups`) and batch-delivers one shared frozen Message per
group. These tests pin the invariants that index must keep under
concurrent subscribe/unsubscribe-during-publish traffic: the two
indexes never disagree, a group dies with its last member (including
overflow terminations discovered mid-publish), and no live subscriber
ever loses or double-receives a message.
"""

import asyncio
import random

import pytest

from tendermint_tpu.pubsub import Server, SubscriptionError

def run(coro):
    return asyncio.run(coro)


def assert_indexes_consistent(s: Server) -> None:
    """_subs and _groups must be two views of the same membership."""
    grouped = {}
    for source, (q, members) in s._groups.items():
        assert members, f"empty group {source!r} not cleaned up"
        assert str(q) == source
        for key, sub in members.items():
            assert key[1] == source
            assert key not in grouped, f"{key} in two groups"
            grouped[key] = sub
    assert grouped == s._subs


QUERIES = (
    "tm.event = 'NewBlock'",
    "tm.event = 'Tx'",
    "tm.event EXISTS",
)


def test_group_index_consistency_under_churn():
    """Seeded random subscribe/unsubscribe/unsubscribe_all/publish soup:
    after every op the group index must exactly mirror _subs."""

    async def go():
        rng = random.Random(0xFEED)
        s = Server()
        await s.start()
        live = {}  # (client, query) → Subscription
        for step in range(600):
            op = rng.random()
            cid = f"c{rng.randrange(12)}"
            q = rng.choice(QUERIES)
            if op < 0.45:
                if (cid, q) in live:
                    with pytest.raises(SubscriptionError):
                        s.subscribe(cid, q)
                else:
                    live[(cid, q)] = s.subscribe(cid, q, limit=4)
            elif op < 0.7:
                if (cid, q) in live:
                    s.unsubscribe(cid, q)
                    del live[(cid, q)]
                else:
                    with pytest.raises(SubscriptionError):
                        s.unsubscribe(cid, q)
            elif op < 0.8:
                mine = [k for k in live if k[0] == cid]
                if mine:
                    s.unsubscribe_all(cid)
                    for k in mine:
                        del live[k]
                else:
                    with pytest.raises(SubscriptionError):
                        s.unsubscribe_all(cid)
            else:
                # publishes overflow slow (never-drained) subscribers,
                # exercising the mid-publish dead-group sweep
                _, _, dropped = s.publish(
                    step, {"tm.event": [rng.choice(["NewBlock", "Tx"])]}
                )
                if dropped:
                    live = {
                        k: v for k, v in live.items() if k in s._subs
                    }
            assert set(live) == set(s._subs), step
            assert_indexes_consistent(s)
        await s.stop()

    run(go())


def test_publish_shares_one_message_across_groups():
    """One publish allocates ONE frozen Message, delivered by reference
    to every matched subscriber in every matched group."""

    async def go():
        s = Server()
        await s.start()
        subs = [
            s.subscribe("a", "tm.event = 'Tx'"),
            s.subscribe("b", "tm.event = 'Tx'"),
            s.subscribe("c", "tm.event EXISTS"),
        ]
        miss = s.subscribe("d", "tm.event = 'NewBlock'")
        s.publish("payload", {"tm.event": ["Tx"]})
        msgs = [await sub.next() for sub in subs]
        assert msgs[0] is msgs[1] is msgs[2]
        assert msgs[0].data == "payload"
        assert miss._queue.qsize() == 0
        await s.stop()

    run(go())


def test_overflow_mid_publish_drops_only_the_dead():
    """A subscriber overflowing during the fan-out is terminated and
    removed from both indexes on that same publish; its group survives
    while it has other members and dies with its last one."""

    async def go():
        s = Server()
        await s.start()
        slow = s.subscribe("slow", "tm.event = 'Tx'", limit=1)
        fast = s.subscribe("fast", "tm.event = 'Tx'", limit=16)
        lone = s.subscribe("lone", "tm.event EXISTS", limit=1)

        s.publish(1, {"tm.event": ["Tx"]})  # fills slow and lone
        matched, _, dropped = s.publish(2, {"tm.event": ["Tx"]})
        assert matched == 3 and dropped == 2  # slow + lone overflow

        # survivors: only fast; the Tx group kept its live member, the
        # EXISTS group lost its last and must be gone entirely
        assert set(s._subs) == {("fast", str(fast.query))}
        assert set(s._groups) == {str(fast.query)}
        assert_indexes_consistent(s)

        # fast is unaffected: both messages, in order
        assert (await fast.next()).data == 1
        assert (await fast.next()).data == 2

        # the dead drain their buffer then error out
        assert (await slow.next()).data == 1
        with pytest.raises(SubscriptionError):
            await slow.next()

        # a fresh publish matches only the survivor
        matched, _, dropped = s.publish(3, {"tm.event": ["Tx"]})
        assert matched == 1 and dropped == 0
        await s.stop()

    run(go())


def test_no_lost_or_duplicate_deliveries_under_concurrent_churn():
    """A publisher streams numbered messages while transient
    subscribers churn on the same query. Stable subscribers must see
    the full stream exactly once in order; every transient subscriber
    must see a contiguous, duplicate-free window of it."""

    async def go():
        s = Server()
        await s.start()
        n_msgs = 120
        stable = [
            s.subscribe(f"stable{i}", "tm.event = 'Tx'", limit=n_msgs + 8)
            for i in range(4)
        ]
        windows = []

        async def publisher():
            for i in range(n_msgs):
                s.publish(i, {"tm.event": ["Tx"]})
                await asyncio.sleep(0)

        async def churner(tag):
            rng = random.Random(hash(tag) & 0xFFFF)
            for r in range(12):
                sub = s.subscribe(
                    f"t{tag}-{r}", "tm.event = 'Tx'", limit=n_msgs + 8
                )
                for _ in range(rng.randrange(1, 6)):
                    await asyncio.sleep(0)
                s.unsubscribe(f"t{tag}-{r}", "tm.event = 'Tx'")
                got = []
                try:
                    while True:
                        got.append(sub._queue.get_nowait())
                except asyncio.QueueEmpty:
                    pass
                # the terminate sentinel has no .data; drop it
                windows.append(
                    [m.data for m in got if hasattr(m, "data")]
                )

        await asyncio.gather(
            publisher(), churner("a"), churner("b"), churner("c")
        )
        assert_indexes_consistent(s)

        for sub in stable:
            seen = []
            while sub._queue.qsize():
                seen.append((await sub.next()).data)
            assert seen == list(range(n_msgs))

        for w in windows:
            assert w == sorted(set(w))  # no dups, ascending
            if w:  # contiguous: a window, not a sieve
                assert w == list(range(w[0], w[0] + len(w)))
        await s.stop()

    run(go())


def test_unsubscribe_wakes_blocked_consumer():
    """A consumer parked in next() must wake with SubscriptionError the
    moment its subscription is unsubscribed mid-publish-stream — the
    sentinel push, not a poll."""

    async def go():
        s = Server()
        await s.start()
        sub = s.subscribe("c1", "tm.event = 'Tx'")

        async def consume():
            with pytest.raises(SubscriptionError, match="unsubscribed"):
                while True:
                    await sub.next()

        task = asyncio.create_task(consume())
        await asyncio.sleep(0.01)
        assert not task.done()
        s.publish("x", {"tm.event": ["Tx"]})
        await asyncio.sleep(0.01)  # consumer drains the real message
        s.unsubscribe("c1", "tm.event = 'Tx'")
        await asyncio.wait_for(task, 1)
        assert s.num_subscriptions() == 0
        assert_indexes_consistent(s)
        await s.stop()

    run(go())


def test_late_subscriber_sees_only_later_messages():
    async def go():
        s = Server()
        await s.start()
        s.subscribe("early", "tm.event = 'Tx'", limit=64)
        s.publish(0, {"tm.event": ["Tx"]})
        s.publish(1, {"tm.event": ["Tx"]})
        late = s.subscribe("late", "tm.event = 'Tx'", limit=64)
        s.publish(2, {"tm.event": ["Tx"]})
        got = []
        while late._queue.qsize():
            got.append((await late.next()).data)
        assert got == [2]
        assert_indexes_consistent(s)
        await s.stop()

    run(go())
