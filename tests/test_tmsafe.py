"""tmsafe: the whole-program adversarial-input safety gate.

Five jobs: (1) run tmsafe over the whole package on every tier-1
invocation, failing on anything beyond the (empty) safe baseline —
the static form of "no wire message can buy asymmetric decode-time
work"; (2) prove the gate is not vacuous by seeding violations into a
COPY of the REAL package (strip the from_words clamp, strip a
handler's validate_basic) and watching the exact rule turn red;
(3) unit-test the engine against the seeded mini-packages in
tests/data/safe/ (each proven to turn exactly its own rule red, with
clamped/validated/suppressed twins green); (4) pin the taint-engine
regressions this PR's own development surfaced (`is None` must not
sanitize, constructor calls must return the tainted instance,
enumerate indexes are LEN); (5) the CLI exit contract and the
update-refusal matrix for --adv.
"""

import importlib.util
import json
import os
import shutil
import subprocess
import sys
import time

import pytest

from tendermint_tpu.analysis import tmsafe
from tendermint_tpu.analysis.tmcheck.callgraph import build_package
from tendermint_tpu.analysis.tmcheck.schema import extract_package
from tendermint_tpu.analysis.tmlint import (
    Violation,
    load_baseline,
    new_violations,
    save_baseline,
)
from tendermint_tpu.analysis.tmsafe import taintflow, validate
from tendermint_tpu.analysis.tmsafe.sources import derive_entries
from tendermint_tpu.analysis.tmsafe.taintflow import LEN, VAL, TaintEngine

FIXTURES = os.path.join(os.path.dirname(__file__), "data", "safe")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG_ROOT = os.path.join(REPO, "tendermint_tpu")


def _fixture_report(name: str):
    pkg = build_package(os.path.join(FIXTURES, name))
    return tmsafe.analyze(pkg)


# ---------------------------------------------------------------------------
# THE gate: whole package against the checked-in (empty) baseline


@pytest.fixture(scope="module")
def head_pkg():
    return build_package()


@pytest.fixture(scope="module")
def head_report(head_pkg):
    t0 = time.monotonic()
    rep = tmsafe.analyze(head_pkg)
    rep.elapsed_s = time.monotonic() - t0
    return rep


def test_package_clean_against_baseline(head_report):
    """tmsafe over the whole package; anything beyond
    tmsafe/safe_baseline.json fails tier-1 — fix it, suppress it with
    a justified `# tmsafe: <rule>-ok`, or consciously re-baseline
    (docs/static_analysis.md)."""
    new = new_violations(
        head_report.violations, load_baseline(tmsafe.SAFE_BASELINE_PATH)
    )
    assert not new, "new tmsafe violations:\n" + "\n".join(
        v.render() for v in new
    )


def test_safe_baseline_is_checked_in_and_empty():
    """Every first-run true positive was FIXED in-tree (the BitArray
    from_words clamp + packed elems encoding, the blockchain page-count
    clamp), none merely grandfathered, so the baseline must stay
    empty — new findings fail loudly."""
    assert os.path.exists(tmsafe.SAFE_BASELINE_PATH)
    with open(tmsafe.SAFE_BASELINE_PATH) as f:
        data = json.load(f)
    assert data["entries"] == {}


def test_full_package_run_under_budget(head_report):
    """Runtime budget: the adv pass runs on every tier-1 invocation
    and must stay under 10 s for the whole package (measured ~2.5 s
    including the call-graph build). Times the module fixture's run
    rather than paying a second analyze."""
    assert head_report.elapsed_s < 10.0, (
        f"tmsafe full-package run took {head_report.elapsed_s:.1f}s"
    )


def test_head_suppression_catalog_is_exactly_the_opaque_tx_sites(
    head_report,
):
    """The head catalog of accepted-by-rationale sites is exactly the
    two mempool-admission calls: a tx is opaque app bytes with no
    validate_basic of its own — CheckTx IS its validation. The batch
    ingest on the gossip receive loop (check_tx_batch, now a cataloged
    sink itself) plus the single serial-admission chokepoint all three
    RPC broadcast routes resolve to (Environment._admit_tx — the
    coalescing-batcher refactor collapsed the two per-route
    suppressions into one). Every other first-run finding got a real
    fix (BitArray clamp + packed elems, blockchain page clamp,
    evidence validate-before-add ×2), not a comment. A new entry here
    means someone added a `# tmsafe: <rule>-ok` — review the
    rationale, then extend this pin deliberately."""
    by_site = {(rule, path) for rule, path, _ln in head_report.suppressed}
    assert by_site == {
        ("safe-unvalidated-use", "mempool/reactor.py"),
        ("safe-unvalidated-use", "rpc/core.py"),
    }
    assert len(head_report.suppressed) == 2


# ---------------------------------------------------------------------------
# the machine-derived source catalog


def test_entries_cover_every_schema_decoder(head_pkg):
    """The decoder entry family IS the schema extraction's decoder
    set: every message with a dec_func resolves to an entry, so the
    source catalog cannot drift from the golden wire schema."""
    entries = {e.key for e in derive_entries(head_pkg)}
    messages, _ = extract_package(head_pkg.root, pkg=head_pkg)
    decoders = 0
    for mkey, msg in messages.items():
        if not msg.dec_func:
            continue
        path, _, tail = mkey.partition("::")
        cands = [(path, f"{tail}.{msg.dec_func}"), (path, msg.dec_func)]
        resolved = [k for k in cands if k in head_pkg.functions]
        if resolved:
            decoders += 1
            assert resolved[0] in entries, f"decoder {resolved[0]} not an entry"
    assert decoders >= 80  # 90+ messages, most with decoders


def test_entry_families_present(head_report):
    fams = {}
    for e in head_report.entries:
        fams[e.family] = fams.get(e.family, 0) + 1
    assert fams.get("decoder", 0) >= 80
    assert fams.get("rpc", 0) >= 30  # every RPCRequest route handler
    assert fams.get("rpc-parse", 0) == 3
    assert fams.get("wal", 0) == 2
    assert fams.get("p2p-framing", 0) >= 2
    assert fams.get("validate", 0) >= 20  # quadratic-rule scope


def test_region_reaches_the_delicate_helpers(head_pkg):
    """The taint region must include the helpers the first run's true
    positives lived in — BitArray.from_words (reached from
    decode_bit_array with VAL size) and FieldReader.__init__ (every
    decoder's receiver)."""
    eng = TaintEngine(head_pkg, derive_entries(head_pkg))
    eng.run()
    fw = ("libs/bits.py", "BitArray.from_words")
    assert fw in eng.states and eng.states[fw].analyzed
    assert eng.states[fw].param_taint.get("size") == VAL
    fr = ("encoding/proto.py", "FieldReader.__init__")
    assert fr in eng.states and eng.states[fr].analyzed


def test_mutation_sink_catalog_resolves(head_pkg):
    """Every MUTATION_SINKS key names a real function — the catalog
    cannot silently rot when a sink is moved or renamed."""
    for key in validate.MUTATION_SINKS:
        assert key in head_pkg.functions, f"stale sink catalog entry {key}"


# ---------------------------------------------------------------------------
# seeded violations against a copy of the REAL package


@pytest.fixture()
def pkg_copy(tmp_path):
    dst = tmp_path / "tendermint_tpu"
    shutil.copytree(
        PKG_ROOT, dst, ignore=shutil.ignore_patterns("__pycache__")
    )
    return dst


def _analyze_copy(dst):
    from tendermint_tpu.analysis.tmcheck import callgraph

    p = callgraph.Package(str(dst), "tendermint_tpu")
    p.build()
    return tmsafe.analyze(p)


def test_seeded_unclamped_from_words_turns_alloc_red(pkg_copy):
    """Acceptance: stripping the from_words MAX_BIT_ARRAY_SIZE clamp
    re-opens the real first-run finding — `1 << size` with a
    wire-chosen size — and the witness names the decode entry."""
    bits = pkg_copy / "libs" / "bits.py"
    src = bits.read_text()
    assert "MAX_BIT_ARRAY_SIZE:" in src
    start = src.index("        if size > MAX_BIT_ARRAY_SIZE:")
    end = src.index("        out = cls(size)")
    bits.write_text(src[:start] + src[end:])
    rep = _analyze_copy(pkg_copy)
    hits = [
        v for v in rep.violations
        if v.rule == "safe-alloc-unbounded" and v.path == "libs/bits.py"
    ]
    assert hits, "unclamped 1 << size not flagged"
    assert "decode_bit_array" in hits[0].message


def test_seeded_unclamped_light_blocks_page_turns_alloc_red(pkg_copy):
    """ISSUE 11 satellite: stripping the bulk light_blocks route's
    page clamp re-opens the exact class PR 10's blockchain fix pinned
    — a range() bound built from attacker-chosen heights instead of a
    clamp expression — and the witness names the route handler."""
    core = pkg_copy / "rpc" / "core.py"
    src = core.read_text()
    needle = "for off in range(min(max_h - min_h + 1, cap)):"
    assert needle in src
    core.write_text(
        src.replace(needle, "for off in range(max_h - min_h + 1):")
    )
    rep = _analyze_copy(pkg_copy)
    hits = [
        v for v in rep.violations
        if v.rule == "safe-alloc-unbounded" and v.path == "rpc/core.py"
    ]
    assert hits, "unclamped light_blocks page not flagged"
    assert "light_blocks" in hits[0].message


def test_seeded_dropped_validate_turns_unvalidated_red(pkg_copy):
    """Acceptance: deleting the vote handler's validate_basic() call
    makes the path to VoteSet.set_has_vote-family state unvalidated —
    the 25-site convention is a checked catalog now."""
    reactor = pkg_copy / "consensus" / "reactor.py"
    src = reactor.read_text()
    needle = (
        "        msg.validate_basic()\n"
        "        vote = msg.vote\n"
    )
    assert needle in src
    reactor.write_text(src.replace(needle, "        vote = msg.vote\n"))
    rep = _analyze_copy(pkg_copy)
    hits = [
        v for v in rep.violations
        if v.rule == "safe-unvalidated-use"
        and v.path == "consensus/reactor.py"
    ]
    assert hits, "dropped validate_basic not flagged"
    assert "_handle_vote_msg" in hits[0].message


# ---------------------------------------------------------------------------
# seeded mini-packages: each turns exactly its own rule red


def test_fixture_alloc_unbounded():
    rep = _fixture_report("alloc_pkg")
    assert {v.rule for v in rep.violations} == {"safe-alloc-unbounded"}
    lines = {(v.path, v.line) for v in rep.violations}
    # bytes(n), range(count), b"\x00"*n, 1<<size, readexactly(length)
    assert len(lines) == 5
    assert any(p == "p2p/conn.py" for p, _ in lines)
    # clamped / len-guarded / min-clamped twins are green: no finding
    # may sit inside them
    bad_lines = {ln for p, ln in lines if p == "types/mod.py"}
    src = open(
        os.path.join(FIXTURES, "alloc_pkg", "types", "mod.py")
    ).read().splitlines()
    for ln in bad_lines:
        fn_region = "\n".join(src[max(0, ln - 8): ln])
        assert "decode_clamped" not in fn_region
        assert "decode_len_guarded" not in fn_region
        assert "decode_min_clamped" not in fn_region
    # the suppressed twin was exercised
    assert rep.stats["suppressed"] == 1


def test_fixture_index_unchecked():
    rep = _fixture_report("index_pkg")
    assert {v.rule for v in rep.violations} == {"safe-index-unchecked"}
    assert len(rep.violations) == 1  # checked/guarded/suppressed green
    assert rep.violations[0].line == 13
    assert rep.stats["suppressed"] == 1


def test_fixture_unvalidated_use():
    rep = _fixture_report("unval_pkg")
    assert {v.rule for v in rep.violations} == {"safe-unvalidated-use"}
    assert len(rep.violations) == 1
    v = rep.violations[0]
    assert "handle_bad" in v.message
    assert "VoteSet.add_vote" in v.message
    # validated + transitively-validated twins green, suppressed twin
    # counted
    assert rep.stats["suppressed"] == 1


def test_fixture_quadratic_decode():
    rep = _fixture_report("quad_pkg")
    assert {v.rule for v in rep.violations} == {"safe-quadratic-decode"}
    lines = sorted(v.line for v in rep.violations)
    # nested-loop decoder, list-membership scan, validate_basic nest
    assert len(lines) == 3
    # clamped-slice twin and set-membership twin are green
    msgs = " ".join(v.message for v in rep.violations)
    assert "O(n^2)" in msgs


def test_fixture_baseline_round_trip(tmp_path):
    """save_baseline over fixture findings -> zero new; a duplicated
    offending line overflows its counted fingerprint."""
    rep = _fixture_report("alloc_pkg")
    path = tmp_path / "safe_baseline.json"
    save_baseline(rep.violations, str(path), note=tmsafe.SAFE_BASELINE_NOTE)
    assert new_violations(rep.violations, load_baseline(str(path))) == []
    extra = rep.violations + [rep.violations[0]]
    over = new_violations(extra, load_baseline(str(path)))
    assert over and "baseline allows" in over[0].message


# ---------------------------------------------------------------------------
# engine regressions (tiny synthetic packages)


def _mini_pkg(tmp_path, source: str):
    d = tmp_path / "mini"
    (d / "types").mkdir(parents=True)
    (d / "types" / "mod.py").write_text(source)
    return build_package(str(d))


def test_is_none_check_does_not_sanitize(tmp_path):
    """Regression: `if data is None: return None` is an identity test,
    not a bound — the engine once sanitized `data` on it and went
    vacuously clean (the tmtrace is-exemption lesson, re-learned)."""
    rep = tmsafe.analyze(_mini_pkg(tmp_path, (
        "from tendermint_tpu.encoding.proto import FieldReader\n"
        "def decode_thing(data):\n"
        "    if data is None:\n"
        "        return None\n"
        "    r = FieldReader(data)\n"
        "    n = r.uint(1)\n"
        "    return bytes(n)\n"
    )))
    assert [v.rule for v in rep.violations] == ["safe-alloc-unbounded"]


def test_enumerate_index_is_len_bounded(tmp_path):
    """`for i, w in enumerate(parsed)`: the index is bounded by the
    collection's length — only the element keeps VAL."""
    rep = tmsafe.analyze(_mini_pkg(tmp_path, (
        "from tendermint_tpu.encoding.proto import FieldReader\n"
        "def decode_thing(data):\n"
        "    r = FieldReader(data)\n"
        "    out = 0\n"
        "    for i, w in enumerate(r.get_all(1)):\n"
        "        out |= 1 << (64 * i)\n"  # index: LEN, no finding
        "    return out\n"
    )))
    assert rep.violations == []
    rep = tmsafe.analyze(_mini_pkg(tmp_path / "b", (
        "from tendermint_tpu.encoding.proto import FieldReader\n"
        "def decode_thing(data):\n"
        "    r = FieldReader(data)\n"
        "    out = 0\n"
        "    for i, w in enumerate(r.get_all(1)):\n"
        "        out |= 1 << w\n"  # element: VAL, flagged
        "    return out\n"
    )))
    assert [v.rule for v in rep.violations] == ["safe-alloc-unbounded"]


def test_slices_are_exempt_but_plain_index_is_not(tmp_path):
    """Python slices clamp (bounded by the source) — only plain
    subscripts are the aliasing hazard."""
    rep = tmsafe.analyze(_mini_pkg(tmp_path, (
        "from tendermint_tpu.encoding.proto import FieldReader\n"
        "def decode_thing(data):\n"
        "    r = FieldReader(data)\n"
        "    n = r.uint(1)\n"
        "    return data[n : n + 4]\n"  # slice: exempt
    )))
    assert rep.violations == []


def test_except_valueerror_does_not_guard_index_sinks(tmp_path):
    """Review finding (this PR): `except ValueError` does NOT catch
    IndexError — and a NEGATIVE wire index raises nothing at all — so
    it must not sanitize an index sink the way `except IndexError`
    does."""
    rep = tmsafe.analyze(_mini_pkg(tmp_path, (
        "from tendermint_tpu.encoding.proto import FieldReader\n"
        "LOOKUP = ['a', 'b']\n"
        "def decode_thing(data):\n"
        "    r = FieldReader(data)\n"
        "    i = r.int64(1)\n"
        "    try:\n"
        "        return LOOKUP[i]\n"
        "    except ValueError:\n"
        "        raise ValueError('bad') from None\n"
    )))
    assert [v.rule for v in rep.violations] == ["safe-index-unchecked"]


def test_kwonly_param_taint_is_not_dropped(tmp_path):
    """Review finding (this PR): taint passed as `count=parsed` into a
    keyword-only parameter must reach the callee."""
    rep = tmsafe.analyze(_mini_pkg(tmp_path, (
        "from tendermint_tpu.encoding.proto import FieldReader\n"
        "def _alloc(data, *, count):\n"
        "    return bytes(count)\n"
        "def decode_thing(data):\n"
        "    r = FieldReader(data)\n"
        "    n = r.uint(1)\n"
        "    return _alloc(data, count=n)\n"
    )))
    assert [v.rule for v in rep.violations] == ["safe-alloc-unbounded"]
    assert "_alloc" in rep.violations[0].message


def test_modulo_by_untainted_sanitizes(tmp_path):
    rep = tmsafe.analyze(_mini_pkg(tmp_path, (
        "from tendermint_tpu.encoding.proto import FieldReader\n"
        "TABLE = ['a', 'b', 'c']\n"
        "def decode_thing(data):\n"
        "    r = FieldReader(data)\n"
        "    n = r.uint(1)\n"
        "    return TABLE[n % len(TABLE)]\n"
    )))
    assert rep.violations == []


def test_fixed_literal_membership_sanitizes_but_accumulator_does_not(
    tmp_path,
):
    """`f in names` against a literal dispatch table sanitizes the tag
    (the abci _dec_pub_key idiom); `x in seen` against a growing
    accumulator must NOT — it is the quadratic scan itself."""
    rep = tmsafe.analyze(_mini_pkg(tmp_path, (
        "from tendermint_tpu.encoding.proto import FieldReader\n"
        "def decode_thing(data):\n"
        "    names = {1: 'ed', 2: 'secp'}\n"
        "    r = FieldReader(data)\n"
        "    f = r.uint(1)\n"
        "    if f in names:\n"
        "        return names[f]\n"
        "    raise ValueError('unknown')\n"
    )))
    assert rep.violations == []


def test_recursion_on_parsed_int_flagged_structural_descent_not(
    tmp_path,
):
    rep = tmsafe.analyze(_mini_pkg(tmp_path, (
        "from tendermint_tpu.encoding.proto import FieldReader\n"
        "def decode_thing(data):\n"
        "    r = FieldReader(data)\n"
        "    depth = r.uint(1)\n"
        "    return decode_thing(depth)\n"  # VAL-driven: flagged
    )))
    assert [v.rule for v in rep.violations] == ["safe-alloc-unbounded"]
    rep = tmsafe.analyze(_mini_pkg(tmp_path / "b", (
        "from tendermint_tpu.encoding.proto import FieldReader\n"
        "def decode_thing(data):\n"
        "    r = FieldReader(data)\n"
        "    sub = r.bytes(1)\n"
        "    if sub:\n"
        "        return decode_thing(sub)\n"  # LEN-driven: bytes per
        "    return ()\n"                     # level, transport-capped
    )))
    assert rep.violations == []


def test_interprocedural_summary_returns_val(tmp_path):
    """A helper that PARSES (LEN in, VAL out) must poison its caller's
    range() — the return-summary fixpoint, not just arg joining."""
    rep = tmsafe.analyze(_mini_pkg(tmp_path, (
        "from tendermint_tpu.encoding.proto import FieldReader\n"
        "def _count_of(data):\n"
        "    r = FieldReader(data)\n"
        "    return r.uint(1)\n"
        "def decode_thing(data):\n"
        "    r = FieldReader(data)\n"
        "    ver = r.uint(2)\n"
        "    if ver > 3:\n"
        "        raise ValueError('bad version')\n"
        "    out = []\n"
        "    for _ in range(_count_of(data)):\n"
        "        out.append(0)\n"
        "    return out\n"
    )))
    assert [
        (v.rule, v.line) for v in rep.violations
    ] == [("safe-alloc-unbounded", 11)]


def test_suppression_comment_block_above(tmp_path):
    """The comment-block-above form (shared family convention) covers
    the first code line below the block."""
    rep = tmsafe.analyze(_mini_pkg(tmp_path, (
        "from tendermint_tpu.encoding.proto import FieldReader\n"
        "def decode_thing(data):\n"
        "    r = FieldReader(data)\n"
        "    n = r.uint(1)\n"
        "    # tmsafe: safe-alloc-unbounded-ok — reviewed: fixture\n"
        "    # rationale spanning the block above the code line\n"
        "    return bytes(n)\n"
    )))
    assert rep.violations == []
    assert rep.stats["suppressed"] == 1


# ---------------------------------------------------------------------------
# CLI contract


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"), *args],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def _load_lint_module():
    spec = importlib.util.spec_from_file_location(
        "lint_cli_safe", os.path.join(REPO, "scripts", "lint.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow
def test_cli_adv_clean_exit_zero():
    r = _run_cli("--adv", "--stats")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "[adv]" in r.stdout


def test_cli_adv_seeded_violation_exit_one(monkeypatch):
    """The exit contract end to end: a safe finding beyond the (empty)
    baseline exits 1 through the real main()."""
    lint = _load_lint_module()
    seeded = [
        Violation(
            rule="safe-alloc-unbounded",
            path="types/fake.py",
            line=1,
            col=0,
            message="seeded unclamped allocation",
            source="return bytes(n)",
        )
    ]
    monkeypatch.setattr(
        lint.tmsafe, "safe_violations", lambda pkg=None, **kw: seeded
    )
    monkeypatch.setattr(
        lint.tmcheck, "build_package", lambda root=None: None
    )
    assert lint.main(["--adv"]) == 1


def test_cli_adv_baseline_update_refuses_filtered_runs():
    r = _run_cli("--adv", "--baseline-update", "--rule", "det-float")
    assert r.returncode == 2
    assert "full-package" in r.stderr


def test_cli_update_modes_refuse_adv():
    """--schema-update / --signatures-update combined with --adv would
    silently skip the adv gate while exiting 0 — the laundering class
    every section must refuse."""
    r = _run_cli("--schema-update", "--adv")
    assert r.returncode == 2 and "full-package" in r.stderr
    r = _run_cli("--signatures-update", "--adv")
    assert r.returncode == 2 and "full-package" in r.stderr


def test_cli_list_rules_includes_safe():
    r = _run_cli("--list-rules")
    assert r.returncode == 0
    for rid, _ in tmsafe.RULES:
        assert rid in r.stdout
