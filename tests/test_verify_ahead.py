"""Verify-ahead vote queue tests: queued votes are batch-verified in
one call before the single-writer loop processes them — valid triples
land in the verified-signature cache (crypto/sigcache) — and the cache
never widens acceptance (SURVEY §7 verify-ahead design; reference hot
path: internal/consensus/state.go:2010,2058 + types/vote_set.go:203).
"""

import asyncio
import time

import pytest

from tendermint_tpu.consensus.msgs import MsgInfo, VoteMessage
from tendermint_tpu.crypto import sigcache, tpu_verifier
from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.canonical import PRECOMMIT_TYPE, PREVOTE_TYPE
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.validator import Validator, ValidatorSet
from tendermint_tpu.types.vote import Vote
from tendermint_tpu.types.vote_set import VoteSet

from tests.test_consensus_state import Node, fast_config

CHAIN = "va-chain"


def _votes(privs, vals, height, block_id, vtype=PREVOTE_TYPE):
    order = {v.address: i for i, v in enumerate(vals.validators)}
    out = []
    now = time.time_ns()
    for p in privs:
        addr = p.pub_key().address()
        v = Vote(
            type=vtype,
            height=height,
            round=0,
            block_id=block_id,
            timestamp_ns=now,
            validator_address=addr,
            validator_index=order[addr],
        )
        v.signature = p.sign(v.sign_bytes(CHAIN))
        out.append(v)
    return out


def _genesis(privs):
    return GenesisDoc(
        chain_id=CHAIN,
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=[
            GenesisValidator(pub_key=p.pub_key(), power=10) for p in privs
        ],
    )


def _cached(vote, pk) -> bool:
    """Whether the vote's exact triple is in the verified-signature
    cache (what _preverify_votes populates instead of a marker)."""
    return sigcache.seen_key(
        sigcache.key_for(
            pk.bytes(), vote.sign_bytes(CHAIN), vote.signature
        )
    )


def test_preverify_marks_valid_and_skips_invalid():
    async def go():
        privs = [PrivKeyEd25519.from_seed(bytes([i + 1]) * 32)
                 for i in range(6)]
        genesis = _genesis(privs)
        node = Node(privs[0], genesis)
        cs = node.cs
        vals = cs.rs.validators
        bid = BlockID(
            hash=b"\x42" * 32,
            part_set_header=PartSetHeader(total=1, hash=b"\x43" * 32),
        )
        votes = _votes(privs, vals, cs.rs.height, bid)
        # corrupt one signature
        votes[3].signature = (
            votes[3].signature[:-1]
            + bytes([votes[3].signature[-1] ^ 1])
        )
        batch = [MsgInfo(msg=VoteMessage(vote=v), peer_id="p") for v in votes]
        cs._preverify_votes(batch)
        cached = [
            _cached(v, p.pub_key()) for p, v in zip(privs, votes)
        ]
        assert cached == [True, True, True, False, True, True]

        # the corrupted vote still fails through the normal path
        vs = VoteSet(CHAIN, cs.rs.height, 0, PREVOTE_TYPE, vals)
        for i, v in enumerate(votes):
            if i == 3:
                with pytest.raises(ValueError, match="invalid signature"):
                    vs.add_vote(v)
            else:
                assert vs.add_vote(v)

    asyncio.run(go())


def test_preverify_ignores_foreign_heights_and_bad_indexes():
    async def go():
        privs = [PrivKeyEd25519.from_seed(bytes([i + 30]) * 32)
                 for i in range(4)]
        node = Node(privs[0], _genesis(privs))
        cs = node.cs
        vals = cs.rs.validators
        bid = BlockID(
            hash=b"\x52" * 32,
            part_set_header=PartSetHeader(total=1, hash=b"\x53" * 32),
        )
        future = _votes(privs, vals, cs.rs.height + 5, bid)
        wrong_index = _votes(privs, vals, cs.rs.height, bid)
        for v in wrong_index:
            v.validator_index = (v.validator_index + 1) % 4
        batch = [
            MsgInfo(msg=VoteMessage(vote=v), peer_id="p")
            for v in future + wrong_index
        ]
        cs._preverify_votes(batch)
        assert not any(
            _cached(v, p.pub_key())
            for p, v in zip(privs + privs, future + wrong_index)
        )

    asyncio.run(go())


def test_cache_does_not_bypass_address_or_hrs_checks():
    """A cached (even legitimately verified) triple cannot smuggle a
    vote past VoteSet: add_vote still enforces index/address/HRS and
    duplicate checks before the signature step ever consults the
    cache."""

    async def go():
        privs = [PrivKeyEd25519.from_seed(bytes([i + 60]) * 32)
                 for i in range(4)]
        node = Node(privs[0], _genesis(privs))
        cs = node.cs
        vals = cs.rs.validators
        bid = BlockID(
            hash=b"\x62" * 32,
            part_set_header=PartSetHeader(total=1, hash=b"\x63" * 32),
        )
        votes = _votes(privs, vals, cs.rs.height, bid)
        # the whole burst verifies ahead: every triple is now cached
        cs._preverify_votes(
            [MsgInfo(msg=VoteMessage(vote=v), peer_id="p") for v in votes]
        )
        vote = votes[0]
        assert _cached(vote, privs[0].pub_key())
        # point at a DIFFERENT validator's slot than the vote's address
        vote.validator_index = (vote.validator_index + 1) % 4
        vs = VoteSet(CHAIN, cs.rs.height, 0, PREVOTE_TYPE, vals)
        with pytest.raises(ValueError, match="does not match"):
            vs.add_vote(vote)

    asyncio.run(go())


def test_batched_votes_flow_through_receive_loop():
    """End-to-end through the running consensus loop: a burst of
    queued votes is drained, pre-verified in one batch, and tallied
    (3 of 4 validators precommit -> commit advances the height)."""

    async def go():
        privs = [PrivKeyEd25519.from_seed(bytes([i + 90]) * 32)
                 for i in range(4)]
        genesis = _genesis(privs)
        # the node must be height 1/round 0's proposer or it has no
        # proposal to vote on (no peers to receive one from)
        probe = ValidatorSet(
            [Validator(pub_key=p.pub_key(), voting_power=10) for p in privs]
        )
        proposer_addr = probe.get_proposer().address
        me = next(
            p for p in privs if p.pub_key().address() == proposer_addr
        )
        node = Node(me, genesis, cfg=fast_config(
            timeout_propose=2.0,
        ))
        cs = node.cs
        sigs_before = tpu_verifier.stats()["sigs"]
        tpu_verifier.install(min_batch=2)
        await cs.start()
        try:
            # wait for our proposal for height 1 to exist
            deadline = time.monotonic() + 10.0
            while cs.rs.proposal_block is None:
                if time.monotonic() > deadline:
                    raise TimeoutError("no proposal")
                await asyncio.sleep(0.02)
            bid = BlockID(
                hash=cs.rs.proposal_block.hash(),
                part_set_header=cs.rs.proposal_block_parts.header(),
            )
            height = cs.rs.height
            # burst: prevotes + precommits from the other 3 validators
            others = [p for p in privs if p is not me]
            for vtype in (PREVOTE_TYPE, PRECOMMIT_TYPE):
                for v in _votes(others, cs.rs.validators, height, bid,
                                vtype):
                    cs.send_peer_msg(VoteMessage(vote=v), "peerX")
                await asyncio.sleep(0.3)
            await cs.wait_for_height(height + 1, timeout=15.0)
            # the burst went through the device batch path
            assert tpu_verifier.stats()["sigs"] > sigs_before
        finally:
            await cs.stop()
            # don't leak the installed factory into later test files:
            # create_batch_verifier would keep routing through the
            # device seam and break their counting stubs
            tpu_verifier.uninstall()

    asyncio.run(go())


def test_preverify_mixed_key_types_batches_both_groups():
    """A 50/50 ed25519/sr25519 validator set: verify-ahead groups the
    burst per key type and pre-verifies BOTH groups (matching
    types/validation.py's per-key-type commit grouping)."""
    from tendermint_tpu.crypto.sr25519 import PrivKeySr25519

    async def go():
        privs = [PrivKeyEd25519.from_seed(bytes([i + 1]) * 32)
                 for i in range(3)] + \
                [PrivKeySr25519.from_seed(bytes([i + 120]) * 32)
                 for i in range(3)]
        node = Node(privs[0], _genesis(privs))
        cs = node.cs
        vals = cs.rs.validators
        bid = BlockID(
            hash=b"\x72" * 32,
            part_set_header=PartSetHeader(total=1, hash=b"\x73" * 32),
        )
        votes = _votes(privs, vals, cs.rs.height, bid)
        # corrupt one signature in each group
        by_type = {}
        for p, v in zip(privs, votes):
            by_type.setdefault(p.pub_key().type(), []).append(v)
        bad = {kt: vs[1] for kt, vs in by_type.items()}
        for v in bad.values():
            v.signature = v.signature[:8] + bytes(
                [v.signature[8] ^ 1]
            ) + v.signature[9:]
        batch = [
            MsgInfo(msg=VoteMessage(vote=v), peer_id="p") for v in votes
        ]
        cs._preverify_votes(batch)
        pk_by_vote = {
            id(v): p.pub_key() for p, v in zip(privs, votes)
        }
        for kt, vs in by_type.items():
            cached = [_cached(v, pk_by_vote[id(v)]) for v in vs]
            want = [v is not bad[kt] for v in vs]
            assert cached == want, (kt, cached)

    asyncio.run(go())
