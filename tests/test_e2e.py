"""Manifest-driven e2e harness tests (reference: test/e2e/ — manifests,
runner phases, generator, invariant tests)."""

import textwrap

import pytest

from tendermint_tpu.e2e import (
    Manifest,
    Perturbation,
    Runner,
    generate,
    run_manifest,
)


def test_manifest_toml_roundtrip(tmp_path):
    toml = textwrap.dedent(
        """
        chain_id = "toml-net"
        initial_height = 7
        target_height = 9

        [validators]
        validator01 = 10
        validator02 = 20

        [node.validator01]
        database = "sqlite"
        perturb = ["kill:3", "restart:4"]

        [node.full01]
        mode = "full"
        start_at = 2

        [load]
        tx_rate = 3.5
        tx_size = 32
        """
    )
    p = tmp_path / "m.toml"
    p.write_text(toml)
    m = Manifest.from_toml(str(p))
    assert m.chain_id == "toml-net"
    assert m.initial_height == 7
    assert m.validators == {"validator01": 10, "validator02": 20}
    assert m.nodes["validator01"].database == "sqlite"
    assert m.nodes["validator01"].perturb == [
        Perturbation("kill", 3),
        Perturbation("restart", 4),
    ]
    assert m.nodes["validator02"].mode == "validator"  # defaulted
    assert m.nodes["full01"].mode == "full"
    assert m.load.tx_rate == 3.5


def test_manifest_rejects_unstartable_network():
    m = Manifest(validators={"a": 10, "b": 10, "c": 10})
    m.validate()  # defaults node specs for the validators
    m.nodes["a"].start_at = 2
    m.nodes["b"].start_at = 2
    with pytest.raises(ValueError, match="2/3 power"):
        m.validate()


def test_generator_deterministic():
    a = generate(seed=11, count=6)
    b = generate(seed=11, count=6)
    assert [m.chain_id for m in a] == [m.chain_id for m in b]
    assert [sorted(m.validators.items()) for m in a] == [
        sorted(m.validators.items()) for m in b
    ]
    # every generated manifest is valid by construction
    for m in a:
        m.validate()


def test_run_basic_load(tmp_path):
    """4 validators + tx load to height 5: no forks, txs committed,
    benchmark stats produced (reference: runner/{load,wait,test,
    benchmark}.go)."""
    m = Manifest(
        chain_id="e2e-basic",
        target_height=5,
        validators={f"validator{i:02d}": 10 for i in range(1, 5)},
    )
    m.load.tx_rate = 5.0
    m.validate()
    rep = run_manifest(m, str(tmp_path), timeout=180.0)
    assert rep.ok, rep.failures
    assert rep.reached_height >= 5
    assert rep.txs_submitted > 0 and rep.txs_committed > 0
    assert rep.blocks >= 4 and rep.interval_avg > 0


def test_run_late_joiner_and_disconnect(tmp_path):
    """A full node joining at height 2 (block sync) plus a disconnect
    perturbation on one validator (reference: runner/perturb.go)."""
    from tendermint_tpu.e2e.manifest import NodeSpec

    m = Manifest(
        chain_id="e2e-perturb",
        target_height=5,
        validators={f"validator{i:02d}": 10 for i in range(1, 5)},
    )
    m.validate()
    m.nodes["validator04"].perturb = [Perturbation("disconnect", 3)]
    m.nodes["full01"] = NodeSpec(name="full01", mode="full", start_at=2)
    m.validate()
    rep = run_manifest(m, str(tmp_path), timeout=180.0)
    assert rep.ok, rep.failures
    assert rep.reached_height >= 5


def test_run_state_sync_late_joiner(tmp_path):
    """A node joining at height 4 with state_sync: discovers a
    snapshot from peers, restores the app without replaying all
    blocks, then follows consensus (reference: the statesync manifests
    in test/e2e/, runner/start.go waitForNodeHeight)."""
    from tendermint_tpu.e2e.manifest import NodeSpec

    m = Manifest(
        chain_id="e2e-statesync",
        target_height=8,
        validators={f"validator{i:02d}": 10 for i in range(1, 4)},
    )
    m.validate()
    m.nodes["full01"] = NodeSpec(
        name="full01", mode="full", start_at=4, state_sync=True
    )
    m.validate()
    rep = run_manifest(m, str(tmp_path), timeout=200.0)
    assert rep.ok, rep.failures
    assert rep.reached_height >= 8
    assert rep.state_synced == {"full01": True}


def test_generated_manifests_are_runnable(tmp_path):
    """The generator's output isn't just structurally valid — a sampled
    manifest must actually converge when run (reference: the CI loop in
    test/e2e/generator + runner). One seeded pick keeps CI bounded;
    the seed walk below selects a small network without a byzantine
    node so the runtime stays in seconds."""
    for seed in range(40):
        (m,) = generate(seed=seed, count=1)
        if (
            len(m.validators) <= 3
            and not any(s.misbehaviors for s in m.nodes.values())
            and m.initial_height == 1
        ):
            break
    else:
        raise AssertionError("no small generated manifest in seed walk")
    rep = run_manifest(m, str(tmp_path), timeout=180.0)
    assert rep.ok, (m.chain_id, rep.failures)
    assert rep.reached_height >= m.target_height
