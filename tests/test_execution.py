"""BlockExecutor tests — proposal→apply→commit over real kvstore app
(reference model: internal/state/execution_test.go, validation_test.go)."""

import asyncio

import pytest

from tendermint_tpu.abci import KVStoreApplication, LocalClient
from tendermint_tpu.abci import types as abci
from tendermint_tpu.config import MempoolConfig
from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.eventbus import EventBus
from tendermint_tpu.mempool import TxMempool
from tendermint_tpu.pubsub.query import query_for_event
from tendermint_tpu.state import StateStore, state_from_genesis
from tendermint_tpu.state.execution import (
    BlockExecutor,
    results_hash,
    update_state,
    validate_block,
)
from tendermint_tpu.store.block_store import BlockStore
from tendermint_tpu.store.kv import MemKV
from tendermint_tpu.types import Commit, CommitSig, events as E
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.vote import Vote
from tendermint_tpu.types.canonical import PRECOMMIT_TYPE

CHAIN = "exec-chain"


def run(coro):
    return asyncio.run(coro)


def make_env(n_vals=1):
    privs = [PrivKeyEd25519.from_seed(bytes([i + 10]) * 32) for i in range(n_vals)]
    genesis = GenesisDoc(
        chain_id=CHAIN,
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(pub_key=p.pub_key(), power=10) for p in privs],
    )
    state = state_from_genesis(genesis)
    app = KVStoreApplication()
    client = LocalClient(app)
    store = StateStore(MemKV())
    store.save(state)
    mempool = TxMempool(client, MempoolConfig())
    bus = EventBus()
    block_store = BlockStore(MemKV())
    execu = BlockExecutor(
        store, client, mempool, block_store=block_store, event_bus=bus
    )
    return state, app, client, store, mempool, bus, execu, privs


def commit_for(state, block, block_id, privs):
    """Sign a precommit for `block` by every validator, as its Commit."""
    sigs = []
    vals = state.validators
    for i, v in enumerate(vals.validators):
        priv = next(p for p in privs if p.pub_key().address() == v.address)
        vote = Vote(
            type=PRECOMMIT_TYPE,
            height=block.header.height,
            round=0,
            block_id=block_id,
            timestamp_ns=block.header.time_ns + 1,
            validator_address=v.address,
            validator_index=i,
        )
        vote.signature = priv.sign(vote.sign_bytes(CHAIN))
        sigs.append(
            CommitSig.for_block(
                vote.signature, vote.validator_address, vote.timestamp_ns
            )
        )
    return Commit(
        height=block.header.height, round=0, block_id=block_id, signatures=sigs
    )


def test_results_hash_deterministic_and_sensitive():
    r1 = [abci.ResponseDeliverTx(code=0, data=b"a", gas_used=1)]
    r2 = [abci.ResponseDeliverTx(code=0, data=b"a", gas_used=1)]
    r3 = [abci.ResponseDeliverTx(code=1, data=b"a", gas_used=1)]
    # log/info/events are non-deterministic fields and must NOT affect it
    r4 = [abci.ResponseDeliverTx(code=0, data=b"a", gas_used=1, log="noise")]
    assert results_hash(r1) == results_hash(r2) == results_hash(r4)
    assert results_hash(r1) != results_hash(r3)


def test_two_block_chain_with_txs_and_events():
    async def go():
        state, app, client, store, mempool, bus, execu, privs = make_env()
        await bus.start()
        sub = bus.subscribe("t", query_for_event(E.EventValue.NEW_BLOCK))
        sub_tx = bus.subscribe("t", "tm.event = 'Tx' AND tx.height = 1")

        await mempool.check_tx(b"alpha=1")
        proposer = state.validators.get_proposer().address

        # ---- height 1 ----
        block1, parts1 = execu.create_proposal_block(
            1, state, Commit(height=0), proposer
        )
        assert block1.txs == [b"alpha=1"]
        bid1 = block1.block_id()
        state1 = await execu.apply_block(state, bid1, block1)

        assert state1.last_block_height == 1
        assert state1.app_hash == app.app_hash != b""
        assert mempool.size() == 0  # committed tx removed
        ev = await sub.next()
        assert ev.data.block.header.height == 1
        txev = await sub_tx.next()
        assert txev.data.tx == b"alpha=1"

        # ---- height 2 (LastCommit batch-verified) ----
        commit1 = commit_for(state1, block1, bid1, privs)
        await mempool.check_tx(b"beta=2")
        block2, _ = execu.create_proposal_block(2, state1, commit1, proposer)
        bid2 = block2.block_id()
        state2 = await execu.apply_block(state1, bid2, block2)
        assert state2.last_block_height == 2
        # results hash of height 2 covers its DeliverTx responses,
        # reloadable from the state store
        reloaded = store.load_abci_responses(2)
        assert state2.last_results_hash == results_hash(reloaded.deliver_tx_objs)
        assert store.load().last_block_height == 2
        assert app.state[b"beta"] == b"2"
        # state store has validators for both heights
        assert store.load_validators(1) is not None
        assert store.load_validators(2) is not None
        await bus.stop()

    run(go())


def test_validate_block_rejects_tampering():
    async def go():
        state, app, client, store, mempool, bus, execu, privs = make_env()
        proposer = state.validators.get_proposer().address
        block1, _ = execu.create_proposal_block(
            1, state, Commit(height=0), proposer
        )
        bid1 = block1.block_id()

        # wrong app hash (re-derive dependent hashes so only AppHash is off)
        block1.hash()  # fill header first
        block1.header.app_hash = b"\xff" * 32
        with pytest.raises(ValueError, match="AppHash"):
            validate_block(state, block1)

        # wrong chain id
        block2, _ = execu.create_proposal_block(
            1, state, Commit(height=0), proposer
        )
        block2.header.chain_id = "not-the-chain"
        with pytest.raises(ValueError, match="ChainID"):
            validate_block(state, block2)

        # non-validator proposer
        block3, _ = execu.create_proposal_block(
            1, state, Commit(height=0), b"\x01" * 20
        )
        with pytest.raises(ValueError, match="proposer"):
            validate_block(state, block3)

    run(go())


def test_apply_block_rejects_bad_last_commit():
    async def go():
        state, app, client, store, mempool, bus, execu, privs = make_env()
        proposer = state.validators.get_proposer().address
        block1, _ = execu.create_proposal_block(
            1, state, Commit(height=0), proposer
        )
        bid1 = block1.block_id()
        state1 = await execu.apply_block(state, bid1, block1)

        # commit signed by an impostor key
        impostor = PrivKeyEd25519.from_seed(b"\x99" * 32)
        commit1 = commit_for(state1, block1, bid1, privs)
        vote = Vote(
            type=PRECOMMIT_TYPE,
            height=1,
            round=0,
            block_id=bid1,
            timestamp_ns=block1.header.time_ns + 1,
            validator_address=state1.validators.validators[0].address,
            validator_index=0,
        )
        commit1.signatures[0] = CommitSig.for_block(
            impostor.sign(vote.sign_bytes(CHAIN)),
            vote.validator_address,
            vote.timestamp_ns,
        )
        block2, _ = execu.create_proposal_block(2, state1, commit1, proposer)
        with pytest.raises(Exception):
            await execu.apply_block(state1, block2.block_id(), block2)

    run(go())


def test_validator_update_via_endblock():
    async def go():
        state, app, client, store, mempool, bus, execu, privs = make_env()
        proposer = state.validators.get_proposer().address
        new_val = PrivKeyEd25519.from_seed(b"\x55" * 32)
        tx = f"val:{new_val.pub_key().bytes().hex()}!8".encode()
        await mempool.check_tx(tx)
        block1, _ = execu.create_proposal_block(
            1, state, Commit(height=0), proposer
        )
        bid1 = block1.block_id()
        state1 = await execu.apply_block(state, bid1, block1)
        # validators update lands in next_validators at h+2
        assert len(state1.validators) == 1
        assert len(state1.next_validators) == 2
        assert state1.last_height_validators_changed == 3
        addrs = {v.address for v in state1.next_validators.validators}
        assert new_val.pub_key().address() in addrs

    run(go())
