"""tmcheck: whole-program sign-bytes taint analysis + wire-schema
conformance — the tier-1 gates and the analyzer's own unit tests.

Two package-wide gates run on every tier-1 invocation, alongside the
tmlint gate in test_lint.py:

- taint: no nondeterminism source reachable (interprocedurally) from
  sign-bytes/hash construction beyond the checked-in baseline;
- schema: the statically-extracted wire schema of every codec matches
  the golden analysis/tmcheck/schema.json, encode/decode are
  symmetric, and emission order is ascending.

The seeded-violation tests copy the real package to a temp tree and
inject the exact failure modes the gates exist to catch (a wall-clock
read in a helper transitively called from types/canonical.py; a
swapped field write / changed tag in a to_proto) and assert they are
reported — with the full call chain for taint.
"""

import json
import os
import shutil
import subprocess
import sys
import time

import pytest

from tendermint_tpu.analysis import tmcheck, tmlint
from tendermint_tpu.analysis.tmcheck import callgraph, schema, taint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG_ROOT = tmlint.package_root()


@pytest.fixture(scope="module")
def pkg():
    return tmcheck.build_package()


# ---------------------------------------------------------------------------
# THE gates


def test_package_taint_clean_against_baseline(pkg):
    """No nondeterminism source reachable from sign-bytes/hash
    construction beyond taint_baseline.json. Fix it, suppress it with
    a justified `# tmcheck: taint-ok`/`taint-break`, or consciously
    re-baseline (docs/static_analysis.md)."""
    new = tmcheck.new_taint_violations(pkg)
    assert not new, "new taint violations:\n" + "\n".join(
        v.render() for v in new
    )


def test_package_schema_conforms_to_golden():
    """Extracted wire schema == golden schema.json, symmetric, and
    ascending. ANY drift in tags/wire types/order is a consensus wire
    break until reviewed via scripts/lint.py --schema-update."""
    violations = tmcheck.schema_violations()
    assert not violations, "schema violations:\n" + "\n".join(
        v.render() for v in violations
    )


def test_whole_package_run_under_budget():
    """The full tmcheck run (call graph + taint + schema extraction +
    golden diff) must stay cheap enough for every tier-1 invocation:
    <10 s on CPU (measured ~2 s)."""
    t0 = time.monotonic()
    p = tmcheck.build_package()
    tmcheck.taint_violations(p)
    tmcheck.schema_violations()
    elapsed = time.monotonic() - t0
    assert elapsed < 10.0, f"tmcheck full run took {elapsed:.1f}s"


def test_golden_schema_is_checked_in_with_provenance():
    golden = tmcheck.load_golden()
    assert golden is not None and golden["version"] == 1
    msgs = golden["messages"]
    assert len(msgs) >= 80
    # provenance: every entry records which reference .proto/.pb.go it
    # mirrors (ISSUE: recorded inline)
    missing = [k for k, m in msgs.items() if not m.get("reference")]
    assert not missing, f"messages without provenance: {missing}"
    # the core consensus messages are present
    for key in (
        "types/vote.py::Vote",
        "types/commit.py::Commit",
        "types/header.py::Header",
        "types/canonical.py::canonical_vote_bytes",
        "types/validator.py::ValidatorSet",
        "consensus/msgs.py::VoteMessage",
        "abci/codec.py::pub_key",
    ):
        assert key in msgs, key


def test_taint_baseline_is_checked_in_and_empty():
    """The taint gate carries NO accepted debt: every exception is an
    in-file justified suppression, so the baseline must stay empty —
    if this fails, someone re-baselined instead of justifying."""
    assert os.path.exists(tmcheck.TAINT_BASELINE_PATH)
    with open(tmcheck.TAINT_BASELINE_PATH) as f:
        data = json.load(f)
    assert data["entries"] == {}


# ---------------------------------------------------------------------------
# seeded violations against a copy of the REAL package


@pytest.fixture()
def pkg_copy(tmp_path):
    dst = tmp_path / "tendermint_tpu"
    shutil.copytree(
        PKG_ROOT, dst, ignore=shutil.ignore_patterns("__pycache__")
    )
    return dst


def _analyze_copy(dst):
    p = callgraph.Package(str(dst), "tendermint_tpu")
    p.build()
    return p


def test_seeded_wallclock_in_helper_reports_full_chain(pkg_copy):
    """Acceptance: a time.time() injected into a helper transitively
    called from types/canonical.py sign-bytes construction is reported
    with its full call chain, and fails the gate as NEW."""
    ts = pkg_copy / "types" / "timestamp.py"
    src = ts.read_text()
    seeded = src.replace(
        "def encode_timestamp(ns: int) -> bytes:\n"
        '    """google.protobuf.Timestamp wire encoding."""\n',
        "def _skew_helper():\n"
        "    return _time.time()\n"
        "\n"
        "\n"
        "def encode_timestamp(ns: int) -> bytes:\n"
        '    """google.protobuf.Timestamp wire encoding."""\n'
        "    _skew_helper()\n",
    )
    assert seeded != src, "injection anchor moved; update the test"
    ts.write_text(seeded)
    p = _analyze_copy(pkg_copy)
    new = tmcheck.new_taint_violations(p)
    hits = [v for v in new if v.rule == "taint-wallclock"]
    assert hits, "seeded wall-clock read not reported"
    msg = hits[0].message
    # the full offending call chain, root first
    assert "types/canonical.py:canonical_vote_bytes" in msg
    assert "types/timestamp.py:encode_timestamp" in msg
    assert "types/timestamp.py:_skew_helper" in msg
    assert hits[0].path == "types/timestamp.py"


def test_seeded_float_in_reachable_helper_is_reported(pkg_copy):
    """Same route, float arithmetic: a division seeded into
    encode_timestamp surfaces as taint-float with the chain."""
    ts = pkg_copy / "types" / "timestamp.py"
    src = ts.read_text()
    seeded = src.replace(
        "    seconds, nanos = divmod(ns, NS)\n    w = ProtoWriter()",
        "    seconds, nanos = divmod(ns, NS)\n"
        "    _skew = ns / NS\n"
        "    w = ProtoWriter()",
    )
    assert seeded != src
    ts.write_text(seeded)
    p = _analyze_copy(pkg_copy)
    new = tmcheck.new_taint_violations(p)
    hits = [v for v in new if v.rule == "taint-float"]
    assert any(
        "encode_timestamp" in v.message and "canonical" in v.message
        for v in hits
    ), "\n".join(v.render() for v in new)


def test_seeded_field_swap_fails_schema_gate(pkg_copy):
    """Acceptance: swapping two field writes in a to_proto fails the
    schema diff (order + drift)."""
    vote = pkg_copy / "types" / "vote.py"
    src = vote.read_text()
    seeded = src.replace(
        "w.int(2, self.height)\n        w.int(3, self.round)",
        "w.int(3, self.round)\n        w.int(2, self.height)",
    )
    assert seeded != src
    vote.write_text(seeded)
    violations = schema.schema_violations(str(pkg_copy))
    rules = {v.rule for v in violations}
    assert "schema-order" in rules
    assert "schema-drift" in rules
    drift = [v for v in violations if v.rule == "schema-drift"]
    assert any("types/vote.py::Vote" in v.message for v in drift)


def test_seeded_tag_change_fails_schema_gate(pkg_copy):
    """Acceptance: changing a tag number in any to_proto fails the
    schema diff."""
    commit = pkg_copy / "types" / "commit.py"
    src = commit.read_text()
    # bump one literal tag in Commit.to_proto's writer calls
    import re

    m = re.search(r"w\.int\(1, self\.height\)", src)
    assert m, "anchor moved; update the test"
    seeded = src.replace("w.int(1, self.height)", "w.int(7, self.height)", 1)
    commit.write_text(seeded)
    violations = schema.schema_violations(str(pkg_copy))
    drift = [v for v in violations if v.rule == "schema-drift"]
    assert any("types/commit.py" in v.path for v in drift)


def test_seeded_dropped_parse_fails_symmetry(pkg_copy):
    """Deleting a decoder's read of a written field is caught by the
    symmetry check (silent codec drift: bytes written, value lost)."""
    vote = pkg_copy / "types" / "vote.py"
    src = vote.read_text()
    seeded = src.replace("validator_address=r.bytes(6),\n", "")
    assert seeded != src
    vote.write_text(seeded)
    violations = schema.schema_violations(str(pkg_copy))
    sym = [v for v in violations if v.rule == "schema-symmetry"]
    assert any(
        "field 6" in v.message and "Vote" in v.message for v in sym
    ), "\n".join(v.render() for v in violations)


# ---------------------------------------------------------------------------
# call-graph resolution units (synthetic two-module package)


@pytest.fixture()
def tiny_pkg(tmp_path):
    root = tmp_path / "tinypkg"
    (root / "types").mkdir(parents=True)
    (root / "libs").mkdir()
    (root / "__init__.py").write_text("")
    (root / "types" / "__init__.py").write_text("")
    (root / "libs" / "__init__.py").write_text("")
    (root / "libs" / "helpers.py").write_text(
        "import time as clock\n"
        "\n"
        "\n"
        "def leaky():\n"
        "    return clock.time()\n"
        "\n"
        "\n"
        "def clean():\n"
        "    return 7\n"
    )
    (root / "types" / "canonical.py").write_text(
        "from ..libs.helpers import leaky\n"
        "from ..libs import helpers\n"
        "\n"
        "\n"
        "class Writer:\n"
        "    def emit(self):\n"
        "        return leaky()\n"
        "\n"
        "\n"
        "def build():\n"
        "    w = Writer()\n"
        "    return w.emit()\n"
        "\n"
        "\n"
        "def build_via_module():\n"
        "    return helpers.clean()\n"
    )
    p = callgraph.Package(str(root), "tinypkg")
    p.build()
    return p


def test_callgraph_resolves_from_import_and_alias(tiny_pkg):
    emit = tiny_pkg.functions[("types/canonical.py", "Writer.emit")]
    assert any(
        s.target == ("libs/helpers.py", "leaky") for s in emit.calls
    )
    leaky = tiny_pkg.functions[("libs/helpers.py", "leaky")]
    # `import time as clock; clock.time()` resolves to the real name
    assert any(s.external == "time.time" for s in leaky.calls)


def test_callgraph_resolves_local_instance_and_module_attr(tiny_pkg):
    build = tiny_pkg.functions[("types/canonical.py", "build")]
    assert any(
        s.target == ("types/canonical.py", "Writer.emit")
        for s in build.calls
    )
    via = tiny_pkg.functions[("types/canonical.py", "build_via_module")]
    assert any(
        s.target == ("libs/helpers.py", "clean") for s in via.calls
    )


def test_taint_chain_through_synthetic_package(tiny_pkg):
    vs = taint.taint_violations(tiny_pkg)
    assert len(vs) == 1
    v = vs[0]
    assert v.rule == "taint-wallclock"
    # shortest chain from a types/canonical.py root
    assert "types/canonical.py" in v.message
    assert "libs/helpers.py:leaky" in v.message


def test_callgraph_resolves_root_init_reexport(tmp_path):
    """Re-exports through the package ROOT __init__.py must resolve —
    a source behind `from <pkg> import helper` (or `from . import x`
    at the root) is otherwise invisible to the gate (false negative)."""
    root = tmp_path / "rootpkg"
    (root / "types").mkdir(parents=True)
    (root / "__init__.py").write_text(
        "from .libsy import leaky\n"
    )
    (root / "libsy.py").write_text(
        "import time\n\n\ndef leaky():\n    return time.time()\n"
    )
    (root / "types" / "__init__.py").write_text("")
    (root / "types" / "canonical.py").write_text(
        "from rootpkg import leaky\n"
        "\n"
        "\n"
        "def build():\n"
        "    return leaky()\n"
    )
    p = callgraph.Package(str(root), "rootpkg")
    p.build()
    build = p.functions[("types/canonical.py", "build")]
    assert any(
        s.target == ("libsy.py", "leaky") for s in build.calls
    ), [(s.target, s.external) for s in build.calls]
    vs = taint.taint_violations(p)
    assert [v.rule for v in vs] == ["taint-wallclock"]
    assert "libsy.py:leaky" in vs[0].message


def test_taint_edge_break_suppression(tmp_path):
    root = tmp_path / "brkpkg"
    (root / "types").mkdir(parents=True)
    (root / "__init__.py").write_text("")
    (root / "types" / "__init__.py").write_text("")
    (root / "types" / "canonical.py").write_text(
        "import time\n"
        "\n"
        "\n"
        "def telemetry():\n"
        "    return time.time()\n"
        "\n"
        "\n"
        "def build():\n"
        "    # tmcheck: taint-break — telemetry only, never hashed\n"
        "    telemetry()\n"
        "    return b''\n"
    )
    p = callgraph.Package(str(root), "brkpkg")
    p.build()
    vs = taint.taint_violations(p)
    # the edge is broken, but telemetry() itself is ALSO a sink-root
    # function (it lives in types/canonical.py) — verify the breaking
    # removed build()'s chain by checking chains never pass through
    # build
    assert all("build" not in v.message for v in vs)


def test_taint_source_ok_suppression(tmp_path):
    root = tmp_path / "okpkg"
    (root / "types").mkdir(parents=True)
    (root / "__init__.py").write_text("")
    (root / "types" / "__init__.py").write_text("")
    (root / "types" / "canonical.py").write_text(
        "import time\n"
        "\n"
        "\n"
        "def build():\n"
        "    t = time.time()  # tmcheck: taint-ok — log line only\n"
        "    return b''\n"
    )
    p = callgraph.Package(str(root), "okpkg")
    p.build()
    assert taint.taint_violations(p) == []


def test_taint_urandom_keygen_exemption(tmp_path):
    root = tmp_path / "kgpkg"
    for d in ("types", "crypto"):
        (root / d).mkdir(parents=True)
        (root / d / "__init__.py").write_text("")
    (root / "__init__.py").write_text("")
    (root / "crypto" / "keys.py").write_text(
        "import os\n\n\ndef gen_seed():\n    return os.urandom(32)\n"
    )
    (root / "types" / "canonical.py").write_text(
        "import os\n\n\ndef build():\n    return os.urandom(8)\n"
    )
    p = callgraph.Package(str(root), "kgpkg")
    p.build()
    vs = taint.taint_violations(p)
    assert len(vs) == 1
    assert vs[0].path == "types/canonical.py"
    assert vs[0].rule == "taint-random"


def test_taint_set_iteration_detected(tmp_path):
    root = tmp_path / "setpkg"
    (root / "types").mkdir(parents=True)
    (root / "__init__.py").write_text("")
    (root / "types" / "__init__.py").write_text("")
    (root / "types" / "canonical.py").write_text(
        "def build(items):\n"
        "    s = set(items)\n"
        "    out = b''\n"
        "    for x in s:\n"
        "        out += x\n"
        "    return out\n"
    )
    p = callgraph.Package(str(root), "setpkg")
    p.build()
    vs = taint.taint_violations(p)
    assert [v.rule for v in vs] == ["taint-set-iter"]


# ---------------------------------------------------------------------------
# schema extractor units


def test_extract_fields_order_repeated_conditional():
    src = (
        "from ..encoding.proto import ProtoWriter, FieldReader\n"
        "\n"
        "\n"
        "class Msg:\n"
        "    def to_proto(self):\n"
        "        w = ProtoWriter()\n"
        "        w.int(1, self.a)\n"
        "        for x in self.xs:\n"
        "            w.message(2, x)\n"
        "        if self.b:\n"
        "            w.bytes(3, self.b)\n"
        "        w.sfixed64(4, self.c)\n"
        "        return w.finish()\n"
        "\n"
        "    @classmethod\n"
        "    def from_proto(cls, data):\n"
        "        r = FieldReader(data)\n"
        "        return cls(r.uint(1), r.get_all(2), r.bytes(3),\n"
        "                   r.sfixed64(4))\n"
    )
    msgs, ov = schema.extract_module(src, "types/fixture.py")
    assert ov == []
    m = msgs["types/fixture.py::Msg"]
    got = [(f.tag, f.method, f.repeated, f.conditional) for f in m.fields]
    assert got == [
        (1, "int", False, False),
        (2, "message", True, False),
        (3, "bytes", False, True),
        (4, "sfixed64", False, False),
    ]
    assert m.parsed == {1, 2, 3, 4}
    assert schema.symmetry_violations(msgs) == []


def test_extract_chained_reader_and_iter_fields_comprehension():
    src = (
        "from ..encoding.proto import ProtoWriter, FieldReader, iter_fields\n"
        "\n"
        "\n"
        "class A:\n"
        "    def to_proto(self):\n"
        "        w = ProtoWriter()\n"
        "        w.uint(1, self.h)\n"
        "        return w.finish()\n"
        "\n"
        "    @classmethod\n"
        "    def from_proto(cls, data):\n"
        "        return cls(FieldReader(data).uint(1))\n"
        "\n"
        "\n"
        "class B:\n"
        "    def to_proto(self):\n"
        "        w = ProtoWriter()\n"
        "        for t in self.ts:\n"
        "            w.string(1, t)\n"
        "        return w.finish()\n"
        "\n"
        "    @classmethod\n"
        "    def from_proto(cls, data):\n"
        "        return cls([v for f, _w, v in iter_fields(data) if f == 1])\n"
    )
    msgs, _ = schema.extract_module(src, "types/fixture.py")
    assert msgs["types/fixture.py::A"].parsed == {1}
    assert msgs["types/fixture.py::B"].parsed == {1}
    assert schema.symmetry_violations(msgs) == []


def test_extract_nested_submessage_reader_not_counted():
    src = (
        "from ..encoding.proto import ProtoWriter, FieldReader, iter_fields\n"
        "\n"
        "\n"
        "class Outer:\n"
        "    def to_proto(self):\n"
        "        w = ProtoWriter()\n"
        "        w.string(1, self.name)\n"
        "        for a in self.attrs:\n"
        "            w.message(2, a)\n"
        "        return w.finish()\n"
        "\n"
        "    @classmethod\n"
        "    def from_proto(cls, data):\n"
        "        name = ''\n"
        "        attrs = []\n"
        "        for f, _wt, v in iter_fields(data):\n"
        "            if f == 1:\n"
        "                name = v.decode()\n"
        "            elif f == 2:\n"
        "                r = FieldReader(v)\n"
        "                attrs.append((r.bytes(1), r.bytes(2), r.uint(3)))\n"
        "        return cls(name, attrs)\n"
    )
    msgs, _ = schema.extract_module(src, "types/fixture.py")
    m = msgs["types/fixture.py::Outer"]
    # fields 1,2,3 of the NESTED reader must not leak into Outer
    assert m.parsed == {1, 2}
    assert schema.symmetry_violations(msgs) == []


def test_extract_oneof_dict_tag():
    src = (
        "from ..encoding.proto import ProtoWriter, FieldReader\n"
        "\n"
        "\n"
        "def _enc_key(pk):\n"
        "    w = ProtoWriter()\n"
        "    fieldno = {'a': 1, 'b': 2}[pk.kind]\n"
        "    w.bytes(fieldno, pk.data)\n"
        "    return w.finish()\n"
        "\n"
        "\n"
        "def _dec_key(data):\n"
        "    names = {1: 'a', 2: 'b'}\n"
        "    from ..encoding.proto import iter_fields\n"
        "    for f, _wt, v in iter_fields(data):\n"
        "        if f in names:\n"
        "            return (names[f], v)\n"
        "    raise ValueError('empty')\n"
    )
    msgs, _ = schema.extract_module(src, "abci/codec.py")
    m = msgs["abci/codec.py::key"]
    assert [(f.tag, f.conditional) for f in m.fields] == [
        (1, True),
        (2, True),
    ]
    assert m.parsed == {1, 2}
    assert schema.symmetry_violations(msgs) == []


def test_symmetry_annotation_suppresses():
    src = (
        "from ..encoding.proto import ProtoWriter, FieldReader\n"
        "\n"
        "\n"
        "class M:\n"
        "    def to_proto(self):\n"
        "        w = ProtoWriter()\n"
        "        w.int(1, self.a)\n"
        "        w.int(2, self.derived)\n"
        "        return w.finish()\n"
        "\n"
        "    @classmethod\n"
        "    def from_proto(cls, data):\n"
        "        # tmcheck: unparsed=2 — recomputed from field 1\n"
        "        return cls(FieldReader(data).uint(1))\n"
    )
    msgs, _ = schema.extract_module(src, "types/fixture.py")
    assert schema.symmetry_violations(msgs) == []
    # and without the annotation it IS a violation
    bare = src.replace(
        "        # tmcheck: unparsed=2 — recomputed from field 1\n", ""
    )
    msgs2, _ = schema.extract_module(bare, "types/fixture.py")
    sym = schema.symmetry_violations(msgs2)
    assert len(sym) == 1 and "field 2" in sym[0].message


def test_oneof_branches_exempt_from_order_check():
    src = (
        "from ..encoding.proto import ProtoWriter\n"
        "\n"
        "\n"
        "def encode_ev(ev):\n"
        "    w = ProtoWriter()\n"
        "    if ev.kind == 'b':\n"
        "        w.message(2, ev.body)\n"
        "    else:\n"
        "        w.message(1, ev.body)\n"
        "    return w.finish()\n"
    )
    msgs, ov = schema.extract_module(src, "types/fixture.py")
    assert ov == [], [v.render() for v in ov]


def test_golden_round_trip(tmp_path):
    msgs, _ = schema.extract_package()
    path = str(tmp_path / "golden.json")
    schema.save_golden(msgs, path)
    golden = schema.load_golden(path)
    assert schema.diff_golden(msgs, golden) == []
    # removing a message from the extraction is reported
    key = "types/vote.py::Vote"
    smaller = {k: v for k, v in msgs.items() if k != key}
    dv = schema.diff_golden(smaller, golden)
    assert any(key in v.message for v in dv)


# ---------------------------------------------------------------------------
# CLI contract


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"), *args],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def test_cli_taint_and_schema_sections_exit_zero():
    r = _run_cli("--taint", "--stats")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "[taint]" in r.stdout
    r = _run_cli("--schema", "--stats")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "[schema]" in r.stdout


def test_cli_full_run_includes_tmcheck_sections():
    r = _run_cli("--stats")
    assert r.returncode == 0, r.stdout + r.stderr
    assert (
        "[tmlint+taint+schema+race+live+adv+cost+mc+ct+memo+trace]" in r.stdout
    )
    # the shared-substrate satellite: the full gate parses the package
    # once and says so in the stats line
    assert "parsed once" in r.stdout
    assert "full-gate wall" in r.stdout


def test_cli_schema_update_refuses_filtered_runs():
    r = _run_cli("--schema-update", "--rule", "det-float")
    assert r.returncode == 2 and "full-package" in r.stderr
    r = _run_cli("--schema-update", "tendermint_tpu/types/vote.py")
    assert r.returncode == 2
    r = _run_cli("--schema-update", "--taint")
    assert r.returncode == 2
    # --race would be silently disabled by the update mode (run_race
    # = False) while the command still exited 0 — refuse it too
    r = _run_cli("--schema-update", "--race")
    assert r.returncode == 2 and "full-package" in r.stderr
    # and the golden table was not touched
    assert tmcheck.schema_violations() == []


def test_cli_baseline_update_refuses_schema_section():
    """`--schema --baseline-update` has nothing to update (the golden
    table is the schema baseline) — silently exiting 0 would let an
    operator believe a red gate was accepted."""
    r = _run_cli("--schema", "--baseline-update")
    assert r.returncode == 2 and "schema-update" in r.stderr


def test_cli_list_rules_includes_tmcheck():
    r = _run_cli("--list-rules")
    assert r.returncode == 0
    # the whole catalog from the single source of truth
    for rid, _title in tmcheck.RULES:
        assert rid in r.stdout


# ---------------------------------------------------------------------------
# memo-soundness audit (ISSUE 7: the machine-checked argument for the
# warm-path memos)


def test_memo_audit_clean_on_head(pkg):
    """Every memoized function is cataloged and taint-clean — the gate
    form of "the memo is sound by construction". The audit ships with
    ZERO accepted debt (no baseline file exists)."""
    v = tmcheck.memo_audit_violations(pkg)
    assert not v, "memo audit violations:\n" + "\n".join(
        x.render() for x in v
    )


def test_memo_audit_catalog_covers_warm_path(pkg):
    """The warm-path memos named in ISSUE 7 are all under audit, and
    the consensus-class entries really get the taint scan."""
    report, findings = tmcheck.memoaudit.audit(pkg)
    assert not findings
    by_fn = {row["function"]: row for row in report}
    for fn in (
        "types/commit.py:Commit.sign_bytes_batch",
        "types/commit.py:Commit.vote_sign_bytes",
        "types/commit.py:Commit.block_id_flags_array",
        "types/validator.py:ValidatorSet.pubkeys_bytes",
        "types/vote.py:Vote.sign_bytes",
    ):
        assert fn in by_fn, fn
        assert by_fn[fn]["taint"] == "clean"
    # identity tokens are audited for catalog presence, exempt from
    # the source scan by declared justification
    assert by_fn["types/commit.py:Commit.fingerprint_token"][
        "taint"
    ].startswith("exempt")


def test_memo_audit_run_under_budget(pkg):
    """Like the other analysis sections: <10 s so the full gate stays
    cheap for every tier-1 invocation (measured well under 1 s on the
    shared package build)."""
    t0 = time.monotonic()
    tmcheck.memo_audit_violations(pkg)
    elapsed = time.monotonic() - t0
    assert elapsed < 10.0, f"memo audit took {elapsed:.1f}s"


def test_seeded_taint_in_memoized_function_fails_audit(pkg_copy):
    """A wall-clock read injected into a helper called from a cataloged
    memoized function (Commit._sign_template -> VoteSignTemplate's
    module) must be reported as memo-taint with the witness chain."""
    from tendermint_tpu.analysis.tmcheck import memoaudit

    ts = pkg_copy / "types" / "timestamp.py"
    src = ts.read_text()
    assert "def encode_timestamp" in src
    src = src.replace(
        "def encode_timestamp(ns: int) -> bytes:",
        "def _memo_skew():\n"
        "    import time\n"
        "    return time.time()\n\n\n"
        "def encode_timestamp(ns: int) -> bytes:\n"
        "    _memo_skew()",
        1,
    )
    ts.write_text(src)
    p = _analyze_copy(pkg_copy)
    v = memoaudit.memo_audit_violations(p)
    assert any(
        x.rule == "memo-taint" and "time.time" in x.message for x in v
    ), "\n".join(x.render() for x in v)


def test_seeded_uncataloged_memoizer_fails_audit(pkg_copy):
    """A new function that lazily caches into a memo-named attribute
    without a CATALOG entry must fail the completeness check."""
    from tendermint_tpu.analysis.tmcheck import memoaudit

    commit = pkg_copy / "types" / "commit.py"
    src = commit.read_text()
    src = src.replace(
        "    def size(self) -> int:",
        "    def rogue_cached(self):\n"
        "        if self._rogue_memo is None:\n"
        "            self._rogue_memo = 1\n"
        "        return self._rogue_memo\n\n"
        "    def size(self) -> int:",
        1,
    )
    commit.write_text(src)
    p = _analyze_copy(pkg_copy)
    v = memoaudit.memo_audit_violations(p)
    assert any(
        x.rule == "memo-uncataloged" and "rogue_cached" in x.message
        for x in v
    ), "\n".join(x.render() for x in v)


def test_catalog_rename_detected(pkg):
    """A cataloged function that no longer exists (rename/move) is a
    violation — the audit cannot silently shrink."""
    from tendermint_tpu.analysis.tmcheck import memoaudit

    entry = memoaudit.MemoEntry(
        "types/commit.py", "Commit.gone_function", "consensus", "test"
    )
    memoaudit.CATALOG.append(entry)
    try:
        v = tmcheck.memo_audit_violations(pkg)
    finally:
        memoaudit.CATALOG.remove(entry)
    assert any(
        x.rule == "memo-uncataloged" and "gone_function" in x.message
        for x in v
    )


def test_cli_memo_audit_prints_listing_and_exits_zero():
    r = _run_cli("--memo-audit")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "memoized-function audit" in r.stdout
    assert "Commit.sign_bytes_batch" in r.stdout
    assert "taint=clean" in r.stdout


def test_cli_memo_audit_update_mode_refusals():
    r = _run_cli("--baseline-update", "--memo-audit")
    assert r.returncode == 2 and "memo audit has no baseline" in r.stderr
    r = _run_cli("--schema-update", "--memo-audit")
    assert r.returncode == 2 and "full-package" in r.stderr
