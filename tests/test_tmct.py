"""tmct: the secret-flow / constant-time gate over the crypto plane.

Five jobs, mirroring the tmsafe harness: (1) run tmct over the whole
package on every tier-1 invocation, failing on anything beyond the
(empty) ct baseline — the static form of "no secret modulates trace
shape or reaches rendered/shared state"; (2) prove the gate is not
vacuous by seeding violations into a COPY of the REAL package (strip a
reviewed `# tmct: ct-ok` rationale, strip the FilePVKey repr=False
fix, plant a module-global nonce memo in the secp256k1 sign path) and
watching the exact rule turn red; (3) unit-test the two-level
CLEAN < CARRIER < SECRET engine against tiny synthetic crypto-plane
packages — every rule red on its minimal trigger, every
declassification boundary green on its twin; (4) pin the head
suppression catalog (the reviewed accepted-by-rationale sites) and the
true-positive fixes this PR's own first run surfaced; (5) the CLI exit
contract and the update-refusal matrix for --ct.
"""

import importlib.util
import json
import os
import shutil
import subprocess
import sys
import time

import pytest

from tendermint_tpu.analysis import tmct
from tendermint_tpu.analysis.tmcheck.callgraph import build_package
from tendermint_tpu.analysis.tmct.secretflow import (
    CARRIER,
    CLEAN,
    SECRET,
    SecretEngine,
)
from tendermint_tpu.analysis.tmct.sources import derive_catalog
from tendermint_tpu.analysis.tmlint import (
    Violation,
    load_baseline,
    new_violations,
    save_baseline,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG_ROOT = os.path.join(REPO, "tendermint_tpu")


# ---------------------------------------------------------------------------
# THE gate: whole package against the checked-in (empty) baseline


@pytest.fixture(scope="module")
def head_pkg():
    return build_package()


@pytest.fixture(scope="module")
def head_report(head_pkg):
    t0 = time.monotonic()
    rep = tmct.analyze(head_pkg)
    rep.elapsed_s = time.monotonic() - t0
    return rep


def test_package_clean_against_baseline(head_report):
    """tmct over the whole package; anything beyond
    tmct/ct_baseline.json fails tier-1 — fix it or suppress it in-file
    with a justified `# tmct: ct-ok — why` (docs/static_analysis.md);
    re-baselining is NOT the sanctioned path for this section."""
    new = new_violations(
        head_report.violations, load_baseline(tmct.CT_BASELINE_PATH)
    )
    assert not new, "new tmct violations:\n" + "\n".join(
        v.render() for v in new
    )


def test_ct_baseline_is_checked_in_and_empty():
    """The crypto plane starts clean and stays clean: every first-run
    true positive was FIXED in-tree (NodeKey/FilePVKey repr=False,
    PrivKey.__repr__ redaction), every reviewed residual suppressed
    in-file with a written reason — nothing was grandfathered, so the
    counted baseline must stay empty forever."""
    assert os.path.exists(tmct.CT_BASELINE_PATH)
    with open(tmct.CT_BASELINE_PATH) as f:
        data = json.load(f)
    assert data["entries"] == {}


def test_full_package_run_under_budget(head_report):
    """Runtime budget: the ct pass runs on every tier-1 invocation and
    must stay under 10 s for the whole package (measured ~1.5 s for
    the three-pass polymorphic engine on ~3000 functions). Times the
    module fixture's run rather than paying a second analyze."""
    assert head_report.elapsed_s < 10.0, (
        f"tmct full-package run took {head_report.elapsed_s:.1f}s"
    )


def test_head_suppression_catalog_is_exactly_the_reviewed_sites(
    head_report,
):
    """The head catalog of accepted-by-rationale sites, by (rule,
    file): rejection sampling + published-signature zero tests in the
    secp256k1 sign path, native verify verdict compares (sr25519 /
    ed25519 batch / ristretto basemul FFI status), gen_validator's
    documented key-JSON emission, and the model checker's deterministic
    fixture keygen cache. Every other first-run finding got a real fix
    (field(repr=False) ×2, PrivKey.__repr__ redaction), not a comment.
    A new entry here means someone added a `# tmct: ct-ok — ...` —
    review the rationale, then extend this pin deliberately."""
    by_site = {(rule, path) for rule, path, _ln in head_report.suppressed}
    assert by_site == {
        ("ct-leak-lifetime", "analysis/tmmc/harness.py"),
        ("ct-leak-telemetry", "cmd/commands.py"),
        ("ct-secret-compare", "crypto/ed25519.py"),
        ("ct-secret-branch", "crypto/secp256k1.py"),
        ("ct-secret-compare", "crypto/secp256k1.py"),
        ("ct-secret-compare", "crypto/sr25519.py"),
        ("ct-secret-compare", "native/__init__.py"),
    }
    assert len(head_report.suppressed) == 11


# ---------------------------------------------------------------------------
# the machine-derived source catalog at head


def test_privkey_closure_is_the_four_key_classes(head_report):
    """The source catalog derives the PrivKey hierarchy, never a hand
    list — a fifth key class joins the gate the moment it subclasses
    PrivKey."""
    assert head_report.catalog.privkey_class_names == {
        "PrivKey",
        "PrivKeyEd25519",
        "PrivKeySr25519",
        "PrivKeySecp256k1",
    }
    assert "PubKey" in head_report.catalog.pubkey_class_names
    assert "PubKeySecp256k1" in head_report.catalog.pubkey_class_names


def test_secret_attr_carriers_include_the_key_records(head_report):
    """PrivKey-annotated fields (FilePVKey.priv_key, NodeKey.priv_key)
    are carriers package-wide, and the raw-material union covers the
    concrete classes' scalar/seed attrs."""
    assert "priv_key" in head_report.catalog.secret_attr_names
    raw = head_report.catalog.raw_attr_union()
    assert "_secret" in raw  # secp256k1 seed bytes + sr25519
    assert "_d" in raw       # secp256k1 scalar


def test_head_has_no_dataclass_repr_leaks(head_report):
    """The two first-run repr leaks (NodeKey.priv_key,
    FilePVKey.priv_key) are fixed with field(repr=False); the catalog
    scan must find zero remaining."""
    assert head_report.catalog.repr_leaks == []


def test_findings_all_zero_at_head(head_report):
    for rid, _ in tmct.RULES:
        assert head_report.stats[f"findings[{rid}]"] == 0
    assert head_report.stats["privkey_classes"] == 4
    assert head_report.stats["region"] > 2000  # whole-program, not crypto/-only


# ---------------------------------------------------------------------------
# seeded violations against a copy of the REAL package


@pytest.fixture()
def pkg_copy(tmp_path):
    dst = tmp_path / "tendermint_tpu"
    shutil.copytree(
        PKG_ROOT, dst, ignore=shutil.ignore_patterns("__pycache__")
    )
    return dst


def _analyze_copy(dst):
    from tendermint_tpu.analysis.tmcheck import callgraph

    p = callgraph.Package(str(dst), "tendermint_tpu")
    p.build()
    return tmct.analyze(p)


def test_seeded_stripped_rationale_turns_branch_red(pkg_copy):
    """Acceptance: the rejection-sampling suppression in _rfc6979_k is
    load-bearing — deleting the reviewed rationale re-opens the real
    first-run ct-secret-branch finding on the nonce-range test."""
    mod = pkg_copy / "crypto" / "secp256k1.py"
    src = mod.read_text()
    needle = (
        "  # tmct: ct-ok — rejection sampling per RFC 6979 §3.2: the "
        "retry event has probability ~2^-128 independent of long-term "
        "key bits"
    )
    assert needle in src
    mod.write_text(src.replace(needle, ""))
    rep = _analyze_copy(pkg_copy)
    hits = [
        v for v in rep.violations
        if v.rule == "ct-secret-branch" and v.path == "crypto/secp256k1.py"
    ]
    assert hits, "unsuppressed nonce-range branch not flagged"
    assert any("_ORDER" in v.source for v in hits)


def test_seeded_stripped_sign_zero_test_turns_compare_red(pkg_copy):
    """Acceptance: the r/s zero-test rationale in sign() is
    load-bearing — the engine still sees r and s as nonce-derived
    secrets at that point (publication happens at return)."""
    mod = pkg_copy / "crypto" / "secp256k1.py"
    src = mod.read_text()
    needle = (
        "  # tmct: ct-ok — r and s ARE the published signature; the "
        "zero test gates output validity (probability ~2^-256) and "
        "reveals nothing beyond the signature itself"
    )
    assert needle in src
    mod.write_text(src.replace(needle, ""))
    rep = _analyze_copy(pkg_copy)
    hits = [
        v for v in rep.violations
        if v.rule == "ct-secret-compare"
        and v.path == "crypto/secp256k1.py"
    ]
    assert hits, "unsuppressed r/s zero test not flagged"


def test_seeded_dropped_repr_false_turns_telemetry_red(pkg_copy):
    """Acceptance: stripping field(repr=False) from FilePVKey.priv_key
    re-opens the real first-run finding — the generated __repr__ would
    embed the key object in every log/crash rendering."""
    mod = pkg_copy / "privval" / "file.py"
    src = mod.read_text()
    needle = "priv_key: PrivKey = field(repr=False)"
    assert needle in src
    mod.write_text(src.replace(needle, "priv_key: PrivKey = None"))
    rep = _analyze_copy(pkg_copy)
    hits = [
        v for v in rep.violations
        if v.rule == "ct-leak-telemetry" and v.path == "privval/file.py"
    ]
    assert hits, "dropped repr=False not flagged"
    assert "repr" in hits[0].message


def test_seeded_nonce_memo_turns_lifetime_red(pkg_copy):
    """ISSUE satellite: the PR-9 shared-container lifetime rule catches
    a planted secret-keyed cache — memoizing the RFC 6979 nonce in a
    module global (the classic 'cache the expensive scalar' mistake
    that turns a local secret into process-lifetime state)."""
    mod = pkg_copy / "crypto" / "secp256k1.py"
    src = mod.read_text()
    needle = "def _rfc6979_k(secret: bytes, h1: bytes) -> int:"
    assert needle in src
    src = src.replace(needle, "_K_MEMO: dict = {}\n\n\n" + needle)
    needle = "            x, _y = _ct_to_affine(_ct_mul_base(k))"
    assert needle in src
    mod.write_text(
        src.replace(needle, "            _K_MEMO[h1] = k\n" + needle)
    )
    rep = _analyze_copy(pkg_copy)
    hits = [
        v for v in rep.violations
        if v.rule == "ct-leak-lifetime" and v.path == "crypto/secp256k1.py"
    ]
    assert hits, "planted module-global nonce memo not flagged"
    assert "_K_MEMO" in hits[0].message


# ---------------------------------------------------------------------------
# engine unit tests: tiny synthetic crypto-plane packages


def _mini_pkg(tmp_path, source: str, path: str = "crypto/mod.py"):
    d = tmp_path / "mini"
    full = d / path
    full.parent.mkdir(parents=True, exist_ok=True)
    full.write_text(source)
    return build_package(str(d))


_KEY_PREAMBLE = (
    "class PrivKey:\n"
    "    pass\n"
    "class PubKey:\n"
    "    pass\n"
    "class PrivKeyMini(PrivKey):\n"
    "    def __init__(self, seed):\n"
    "        self._key = seed\n"
    "        self._pub = b'public-bytes'\n"
)


def _rules(rep):
    return sorted(v.rule for v in rep.violations)


def test_branch_on_secret_flagged_public_twin_clean(tmp_path):
    rep = tmct.analyze(_mini_pkg(tmp_path, _KEY_PREAMBLE + (
        "    def bad(self):\n"
        "        if self._key[0]:\n"
        "            return 1\n"
        "        return 0\n"
        "    def ok(self):\n"
        "        if self._pub[0]:\n"
        "            return 1\n"
        "        return 0\n"
    )))
    assert _rules(rep) == ["ct-secret-branch"]
    assert rep.violations[0].source == "if self._key[0]:"


def test_range_bound_flagged_byte_iteration_clean(tmp_path):
    """`range(secret)` is a secret trip count; `for b in key` iterates
    the public length — only the bound is the finding."""
    rep = tmct.analyze(_mini_pkg(tmp_path, _KEY_PREAMBLE + (
        "    def bad(self):\n"
        "        acc = 0\n"
        "        for i in range(self._key[0]):\n"
        "            acc += i\n"
        "        return acc\n"
        "    def ok(self):\n"
        "        acc = 0\n"
        "        for b in self._key:\n"
        "            acc += 1\n"
        "        return acc\n"
    )))
    assert _rules(rep) == ["ct-secret-branch"]
    assert "range" in rep.violations[0].source


def test_eq_on_secret_flagged_bytes_eq_clean(tmp_path):
    rep = tmct.analyze(_mini_pkg(tmp_path, _KEY_PREAMBLE + (
        "    def bad(self, other):\n"
        "        return self._key == other\n"
        "    def ok(self, other):\n"
        "        return bytes_eq(self._key, other)\n"
    )))
    assert _rules(rep) == ["ct-secret-compare"]
    assert "bytes_eq" in rep.violations[0].message


def test_two_arg_pow_flagged_three_arg_clean(tmp_path):
    rep = tmct.analyze(_mini_pkg(tmp_path, _KEY_PREAMBLE + (
        "    def bad(self):\n"
        "        return pow(3, self._key)\n"
        "    def ok(self):\n"
        "        return pow(3, self._key, 97)\n"
    )))
    assert _rules(rep) == ["ct-vartime-pow"]


def test_table_index_by_secret_flagged(tmp_path):
    rep = tmct.analyze(_mini_pkg(tmp_path, (
        "TABLE = (0, 1, 2, 3)\n"
    ) + _KEY_PREAMBLE + (
        "    def bad(self):\n"
        "        return TABLE[self._key[0] & 3]\n"
        "    def ok(self, i):\n"
        "        return TABLE[i & 3]\n"
    )))
    assert _rules(rep) == ["ct-secret-index"]


def test_telemetry_sinks_fstring_exception_print(tmp_path):
    rep = tmct.analyze(_mini_pkg(tmp_path, _KEY_PREAMBLE + (
        "    def bad_f(self):\n"
        "        return f'key={self._key}'\n"
        "    def bad_exc(self):\n"
        "        raise ValueError(self._key)\n"
        "    def bad_print(self):\n"
        "        print(self._key)\n"
        "    def ok(self):\n"
        "        return f'key={len(self._key)} bytes'\n"
    )))
    assert _rules(rep) == ["ct-leak-telemetry"] * 3


def test_lifetime_sinks_module_global_and_container(tmp_path):
    rep = tmct.analyze(_mini_pkg(tmp_path, (
        "_CACHE = {}\n"
        "_RING = []\n"
    ) + _KEY_PREAMBLE + (
        "    def bad_store(self):\n"
        "        _CACHE[b'k'] = self._key\n"
        "    def bad_push(self):\n"
        "        _RING.append(self._key)\n"
        "    def ok_local(self):\n"
        "        local = {}\n"
        "        local[b'k'] = self._key\n"
        "        return local\n"
    )))
    assert _rules(rep) == ["ct-leak-lifetime"] * 2


def test_carrier_object_fires_lifetime_but_not_timing(tmp_path):
    """The two-level lattice: a PrivKey *object* parked in a module
    global is a lifetime leak, but branching on it (presence checks,
    dispatch) is not a timing finding — only raw material is."""
    rep = tmct.analyze(_mini_pkg(tmp_path, (
        "_KEYS = {}\n"
    ) + _KEY_PREAMBLE + (
        "def use(pk: PrivKeyMini, name):\n"
        "    if pk is None:\n"
        "        return None\n"
        "    if name:\n"
        "        _KEYS[name] = pk\n"
        "    return pk\n"
    )))
    assert _rules(rep) == ["ct-leak-lifetime"]


def test_raw_attr_read_off_carrier_reenters_secret(tmp_path):
    rep = tmct.analyze(_mini_pkg(tmp_path, _KEY_PREAMBLE + (
        "def bad(pk: PrivKeyMini):\n"
        "    if pk._key[0]:\n"
        "        return 1\n"
        "    return 0\n"
    )))
    assert _rules(rep) == ["ct-secret-branch"]


def test_declassified_methods_are_public(tmp_path):
    """sign/pub_key/address results are published output by design —
    branching on them is not a finding (their internals still are
    analyzed, as the other tests prove)."""
    rep = tmct.analyze(_mini_pkg(tmp_path, _KEY_PREAMBLE + (
        "    def sign(self, msg):\n"
        "        return bytes(32)\n"
        "    def pub_key(self):\n"
        "        return PubKey()\n"
        "def ok(pk: PrivKeyMini, msg):\n"
        "    sig = pk.sign(msg)\n"
        "    if sig[0]:\n"
        "        return sig\n"
        "    return pk.pub_key()\n"
    )))
    assert rep.violations == []


def test_urandom_births_secret_only_in_crypto_plane(tmp_path):
    src = (
        "import os\n"
        "def gen():\n"
        "    nonce = os.urandom(32)\n"
        "    if nonce[0] & 1:\n"
        "        return 1\n"
        "    return 0\n"
    )
    rep = tmct.analyze(_mini_pkg(tmp_path, src, "crypto/mod.py"))
    assert _rules(rep) == ["ct-secret-branch"]
    rep = tmct.analyze(_mini_pkg(tmp_path / "b", src, "rpc/mod.py"))
    assert rep.violations == []


def test_polymorphic_helper_summary_no_public_poisoning(tmp_path):
    """The caller-sensitivity regression this PR's own development
    surfaced: shared arithmetic called with secrets from the sign path
    must NOT make its return secret for public callers (precompute
    tables, verify paths) — the ret_base/param_dep summary split."""
    rep = tmct.analyze(_mini_pkg(tmp_path, _KEY_PREAMBLE + (
        "def dbl(x):\n"
        "    return x + x\n"
        "class Signer(PrivKeyMini):\n"
        "    def bad(self):\n"
        "        t = dbl(self._key[0])\n"
        "        if t & 1:\n"
        "            return 1\n"
        "        return 0\n"
        "def public_precompute():\n"
        "    n = dbl(3)\n"
        "    if n > 4:\n"
        "        return 1\n"
        "    return 0\n"
    )))
    assert [(v.rule, v.source) for v in rep.violations] == [
        ("ct-secret-branch", "if t & 1:")
    ]


def test_internal_secret_birth_propagates_to_caller(tmp_path):
    """ret_base: a function that mints a secret internally (urandom in
    the crypto plane) taints every caller even with clean args."""
    rep = tmct.analyze(_mini_pkg(tmp_path, (
        "import os\n"
        "def fresh_scalar():\n"
        "    return os.urandom(32)\n"
        "def caller():\n"
        "    k = fresh_scalar()\n"
        "    if k[0]:\n"
        "        return 1\n"
        "    return 0\n"
    )))
    assert _rules(rep) == ["ct-secret-branch"]


def test_structural_reads_are_clean(tmp_path):
    """len() / type() / isinstance() / `is None` read structure, not
    content — the public-length contract."""
    rep = tmct.analyze(_mini_pkg(tmp_path, _KEY_PREAMBLE + (
        "    def ok(self):\n"
        "        if self._key is None:\n"
        "            return 0\n"
        "        if len(self._key) != 32:\n"
        "            return 1\n"
        "        if isinstance(self._key, bytearray):\n"
        "            return 2\n"
        "        return 3\n"
    )))
    assert rep.violations == []


def test_suppression_requires_reason(tmp_path):
    """`# tmct: ct-ok — why` suppresses; a bare `# tmct: ct-ok` does
    not parse — every sanctioned site is a written, reviewable claim."""
    rep = tmct.analyze(_mini_pkg(tmp_path, _KEY_PREAMBLE + (
        "    def ok(self):\n"
        "        if self._key[0]:  # tmct: ct-ok — fixture: reviewed reason\n"
        "            return 1\n"
        "        return 0\n"
        "    def still_bad(self):\n"
        "        if self._key[0]:  # tmct: ct-ok\n"
        "            return 1\n"
        "        return 0\n"
    )))
    assert _rules(rep) == ["ct-secret-branch"]
    assert rep.stats["suppressed"] == 1


def test_suppression_comment_block_above(tmp_path):
    rep = tmct.analyze(_mini_pkg(tmp_path, _KEY_PREAMBLE + (
        "    def ok(self):\n"
        "        # tmct: ct-ok — fixture: rejection sampling twin,\n"
        "        # rationale spanning the block above the code line\n"
        "        if self._key[0]:\n"
        "            return 1\n"
        "        return 0\n"
    )))
    assert rep.violations == []
    assert rep.stats["suppressed"] == 1


def test_dataclass_repr_leak_and_repr_false_twin(tmp_path):
    rep = tmct.analyze(_mini_pkg(tmp_path, (
        "from dataclasses import dataclass, field\n"
        "class PrivKey:\n"
        "    pass\n"
        "@dataclass\n"
        "class BadRec:\n"
        "    priv_key: PrivKey\n"
        "@dataclass\n"
        "class OkRec:\n"
        "    priv_key: PrivKey = field(repr=False)\n"
    )))
    assert _rules(rep) == ["ct-leak-telemetry"]
    assert "BadRec" in rep.violations[0].message


def test_witness_chain_names_the_source_function(tmp_path):
    """Findings carry an interprocedural witness so the operator can
    see how the secret reached the sink."""
    rep = tmct.analyze(_mini_pkg(tmp_path, _KEY_PREAMBLE + (
        "    def bad(self):\n"
        "        if self._key[0]:\n"
        "            return 1\n"
        "        return 0\n"
    )))
    assert len(rep.violations) == 1
    assert "witness" in rep.violations[0].message


def test_lattice_constants():
    assert CLEAN < CARRIER < SECRET


def test_engine_seeds_init_params_secret(tmp_path):
    pkg = _mini_pkg(tmp_path, _KEY_PREAMBLE)
    cat = derive_catalog(pkg)
    assert cat.seed_params == {
        ("crypto/mod.py", "PrivKeyMini.__init__"): {"seed"}
    }
    eng = SecretEngine(pkg, cat)
    eng.run()
    st = eng.states[("crypto/mod.py", "PrivKeyMini.__init__")]
    assert st.param_taint["seed"] == SECRET


def test_baseline_round_trip(tmp_path):
    """save_baseline over synthetic findings -> zero new; a duplicated
    offending line overflows its counted fingerprint."""
    rep = tmct.analyze(_mini_pkg(tmp_path, _KEY_PREAMBLE + (
        "    def bad(self):\n"
        "        if self._key[0]:\n"
        "            return 1\n"
        "        return 0\n"
    )))
    assert rep.violations
    path = tmp_path / "ct_baseline.json"
    save_baseline(rep.violations, str(path), note=tmct.CT_BASELINE_NOTE)
    assert new_violations(rep.violations, load_baseline(str(path))) == []
    extra = rep.violations + [rep.violations[0]]
    over = new_violations(extra, load_baseline(str(path)))
    assert over and "baseline allows" in over[0].message


# ---------------------------------------------------------------------------
# CLI contract


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"), *args],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def _load_lint_module():
    spec = importlib.util.spec_from_file_location(
        "lint_cli_ct", os.path.join(REPO, "scripts", "lint.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow
def test_cli_ct_clean_exit_zero():
    r = _run_cli("--ct", "--stats")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "[ct]" in r.stdout
    assert "tmct gate:" in r.stdout


def test_cli_ct_seeded_violation_exit_one(monkeypatch):
    """The exit contract end to end: a ct finding beyond the (empty)
    baseline exits 1 through the real main()."""
    lint = _load_lint_module()
    seeded = tmct.CtReport()
    seeded.violations = [
        Violation(
            rule="ct-secret-branch",
            path="crypto/fake.py",
            line=1,
            col=0,
            message="seeded secret-dependent branch",
            source="if key[0]:",
        )
    ]
    monkeypatch.setattr(lint.tmct, "analyze", lambda pkg=None: seeded)
    monkeypatch.setattr(
        lint.tmcheck, "build_package", lambda root=None: None
    )
    assert lint.main(["--ct"]) == 1


def test_cli_ct_baseline_update_refuses_filtered_runs():
    r = _run_cli("--ct", "--baseline-update", "--rule", "det-float")
    assert r.returncode == 2
    assert "full-package" in r.stderr


def test_cli_update_modes_refuse_ct():
    """--schema-update / --signatures-update / --cost-update combined
    with --ct would silently skip the ct gate while exiting 0 — the
    laundering class every section must refuse."""
    r = _run_cli("--schema-update", "--ct")
    assert r.returncode == 2 and "full-package" in r.stderr
    r = _run_cli("--signatures-update", "--ct")
    assert r.returncode == 2 and "full-package" in r.stderr
    r = _run_cli("--cost-update", "--ct")
    assert r.returncode == 2 and "full-package" in r.stderr


def test_cli_list_rules_includes_ct():
    r = _run_cli("--list-rules")
    assert r.returncode == 0
    for rid, _ in tmct.RULES:
        assert rid in r.stdout
