"""Concurrency-schedule exploration — the framework's analog of the
reference's `go test -race` CI runs (SURVEY §5b).

The consensus core is a single-writer loop fed by queues, so the race
surface is ORDERING: which peer inputs land first, interleaved how,
duplicated or delayed. These tests drive one real ConsensusState
through many seeded random schedules of the same logical inputs and
assert the outcome is schedule-independent — the commit safety
property the single-writer design exists to protect. A regression that
makes a transition order-dependent (e.g. a lock update racing a vote
add) shows up as one seed committing a different block or deadlocking.
"""

import asyncio

from tendermint_tpu.libs.schedulefuzz import Schedule, explore
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.canonical import PRECOMMIT_TYPE, PREVOTE_TYPE

from tests.test_consensus_lock import LockHarness, wait_for


def run(coro):
    return asyncio.run(coro)


def test_commit_is_schedule_independent():
    """Height 1 with a full vote set delivered in 8 different seeded
    orders (votes shuffled, some duplicated, prevotes/precommits
    interleaved): every schedule must commit cs1's proposal B1."""

    async def scenario(sched: Schedule) -> bytes:
        h = LockHarness(seed_base=200)
        await h.cs.start()
        try:
            prevote = await h.wait_own_vote(PREVOTE_TYPE, 0)
            b1 = prevote.block_id
            # the full honest-stub schedule: every stub prevotes and
            # precommits B1. Each vote is signed ONCE; duplicated plan
            # entries redeliver the identical signed vote object —
            # byte-for-byte gossip redelivery, the idempotent-dup path
            plan = []
            for priv in h.stubs:
                plan.append(await h.make_vote(priv, PREVOTE_TYPE, 0, b1))
                plan.append(
                    await h.make_vote(priv, PRECOMMIT_TYPE, 0, b1)
                )
            for vote in sched.with_dups(sched.shuffled(plan), 4):
                h.send_vote(vote)
                await sched.yield_point()
            await wait_for(
                lambda: h.node.block_store.height() >= 1,
                timeout=30.0,
                what=f"commit under schedule {sched.seed}",
            )
            return h.node.block_store.load_block(1).hash()
        finally:
            await h.cs.stop()

    run(explore(scenario, schedules=8, base_seed=0))


def test_lock_outcome_schedule_independent_across_rounds():
    """The round-1 relock cell under shuffled delivery: round-0 lock,
    nil precommits, then round-1 POL + precommits for B1 — delivered in
    seeded random orders with duplicates. Every schedule must end with
    B1 committed at round >= 1 (timing may let a schedule slip an extra
    round; safety — same block — is what ordering must never change)."""

    async def scenario(sched: Schedule) -> bytes:
        h = LockHarness(seed_base=210)
        await h.cs.start()
        try:
            prevote = await h.lock_b1_round0()
            b1 = prevote.block_id
            await h.push_to_round1_nil_precommits()
            plan = []
            for priv in h.stubs:
                plan.append(await h.make_vote(priv, PREVOTE_TYPE, 1, b1))
                plan.append(
                    await h.make_vote(priv, PRECOMMIT_TYPE, 1, b1)
                )
            for vote in sched.with_dups(sched.shuffled(plan), 3):
                h.send_vote(vote)
                await sched.yield_point()
            await wait_for(
                lambda: h.node.block_store.height() >= 1,
                timeout=30.0,
                what=f"relock commit under schedule {sched.seed}",
            )
            block = h.node.block_store.load_block(1)
            assert block.hash() == b1.hash
            seen = h.node.block_store.load_seen_commit()
            assert seen.round >= 1
            return block.hash()
        finally:
            await h.cs.stop()

    run(explore(scenario, schedules=6, base_seed=0))


def test_future_round_votes_before_current_round_votes():
    """Adversarial ordering: round-1 votes arrive BEFORE any round-0
    votes (gossip reordering across rounds). The state machine must
    neither crash nor skip committing once the round-0 votes land."""

    async def go():
        h = LockHarness(seed_base=220)
        await h.cs.start()
        try:
            prevote = await h.wait_own_vote(PREVOTE_TYPE, 0)
            b1 = prevote.block_id
            # future-round nil prevotes first (tracked, round not yet
            # entered by cs1 beyond 2/3-any future-round pull)
            await h.stub_votes(PREVOTE_TYPE, 1, BlockID(), stubs=h.stubs[:1])
            # now the round-0 votes, same-block
            await h.stub_votes(PREVOTE_TYPE, 0, b1, stubs=h.stubs[:2])
            await h.stub_votes(PRECOMMIT_TYPE, 0, b1, stubs=h.stubs[:2])
            await wait_for(
                lambda: h.node.block_store.height() >= 1,
                timeout=30.0,
                what="commit despite future-round noise",
            )
            assert h.node.block_store.load_block(1).hash() == b1.hash
        finally:
            await h.cs.stop()

    run(go())


# ---- beyond consensus -----------------------------------------------
# mempool update/reap/recheck, statesync chunk ingestion, peer-manager
# lifecycles, vote-set ingestion, pubsub fan-out — all through the
# same seeded explorer. Every failure prints the reproducing seed.


def test_mempool_update_reap_schedule_independent():
    """check_tx / update(commit) / reap interleaved in seeded orders:
    the final pool content must always be exactly the un-committed
    txs — no schedule may let a committed tx survive or re-enter."""
    from tendermint_tpu.abci import types as abci
    from tendermint_tpu.abci.client import LocalClient
    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.config import MempoolConfig
    from tendermint_tpu.mempool.mempool import TxMempool

    committed = [b"c%d=1" % i for i in range(4)]
    others = [b"o%d=1" % i for i in range(8)]

    async def scenario(sched):
        mp = TxMempool(LocalClient(KVStoreApplication()), MempoolConfig())

        async def check(tx):
            try:
                await mp.check_tx(tx)
            except Exception:
                pass
            await sched.yield_point()

        async def do_update():
            await mp.update(
                1,
                committed,
                [abci.ResponseDeliverTx() for _ in committed],
            )
            await sched.yield_point()

        async def do_reap():
            mp.reap_max_txs(5)
            await sched.yield_point()

        # per-source FIFO: the commit sequence checks its txs before
        # the update that commits them (as the chain would), the other
        # txs and reaps land wherever the schedule puts them
        plan = sched.interleave(
            [lambda tx=tx: check(tx) for tx in committed] + [do_update],
            [lambda tx=tx: check(tx) for tx in others],
            [do_reap, do_reap],
        )
        for thunk in plan:
            await thunk()
        return tuple(sorted(mp.reap_max_txs(-1)))

    final = run(explore(scenario, schedules=8, base_seed=300))
    assert final == tuple(sorted(others))


def test_mempool_recheck_schedule_independent():
    """Same shape with recheck enabled and a second commit: rechecks
    triggered by each update must not eat, duplicate, or resurrect
    txs regardless of interleaving."""
    from tendermint_tpu.abci import types as abci
    from tendermint_tpu.abci.client import LocalClient
    from tendermint_tpu.abci.kvstore import KVStoreApplication
    from tendermint_tpu.config import MempoolConfig
    from tendermint_tpu.mempool.mempool import TxMempool

    batch1 = [b"r%d=1" % i for i in range(3)]
    batch2 = [b"s%d=1" % i for i in range(3)]
    keep = [b"k%d=1" % i for i in range(5)]

    async def scenario(sched):
        cfg = MempoolConfig()
        cfg.recheck = True
        mp = TxMempool(LocalClient(KVStoreApplication()), cfg)

        async def check(tx):
            try:
                await mp.check_tx(tx)
            except Exception:
                pass
            await sched.yield_point()

        async def update(height, txs):
            await mp.update(
                height, txs, [abci.ResponseDeliverTx() for _ in txs]
            )
            await sched.yield_point()

        plan = sched.interleave(
            [lambda tx=tx: check(tx) for tx in batch1]
            + [lambda: update(1, batch1)],
            [lambda tx=tx: check(tx) for tx in batch2]
            + [lambda: update(2, batch2)],
            [lambda tx=tx: check(tx) for tx in keep],
        )
        for thunk in plan:
            await thunk()
        return tuple(sorted(mp.reap_max_txs(-1)))

    final = run(explore(scenario, schedules=8, base_seed=310))
    assert final == tuple(sorted(keep))


def test_statesync_chunk_ingestion_schedule_independent():
    """Chunks arriving in any order, with duplicates and one hole
    filled by refetch: the app must receive indices strictly in order,
    each exactly once."""
    from tendermint_tpu.abci import types as abci
    from tendermint_tpu.statesync.chunks import ChunkQueue
    from tendermint_tpu.statesync.reactor import _Snapshot

    from tests.test_statesync import _bare_reactor

    async def scenario(sched):
        reactor = _bare_reactor()
        snapshot = _Snapshot(
            height=7, format=1, chunks=8, hash=b"h", metadata=b"",
            peers={"p"},
        )

        async def fake_fetch(snap, queue, indexes=None):
            for i in (
                indexes if indexes is not None else range(snap.chunks)
            ):
                queue.put(i, b"chunk-%d" % i, sender="p")

        reactor._fetch_chunks = fake_fetch
        applied = []

        class App:
            async def apply_snapshot_chunk(self, req):
                applied.append(req.index)
                return abci.ResponseApplySnapshotChunk(
                    result=abci.APPLY_CHUNK_ACCEPT
                )

        reactor.app = App()
        queue = ChunkQueue(8)
        try:
            # arrival: shuffled, duplicated, one index withheld (the
            # apply loop's hole-refetch must fill it)
            hole = sched.rng.randrange(8)
            arrivals = sched.with_dups(
                sched.shuffled(i for i in range(8) if i != hole), 3
            )
            for i in arrivals:
                queue.put(i, b"chunk-%d" % i, sender="p")
                await sched.yield_point()
            await reactor._apply_chunks(snapshot, queue)
        finally:
            queue.close()
        return tuple(applied)

    order = run(explore(scenario, schedules=8, base_seed=320))
    assert order == tuple(range(8))


def test_statesync_refetch_retry_schedule_independent():
    """A deterministic app control script (RETRY chunk 2 once, refetch
    chunk 1 once) must produce the same apply sequence no matter the
    arrival order of the chunks."""
    from tendermint_tpu.abci import types as abci
    from tendermint_tpu.statesync.chunks import ChunkQueue
    from tendermint_tpu.statesync.reactor import _Snapshot

    from tests.test_statesync import _bare_reactor

    async def scenario(sched):
        reactor = _bare_reactor()
        snapshot = _Snapshot(
            height=7, format=1, chunks=4, hash=b"h", metadata=b"",
            peers={"p"},
        )

        async def fake_fetch(snap, queue, indexes=None):
            for i in (
                indexes if indexes is not None else range(snap.chunks)
            ):
                queue.put(i, b"chunk-%d" % i, sender="p")
                await sched.yield_point()

        reactor._fetch_chunks = fake_fetch
        applied = []
        fired = set()

        class App:
            async def apply_snapshot_chunk(self, req):
                applied.append(req.index)
                if req.index == 2 and "retry" not in fired:
                    fired.add("retry")
                    return abci.ResponseApplySnapshotChunk(
                        result=abci.APPLY_CHUNK_RETRY,
                        refetch_chunks=(1,),
                    )
                return abci.ResponseApplySnapshotChunk(
                    result=abci.APPLY_CHUNK_ACCEPT
                )

        reactor.app = App()
        queue = ChunkQueue(4)
        try:
            for i in sched.shuffled(range(4)):
                queue.put(i, b"chunk-%d" % i, sender="p")
                await sched.yield_point()
            await reactor._apply_chunks(snapshot, queue)
        finally:
            queue.close()
        return tuple(applied)

    order = run(explore(scenario, schedules=8, base_seed=330))
    # 0,1,2 -> RETRY(2)+refetch(1) rewinds the cursor to 1 -> 1,2,3
    assert order == (0, 1, 2, 1, 2, 3)


def test_peermanager_lifecycles_schedule_independent():
    """Per-peer lifecycle events (accepted -> ready -> errored ->
    disconnected) interleaved across six peers in seeded orders: no
    ordering may corrupt the manager (phantom connections, stuck
    evictions, crashes)."""
    from tendermint_tpu.p2p.peermanager import (
        PeerManager,
        PeerManagerOptions,
    )

    async def scenario(sched):
        pm = PeerManager(
            "00" * 20,
            PeerManagerOptions(max_connected=16),
        )
        peers = ["%02d" % (i + 1) * 20 for i in range(6)]

        def lifecycle(pid, evil):
            steps = [
                lambda: pm.accepted(pid),
                lambda: pm.ready(pid),
            ]
            if evil:
                steps.append(lambda: pm.errored(pid, "misbehavior"))
            steps.append(lambda: pm.disconnected(pid))
            return steps

        seqs = [
            lifecycle(pid, evil=(i % 2 == 0))
            for i, pid in enumerate(peers)
        ]
        for step in sched.interleave(*seqs):
            step()
            await sched.yield_point()
        assert pm.num_connected() == 0, "phantom connection"
        # every errored peer's eviction was scheduled; drain them
        drained = 0
        while not pm._evict_queue.empty():
            pm._evict_queue.get_nowait()
            drained += 1
        assert drained == 3
        return "ok"

    run(explore(scenario, schedules=10, base_seed=340))


def test_vote_set_ingestion_schedule_independent():
    """VoteSet ingestion (types/vote_set.go:143-300 analog): the same
    prevotes delivered shuffled + duplicated must always yield the
    same 2/3 majority and bit array."""
    import time as _time

    from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
    from tendermint_tpu.types.block_id import BlockID, PartSetHeader
    from tendermint_tpu.types.validator import Validator, ValidatorSet
    from tendermint_tpu.types.vote import Vote
    from tendermint_tpu.types.vote_set import VoteSet

    privs = [
        PrivKeyEd25519.from_seed(bytes([i + 1, 0x77]) + b"\x31" * 30)
        for i in range(7)
    ]
    vals = ValidatorSet(
        [Validator(pub_key=p.pub_key(), voting_power=10) for p in privs]
    )
    order = {v.address: i for i, v in enumerate(vals.validators)}
    bid = BlockID(
        hash=b"\x61" * 32,
        part_set_header=PartSetHeader(total=1, hash=b"\x62" * 32),
    )
    now = _time.time_ns()
    votes = []
    for p in privs[:5]:  # 50/70 power > 2/3
        addr = p.pub_key().address()
        v = Vote(
            type=PREVOTE_TYPE,
            height=3,
            round=0,
            block_id=bid,
            timestamp_ns=now,
            validator_address=addr,
            validator_index=order[addr],
        )
        v.signature = p.sign(v.sign_bytes("sf-chain"))
        votes.append(v)

    async def scenario(sched):
        vs = VoteSet("sf-chain", 3, 0, PREVOTE_TYPE, vals)
        for v in sched.with_dups(sched.shuffled(votes), 4):
            vs.add_vote(v)
            await sched.yield_point()
        maj, ok = vs.two_thirds_majority()
        return (ok, maj.hash, str(vs.votes_bit_array))

    ok, maj_hash, _bits = run(
        explore(scenario, schedules=10, base_seed=350)
    )
    assert ok and maj_hash == bid.hash


def test_pubsub_fanout_schedule_independent():
    """Two publishers' event streams interleaved under seeded
    schedules: each subscriber sees its matching events with
    per-publisher order preserved."""
    from tendermint_tpu.pubsub import Server

    async def scenario(sched):
        srv = Server(name="sf-pubsub")
        await srv.start()
        try:
            sub_a = srv.subscribe("c1", "tm.event = 'A'")
            sub_all = srv.subscribe("c2", "tm.event EXISTS")
            pub_a = [("A", i) for i in range(5)]
            pub_b = [("B", i) for i in range(5)]
            for ev, i in sched.interleave(pub_a, pub_b):
                srv.publish((ev, i), {"tm.event": [ev]})
                await sched.yield_point()
            got_a = []
            while not sub_a._queue.empty():
                got_a.append(sub_a._queue.get_nowait().data)
            got_all = []
            while not sub_all._queue.empty():
                got_all.append(sub_all._queue.get_nowait().data)
            # subscriber A: exactly the A stream in order
            assert got_a == pub_a, got_a
            # subscriber ALL: both streams, each internally in order
            assert [x for x in got_all if x[0] == "A"] == pub_a
            assert [x for x in got_all if x[0] == "B"] == pub_b
            return ("ok", tuple(got_a))
        finally:
            await srv.stop()

    run(explore(scenario, schedules=8, base_seed=360))


def test_harness_reports_reproducing_seed():
    """The explorer's failure modes both name the seed: a scenario
    exception, and an outcome that diverges across schedules."""
    import pytest

    from tendermint_tpu.libs.schedulefuzz import Schedule

    async def crashes_on_second(sched):
        if sched.seed == 401:
            raise RuntimeError("boom")
        return 1

    with pytest.raises(AssertionError, match="seed=401"):
        run(explore(crashes_on_second, schedules=4, base_seed=400))

    async def schedule_dependent(sched):
        return sched.rng.random()  # guaranteed to diverge

    with pytest.raises(AssertionError, match="depends on the delivery"):
        run(explore(schedule_dependent, schedules=2, base_seed=0))

    # reproducibility: same seed -> same schedule decisions
    a = Schedule(77).shuffled(range(20))
    b = Schedule(77).shuffled(range(20))
    assert a == b


def test_evidence_pool_intake_schedule_independent():
    """Gossiped double-sign evidence arriving shuffled + duplicated,
    interleaved with a commit marking one piece: the final pending set
    must always be exactly the uncommitted evidence, and re-adding
    committed evidence must never resurrect it."""
    import time as _time

    from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
    from tendermint_tpu.evidence import EvidencePool
    from tendermint_tpu.state.types import State
    from tendermint_tpu.store.kv import MemKV
    from tendermint_tpu.types.validator import Validator, ValidatorSet

    from tests.test_evidence import CHAIN, make_double_sign

    now = _time.time_ns()
    privs = [
        PrivKeyEd25519.from_seed(bytes([i + 1, 0xEE]) + b"\x12" * 30)
        for i in range(4)
    ]
    vals = ValidatorSet(
        [Validator(pub_key=p.pub_key(), voting_power=10) for p in privs]
    )
    order = {v.address: i for i, v in enumerate(vals.validators)}
    evs = [
        make_double_sign(
            p, 2, vals, now, index=order[p.pub_key().address()]
        )
        for p in privs[:3]
    ]

    class Header:
        time_ns = now

    class Meta:
        header = Header()

    class BlockStore:
        def load_block_meta(self, height):
            return Meta() if height == 2 else None

    class StateStore:
        def load(self):
            return State(
                chain_id=CHAIN,
                last_block_height=3,
                last_block_time_ns=now,
                validators=vals,
            )

        def load_validators(self, height):
            return vals if height == 2 else None

    async def scenario(sched):
        pool = EvidencePool(MemKV(), StateStore(), BlockStore())
        committed = evs[1]
        # per-source FIFO: the commit marks evs[1] only after it was
        # gossiped at least once; other arrivals land anywhere
        plan = sched.interleave(
            [("add", committed), ("commit", committed), ("add", committed)],
            sched.with_dups(
                [("add", e) for e in sched.shuffled([evs[0], evs[2]])], 3
            ),
        )
        for action, ev in plan:
            if action == "add":
                # re-adding pending/committed evidence is a silent
                # no-op (pool.py add_evidence early-return); anything
                # raising here should surface with the seed
                pool.add_evidence(ev)
            else:
                pool.update(
                    State(
                        chain_id=CHAIN,
                        last_block_height=3,
                        last_block_time_ns=now,
                        validators=vals,
                    ),
                    [ev],
                )
            await sched.yield_point()
        pending, _ = pool.pending_evidence(1 << 20)
        assert pool.is_committed(committed)
        assert not pool.is_pending(committed)
        return tuple(sorted(e.hash() for e in pending))

    final = run(explore(scenario, schedules=8, base_seed=370))
    assert final == tuple(sorted(e.hash() for e in (evs[0], evs[2])))


def test_vote_ingest_with_device_faults_schedule_independent():
    """ISSUE 3 satellite: duplicated/reordered vote delivery through
    the device seam WHILE seeded device faults (raise + bit-flip) fire
    at the dispatch/gather boundary — verify-ahead batches drain
    between deliveries exactly like consensus _preverify_votes, the
    ed25519 breaker trips (and ticket-re-arms in-band) at whatever
    point each schedule's fault seed dictates, and the vote-set
    outcome must be byte-identical across every schedule. The fault
    seeds derive from the schedule seed (Schedule.subseed); the
    breaker deliberately has NO background probe here, so every fault
    rule consult happens in scenario order (a timer-driven probe
    would advance the shared seeded RNGs at wall-clock-dependent
    points). Backoff expiry is still wall-clock, so the exact
    device-vs-CPU routing per burst may vary — the assertion is the
    invariant that must NOT vary: the vote-set outcome."""
    import time as _time

    from tendermint_tpu.crypto import breaker as B
    from tendermint_tpu.crypto import faults, sigcache
    from tendermint_tpu.crypto import tpu_verifier as T
    from tendermint_tpu.crypto.batch import (
        create_batch_verifier,
        drain_and_cache,
        register_device_factory,
        unregister_device_factory,
    )
    from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
    from tendermint_tpu.types.block_id import BlockID, PartSetHeader
    from tendermint_tpu.types.validator import Validator, ValidatorSet
    from tendermint_tpu.types.vote import Vote
    from tendermint_tpu.types.vote_set import VoteSet

    from tests.test_chaos_consensus import HostBacking

    privs = [
        PrivKeyEd25519.from_seed(bytes([i + 1, 0x99]) + b"\x27" * 30)
        for i in range(7)
    ]
    vals = ValidatorSet(
        [Validator(pub_key=p.pub_key(), voting_power=10) for p in privs]
    )
    order = {v.address: i for i, v in enumerate(vals.validators)}
    bid = BlockID(
        hash=b"\x71" * 32,
        part_set_header=PartSetHeader(total=1, hash=b"\x72" * 32),
    )
    now = _time.time_ns()
    votes = []
    by_key = {}
    for p in privs[:5]:  # 50/70 power > 2/3
        addr = p.pub_key().address()
        v = Vote(
            type=PRECOMMIT_TYPE,
            height=5,
            round=0,
            block_id=bid,
            timestamp_ns=now,
            validator_address=addr,
            validator_index=order[addr],
        )
        v.signature = p.sign(v.sign_bytes("sf-chain"))
        votes.append(v)
        by_key[addr] = p.pub_key()

    backing = HostBacking()

    async def scenario(sched):
        sigcache.reset()
        T._SHARED_VERIFIER, shared0 = backing, T._SHARED_VERIFIER
        T._MIN_BATCH, min0 = 2, T._MIN_BATCH
        register_device_factory("ed25519", T._factory)
        B.fresh("ed25519", backoff_base_s=0.01)  # probe-less: in-band re-arm
        try:
            with faults.inject(
                "tpu.dispatch", mode="raise", p=0.4,
                seed=sched.subseed("dispatch"),
            ), faults.inject(
                "tpu.gather", mode="bitflip", p=0.3,
                seed=sched.subseed("gather"),
            ):
                vs = VoteSet("sf-chain", 5, 0, PRECOMMIT_TYPE, vals)
                buffer = []
                plan = sched.with_dups(sched.shuffled(votes), 4)
                for i, v in enumerate(plan):
                    buffer.append(v)
                    if len(buffer) >= 3 or i == len(plan) - 1:
                        # the verify-ahead shape: one device batch over
                        # the queued burst, cache misses only — faults
                        # land here, containment must keep the answers
                        triples, keys = [], []
                        for bv_vote in buffer:
                            pk = by_key[bv_vote.validator_address]
                            sb = bv_vote.sign_bytes("sf-chain")
                            key = sigcache.key_for(
                                pk.bytes(), sb, bv_vote.signature
                            )
                            if not sigcache.seen_key(key):
                                triples.append(
                                    (pk, sb, bv_vote.signature)
                                )
                                keys.append(key)
                        if len(triples) >= 2:
                            bv = create_batch_verifier(
                                triples[0][0], size_hint=len(triples)
                            )
                            for pk, sb, sig in triples:
                                bv.add(pk, sb, sig)
                            ok, bits = drain_and_cache(bv, keys)
                            assert ok and all(bits), (
                                "valid votes rejected under faults"
                            )
                        for bv_vote in buffer:
                            vs.add_vote(bv_vote)
                        buffer = []
                    await sched.yield_point()
            maj, ok = vs.two_thirds_majority()
            return (ok, maj.hash, str(vs.votes_bit_array))
        finally:
            unregister_device_factory("ed25519")
            T._SHARED_VERIFIER = shared0
            T._MIN_BATCH = min0
            B.reset_all()

    ok, maj_hash, _bits = run(
        explore(scenario, schedules=10, base_seed=500)
    )
    assert ok and maj_hash == bid.hash


def test_gossip_rng_replays_from_schedule_seed():
    """The gossip RNG (libs/rng.py — reactor part/vote picks,
    BitArray.pick_random) is pinned per schedule: the same seed must
    reproduce the same pick sequence, and explore() must hand the RNG
    back to OS entropy afterwards. This is what makes a fuzz failure
    that involved gossip choices actually replayable from the seed the
    failure message names (tmlint rule det-random enforces that no
    replay-critical code bypasses this RNG)."""
    from tendermint_tpu.libs import rng
    from tendermint_tpu.libs.bits import BitArray

    def draw():
        ba = BitArray(64)
        for i in range(0, 64, 3):
            ba.set(i, True)
        return [rng.choice(range(100)) for _ in range(16)] + [
            ba.pick_random() for _ in range(8)
        ]

    Schedule(42).seed_gossip()
    first = draw()
    Schedule(42).seed_gossip()
    assert draw() == first, "same seed must replay identical picks"
    Schedule(43).seed_gossip()
    assert draw() != first, "different seed must diverge"

    async def scenario(sched: Schedule):
        return rng.choice(range(10**9))

    picks = {}
    for base in (7, 7, 8):
        picks.setdefault(base, []).append(
            run(explore(scenario, schedules=1, base_seed=base))
        )
    assert picks[7][0] == picks[7][1], "explore() must pin gossip picks"
    rng.reseed(None)


def test_vote_delivery_with_net_faults_schedule_independent():
    """ISSUE 13 satellite, mirroring the PR-3 device-fault scenario one
    layer up: duplicated/reordered vote DELIVERY (the schedule)
    composed with seeded NETWORK faults — drop + reorder rules on the
    consensus vote channel, armed through a real 2-node router pair —
    with the fault seeds derived from the schedule seed via
    Schedule.subseed, so the combined exploration replays from the one
    seed a failure message names. The sender keeps resending votes the
    receiver's VoteSet still lacks (the gossip-retry shape; a dropped
    frame on a live connection is exactly what the stall-reset exists
    for), so the OUTCOME — the receiver's 2/3-majority decision — must
    be identical under every schedule."""
    import time as _time

    from tendermint_tpu.consensus import msgs as cmsgs
    from tendermint_tpu.consensus.reactor import (
        VOTE_CHANNEL,
        consensus_channel_descriptors,
    )
    from tendermint_tpu.crypto import faults
    from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
    from tendermint_tpu.p2p.p2ptest import TestNetwork
    from tendermint_tpu.p2p.types import Envelope
    from tendermint_tpu.types.block_id import BlockID, PartSetHeader
    from tendermint_tpu.types.validator import Validator, ValidatorSet
    from tendermint_tpu.types.vote import Vote
    from tendermint_tpu.types.vote_set import VoteSet

    privs = [
        PrivKeyEd25519.from_seed(bytes([i + 1, 0x77]) + b"\x35" * 30)
        for i in range(5)
    ]
    vals = ValidatorSet(
        [Validator(pub_key=p.pub_key(), voting_power=10) for p in privs]
    )
    order = {v.address: i for i, v in enumerate(vals.validators)}
    bid = BlockID(
        hash=b"\x55" * 32,
        part_set_header=PartSetHeader(total=1, hash=b"\x56" * 32),
    )
    now = 1_700_000_000_000_000_000
    votes = []
    for p in privs[:4]:  # 40/50 power > 2/3
        addr = p.pub_key().address()
        v = Vote(
            type=PRECOMMIT_TYPE,
            height=9,
            round=0,
            block_id=bid,
            timestamp_ns=now,
            validator_address=addr,
            validator_index=order[addr],
        )
        v.signature = p.sign(v.sign_bytes("nf-chain"))
        votes.append(v)

    vote_desc = consensus_channel_descriptors()[VOTE_CHANNEL]

    async def scenario(sched: Schedule):
        net = TestNetwork(2, chain_id="nf-chain")
        chans = [n.open_channel(vote_desc) for n in net.nodes]
        await net.start()
        vs = VoteSet("nf-chain", 9, 0, PRECOMMIT_TYPE, vals)
        stop = asyncio.Event()

        async def ingest():
            while not stop.is_set():
                try:
                    env = await asyncio.wait_for(chans[1].receive(), 0.2)
                except asyncio.TimeoutError:
                    continue
                if isinstance(env.message, cmsgs.VoteMessage):
                    vs.add_vote(env.message.vote)

        ingester = asyncio.ensure_future(ingest())
        try:
            with faults.inject(
                "p2p.send", mode="drop", p=0.3,
                seed=sched.subseed("net-drop"), ch=VOTE_CHANNEL,
            ), faults.inject(
                "p2p.recv", mode="reorder", p=0.3,
                seed=sched.subseed("net-reorder"), ch=VOTE_CHANNEL,
            ), faults.inject(
                "p2p.recv", mode="duplicate", p=0.2,
                seed=sched.subseed("net-dup"), ch=VOTE_CHANNEL,
            ):
                plan = sched.with_dups(sched.shuffled(votes), 3)
                for v in plan:
                    await chans[0].send(
                        Envelope(
                            message=cmsgs.VoteMessage(vote=v),
                            to=net.nodes[1].node_id,
                        )
                    )
                    await sched.yield_point()
                # gossip-retry: resend whatever the drops ate until
                # the receiver's set is complete (bounded)
                deadline = _time.monotonic() + 20.0
                while (
                    len(list(vs.bit_array().indices())) < len(votes)
                    and _time.monotonic() < deadline
                ):
                    for v in votes:
                        await chans[0].send(
                            Envelope(
                                message=cmsgs.VoteMessage(vote=v),
                                to=net.nodes[1].node_id,
                            )
                        )
                    await asyncio.sleep(0.05)
            maj, ok = vs.two_thirds_majority()
            return (ok, maj.hash, str(vs.votes_bit_array))
        finally:
            stop.set()
            ingester.cancel()
            await asyncio.gather(ingester, return_exceptions=True)
            await net.stop()

    ok, maj_hash, _bits = run(
        explore(scenario, schedules=6, base_seed=900)
    )
    assert ok and maj_hash == bid.hash
