"""Concurrency-schedule exploration — the framework's analog of the
reference's `go test -race` CI runs (SURVEY §5b).

The consensus core is a single-writer loop fed by queues, so the race
surface is ORDERING: which peer inputs land first, interleaved how,
duplicated or delayed. These tests drive one real ConsensusState
through many seeded random schedules of the same logical inputs and
assert the outcome is schedule-independent — the commit safety
property the single-writer design exists to protect. A regression that
makes a transition order-dependent (e.g. a lock update racing a vote
add) shows up as one seed committing a different block or deadlocking.
"""

import asyncio
import random

from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.canonical import PRECOMMIT_TYPE, PREVOTE_TYPE

from tests.test_consensus_lock import LockHarness, wait_for


def run(coro):
    return asyncio.run(coro)


def test_commit_is_schedule_independent():
    """Height 1 with a full vote set delivered in 8 different seeded
    orders (votes shuffled, some duplicated, prevotes/precommits
    interleaved): every schedule must commit cs1's proposal B1."""

    async def one_schedule(seed: int) -> bytes:
        h = LockHarness(seed_base=200)
        await h.cs.start()
        try:
            prevote = await h.wait_own_vote(PREVOTE_TYPE, 0)
            b1 = prevote.block_id
            rng = random.Random(seed)
            # the full honest-stub schedule: every stub prevotes and
            # precommits B1. Each vote is signed ONCE; duplicated plan
            # entries redeliver the identical signed vote object —
            # byte-for-byte gossip redelivery, the idempotent-dup path
            plan = []
            for priv in h.stubs:
                plan.append(await h.make_vote(priv, PREVOTE_TYPE, 0, b1))
                plan.append(
                    await h.make_vote(priv, PRECOMMIT_TYPE, 0, b1)
                )
            plan += [plan[rng.randrange(len(plan))] for _ in range(4)]
            rng.shuffle(plan)
            for vote in plan:
                h.send_vote(vote)
                if rng.random() < 0.5:
                    await asyncio.sleep(0)  # yield: vary interleaving
            await wait_for(
                lambda: h.node.block_store.height() >= 1,
                timeout=30.0,
                what=f"commit under schedule {seed}",
            )
            return h.node.block_store.load_block(1).hash()
        finally:
            await h.cs.stop()

    async def go():
        hashes = set()
        for seed in range(8):
            hashes.add(await one_schedule(seed))
        assert len(hashes) == 1, "commit depended on delivery schedule"

    run(go())


def test_lock_outcome_schedule_independent_across_rounds():
    """The round-1 relock cell under shuffled delivery: round-0 lock,
    nil precommits, then round-1 POL + precommits for B1 — delivered in
    seeded random orders with duplicates. Every schedule must end with
    B1 committed at round >= 1 (timing may let a schedule slip an extra
    round; safety — same block — is what ordering must never change)."""

    async def one_schedule(seed: int) -> bytes:
        h = LockHarness(seed_base=210)
        await h.cs.start()
        try:
            prevote = await h.lock_b1_round0()
            b1 = prevote.block_id
            rng = random.Random(seed)
            await h.push_to_round1_nil_precommits()
            plan = []
            for priv in h.stubs:
                plan.append(await h.make_vote(priv, PREVOTE_TYPE, 1, b1))
                plan.append(
                    await h.make_vote(priv, PRECOMMIT_TYPE, 1, b1)
                )
            plan += [plan[rng.randrange(len(plan))] for _ in range(3)]
            rng.shuffle(plan)
            for vote in plan:
                h.send_vote(vote)
                if rng.random() < 0.5:
                    await asyncio.sleep(0)
            await wait_for(
                lambda: h.node.block_store.height() >= 1,
                timeout=30.0,
                what=f"relock commit under schedule {seed}",
            )
            block = h.node.block_store.load_block(1)
            assert block.hash() == b1.hash
            seen = h.node.block_store.load_seen_commit()
            assert seen.round >= 1
            return block.hash()
        finally:
            await h.cs.stop()

    async def go():
        hashes = {await one_schedule(seed) for seed in range(6)}
        assert len(hashes) == 1

    run(go())


def test_future_round_votes_before_current_round_votes():
    """Adversarial ordering: round-1 votes arrive BEFORE any round-0
    votes (gossip reordering across rounds). The state machine must
    neither crash nor skip committing once the round-0 votes land."""

    async def go():
        h = LockHarness(seed_base=220)
        await h.cs.start()
        try:
            prevote = await h.wait_own_vote(PREVOTE_TYPE, 0)
            b1 = prevote.block_id
            # future-round nil prevotes first (tracked, round not yet
            # entered by cs1 beyond 2/3-any future-round pull)
            await h.stub_votes(PREVOTE_TYPE, 1, BlockID(), stubs=h.stubs[:1])
            # now the round-0 votes, same-block
            await h.stub_votes(PREVOTE_TYPE, 0, b1, stubs=h.stubs[:2])
            await h.stub_votes(PRECOMMIT_TYPE, 0, b1, stubs=h.stubs[:2])
            await wait_for(
                lambda: h.node.block_store.height() >= 1,
                timeout=30.0,
                what="commit despite future-round noise",
            )
            assert h.node.block_store.load_block(1).hash() == b1.hash
        finally:
            await h.cs.stop()

    run(go())
