"""Schema-seeded decoder fuzzer — the dynamic twin of the tmsafe
static gate.

tmsafe proves no *reachable* unclamped sink exists on decode paths;
this suite proves no *observed* unclamped behavior exists: golden wire
bytes are derived from the SAME schema extraction that pins tmcheck's
`schema.json`, then deterministically mutated (truncate, tag-swap,
varint-inflate, length-field inflation, byte flips), and every decoder
must

- raise only SANCTIONED error types (ValueError and subclasses — the
  contract the WAL's corruption handling and the RPC error mapper
  already rely on), never a TypeError/struct.error/AttributeError
  that would escape those handlers;
- never allocate past a byte budget proportional to the bytes the
  "attacker" actually sent (tracemalloc peak — the dynamic form of
  "no allocation from an unclamped parsed integer");
- never hang (per-message wall budget).

Replayability: every mutation is derived from
`random.Random(crc32(message_key) ^ FUZZ_SEED)` plus the mutation
index printed in the failure message — rerun with the same seed to
get the identical byte string (the schedulefuzz convention)."""

import importlib
import inspect
import time
import tracemalloc
import zlib

import pytest

from tendermint_tpu.analysis.tmcheck.schema import extract_package
from tendermint_tpu.encoding.proto import ProtoWriter, encode_varint

FUZZ_SEED = 0x7E4D
MUTATIONS_PER_MESSAGE = 14
MIN_MESSAGE_TYPES = 20
MIN_TOTAL_MUTATIONS = 1000

# the sanctioned decode-failure contract: everything downstream
# (WAL _decode_record, RPC dispatch, reactor error paths) catches
# ValueError; UnicodeDecodeError (garbage in a string field) is a
# ValueError subclass by design
SANCTIONED = (ValueError,)

# bytes a decoder may allocate per byte of attacker input, plus slack
# for fixed per-message object overhead (dataclass instances, the
# FieldReader dict). The point is the SHAPE — linear in input, never
# keyed off a parsed integer — not a tight constant.
BYTES_PER_INPUT_BYTE = 64
BYTE_BUDGET_SLACK = 512 * 1024


def _dummy_value(method: str):
    if method in ("uint", "int", "sint", "sfixed64", "fixed64", "sfixed32"):
        return 1
    if method == "bool":
        return True
    if method == "double":
        return 1.0
    if method == "bytes":
        return b"\x01\x02\x03"
    if method == "string":
        return "x"
    if method == "message":
        return b""
    raise AssertionError(f"unknown writer method {method}")


def _build_golden(msg) -> bytes:
    """Golden bytes straight from the extracted encoder schema: one
    write per field, ascending tags only (a oneof contributes its
    first arm), dummy values per writer method."""
    w = ProtoWriter()
    last = 0
    for f in msg.fields:
        if f.tag <= last:
            continue  # oneof sibling arm / duplicate
        getattr(w, f.method)(f.tag, _dummy_value(f.method))
        last = f.tag
    return w.finish()


def _resolve_decoder(path: str, qualname: str):
    mod_name = "tendermint_tpu." + path[:-3].replace("/", ".")
    mod = importlib.import_module(mod_name)
    obj = mod
    for part in qualname.split("."):
        obj = getattr(obj, part)
    return obj


def _single_bytes_param(fn) -> bool:
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    required = [
        p
        for p in sig.parameters.values()
        if p.default is inspect.Parameter.empty
        and p.kind
        in (
            inspect.Parameter.POSITIONAL_ONLY,
            inspect.Parameter.POSITIONAL_OR_KEYWORD,
        )
        and p.name not in ("self", "cls")
    ]
    return len(required) == 1


def _mutations(golden: bytes, rng) -> list:
    """Deterministic mutation set for one message. Index order is part
    of the replay recipe."""
    out = []
    n = len(golden)
    # 1-3: truncations
    for frac in (0.25, 0.5, 0.9):
        out.append(golden[: int(n * frac)])
    # 4: tag swap — rewrite the leading tag byte
    if n:
        out.append(bytes([rng.randrange(256)]) + golden[1:])
    else:
        out.append(b"\xff")
    # 5: varint-inflate — append a field-1 varint of 2**64 - 1
    out.append(golden + b"\x08" + encode_varint((1 << 64) - 1))
    # 6: length-field x1000 — claim a huge length-delimited field
    out.append(golden + b"\x12" + encode_varint(1000 * max(n, 1)) + b"\x00")
    # 7: claimed length FAR past the payload (the classic over-alloc
    # lever if a decoder trusts it)
    out.append(b"\x0a" + encode_varint(1 << 40) + golden)
    # 8: wire-type corruption — same field numbers, wire type 7
    if n:
        out.append(bytes([golden[0] | 0x07]) + golden[1:])
    else:
        out.append(b"\x07")
    # 9-14: seeded byte flips / splices
    for _ in range(6):
        if not n:
            out.append(bytes([rng.randrange(256)]))
            continue
        b = bytearray(golden)
        for _ in range(rng.randrange(1, 4)):
            b[rng.randrange(n)] = rng.randrange(256)
        out.append(bytes(b))
    return out


@pytest.fixture(scope="module")
def corpus():
    """(key, decoder callable, golden bytes) for every schema-derived
    message whose decoder takes a single bytes argument."""
    messages, _ = extract_package()
    out = []
    for key in sorted(messages):
        msg = messages[key]
        if not msg.dec_func or not msg.fields:
            continue
        path, _, tail = key.partition("::")
        for qual in (f"{tail}.{msg.dec_func}", msg.dec_func):
            try:
                fn = _resolve_decoder(path, qual)
            except (AttributeError, ImportError):
                continue
            if _single_bytes_param(fn):
                out.append((key, fn, _build_golden(msg)))
            break
    return out


def test_corpus_is_broad_enough(corpus):
    """The acceptance floor: >= 20 message types, >= 1000 deterministic
    mutations per full run."""
    assert len(corpus) >= MIN_MESSAGE_TYPES, (
        f"only {len(corpus)} fuzzable decoders"
    )
    assert len(corpus) * MUTATIONS_PER_MESSAGE >= MIN_TOTAL_MUTATIONS


def test_decoders_raise_only_sanctioned_errors(corpus):
    """Every mutation either decodes or raises a sanctioned error —
    never a TypeError/struct.error/KeyError that would escape the
    WAL/RPC/reactor error handlers, never a hang, never an allocation
    past the input-proportional byte budget."""
    total = 0
    failures = []
    for key, fn, golden in corpus:
        rng_seed = zlib.crc32(key.encode()) ^ FUZZ_SEED
        import random

        rng = random.Random(rng_seed)
        muts = _mutations(golden, rng)
        assert len(muts) == MUTATIONS_PER_MESSAGE
        t0 = time.monotonic()
        for i, data in enumerate(muts):
            total += 1
            budget = BYTES_PER_INPUT_BYTE * len(data) + BYTE_BUDGET_SLACK
            tracemalloc.start()
            try:
                fn(data)
            except SANCTIONED:
                pass
            except Exception as e:  # noqa: BLE001 - the point
                failures.append(
                    f"{key} mutation #{i} (seed {rng_seed:#x}): "
                    f"unsanctioned {type(e).__name__}: {e}"
                )
            finally:
                _, peak = tracemalloc.get_traced_memory()
                tracemalloc.stop()
            if peak > budget:
                failures.append(
                    f"{key} mutation #{i} (seed {rng_seed:#x}): "
                    f"allocated {peak} bytes for {len(data)} input "
                    f"bytes (budget {budget})"
                )
        elapsed = time.monotonic() - t0
        if elapsed > 5.0:
            failures.append(
                f"{key}: {MUTATIONS_PER_MESSAGE} mutations took "
                f"{elapsed:.1f}s — a decode hang or superlinear cost"
            )
    assert total >= MIN_TOTAL_MUTATIONS
    assert not failures, (
        f"{len(failures)} fuzz failures:\n" + "\n".join(failures[:25])
    )


def test_golden_bytes_decode_or_fail_sanctioned(corpus):
    """The unmutated goldens themselves: dummy field values are not
    semantically valid (a 3-byte pubkey), so decoders may reject them
    — but only with sanctioned errors."""
    for key, fn, golden in corpus:
        try:
            fn(golden)
        except SANCTIONED:
            pass
        except Exception as e:  # noqa: BLE001
            pytest.fail(
                f"{key}: golden decode raised unsanctioned "
                f"{type(e).__name__}: {e}"
            )


def test_mutations_are_deterministic(corpus):
    """Replayability: the same (message, seed) yields byte-identical
    mutations — the schedulefuzz convention for this suite."""
    import random

    key, fn, golden = corpus[0]
    seed = zlib.crc32(key.encode()) ^ FUZZ_SEED
    a = _mutations(golden, random.Random(seed))
    b = _mutations(golden, random.Random(seed))
    assert a == b
