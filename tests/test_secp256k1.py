"""The native secp256k1 backend, pinned against external vectors.

Four layers: (1) RFC 6979 deterministic nonces and full signatures
against the community-standard secp256k1+SHA-256 vector set (the
Trezor/bitcointalk corpus — RFC 6979's own appendix covers only the
NIST curves); (2) Wycheproof-class edge cases, ported by construction
rather than by hex blob: zero/overflow scalars, high-s malleability,
malformed encodings, off-curve and invalid-prefix pubkeys; (3) the
property pin the batch plugin depends on — verify_batch's accept/
reject is byte-identical to the single-verify loop over any mixed
batch; (4) the BatchVerifier plugin contract (one-shot drain, exact
bitmap, type discipline) and the crypto.keys first-class dispatch the
PR-1 shim used to raise on.
"""

import hashlib
import random

import pytest

from tendermint_tpu.crypto import batch
from tendermint_tpu.crypto.keys import (
    generate_priv_key,
    privkey_from_type_and_bytes,
    pubkey_from_proto,
    pubkey_from_type_and_bytes,
    pubkey_to_proto,
)
from tendermint_tpu.crypto.secp256k1 import (
    _HALF_ORDER,
    _ORDER,
    _P,
    _decompress,
    _rfc6979_k,
    PrivKeySecp256k1,
    PubKeySecp256k1,
    Secp256k1BatchVerifier,
    verify_batch,
)

# ---------------------------------------------------------------------------
# RFC 6979 deterministic nonces + full signatures (external vectors)

# (privkey scalar, message, expected k) — the secp256k1+SHA-256 set
# circulated with the RFC (Trezor crypto tests / bitcointalk vectors).
_K_VECTORS = [
    (
        1,
        b"Satoshi Nakamoto",
        0x8F8A276C19F4149656B280621E358CCE24F5F52542772691EE69063B74F15D15,
    ),
    (
        1,
        b"All those moments will be lost in time, like tears in rain. "
        b"Time to die...",
        0x38AA22D72376B4DBC472E06C3BA403EE0A394DA63FC58D88686C611ABA98D6B3,
    ),
    (
        _ORDER - 1,
        b"Satoshi Nakamoto",
        0x33A19B60E25FB6F4435AF53A3D42D493644827367E6453928554F43E49AA6F90,
    ),
    (
        0xF8B8AF8CE3C7CCA5E300D33939540C10D45CE001B8F252BFBC57BA0342904181,
        b"Alan Turing",
        0x525A82B70E67874398067543FD84C83D30C175FDC45FDEEE082FE13B1D7CFDF1,
    ),
]

# (privkey scalar, message, r hex, s hex) — full low-s signatures
_SIG_VECTORS = [
    (
        1,
        b"Satoshi Nakamoto",
        "934b1ea10a4b3c1757e2b0c017d0b6143ce3c9a7e6a4a49860d7a6ab210ee3d8",
        "2442ce9d2b916064108014783e923ec36b49743e2ffa1c4496f01a512aafd9e5",
    ),
    (
        0xF8B8AF8CE3C7CCA5E300D33939540C10D45CE001B8F252BFBC57BA0342904181,
        b"Alan Turing",
        "7063ae83e7f62bbb171798131b4a0564b956930092b33b07b395615d9ec7e15c",
        "58dfcc1e00a35e1572f366ffe34ba0fc47db1e7189759b9fb233c5b05ab388ea",
    ),
]


@pytest.mark.parametrize("d,msg,expected_k", _K_VECTORS)
def test_rfc6979_nonce_vectors(d, msg, expected_k):
    h1 = hashlib.sha256(msg).digest()
    assert _rfc6979_k(d.to_bytes(32, "big"), h1) == expected_k


@pytest.mark.parametrize("d,msg,r_hex,s_hex", _SIG_VECTORS)
def test_signature_vectors(d, msg, r_hex, s_hex):
    sk = PrivKeySecp256k1(d.to_bytes(32, "big"))
    sig = sk.sign(msg)
    assert sig[:32].hex() == r_hex
    assert sig[32:].hex() == s_hex
    assert sk.pub_key().verify_signature(msg, sig)


def test_sign_is_deterministic():
    sk = PrivKeySecp256k1((7).to_bytes(32, "big"))
    assert sk.sign(b"msg") == sk.sign(b"msg")
    assert sk.sign(b"msg") != sk.sign(b"msg2")


def test_sign_always_low_s():
    for d in (1, 2, 3, 0xDEADBEEF, _ORDER - 2):
        sk = PrivKeySecp256k1(d.to_bytes(32, "big"))
        for i in range(4):
            sig = sk.sign(b"low-s probe %d" % i)
            assert int.from_bytes(sig[32:], "big") <= _HALF_ORDER


# ---------------------------------------------------------------------------
# Wycheproof-class edge cases (ported by construction)


@pytest.fixture(scope="module")
def keypair():
    sk = PrivKeySecp256k1(
        0xC9AFA9D845BA75166B5C215767B1D6934E50C3DB36E89B127B8A622B120F6721
        .to_bytes(32, "big")
    )
    return sk, sk.pub_key()


def test_valid_signature_accepts(keypair):
    sk, pk = keypair
    msg = b"wycheproof-style base case"
    assert pk.verify_signature(msg, sk.sign(msg))


def test_modified_message_rejects(keypair):
    sk, pk = keypair
    sig = sk.sign(b"message one")
    assert not pk.verify_signature(b"message two", sig)


def test_zero_r_or_s_rejects(keypair):
    _, pk = keypair
    msg = b"zero scalar cases"
    zero = (0).to_bytes(32, "big")
    one = (1).to_bytes(32, "big")
    assert not pk.verify_signature(msg, zero + one)
    assert not pk.verify_signature(msg, one + zero)
    assert not pk.verify_signature(msg, zero + zero)


def test_r_or_s_at_or_above_order_rejects(keypair):
    sk, pk = keypair
    msg = b"overflow scalar cases"
    sig = sk.sign(msg)
    n = _ORDER.to_bytes(32, "big")
    big = (_ORDER + 1).to_bytes(32, "big")
    ff = b"\xff" * 32
    assert not pk.verify_signature(msg, n + sig[32:])
    assert not pk.verify_signature(msg, big + sig[32:])
    assert not pk.verify_signature(msg, ff + sig[32:])
    assert not pk.verify_signature(msg, sig[:32] + n)
    assert not pk.verify_signature(msg, sig[:32] + ff)


def test_high_s_malleated_twin_rejects(keypair):
    """The reference requires normalized s (secp256k1.go Verify): the
    algebraically-valid (r, N-s) twin must NOT verify — consensus
    signatures cannot be malleable."""
    sk, pk = keypair
    msg = b"malleability case"
    sig = sk.sign(msg)
    s = int.from_bytes(sig[32:], "big")
    twin = sig[:32] + (_ORDER - s).to_bytes(32, "big")
    assert not pk.verify_signature(msg, twin)


def test_wrong_length_signature_rejects(keypair):
    sk, pk = keypair
    msg = b"length cases"
    sig = sk.sign(msg)
    assert not pk.verify_signature(msg, sig[:63])
    assert not pk.verify_signature(msg, sig + b"\x00")
    assert not pk.verify_signature(msg, b"")


def test_garbage_signature_rejects(keypair):
    _, pk = keypair
    assert not pk.verify_signature(b"m", b"\x01" * 64)


def test_decompress_rejects_bad_encodings():
    # x with no square-root solution for y^2 = x^3 + 7
    assert _decompress(b"\x02" + (5).to_bytes(32, "big")) is None
    # x >= field prime
    assert _decompress(b"\x02" + _P.to_bytes(32, "big")) is None
    assert _decompress(b"\x02" + b"\xff" * 32) is None
    # uncompressed / infinity prefixes are not valid compressed forms
    assert _decompress(b"\x04" + (1).to_bytes(32, "big")) is None
    assert _decompress(b"\x00" + (1).to_bytes(32, "big")) is None


def test_decompress_parity_selects_y(keypair):
    _, pk = keypair
    x, y = _decompress(pk.bytes())
    assert (y * y - (x * x * x + 7)) % _P == 0
    assert (y & 1) == (pk.bytes()[0] & 1)
    # the flipped-parity encoding is the conjugate point
    flipped = bytes([pk.bytes()[0] ^ 1]) + pk.bytes()[1:]
    x2, y2 = _decompress(flipped)
    assert x2 == x and y2 == (_P - y)


def test_off_curve_pubkey_never_verifies(keypair):
    sk, _ = keypair
    msg = b"off-curve pubkey"
    sig = sk.sign(msg)
    bad_pk = PubKeySecp256k1(b"\x02" + (5).to_bytes(32, "big"))
    assert not bad_pk.verify_signature(msg, sig)


def test_privkey_scalar_range_enforced():
    with pytest.raises(ValueError):
        PrivKeySecp256k1(b"\x00" * 32)  # d = 0
    with pytest.raises(ValueError):
        PrivKeySecp256k1(_ORDER.to_bytes(32, "big"))  # d = N
    with pytest.raises(ValueError):
        PrivKeySecp256k1(b"\x00" * 31)  # wrong length
    PrivKeySecp256k1((_ORDER - 1).to_bytes(32, "big"))  # d = N-1 valid


# ---------------------------------------------------------------------------
# batch: byte-identical to the single-verify loop


def _mixed_batch(n=24, seed=0xC0FFEE):
    """Deterministic mixed batch: valid sigs, corrupted sigs, wrong
    messages, high-s twins, malformed pubkeys — the verify_batch
    equivalence domain."""
    rng = random.Random(seed)
    keys = [
        PrivKeySecp256k1(rng.randrange(1, _ORDER).to_bytes(32, "big"))
        for _ in range(6)
    ]
    items = []
    for i in range(n):
        sk = keys[i % len(keys)]
        pk = sk.pub_key()
        msg = b"batch item %d" % i
        sig = sk.sign(msg)
        kind = i % 5
        if kind == 1:  # corrupt one signature byte
            pos = rng.randrange(64)
            sig = sig[:pos] + bytes([sig[pos] ^ 0x40]) + sig[pos + 1:]
        elif kind == 2:  # signature over a different message
            msg = b"different message %d" % i
        elif kind == 3:  # high-s malleated twin
            s = int.from_bytes(sig[32:], "big")
            sig = sig[:32] + (_ORDER - s).to_bytes(32, "big")
        elif kind == 4:  # pubkey with an off-curve x
            pk = PubKeySecp256k1(b"\x02" + (5).to_bytes(32, "big"))
        items.append((pk, msg, sig))
    return items


def test_verify_batch_matches_single_loop_exactly():
    items = _mixed_batch()
    ok, bits = verify_batch(items)
    expected = [
        PubKeySecp256k1(pk.bytes()).verify_signature(msg, sig)
        for pk, msg, sig in items
    ]
    assert bits == expected
    assert ok == all(expected)
    assert any(expected) and not all(expected)  # the mix is a real mix


def test_verify_batch_all_valid():
    items = [it for it in _mixed_batch(n=25) if it[0].bytes()[0] != 0x02
             or _decompress(it[0].bytes()) is not None]
    valid = []
    for i in range(8):
        sk = PrivKeySecp256k1((i + 11).to_bytes(32, "big"))
        msg = b"all-valid %d" % i
        valid.append((sk.pub_key(), msg, sk.sign(msg)))
    ok, bits = verify_batch(valid)
    assert ok is True and bits == [True] * 8


def test_verify_batch_empty_is_false():
    assert verify_batch([]) == (False, [])


def test_batch_verifier_contract():
    """The plugin contract: exact bitmap in add() order, one-shot
    drain — a second verify() without new add()s returns (False, []) —
    and type/size discipline at add()."""
    items = _mixed_batch(n=10, seed=7)
    bv = Secp256k1BatchVerifier()
    for pk, msg, sig in items:
        bv.add(pk, msg, sig)
    assert len(bv) == 10
    ok, bits = bv.verify()
    expected = [
        PubKeySecp256k1(pk.bytes()).verify_signature(msg, sig)
        for pk, msg, sig in items
    ]
    assert bits == expected and ok == all(expected)
    assert bv.verify() == (False, [])  # drained
    sk = PrivKeySecp256k1((3).to_bytes(32, "big"))
    with pytest.raises(ValueError):
        bv.add(sk.pub_key(), b"m", b"short")
    from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519

    with pytest.raises(TypeError):
        bv.add(PrivKeyEd25519.generate().pub_key(), b"m", b"\x00" * 64)


def test_batch_dispatch_returns_secp_verifier():
    """crypto.batch now serves secp256k1 first-class — the PR-1 shim's
    'does not support batching' raise is gone."""
    sk = PrivKeySecp256k1.generate()
    assert batch.supports_batch_verifier(sk.pub_key())
    bv = batch.create_batch_verifier(sk.pub_key(), size_hint=4)
    assert isinstance(bv, Secp256k1BatchVerifier)


# ---------------------------------------------------------------------------
# crypto.keys first-class dispatch


def test_keys_dispatch_no_longer_raises():
    sk = generate_priv_key("secp256k1")
    assert isinstance(sk, PrivKeySecp256k1)
    assert sk.type() == "secp256k1"
    clone = privkey_from_type_and_bytes("secp256k1", sk.bytes())
    assert clone.pub_key().bytes() == sk.pub_key().bytes()
    pk = pubkey_from_type_and_bytes("secp256k1", sk.pub_key().bytes())
    assert pk == sk.pub_key()


def test_pubkey_proto_roundtrip_secp():
    sk = PrivKeySecp256k1((42).to_bytes(32, "big"))
    pk = sk.pub_key()
    assert pubkey_from_proto(pubkey_to_proto(pk)) == pk


def test_generate_yields_working_key():
    sk = PrivKeySecp256k1.generate()
    assert len(sk.bytes()) == 32
    msg = b"fresh key"
    assert sk.pub_key().verify_signature(msg, sk.sign(msg))
    assert len(sk.pub_key().bytes()) == 33
    assert len(sk.pub_key().address()) == 20
