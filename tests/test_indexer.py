"""Indexer sink + service tests (reference model:
internal/state/indexer/indexer_service_test.go, sink/kv/kv_test.go)."""

import asyncio

from tendermint_tpu.abci import types as abci
from tendermint_tpu.eventbus import EventBus
from tendermint_tpu.state.indexer import (
    IndexerService,
    KVSink,
    NullSink,
    TxResult,
)
from tendermint_tpu.store.kv import MemKV
from tendermint_tpu.types import events as E
from tendermint_tpu.types.tx import tx_hash


def run(coro):
    return asyncio.run(coro)


def make_tx_result(height, index, tx, key=b"k", indexed=True):
    return TxResult(
        height=height,
        index=index,
        tx=tx,
        result=abci.ResponseDeliverTx(
            events=(
                abci.Event(
                    type="app",
                    attributes=(
                        abci.EventAttribute(b"key", key, indexed),
                        abci.EventAttribute(b"noindex", b"x", False),
                    ),
                ),
            )
        ),
    )


def test_kv_sink_tx_roundtrip_and_search():
    sink = KVSink(MemKV())
    trs = [
        make_tx_result(1, 0, b"tx-a", key=b"apple"),
        make_tx_result(1, 1, b"tx-b", key=b"banana"),
        make_tx_result(2, 0, b"tx-c", key=b"apple"),
    ]
    sink.index_tx_events(trs)

    got = sink.get_tx_by_hash(tx_hash(b"tx-a"))
    assert got is not None and got.tx == b"tx-a" and got.height == 1

    # search by indexed app event
    hits = sink.search_tx_events("app.key = 'apple'")
    assert [t.tx for t in hits] == [b"tx-a", b"tx-c"]

    # non-indexed attributes are not searchable
    assert sink.search_tx_events("app.noindex = 'x'") == []

    # reserved keys: height + hash
    assert [t.tx for t in sink.search_tx_events("tx.height = 2")] == [b"tx-c"]
    h = tx_hash(b"tx-b").hex().upper()
    assert [t.tx for t in sink.search_tx_events(f"tx.hash = '{h}'")] == [b"tx-b"]

    # conjunction intersects
    hits = sink.search_tx_events("app.key = 'apple' AND tx.height < 2")
    assert [t.tx for t in hits] == [b"tx-a"]

    # range over heights
    hits = sink.search_tx_events("tx.height >= 1")
    assert len(hits) == 3


def test_kv_sink_block_events():
    sink = KVSink(MemKV())
    sink.index_block_events(
        5,
        [
            abci.Event(
                type="val_update",
                attributes=(abci.EventAttribute(b"pubkey", b"aa", True),),
            )
        ],
    )
    sink.index_block_events(6, [])
    assert sink.has_block(5) and sink.has_block(6) and not sink.has_block(7)
    assert sink.search_block_events("val_update.pubkey = 'aa'") == [5]
    assert sink.search_block_events("block.height > 5") == [6]


def test_indexer_service_end_to_end():
    async def go():
        bus = EventBus()
        await bus.start()
        sink = KVSink(MemKV())
        svc = IndexerService([sink, NullSink()], bus)
        await svc.start()

        class _Hdr:
            height = 3

        class _Blk:
            header = _Hdr()

        bus.publish_new_block(
            E.EventDataNewBlock(
                block=_Blk(),
                block_id=None,
                result_end_block=abci.ResponseEndBlock(
                    events=(
                        abci.Event(
                            type="end",
                            attributes=(
                                abci.EventAttribute(b"done", b"yes", True),
                            ),
                        ),
                    )
                ),
            )
        )
        bus.publish_tx(
            E.EventDataTx(
                height=3,
                tx=b"indexed-tx",
                index=0,
                result=abci.ResponseDeliverTx(),
            ),
            tx_hash=tx_hash(b"indexed-tx"),
        )
        # service consumes asynchronously
        for _ in range(100):
            if sink.has_block(3) and sink.get_tx_by_hash(tx_hash(b"indexed-tx")):
                break
            await asyncio.sleep(0.01)
        assert sink.has_block(3)
        assert sink.search_block_events("end.done = 'yes'") == [3]
        assert sink.get_tx_by_hash(tx_hash(b"indexed-tx")).height == 3
        await svc.stop()
        await bus.stop()

    run(go())


def test_kv_sink_nul_bytes_in_values():
    """Values containing the key separator must not corrupt matching."""
    sink = KVSink(MemKV())
    sink.index_tx_events(
        [make_tx_result(1, 0, b"tx-nul", key=b"a\x00b"),
         make_tx_result(1, 1, b"tx-plain", key=b"a")]
    )
    hits = sink.search_tx_events("app.key = 'a'")
    assert [t.tx for t in hits] == [b"tx-plain"]
    hits = sink.search_tx_events("app.key CONTAINS 'a'")
    assert {t.tx for t in hits} == {b"tx-nul", b"tx-plain"}
