"""Indexer sink + service tests (reference model:
internal/state/indexer/indexer_service_test.go, sink/kv/kv_test.go)."""

import asyncio

from tendermint_tpu.abci import types as abci
from tendermint_tpu.eventbus import EventBus
from tendermint_tpu.state.indexer import (
    IndexerService,
    KVSink,
    NullSink,
    TxResult,
)
from tendermint_tpu.store.kv import MemKV
from tendermint_tpu.types import events as E
from tendermint_tpu.types.tx import tx_hash


def run(coro):
    return asyncio.run(coro)


def make_tx_result(height, index, tx, key=b"k", indexed=True):
    return TxResult(
        height=height,
        index=index,
        tx=tx,
        result=abci.ResponseDeliverTx(
            events=(
                abci.Event(
                    type="app",
                    attributes=(
                        abci.EventAttribute(b"key", key, indexed),
                        abci.EventAttribute(b"noindex", b"x", False),
                    ),
                ),
            )
        ),
    )


def test_kv_sink_tx_roundtrip_and_search():
    sink = KVSink(MemKV())
    trs = [
        make_tx_result(1, 0, b"tx-a", key=b"apple"),
        make_tx_result(1, 1, b"tx-b", key=b"banana"),
        make_tx_result(2, 0, b"tx-c", key=b"apple"),
    ]
    sink.index_tx_events(trs)

    got = sink.get_tx_by_hash(tx_hash(b"tx-a"))
    assert got is not None and got.tx == b"tx-a" and got.height == 1

    # search by indexed app event
    hits = sink.search_tx_events("app.key = 'apple'")
    assert [t.tx for t in hits] == [b"tx-a", b"tx-c"]

    # non-indexed attributes are not searchable
    assert sink.search_tx_events("app.noindex = 'x'") == []

    # reserved keys: height + hash
    assert [t.tx for t in sink.search_tx_events("tx.height = 2")] == [b"tx-c"]
    h = tx_hash(b"tx-b").hex().upper()
    assert [t.tx for t in sink.search_tx_events(f"tx.hash = '{h}'")] == [b"tx-b"]

    # conjunction intersects
    hits = sink.search_tx_events("app.key = 'apple' AND tx.height < 2")
    assert [t.tx for t in hits] == [b"tx-a"]

    # range over heights
    hits = sink.search_tx_events("tx.height >= 1")
    assert len(hits) == 3


def test_kv_sink_block_events():
    sink = KVSink(MemKV())
    sink.index_block_events(
        5,
        [
            abci.Event(
                type="val_update",
                attributes=(abci.EventAttribute(b"pubkey", b"aa", True),),
            )
        ],
    )
    sink.index_block_events(6, [])
    assert sink.has_block(5) and sink.has_block(6) and not sink.has_block(7)
    assert sink.search_block_events("val_update.pubkey = 'aa'") == [5]
    assert sink.search_block_events("block.height > 5") == [6]


def test_indexer_service_end_to_end():
    async def go():
        bus = EventBus()
        await bus.start()
        sink = KVSink(MemKV())
        svc = IndexerService([sink, NullSink()], bus)
        await svc.start()

        class _Hdr:
            height = 3

        class _Blk:
            header = _Hdr()

        bus.publish_new_block(
            E.EventDataNewBlock(
                block=_Blk(),
                block_id=None,
                result_end_block=abci.ResponseEndBlock(
                    events=(
                        abci.Event(
                            type="end",
                            attributes=(
                                abci.EventAttribute(b"done", b"yes", True),
                            ),
                        ),
                    )
                ),
            )
        )
        bus.publish_tx(
            E.EventDataTx(
                height=3,
                tx=b"indexed-tx",
                index=0,
                result=abci.ResponseDeliverTx(),
            ),
            tx_hash=tx_hash(b"indexed-tx"),
        )
        # service consumes asynchronously
        for _ in range(100):
            if sink.has_block(3) and sink.get_tx_by_hash(tx_hash(b"indexed-tx")):
                break
            await asyncio.sleep(0.01)
        assert sink.has_block(3)
        assert sink.search_block_events("end.done = 'yes'") == [3]
        assert sink.get_tx_by_hash(tx_hash(b"indexed-tx")).height == 3
        await svc.stop()
        await bus.stop()

    run(go())


def test_kv_sink_nul_bytes_in_values():
    """Values containing the key separator must not corrupt matching."""
    sink = KVSink(MemKV())
    sink.index_tx_events(
        [make_tx_result(1, 0, b"tx-nul", key=b"a\x00b"),
         make_tx_result(1, 1, b"tx-plain", key=b"a")]
    )
    hits = sink.search_tx_events("app.key = 'a'")
    assert [t.tx for t in hits] == [b"tx-plain"]
    hits = sink.search_tx_events("app.key CONTAINS 'a'")
    assert {t.tx for t in hits} == {b"tx-nul", b"tx-plain"}


def _sql_sink():
    from tendermint_tpu.state.sink_sql import SQLSink

    return SQLSink("sqlite::memory:", chain_id="sql-chain")


def test_sql_sink_search_parity_with_kv():
    """The SQL sink (reference psql schema) answers the same queries
    the KV sink does — over every operator the query language has."""
    kv = KVSink(MemKV())
    sql = _sql_sink()
    trs = [
        make_tx_result(1, 0, b"tx-a", key=b"apple"),
        make_tx_result(1, 1, b"tx-b", key=b"banana"),
        make_tx_result(2, 0, b"tx-c", key=b"apple"),
        make_tx_result(3, 0, b"tx-d", key=b"apricot"),
    ]
    kv.index_tx_events(trs)
    sql.index_tx_events(trs)
    h = tx_hash(b"tx-b").hex().upper()
    for q in (
        "app.key = 'apple'",
        "app.noindex = 'x'",
        "tx.height = 2",
        f"tx.hash = '{h}'",
        "app.key = 'apple' AND tx.height < 2",
        "tx.height >= 1",
        "app.key CONTAINS 'ap'",
        "app.key EXISTS",
    ):
        assert [t.tx for t in sql.search_tx_events(q)] == [
            t.tx for t in kv.search_tx_events(q)
        ], q
    assert sql.get_tx_by_hash(tx_hash(b"tx-c")).height == 2
    sql.close()


def test_sql_sink_block_events_and_schema():
    sql = _sql_sink()
    sql.index_block_events(
        5,
        [
            abci.Event(
                type="epoch",
                attributes=(abci.EventAttribute(b"phase", b"end", True),),
            )
        ],
    )
    sql.index_block_events(6, [])
    assert sql.has_block(5) and sql.has_block(6) and not sql.has_block(7)
    assert sql.search_block_events("epoch.phase = 'end'") == [5]
    assert sql.search_block_events("block.height > 5") == [6]
    # the reference schema shape is queryable directly (operators join
    # these tables; psql/schema.sql)
    rows = sql._exec(
        "SELECT b.height, e.type, a.composite_key, a.value "
        "FROM attributes a JOIN events e ON e.rowid = a.event_id "
        "JOIN blocks b ON b.rowid = e.block_id"
    ).fetchall()
    assert (5, "epoch", "epoch.phase", "end") in rows
    sql.close()


def test_sql_sink_replay_is_idempotent():
    sql = _sql_sink()
    tr = make_tx_result(4, 0, b"tx-r", key=b"kiwi")
    sql.index_tx_events([tr])
    sql.index_tx_events([tr])  # replay after crash-restart
    assert len(sql.search_tx_events("app.key = 'kiwi'")) == 1
    sql.close()


def test_sql_sink_in_node_config(tmp_path):
    """`indexer = ["psql"]` boots a node writing the SQL sink and
    tx_search over RPC answers from it."""
    import time as _time

    from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
    from tendermint_tpu.node.node import make_node
    from tests.test_node import make_genesis, make_home

    async def go():
        priv = PrivKeyEd25519.from_seed(b"\x71" * 32)
        genesis = make_genesis([priv])
        cfg = make_home(tmp_path, 0, genesis, priv)
        cfg.tx_index.indexer = ["psql"]
        node = make_node(cfg)
        from tendermint_tpu.state.sink_sql import SQLSink

        assert any(isinstance(s, SQLSink) for s in node.indexer.sinks)
        await node.start()
        try:
            tx = b"sql-sink-tx=%d" % _time.time_ns()
            await node.mempool.check_tx(tx)
            deadline = _time.monotonic() + 30
            sink = next(
                s for s in node.indexer.sinks if isinstance(s, SQLSink)
            )
            h = tx_hash(tx)
            while sink.get_tx_by_hash(h) is None:
                assert _time.monotonic() < deadline, "tx never indexed"
                await asyncio.sleep(0.1)
            got = sink.get_tx_by_hash(h)
            assert got.tx == tx
            # tx_search serves from the SQL sink (no kv sink configured)
            from tendermint_tpu.rpc.jsonrpc import RPCRequest

            resp = await node.rpc_env.tx_search(
                RPCRequest(
                    method="tx_search",
                    params={"query": f"tx.hash='{h.hex().upper()}'"},
                    req_id=1,
                )
            )
            assert resp["total_count"] == 1
            assert resp["txs"][0]["hash"] == h.hex()
        finally:
            await node.stop()

    run(go())
