"""tmtrace: the whole-program device-dispatch proof gate.

Four jobs: (1) run tmtrace (static passes + fast-tier compile gate)
over the whole package on every tier-1 invocation, failing on
anything beyond the (empty) trace baseline; (2) pin the golden
jit-signature contract — every jit root in ops//parallel/ appears,
drift in any direction turns the gate red; (3) unit-test each
seeded-violation class against the mini-packages in
tests/data/trace/ (dynamic shape, tracer leak, mesh-axis mismatch,
use-after-donate, indivisible bucket, trace failure, unknown root);
(4) the CLI exit contract incl. the --signatures-update refusal
matrix, plus the fixture corpus for the two rules migrated out of
tmlint (dev-host-sync / dev-shape-leak).
"""

import importlib.util
import json
import os
import subprocess
import sys
import time

import pytest

from tendermint_tpu.analysis import tmtrace
from tendermint_tpu.analysis.tmcheck.callgraph import build_package
from tendermint_tpu.analysis.tmlint import (
    load_baseline,
    new_violations,
    save_baseline,
)
from tendermint_tpu.analysis.tmtrace import (
    jitroots,
    shapeflow,
    shapemodel,
    shardcheck,
    tracegate,
)

FIXTURES = os.path.join(os.path.dirname(__file__), "data", "trace")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EXPECTED_ROOT_IDS = {
    "ops/ed25519_kernel.py:_verify_tile",
    "ops/ed25519_kernel.py:sha512_fixed",
    "ops/ed25519_pallas.py:verify_pallas",
    "ops/ed25519_pallas.py:dual_mult_pallas",
    "ops/ed25519_pallas.py:verify_hybrid",
    "ops/merkle_kernel.py:S.inner_hash_batch",
    "ops/merkle_kernel.py:_verify_program",
    "ops/sr25519_kernel.py:_verify_tile_sr",
    "ops/sr25519_kernel.py:functools.partial(_verify_tile_sr, "
    "dual_fn=dual_mult_pallas)",
    "parallel/sharding.py:type(self)._TILE_FN",
}


@pytest.fixture(scope="module")
def pkg():
    return build_package()


@pytest.fixture(scope="module")
def head_report(pkg):
    return tmtrace.analyze(pkg)


def _fixture_pkg(name):
    return build_package(os.path.join(FIXTURES, name))


def _fixture_report(name, **kwargs):
    kwargs.setdefault("signatures", False)
    kwargs.setdefault("live", False)
    return tmtrace.analyze(_fixture_pkg(name), **kwargs)


# ---------------------------------------------------------------------------
# THE gate: whole package against the checked-in (empty) baseline


def test_package_clean_against_baseline(head_report):
    """tmtrace over the whole package (static + fast-tier live);
    anything beyond tmtrace/trace_baseline.json fails tier-1 — fix
    it, suppress it with a justified `# tmtrace: trace-ok`, or
    consciously re-baseline (docs/static_analysis.md)."""
    new = new_violations(
        head_report.violations,
        load_baseline(tmtrace.TRACE_BASELINE_PATH),
    )
    assert new == [], "\n".join(v.render() for v in new)


def test_trace_baseline_pinned_empty():
    """The shipped baseline is EMPTY: tmtrace launched with zero
    accepted debt and must stay that way — new findings are fixed or
    suppressed in-file with justification, never grandfathered."""
    assert load_baseline(tmtrace.TRACE_BASELINE_PATH) == {}


def test_gate_budget_under_10s():
    """The acceptance budget: the full tmtrace gate (call graph +
    static passes + fast-tier eval_shape) in under 10 s on CPU."""
    t0 = time.monotonic()
    tmtrace.analyze()
    elapsed = time.monotonic() - t0
    assert elapsed < 10.0, f"tmtrace gate took {elapsed:.1f}s"


def test_fast_tier_records_skipped_heavy(head_report):
    """The default tier skips the heavy crypto tiles BY NAME, never
    silently — the full sweep (--trace-full / bench trace_all_buckets)
    is where they trace."""
    st = head_report.stats
    assert st["tier"] == "fast"
    assert st["traced"] >= 4
    assert not st["skipped_budget"]
    assert "ops/ed25519_kernel.py:_verify_tile" in st["skipped_heavy"]
    assert (
        "ops/sr25519_kernel.py:_verify_tile_sr" in st["skipped_heavy"]
    )


# ---------------------------------------------------------------------------
# golden jit-signature contract


def test_every_device_jit_root_in_golden(pkg):
    """Acceptance criterion: every jit root in ops/, parallel/, and
    crypto/tpu_verifier.py appears in jit_signatures.json — and
    discovery found exactly the known set (a new root shows up here
    first, by design)."""
    roots = jitroots.discover(pkg)
    rids = {r.rid for r in roots}
    assert rids == EXPECTED_ROOT_IDS
    golden = shapemodel.load_golden()
    assert golden is not None
    gold_rids = set(golden["roots"])
    device_rids = {
        r.rid
        for r in roots
        if r.path.startswith(("ops/", "parallel/"))
        or r.path == "crypto/tpu_verifier.py"
    }
    assert device_rids <= gold_rids
    assert gold_rids == rids


def test_golden_records_static_args_and_buckets():
    golden = shapemodel.load_golden()
    vp = golden["roots"]["ops/ed25519_pallas.py:verify_pallas"]
    assert vp["static_argnames"] == ["interpret", "tile"]
    tile = golden["roots"]["ops/ed25519_kernel.py:_verify_tile"]
    from tendermint_tpu.config import DEFAULT_BUCKET_SIZES

    for b in DEFAULT_BUCKET_SIZES:
        assert any(f"[32,{b}]" in s for s in tile["signatures"]), b


def test_new_bucket_is_signature_drift(pkg, monkeypatch):
    """An accidental new pad bucket (= a silent recompilation on the
    hot path) must turn the gate red until --signatures-update."""
    from tendermint_tpu import config

    monkeypatch.setattr(
        config,
        "DEFAULT_BUCKET_SIZES",
        tuple(config.DEFAULT_BUCKET_SIZES) + (24576,),
    )
    roots = jitroots.discover(pkg)
    drift = shapemodel.drift_violations(
        roots, shapemodel.load_golden(), pkg
    )
    assert any(v.rule == "trace-signature-drift" for v in drift)
    assert any("24576" in v.message for v in drift)


def test_removed_root_is_signature_drift(pkg):
    roots = jitroots.discover(pkg)
    golden = shapemodel.load_golden()
    pruned = [
        r for r in roots if r.rid != "ops/ed25519_kernel.py:_verify_tile"
    ]
    drift = shapemodel.drift_violations(pruned, golden, pkg)
    assert any(
        v.rule == "trace-signature-drift"
        and "no longer exists" in v.message
        for v in drift
    )


def test_golden_extra_entry_is_signature_drift(pkg):
    roots = jitroots.discover(pkg)
    golden = json.loads(json.dumps(shapemodel.load_golden()))
    del golden["roots"]["ops/ed25519_kernel.py:_verify_tile"]
    drift = shapemodel.drift_violations(roots, golden, pkg)
    assert any(
        v.rule == "trace-signature-drift"
        and "not in the golden" in v.message
        for v in drift
    )


def test_unknown_root_fails_gate_on_fixture():
    """A brand-new jax.jit root with no shapemodel entry (the fixture
    package's) must fail as trace-unknown-root — the author declares
    the shape family before the gate passes."""
    rep = _fixture_report("leak_pkg", signatures=True)
    rules = {v.rule for v in rep.violations}
    assert "trace-unknown-root" in rules
    assert "trace-signature-drift" in rules


# ---------------------------------------------------------------------------
# seeded-violation fixtures


def test_fixture_tracer_leak_flags_bad_and_passes_clean():
    rep = _fixture_report("leak_pkg")
    leaks = [
        v for v in rep.violations if v.rule == "trace-tracer-leak"
    ]
    assert len(leaks) == 2, [v.render() for v in rep.violations]
    # the interprocedural leak (float() inside helper) and the branch
    assert any("float" in v.message for v in leaks)
    assert any("branch" in v.message for v in leaks)
    # the clean twin (jnp.where / shape reads / is-None config check)
    # produced nothing
    clean_lines = {
        v.line for v in leaks if "tile_clean" in v.message
    }
    assert not clean_lines


def test_fixture_dynamic_shape_flags_bad_and_passes_clean():
    rep = _fixture_report("dynshape_pkg")
    shapes = [v for v in rep.violations if v.rule == "dev-shape-leak"]
    assert len(shapes) == 1, [v.render() for v in rep.violations]
    assert "(32, n)" in shapes[0].message
    assert "dynamic" in shapes[0].message


def test_fixture_mesh_axis_flags_bad_and_passes_clean():
    rep = _fixture_report("mesh_pkg")
    mesh = [v for v in rep.violations if v.rule == "trace-mesh-axis"]
    assert len(mesh) == 1, [v.render() for v in rep.violations]
    assert "'model'" in mesh[0].message
    assert "'sig'" not in mesh[0].message.split("declared")[0]


def test_fixture_donated_reuse_flags_bad_and_passes_clean():
    rep = _fixture_report("donate_pkg")
    don = [
        v for v in rep.violations if v.rule == "trace-donated-reuse"
    ]
    assert len(don) == 1, [v.render() for v in rep.violations]
    assert "`buf`" in don[0].message and "_step" in don[0].message


def test_fixture_suppressions_silence_every_form():
    rep = _fixture_report("suppressed_pkg")
    assert rep.violations == [], [
        v.render() for v in rep.violations
    ]


def test_divisibility_real_classes_pass():
    """The production _MeshSharded rounding keeps every bucket
    divisible by every virtual mesh width."""
    assert shardcheck.divisibility_violations() == []


def test_divisibility_seeded_bad_class_fails():
    spec = importlib.util.spec_from_file_location(
        "divis_bad", os.path.join(FIXTURES, "divis_bad.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    v = shardcheck.divisibility_violations(
        [mod.BadSharded], mesh_sizes=(8,)
    )
    assert v and all(
        x.rule == "trace-bucket-indivisible" for x in v
    )
    assert any("12" in x.message for x in v)


def test_trace_compile_fail_seeded():
    """A root that cannot trace (Python branch on an abstract value)
    must turn the compile gate red with the trace error; a clean root
    passes."""

    def build_bad():
        import jax
        import jax.numpy as jnp

        def bad(x):
            if x.sum() > 0:  # concretization error under eval_shape
                return x
            return -x

        return bad, (jax.ShapeDtypeStruct((8,), jnp.int32),)

    def build_ok():
        import jax
        import jax.numpy as jnp

        def ok(x):
            return jnp.where(x > 0, x, -x)

        return ok, (jax.ShapeDtypeStruct((8,), jnp.int32),)

    cases = [
        shapemodel.TraceCase("ops/fake.py:bad", "bad@8", "fast", build_bad),
        shapemodel.TraceCase("ops/fake.py:ok", "ok@8", "fast", build_ok),
    ]
    violations, stats = tracegate.run_cases(cases)
    assert len(violations) == 1
    assert violations[0].rule == "trace-compile-fail"
    assert "bad@8" in violations[0].message
    assert stats["traced"] == 2


def test_trace_budget_stops_sweep_late_and_records():
    def build_ok():
        import jax
        import jax.numpy as jnp

        return (lambda x: x + 1), (
            jax.ShapeDtypeStruct((8,), jnp.int32),
        )

    cases = [
        shapemodel.TraceCase("ops/fake.py:f", f"f@{i}", "fast", build_ok)
        for i in range(3)
    ]
    violations, stats = tracegate.run_cases(cases, budget_s=0.0)
    assert violations == []
    assert stats["traced"] == 0
    assert len(stats["skipped_budget"]) == 3


# ---------------------------------------------------------------------------
# migrated rules: the tmlint fixture corpus now runs through tmtrace


def _mini_pkg(tmp_path, relpath, src_name):
    src = open(
        os.path.join(
            os.path.dirname(__file__), "data", "lint", src_name
        )
    ).read()
    dest = tmp_path / relpath
    dest.parent.mkdir(parents=True, exist_ok=True)
    dest.write_text(src)
    return build_package(str(tmp_path))


def test_migrated_host_sync_flags_bad_fixture(tmp_path):
    pkg = _mini_pkg(tmp_path, "parallel/fixture.py", "dev_host_sync_bad.py")
    v = shapeflow.host_sync_violations(pkg)
    assert v and all(x.rule == "dev-host-sync" for x in v)
    assert len(v) == 3  # .item(), float(), np.asarray


def test_migrated_host_sync_passes_clean_fixture(tmp_path):
    pkg = _mini_pkg(
        tmp_path, "parallel/fixture.py", "dev_host_sync_clean.py"
    )
    assert shapeflow.host_sync_violations(pkg) == []


def test_migrated_shape_leak_flags_bad_fixture(tmp_path):
    pkg = _mini_pkg(tmp_path, "crypto/batch.py", "dev_shape_leak_bad.py")
    v = shapeflow.shape_leak_violations(pkg)
    assert v and all(x.rule == "dev-shape-leak" for x in v)
    assert len(v) == 2  # jnp.zeros(n), jnp.arange(len(batch))


def test_migrated_shape_leak_passes_clean_fixture(tmp_path):
    pkg = _mini_pkg(
        tmp_path, "crypto/batch.py", "dev_shape_leak_clean.py"
    )
    assert shapeflow.shape_leak_violations(pkg) == []


def test_head_host_sync_clean_with_legacy_suppressions(pkg):
    """The in-tree justified `# tmlint: disable=dev-host-sync` sites
    (tpu_verifier env parse, sharding mesh topology) keep working
    through tmtrace."""
    assert shapeflow.host_sync_violations(pkg) == []


def test_taint_propagates_through_traced_region(pkg):
    """Regression: the taint pass must actually REACH the field/curve
    layer from the jit targets — a stack-order AST walk read uses
    before defs (empty env ⇒ nothing tainted ⇒ no edges), and a
    short-circuiting `or` skipped call operands once the result was
    known (`x + helper(y)` never analyzed helper); both produced a
    vacuously-clean gate. leak_pkg's interprocedural float() pins the
    short-circuit shape; this pins the depth."""
    roots = jitroots.discover(pkg)
    rep = shapeflow._Findings()
    tp = shapeflow._TaintPass(pkg, rep)
    for root in roots:
        if root.target_key is None:
            continue
        fi = pkg.functions.get(root.target_key)
        if fi is None:
            continue
        params = shapeflow._array_params(fi, root)
        if params:
            tp.seed(root.target_key, params)
    tp.run()
    fns = {k for k, _mask in tp.done}
    assert ("ops/field25519.py", "mul") in fns
    assert ("ops/field25519.py", "sqr") in fns
    assert ("ops/edwards.py", "point_double") in fns
    assert ("ops/sha512_kernel.py", "_compress") in fns
    assert len(fns) >= 30, len(fns)


def test_traced_region_reaches_field_ops(pkg):
    roots = jitroots.discover(pkg)
    region = jitroots.traced_region(pkg, roots)
    assert ("ops/field25519.py", "mul") in region
    assert ("ops/edwards.py", "point_double") in region
    # dispatch wrappers are NOT traced-region: they are host code
    assert (
        "crypto/tpu_verifier.py",
        "_TpuBatchVerifier.verify",
    ) not in region


# ---------------------------------------------------------------------------
# suppression map + baseline round-trip


def test_suppression_map_forms():
    lines = [
        "x = 1  # tmtrace: trace-ok — why",
        "# tmtrace: trace-ok=dev-shape-leak — reason",
        "y = jnp.zeros(n)",
        "z = 2",
    ]
    m = tmtrace.suppression_map(lines)
    assert m[1] == {"all"}
    assert m[2] == {"dev-shape-leak"}
    assert m[3] == {"dev-shape-leak"}  # comment-block-above form
    assert 4 not in m


def test_golden_gated_rules_cannot_be_baselined(tmp_path):
    """trace-signature-drift / trace-unknown-root / trace-compile-fail
    can never be absorbed by --baseline-update: their accepted state
    is jit_signatures.json, and letting the counted baseline eat them
    would be the same laundering class the PR-5 '--schema
    --baseline-update refused' fix closed."""
    fpkg = _fixture_pkg("leak_pkg")
    path = str(tmp_path / "trace_baseline.json")
    counts = tmtrace.update_trace_baseline(
        fpkg, baseline_path=path, signatures=True, live=False
    )
    # only the dataflow findings were fingerprinted...
    assert counts
    new = tmtrace.new_trace_violations(
        fpkg, baseline_path=path, signatures=True, live=False
    )
    # ...so the unknown-root/drift findings are STILL new
    assert new
    assert {v.rule for v in new} <= tmtrace.NON_BASELINE_RULES


def test_baseline_roundtrip(tmp_path):
    rep = _fixture_report("leak_pkg")
    assert rep.violations
    path = str(tmp_path / "trace_baseline.json")
    counts = save_baseline(
        rep.violations, path, note=tmtrace.TRACE_BASELINE_NOTE
    )
    assert counts
    data = json.load(open(path))
    assert "tmtrace" in data["note"]
    assert new_violations(rep.violations, load_baseline(path)) == []
    # one extra identical-fingerprint finding still fails
    extra = rep.violations + [rep.violations[0]]
    assert new_violations(extra, load_baseline(path))


def test_mosaic_probe_contract():
    """The toolchain probe (satellite of this PR: gates
    test_mosaic_jaxpr_clean) returns the recorded shape and its
    banned-prim walker actually detects a real gather."""
    import jax
    import jax.numpy as jnp

    from tendermint_tpu.ops import toolchain

    probe = toolchain.mosaic_probe()
    assert set(probe) == {"clean", "introduced", "jax_version"}
    assert isinstance(probe["clean"], bool)
    # a genuine dynamic gather is always detected
    bad = toolchain.banned_prims_of(
        lambda x, i: x[i],
        jax.ShapeDtypeStruct((16,), jnp.int32),
        jax.ShapeDtypeStruct((4,), jnp.int32),
    )
    assert "gather" in bad


# ---------------------------------------------------------------------------
# CLI contract (scripts/lint.py --trace)


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"), *args],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


@pytest.mark.slow
def test_cli_trace_clean_exit_zero():
    r = _run_cli("--trace", "--stats")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "[trace]" in r.stdout
    assert "tmtrace live tier=fast" in r.stdout


def test_cli_trace_baseline_update_refuses_filtered_runs():
    r = _run_cli("--trace", "--baseline-update", "--rule", "det-float")
    assert r.returncode == 2
    assert "full-package" in r.stderr


def test_cli_signatures_update_refusal_matrix():
    for combo in (
        ("--signatures-update", "--taint"),
        ("--signatures-update", "--race"),
        ("--signatures-update", "--trace"),
        ("--signatures-update", "--schema-update"),
        ("--signatures-update", "--baseline-update"),
        ("--signatures-update", "--rule", "det-float"),
        ("--signatures-update", "tendermint_tpu/ops/merkle_kernel.py"),
    ):
        r = _run_cli(*combo)
        assert r.returncode == 2, combo
        assert "full-package" in r.stderr, combo


def test_cli_schema_update_refuses_trace():
    r = _run_cli("--schema-update", "--trace")
    assert r.returncode == 2
    assert "--trace" in r.stderr


def test_cli_list_rules_includes_trace():
    r = _run_cli("--list-rules")
    assert r.returncode == 0
    for rid, _title in tmtrace.RULES:
        assert rid in r.stdout
