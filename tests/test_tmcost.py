"""tmcost: the whole-program per-request cost-bound gate.

Six jobs: (1) run tmcost over the whole package on every tier-1
invocation, failing on anything beyond the (empty) cost baseline and
on ANY budget drift — the static form of "no request may cost more
than its reviewed budget"; (2) pin the budget table's coverage: every
RPC route handler and p2p recv handler has a reviewed entry; (3)
prove the gate non-vacuous by seeding violations into a COPY of the
REAL package (strip the serving cache from light_blocks, drop the
page clamp) and watching the exact rule turn red naming the handler;
(4) unit-test the engine against the seeded mini-packages in
tests/data/cost/ (each turning exactly its rule red, with
clamped/cached/guarded/suppressed twins green); (5) pin the engine
decisions this PR's own development surfaced (lin factors don't fire
superlinear, stability never crosses parameters, the pagination-slice
idiom, guard-then-raise re-classing); (6) the CLI exit contract and
the --cost-update refusal matrix.
"""

import json
import os
import shutil
import time

import pytest

from tendermint_tpu.analysis import tmcost
from tendermint_tpu.analysis.tmcheck.callgraph import build_package
from tendermint_tpu.analysis.tmcost import boundflow, roots as roots_mod
from tendermint_tpu.analysis.tmlint import load_baseline, new_violations

FIXTURES = os.path.join(os.path.dirname(__file__), "data", "cost")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG_ROOT = os.path.join(REPO, "tendermint_tpu")


def _rule_hits(rep, rule):
    return [v for v in rep.violations if v.rule == rule]


def _fixture_report(name: str):
    pkg = build_package(os.path.join(FIXTURES, name))
    return tmcost.analyze(pkg)


# ---------------------------------------------------------------------------
# THE gate: whole package, empty baseline, zero budget drift


@pytest.fixture(scope="module")
def head_pkg():
    return build_package()


@pytest.fixture(scope="module")
def head_report(head_pkg):
    t0 = time.monotonic()
    rep = tmcost.analyze(head_pkg)
    rep.elapsed_s = time.monotonic() - t0
    return rep


def test_package_clean_against_baseline_and_budgets(head_report):
    """tmcost over the whole package: nothing beyond the (empty)
    counted baseline, and zero cost-budget findings — every serving
    root matches its reviewed budget exactly."""
    base, gated = tmcost.split_baselineable(head_report.violations)
    new = new_violations(
        base, load_baseline(tmcost.COST_BASELINE_PATH)
    )
    assert not new, "new tmcost violations:\n" + "\n".join(
        v.render() for v in new
    )
    assert not gated, "cost-budget drift:\n" + "\n".join(
        v.render() for v in gated
    )


def test_cost_baseline_is_checked_in_and_empty():
    """Every first-run finding was FIXED (the light_block/light_blocks
    serving cache, the evidence per-message clamp) or suppressed with
    an in-file rationale — none grandfathered, so the baseline must
    stay empty."""
    assert os.path.exists(tmcost.COST_BASELINE_PATH)
    with open(tmcost.COST_BASELINE_PATH) as f:
        data = json.load(f)
    assert data["entries"] == {}


def test_full_package_run_under_budget(head_report):
    """Runtime budget: the cost pass runs on every tier-1 invocation
    and must stay under 10 s for the whole package (measured ~3 s
    including the call-graph build). Times the module fixture's run
    rather than paying a second analyze."""
    assert head_report.elapsed_s < 10.0, (
        f"tmcost full-package run took {head_report.elapsed_s:.1f}s"
    )


def test_budgets_cover_every_rpc_route_and_p2p_recv_handler(
    head_report,
):
    """The head-catalog pin: cost_budgets.json covers EVERY discovered
    serving root — all RPC route handlers, all p2p recv handlers
    (Envelope-annotated + inline envelope loops), and the reviewed
    consensus entry points — with no stale extras."""
    budgets = tmcost.load_budgets()
    computed = set(head_report.costs)
    assert set(budgets) == computed
    fams = {}
    for rec in budgets.values():
        fams[rec["family"]] = fams.get(rec["family"], 0) + 1
    # every routes() entry in rpc/core.py is RPCRequest-annotated, so
    # the rpc family must be at least that big (+ the jsonrpc dispatch
    # chokepoint); the p2p family covers the reactor handlers
    assert fams["rpc"] >= 37, fams
    assert fams["p2p"] >= 13, fams
    assert fams["consensus"] == len(roots_mod.CONSENSUS_ROOTS)
    for rid in (
        "rpc/core.py:Environment.light_blocks",
        "rpc/core.py:Environment.tx_proofs",
        "rpc/core.py:Environment.broadcast_tx_commit",
        "consensus/reactor.py:ConsensusReactor._handle_vote_msg",
        "evidence/reactor.py:EvidenceReactor._recv_routine",
        "mempool/reactor.py:MempoolReactor._recv_routine",
        "statesync/reactor.py:StatesyncReactor._on_light_msg",
        "types/validation.py:verify_commit",
    ):
        assert rid in budgets, f"missing budget for {rid}"


def test_consensus_roots_all_resolve(head_pkg):
    """Adding a CONSENSUS_ROOTS entry is a reviewed change; a key that
    no longer resolves is a silently weakened gate."""
    for key in roots_mod.CONSENSUS_ROOTS:
        assert key in head_pkg.functions, key


def test_serving_cache_cost_is_visible_in_budgets(head_report):
    """The cached light_blocks budget records the CLAMPED page plus
    the cache's cold-miss per-block encode — the pre-fix per-request
    re-assembly shape (vset with no clamp factor) must be gone."""
    rec = head_report.costs["rpc/core.py:Environment.light_blocks"]
    assert "clamped" in rec["cost"]
    assert all("attacker" not in t and "store" not in t
               for t in rec["cost"]), rec
    # the single-block route is a pure cache lookup on the warm path
    lb = head_report.costs["rpc/core.py:Environment.light_block"]
    assert all("attacker" not in t for t in lb["cost"]), lb


def test_head_suppression_catalog_is_exactly_the_reviewed_sites(
    head_report,
):
    """The accepted-by-rationale sites are exactly: the three
    block_results encode() loops (generic-encoder summary imprecision,
    the real cost is block-linear) and the statesync ConsensusParams
    encode (a fixed handful of ints). Every other first-run finding
    got a real fix — the serving cache for light_block/light_blocks,
    the per-message evidence clamp. A new entry here means someone
    added a `# tmcost: <rule>-ok` — review it, then extend this pin
    deliberately."""
    by_site = {(rule, path) for rule, path, _ln in head_report.suppressed}
    assert by_site == {
        ("cost-superlinear", "rpc/core.py"),
        ("cost-recompute", "statesync/reactor.py"),
    }
    assert len(head_report.suppressed) == 4


# ---------------------------------------------------------------------------
# budget gate semantics (tmp golden files)


def _write_budgets(tmp_path, roots):
    p = tmp_path / "budgets.json"
    p.write_text(json.dumps({"note": "", "roots": roots}))
    return str(p)


def test_budget_missing_root_is_red(head_pkg, tmp_path):
    rep = tmcost.analyze(head_pkg, budgets_path=_write_budgets(
        tmp_path, {}
    ))
    hits = _rule_hits(rep, "cost-budget")
    assert len(hits) == len(rep.costs)
    assert any("no reviewed cost budget" in v.message for v in hits)


def test_budget_drift_both_directions_and_stale_are_red(
    head_pkg, tmp_path
):
    good = {rid: dict(rec) for rid, rec in tmcost.analyze(
        head_pkg
    ).costs.items()}
    # cheaper-than-budgeted is ALSO drift: a budget raise or cut is a
    # reviewed change either way
    rid = "rpc/core.py:Environment.light_blocks"
    good[rid] = {"family": "rpc", "cost": ["attacker"]}
    good["rpc/core.py:Environment.gone_route"] = {
        "family": "rpc", "cost": ["const"],
    }
    rep = tmcost.analyze(
        head_pkg, budgets_path=_write_budgets(tmp_path, good)
    )
    msgs = [v.message for v in _rule_hits(rep, "cost-budget")]
    assert len(msgs) == 2
    assert any("cost drift" in m and "light_blocks" in m for m in msgs)
    assert any("stale budget entry" in m for m in msgs)


def test_budget_findings_never_absorbed_by_baseline(
    head_pkg, tmp_path
):
    """cost-budget is golden-gated: new_cost_violations reports it
    even though the counted baseline is consulted for the dataflow
    rules (the tmtrace laundering class)."""
    new = tmcost.new_cost_violations(
        head_pkg, baseline_path=tmcost.COST_BASELINE_PATH
    )
    assert not new  # clean head
    rep_new = tmcost.analyze(
        head_pkg, budgets_path=_write_budgets(tmp_path, {})
    )
    base, gated = tmcost.split_baselineable(rep_new.violations)
    assert gated and not base


# ---------------------------------------------------------------------------
# seeded violations into a COPY of the REAL package (non-vacuousness)


@pytest.fixture()
def pkg_copy(tmp_path):
    dst = tmp_path / "tendermint_tpu"
    shutil.copytree(
        PKG_ROOT, dst, ignore=shutil.ignore_patterns("__pycache__")
    )
    return dst


def _analyze_copy(dst):
    from tendermint_tpu.analysis.tmcheck import callgraph

    p = callgraph.Package(str(dst), "tendermint_tpu")
    p.build()
    return tmcost.analyze(p)


def test_stripping_the_serving_cache_turns_recompute_red(pkg_copy):
    """Acceptance A/B, direction one: restore the pre-fix light_blocks
    shape (per-request re-assembly + re-encode) and the cost-recompute
    rule comes back red NAMING THE HANDLER."""
    core = pkg_copy / "rpc" / "core.py"
    src = core.read_text()
    old = (
        "blob = self.serving_cache.encoded_light_block(\n"
        "                    min_h + off\n"
        "                )\n"
        "                if blob is None:\n"
        "                    break\n"
        "                w.message(1, blob)"
    )
    new = (
        "lb = self.serving_cache.light_block_at(min_h + off)\n"
        "                if lb is None:\n"
        "                    break\n"
        "                w.message(1, lb.to_proto())"
    )
    assert old in src, "light_blocks serving loop moved; update test"
    core.write_text(src.replace(old, new))
    rep = _analyze_copy(pkg_copy)
    hits = [
        v for v in _rule_hits(rep, "cost-recompute")
        if v.path == "rpc/core.py"
    ]
    assert hits, "uncached per-request re-encode not flagged"
    assert any(
        "Environment.light_blocks" in v.message for v in hits
    ), [v.message for v in hits]


def test_dropping_the_page_clamp_turns_superlinear_and_budget_red(
    pkg_copy,
):
    """Acceptance A/B, direction two: removing the light_blocks page
    clamp makes the loop store-range-sized — cost-superlinear fires
    (store x per-block vset encode) AND the budget gate reports the
    drift."""
    core = pkg_copy / "rpc" / "core.py"
    src = core.read_text()
    old = "for off in range(min(max_h - min_h + 1, cap)):"
    new = "for off in range(max_h - min_h + 1):"
    # `cap` only appears in light_blocks (blockchain clamps with a
    # literal) — exactly one site to strip
    assert src.count(old) == 1, "light_blocks page loop moved"
    core.write_text(src.replace(old, new))
    rep = _analyze_copy(pkg_copy)
    sl = [
        v for v in _rule_hits(rep, "cost-superlinear")
        if v.path == "rpc/core.py"
        and "Environment.light_blocks" in v.message
    ]
    assert sl, "unclamped store-range page loop not flagged"
    drift = [
        v for v in _rule_hits(rep, "cost-budget")
        if "light_blocks" in v.message and "cost drift" in v.message
    ]
    assert drift, "budget gate missed the cost change"


# ---------------------------------------------------------------------------
# fixture mini-packages: each rule red exactly once per seeded site,
# twins green


def test_fixture_superlinear_red_and_twins_green():
    rep = _fixture_report("superlinear_pkg")
    hits = _rule_hits(rep, "cost-superlinear")
    assert {(v.path, v.line) for v in hits} == {
        ("handlers.py", 17),  # nested loops
        ("handlers.py", 54),  # helper fold at the call site
    }
    assert all("attacker*vset" in v.message for v in hits)
    # witness names the serving root
    assert all("scan" in v.message for v in hits)
    assert ("cost-superlinear", "handlers.py", 37) in rep.suppressed


def test_fixture_recompute_red_and_twins_green():
    rep = _fixture_report("recompute_pkg")
    hits = _rule_hits(rep, "cost-recompute")
    assert [(v.path, v.line) for v in hits] == [("handlers.py", 17)]
    assert "Env.header_raw" in hits[0].message
    assert ("cost-recompute", "handlers.py", 29) in rep.suppressed


def test_fixture_alloc_red_and_twins_green():
    rep = _fixture_report("alloc_pkg")
    hits = _rule_hits(rep, "cost-unclamped-alloc")
    assert {(v.path, v.line) for v in hits} == {
        ("handlers.py", 17),  # bytes(store-height)
        ("handlers.py", 27),  # b"\x00" * attacker
    }
    assert (
        "cost-unclamped-alloc", "handlers.py", 41
    ) in rep.suppressed


# ---------------------------------------------------------------------------
# engine decision units (the development-surfaced pins)


def _one_fn_report(tmp_path, body: str):
    pkg_dir = tmp_path / "mini"
    pkg_dir.mkdir(parents=True)
    (pkg_dir / "__init__.py").write_text("")
    (pkg_dir / "m.py").write_text(
        "class RPCRequest:\n    params: dict = {}\n\n" + body
    )
    pkg = build_package(str(pkg_dir))
    return tmcost.analyze(pkg)


def test_lin_factors_do_not_fire_superlinear(tmp_path):
    """Nested unknown-provenance (lin) collections stay findable via
    budget drift but don't fire the red rule — the first development
    run drowned in 50+ label-tuple micro-iterations."""
    rep = _one_fn_report(
        tmp_path,
        "async def h(req: RPCRequest, groups, sinks):\n"
        "    for g in groups.values():\n"
        "        for s in sinks:\n"
        "            g(s)\n",
    )
    assert not _rule_hits(rep, "cost-superlinear")
    assert rep.costs["m.py:h"]["cost"] == ["lin*lin", "lin"] or (
        "lin*lin" in rep.costs["m.py:h"]["cost"]
    )


def test_stability_never_crosses_parameters(tmp_path):
    """A helper that encodes its PARAMETER is not a recompute site —
    only locally store-derived receivers count (the cross-caller
    contamination class: store content in one caller, request content
    in another)."""
    rep = _one_fn_report(
        tmp_path,
        "def enc(meta):\n"
        "    return meta.header.to_proto()\n\n"
        "async def h(req: RPCRequest, block_store):\n"
        "    meta = block_store.load_block_meta(1)\n"
        "    return enc(meta)\n",
    )
    assert not _rule_hits(rep, "cost-recompute")


def test_local_store_derivation_is_flagged(tmp_path):
    rep = _one_fn_report(
        tmp_path,
        "async def h(req: RPCRequest, block_store):\n"
        "    meta = block_store.load_block_meta(1)\n"
        "    return meta.header.to_proto()\n",
    )
    assert len(_rule_hits(rep, "cost-recompute")) == 1


def test_pagination_slice_idiom_is_clamped(tmp_path):
    """`x[start : start + per_page]` with a clamped per_page bounds
    the slice LENGTH even when start is attacker-chosen (the
    validators/tx_search page shape)."""
    rep = _one_fn_report(
        tmp_path,
        "async def h(req: RPCRequest, vals):\n"
        "    page = int(req.params.get('page', 1))\n"
        "    per_page = min(int(req.params.get('per_page', 30)), 100)\n"
        "    start = (page - 1) * per_page\n"
        "    sel = vals.validators[start : start + per_page]\n"
        "    out = 0\n"
        "    for v in sel:\n"
        "        for w in vals.validators:\n"
        "            out += 1\n"
        "    return out\n",
    )
    # clamped page x vset = NOT superlinear (one clamp is enough)
    assert not _rule_hits(rep, "cost-superlinear")


def test_guard_then_raise_reclasses_the_bound(tmp_path):
    """`if height > top: raise` pins an attacker int into the store
    range; comparing against a constant clamps it."""
    rep = _one_fn_report(
        tmp_path,
        "MAX_N = 100\n\n"
        "async def h(req: RPCRequest, block_store):\n"
        "    n = int(req.params.get('n'))\n"
        "    if n > MAX_N:\n"
        "        raise ValueError('too big')\n"
        "    return bytes(n)\n",
    )
    assert not _rule_hits(rep, "cost-unclamped-alloc")
    # the unguarded twin is alloc_pkg's attacker_repeat fixture


def test_envelope_loop_is_the_request_boundary(tmp_path):
    """A p2p root's own `async for envelope in channel` loop is the
    per-request boundary, not a cost factor — but a loop over the
    MESSAGE's content still counts."""
    rep = _one_fn_report(
        tmp_path,
        "class Envelope:\n    message = None\n\n"
        "async def recv(channel, vals):\n"
        "    async for envelope in channel:\n"
        "        for item in envelope.message.items_list:\n"
        "            for v in vals.validators:\n"
        "                item(v)\n",
    )
    rec = rep.costs["m.py:recv"]
    # attacker*vset from the message-content nesting fires, and the
    # envelope loop itself contributed no third factor to any term
    assert _rule_hits(rep, "cost-superlinear")
    assert not any(
        t.count("*") >= 2 for t in rec["cost"]
    ), rec


def test_store_height_range_classifies_store(tmp_path):
    """`range(store.height() - store.base())`-shaped walks are
    store-class: unbounded over the chain's life."""
    rep = _one_fn_report(
        tmp_path,
        "async def h(req: RPCRequest, block_store, vals):\n"
        "    top = block_store.height()\n"
        "    base = block_store.base()\n"
        "    for hh in range(top - base + 1):\n"
        "        for v in vals.validators:\n"
        "            v(hh)\n",
    )
    hits = _rule_hits(rep, "cost-superlinear")
    assert hits and "store" in hits[0].message


def test_while_event_loops_are_not_cost_factors(tmp_path):
    """`while not closed.is_set()` pump loops don't contribute terms;
    a while whose COMPARISON reads an attacker counter does."""
    rep = _one_fn_report(
        tmp_path,
        "async def pump(req: RPCRequest, ws, sub):\n"
        "    while not ws.closed.is_set():\n"
        "        await sub.next()\n",
    )
    assert rep.costs["m.py:pump"]["cost"] == ["const"]
    rep2 = _one_fn_report(
        tmp_path / "w2",
        "async def count(req: RPCRequest, vals):\n"
        "    n = int(req.params.get('n'))\n"
        "    i = 0\n"
        "    while i < n:\n"
        "        for v in vals.validators:\n"
        "            v(i)\n"
        "        i += 1\n",
    )
    assert _rule_hits(rep2, "cost-superlinear")


# ---------------------------------------------------------------------------
# CLI contract


def _lint_main(argv):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "lintcli", os.path.join(REPO, "scripts", "lint.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main(argv)


def test_cli_cost_section_clean_head():
    assert _lint_main(["--cost"]) == 0


def test_cli_cost_update_refusal_matrix():
    # --cost-update refuses combined/filtered runs
    assert _lint_main(["--cost-update", "--adv"]) == 2
    assert _lint_main(["--cost-update", "--rule", "det-float"]) == 2
    assert _lint_main(["--cost-update", "--baseline-update"]) == 2
    assert _lint_main(["--cost-update", "--schema-update"]) == 2
    # the other update modes refuse --cost
    assert _lint_main(["--schema-update", "--cost"]) == 2
    assert _lint_main(["--signatures-update", "--cost"]) == 2


def test_cli_list_rules_includes_cost(capsys):
    assert _lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid, _ in tmcost.RULES:
        assert rid in out
