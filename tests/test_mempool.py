"""Mempool tests (reference model: internal/mempool/mempool_test.go,
cache_test.go)."""

import asyncio

import pytest

from tendermint_tpu.abci import KVStoreApplication, LocalClient
from tendermint_tpu.abci import types as abci
from tendermint_tpu.config import MempoolConfig
from tendermint_tpu.mempool import (
    LRUTxCache,
    MempoolError,
    TxInfo,
    TxMempool,
    tx_key,
)


def run(coro):
    return asyncio.run(coro)


class PriorityApp(KVStoreApplication):
    """CheckTx priority = int suffix of the tx (`p<prio>:payload`)."""

    def check_tx(self, req):
        tx = req.tx
        if tx.startswith(b"bad"):
            return abci.ResponseCheckTx(code=1, log="rejected")
        prio = 0
        if tx.startswith(b"p") and b":" in tx:
            try:
                prio = int(tx[1 : tx.index(b":")])
            except ValueError:
                pass
        return abci.ResponseCheckTx(gas_wanted=1, priority=prio)


def make_pool(cfg=None):
    app = PriorityApp()
    client = LocalClient(app)
    return TxMempool(client, cfg or MempoolConfig()), app


# ---------------------------------------------------------------------------


def test_cache_lru_eviction():
    c = LRUTxCache(2)
    assert c.push(b"a") and c.push(b"b")
    assert not c.push(b"a")  # dup
    c.push(b"c")  # evicts b (a was refreshed by the dup push)
    assert c.has(b"a") and c.has(b"c") and not c.has(b"b")
    c.remove(b"a")
    assert not c.has(b"a")


def test_checktx_admits_and_dedups():
    async def go():
        pool, _ = make_pool()
        res = await pool.check_tx(b"p5:hello")
        assert res.is_ok and pool.size() == 1
        with pytest.raises(MempoolError):
            await pool.check_tx(b"p5:hello")  # cache dup
        assert pool.size() == 1
        # invalid tx not admitted, and removed from cache so it can retry
        res = await pool.check_tx(b"bad1")
        assert not res.is_ok and pool.size() == 1
        assert not pool.cache.has(b"bad1")

    run(go())


def test_reap_priority_order_and_budgets():
    async def go():
        pool, _ = make_pool()
        for i, prio in enumerate([3, 9, 1, 7]):
            await pool.check_tx(f"p{prio}:tx{i}".encode())
        txs = pool.reap_max_bytes_max_gas(-1, -1)
        prios = [int(t[1 : t.index(b":")]) for t in txs]
        assert prios == [9, 7, 3, 1]
        # gas budget of 2 → only two txs (gas_wanted=1 each)
        assert len(pool.reap_max_bytes_max_gas(-1, 2)) == 2
        # byte budget fits only the first tx
        assert len(pool.reap_max_bytes_max_gas(8, -1)) == 1
        assert len(pool.reap_max_txs(3)) == 3

    run(go())


def test_eviction_of_lower_priority_when_full():
    async def go():
        cfg = MempoolConfig()
        cfg.size = 2
        pool, _ = make_pool(cfg)
        await pool.check_tx(b"p1:a")
        await pool.check_tx(b"p2:b")
        # higher priority evicts the lowest
        await pool.check_tx(b"p9:c")
        assert pool.size() == 2
        keys = {w.tx for w in pool._txs.values()}
        assert keys == {b"p2:b", b"p9:c"}
        # lower priority than everything resident → rejected
        with pytest.raises(MempoolError):
            await pool.check_tx(b"p0:d")
        # rejected tx must be re-admittable later (not stuck in cache)
        assert not pool.cache.has(b"p0:d")

    run(go())


def test_update_removes_committed_and_rechecks():
    async def go():
        pool, app = make_pool()
        await pool.check_tx(b"p5:a")
        await pool.check_tx(b"p6:b")
        assert pool.size() == 2

        # commit tx a → removed from pool, stays in cache
        await pool.update(
            2, [b"p5:a"], [abci.ResponseDeliverTx(code=0)]
        )
        assert pool.size() == 1
        with pytest.raises(MempoolError):
            await pool.check_tx(b"p5:a")  # committed txs stay cached

        # app starts rejecting everything → recheck clears the pool
        app.check_tx = lambda req: abci.ResponseCheckTx(code=1)
        await pool.update(3, [], [])
        assert pool.size() == 0

    run(go())


def test_ttl_purge_by_blocks():
    async def go():
        cfg = MempoolConfig()
        cfg.ttl_num_blocks = 2
        cfg.recheck = False
        pool, _ = make_pool(cfg)
        await pool.check_tx(b"p1:old")  # enters at height 0
        await pool.update(1, [], [])
        assert pool.size() == 1
        await pool.update(3, [], [])  # 3 - 0 > 2 → expired
        assert pool.size() == 0

    run(go())


def test_gossip_cursor_fifo():
    async def go():
        pool, _ = make_pool()
        await pool.check_tx(b"p9:first")
        await pool.check_tx(b"p1:second")
        w1 = pool.next_gossip_tx(0)
        assert w1.tx == b"p9:first"  # FIFO despite priority
        w2 = pool.next_gossip_tx(w1.seq)
        assert w2.tx == b"p1:second"
        assert pool.next_gossip_tx(w2.seq) is None

        # wait_for_tx wakes on insert
        waiter = asyncio.create_task(pool.wait_for_tx(w2.seq))
        await asyncio.sleep(0.01)
        assert not waiter.done()
        await pool.check_tx(b"p2:third")
        got = await asyncio.wait_for(waiter, 1)
        assert got.tx == b"p2:third"

    run(go())


def test_max_tx_bytes_enforced():
    async def go():
        cfg = MempoolConfig()
        cfg.max_tx_bytes = 4
        pool, _ = make_pool(cfg)
        with pytest.raises(MempoolError):
            await pool.check_tx(b"way-too-long")

    run(go())


def test_peer_tracking_on_duplicate():
    async def go():
        pool, _ = make_pool()
        await pool.check_tx(b"p1:x", TxInfo(sender_id=1))
        with pytest.raises(MempoolError):
            await pool.check_tx(b"p1:x", TxInfo(sender_id=2))
        wtx = pool._txs[tx_key(b"p1:x")]
        assert wtx.peers == {1, 2}

    run(go())


def test_duplicate_with_no_cache_does_not_double_count():
    """Pool-resident tx re-gossiped while absent from the cache must not
    double-count bytes or reset the gossip seq (cache_size=0 → NopTxCache)."""
    async def go():
        pool, _ = make_pool(MempoolConfig(cache_size=0))
        await pool.check_tx(b"p5:hello")
        n, b, seq = pool.size(), pool.size_bytes(), pool.next_gossip_tx(-1).seq
        with pytest.raises(MempoolError, match="already exists in the mempool"):
            await pool.check_tx(b"p5:hello")
        assert pool.size() == n
        assert pool.size_bytes() == b
        assert pool.next_gossip_tx(-1).seq == seq

    run(go())
