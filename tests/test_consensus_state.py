"""ConsensusState end-to-end: single-validator block production, tx
inclusion, WAL crash-recovery, and 4-validator consensus with perfect
in-process gossip (reference model: internal/consensus/state_test.go).
"""

import asyncio

import pytest

from tendermint_tpu.abci import KVStoreApplication, LocalClient
from tendermint_tpu.config import ConsensusConfig, MempoolConfig
from tendermint_tpu.consensus import ConsensusState, RoundStep
from tendermint_tpu.consensus.msgs import EndHeightMessage
from tendermint_tpu.consensus.wal import WAL, iter_wal_records
from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.mempool import TxMempool
from tendermint_tpu.privval import MockPV
from tendermint_tpu.state import StateStore, state_from_genesis
from tendermint_tpu.state.execution import BlockExecutor
from tendermint_tpu.store.block_store import BlockStore
from tendermint_tpu.store.kv import MemKV
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator

CHAIN = "cs-chain"


def run(coro):
    return asyncio.run(coro)


def fast_config(**kw) -> ConsensusConfig:
    cfg = ConsensusConfig(
        timeout_propose=0.5,
        timeout_propose_delta=0.1,
        timeout_prevote=0.2,
        timeout_prevote_delta=0.1,
        timeout_precommit=0.2,
        timeout_precommit_delta=0.1,
        timeout_commit=0.05,
        skip_timeout_commit=True,
    )
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


class Node:
    """One in-process validator node (no p2p)."""

    def __init__(self, priv, genesis, cfg=None, wal=None, dbs=None):
        self.priv = priv
        self.app = KVStoreApplication()
        self.client = LocalClient(self.app)
        self.state_db, self.block_db = dbs or (MemKV(), MemKV())
        self.state_store = StateStore(self.state_db)
        state = self.state_store.load()
        if state is None:
            state = state_from_genesis(genesis)
            self.state_store.save(state)
        self.block_store = BlockStore(self.block_db)
        self.mempool = TxMempool(self.client, MempoolConfig())
        self.exec = BlockExecutor(
            self.state_store, self.client, self.mempool,
            block_store=self.block_store,
        )
        self.cs = ConsensusState(
            cfg or fast_config(),
            state,
            self.exec,
            self.block_store,
            privval=MockPV(priv),
            wal=wal,
        )

    async def replay_blocks_into_app(self):
        """Poor man's handshake for restart tests: re-execute stored
        blocks into the fresh app instance (full Handshaker comes with
        the replay module)."""
        from tendermint_tpu.abci import types as abci

        for h in range(1, self.block_store.height() + 1):
            block = self.block_store.load_block(h)
            await self.client.begin_block(
                abci.RequestBeginBlock(hash=block.hash())
            )
            for tx in block.txs:
                await self.client.deliver_tx(abci.RequestDeliverTx(tx=tx))
            await self.client.end_block(abci.RequestEndBlock(height=h))
            await self.client.commit()


def single_genesis(priv):
    return GenesisDoc(
        chain_id=CHAIN,
        genesis_time_ns=1_700_000_000_000_000_000,
        validators=[GenesisValidator(pub_key=priv.pub_key(), power=10)],
    )


def test_single_validator_produces_blocks():
    async def go():
        priv = PrivKeyEd25519.from_seed(b"\x01" * 32)
        node = Node(priv, single_genesis(priv))
        await node.cs.start()
        try:
            await node.cs.wait_for_height(4, timeout=20.0)
        finally:
            await node.cs.stop()
        tip = node.block_store.height()
        assert tip >= 3
        # every stored block present; commits available below the tip
        # (commit(h) comes from block h+1's LastCommit)
        for h in range(1, tip + 1):
            block = node.block_store.load_block(h)
            assert block is not None and block.header.height == h
        for h in range(1, tip):
            commit = node.block_store.load_block_commit(h)
            assert commit is not None and commit.height == h
        seen = node.block_store.load_seen_commit()
        assert seen is not None and seen.height == tip

    run(go())


def test_tx_lands_in_block_and_app_state():
    async def go():
        priv = PrivKeyEd25519.from_seed(b"\x02" * 32)
        node = Node(priv, single_genesis(priv))
        await node.cs.start()
        try:
            await node.cs.wait_for_height(2, timeout=20.0)
            await node.mempool.check_tx(b"name=satoshi")
            await node.cs.wait_for_height(node.cs.rs.height + 2, timeout=20.0)
        finally:
            await node.cs.stop()
        # tx committed into some block
        found = any(
            b"name=satoshi" in node.block_store.load_block(h).txs
            for h in range(1, node.block_store.height() + 1)
        )
        assert found
        assert node.app.state.get(b"name") == b"satoshi"
        assert node.mempool.size() == 0  # removed post-commit

    run(go())


def test_wal_records_end_heights(tmp_path):
    async def go():
        priv = PrivKeyEd25519.from_seed(b"\x03" * 32)
        wal = WAL(str(tmp_path / "wal"))
        node = Node(priv, single_genesis(priv), wal=wal)
        await node.cs.start()
        try:
            await node.cs.wait_for_height(3, timeout=20.0)
        finally:
            await node.cs.stop()
        ends = [
            m.height
            for _, m in iter_wal_records(str(tmp_path / "wal"))
            if isinstance(m, EndHeightMessage)
        ]
        assert ends[:2] == [1, 2]

    run(go())


def test_restart_continues_from_stored_state(tmp_path):
    async def go():
        priv = PrivKeyEd25519.from_seed(b"\x04" * 32)
        genesis = single_genesis(priv)
        dbs = (MemKV(), MemKV())
        wal_path = str(tmp_path / "wal")

        node = Node(priv, genesis, wal=WAL(wal_path), dbs=dbs)
        await node.cs.start()
        await node.cs.wait_for_height(3, timeout=20.0)
        await node.cs.stop()
        h1 = node.block_store.height()
        assert h1 >= 2

        # restart on the same stores + WAL (fresh app; replay blocks in)
        node2 = Node(priv, genesis, wal=WAL(wal_path), dbs=dbs)
        await node2.replay_blocks_into_app()
        assert node2.cs.rs.height == h1 + 1  # resumed, not from genesis
        await node2.cs.start()
        try:
            await node2.cs.wait_for_height(h1 + 2, timeout=20.0)
        finally:
            await node2.cs.stop()
        assert node2.block_store.height() >= h1 + 1

    run(go())


class RelayNet:
    """Perfect in-process gossip: every signed message a node feeds into
    its own state machine is also delivered to every peer's queue.
    Stand-in for the p2p reactor in state-machine tests."""

    def __init__(self, nodes):
        self.nodes = nodes
        self.drop = lambda msg: False  # gossip fault injection hook

        for i, n in enumerate(nodes):
            orig = n.cs._send_internal

            def relayed(msg, _i=i, _orig=orig):
                _orig(msg)
                if self.drop(msg):
                    return
                for j, other in enumerate(self.nodes):
                    if j != _i:
                        other.cs.send_peer_msg(msg, peer_id=f"node{_i}")

            n.cs._send_internal = relayed


def test_four_validators_reach_consensus():
    async def go():
        privs = [PrivKeyEd25519.from_seed(bytes([i + 50]) * 32) for i in range(4)]
        genesis = GenesisDoc(
            chain_id=CHAIN,
            genesis_time_ns=1_700_000_000_000_000_000,
            validators=[
                GenesisValidator(pub_key=p.pub_key(), power=10) for p in privs
            ],
        )
        nodes = [Node(p, genesis) for p in privs]
        RelayNet(nodes)
        for n in nodes:
            await n.cs.start()
        try:
            await asyncio.gather(
                *(n.cs.wait_for_height(4, timeout=40.0) for n in nodes)
            )
        finally:
            for n in nodes:
                await n.cs.stop()

        # all nodes committed identical blocks
        for h in range(1, 4):
            hashes = {
                n.block_store.load_block(h).hash() for n in nodes
            }
            assert len(hashes) == 1, f"divergent block at height {h}"
        # proposer rotation: headers name different proposers over time
        proposers = {
            nodes[0].block_store.load_block(h).header.proposer_address
            for h in range(1, 4)
        }
        assert len(proposers) >= 2

    run(go())


def test_dropped_proposal_forces_nil_round_then_commit():
    """If height H's round-0 proposal never reaches the other
    validators, they prevote/precommit nil, move to round 1, and commit
    there (reference: state_test.go TestStateFullRoundNil + the
    round-progression cells)."""

    async def go():
        privs = [
            PrivKeyEd25519.from_seed(bytes([i + 90]) * 32)
            for i in range(4)
        ]
        genesis = GenesisDoc(
            chain_id=CHAIN,
            genesis_time_ns=1_700_000_000_000_000_000,
            validators=[
                GenesisValidator(pub_key=p.pub_key(), power=10)
                for p in privs
            ],
        )
        nodes = [Node(p, genesis) for p in privs]
        net = RelayNet(nodes)

        from tendermint_tpu.consensus.msgs import (
            BlockPartMessage,
            ProposalMessage,
        )

        target_height = 2

        def drop(msg) -> bool:
            # suppress gossip of height-2 round-0 proposal + parts
            if isinstance(msg, ProposalMessage):
                p = msg.proposal
                return p.height == target_height and p.round == 0
            if isinstance(msg, BlockPartMessage):
                return msg.height == target_height and msg.round == 0
            return False

        net.drop = drop
        for n in nodes:
            await n.cs.start()
        try:
            await asyncio.gather(
                *(
                    n.cs.wait_for_height(target_height + 2, timeout=60.0)
                    for n in nodes
                )
            )
        finally:
            for n in nodes:
                await n.cs.stop()

        commit = nodes[0].block_store.load_block_commit(target_height)
        assert commit.round >= 1, (
            f"height {target_height} committed in round {commit.round}; "
            "the dropped proposal should have forced a nil round"
        )
        hashes = {
            n.block_store.load_block(target_height).hash() for n in nodes
        }
        assert len(hashes) == 1

    run(go())


def test_invalid_proposal_prevoted_nil_and_skipped():
    """A proposer whose block fails ValidateBlock (wrong app_hash) gets
    nil prevotes from honest validators; the height commits under a
    later round's proposer and the chain continues (reference:
    state_test.go TestStateBadProposal)."""

    async def go():
        privs = [
            PrivKeyEd25519.from_seed(bytes([i + 110]) * 32)
            for i in range(4)
        ]
        genesis = GenesisDoc(
            chain_id=CHAIN,
            genesis_time_ns=1_700_000_000_000_000_000,
            validators=[
                GenesisValidator(pub_key=p.pub_key(), power=10)
                for p in privs
            ],
        )
        nodes = [Node(p, genesis) for p in privs]
        RelayNet(nodes)

        # every node, when proposing at height 2, produces a block with
        # a corrupted app_hash — all validators (including itself on
        # revalidation) reject it, so height 2 can only commit once the
        # corruption window is past (we stop corrupting after round 1)
        bad_heights = {2}
        for n in nodes:
            orig_create = n.exec.create_proposal_block

            def create(
                height, state, commit, proposer,
                _orig=orig_create,
            ):
                block, part_set = _orig(height, state, commit, proposer)
                if height in bad_heights:
                    block.header.app_hash = b"\xbd" * 32
                    block.fill_header()
                    part_set = block.make_part_set()
                return block, part_set
            n.exec.create_proposal_block = create

        for n in nodes:
            await n.cs.start()
        try:
            # let height 2 churn one bad round, then lift the corruption
            # (generous timeouts: under full-suite load with a cold XLA
            # cache, rounds can take tens of seconds of wall time)
            await nodes[0].cs.wait_for_height(2, timeout=90.0)
            deadline = asyncio.get_event_loop().time() + 60.0
            while (
                nodes[0].cs.rs.height == 2 and nodes[0].cs.rs.round < 1
            ):
                await asyncio.sleep(0.05)
                if asyncio.get_event_loop().time() > deadline:
                    break
            bad_heights.clear()
            await asyncio.gather(
                *(n.cs.wait_for_height(4, timeout=120.0) for n in nodes)
            )
        finally:
            for n in nodes:
                await n.cs.stop()

        commit = nodes[0].block_store.load_block_commit(2)
        assert commit.round >= 1, (
            "bad proposal at height 2 should have burned round 0, "
            f"got commit round {commit.round}"
        )
        for h in range(1, 4):
            hashes = {n.block_store.load_block(h).hash() for n in nodes}
            assert len(hashes) == 1

    run(go())
