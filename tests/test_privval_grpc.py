"""gRPC remote signer tests (reference model:
privval/grpc/{client_test.go,server_test.go}): pubkey/vote/proposal
round-trips over a real gRPC channel, double-sign refusal as a
non-retryable error, transport failure as a retryable one, and a full
node signing through a gRPC signer (`grpc://` listen address,
reference: node/setup.go:586)."""

import asyncio
import time

import pytest

from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.privval import FilePV
from tendermint_tpu.privval.grpc import GRPCSignerClient, GRPCSignerServer
from tendermint_tpu.privval.signer import (
    RemoteSignerConnectionError,
    RemoteSignerError,
)
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.canonical import PRECOMMIT_TYPE, PREVOTE_TYPE
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.vote import Vote

CHAIN = "grpc-signer-chain"


def run(coro):
    return asyncio.run(coro)


def _file_pv(tmp_path, seed=b"\x41"):
    return FilePV.from_priv_key(
        PrivKeyEd25519.from_seed(seed * 32),
        str(tmp_path / "pv_key.json"),
        str(tmp_path / "pv_state.json"),
    )


def _block_id(tag: bytes = b"\xaa") -> BlockID:
    return BlockID(
        hash=tag * 32,
        part_set_header=PartSetHeader(total=1, hash=b"\xbb" * 32),
    )


async def _pair(tmp_path):
    pv = _file_pv(tmp_path)
    server = GRPCSignerServer("127.0.0.1:0", CHAIN, pv)
    await server.start()
    client = GRPCSignerClient(f"grpc://127.0.0.1:{server.bound_port}")
    await client.start()
    return pv, server, client


def test_pubkey_vote_proposal_roundtrip(tmp_path):
    async def go():
        pv, server, client = await _pair(tmp_path)
        try:
            pk = await client.get_pub_key()
            assert pk.bytes() == (await pv.get_pub_key()).bytes()

            vote = Vote(
                type=PREVOTE_TYPE,
                height=3,
                round=0,
                block_id=_block_id(),
                timestamp_ns=time.time_ns(),
                validator_address=pk.address(),
                validator_index=0,
            )
            await client.sign_vote(CHAIN, vote)
            assert vote.signature
            assert pk.verify_signature(
                vote.sign_bytes(CHAIN), vote.signature
            )

            prop = Proposal(
                height=4,
                round=0,
                pol_round=-1,
                block_id=_block_id(b"\xcc"),
                timestamp_ns=time.time_ns(),
            )
            await client.sign_proposal(CHAIN, prop)
            assert prop.signature
            assert pk.verify_signature(
                prop.sign_bytes(CHAIN), prop.signature
            )
        finally:
            await client.stop()
            await server.stop()

    run(go())


def test_double_sign_refused_not_retryable(tmp_path):
    async def go():
        pv, server, client = await _pair(tmp_path)
        try:
            pk = await client.get_pub_key()
            v1 = Vote(
                type=PRECOMMIT_TYPE,
                height=7,
                round=0,
                block_id=_block_id(b"\xaa"),
                timestamp_ns=time.time_ns(),
                validator_address=pk.address(),
                validator_index=0,
            )
            await client.sign_vote(CHAIN, v1)
            # same HRS, DIFFERENT block: the signer's FilePV refuses
            v2 = Vote(
                type=PRECOMMIT_TYPE,
                height=7,
                round=0,
                block_id=_block_id(b"\xdd"),
                timestamp_ns=time.time_ns(),
                validator_address=pk.address(),
                validator_index=0,
            )
            with pytest.raises(RemoteSignerError) as ei:
                await client.sign_vote(CHAIN, v2)
            # a refusal must NOT look like a retryable transport error
            assert not isinstance(ei.value, RemoteSignerConnectionError)
        finally:
            await client.stop()
            await server.stop()

    run(go())


def test_transport_failure_is_retryable_shaped(tmp_path):
    async def go():
        pv, server, client = await _pair(tmp_path)
        await server.stop()  # signer goes away
        try:
            client.timeout = 0.5
            with pytest.raises(RemoteSignerConnectionError):
                await client.get_pub_key()
        finally:
            await client.stop()

    run(go())


def test_node_with_grpc_signer_produces_blocks(tmp_path):
    """Full node whose key lives in an external gRPC signer process
    (in-process here): grpc:// listen address selects the client."""
    from tendermint_tpu.node.node import make_node

    from tests.test_node import make_genesis, make_home

    async def go():
        priv = PrivKeyEd25519.from_seed(b"\x61" * 32)
        genesis = make_genesis([priv])
        cfg = make_home(tmp_path, 0, genesis, None)
        cfg.base.mode = "validator"

        pv = FilePV.from_priv_key(
            priv,
            str(tmp_path / "signer_key.json"),
            str(tmp_path / "signer_state.json"),
        )
        server = GRPCSignerServer("127.0.0.1:0", genesis.chain_id, pv)
        await server.start()
        cfg.priv_validator.listen_addr = (
            f"grpc://127.0.0.1:{server.bound_port}"
        )
        node = make_node(cfg)
        from tendermint_tpu.privval.signer import RetrySignerClient

        assert isinstance(node.privval, RetrySignerClient)
        assert isinstance(node.privval.inner, GRPCSignerClient)
        await node.start()
        try:
            await node.consensus.wait_for_height(3, timeout=60.0)
            assert node.block_store.height() >= 2
        finally:
            await node.stop()
            await server.stop()

    run(go())
