"""Device-fault containment: the fault plane, the circuit breakers,
and the _TpuBatchVerifier recovery paths.

The invariants under test are the acceptance criteria of the
containment layer (docs/resilience.md):

- every injected fault mode (raise / hang / mis-shape / bit-flip) is
  contained inside BatchVerifier.verify(): callers always get the
  (all_ok, bitmap) answer a healthy CPU run would give, with the SAME
  wrong-signature index attribution;
- nothing learned from a faulted batch reaches the verified-signature
  cache;
- a tripped breaker routes new work to CPU with zero device touches,
  re-arms through a single-flight probe, and never admits traffic onto
  a possibly-wedged claim before its backoff (the probe-delay policy
  the old trip_sr_singles machinery implemented by hand);
- fault-path metrics count only work the device actually completed.
"""

import threading
import time

import pytest

from tendermint_tpu.crypto import breaker as B
from tendermint_tpu.crypto import faults, sigcache
from tendermint_tpu.crypto import tpu_verifier as T
from tendermint_tpu.crypto.ed25519 import PrivKeyEd25519
from tendermint_tpu.types import InvalidCommitError, verify_commit

from .test_types import CHAIN_ID
from .test_validation import make_commit


def _triples(n, tag=b"fault", seed0=41):
    out = []
    for i in range(n):
        priv = PrivKeyEd25519.from_seed(bytes([seed0 + i]) * 32)
        msg = tag + b"-%d" % i
        out.append((priv.pub_key(), msg, priv.sign(msg)))
    return out


def _fill(v, triples):
    for pk, msg, sig in triples:
        v.add(pk, msg, sig)
    return v


# -- the fault plane ---------------------------------------------------


def test_rules_are_seed_reproducible():
    """Whether consult k fires is a pure function of (seed, k): two
    rules with the same seed fire on identical consult indexes."""

    def pattern(seed):
        fired = []
        with faults.inject("p", mode="raise", p=0.5, seed=seed) as rule:
            for i in range(50):
                try:
                    faults.fire("p")
                except faults.DeviceFault:
                    fired.append(i)
            assert rule.fired == len(fired)
        return fired

    a, b, c = pattern(7), pattern(7), pattern(8)
    assert a == b
    assert a != c  # different seed, different schedule
    assert a  # p=0.5 over 50 consults fires at least once


def test_inject_scope_and_times_budget():
    with faults.inject("p", mode="raise", times=2) as rule:
        for _ in range(2):
            with pytest.raises(faults.DeviceFault):
                faults.fire("p")
        faults.fire("p")  # budget spent: no fault
        assert rule.fired == 2
    faults.fire("p")  # scope exited: disarmed
    assert not faults.armed()


def test_key_filter_scopes_rule():
    with faults.inject("p", mode="raise", key="sr25519"):
        faults.fire("p", key="ed25519")  # filtered out
        with pytest.raises(faults.DeviceFault):
            faults.fire("p", key="sr25519")


def test_env_spec_parses_and_arms(monkeypatch):
    monkeypatch.setenv(
        "TM_TPU_FAULT", "tpu.dispatch:raise:p=0.25:seed=9;wal.fsync:io_error"
    )
    faults.load_env()
    armed = {(r.point, r.mode) for r in faults.rules()}
    assert ("tpu.dispatch", "raise") in armed
    assert ("wal.fsync", "io_error") in armed
    with pytest.raises(OSError):
        faults.fire("wal.fsync")
    monkeypatch.setenv("TM_TPU_FAULT", "")
    faults.load_env()
    assert not faults.armed()


def test_malformed_env_spec_raises_once_then_disarmed(monkeypatch):
    """A bad TM_TPU_FAULT must surface ONCE, not turn every hot-path
    armed() check into a re-parse + re-raise: the latch rises (and
    _ARMED refreshes) even when the parse fails, all-or-nothing so a
    spec that dies mid-list arms none of its rules."""
    monkeypatch.setenv(
        "TM_TPU_FAULT", "tpu.dispatch:raise;tpu.gather:bogus-mode"
    )
    monkeypatch.setattr(faults, "_ENV_LOADED", False)
    with pytest.raises(ValueError):
        faults.armed()
    # second call: latched, disarmed, no re-raise
    assert not faults.armed()
    assert all(
        not getattr(r, "_from_env", False) for r in faults.rules()
    )
    # a corrected spec re-arms via the explicit reload path
    monkeypatch.setenv("TM_TPU_FAULT", "tpu.dispatch:raise")
    faults.load_env()
    assert faults.armed()
    monkeypatch.setenv("TM_TPU_FAULT", "")
    faults.load_env()


def test_mangle_and_clip_modes():
    bits = [True, True, True, True]
    with faults.inject("g", mode="misshape"):
        assert len(faults.mangle("g", bits)) == 3
    with faults.inject("g", mode="bitflip", seed=3):
        flipped = faults.mangle("g", bits)
        assert len(flipped) == 4 and flipped != bits
    data = bytes(range(64))
    with faults.inject("w", mode="short_write", seed=5):
        prefix = faults.clip("w", data)
        assert len(prefix) < 64 and data.startswith(prefix)


# -- the circuit breaker ----------------------------------------------


class FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now


def test_breaker_trips_and_backs_off_exponentially():
    clk = FakeClock()
    b = B.CircuitBreaker("t1", backoff_base_s=10.0, clock=clk)
    assert b.state() == B.CLOSED and b.allow()
    b.record_failure()
    assert b.state() == B.OPEN
    assert not b.allow()  # inside the backoff window: nobody admitted
    clk.now += 9.9
    assert not b.allow()  # the probe-delay policy: never pile on early
    clk.now += 0.2  # past the base backoff
    assert b.allow()  # probe-less breaker: ONE half-open ticket
    assert not b.allow()  # ...and only one
    b.record_failure()  # the ticket-holder failed -> backoff doubles
    assert b.stats()["retry_in_s"] == pytest.approx(20.0, abs=0.1)
    clk.now += 20.1
    assert b.allow()
    b.record_success()  # healed: closed, exponent reset
    assert b.state() == B.CLOSED
    b.record_failure()
    assert b.stats()["retry_in_s"] == pytest.approx(10.0, abs=0.1)


def test_breaker_backoff_is_capped():
    clk = FakeClock()
    b = B.CircuitBreaker(
        "t2", backoff_base_s=10.0, backoff_max_s=60.0, clock=clk
    )
    for _ in range(10):
        b.record_failure()
    assert b.stats()["retry_in_s"] <= 60.0


def test_breaker_probe_is_single_flight():
    """With a probe fn armed, traffic is NEVER admitted while open or
    half-open — exactly one background probe decides, and concurrent
    allow() storms cannot start a second one."""
    gate = threading.Event()
    in_flight = []
    peak = []

    def probe():
        in_flight.append(1)
        peak.append(len(in_flight))
        gate.wait(5.0)
        in_flight.pop()
        return True

    b = B.CircuitBreaker("t3", backoff_base_s=0.01, probe=probe)
    b.record_failure()
    time.sleep(0.1)  # timer fires, probe starts and parks on the gate
    assert b.state() == B.HALF_OPEN
    for _ in range(50):
        assert not b.allow()  # traffic stays off the device meanwhile
    assert b.stats()["probes"] == 1  # the storm started no new probes
    gate.set()
    deadline = time.monotonic() + 5.0
    while b.state() != B.CLOSED and time.monotonic() < deadline:
        time.sleep(0.01)
    assert b.state() == B.CLOSED  # probe success re-armed the route
    assert max(peak) == 1  # <= 1 probe in flight at all times
    assert b.allow()


def test_breaker_failed_probe_reopens_with_backoff():
    b = B.CircuitBreaker("t4", backoff_base_s=0.02, probe=lambda: False)
    b.record_failure()
    time.sleep(0.1)
    # the probe failed; the breaker is open again with a doubled window
    assert b.state() == B.OPEN
    assert b.stats()["trips"] >= 2
    assert not b.allow()
    # bounded probing: backoff doubling means a dead device sees a
    # logarithmic number of probes, not one per caller
    time.sleep(0.3)
    assert b.stats()["probes"] <= 6


def test_start_open_breaker_closes_via_probe():
    """The sr25519-single warm gate re-expressed: cold == OPEN, a
    successful probe (install's warm-up) closes it."""
    b = B.CircuitBreaker("t5", backoff_base_s=5.0, start_open=True,
                         probe=lambda: True)
    assert not b.allow()  # cold: no device routing, no blocking
    b.probe_now()
    deadline = time.monotonic() + 5.0
    while b.state() != B.CLOSED and time.monotonic() < deadline:
        time.sleep(0.01)
    assert b.state() == B.CLOSED


# -- verifier containment ---------------------------------------------


def test_dispatch_raise_contained_and_uncacheable():
    triples = _triples(5)
    with faults.inject("tpu.dispatch", mode="raise"):
        v = _fill(T.TpuEd25519BatchVerifier(), triples)
        from tendermint_tpu.crypto.batch import drain_and_cache

        keys = [
            sigcache.key_for(pk.bytes(), m, s) for pk, m, s in triples
        ]
        ok, bits = drain_and_cache(v, keys)
    assert (ok, bits) == (True, [True] * 5)
    assert v.faulted
    # the CPU re-verify was correct, but nothing a faulted batch
    # touched may enter the cache
    assert sigcache.entries() == 0
    assert B.breaker_for("ed25519").state() == B.OPEN


def test_gather_hang_surfaces_as_timeout_and_falls_back(monkeypatch):
    monkeypatch.setenv("TM_TPU_GATHER_DEADLINE_S", "0.2")
    triples = _triples(4)
    # warm the kernel program first: the XLA compile happens inside
    # dispatch() and must not be charged to the hang-containment wall
    assert _fill(T.TpuEd25519BatchVerifier(), triples).verify()[0]
    t0 = time.perf_counter()
    with faults.inject("tpu.gather", mode="hang", hang_s=5.0):
        v = _fill(T.TpuEd25519BatchVerifier(), triples)
        ok, bits = v.verify()
    wall = time.perf_counter() - t0
    assert (ok, bits) == (True, [True] * 4)
    assert v.faulted
    assert wall < 3.0  # the 5 s hang never reached the caller
    assert T.stats()["faults"] >= 1


def test_misshaped_gather_contained():
    triples = _triples(4)
    with faults.inject("tpu.gather", mode="misshape"):
        v = _fill(T.TpuEd25519BatchVerifier(), triples)
        ok, bits = v.verify()
    assert (ok, bits) == (True, [True] * 4)
    assert v.faulted


def test_bitflipped_lane_disproven_and_contained():
    """A device that silently invalidates a good lane is caught by the
    CPU disprover and treated as a faulted device, not a bad vote."""
    triples = _triples(6)
    with faults.inject("tpu.gather", mode="bitflip", seed=3):
        v = _fill(T.TpuEd25519BatchVerifier(), triples)
        ok, bits = v.verify()
    assert (ok, bits) == (True, [True] * 6)
    assert v.faulted


def test_genuinely_bad_signature_not_a_device_fault():
    """The disprover must not cry wolf: a real wrong signature keeps
    its per-index attribution and trips nothing."""
    triples = _triples(5)
    pk, msg, sig = triples[3]
    triples[3] = (pk, msg, sig[:6] + bytes([sig[6] ^ 1]) + sig[7:])
    v = _fill(T.TpuEd25519BatchVerifier(), triples)
    ok, bits = v.verify()
    assert not ok and bits == [True, True, True, False, True]
    assert not v.faulted
    assert B.breaker_for("ed25519").state() == B.CLOSED


def test_open_breaker_routes_silently_without_device_touch():
    touched = []

    class SpyBacking:
        def dispatch(self, pks, msgs, sigs):  # pragma: no cover - guard
            touched.append(len(pks))
            raise AssertionError("device touched through open breaker")

        def gather(self, handle):  # pragma: no cover - guard
            raise AssertionError("device touched through open breaker")

    B.breaker_for("ed25519").open_now()
    triples = _triples(4)
    v = _fill(T.TpuEd25519BatchVerifier(SpyBacking()), triples)
    ok, bits = v.verify()
    assert (ok, bits) == (True, [True] * 4)
    assert not touched
    assert not v.faulted  # a quiet reroute is not a fault
    # ...and the factory declines outright, so new batches are born CPU
    assert T._factory(64) is None


def test_streaming_dispatch_fault_does_not_raise_from_add(monkeypatch):
    """add() may only raise on malformed input; a fault in the async
    chunk launch is deferred to verify()'s CPU fallback."""
    monkeypatch.setattr(T, "_STREAMING", True)
    monkeypatch.setattr(T._TpuBatchVerifier, "STREAM_CHUNK", 2)
    triples = _triples(5)
    with faults.inject("tpu.dispatch", mode="raise"):
        v = T.TpuEd25519BatchVerifier()
        for pk, msg, sig in triples:
            v.add(pk, msg, sig)  # chunk launches fault silently here
            assert len(v) <= 5
        ok, bits = v.verify()
    assert (ok, bits) == (True, [True] * 5)
    assert v.faulted


def test_midloop_gather_fault_counts_only_completed_work(monkeypatch):
    """Three streamed chunks in flight; the gather of the SECOND one
    faults. tpu_verify_sigs_total must advance by exactly the one
    chunk the device completed — the old code left the counters
    claiming work the device never finished — and the verifier must
    still answer the full batch correctly from CPU."""
    monkeypatch.setattr(T, "_STREAMING", True)
    monkeypatch.setattr(T._TpuBatchVerifier, "STREAM_CHUNK", 2)

    class FlakyBacking:
        """dispatch/gather pair whose SECOND gather raises — the
        mid-flight device death shape."""

        def __init__(self):
            self.gathers = 0

        def dispatch(self, pks, msgs, sigs):
            from tendermint_tpu.crypto.ed25519 import Ed25519BatchVerifier
            from tendermint_tpu.crypto.keys import pubkey_from_type_and_bytes

            bv = Ed25519BatchVerifier()
            for pk, m, s in zip(pks, msgs, sigs):
                bv.add(pubkey_from_type_and_bytes("ed25519", pk), m, s)
            return bv.verify()[1]

        def gather(self, handle):
            self.gathers += 1
            if self.gathers == 2:
                raise T.DeviceFault("device died mid-flight")
            return handle

    triples = _triples(6)
    sigs0 = T.stats()["sigs"]
    faults0 = T.stats()["faults"]
    v = T.TpuEd25519BatchVerifier(FlakyBacking())
    for pk, msg, sig in triples:
        v.add(pk, msg, sig)  # streams three 2-sig chunks
    ok, bits = v.verify()
    assert (ok, bits) == (True, [True] * 6)
    assert v.faulted
    # only the ONE gathered chunk (2 sigs) counts as device work
    assert T.stats()["sigs"] == sigs0 + 2
    assert T.stats()["faults"] == faults0 + 1
    assert len(v) == 0 and v.verify() == (False, [])


def test_verify_commit_error_parity_across_fault_paths():
    """The acceptance criterion: the wrong-signature index and message
    are byte-identical on the device path, the pure CPU path, and the
    mid-batch-fault-then-fallback path — and no path leaks sigcache
    entries from a faulted batch."""
    from tendermint_tpu.crypto.batch import (
        register_device_factory,
        unregister_device_factory,
    )

    def run():
        vals, bid, commit = make_commit(4)
        forged = bytearray(commit.signatures[2].signature)
        forged[5] ^= 0x40
        commit.signatures[2].signature = bytes(forged)
        with pytest.raises(InvalidCommitError) as ei:
            verify_commit(CHAIN_ID, vals, bid, 1, commit)
        return str(ei.value)

    register_device_factory(
        "ed25519", lambda hint: T.TpuEd25519BatchVerifier()
    )
    try:
        device = run()
        sigcache.reset()
        with faults.inject("tpu.dispatch", mode="raise"):
            mid_fault = run()
        # a faulted batch never populates the cache — not even its
        # three good signatures
        assert sigcache.entries() == 0
        B.reset_all()
    finally:
        unregister_device_factory("ed25519")
    cpu = run()
    assert device == mid_fault == cpu
    assert "wrong signature (#2)" in cpu


def test_probe_rearms_route_after_faults_clear():
    """install()-style wiring: fault trips the breaker, the fault
    clears, the timer-scheduled probe closes it again — open ->
    half-open -> closed, with no traffic required."""
    b = B.fresh("ed25519", backoff_base_s=0.05)
    b.set_probe(
        lambda: T._device_probe("ed25519", T._ed_backing)
    )
    triples = _triples(3)
    with faults.inject("tpu.dispatch", mode="raise"):
        v = _fill(T.TpuEd25519BatchVerifier(), triples)
        assert v.verify() == (True, [True] * 3)
        assert b.state() == B.OPEN
    # fault plane disarmed: the next probe finds a healthy device
    deadline = time.monotonic() + 10.0
    while b.state() != B.CLOSED and time.monotonic() < deadline:
        time.sleep(0.01)
    assert b.state() == B.CLOSED
    # and the route serves the device again
    v = _fill(T.TpuEd25519BatchVerifier(), triples)
    assert v.verify() == (True, [True] * 3)
    assert not v.faulted


def test_half_open_ticket_expires_and_reissues():
    """A probe-less breaker whose half-open ticket holder never
    reports back (its work was rerouted, its caller died) must re-admit
    a caller after the backoff window — half-open may stall the route,
    never wedge it permanently (review finding)."""
    clk = FakeClock()
    b = B.CircuitBreaker("t6", backoff_base_s=10.0, clock=clk)
    b.record_failure()
    clk.now += 10.1
    assert b.allow()  # ticket out; holder silently vanishes
    assert not b.allow()
    clk.now += 10.1  # a full backoff with no report
    assert b.allow()  # fresh ticket
    b.record_success()
    assert b.state() == B.CLOSED


def test_factory_admission_pays_back_the_ticket():
    """The double-consult wedge (review finding): _factory's allow()
    takes the half-open ticket, and verify() must then ATTEMPT the
    device and report the outcome — not consult allow() again, reroute
    to CPU, and leave the breaker half-open forever."""
    b = B.fresh("ed25519", backoff_base_s=0.0)  # probe-less
    b.record_failure()
    assert b.state() == B.OPEN
    # backoff 0: the next factory consult transitions to HALF_OPEN and
    # admits ONE verifier
    v = T._factory(8)
    assert v is not None and b.state() == B.HALF_OPEN
    triples = _triples(3)
    for pk, msg, sig in triples:
        v.add(pk, msg, sig)
    ok, bits = v.verify()  # the admitted verifier IS the probe
    assert (ok, bits) == (True, [True] * 3)
    assert not v.faulted
    assert b.state() == B.CLOSED  # ticket paid back, route re-armed


def test_open_now_wins_over_inflight_probe():
    """Operator kill switch vs a racing probe (review finding): a probe
    launched before open_now() must not close the breaker the operator
    just ordered open, even if it succeeds against the device."""
    release = threading.Event()

    def probe():
        release.wait(5.0)
        return True  # the device looks healthy to the stale probe

    b = B.CircuitBreaker("t7", backoff_base_s=0.01, probe=probe)
    b.record_failure()
    deadline = time.monotonic() + 5.0
    while not b.probe_in_flight() and time.monotonic() < deadline:
        time.sleep(0.005)
    assert b.probe_in_flight()
    b.open_now()  # operator override while the probe is parked
    release.set()
    time.sleep(0.2)  # give the stale probe time to (try to) publish
    assert b.state() == B.OPEN  # the override held
